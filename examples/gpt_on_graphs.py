"""GPT-on-graphs demo — ego-subgraph -> LLM link-prediction prompts.

The reference's examples/gpt/arxiv.py workload: a LinkNeighborLoader
samples the combined neighborhood of candidate (src, dst) paper pairs
(fanout [12, 6], binary negatives, batch_size 2), node ids are mapped
back to raw titles, and the textualized ego-subgraph is sent to an LLM
that judges whether the two seed papers cite each other
(reference arxiv.py:24-50 `run`, utils.link_prediction).

No dataset or model weights are downloadable here, so this demo
  * synthesizes a titled citation graph (deterministic word-pool
    titles standing in for arxiv_2023/raw/titles.csv.gz), and
  * prints the prompts by default; ``--model <local-hf-dir>`` scores
    them with any locally available causal LM through ``transformers``
    (the reference calls the OpenAI API at the same point).

The graph/ sampling machinery is the part under test: the prompt's
structure section is exactly the sampled `Batch` (global `node` ids,
masked `edge_index`, `edge_label_index` metadata) — the same contract
every other loader consumer sees.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import common  # noqa: F401  — honors GLT_PLATFORM=cpu before backend init

import numpy as np

from glt_tpu.data import Dataset
from glt_tpu.loader import LinkNeighborLoader
from glt_tpu.sampler import NegativeSampling

_ADJ = ('Scalable', 'Sparse', 'Neural', 'Sampled', 'Distributed',
        'Quantized', 'Streaming', 'Robust', 'Latent', 'Causal')
_NOUN = ('Graph Learning', 'Attention', 'Message Passing', 'Embeddings',
         'Link Prediction', 'Clustering', 'Transformers', 'Sampling',
         'Partitioning', 'Representation Learning')
_TAIL = ('at Scale', 'on TPUs', 'with Negative Sampling', 'for Citations',
         'under Distribution Shift', 'in Heterogeneous Networks',
         'with Frontier Trimming', 'via Collectives', 'for MAG',
         'with Hot Caches')


def synth_titled_citations(num_papers: int, avg_cites: int = 6,
                           seed: int = 0):
  """Citation graph + deterministic titles (the arxiv stand-in)."""
  rng = np.random.default_rng(seed)
  e = num_papers * avg_cites
  src = rng.integers(0, num_papers, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * num_papers).astype(np.int64) % num_papers
  keep = src != dst
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src[keep], dst[keep]]),
                num_nodes=num_papers)
  ids = rng.integers(0, len(_ADJ), size=(num_papers, 3))
  titles = np.array(
      [f'{_ADJ[a]} {_NOUN[b % len(_NOUN)]} {_TAIL[c % len(_TAIL)]}'
       for a, b, c in ids])
  return ds, titles


def ego_prompt(batch, titles: np.ndarray) -> str:
  """Textualize one sampled ego-subgraph into a link-prediction prompt
  (the reference's utils.link_prediction message builder)."""
  node = np.asarray(batch.node)
  mask = np.asarray(batch.edge_mask).astype(bool)
  row = np.asarray(batch.row)[mask]
  col = np.asarray(batch.col)[mask]
  eli = np.asarray(batch.metadata['edge_label_index'])
  lines = ['You are given a citation subgraph. Papers:']
  for local, gid in enumerate(node[:np.asarray(batch.node_count)]):
    lines.append(f'  [{local}] "{titles[gid]}"')
  lines.append('Known citations (citing -> cited):')
  for r, c in zip(row.tolist(), col.tolist()):
    lines.append(f'  [{r}] -> [{c}]')
  a, b = int(eli[0][0]), int(eli[1][0])
  lines.append(
      f'Question: based only on the structure above, is paper [{a}] '
      f'likely to cite paper [{b}]? Answer yes or no with one reason.')
  return '\n'.join(lines)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--papers', type=int, default=2_000)
  ap.add_argument('--num-batches', type=int, default=3)
  ap.add_argument('--fanout', default='12,6')
  ap.add_argument('--model', default=None,
                  help='local HF causal-LM dir; omit to just print '
                       'prompts (no downloads in this environment)')
  ap.add_argument('--max-new-tokens', type=int, default=48)
  args = ap.parse_args()

  ds, titles = synth_titled_citations(args.papers)
  loader = LinkNeighborLoader(
      ds, [int(f) for f in args.fanout.split(',')],
      batch_size=2, shuffle=True, drop_last=True, seed=0,
      neg_sampling=NegativeSampling('binary', amount=1),
      collect_features=False)

  generate = None
  if args.model:
    from transformers import pipeline  # baked in; weights must be local
    generate = pipeline('text-generation', model=args.model,
                        device=-1)

  for i, batch in enumerate(loader):
    if i >= args.num_batches:
      break
    prompt = ego_prompt(batch, titles)
    print(f'=== batch {i} '
          f'(label={np.asarray(batch.metadata["edge_label"])[0]:.0f})')
    print(prompt)
    if generate is not None:
      out = generate(prompt, max_new_tokens=args.max_new_tokens,
                     do_sample=False)[0]['generated_text']
      print(f'--- model response:\n{out[len(prompt):]}')
  print('done')


if __name__ == '__main__':
  main()

"""Train -> checkpoint -> serve: GraphSAGE online inference end-to-end.

Phase 1 trains a small supervised SAGE on the synthetic products graph
(as train_sage_products.py) and saves params with
glt_tpu.utils.checkpoint. Phase 2 restores the checkpoint into an
InferenceEngine, stands up a ServingServer (micro-batching + bucketed
compilation + embedding cache), and fires synthetic queries at it
through a ServingClient over the rpc fabric.
"""
import argparse
import os
import sys
import tempfile
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from glt_tpu.utils.backend import force_backend

force_backend()

import jax
import jax.numpy as jnp
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.serving import InferenceEngine, ServingClient, ServingServer
from glt_tpu.typing import Split
from glt_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint

from common import synthetic_products


def train(ds, num_classes, args) -> dict:
  fanout = [int(x) for x in args.fanout.split(',')]
  loader = NeighborLoader(ds, fanout,
                          input_nodes=ds.get_split(Split.train),
                          batch_size=args.batch_size, shuffle=True,
                          seed=0)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))
  params = model.init(jax.random.key(0), next(iter(loader)))
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  done = 0
  for epoch in range(args.epochs):
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt,
                               batch.replace(metadata=meta))
      done += 1
      if args.max_steps and done >= args.max_steps:
        break
    print(f'epoch {epoch}: loss={float(loss):.4f}')
    if args.max_steps and done >= args.max_steps:
      break
  return params


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--nodes', type=int, default=8_000)
  ap.add_argument('--epochs', type=int, default=1)
  ap.add_argument('--max-steps', type=int, default=0,
                  help='cap total train steps (0 = full epochs)')
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--hidden', type=int, default=64)
  ap.add_argument('--buckets', default='8,32')
  ap.add_argument('--queries', type=int, default=32)
  ap.add_argument('--max-request', type=int, default=8)
  ap.add_argument('--ckpt-dir', default=None,
                  help='checkpoint location (default: a temp dir)')
  args = ap.parse_args()

  ds, num_classes = synthetic_products(num_nodes=args.nodes)
  ckpt_dir = args.ckpt_dir or os.path.join(
      tempfile.mkdtemp(prefix='glt_serve_'), 'ckpt')

  # -- phase 1: train + checkpoint --------------------------------------
  params = train(ds, num_classes, args)
  save_checkpoint(ckpt_dir, step=0, params=params)
  print(f'checkpoint saved: {ckpt_dir}')

  # -- phase 2: restore + serve -----------------------------------------
  step, payload = restore_checkpoint(ckpt_dir, template={'params': params})
  print(f'restored step {step}')
  fanout = [int(x) for x in args.fanout.split(',')]
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))
  engine = InferenceEngine(ds, model, payload['params'], fanout,
                           buckets=[int(b) for b in
                                    args.buckets.split(',')])
  with ServingServer(engine, max_wait_ms=2.0,
                     request_timeout_ms=60_000.0) as srv:
    print(f'serving on {srv.address}; '
          f'warmup compiled buckets {engine.compile_stats()["forward_traces"]}')
    cli = ServingClient(*srv.address)
    rng = np.random.default_rng(0)
    for i in range(args.queries):
      n = int(rng.integers(1, args.max_request + 1))
      ids = ((rng.random(n) ** 2) * args.nodes).astype(np.int64)
      logits = cli.infer(ids)
      assert logits.shape == (n, num_classes)
    print('sample prediction:',
          int(np.argmax(cli.infer([0])[0])))
    print('serving stats:', srv.metrics.report(cache=engine.cache))
    recompiles = (sum(engine.compile_stats()['forward_traces'].values())
                  - len(engine.buckets))
    print(f'steady-state recompiles: {recompiles}')
    cli.close()


if __name__ == '__main__':
  main()

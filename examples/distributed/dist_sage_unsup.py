"""Distributed unsupervised GraphSAGE — the reference's
examples/distributed/dist_sage_unsup workload: per-rank edge seed pools,
binary negative sampling, endpoint neighborhood expansion through the
distributed engine, dot-product BCE on edge_label_index pairs.

TPU formulation: DistLinkNeighborLoader drives the SPMD collective
sampler + DistFeature lookup; the train step is one shard_map program
(per-device forward + pmean'd grads — the DDP allreduce as an XLA
collective).
"""
import argparse
import os
import sys
import tempfile
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--nodes', type=int, default=4_000)
  ap.add_argument('--epochs', type=int, default=2)
  ap.add_argument('--batch-size', type=int, default=32,
                  help='positive edges per device per step')
  ap.add_argument('--fanout', default='8,4')
  ap.add_argument('--cpu-mesh', action=argparse.BooleanOptionalAction,
                  default=True)
  args = ap.parse_args()

  if args.cpu_mesh:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  import jax
  if args.cpu_mesh:
    from glt_tpu.utils.backend import force_backend
    force_backend('cpu')
  import jax.numpy as jnp
  import numpy as np
  import optax
  from jax.sharding import NamedSharding, PartitionSpec as P

  from glt_tpu.distributed import (
      DistFeature, DistGraph, DistLinkNeighborLoader, DistDataset,
  )
  from glt_tpu.loader.transform import Batch
  from glt_tpu.models import GraphSAGE
  from glt_tpu.ops.pipeline import edge_hop_offsets
  from glt_tpu.parallel import make_mesh
  from glt_tpu.partition import RandomPartitioner
  from glt_tpu.sampler import NegativeSampling

  n = args.nodes
  rng = np.random.default_rng(0)
  src = np.concatenate([np.arange(n), rng.integers(0, n, n * 4)])
  dst = np.concatenate([(np.arange(n) + 1) % n, rng.integers(0, n, n * 4)])
  feats = rng.normal(size=(n, 64)).astype(np.float32)

  root = tempfile.mkdtemp(prefix='unsup_parts_')
  RandomPartitioner(root, num_parts=args.num_devices, num_nodes=n,
                    edge_index=np.stack([src, dst]),
                    node_feat=feats).partition()
  mesh = make_mesh(args.num_devices)
  dg = DistGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(args.num_devices)]
  df = DistFeature.from_dist_datasets(mesh, dss)

  # per-device positive-edge pools = the edges whose src the device owns
  pb = np.asarray(dg.node_pb)
  pools = []
  for p in range(args.num_devices):
    m = pb[src] == p
    pools.append(np.stack([src[m], dst[m]]))

  fanout = [int(x) for x in args.fanout.split(',')]
  loader = DistLinkNeighborLoader(
      dg, fanout, pools, dist_feature=df,
      neg_sampling=NegativeSampling('binary', amount=1),
      batch_size=args.batch_size, shuffle=True, seed=0)

  spd = loader.seeds_per_device
  offs = tuple(edge_hop_offsets(spd, fanout))
  model = GraphSAGE(hidden_features=128, out_features=64, num_layers=2)
  tx = optax.adam(3e-3)
  axis = dg.axis

  def device_step(params, opt_state, x, row, col, emask, eli, lab):
    batch = Batch(x=x[0], row=row[0], col=col[0], edge_mask=emask[0],
                  node=jnp.zeros((x.shape[1],), jnp.int32),
                  node_count=jnp.zeros((), jnp.int32),
                  batch_size=spd, edge_hop_offsets=offs)

    def loss_fn(p):
      emb = model.apply(p, batch, method=GraphSAGE.embed)
      logit = (jnp.take(emb, eli[0, 0], axis=0)
               * jnp.take(emb, eli[0, 1], axis=0)).sum(-1)
      return optax.sigmoid_binary_cross_entropy(logit, lab[0]).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    ups, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, ups), opt_state, loss[None]

  sp = P(axis)
  step = jax.jit(jax.shard_map(
      device_step, mesh=mesh,
      in_specs=(P(), P(), sp, sp, sp, sp, sp, sp),
      out_specs=(P(), P(), sp), check_vma=False))

  b0 = next(iter(loader))
  dummy = Batch(x=jnp.asarray(b0['x'][0]), row=jnp.asarray(b0['row'][0]),
                col=jnp.asarray(b0['col'][0]),
                edge_mask=jnp.asarray(b0['edge_mask'][0]),
                node=jnp.zeros((b0['x'].shape[1],), jnp.int32),
                node_count=jnp.zeros((), jnp.int32), batch_size=spd,
                edge_hop_offsets=offs)
  params = jax.device_put(model.init(jax.random.key(0), dummy),
                          NamedSharding(mesh, P()))
  opt = jax.device_put(tx.init(params), NamedSharding(mesh, P()))

  shard = NamedSharding(mesh, P(axis))
  for epoch in range(args.epochs):
    for b in loader:
      args_dev = [jax.device_put(jnp.asarray(b[k]), shard)
                  for k in ('x', 'row', 'col', 'edge_mask',
                            'edge_label_index', 'edge_label')]
      params, opt, loss = step(params, opt, *args_dev)
    print(f'epoch {epoch}: loss={float(np.asarray(loss)[0]):.4f}')


if __name__ == '__main__':
  main()

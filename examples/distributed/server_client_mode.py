"""Server-client deployment — the reference's
examples/distributed/server_client_mode/: sampling servers (CPU hosts)
feed a training client over rpc with prefetching. One-host demo with
server subprocesses.
"""
import argparse
import multiprocessing as mp
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import numpy as np


def build_dataset():
  sys.path.insert(0, os.path.join(os.path.dirname(
      os.path.abspath(__file__)), '..'))
  from common import synthetic_products
  ds, _ = synthetic_products(num_nodes=4_000)
  return ds


def run_server(rank, num_servers, port):
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.distributed import init_server, wait_and_shutdown_server
  init_server(num_servers=num_servers, num_clients=1, server_rank=rank,
              dataset=build_dataset(), master_port=port,
              dataset_builder=build_dataset)
  wait_and_shutdown_server()


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-servers', type=int, default=2)
  ap.add_argument('--port', type=int, default=29600)
  args = ap.parse_args()

  ctx = mp.get_context('spawn')
  servers = [ctx.Process(target=run_server,
                         args=(r, args.num_servers, args.port))
             for r in range(args.num_servers)]
  for s in servers:
    s.start()

  import time
  time.sleep(3)
  import jax
  import jax.numpy as jnp
  import optax
  from glt_tpu.distributed import (
      RemoteDistSamplingWorkerOptions, RemoteNeighborLoader, init_client,
      shutdown_client,
  )
  from glt_tpu.models import GraphSAGE

  init_client(args.num_servers, 1, 0, master_port=args.port)
  n = 4_000
  per_server = np.array_split(np.arange(n), args.num_servers)
  loader = RemoteNeighborLoader(
      [10, 5], per_server, batch_size=128, shuffle=True,
      collect_features=True, seed=0,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=list(range(args.num_servers)), prefetch_size=4))

  model = GraphSAGE(hidden_features=64, out_features=47, num_layers=2)
  params = None
  tx = optax.adam(1e-3)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(logits, batch.y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  for epoch in range(2):
    for batch in loader:
      if params is None:
        params = model.init(jax.random.key(0), batch)
        opt = tx.init(params)
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    print(f'epoch {epoch}: loss={float(loss):.4f}')

  shutdown_client()
  for s in servers:
    s.join(timeout=15)
  print('done')


if __name__ == '__main__':
  main()

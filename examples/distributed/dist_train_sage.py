"""Distributed (sharded-topology) GraphSAGE — the reference's
examples/distributed/dist_train_sage_supervised.py, as one SPMD program:
partition to disk, load per-partition stores, run the collocated
sample+gather+train step over the mesh.

On a single host this uses the virtual CPU mesh for demonstration; on a
real slice the same code runs over the TPU mesh (one process per host,
jax.distributed.initialize()).
"""
import argparse
import os
import sys
import tempfile
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--steps', type=int, default=30)
  ap.add_argument('--cpu-mesh', action=argparse.BooleanOptionalAction,
                  default=True,
                  help='--no-cpu-mesh runs on the real device mesh')
  args = ap.parse_args()

  if args.cpu_mesh:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  import jax
  if args.cpu_mesh:
    from glt_tpu.utils.backend import force_backend
    force_backend('cpu')
  import numpy as np
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistGraph, DistTrainStep,
  )
  from glt_tpu.models import GraphSAGE
  from glt_tpu.parallel import make_mesh
  from glt_tpu.partition import RandomPartitioner
  from common import synthetic_products

  ds, num_classes = synthetic_products(num_nodes=8_000)
  root = tempfile.mkdtemp(prefix='glt_parts_')
  g = ds.get_graph()
  src, dst, _ = g.topo.to_coo()
  RandomPartitioner(
      root, num_parts=args.num_devices, num_nodes=g.num_nodes,
      edge_index=np.stack([src, dst]),
      node_feat=ds.get_node_feature()[np.arange(g.num_nodes)],
  ).partition()

  mesh = make_mesh(args.num_devices)
  dg = DistGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(args.num_devices)]
  df = DistFeature.from_dist_datasets(mesh, dss)
  labels = ds.get_node_label()

  model = GraphSAGE(hidden_features=128, out_features=num_classes,
                    num_layers=2)
  tx = optax.adam(1e-3)
  step = DistTrainStep(dg, df, model, tx, labels, fanouts=[10, 5],
                       batch_size_per_device=128)
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  rng = np.random.default_rng(0)
  for it in range(args.steps):
    seeds = rng.integers(0, g.num_nodes, (args.num_devices, 128))
    params, opt, loss = step(params, opt, seeds,
                             np.full(args.num_devices, 128),
                             jax.random.key(it))
    if it % 10 == 0:
      print(f'step {it}: loss={float(np.asarray(loss)[0]):.4f}')
  print('done')


if __name__ == '__main__':
  main()

"""Unsupervised GraphSAGE link prediction with negative sampling — the
reference's examples/graph_sage_unsup_ppi.py workload:
LinkNeighborLoader + binary NegativeSampling + dot-product BCE."""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.loader import LinkNeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.sampler import NegativeSampling

from common import synthetic_products


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--batch-size', type=int, default=128)
  args = ap.parse_args()

  ds, _ = synthetic_products(num_nodes=3_000)
  loader = LinkNeighborLoader(
      ds, [8, 4], batch_size=args.batch_size, shuffle=True, seed=0,
      neg_sampling=NegativeSampling('binary', amount=1))
  model = GraphSAGE(hidden_features=128, out_features=64, num_layers=2)
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(3e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      emb = model.apply(p, batch, method=GraphSAGE.embed)
      eli = batch.metadata['edge_label_index']
      lab = batch.metadata['edge_label']
      logit = (emb[eli[0]] * emb[eli[1]]).sum(-1)
      return optax.sigmoid_binary_cross_entropy(logit, lab).mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  for epoch in range(args.epochs):
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    print(f'epoch {epoch}: loss={float(loss):.4f}')


if __name__ == '__main__':
  main()

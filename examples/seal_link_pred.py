"""SEAL-style link prediction over induced subgraphs — the reference's
examples/seal_link_pred.py (NeighborSampler full-neighborhood + subgraph
extraction via SubGraphLoader)."""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.loader import SubGraphLoader
from glt_tpu.models import GraphSAGE

from common import synthetic_products


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=2)
  args = ap.parse_args()

  ds, num_classes = synthetic_products(num_nodes=2_000, avg_degree=6)
  loader = SubGraphLoader(ds, [10, 10], input_nodes=np.arange(2_000),
                          batch_size=64, shuffle=True, seed=0,
                          with_edge=True)
  model = GraphSAGE(hidden_features=64, out_features=num_classes,
                    num_layers=2, trim=False)
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(2e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(logits, batch.y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  for epoch in range(args.epochs):
    for batch in loader:
      meta = {'n_valid': jnp.asarray(batch.metadata['n_valid']),
              'mapping': batch.metadata['mapping']}
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    print(f'epoch {epoch}: loss={float(loss):.4f}')


if __name__ == '__main__':
  main()

"""SEAL link prediction — real SEAL semantics, TPU-first.

Reference: examples/seal_link_pred.py (238 LoC): full-neighborhood
enclosing subgraphs via ``NeighborSampler([-1]*hops).subgraph``, target
link removed, DRNL node labels one-hot encoded as the only features, a
DGCNN (GCN stack -> sort-pool -> Conv1d -> MLP) trained with BCE, model
selection by validation ROC-AUC. The reference runs on Cora; this
environment has no dataset downloads, so the graph is a synthetic
ring-plus-chords graph whose link structure is learnable from topology
alone.

TPU design: enclosing subgraphs are padded static [N_cap]-node graphs,
DRNL is a jitted edge-parallel BFS (``glt_tpu.ops.drnl``), and the DGCNN
forward is vmapped over the batch so XLA fuses the whole batch into
dense MXU matmuls.
"""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import common  # noqa: F401  (GLT_PLATFORM handling)

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.data import Dataset
from glt_tpu.models.dgcnn import DGCNN
from glt_tpu.ops.drnl import drnl_node_labeling
from glt_tpu.sampler import NeighborSampler

MAX_Z = 12  # DRNL vocabulary clip (2-hop labels are small)


def ring_chord_graph(n=200, chords=60, seed=0):
  """Undirected ring + random chords; returns directed-both-ways COO."""
  rng = np.random.default_rng(seed)
  ring = {(min(i, (i + 1) % n), max(i, (i + 1) % n)) for i in range(n)}
  while len(ring) < n + chords:
    a, b = rng.integers(0, n, 2)
    if a != b:
      ring.add((min(int(a), int(b)), max(int(a), int(b))))
  und = sorted(ring)
  return und


def link_split(und_edges, rng, num_val=0.05, num_test=0.10, n=200):
  """RandomLinkSplit equivalent: held-out positives + sampled negatives."""
  und = list(und_edges)
  rng.shuffle(und)
  n_test = int(len(und) * num_test)
  n_val = int(len(und) * num_val)
  test_pos, val_pos = und[:n_test], und[n_test:n_test + n_val]
  train_pos = und[n_test + n_val:]
  edge_set = set(und_edges)
  negs = []
  while len(negs) < n_test + n_val + len(train_pos):
    a, b = int(rng.integers(0, n)), int(rng.integers(0, n))
    if a != b and (min(a, b), max(a, b)) not in edge_set:
      negs.append((a, b))
  test_neg = negs[:n_test]
  val_neg = negs[n_test:n_test + n_val]
  train_neg = negs[n_test + n_val:]
  return train_pos, train_neg, val_pos, val_neg, test_pos, test_neg


def build_train_dataset(train_pos, n):
  both = np.array(train_pos + [(b, a) for a, b in train_pos], np.int64)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=both.T.copy(), num_nodes=n)
  return ds


def extract_enclosing(sampler, links, y, drnl_fn, n_cap):
  """Enclosing subgraph + DRNL features per candidate link (reference
  SEALDataset.extract_enclosing_subgraphs)."""
  out = []
  for src, dst in links:
    sub = sampler.subgraph(np.array([src, dst], np.int64),
                           node_capacity=n_cap)
    # target-link removal + DRNL run jitted on device
    z, rows, cols, emask = drnl_fn(sub.rows, sub.cols, sub.edge_mask,
                                   sub.node_count)
    out.append((np.asarray(z), np.asarray(rows), np.asarray(cols),
                np.asarray(emask),
                np.arange(n_cap) < int(sub.node_count), y))
  return out


def collate(items):
  z = np.stack([i[0] for i in items])
  rows = np.stack([i[1] for i in items])
  cols = np.stack([i[2] for i in items])
  emask = np.stack([i[3] for i in items])
  nmask = np.stack([i[4] for i in items])
  y = np.array([i[5] for i in items], np.float32)
  x = np.eye(MAX_Z + 1, dtype=np.float32)[z]  # one-hot DRNL features
  return x, rows, cols, emask, nmask, y


def roc_auc(y_true, scores):
  """Rank-statistic ROC-AUC (no sklearn dependency)."""
  order = np.argsort(scores)
  ranks = np.empty_like(order, dtype=np.float64)
  ranks[order] = np.arange(1, len(scores) + 1)
  # average ranks over ties
  for s in np.unique(scores):
    m = scores == s
    ranks[m] = ranks[m].mean()
  pos = y_true > 0.5
  n_pos, n_neg = pos.sum(), (~pos).sum()
  if n_pos == 0 or n_neg == 0:
    return 0.5
  return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=10)
  ap.add_argument('--nodes', type=int, default=200)
  ap.add_argument('--hops', type=int, default=2)
  ap.add_argument('--batch-size', type=int, default=32)
  args = ap.parse_args()

  rng = np.random.default_rng(0)
  und = ring_chord_graph(n=args.nodes, seed=0)
  train_pos, train_neg, val_pos, val_neg, test_pos, test_neg = \
      link_split(und, rng, n=args.nodes)
  ds = build_train_dataset(train_pos, args.nodes)
  g = ds.get_graph()

  sampler = NeighborSampler(g, [-1] * args.hops, seed=0)
  from glt_tpu.ops.pipeline import sample_budget
  # 2 seeds expanded through the resolved full-neighborhood windows
  n_cap = sample_budget(2, sampler.num_neighbors)

  @jax.jit
  def drnl_fn(rows, cols, emask, node_count):
    # remove the target link (labels 0 and 1 by first-occurrence order)
    keep = emask & ~(((rows == 0) & (cols == 1)) |
                     ((rows == 1) & (cols == 0)))
    z = drnl_node_labeling(rows, cols, keep, n_cap,
                           jnp.int32(0), jnp.int32(1), MAX_Z)
    z = jnp.where(jnp.arange(n_cap) < node_count, z, 0)
    return z, rows, cols, keep

  print('extracting enclosing subgraphs...')
  splits = {}
  for name, pos, neg in [('train', train_pos, train_neg),
                         ('val', val_pos, val_neg),
                         ('test', test_pos, test_neg)]:
    items = (extract_enclosing(sampler, pos, 1.0, drnl_fn, n_cap)
             + extract_enclosing(sampler, neg, 0.0, drnl_fn, n_cap))
    splits[name] = collate(items)
    print(f'  {name}: {len(items)} subgraphs')

  # sort-pool k = 60th percentile of subgraph sizes (reference k=0.6)
  sizes = sorted(splits['train'][4].sum(axis=1).tolist())
  k = max(10, int(sizes[int(np.ceil(0.6 * len(sizes))) - 1]))
  model = DGCNN(hidden=32, num_layers=3, k=k)

  fwd = jax.vmap(model.apply, in_axes=(None, 0, 0, 0, 0, 0))
  x0 = jax.tree.map(jnp.asarray, splits['train'][:5])
  params = model.init(jax.random.key(0), *[a[0] for a in x0])
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def train_step(params, opt, batch):
    x, rows, cols, emask, nmask, y = batch
    def loss_fn(p):
      logits = fwd(p, x, rows, cols, emask, nmask)
      return optax.sigmoid_binary_cross_entropy(logits, y).mean()
    loss, grads = jax.value_and_grad(loss_fn)(params)
    ups, opt = tx.update(grads, opt)
    return optax.apply_updates(params, ups), opt, loss

  @jax.jit
  def predict(params, batch):
    x, rows, cols, emask, nmask, _ = batch
    return fwd(params, x, rows, cols, emask, nmask)

  def evaluate(split):
    x, rows, cols, emask, nmask, y = splits[split]
    scores = np.asarray(predict(params,
                                tuple(map(jnp.asarray, splits[split]))))
    return roc_auc(y, scores)

  x, rows, cols, emask, nmask, y = splits['train']
  n_train = y.shape[0]
  bs = args.batch_size
  best_val = test_auc = 0.0
  for epoch in range(1, args.epochs + 1):
    perm = rng.permutation(n_train)
    losses = []
    for lo in range(0, n_train - bs + 1, bs):
      sel = perm[lo:lo + bs]
      batch = tuple(jnp.asarray(a[sel]) for a in
                    (x, rows, cols, emask, nmask, y))
      params, opt, loss = train_step(params, opt, batch)
      losses.append(float(loss))
    val_auc = evaluate('val')
    if val_auc > best_val:
      best_val, test_auc = val_auc, evaluate('test')
    print(f'Epoch: {epoch:02d}, Loss: {np.mean(losses):.4f}, '
          f'Val: {val_auc:.4f}, Test: {test_auc:.4f}')
  return test_auc


if __name__ == '__main__':
  main()

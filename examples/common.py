"""Shared helpers for examples: synthetic datasets standing in for OGB
downloads (this environment has no network egress). Generators are
scale-parameterized so the same scripts run as smoke tests or at
products-scale."""
from __future__ import annotations

import os

from glt_tpu.utils.backend import force_backend

# honor GLT_PLATFORM/GLT_BENCH_PLATFORM even where the TPU plugin
# overrides JAX_PLATFORMS (must run before backend init)
force_backend()

import numpy as np

from glt_tpu.data import Dataset, sort_by_in_degree


def synthetic_products(num_nodes=24_000, avg_degree=25, feat_dim=100,
                       num_classes=47, seed=0, split_ratio=1.0,
                       sort_features=False):
  """ogbn-products-shaped synthetic graph (2.45M nodes / 62M edges at
  full scale; defaults are a 1000x smaller smoke config)."""
  rng = np.random.default_rng(seed)
  e = num_nodes * avg_degree
  src = rng.integers(0, num_nodes, e, dtype=np.int64)
  # mild power-law: square a uniform to concentrate on low ids
  dst = (rng.random(e) ** 2 * num_nodes).astype(np.int64) % num_nodes
  feats = rng.normal(size=(num_nodes, feat_dim)).astype(np.float32)
  # learnable labels: block structure + feature signal
  w = rng.normal(size=(feat_dim, num_classes)).astype(np.float32)
  labels = np.argmax(feats @ w, axis=1).astype(np.int32)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=num_nodes)
  ds.init_node_features(
      feats, sort_func=sort_by_in_degree if sort_features else None,
      split_ratio=split_ratio)
  ds.init_node_labels(labels)
  ds.random_node_split(num_val=0.1, num_test=0.1)
  return ds, num_classes


def synthetic_hetero_mag(num_papers=2_000, num_authors=1_000,
                         feat_dim=64, num_classes=8, seed=0):
  """ogbn-mag-shaped hetero graph: paper-cites-paper, author-writes-paper."""
  rng = np.random.default_rng(seed)
  cites = ('paper', 'cites', 'paper')
  writes = ('author', 'writes', 'paper')
  pp = np.stack([rng.integers(0, num_papers, num_papers * 8),
                 rng.integers(0, num_papers, num_papers * 8)])
  ap = np.stack([rng.integers(0, num_authors, num_papers * 3),
                 rng.integers(0, num_papers, num_papers * 3)])
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index={cites: pp, writes: ap},
                num_nodes={'paper': num_papers, 'author': num_authors})
  pf = rng.normal(size=(num_papers, feat_dim)).astype(np.float32)
  af = rng.normal(size=(num_authors, feat_dim)).astype(np.float32)
  w = rng.normal(size=(feat_dim, num_classes)).astype(np.float32)
  labels = np.argmax(pf @ w, 1).astype(np.int32)
  ds.init_node_features({'paper': pf, 'author': af})
  ds.init_node_labels({'paper': labels})
  return ds, num_classes, cites, writes

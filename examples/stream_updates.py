"""Train -> serve -> mutate: live graph & feature updates end-to-end.

Phase 1 trains a small supervised GraphSAGE on the synthetic products
graph (as serve_sage_products.py). Phase 2 serves it through an
InferenceEngine backed by a **StreamSampler** over a SnapshotManager.
Phase 3 applies live updates through a StreamIngestor — edge inserts
visible to the very next request via the delta overlay, feature updates
landing at compaction — and shows the cache-coherence contract in
action: touched entries invalidate, predictions refresh, and the
compiled programs never retrace (steady-state recompiles stay 0 across
the snapshot swap).
"""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from glt_tpu.utils.backend import force_backend

force_backend()

import jax
import jax.numpy as jnp
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.serving import InferenceEngine, ServingMetrics
from glt_tpu.stream import (
    CompactionPolicy, SnapshotManager, StreamIngestor, StreamSampler,
)
from glt_tpu.typing import Split

from common import synthetic_products


def train(ds, num_classes, args) -> dict:
  fanout = [int(x) for x in args.fanout.split(',')]
  loader = NeighborLoader(ds, fanout,
                          input_nodes=ds.get_split(Split.train),
                          batch_size=args.batch_size, shuffle=True,
                          seed=0)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))
  params = model.init(jax.random.key(0), next(iter(loader)))
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  done = 0
  for batch in loader:
    meta = dict(batch.metadata)
    meta['n_valid'] = jnp.asarray(meta['n_valid'])
    params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    done += 1
    if args.max_steps and done >= args.max_steps:
      break
  print(f'trained {done} steps: loss={float(loss):.4f}')
  return model, params


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--nodes', type=int, default=4_000)
  ap.add_argument('--max-steps', type=int, default=10)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--hidden', type=int, default=32)
  ap.add_argument('--buckets', default='8,32')
  ap.add_argument('--delta-window', type=int, default=8)
  ap.add_argument('--updates', type=int, default=64,
                  help='live edge inserts to stream in')
  args = ap.parse_args()

  ds, num_classes = synthetic_products(num_nodes=args.nodes)
  fanout = [int(x) for x in args.fanout.split(',')]

  # -- phase 1: train ----------------------------------------------------
  model, params = train(ds, num_classes, args)

  # -- phase 2: serve over a versioned snapshot chain --------------------
  manager = SnapshotManager(ds.get_graph().topo, ds.get_node_feature(),
                            delta_capacity=max(args.updates * 4, 256))
  sampler = StreamSampler(manager, fanout,
                          delta_window=args.delta_window, seed=0)
  engine = InferenceEngine(
      ds, model, params, fanout, sampler=sampler,
      buckets=[int(b) for b in args.buckets.split(',')])
  engine.warmup()
  warm = engine.compile_stats()
  print(f'warmed buckets {warm["forward_traces"]}; snapshot '
        f'v{manager.current().version}')

  metrics = ServingMetrics()
  ingestor = StreamIngestor(
      manager, sampler=sampler, engine=engine, metrics=metrics,
      policy=CompactionPolicy(occupancy_threshold=0.5,
                              max_staleness_s=5.0),
      expand_invalidation=True)

  rng = np.random.default_rng(0)
  probe = np.arange(8)
  before = engine.infer(probe)
  print('cache after first pass:', engine.cache.stats()['size'],
        'entries')

  # -- phase 3: live updates ---------------------------------------------
  # edge inserts: visible to sampling immediately via the delta overlay
  src = rng.integers(0, args.nodes, args.updates)
  dst = rng.integers(0, args.nodes, args.updates)
  ingestor.insert_edges(src, dst)
  # feature updates on the probe nodes: land at compaction
  new_rows = rng.normal(
      size=(4, ds.get_node_feature().feature_dim)).astype(np.float32)
  ingestor.update_features(probe[:4], new_rows)
  info = ingestor.flush()
  dropped = info['invalidated']
  print(f'compacted to snapshot v{info["version"]} in '
        f'{info["compaction_s"] * 1e3:.1f}ms; touched '
        f'{info["touched"].size} nodes, invalidated {dropped} '
        f'cache entries')
  assert dropped > 0

  after = engine.infer(probe)
  changed = [int(i) for i in probe[:4]
             if not np.allclose(before[i], after[i])]
  print(f'fresh predictions for updated nodes: {changed}')
  assert changed, 'feature updates must change served predictions'

  end = engine.compile_stats()
  recompiles = (sum(end['forward_traces'].values())
                - sum(warm['forward_traces'].values()))
  recompiles += end['sampler_compiled_fns'] \
      - warm['sampler_compiled_fns']
  print(f'steady-state recompiles across swap: {recompiles}')
  assert recompiles == 0
  print('gauges:', {k: round(v, 3)
                    for k, v in metrics.snapshot()['gauges'].items()})
  print('stream stats:', ingestor.stats()['edge_delta'])


if __name__ == '__main__':
  main()

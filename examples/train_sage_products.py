"""Supervised GraphSAGE node classification — the reference's headline
single-device workload (examples/train_sage_ogbn_products.py: fanout
[15,10,5], batch 1024, 3 layers, hidden 256, ~0.787 test acc).

Runs on a synthetic products-shaped graph (no dataset egress here); pass
--scale full for the 2.45M-node configuration.
"""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE
from glt_tpu.typing import Split
from glt_tpu.utils.profile import ThroughputMeter

from common import synthetic_products


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--scale', default='smoke', choices=['smoke', 'full'])
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='15,10,5')
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--split-ratio', type=float, default=1.0,
                  help='device-resident feature fraction')
  args = ap.parse_args()

  n = 2_450_000 if args.scale == 'full' else 24_000
  ds, num_classes = synthetic_products(
      num_nodes=n, split_ratio=args.split_ratio,
      sort_features=args.split_ratio < 1.0)
  fanout = [int(x) for x in args.fanout.split(',')]
  train_idx = ds.get_split(Split.train)

  loader = NeighborLoader(ds, fanout, input_nodes=train_idx,
                          batch_size=args.batch_size, shuffle=True,
                          seed=0)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(logits, batch.y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  @jax.jit
  def predict(params, batch):
    return jnp.argmax(model.apply(params, batch), -1)

  meter = ThroughputMeter('edges')
  for epoch in range(args.epochs):
    t0 = time.time()
    edges = 0
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
      edges += int(jnp.sum(batch.num_sampled_edges))
    jax.block_until_ready(loss)
    dt = time.time() - t0
    meter.update(edges, dt)
    print(f'epoch {epoch}: loss={float(loss):.4f} time={dt:.1f}s '
          f'({meter.report()})')

  # test accuracy
  test_idx = ds.get_split(Split.test)
  eval_loader = NeighborLoader(ds, fanout, input_nodes=test_idx,
                               batch_size=args.batch_size, seed=1)
  correct = total = 0
  for batch in eval_loader:
    nv = batch.metadata['n_valid']
    pred = np.asarray(predict(params, batch))[:nv]
    correct += (pred == np.asarray(batch.y)[:nv]).sum()
    total += nv
  print(f'test acc: {correct / total:.4f}')


if __name__ == '__main__':
  main()

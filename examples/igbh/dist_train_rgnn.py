"""IGBH-style hetero distributed training — the reference's MLPerf GNN
vehicle (examples/igbh/dist_train_rgnn.py:104-213: ckpt_steps
save/restore, mlperf logging, validation evaluate loop, bf16 features).

Pipeline (mirrors the reference's):
  compress_graph.py --path R --synthesize 100000 --bf16   # no downloads
  split_seeds.py --path R
  dist_train_rgnn.py --data-root R ...

All stages run here on the virtual CPU mesh; on a real slice the same
program runs over TPU chips with per-host partition loading. At
``--papers 100000`` (the default via --synthesize) the graph holds
~1.35M directed edges — a capability-scale demo, not a toy.
"""
import argparse
import os
import resource
import sys
import tempfile
import time


def peak_rss_gb() -> float:
  """Linux ru_maxrss is KiB; the high-water mark of this process."""
  return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def log_rss(stage: str) -> None:
  print(f'[rss] {stage}: peak {peak_rss_gb():.2f} GB', flush=True)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))


def load_igbh_root(root: str, load_feats: bool = True,
                   load_edges: bool = True):
  """Load the compress_graph/split_seeds output tree. ``load_feats=
  False`` / ``load_edges=False`` skip the full feature matrices / edge
  payloads (multihost mode builds the stores from the per-rank
  partition blocks instead — loading whole tables on every rank would
  defeat per-rank memory discipline; edge-type NAMES then come from the
  partition dir's META.json)."""
  import numpy as np
  from compress_graph import load_meta
  proc = os.path.join(root, 'processed')
  counts = load_meta(root)
  edges = {}
  for name in sorted(os.listdir(proc)) if load_edges else ():
    p = os.path.join(proc, name, 'edge_index.npy')
    if os.path.exists(p):
      s, r, d = name.split('__')
      edges[(s, r, d)] = np.load(p)
  feats = {}
  for t in counts if load_feats else ():
    bf = next((p for p in (os.path.join(root, lay, t,
                                        'node_feat_bf16.npy')
                           for lay in ('csc', 'csr'))
               if os.path.exists(p)), None)
    if bf is not None:
      import ml_dtypes
      feats[t] = np.load(bf).view(ml_dtypes.bfloat16)
    else:
      feats[t] = np.load(os.path.join(proc, t, 'node_feat.npy'))
  labels = np.load(os.path.join(proc, 'paper', 'node_label.npy'))
  train_idx = np.load(os.path.join(proc, 'train_idx.npy'))
  val_idx = np.load(os.path.join(proc, 'val_idx.npy'))
  return counts, edges, feats, labels, train_idx, val_idx


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--conv', default='rgat', choices=['rgat', 'rsage'])
  ap.add_argument('--epochs', type=int, default=1)
  ap.add_argument('--steps-per-epoch', type=int, default=0,
                  help='0 = full epoch over the train split')
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--batch-size', type=int, default=64)
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--data-root', default=None,
                  help='compress_graph/split_seeds output tree; default '
                       'synthesizes one in a temp dir')
  ap.add_argument('--papers', type=int, default=100_000,
                  help='synthetic scale when --data-root is absent')
  ap.add_argument('--bf16', action=argparse.BooleanOptionalAction,
                  default=True, help='bfloat16 feature store')
  ap.add_argument('--split-ratio', type=float, default=1.0,
                  help='<1 spills each partition\'s cold feature tail '
                       'to pinned host memory, served in-program '
                       '(beyond-HBM training through the fused step)')
  ap.add_argument('--learning-rate', type=float, default=1e-3,
                  help='adam base lr (the reference trainer default, '
                       'dist_train_rgnn.py:368; logged as '
                       'opt_base_learning_rate)')
  ap.add_argument('--lr-schedule', default='constant',
                  choices=['constant', 'cosine', 'linear'],
                  help='decay shape over epochs*steps_per_epoch')
  ap.add_argument('--lr-warmup-steps', type=int, default=0,
                  help='linear ramp 0 -> lr before the schedule body')
  ap.add_argument('--seed', type=int, default=0,
                  help='rng seed for init, shuffling and sampling')
  ap.add_argument('--mlperf', action='store_true',
                  help='reference-trainer preset: full MLLOG key set '
                       'with submission block, validation over the '
                       'whole val split, 3 epochs unless overridden '
                       '(mirrors dist_train_rgnn.py:368-440 flags)')
  ap.add_argument('--ckpt-dir', default=None)
  ap.add_argument('--ckpt-steps', type=int, default=200)
  ap.add_argument('--resume', action='store_true')
  ap.add_argument('--val-batches', type=int, default=20)
  ap.add_argument('--cpu-mesh', action=argparse.BooleanOptionalAction,
                  default=True,
                  help='--no-cpu-mesh runs on the real device mesh')
  ap.add_argument('--part-root', default=None,
                  help='partition dir; reused if it already holds META '
                       '(required pre-built in --coordinator mode)')
  ap.add_argument('--coordinator', default=None,
                  help='host:port — run as ONE of --nprocs '
                       'jax.distributed processes, each loading ONLY '
                       'its own partitions (the reference per-rank '
                       'loading discipline, dist_train_rgnn.py)')
  ap.add_argument('--nprocs', type=int, default=1)
  ap.add_argument('--rank', type=int, default=0)
  args = ap.parse_args()

  multihost = args.coordinator is not None
  if multihost and args.num_devices % args.nprocs:
    raise SystemExit(f'--num-devices {args.num_devices} must divide '
                     f'evenly over --nprocs {args.nprocs}')
  if multihost and not args.part_root:
    raise SystemExit('--coordinator mode needs a pre-built --part-root')
  if args.cpu_mesh:
    per_proc = (args.num_devices // args.nprocs if multihost
                else args.num_devices)
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={per_proc}')
  import jax
  if args.cpu_mesh:
    from glt_tpu.utils.backend import force_backend
    force_backend('cpu')
  if multihost:
    from glt_tpu.parallel.multihost import initialize
    initialize(coordinator_address=args.coordinator,
               num_processes=args.nprocs, process_id=args.rank)
  import jax.numpy as jnp
  import numpy as np
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
      dist_feature_from_partitions_multihost,
      dist_hetero_graph_from_partitions_multihost,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.parallel import make_mesh
  from glt_tpu.partition import RandomPartitioner
  from glt_tpu.typing import reverse_edge_type
  from glt_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint
  from glt_tpu.utils.mlperf_logging import MLLogger

  if args.mlperf:
    # the reference's MLPerf protocol: 3 training epochs with a full
    # validation sweep each (dist_train_rgnn.py:368-440); explicit
    # --epochs still wins
    if args.epochs == 1:
      args.epochs = 3
    args.val_batches = 1 << 30  # the eval loop stops at the split end

  # one MLLOG stream per job: non-zero ranks emit nothing
  mll = MLLogger(benchmark='gnn',
                 emit=(print if not multihost or args.rank == 0
                       else (lambda *_: None)))
  if args.mlperf:
    mll.submission_info(benchmark='GNN', submitter='glt_tpu',
                        platform='tpu-v5e' if not args.cpu_mesh
                        else 'cpu-virtual-mesh')
  mll.init_start()

  root = args.data_root
  have_data = root is not None and os.path.exists(
      os.path.join(root, 'processed', 'meta.txt'))
  if not have_data:
    if multihost:
      raise SystemExit('--coordinator mode needs a pre-built shared '
                       '--data-root (each process would otherwise '
                       'synthesize a different dataset)')
    if root is None:
      root = tempfile.mkdtemp(prefix='igbh_data_')
    from compress_graph import compress, synthesize
    from split_seeds import split_seeds
    print(f'synthesizing IGBH-layout data at {args.papers} papers...')
    synthesize(root, args.papers)
    # this path re-partitions from COO, so only the bf16 feature pass of
    # compress() is consumed; the topology pass is for --data-root users
    compress(root, layout='CSC', bf16=args.bf16, topology=False)
    split_seeds(root)
  counts, edges, feats, labels, train_idx, val_idx = load_igbh_root(
      root, load_feats=not multihost, load_edges=not multihost)
  log_rss('data loaded')
  num_classes = int(labels.max()) + 1
  mll.event('global_batch_size',
            args.batch_size * args.num_devices)
  mll.event('train_samples', int(train_idx.shape[0]))
  mll.event('eval_samples', int(val_idx.shape[0]))
  fanout = [int(x) for x in args.fanout.split(',')]
  if multihost:
    # edge payloads stay on disk; the model/fanout only need the etype
    # NAMES, which the partition META records (incl. reversed types)
    from glt_tpu.partition import load_meta as load_part_meta
    etypes = [tuple(e) for e in
              load_part_meta(args.part_root)['edge_types']]
    print(f'{len(etypes)} edge types over '
          f'{ {t: int(n) for t, n in counts.items()} }')
  else:
    total_edges = sum(e.shape[1] for e in edges.values())
    print(f'{total_edges} directed edges over '
          f'{ {t: int(n) for t, n in counts.items()} }')
    # reversed relations make authors/institutes reachable from paper
    # seeds (the reference inserts reverse edge types the same way)
    rev = {}
    for (s, r, d), ei in list(edges.items()):
      if s != d:
        rev[(d, f'rev_{r}', s)] = ei[::-1].copy()
    edges.update(rev)
    etypes = list(edges)

  part_root = args.part_root or tempfile.mkdtemp(prefix='igbh_parts_')
  have_parts = os.path.exists(os.path.join(part_root, 'META.json'))
  if multihost and not have_parts:
    raise SystemExit('--coordinator mode needs a pre-built --part-root '
                     '(run once without --coordinator, or rank-0-only, '
                     'to partition first)')
  if not have_parts:
    print('partitioning...')
    # partition blocks travel as f32 (npz cannot express bf16); the
    # device store below re-casts to bf16, where the HBM savings matter
    part_feats = {t: np.asarray(f, dtype=np.float32)
                  for t, f in feats.items()}
    RandomPartitioner(part_root, num_parts=args.num_devices,
                      num_nodes=dict(counts), edge_index=edges,
                      node_feat=part_feats).partition()
    del part_feats
  log_rss('partitioned')

  mesh = make_mesh(args.num_devices)
  dtype = jnp.bfloat16 if args.bf16 else None
  sr = (args.split_ratio if args.split_ratio < 1.0 else None)
  if multihost:
    # each process loads ONLY its local devices' partitions
    dg = dist_hetero_graph_from_partitions_multihost(mesh, part_root)
    dfeats = {t: dist_feature_from_partitions_multihost(
        mesh, part_root, ntype=t, dtype=dtype,
        split_ratio=args.split_ratio) for t in counts}
  else:
    dg = DistHeteroGraph.from_dataset_partitions(mesh, part_root)
    dss = [DistDataset().load(part_root, p)
           for p in range(args.num_devices)]
    dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t,
                                                dtype=dtype,
                                                split_ratio=sr)
              for t in counts}
  if sr is not None:
    spilled = {t: st.cold_array is not None for t, st in dfeats.items()}
    print(f'host-offloaded cold blocks active: {spilled}')
  label_dict = {'paper': labels}

  model = RGNN(edge_types=[reverse_edge_type(e) for e in etypes],
               hidden_features=args.hidden, out_features=num_classes,
               num_layers=len(fanout), conv=args.conv)
  n_dev, bs = args.num_devices, args.batch_size
  per_epoch = (args.steps_per_epoch
               or train_idx.shape[0] // (n_dev * bs))
  total_steps = max(args.epochs * per_epoch, 1)
  # rgat at 2e-3 constant went NaN in epoch 2 (igbh_epoch_17m_rgat3.log)
  # — the reference exposes lr and defaults 1e-3; warmup/decay on top
  lr, warm = args.learning_rate, args.lr_warmup_steps
  if args.lr_schedule == 'cosine':
    sched = optax.warmup_cosine_decay_schedule(
        0.0 if warm else lr, lr, warm, total_steps, end_value=lr * 0.01)
  elif args.lr_schedule == 'linear':
    body = optax.linear_schedule(lr, lr * 0.01,
                                 max(total_steps - warm, 1))
    sched = (optax.join_schedules(
        [optax.linear_schedule(0.0, lr, warm), body], [warm])
        if warm else body)
  else:
    sched = (optax.linear_schedule(0.0, lr, warm) if warm else lr)
  mll.event('opt_base_learning_rate', lr)
  mll.event('opt_learning_rate_warmup_steps', warm)
  mll.event('opt_learning_rate_decay_schedule', args.lr_schedule)
  mll.event('seed', args.seed)
  tx = optax.adam(sched)
  step = DistHeteroTrainStep(
      dg, dfeats, model, tx, label_dict,
      {e: fanout for e in etypes},
      batch_size_per_device=args.batch_size, seed_type='paper',
      seed=args.seed)
  params = step.init_params(jax.random.key(args.seed))
  opt = tx.init(params)
  log_rss('stores built + step compiled-ready')

  start_step = 0
  if args.ckpt_dir and args.resume:
    got_step, payload = restore_checkpoint(
        args.ckpt_dir, template={'params': params, 'opt_state': opt})
    if payload is not None:
      from jax.sharding import NamedSharding, PartitionSpec as P
      rep = NamedSharding(mesh, P())
      params = jax.device_put(payload['params'], rep)
      opt = jax.device_put(payload['opt_state'], rep)
      start_step = int(got_step)
      print(f'resumed from checkpoint step {start_step}')

  rng = np.random.default_rng(args.seed)
  global_step = start_step
  mll.init_stop()
  mll.run_start()
  t_start = time.time()
  for epoch in range(args.epochs):
    mll.epoch_start(epoch)
    order = rng.permutation(train_idx.shape[0])
    ndb = n_dev * bs
    for it in range(per_epoch):
      lo = (it * ndb) % train_idx.shape[0]
      sel = order[lo:lo + ndb]
      if sel.shape[0] < ndb:  # wrap the permutation at the epoch seam
        sel = np.concatenate(
            [sel, np.resize(order, ndb - sel.shape[0])])
      seeds = train_idx[sel].reshape(n_dev, bs)
      params, opt, loss = step(params, opt, seeds, np.full(n_dev, bs),
                               jax.random.key(global_step))
      global_step += 1
      if it % 20 == 0:
        # loss is mesh-sharded (every lane equal); read a LOCAL shard
        # so multihost processes can fetch it
        l = float(np.asarray(loss.addressable_shards[0].data)[0])
        dt = time.time() - t_start
        print(f'epoch {epoch} step {it}/{per_epoch}: loss={l:.4f} '
              f'({global_step * n_dev * bs / max(dt, 1e-9):.0f} '
              'seeds/s)')
      if args.ckpt_dir and global_step % args.ckpt_steps == 0:
        save_checkpoint(args.ckpt_dir, global_step, params,
                        opt_state=opt)
        print(f'checkpoint saved at step {global_step}')
    # validation accuracy (reference evaluate loop)
    mll.eval_start(epoch)
    correct = total = 0
    for vb in range(args.val_batches):
      lo = vb * n_dev * bs
      if lo >= val_idx.shape[0]:
        break
      chunk = val_idx[lo:lo + n_dev * bs]
      nv = np.array([min(bs, max(0, chunk.shape[0] - p * bs))
                     for p in range(n_dev)], np.int32)
      pad = n_dev * bs - chunk.shape[0]
      if pad:
        chunk = np.concatenate([chunk, np.full(pad, chunk[-1])])
      c, t = step.eval_step(params, chunk.reshape(n_dev, bs), nv,
                            jax.random.key(10_000 + vb))
      correct += c
      total += t
    acc = correct / max(total, 1)
    mll.eval_accuracy(acc, epoch)
    mll.eval_stop(epoch)
    mll.epoch_stop(epoch)
    print(f'epoch {epoch}: val_acc={acc:.4f} ({correct}/{total})')
    log_rss(f'epoch {epoch} done')

  if args.ckpt_dir:
    save_checkpoint(args.ckpt_dir, global_step, params, opt_state=opt)
    print(f'final checkpoint at step {global_step}')
  mll.run_stop(epoch=args.epochs - 1)
  print('done')


if __name__ == '__main__':
  main()

"""IGBH-style hetero distributed training — the reference's MLPerf GNN
vehicle (examples/igbh/dist_train_rgnn.py): billion-edge heterogeneous
graph, partitioned, RGAT/RSAGE over multi-hop sampled neighborhoods,
data-parallel training.

Single-host demo on the virtual CPU mesh with a synthetic paper/author
graph; on a real slice the same program runs over TPU chips with
per-host partition loading.
"""
import argparse
import os
import sys
import tempfile
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--conv', default='rgat', choices=['rgat', 'rsage'])
  ap.add_argument('--steps', type=int, default=30)
  ap.add_argument('--fanout', default='5,5')
  ap.add_argument('--batch-size', type=int, default=64)
  ap.add_argument('--cpu-mesh', action=argparse.BooleanOptionalAction,
                  default=True,
                  help='--no-cpu-mesh runs on the real device mesh')
  args = ap.parse_args()

  if args.cpu_mesh:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  import jax
  if args.cpu_mesh:
    jax.config.update('jax_platforms', 'cpu')
  import numpy as np
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.parallel import make_mesh
  from glt_tpu.partition import RandomPartitioner
  from glt_tpu.typing import reverse_edge_type
  from common import synthetic_hetero_mag

  ds, num_classes, cites, writes = synthetic_hetero_mag(
      num_papers=4_000, num_authors=2_000)
  fanout = [int(x) for x in args.fanout.split(',')]

  # offline partition (reference: examples/igbh/partition.py)
  root = tempfile.mkdtemp(prefix='igbh_parts_')
  npapers = ds.node_count('paper')
  nauthors = ds.node_count('author')
  ei = {}
  for etype, g in ds.graph.items():
    ptr, other, _ = g.topo.to_coo()
    ei[etype] = (np.stack([ptr, other]) if g.layout == 'CSR'
                 else np.stack([other, ptr]))
  feats = {'paper': ds.node_features['paper'][np.arange(npapers)],
           'author': ds.node_features['author'][np.arange(nauthors)]}
  # insert the reversed write relation so author nodes are reachable from
  # paper seeds (the reference inserts reverse edge types the same way)
  rev_writes = ('paper', 'rev_writes', 'author')
  ei[rev_writes] = ei[writes][::-1].copy()
  RandomPartitioner(root, num_parts=args.num_devices,
                    num_nodes={'paper': npapers, 'author': nauthors},
                    edge_index=ei, node_feat=feats).partition()

  mesh = make_mesh(args.num_devices)
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(args.num_devices)]
  dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t)
            for t in ('paper', 'author')}
  labels = {'paper': ds.node_labels['paper']}

  model = RGNN(edge_types=[reverse_edge_type(cites),
                           reverse_edge_type(writes),
                           reverse_edge_type(rev_writes)],
               hidden_features=64, out_features=num_classes,
               num_layers=len(fanout), conv=args.conv)
  tx = optax.adam(2e-3)
  step = DistHeteroTrainStep(
      dg, dfeats, model, tx, labels,
      {cites: fanout, writes: fanout, rev_writes: fanout},
      batch_size_per_device=args.batch_size, seed_type='paper', seed=0)
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  rng = np.random.default_rng(0)
  for it in range(args.steps):
    seeds = rng.integers(0, npapers, (args.num_devices, args.batch_size))
    params, opt, loss = step(params, opt, seeds,
                             np.full(args.num_devices, args.batch_size),
                             jax.random.key(it))
    if it % 10 == 0:
      print(f'step {it}: loss={float(np.asarray(loss)[0]):.4f}')
  print('done')


if __name__ == '__main__':
  main()

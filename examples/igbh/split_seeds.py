"""Deterministic train/val seed split for IGBH-layout datasets.

TPU equivalent of the reference's examples/igbh/split_seeds.py: a seeded
permutation of the labeled papers, 60% train / ``validation_frac`` val,
saved beside the processed data as train_idx.npy / val_idx.npy.
"""
import argparse
import os

import numpy as np


def split_seeds(path: str, random_seed: int = 42,
                validation_frac: float = 0.01,
                train_frac: float = 0.6) -> None:
  proc = os.path.join(path, 'processed')
  labels = np.load(os.path.join(proc, 'paper', 'node_label.npy'))
  n = labels.shape[0]
  rng = np.random.default_rng(random_seed)
  perm = rng.permutation(n)
  n_train = int(n * train_frac)
  n_val = int(n * validation_frac)
  np.save(os.path.join(proc, 'train_idx.npy'), perm[:n_train])
  np.save(os.path.join(proc, 'val_idx.npy'),
          perm[n_train:n_train + n_val])
  print(f'{n} labeled papers -> {n_train} train / {n_val} val')


if __name__ == '__main__':
  ap = argparse.ArgumentParser()
  ap.add_argument('--path', required=True)
  ap.add_argument('--random_seed', type=int, default=42)
  ap.add_argument('--validation_frac', type=float, default=0.01)
  ap.add_argument('--train_frac', type=float, default=0.6)
  a = ap.parse_args()
  split_seeds(a.path, a.random_seed, a.validation_frac, a.train_frac)

"""Offline COO -> compressed (CSC/CSR) conversion for IGBH-layout data.

TPU equivalent of the reference's examples/igbh/compress_graph.py
(:106-107 saves indptr/indices per edge type after layout conversion)
plus its ``float2half`` feature compression (dataset.py): here features
compress to bfloat16 (the TPU-native half type).

Input layout (the IGBH on-disk convention):
  <root>/processed/<src>__<rel>__<dst>/edge_index.npy     [2, E] COO
  <root>/processed/<ntype>/node_feat.npy                  [N, D]
  <root>/processed/paper/node_label.npy                   [N]

Output:
  <root>/<layout>/<src>__<rel>__<dst>/compressed.npz  (indptr, indices,
  edge_ids) + <root>/<layout>/<ntype>/node_feat_bf16.npy when --bf16.

This environment has no dataset downloads, so ``--synthesize N`` first
materializes a synthetic MAG-shaped graph at that paper count in the
same on-disk layout — the tool chain (synthesize -> compress ->
split_seeds -> dist_train_rgnn) then mirrors the reference's
(download -> compress -> split_seeds -> dist_train_rgnn).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))


def synthesize(root: str, num_papers: int, seed: int = 0,
               feat_dim: int = 128, num_classes: int = 16) -> None:
  """Materialize a synthetic MAG-shaped IGBH-layout dataset on disk:
  paper-cites-paper (~10/paper), author-writes-paper (~3/paper),
  author-affiliated-institute."""
  rng = np.random.default_rng(seed)
  num_authors = max(num_papers // 2, 4)
  num_inst = max(num_papers // 50, 4)
  proc = os.path.join(root, 'processed')
  rels = {
      ('paper', 'cites', 'paper'): (
          rng.integers(0, num_papers, num_papers * 10),
          rng.integers(0, num_papers, num_papers * 10)),
      ('author', 'writes', 'paper'): (
          rng.integers(0, num_authors, num_papers * 3),
          rng.integers(0, num_papers, num_papers * 3)),
      ('author', 'affiliated', 'institute'): (
          rng.integers(0, num_authors, num_authors),
          rng.integers(0, num_inst, num_authors)),
  }
  for (s, r, d), (src, dst) in rels.items():
    ed = os.path.join(proc, f'{s}__{r}__{d}')
    os.makedirs(ed, exist_ok=True)
    np.save(os.path.join(ed, 'edge_index.npy'),
            np.stack([src, dst]).astype(np.int64))
  counts = {'paper': num_papers, 'author': num_authors,
            'institute': num_inst}
  pf = rng.normal(size=(num_papers, feat_dim)).astype(np.float32)
  w = rng.normal(size=(feat_dim, num_classes)).astype(np.float32)
  for t, n in counts.items():
    nd = os.path.join(proc, t)
    os.makedirs(nd, exist_ok=True)
    feat = pf if t == 'paper' else \
        rng.normal(size=(n, feat_dim)).astype(np.float32)
    np.save(os.path.join(nd, 'node_feat.npy'), feat)
  labels = np.argmax(pf @ w, 1).astype(np.int32)
  np.save(os.path.join(proc, 'paper', 'node_label.npy'), labels)
  with open(os.path.join(proc, 'meta.txt'), 'w') as f:
    for t, n in counts.items():
      f.write(f'{t} {n}\n')


def load_meta(root: str) -> dict:
  counts = {}
  with open(os.path.join(root, 'processed', 'meta.txt')) as f:
    for line in f:
      t, n = line.split()
      counts[t] = int(n)
  return counts


def compress(root: str, layout: str = 'CSC', bf16: bool = False,
             topology: bool = True) -> None:
  """COO -> compressed per-etype topology (+ optional bf16 features).

  ``topology=False`` runs only the feature compression — callers that
  re-partition from COO anyway (dist_train_rgnn's synthesize path) skip
  the topology pass they would not read.
  """
  from glt_tpu.data import Topology
  proc = os.path.join(root, 'processed')
  out_root = os.path.join(root, layout.lower())
  counts = load_meta(root)
  for name in (sorted(os.listdir(proc)) if topology else ()):
    path = os.path.join(proc, name, 'edge_index.npy')
    if not os.path.exists(path):
      continue
    s, r, d = name.split('__')
    ei = np.load(path)
    n_rows, n_cols = ((d, s) if layout.upper() == 'CSC' else (s, d))
    topo = Topology(edge_index=ei, layout=layout.upper(),
                    num_rows=counts[n_rows], num_cols=counts[n_cols])
    od = os.path.join(out_root, name)
    os.makedirs(od, exist_ok=True)
    np.savez(os.path.join(od, 'compressed.npz'),
             indptr=topo.indptr, indices=topo.indices,
             edge_ids=topo.edge_ids)
    print(f'{name}: {ei.shape[1]} edges -> {layout} '
          f'(indptr {topo.indptr.shape[0]})')
  if bf16:
    import ml_dtypes
    for t in counts:
      fp = os.path.join(proc, t, 'node_feat.npy')
      if os.path.exists(fp):
        feat = np.load(fp).astype(ml_dtypes.bfloat16)
        od = os.path.join(out_root, t)
        os.makedirs(od, exist_ok=True)
        # .npy cannot express the bfloat16 dtype; store the bit pattern
        # (readers view it back, see dist_train_rgnn.load_igbh_root)
        np.save(os.path.join(od, 'node_feat_bf16.npy'),
                feat.view(np.uint16))
        print(f'{t}: features -> bf16 {feat.shape}')


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--path', required=True,
                  help='dataset root (IGBH on-disk layout)')
  ap.add_argument('--layout', default='CSC', choices=['CSC', 'CSR'])
  ap.add_argument('--bf16', action='store_true',
                  help='also compress features to bfloat16')
  ap.add_argument('--synthesize', type=int, default=0, metavar='PAPERS',
                  help='first materialize a synthetic IGBH-layout '
                       'dataset at this paper count (no downloads here)')
  ap.add_argument('--seed', type=int, default=0)
  args = ap.parse_args()
  if args.synthesize:
    synthesize(args.path, args.synthesize, seed=args.seed)
  compress(args.path, layout=args.layout, bf16=args.bf16)


if __name__ == '__main__':
  main()

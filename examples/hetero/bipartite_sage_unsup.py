"""Bipartite unsupervised SAGE — the reference's
examples/hetero/bipartite_sage_unsup.py (Taobao): user<->item link
prediction with a sparsified item<->item co-occurrence relation, hetero
LinkNeighborLoader over ('user','to','item') seed edges, dot-product
BCE, ROC-AUC eval.

Synthetic stand-in (no downloads): users have latent group preferences,
items belong to groups, so observed links are predictable from graph
structure. item<->item edges connect items co-purchased by >= 2 users —
the same co-occurrence construction the reference computes from the
user-item matrix.
"""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..', '..'))

import common  # noqa: F401

import collections

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.data import Dataset
from glt_tpu.loader import LinkNeighborLoader
from glt_tpu.models import RGNN
from glt_tpu.sampler import NegativeSampling
from glt_tpu.typing import reverse_edge_type


def synthetic_taobao(num_users=600, num_items=300, num_groups=6,
                     links_per_user=8, seed=0):
  rng = np.random.default_rng(seed)
  item_group = rng.integers(0, num_groups, num_items)
  user_pref = rng.integers(0, num_groups, num_users)
  src, dst = [], []
  for u in range(num_users):
    own = np.nonzero(item_group == user_pref[u])[0]
    picks = rng.choice(own, min(links_per_user, own.shape[0]),
                       replace=False)
    src += [u] * picks.shape[0]
    dst += picks.tolist()
  ui = np.stack([np.array(src), np.array(dst)])
  # item<->item co-occurrence (>= 2 shared users), the reference's comat
  per_user = collections.defaultdict(list)
  for u, i in zip(ui[0], ui[1]):
    per_user[u].append(i)
  pair_count = collections.Counter()
  for items in per_user.values():
    for a in items:
      for b in items:
        if a != b:
          pair_count[(a, b)] += 1
  ii = np.array([[a, b] for (a, b), c in pair_count.items()
                 if c >= 2]).T
  if ii.size == 0:
    ii = np.zeros((2, 0), np.int64)
  return ui, ii, num_users, num_items


def roc_auc(y, s):
  order = np.argsort(s)
  ranks = np.empty(len(s))
  ranks[order] = np.arange(1, len(s) + 1)
  pos = y > 0.5
  np_, nn = pos.sum(), (~pos).sum()
  if np_ == 0 or nn == 0:
    return 0.5
  return (ranks[pos].sum() - np_ * (np_ + 1) / 2) / (np_ * nn)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--batch-size', type=int, default=64)
  ap.add_argument('--users', type=int, default=600)
  args = ap.parse_args()

  ui, ii, nu, ni = synthetic_taobao(num_users=args.users,
                                    num_items=args.users // 2)
  u2i = ('user', 'to', 'item')
  i2u = ('item', 'rev_to', 'user')
  i2i = ('item', 'sim', 'item')
  # 80/20 link split (RandomLinkSplit equivalent)
  rng = np.random.default_rng(1)
  perm = rng.permutation(ui.shape[1])
  n_test = ui.shape[1] // 5
  test_edges = ui[:, perm[:n_test]]
  train_edges = ui[:, perm[n_test:]]

  ds = Dataset(edge_dir='out')
  ds.init_graph(
      edge_index={u2i: train_edges, i2u: train_edges[::-1].copy(),
                  i2i: ii},
      num_nodes={'user': nu, 'item': ni})
  # id-encoded features (the reference uses learnable id embeddings;
  # one-hot-free here: a few random fourier features of the id)
  rngf = np.random.default_rng(2)
  ds.init_node_features({
      'user': rngf.normal(size=(nu, 32)).astype(np.float32),
      'item': rngf.normal(size=(ni, 32)).astype(np.float32)})

  loader = LinkNeighborLoader(
      ds, [8, 4], edge_label_index=(u2i, train_edges),
      batch_size=args.batch_size, shuffle=True, seed=0,
      neg_sampling=NegativeSampling('binary', amount=1))

  model = RGNN(edge_types=[reverse_edge_type(u2i), reverse_edge_type(i2u),
                           reverse_edge_type(i2i)],
               hidden_features=64, out_features=32, num_layers=2,
               conv='rsage', trim=False)
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0, return_all=True)
  tx = optax.adam(3e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      emb = model.apply(p, batch, return_all=True)
      eli = batch.metadata['edge_label_index']
      lab = batch.metadata['edge_label']
      zu = jnp.take(emb['user'], eli[0], axis=0)
      zi = jnp.take(emb['item'], eli[1], axis=0)
      logit = (zu * zi).sum(-1)
      return optax.sigmoid_binary_cross_entropy(logit, lab).mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  @jax.jit
  def score(params, batch):
    emb = model.apply(params, batch, return_all=True)
    eli = batch.metadata['edge_label_index']
    zu = jnp.take(emb['user'], eli[0], axis=0)
    zi = jnp.take(emb['item'], eli[1], axis=0)
    return (zu * zi).sum(-1)

  def clean_meta(batch):
    meta = {k: v for k, v in (batch.metadata or {}).items()
            if k in ('edge_label_index', 'edge_label')}
    return batch.replace(metadata=meta)

  eval_loader = LinkNeighborLoader(
      ds, [8, 4], edge_label_index=(u2i, test_edges),
      batch_size=args.batch_size, seed=3,
      neg_sampling=NegativeSampling('binary', amount=1))

  for epoch in range(args.epochs):
    for batch in loader:
      params, opt, loss = step(params, opt, clean_meta(batch))
    ys, ss = [], []
    for batch in eval_loader:
      b = clean_meta(batch)
      ss.append(np.asarray(score(params, b)))
      ys.append(np.asarray(batch.metadata['edge_label']))
    auc = roc_auc(np.concatenate(ys), np.concatenate(ss))
    print(f'epoch {epoch}: loss={float(loss):.4f} test_auc={auc:.4f}')


if __name__ == '__main__':
  main()

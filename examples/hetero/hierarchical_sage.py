"""Hierarchical hetero GraphSAGE — the reference's
examples/hetero/hierarchical_sage.py: hetero NeighborLoader over OGB-MAG
with trim_to_layer per conv layer so layer i only processes the hops it
still needs.

TPU formulation: trimming is STATIC slicing by per-etype hop offsets
(`HeteroBatch.edge_hop_offsets_dict`, built by the hetero sampler), so
every layer's program shrinks at trace time — no dynamic shapes. The
dataset is a synthetic MAG (no downloads here).
"""
import argparse
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..', '..'))

import common  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import RGNN
from glt_tpu.typing import reverse_edge_type

from common import synthetic_hetero_mag


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=2)
  ap.add_argument('--batch-size', type=int, default=128)
  ap.add_argument('--papers', type=int, default=4_000)
  args = ap.parse_args()

  ds, num_classes, cites, writes = synthetic_hetero_mag(
      num_papers=args.papers, num_authors=args.papers // 2)
  train_idx = np.arange(ds.node_count('paper'))

  loader = NeighborLoader(ds, [10, 10], ('paper', train_idx),
                          batch_size=args.batch_size, shuffle=True,
                          seed=0)
  # 'out' sampling emits reversed final keys
  model = RGNN(edge_types=[reverse_edge_type(cites),
                           reverse_edge_type(writes)],
               hidden_features=64, out_features=num_classes,
               num_layers=2, conv='rsage', trim=True)
  b0 = next(iter(loader))
  assert b0.edge_hop_offsets_dict, 'loader must supply trim offsets'
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(1e-2)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      y = batch.y_dict['paper']
      nv = batch.metadata['n_valid']
      mask = jnp.arange(logits.shape[0]) < nv
      l = optax.softmax_cross_entropy_with_integer_labels(logits, y)
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  for epoch in range(args.epochs):
    for batch in loader:
      meta = dict(batch.metadata or {})
      meta['n_valid'] = jnp.asarray(meta.get('n_valid',
                                             args.batch_size))
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    print(f'epoch {epoch}: loss={float(loss):.4f}')


if __name__ == '__main__':
  main()

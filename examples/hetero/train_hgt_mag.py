"""HGT training on a mag-shaped hetero graph — the reference's
examples/hetero/train_hgt_mag.py workload (hetero NeighborLoader +
HGTConv stack, paper-seeded classification) on a synthetic ogbn-mag
proxy (dataset downloads are unavailable in this environment).
"""
import argparse
import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import HGT
from glt_tpu.typing import reverse_edge_type

from common import synthetic_hetero_mag


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=3)
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--hidden', type=int, default=64)
  args = ap.parse_args()

  ds, num_classes, cites, writes = synthetic_hetero_mag()
  mp_etypes = [reverse_edge_type(cites), reverse_edge_type(writes)]
  loader = NeighborLoader(ds, {cites: [5, 5], writes: [5, 5]},
                          input_nodes=('paper', np.arange(2000)),
                          batch_size=128, shuffle=True, seed=0)
  model = HGT(node_types=['paper', 'author'], edge_types=mp_etypes,
              hidden_features=args.hidden, out_features=num_classes,
              num_layers=2, heads=args.heads)
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(2e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      l = optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y_dict['paper'])
      return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  for epoch in range(args.epochs):
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
    print(f'epoch {epoch}: loss={float(loss):.4f}')


if __name__ == '__main__':
  main()

"""Table-sourced training — the reference's examples/pai/ogbn_products
workload (TableDataset fed by ODPS table readers, then standard
supervised SAGE). The ODPS service is unreachable outside Alibaba
cloud; the reader protocol is the capability, so this script feeds the
same TableDataset.load path from CSV readers written to a temp dir —
swap `csv_*_reader` for `odps_table_reader('odps://...')` on PAI.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import common  # noqa: F401  (GLT_PLATFORM handling)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from glt_tpu.data.table_dataset import (
    TableDataset, csv_edge_reader, csv_node_reader,
)
from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE


def write_tables(root, num_nodes=2_000, avg_deg=8, feat_dim=32,
                 num_classes=8, seed=0):
  """Emit edge/node tables in the (src,dst[,weight]) / (id,feat...,label)
  record layout the readers stream."""
  rng = np.random.default_rng(seed)
  e = num_nodes * avg_deg
  src = rng.integers(0, num_nodes, e)
  dst = rng.integers(0, num_nodes, e)
  edge_csv = os.path.join(root, 'edges.csv')
  with open(edge_csv, 'w') as f:
    for s, d in zip(src, dst):
      f.write(f'{s},{d}\n')
  feats = rng.normal(size=(num_nodes, feat_dim)).astype(np.float32)
  w = rng.normal(size=(feat_dim, num_classes)).astype(np.float32)
  labels = np.argmax(feats @ w, 1)
  node_csv = os.path.join(root, 'nodes.csv')
  # reader record layout: id,<f0:f1:...>,label (csv_node_reader)
  with open(node_csv, 'w') as f:
    for i in range(num_nodes):
      row = ':'.join(f'{v:.6f}' for v in feats[i])
      f.write(f'{i},{row},{labels[i]}\n')
  return edge_csv, node_csv, num_nodes, num_classes


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--epochs', type=int, default=2)
  ap.add_argument('--batch-size', type=int, default=256)
  args = ap.parse_args()

  with tempfile.TemporaryDirectory() as root:
    edge_csv, node_csv, n, num_classes = write_tables(root)
    ds = TableDataset(edge_dir='out').load(
        edge_reader=csv_edge_reader(edge_csv),
        node_reader=csv_node_reader(node_csv, label_col=2),
        num_nodes=n)

    loader = NeighborLoader(ds, [10, 5], input_nodes=np.arange(n),
                            batch_size=args.batch_size, shuffle=True,
                            seed=0)
    model = GraphSAGE(hidden_features=128, out_features=num_classes,
                      num_layers=2)
    b0 = next(iter(loader))
    params = model.init(jax.random.key(0), b0)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, batch):
      def loss_fn(p):
        logits = model.apply(p, batch)
        mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch.y)
        return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)
      loss, g = jax.value_and_grad(loss_fn)(params)
      up, opt = tx.update(g, opt)
      return optax.apply_updates(params, up), opt, loss

    for epoch in range(args.epochs):
      for batch in loader:
        meta = dict(batch.metadata)
        meta['n_valid'] = jnp.asarray(meta['n_valid'])
        params, opt, loss = step(params, opt,
                                 batch.replace(metadata=meta))
      print(f'epoch {epoch}: loss={float(loss):.4f}')


if __name__ == '__main__':
  main()

"""Feature store shared across processes — the reference's
examples/feature_mp.py (Feature IPC via CUDA handles). The TPU analogue
ships feature *lookups* between processes through the native shm
channel: a worker process resolves rows from its copy and streams them
back (the pattern the mp sampling workers use for collected features)."""
import multiprocessing as mp
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def feature_worker(chan_req, chan_resp):
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.data import Feature
  rng = np.random.default_rng(0)
  feats = rng.normal(size=(1000, 16)).astype(np.float32)
  f = Feature(feats, split_ratio=0.5)
  while True:
    msg = chan_req.recv(timeout_ms=30_000)
    if '#EXIT' in msg:
      break
    chan_resp.send({'rows': f[msg['ids']]})


def main():
  from glt_tpu.channel import ShmChannel
  chan_req = ShmChannel(capacity_bytes=1 << 20)
  chan_resp = ShmChannel(capacity_bytes=1 << 22)
  p = mp.get_context('spawn').Process(
      target=feature_worker, args=(chan_req, chan_resp))
  p.start()
  rng = np.random.default_rng(1)
  for i in range(5):
    ids = rng.integers(0, 1000, 64)
    chan_req.send({'ids': ids})
    out = chan_resp.recv(timeout_ms=30_000)
    print(f'batch {i}: got {out["rows"].shape} rows')
  chan_req.send({'#EXIT': np.array([1])})
  p.join(timeout=15)
  chan_req.close()
  chan_resp.close()
  print('done')


if __name__ == '__main__':
  main()

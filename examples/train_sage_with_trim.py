"""Hop-trimming A/B — the reference's train_sage_prod_with_trim.py
workload (its comment :38 wires `num_sampled_nodes/edges` into PyG's
trim_to_layer so layer i only propagates the hops later layers read).

Here trimming is built into the models (`trim=True`, the default):
`edge_hop_offsets` are STATIC per-hop slices of the padded edge buffer,
so each layer's gathers/matmuls shrink with zero recompilation. This
example trains the same model both ways: on DEDUPLICATED batches a
deep hop can re-discover a shallow node, so trimming (like the
reference's trim_to_layer) is an approximation, not a bit-exact no-op
— the check is equal-quality accuracy at fewer processed edge slots
per layer.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import common  # noqa: F401  — honors GLT_PLATFORM before backend init
import jax
import numpy as np
import optax

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE

from common import synthetic_products


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--nodes', type=int, default=4_000)
  ap.add_argument('--epochs', type=int, default=1)
  ap.add_argument('--batch-size', type=int, default=256)
  ap.add_argument('--fanout', default='15,10,5')
  args = ap.parse_args()

  ds, num_classes = synthetic_products(num_nodes=args.nodes)
  fanout = [int(x) for x in args.fanout.split(',')]

  def make_loader():
    # fresh loader per run: shuffle order and sampling keys must be
    # identical for the two trajectories to be comparable
    return NeighborLoader(ds, fanout,
                          input_nodes=ds.get_split('train'),
                          batch_size=args.batch_size, shuffle=True,
                          seed=0, rng=np.random.default_rng(0))

  b0 = next(iter(make_loader()))
  offs = b0.edge_hop_offsets
  kept = offs[len(fanout) - 0] if offs else None  # layer-0 slots
  print(f'edge buffer {b0.row.shape[0]} slots; per-layer trim offsets '
        f'{offs}')

  def train(trim):
    model = GraphSAGE(hidden_features=128, out_features=num_classes,
                      num_layers=len(fanout), trim=trim)
    params = model.init(jax.random.key(0), b0)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, batch):
      def loss_fn(p):
        logits = model.apply(p, batch)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch.y).mean()
      loss, g = jax.value_and_grad(loss_fn)(params)
      up, opt = tx.update(g, opt)
      return optax.apply_updates(params, up), opt, loss

    t0 = time.time()
    loader = make_loader()
    for epoch in range(args.epochs):
      for batch in loader:
        params, opt, loss = step(params, opt, batch)
    jax.block_until_ready(loss)
    dt = time.time() - t0
    # test accuracy over a fixed eval slice
    test_idx = ds.get_split('test')[:1024]
    correct = total = 0
    ev = NeighborLoader(ds, fanout, input_nodes=test_idx,
                        batch_size=args.batch_size, seed=1,
                        rng=np.random.default_rng(1))
    for batch in ev:
      logits = model.apply(params, batch)
      nv = batch.metadata['n_valid'] if batch.metadata else len(logits)
      pred = np.asarray(logits).argmax(1)[:nv]
      correct += int((pred == np.asarray(batch.y)[:nv]).sum())
      total += int(nv)
    return float(loss), correct / max(total, 1), dt

  loss_t, acc_t, dt_t = train(trim=True)
  loss_f, acc_f, dt_f = train(trim=False)
  print(f'trim=True : loss={loss_t:.4f}  acc={acc_t:.4f}  '
        f'wall={dt_t:.1f}s')
  print(f'trim=False: loss={loss_f:.4f}  acc={acc_f:.4f}  '
        f'wall={dt_f:.1f}s')
  assert np.isfinite(loss_t) and np.isfinite(loss_f)
  assert abs(acc_t - acc_f) < 0.15, (acc_t, acc_f)
  print('done')


if __name__ == '__main__':
  main()

"""Device-resident graph storage.

Reference: graphlearn_torch/python/data/graph.py:184-306 (py Graph binding a
native CSR container, include/graph.h:30-133). The reference's residency
modes CPU / DMA / ZERO_COPY map to:

  * ``GraphMode.HBM``  -- indptr/indices/(eids,weights) live as jax arrays in
    TPU HBM (the DMA analogue, graph.cu:69-80).
  * ``GraphMode.HOST`` -- arrays stay as numpy in host RAM; jitted code
    receives gathered slices via the loader's host stage (the ZERO_COPY/UVA
    analogue for beyond-HBM topologies).

There is no CUDA-IPC equivalent (data/graph.py:257-306): under SPMD a single
jax global array is already visible to every participating device, so the
share-via-handle machinery is unnecessary by design.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np

from ..typing import GraphMode
from .topology import Topology


class Graph:
  """Binds a host :class:`Topology` to device arrays, lazily.

  Lazy-init mirrors the reference (data/graph.py:219-252): the device copy
  happens on first access so partition loading can build many Graph objects
  cheaply.
  """

  def __init__(self, topo: Topology, mode: GraphMode = GraphMode.HBM,
               device: Optional[jax.Device] = None):
    if isinstance(mode, str):
      mode = GraphMode(mode.upper())
    self.topo = topo
    self.mode = mode
    self.device = device
    self._indptr = None
    self._indices = None
    self._edge_ids = None
    self._edge_weights = None
    self._initialized = False
    self._window_cache = {}   # field -> (padded_width, array)
    self._window_lock = threading.Lock()

  # threading.Lock is unpicklable; producers currently ship a
  # dataset_builder callable rather than Graph objects, but mp channel
  # payloads / checkpoints may pickle a Graph directly. Device arrays and
  # the window cache are dropped too: they are lazily rebuilt, and a
  # fresh process must re-place them on its own devices anyway.
  def __getstate__(self):
    state = self.__dict__.copy()
    state['_window_lock'] = None
    state['_window_cache'] = {}
    if self.mode == GraphMode.HBM:
      state['_indptr'] = state['_indices'] = None
      state['_edge_ids'] = state['_edge_weights'] = None
      state['_initialized'] = False
    return state

  def __setstate__(self, state):
    self.__dict__.update(state)
    self._window_lock = threading.Lock()

  # -- lazy init ---------------------------------------------------------

  def lazy_init(self) -> None:
    if self._initialized:
      return
    if self.mode == GraphMode.HBM:
      put = lambda a: (jax.device_put(a, self.device)
                       if a is not None else None)
    else:  # HOST: keep numpy; jnp ops on host stage use them directly
      put = lambda a: a
    # indptr is int64 on host (billion-edge safe); narrow for device
    # placement when the edge count fits int32.
    indptr = self.topo.indptr
    if self.num_edges < np.iinfo(np.int32).max:
      indptr = indptr.astype(np.int32, copy=False)
    self._indptr = put(indptr)
    self._indices = put(self.topo.indices)
    self._edge_ids = put(self.topo.edge_ids)
    self._edge_weights = put(self.topo.edge_weights)
    self._initialized = True

  # NOTE on edge-array length: after any windowed sample has called
  # ``window_arrays``, the edge arrays below may carry a sentinel-padded
  # tail (indices/edge_ids = -1, edge_weights = 0.0) — the padded copy
  # supersedes the original so only ONE resident copy exists (see
  # window_arrays). The LOGICAL edge list is always ``[:num_edges]``;
  # ``shape[0] == num_edges`` is NOT an invariant of these properties.
  # Kernels are insensitive (gathers clip into the logical prefix);
  # code iterating a full array must slice to ``num_edges`` first.

  @property
  def indptr(self):
    self.lazy_init()
    return self._indptr

  @property
  def indices(self):
    """Neighbor ids; may be sentinel-padded past ``num_edges`` (see
    class note above)."""
    self.lazy_init()
    return self._indices

  @property
  def edge_ids(self):
    """Edge ids; may be sentinel-padded past ``num_edges`` (see class
    note above)."""
    self.lazy_init()
    return self._edge_ids

  @property
  def edge_weights(self):
    """Edge weights; may be sentinel-padded past ``num_edges`` (see
    class note above)."""
    self.lazy_init()
    return self._edge_weights

  def window_arrays(self, width: int, fields=('indices', 'edge_ids',
                                              'edge_weights')):
    """Edge arrays padded by ``width`` trailing sentinel elements — the
    precondition of the Pallas window-DMA gather
    (ops/pallas_kernels.py::gather_windows): every [start, start+width)
    window of a real row then lies fully inside the array. The padded
    copy SUPERSEDES the original device array (``self._<field>`` is
    rebound to it and the original freed): row gathers address the same
    logical prefix and the clip bounds only loosen, so one resident copy
    serves both the window-DMA and XLA-gather paths — at papers100M
    scale a duplicate edge array would cost ~GBs of HBM. Peak transient
    HBM during the rebind is ~2x the field (concatenate reads old,
    writes new), same as the old steady state. Callers name only the
    fields they read (the weighted path needs just ``edge_weights``);
    entries are cached per (width, field), grown to the max width ever
    asked, and are None where the source array is None.
    """
    if self.mode != GraphMode.HBM:
      # jnp.concatenate below would silently device-place a HOST-mode
      # (beyond-HBM) edge array, defeating the residency mode; the
      # window-DMA path requires device-resident topology, so samplers
      # fall back to the XLA gather when this returns None fields.
      return {f: None for f in fields}
    self.lazy_init()
    import jax.numpy as jnp
    fills = {'indices': -1, 'edge_ids': -1, 'edge_weights': 0.0}
    out = {}
    with self._window_lock:
      for f in fields:
        have = self._window_cache.get(f)
        # one padded copy per FIELD, grown to the max width ever asked:
        # containment (start + w <= len) holds for every w <= padded
        # width, so distinct hop widths share the copy instead of each
        # materializing another full-edge-array duplicate
        if have is None or have[0] < width:
          a = getattr(self, '_' + f)
          if a is None:
            have = (width, None)
          else:
            # logical prefix: when growing an existing padded copy the
            # stored array already carries the previous width's tail.
            # Samplers call this at TRACE time (one_hop closures), so
            # the pad must evaluate eagerly — a staged concatenate
            # would rebind self._<f> to a tracer that leaks into the
            # next compiled program (multi-bucket serving traces the
            # same graph more than once).
            with jax.ensure_compile_time_eval():
              a = jnp.asarray(a)[:self.num_edges]
              padded = jnp.concatenate(
                  [a, jnp.full((width,), fills[f], a.dtype)])
            setattr(self, '_' + f, padded)  # supersede: one HBM copy
            have = (width, padded)
          self._window_cache[f] = have
        out[f] = have[1]
    return out

  def indptr_pad(self):
    """The CSR offsets with ONE trailing ``num_edges`` sentinel
    (``[N + 2]`` int32) — the cross-hop walk kernel's row-window source
    (ops/pallas_kernels.py::sample_walk_dedup): a clamped 2-wide read
    at row ``min(id, N)`` then reproduces the element path's
    per-element ``take(..., mode='clip')`` start/degree semantics for
    masked frontier rows. Built eagerly once and cached (the sampler
    builds one FusedHopPlan per compiled batch shape — multi-bucket
    serving must not materialize one padded copy per bucket)."""
    self.lazy_init()
    with self._window_lock:
      have = self._window_cache.get('indptr_pad')
      if have is None:
        import jax.numpy as jnp
        with jax.ensure_compile_time_eval():
          have = jnp.concatenate(
              [jnp.asarray(self.indptr, jnp.int32),
               jnp.full((1,), int(self.num_edges), jnp.int32)])
        self._window_cache['indptr_pad'] = have
      return have

  def hub_count(self, width: int) -> int:
    """Number of rows with degree > ``width`` — the exact hub capacity
    ``H`` of the windowed sampling paths (``sample_neighbors``'s
    ``window=(W, H)``): derived host-side from the true degree
    distribution, once per width, so the bit-identical window/pallas
    guarantee is unconditional. Cached alongside the window arrays
    (same lock; cheap per-width recompute on unpickle)."""
    with self._window_lock:
      key = ('hub_count', int(width))
      have = self._window_cache.get(key)
      if have is None:
        deg = np.diff(self.topo.indptr)
        have = int((deg > int(width)).sum())
        self._window_cache[key] = have
      return have

  # -- probes (reference graph.cu:30-48 LookupDegreeKernel) ---------------

  @property
  def num_nodes(self) -> int:
    return self.topo.num_nodes

  @property
  def num_edges(self) -> int:
    return self.topo.num_edges

  @property
  def layout(self) -> str:
    return self.topo.layout

  def degree(self, ids) -> np.ndarray:
    ids = np.asarray(ids)
    return self.topo.indptr[ids + 1] - self.topo.indptr[ids]

"""TableDataset — build datasets from tabular edge/node sources.

Reference: graphlearn_torch/python/data/table_dataset.py (PAI/ODPS
tables via common_io readers) and distributed/dist_table_dataset.py. The
ODPS service is Alibaba-cloud-specific; the capability kept here is the
*reader protocol*: any iterable yielding (ids..., payload) record chunks
can feed a Dataset — plug in ODPS readers where available, CSV/npz
readers elsewhere.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from ..utils import as_numpy
from .dataset import Dataset

#: a reader yields chunks: edge readers -> (src_ids, dst_ids[, weights]);
#: node readers -> (node_ids, feature_rows[, labels])
TableReader = Iterable


class TableDataset(Dataset):
  """Assembles a Dataset by streaming table readers (reference
  table_dataset.py:30-100)."""

  def load(self,
           edge_reader: Optional[TableReader] = None,
           node_reader: Optional[TableReader] = None,
           num_nodes: Optional[int] = None,
           directed: bool = True,
           graph_mode='HBM') -> 'TableDataset':
    srcs, dsts, weights = [], [], []
    if edge_reader is not None:
      for rec in edge_reader:
        srcs.append(as_numpy(rec[0]).astype(np.int64))
        dsts.append(as_numpy(rec[1]).astype(np.int64))
        if len(rec) > 2 and rec[2] is not None:
          weights.append(as_numpy(rec[2]).astype(np.float32))
    ids_l, feats_l, labels_l = [], [], []
    if node_reader is not None:
      for rec in node_reader:
        ids_l.append(as_numpy(rec[0]).astype(np.int64))
        feats_l.append(as_numpy(rec[1]))
        if len(rec) > 2 and rec[2] is not None:
          labels_l.append(as_numpy(rec[2]))

    if srcs:
      src = np.concatenate(srcs)
      dst = np.concatenate(dsts)
      if not directed:
        src, dst = (np.concatenate([src, dst]),
                    np.concatenate([dst, src]))
      w = np.concatenate(weights) if weights else None
      if not directed and w is not None:
        w = np.concatenate([w, w])
      n = num_nodes or int(max(src.max(), dst.max())) + 1
      self.init_graph(edge_index=np.stack([src, dst]), edge_weights=w,
                      num_nodes=n, graph_mode=graph_mode)
    if ids_l:
      ids = np.concatenate(ids_l)
      feats = np.concatenate(feats_l)
      # table must cover every graph node, not just ids seen by the reader
      n_rows = max(int(ids.max()) + 1,
                   num_nodes or 0,
                   self.graph.num_nodes if self.graph is not None else 0)
      dense = np.zeros((n_rows, feats.shape[1]), feats.dtype)
      dense[ids] = feats
      self.init_node_features(dense)
      if labels_l:
        labels = np.concatenate(labels_l)
        dense_y = np.zeros(n_rows, labels.dtype)
        dense_y[ids] = labels
        self.init_node_labels(dense_y)
    return self


  def load_tables(self,
                  edge_tables=None,
                  node_tables=None,
                  num_nodes=None,
                  directed: bool = True,
                  graph_mode='HBM',
                  reader_batch_size: int = 1024,
                  reader_threads: int = 10) -> 'TableDataset':
    """Hetero-capable table loading (reference table_dataset.py:31-105):
    ``edge_tables`` maps EdgeType -> source, ``node_tables`` maps
    NodeType -> source. A source is either a reader iterable (see the
    module protocol) or an ``odps://`` URL resolved through the gated
    :func:`odps_table_reader` adapter. Single-entry dicts collapse to a
    homogeneous dataset, exactly as the reference does.
    """
    def resolve(source, kind):
      if isinstance(source, str):
        return odps_table_reader(source, kind=kind,
                                 batch_size=reader_batch_size,
                                 num_threads=reader_threads)
      return source

    edge_tables = edge_tables or {}
    node_tables = node_tables or {}
    e_hetero = len(edge_tables) > 1
    n_hetero = len(node_tables) > 1

    edge_index, weights_d = {}, {}
    for etype, src in edge_tables.items():
      srcs, dsts, ws = [], [], []
      for rec in resolve(src, 'edge'):
        srcs.append(as_numpy(rec[0]).astype(np.int64))
        dsts.append(as_numpy(rec[1]).astype(np.int64))
        if len(rec) > 2 and rec[2] is not None:
          ws.append(as_numpy(rec[2]).astype(np.float32))
      s = np.concatenate(srcs)
      d = np.concatenate(dsts)
      w = np.concatenate(ws) if ws else None
      if not directed:
        s, d = np.concatenate([s, d]), np.concatenate([d, s])
        w = np.concatenate([w, w]) if w is not None else None
      edge_index[etype] = np.stack([s, d])
      if w is not None:
        weights_d[etype] = w

    feats_by_type, labels_by_type, counts = {}, {}, {}
    for ntype, src in node_tables.items():
      ids_l, feats_l, labels_l = [], [], []
      for rec in resolve(src, 'node'):
        ids_l.append(as_numpy(rec[0]).astype(np.int64))
        feats_l.append(as_numpy(rec[1]))
        if len(rec) > 2 and rec[2] is not None:
          labels_l.append(as_numpy(rec[2]))
      ids = np.concatenate(ids_l)
      feats = np.concatenate(feats_l)
      n_rows = int(ids.max()) + 1
      if isinstance(num_nodes, dict):
        n_rows = max(n_rows, num_nodes.get(ntype, 0))
      elif num_nodes:
        n_rows = max(n_rows, num_nodes)
      dense = np.zeros((n_rows, feats.shape[1]), feats.dtype)
      dense[ids] = feats
      feats_by_type[ntype] = dense
      counts[ntype] = n_rows
      if labels_l:
        labels = np.concatenate(labels_l)
        dense_y = np.zeros(n_rows, labels.dtype)
        dense_y[ids] = labels
        labels_by_type[ntype] = dense_y

    if edge_index:
      if e_hetero or n_hetero:
        nn = dict(counts)
        for (s_t, _, d_t), ei in edge_index.items():
          for t, col in ((s_t, ei[0]), (d_t, ei[1])):
            nn[t] = max(nn.get(t, 0), int(col.max()) + 1 if col.size
                        else 0)
        if isinstance(num_nodes, dict):
          for t, v in num_nodes.items():
            nn[t] = max(nn.get(t, 0), v)
        self.init_graph(edge_index=edge_index,
                        edge_weights=weights_d or None,
                        num_nodes=nn, graph_mode=graph_mode)
      else:
        (etype, ei), = edge_index.items()
        if isinstance(num_nodes, dict):  # single-entry hetero spec
          num_nodes = max(num_nodes.values())
        # widen to the observed id space, mirroring the hetero branch
        n = max(num_nodes or 0,
                (int(ei.max()) + 1) if ei.size else 1,
                *(counts.values() or [0]))
        self.init_graph(edge_index=ei,
                        edge_weights=weights_d.get(etype),
                        num_nodes=n, graph_mode=graph_mode)
    if feats_by_type:
      if e_hetero or n_hetero:
        self.init_node_features(feats_by_type)
        if labels_by_type:
          self.init_node_labels(labels_by_type)
      else:
        (feat,) = feats_by_type.values()
        self.init_node_features(feat)
        if labels_by_type:
          (lab,) = labels_by_type.values()
          self.init_node_labels(lab)
    return self


def odps_table_reader(url: str, kind: str = 'edge',
                      batch_size: int = 1024, num_threads: int = 10):
  """ODPS table reader adapter (reference common_io usage,
  table_dataset.py:80-105): yields record chunks from an
  ``odps://project/tables/name`` URL. Gated on the PAI-only common_io
  package; everywhere else, pass reader iterables (csv_edge_reader /
  csv_node_reader are drop-in stand-ins with the same chunk protocol).
  """
  try:
    import common_io  # noqa: F401
  except ImportError as e:
    raise ImportError(
        'odps:// table sources need the common_io package (available '
        'on PAI); pass a reader iterable such as csv_edge_reader '
        'instead') from e
  reader = common_io.table.TableReader(url, num_threads=num_threads,
                                       capacity=batch_size * 10)
  try:
    while True:
      try:
        recs = reader.read(batch_size, allow_smaller_final_batch=True)
      except common_io.exception.OutOfRangeException:
        return
      if not recs:
        return
      cols = list(zip(*recs))
      if kind == 'edge':
        yield (np.asarray(cols[0], np.int64),
               np.asarray(cols[1], np.int64)) + (
                   (np.asarray(cols[2], np.float32),)
                   if len(cols) > 2 else ())
      else:
        ids = np.asarray(cols[0], np.int64)
        feats = np.stack([np.fromstring(c, sep=':', dtype=np.float32)
                          if isinstance(c, (str, bytes))
                          else np.asarray(c, np.float32)
                          for c in cols[1]])
        rest = ((np.asarray(cols[2]),) if len(cols) > 2 else ())
        yield (ids, feats) + rest
  finally:
    reader.close()


def csv_edge_reader(path: str, chunk_size: int = 1_000_000,
                    src_col: int = 0, dst_col: int = 1,
                    weight_col: Optional[int] = None,
                    delimiter: str = ','):
  """Chunked CSV edge reader (the common_io stand-in)."""
  import itertools
  with open(path) as f:
    while True:
      rows = list(itertools.islice(f, chunk_size))
      if not rows:
        return
      parts = [r.rstrip('\n').split(delimiter) for r in rows if r.strip()]
      src = np.array([int(p[src_col]) for p in parts], np.int64)
      dst = np.array([int(p[dst_col]) for p in parts], np.int64)
      if weight_col is not None:
        w = np.array([float(p[weight_col]) for p in parts], np.float32)
        yield src, dst, w
      else:
        yield src, dst


def csv_node_reader(path: str, chunk_size: int = 1_000_000,
                    id_col: int = 0, label_col: Optional[int] = None,
                    delimiter: str = ',', feat_delimiter: str = ':'):
  """Chunked CSV node reader: ``id,<f0:f1:...>[,label]`` rows."""
  import itertools
  with open(path) as f:
    while True:
      rows = list(itertools.islice(f, chunk_size))
      if not rows:
        return
      parts = [r.rstrip('\n').split(delimiter) for r in rows if r.strip()]
      ids = np.array([int(p[id_col]) for p in parts], np.int64)
      feats = np.stack([
          np.array(p[id_col + 1].split(feat_delimiter), np.float32)
          for p in parts])
      if label_col is not None:
        labels = np.array([int(p[label_col]) for p in parts], np.int32)
        yield ids, feats, labels
      else:
        yield ids, feats

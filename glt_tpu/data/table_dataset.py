"""TableDataset — build datasets from tabular edge/node sources.

Reference: graphlearn_torch/python/data/table_dataset.py (PAI/ODPS
tables via common_io readers) and distributed/dist_table_dataset.py. The
ODPS service is Alibaba-cloud-specific; the capability kept here is the
*reader protocol*: any iterable yielding (ids..., payload) record chunks
can feed a Dataset — plug in ODPS readers where available, CSV/npz
readers elsewhere.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from ..utils import as_numpy
from .dataset import Dataset

#: a reader yields chunks: edge readers -> (src_ids, dst_ids[, weights]);
#: node readers -> (node_ids, feature_rows[, labels])
TableReader = Iterable


class TableDataset(Dataset):
  """Assembles a Dataset by streaming table readers (reference
  table_dataset.py:30-100)."""

  def load(self,
           edge_reader: Optional[TableReader] = None,
           node_reader: Optional[TableReader] = None,
           num_nodes: Optional[int] = None,
           directed: bool = True,
           graph_mode='HBM') -> 'TableDataset':
    srcs, dsts, weights = [], [], []
    if edge_reader is not None:
      for rec in edge_reader:
        srcs.append(as_numpy(rec[0]).astype(np.int64))
        dsts.append(as_numpy(rec[1]).astype(np.int64))
        if len(rec) > 2 and rec[2] is not None:
          weights.append(as_numpy(rec[2]).astype(np.float32))
    ids_l, feats_l, labels_l = [], [], []
    if node_reader is not None:
      for rec in node_reader:
        ids_l.append(as_numpy(rec[0]).astype(np.int64))
        feats_l.append(as_numpy(rec[1]))
        if len(rec) > 2 and rec[2] is not None:
          labels_l.append(as_numpy(rec[2]))

    if srcs:
      src = np.concatenate(srcs)
      dst = np.concatenate(dsts)
      if not directed:
        src, dst = (np.concatenate([src, dst]),
                    np.concatenate([dst, src]))
      w = np.concatenate(weights) if weights else None
      if not directed and w is not None:
        w = np.concatenate([w, w])
      n = num_nodes or int(max(src.max(), dst.max())) + 1
      self.init_graph(edge_index=np.stack([src, dst]), edge_weights=w,
                      num_nodes=n, graph_mode=graph_mode)
    if ids_l:
      ids = np.concatenate(ids_l)
      feats = np.concatenate(feats_l)
      # table must cover every graph node, not just ids seen by the reader
      n_rows = max(int(ids.max()) + 1,
                   num_nodes or 0,
                   self.graph.num_nodes if self.graph is not None else 0)
      dense = np.zeros((n_rows, feats.shape[1]), feats.dtype)
      dense[ids] = feats
      self.init_node_features(dense)
      if labels_l:
        labels = np.concatenate(labels_l)
        dense_y = np.zeros(n_rows, labels.dtype)
        dense_y[ids] = labels
        self.init_node_labels(dense_y)
    return self


def csv_edge_reader(path: str, chunk_size: int = 1_000_000,
                    src_col: int = 0, dst_col: int = 1,
                    weight_col: Optional[int] = None,
                    delimiter: str = ','):
  """Chunked CSV edge reader (the common_io stand-in)."""
  import itertools
  with open(path) as f:
    while True:
      rows = list(itertools.islice(f, chunk_size))
      if not rows:
        return
      parts = [r.rstrip('\n').split(delimiter) for r in rows if r.strip()]
      src = np.array([int(p[src_col]) for p in parts], np.int64)
      dst = np.array([int(p[dst_col]) for p in parts], np.int64)
      if weight_col is not None:
        w = np.array([float(p[weight_col]) for p in parts], np.float32)
        yield src, dst, w
      else:
        yield src, dst

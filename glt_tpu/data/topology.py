"""Layout-normalizing graph topology container.

Reference: graphlearn_torch/python/data/graph.py:28-181 (Topology) and
graphlearn_torch/python/utils/topo.py:22-91 (coo_to_csr/csc). The reference
depends on torch_sparse for conversions; here all conversions are host-side
numpy (one-time cost) and the device currency is CSR/CSC with **columns
sorted within each row** — sorted adjacency is what makes the TPU
negative-sampler's edge-membership check a vectorized binary search
(vs the reference's per-thread binary search, random_negative_sampler.cu:37-54).

Bipartite-aware: the pointer axis (rows) and the indices axis (cols) carry
independent node counts, so hetero edge types like ('user','u2i','item')
compress and flip correctly. ``indptr`` is always int64 on the host — a
graph with >= 2^31 edges (IGBH-full scale) must not wrap; device placement
narrows it to int32 only when the edge count allows.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils import as_numpy


class Topology:
  """CSR ('out' edges, indptr over src) or CSC ('in' edges, indptr over dst).

  Args:
    edge_index: [2, E] COO (row=src, col=dst), mutually exclusive with
      indptr/indices.
    indptr/indices: pre-built compressed representation.
    edge_ids: original edge ids aligned with the *input* edge order; after
      normalization ``self.edge_ids[k]`` is the original id of compressed
      slot k (so features indexed by original eid keep working).
    edge_weights: optional per-edge weights, same alignment rules.
    layout: 'CSR' | 'CSC' | 'COO'. For COO input, the *target* layout to
      build ('CSR' default). For compressed input, what the given
      indptr/indices already are.
    num_nodes: node count when src and dst share an id space (homogeneous).
    num_rows/num_cols: independent axis sizes for bipartite edge types;
      rows = the pointer axis of the *chosen layout* (src for CSR, dst for
      CSC), cols = the indices axis.
  """

  def __init__(
      self,
      edge_index: Optional[np.ndarray] = None,
      indptr: Optional[np.ndarray] = None,
      indices: Optional[np.ndarray] = None,
      edge_ids: Optional[np.ndarray] = None,
      edge_weights: Optional[np.ndarray] = None,
      layout: str = 'CSR',
      num_nodes: Optional[int] = None,
      num_rows: Optional[int] = None,
      num_cols: Optional[int] = None,
      index_dtype=np.int32,
  ):
    layout = layout.upper()
    if layout == 'COO':
      layout = 'CSR'
    if layout not in ('CSR', 'CSC'):
      raise ValueError(f'unsupported layout {layout!r}')
    self.layout = layout
    self._index_dtype = index_dtype

    if num_nodes is not None:
      num_rows = num_nodes if num_rows is None else num_rows
      num_cols = num_nodes if num_cols is None else num_cols

    if edge_index is not None:
      edge_index = as_numpy(edge_index)
      row, col = edge_index[0], edge_index[1]
      if layout == 'CSC':
        row, col = col, row
      self.num_rows = int(num_rows) if num_rows is not None else (
          int(row.max()) + 1 if row.size else 0)
      self.num_cols = int(num_cols) if num_cols is not None else (
          int(col.max()) + 1 if col.size else 0)
      self.indptr, self.indices, perm = _compress(
          row, col, self.num_rows, index_dtype,
          num_cols=self.num_cols if num_cols is not None else None)
      edge_ids = as_numpy(edge_ids)
      if edge_ids is not None:
        self.edge_ids = edge_ids[perm]
      else:
        self.edge_ids = perm.astype(np.int64, copy=False)
      w = as_numpy(edge_weights)
      self.edge_weights = w[perm] if w is not None else None
    elif indptr is not None and indices is not None:
      self.indptr = as_numpy(indptr).astype(np.int64, copy=False)
      self.indices = as_numpy(indices).astype(index_dtype, copy=False)
      self.num_rows = (int(num_rows) if num_rows is not None
                       else self.indptr.shape[0] - 1)
      self.num_cols = int(num_cols) if num_cols is not None else (
          int(self.indices.max()) + 1 if self.indices.size else 0)
      self.indptr, self.indices, perm = _sort_within_rows(
          self.indptr, self.indices)
      eid = as_numpy(edge_ids)
      self.edge_ids = (eid[perm] if eid is not None
                       else perm.astype(np.int64, copy=False))
      w = as_numpy(edge_weights)
      self.edge_weights = w[perm] if w is not None else None
    else:
      raise ValueError('provide either edge_index or indptr+indices')

    if self.indptr.shape[0] - 1 < self.num_rows:
      # pad indptr so every row node has a (possibly empty) row
      pad = np.full(self.num_rows + 1 - self.indptr.shape[0],
                    self.indptr[-1], dtype=self.indptr.dtype)
      self.indptr = np.concatenate([self.indptr, pad])

  # -- views -------------------------------------------------------------

  @property
  def num_nodes(self) -> int:
    """Node count of the pointer axis (square graphs: the node count)."""
    return self.num_rows

  @property
  def num_edges(self) -> int:
    return int(self.indices.shape[0])

  @property
  def degrees(self) -> np.ndarray:
    return self.indptr[1:] - self.indptr[:-1]

  @property
  def max_degree(self) -> int:
    d = self.degrees
    return int(d.max()) if d.size else 0

  def to_coo(self):
    """Return (ptr_axis, other_axis, edge_ids) in compressed-slot order.
    For CSR that is (src, dst, eid); for CSC (dst, src, eid)."""
    row = np.repeat(
        np.arange(self.num_rows, dtype=self.indices.dtype), self.degrees)
    return row, self.indices.copy(), self.edge_ids.copy()

  def flip_layout(self) -> 'Topology':
    """CSR <-> CSC re-compression (reference utils/topo.py:29-91)."""
    ptr_axis, other, eids = self.to_coo()
    target = 'CSC' if self.layout == 'CSR' else 'CSR'
    if self.layout == 'CSR':          # ptr_axis = src, other = dst
      edge_index = np.stack([ptr_axis, other])
    else:                             # ptr_axis = dst, other = src
      edge_index = np.stack([other, ptr_axis])
    return Topology(
        edge_index=edge_index,
        edge_ids=eids,
        edge_weights=self.edge_weights,
        layout=target,
        num_rows=self.num_cols, num_cols=self.num_rows,
        index_dtype=self._index_dtype)


def _compress(row, col, num_rows, index_dtype, num_cols=None):
  """COO -> compressed, sorting by (row, col); returns perm mapping
  compressed slot -> original COO position. indptr is int64 (overflow-safe
  for >= 2^31 edges)."""
  row = as_numpy(row).astype(np.int64, copy=False)
  col = as_numpy(col).astype(np.int64, copy=False)
  if row.size and num_rows <= int(row.max()):
    raise ValueError(
        f'row id {int(row.max())} out of range for num_rows={num_rows}')
  if num_cols is not None and col.size and num_cols <= int(col.max()):
    # out-of-range neighbor ids would be silently dropped by the
    # dense-table scatters downstream — fail loudly like the row side
    raise ValueError(
        f'col id {int(col.max())} out of range for num_cols={num_cols}')
  perm = np.lexsort((col, row))
  counts = np.bincount(row, minlength=num_rows)
  indptr = np.zeros(num_rows + 1, dtype=np.int64)
  np.cumsum(counts, out=indptr[1:])
  indices = col[perm].astype(index_dtype, copy=False)
  return indptr, indices, perm


def _sort_within_rows(indptr, indices):
  """Ensure columns are ascending within each row; returns perm over slots."""
  n = indptr.shape[0] - 1
  deg = (indptr[1:] - indptr[:-1]).astype(np.int64)
  row = np.repeat(np.arange(n, dtype=np.int64), deg)
  perm = np.lexsort((indices.astype(np.int64), row))
  return indptr, indices[perm], perm

from .topology import Topology
from .graph import Graph
from .feature import Feature
from .dataset import Dataset
from .reorder import sort_by_in_degree, in_degrees

__all__ = [
    'Topology', 'Graph', 'Feature', 'Dataset',
    'sort_by_in_degree', 'in_degrees',
]
from .table_dataset import (
    TableDataset, csv_edge_reader, csv_node_reader, odps_table_reader,
)

__all__ += ['TableDataset', 'csv_edge_reader', 'csv_node_reader',
            'odps_table_reader']

"""Dataset: the user-facing container of graph storage + features + labels.

Reference: graphlearn_torch/python/data/dataset.py:30-515. Homogeneous
payloads are single objects; heterogeneous payloads are dicts keyed by
NodeType / EdgeType, same convention as the reference's typed getters
(dataset.py:396-444). Layout rule preserved from dataset.py:110-120:
edge_dir 'out' -> CSR (indptr over src, sample out-neighbors),
edge_dir 'in'  -> CSC (indptr over dst, sample in-neighbors).
"""
from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

from ..typing import EdgeType, GraphMode, NodeType, Split
from ..utils import as_numpy
from .feature import Feature
from .graph import Graph
from .topology import Topology

GraphLike = Union[Graph, Dict[EdgeType, Graph]]
FeatureLike = Union[Feature, Dict[Union[NodeType, EdgeType], Feature]]


class Dataset:
  def __init__(self,
               graph: Optional[GraphLike] = None,
               node_features: Optional[FeatureLike] = None,
               edge_features: Optional[FeatureLike] = None,
               node_labels=None,
               edge_dir: str = 'out',
               node_split=None):
    self.graph = graph
    self.node_features = node_features
    self.edge_features = edge_features
    self.node_labels = node_labels
    assert edge_dir in ('out', 'in')
    self.edge_dir = edge_dir
    self.node_split = node_split  # (train_idx, val_idx, test_idx) or dicts

  # -- graph init (reference dataset.py:53-122) --------------------------

  def init_graph(self,
                 edge_index=None,
                 edge_ids=None,
                 edge_weights=None,
                 num_nodes=None,
                 layout: str = 'COO',
                 graph_mode: Union[str, GraphMode] = GraphMode.HBM,
                 device=None):
    """``edge_index`` may be an array (homo) or Dict[EdgeType, array]."""
    target = 'CSR' if self.edge_dir == 'out' else 'CSC'

    def build(ei, eid, ew, n_src, n_dst):
      # pointer axis of the chosen layout: src for CSR, dst for CSC
      n_rows, n_cols = (n_src, n_dst) if target == 'CSR' else (n_dst, n_src)
      if layout.upper() == 'COO':
        topo = Topology(edge_index=ei, edge_ids=eid, edge_weights=ew,
                        layout=target, num_rows=n_rows, num_cols=n_cols)
      else:
        in_rows, in_cols = ((n_src, n_dst) if layout.upper() == 'CSR'
                            else (n_dst, n_src))
        topo = Topology(indptr=ei[0], indices=ei[1], edge_ids=eid,
                        edge_weights=ew, layout=layout.upper(),
                        num_rows=in_rows, num_cols=in_cols)
        if topo.layout != target:
          topo = topo.flip_layout()
      return Graph(topo, mode=graph_mode, device=device)

    if isinstance(edge_index, dict):
      self.graph = {}
      for etype, ei in edge_index.items():
        eid = edge_ids.get(etype) if isinstance(edge_ids, dict) else None
        ew = (edge_weights.get(etype)
              if isinstance(edge_weights, dict) else None)
        # num_nodes may be keyed by NodeType (preferred for bipartite
        # types) or by EdgeType (square), or be a single int.
        src_t, _, dst_t = etype
        if isinstance(num_nodes, dict):
          if src_t in num_nodes or dst_t in num_nodes:
            n_src = num_nodes.get(src_t)
            n_dst = num_nodes.get(dst_t)
          else:
            n_src = n_dst = num_nodes.get(etype)
        else:
          n_src = n_dst = num_nodes
        self.graph[etype] = build(ei, eid, ew, n_src, n_dst)
    elif edge_index is not None:
      self.graph = build(edge_index, edge_ids, edge_weights,
                         num_nodes, num_nodes)
    return self

  # -- features (reference dataset.py:236-341) ---------------------------

  def init_node_features(self, node_feature_data=None,
                         sort_func=None, split_ratio: float = 1.0,
                         dtype=None, device=None, host_offload=None):
    """``sort_func`` (e.g. sort_by_in_degree) reorders rows so the hot
    prefix is device-resident; the resulting old->new map is installed as
    the Feature's id2index so lookups by original id keep working
    (reference dataset.py:236-298). ``host_offload`` forwards to
    Feature (pinned-host cold block vs numpy host phase)."""
    def build(feats, topo):
      feats = as_numpy(feats)
      id2index = None
      if sort_func is not None and topo is not None:
        feats, id2index = sort_func(feats, split_ratio, topo)
      return Feature(feats, split_ratio=split_ratio, id2index=id2index,
                     dtype=dtype, device=device,
                     host_offload=host_offload)

    if isinstance(node_feature_data, dict):
      self.node_features = {}
      for ntype, feats in node_feature_data.items():
        topo = self._topo_for_node_type(ntype)
        self.node_features[ntype] = build(feats, topo)
    elif node_feature_data is not None:
      topo = self.graph.topo if isinstance(self.graph, Graph) else None
      self.node_features = build(node_feature_data, topo)
    return self

  def init_edge_features(self, edge_feature_data=None, dtype=None,
                         device=None):
    if isinstance(edge_feature_data, dict):
      self.edge_features = {
          etype: Feature(f, dtype=dtype, device=device)
          for etype, f in edge_feature_data.items()}
    elif edge_feature_data is not None:
      self.edge_features = Feature(edge_feature_data, dtype=dtype,
                                   device=device)
    return self

  def init_node_labels(self, node_label_data=None):
    if isinstance(node_label_data, dict):
      self.node_labels = {k: as_numpy(v) for k, v in node_label_data.items()}
    elif node_label_data is not None:
      self.node_labels = as_numpy(node_label_data)
    return self

  # -- splits (reference dataset.py:124-153) -----------------------------

  def random_node_split(self, num_val, num_test, seed: int = 0):
    def split_one(n):
      rng = np.random.default_rng(seed)
      perm = rng.permutation(n)
      nv = int(num_val * n) if isinstance(num_val, float) else num_val
      nt = int(num_test * n) if isinstance(num_test, float) else num_test
      return (perm[nv + nt:], perm[:nv], perm[nv:nv + nt])

    if isinstance(self.graph, dict):
      self.node_split = {
          nt: split_one(self.node_count(nt)) for nt in self.get_node_types()}
    else:
      self.node_split = split_one(self.graph.num_nodes)
    return self

  def get_split(self, split: Split, ntype: Optional[NodeType] = None):
    s = self.node_split
    if isinstance(s, dict) and ntype is not None:
      s = s[ntype]
    idx = {Split.train: 0, Split.valid: 1, Split.test: 2}[Split(split)]
    return s[idx]

  # -- typed getters (reference dataset.py:396-444) ----------------------

  @property
  def is_hetero(self) -> bool:
    return isinstance(self.graph, dict)

  def get_graph(self, etype: Optional[EdgeType] = None) -> Graph:
    if isinstance(self.graph, dict):
      return self.graph[etype]
    return self.graph

  def get_node_feature(self, ntype: Optional[NodeType] = None) -> Feature:
    if isinstance(self.node_features, dict):
      return self.node_features[ntype]
    return self.node_features

  def get_edge_feature(self, etype: Optional[EdgeType] = None) -> Feature:
    if isinstance(self.edge_features, dict):
      return self.edge_features[etype]
    return self.edge_features

  def get_node_label(self, ntype: Optional[NodeType] = None):
    if isinstance(self.node_labels, dict):
      return self.node_labels[ntype]
    return self.node_labels

  def get_node_types(self):
    if not self.is_hetero:
      return None
    out = []
    for (src, _, dst) in self.graph.keys():
      for t in (src, dst):
        if t not in out:
          out.append(t)
    return out

  def get_edge_types(self):
    if not self.is_hetero:
      return None
    return list(self.graph.keys())

  def node_count(self, ntype: Optional[NodeType] = None) -> int:
    if not self.is_hetero:
      return self.graph.num_nodes
    best = 0
    for (src, _, dst), g in self.graph.items():
      # CSR rows are src, CSC rows are dst; indices are the other endpoint
      row_t = src if g.layout == 'CSR' else dst
      col_t = dst if g.layout == 'CSR' else src
      if row_t == ntype:
        best = max(best, g.topo.num_rows)
      if col_t == ntype:
        best = max(best, g.topo.num_cols)
    if isinstance(self.node_features, dict) and ntype in self.node_features:
      best = max(best, self.node_features[ntype].num_rows)
    return best

  # -- internals ---------------------------------------------------------

  def _topo_for_node_type(self, ntype: NodeType):
    if not isinstance(self.graph, dict):
      return None
    for (src, _, dst), g in self.graph.items():
      row_t = src if g.layout == 'CSR' else dst
      if row_t == ntype:
        return g.topo
    return None

"""Vineyard (GraphScope) connector — protocol-based, contract-tested.

Reference: graphlearn_torch/python/data/vineyard_utils.py + v6d/
(vineyard_utils.cc:318: reads ArrowFragment graph data from a vineyard
store as CSR + feature tensors; built only WITH_VINEYARD).

A live vineyard service does not exist in this environment, so the
integration seam is made explicit instead of stubbed: every loader
works against a :class:`FragmentClient` protocol (connect-by-socket for
the real service, or any object implementing the protocol). The
in-memory :class:`InMemoryFragmentStore` is the contract's reference
implementation — tests drive the full loader surface through it
(tests/test_vineyard.py), so wiring a real vineyard client is only a
matter of implementing the five protocol methods over the fragment API.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class FragmentClient:
  """What the loaders need from a fragment store (the subset of the
  v6d ArrowFragment surface the reference reads, vineyard_utils.cc):

  - ``frag_csr(fid, v_label, e_label, edge_dir)`` ->
    (indptr [Nv+1], indices [E], edge_ids [E] or None)
  - ``frag_vertex_feature(fid, v_label, columns)`` -> [Nv, len(cols)]
  - ``frag_edge_feature(fid, e_label, columns)`` -> [E, len(cols)]
  - ``frag_vertex_offset(fid, v_label)`` / ``frag_vertex_num(fid,
    v_label)`` -> the fragment's global-id window.
  """

  def frag_csr(self, fid, v_label, e_label, edge_dir='out'):
    raise NotImplementedError

  def frag_vertex_feature(self, fid, v_label, columns):
    raise NotImplementedError

  def frag_edge_feature(self, fid, e_label, columns):
    raise NotImplementedError

  def frag_vertex_offset(self, fid, v_label) -> int:
    raise NotImplementedError

  def frag_vertex_num(self, fid, v_label) -> int:
    raise NotImplementedError


class InMemoryFragmentStore(FragmentClient):
  """Reference implementation of the contract: partitioned COO graphs +
  per-vertex/edge property tables, held in process memory.

  ``add_fragment`` registers one partition's slice: vertices
  [offset, offset + num_vertices) of ``v_label`` and the edges whose
  source falls in that window.
  """

  def __init__(self):
    self._frags: Dict[tuple, dict] = {}

  def add_fragment(self, fid, v_label: str, e_label: str,
                   offset: int, num_vertices: int,
                   edge_index: np.ndarray,
                   edge_ids: Optional[np.ndarray] = None,
                   vertex_feats: Optional[Dict[str, np.ndarray]] = None,
                   edge_feats: Optional[Dict[str, np.ndarray]] = None):
    self._frags[(fid, v_label, e_label)] = dict(
        offset=int(offset), num=int(num_vertices),
        edge_index=np.asarray(edge_index),
        edge_ids=None if edge_ids is None else np.asarray(edge_ids),
        vfeats=vertex_feats or {}, efeats=edge_feats or {})

  def _get(self, fid, v_label, e_label=None):
    if e_label is None:
      for (f, v, _), frag in self._frags.items():
        if f == fid and v == v_label:
          return frag
      raise KeyError((fid, v_label))
    return self._frags[(fid, v_label, e_label)]

  def frag_csr(self, fid, v_label, e_label, edge_dir='out'):
    from .topology import Topology
    frag = self._get(fid, v_label, e_label)
    ei = frag['edge_index']
    layout = 'CSR' if edge_dir == 'out' else 'CSC'
    # pointer axis is fragment-local: shift sources into window space
    ptr_axis = 0 if edge_dir == 'out' else 1
    local = ei.copy()
    local[ptr_axis] = local[ptr_axis] - frag['offset']
    topo = Topology(edge_index=local, edge_ids=frag['edge_ids'],
                    layout=layout, num_rows=frag['num'],
                    num_cols=(int(ei.max()) + 1) if ei.size else 1)
    return topo.indptr, topo.indices, topo.edge_ids

  def frag_vertex_feature(self, fid, v_label, columns):
    frag = self._get(fid, v_label)
    return np.stack([np.asarray(frag['vfeats'][c]) for c in columns], 1)

  def frag_edge_feature(self, fid, e_label, columns):
    for (f, _, e), frag in self._frags.items():
      if f == fid and e == e_label:
        return np.stack([np.asarray(frag['efeats'][c])
                         for c in columns], 1)
    raise KeyError((fid, e_label))

  def frag_vertex_offset(self, fid, v_label) -> int:
    return self._get(fid, v_label)['offset']

  def frag_vertex_num(self, fid, v_label) -> int:
    return self._get(fid, v_label)['num']


def _client(sock_or_client) -> FragmentClient:
  if isinstance(sock_or_client, FragmentClient):
    return sock_or_client
  try:
    import vineyard  # noqa: F401
  except ImportError as e:
    raise ImportError(
        'connecting by socket path requires the vineyard client '
        '(pip install vineyard) and a running vineyard/GraphScope '
        'instance; alternatively pass any FragmentClient '
        'implementation (e.g. InMemoryFragmentStore)') from e
  raise NotImplementedError(
      'socket-path connection requires wiring a vineyard '
      'ArrowFragment adapter over FragmentClient (5 methods, see '
      'class docstring); no live service exists in this environment')


# -- loader surface (reference vineyard_utils.py:30-75) ------------------

def vineyard_to_csr(sock, fid, v_label, e_label, edge_dir: str = 'out'):
  """Fragment -> (indptr, indices, edge_ids); reference :30-41."""
  return _client(sock).frag_csr(fid, v_label, e_label, edge_dir)


def load_vertex_feature_from_vineyard(sock, fid,
                                      vcols: Sequence[str], v_label):
  """Fragment vertex property columns -> [Nv, C]; reference :38-45."""
  return _client(sock).frag_vertex_feature(fid, v_label, vcols)


def load_edge_feature_from_vineyard(sock, fid,
                                    ecols: Sequence[str], e_label):
  """Fragment edge property columns -> [E, C]; reference :47-54."""
  return _client(sock).frag_edge_feature(fid, e_label, ecols)


def get_frag_vertex_offset(sock, fid, v_label) -> int:
  return _client(sock).frag_vertex_offset(fid, v_label)


def get_frag_vertex_num(sock, fid, v_label) -> int:
  return _client(sock).frag_vertex_num(fid, v_label)


def load_vineyard_dataset(sock, fids: Sequence, v_label, e_label,
                          vcols: Sequence[str] = (),
                          edge_dir: str = 'out'):
  """Assemble a whole-graph :class:`Dataset` from a set of fragments —
  the capability the reference maps onto its vineyard-backed
  DistDataset, expressed over the Dataset init hooks.
  """
  from .dataset import Dataset
  client = _client(sock)
  rows_l, cols_l, eids_l, feats_l = [], [], [], []
  total = 0
  for fid in sorted(fids, key=lambda f: client.frag_vertex_offset(
      f, v_label)):
    off = client.frag_vertex_offset(fid, v_label)
    num = client.frag_vertex_num(fid, v_label)
    indptr, indices, eids = client.frag_csr(fid, v_label, e_label,
                                            edge_dir)
    deg = np.diff(np.asarray(indptr))
    src_local = np.repeat(np.arange(num), deg[:num])
    rows_l.append(src_local + off)
    cols_l.append(np.asarray(indices))
    if eids is not None:
      eids_l.append(np.asarray(eids))
    if vcols:
      feats_l.append(client.frag_vertex_feature(fid, v_label, vcols))
    total = max(total, off + num)
  rows = np.concatenate(rows_l)
  cols = np.concatenate(cols_l)
  if edge_dir == 'in':  # CSC fragments: the pointer axis was dst
    rows, cols = cols, rows
  ds = Dataset(edge_dir=edge_dir)
  # edge ids are usable only if EVERY fragment supplied them; a partial
  # set would silently misattribute ids across fragments
  eids = (np.concatenate(eids_l) if len(eids_l) == len(fids) else None)
  ds.init_graph(
      edge_index=np.stack([rows, cols]),
      edge_ids=eids,
      num_nodes=max(total, (int(cols.max()) + 1) if cols.size else 1))
  if feats_l:
    ds.init_node_features(np.concatenate(feats_l).astype(np.float32))
  return ds

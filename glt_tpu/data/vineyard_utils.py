"""Vineyard (GraphScope) connector — optional, gated.

Reference: graphlearn_torch/python/data/vineyard_utils.py + v6d/ (reads
graph fragments from a vineyard store as CSR + feature tensors; built
only WITH_VINEYARD, setup.py:35-36). A vineyard client is not part of
this environment; the functions keep the reference API surface and raise
with instructions if the client is missing so downstream code can gate
on availability, matching the reference's optional-extension pattern.
"""
from __future__ import annotations



def _require_vineyard():
  try:
    import vineyard  # noqa: F401
    return vineyard
  except ImportError as e:
    raise ImportError(
        'vineyard support requires the vineyard client (pip install '
        'vineyard) and a running vineyard/GraphScope instance; this '
        'optional connector is disabled in the current environment'
    ) from e


def vineyard_to_csr(sock: str, object_id, edge_label: int,
                    edge_dir: str = 'out'):
  """Reference data/vineyard_utils.py:30-41: fragment -> (indptr,
  indices, edge_ids)."""
  _require_vineyard()
  raise NotImplementedError(
      'vineyard fragment decoding is pending a live vineyard service')


def load_vertex_feature_from_vineyard(sock: str, object_id,
                                      feature_labels, vertex_label: int):
  _require_vineyard()
  raise NotImplementedError(
      'vineyard feature loading is pending a live vineyard service')


def load_edge_feature_from_vineyard(sock: str, object_id,
                                    feature_labels, edge_label: int):
  _require_vineyard()
  raise NotImplementedError(
      'vineyard feature loading is pending a live vineyard service')

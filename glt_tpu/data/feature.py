"""Feature store with a device hot-cache and host spill.

Reference: graphlearn_torch/python/data/feature.py:32-283 and the native
UnifiedTensor (csrc/cuda/unified_tensor.cu). The reference splits rows by
``split_ratio`` into a GPU part (replicated per NVLink DeviceGroup) and a
pinned-CPU zero-copy part read over UVA inside GatherTensorKernel
(unified_tensor.cu:35-81). TPU-native translation:

  * hot rows  -> one jax array in HBM, gathered in-jit (``jnp.take``; the
    XLA gather runs at HBM bandwidth which is exactly what the warp-per-row
    GatherTensorKernel achieves on GPU);
  * cold rows -> by default ALSO a pinned-host jax array gathered inside
    the jitted collate (``gather_mixed``: a compute_on('device_host') read
    staged by XLA — the true zero-copy/UVA analogue); with
    host_offload=False, numpy in host RAM gathered between device calls,
    overlapped by the loader's prefetch thread.

DeviceGroup/NVLink replication (feature.py:179-199) and CUDA-IPC sharing
(feature.py:209-261) have no TPU equivalent: under SPMD one sharded global
array is addressable from every chip, and the distributed feature store
(glt_tpu.distributed.dist_feature) shards rows over the mesh instead.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import as_numpy


@functools.partial(jax.jit, static_argnames=('row_gather',))
def _mixed_gather(hot: jax.Array, cold: jax.Array,
                  rows: jax.Array, row_gather=None) -> jax.Array:
  """hot [H, D] device block; cold [C, D] pinned-host block; rows [B]
  absolute row indices (cold row r lives at cold[r - H]). Index
  arithmetic stays on device; the cold read runs host-side via raw
  indexing (bounds ops would materialize device-space constants inside
  the host region). ``row_gather`` (static: keyed by identity in the
  jit cache) overrides the HOT-block gather kernel — the same seam as
  Feature.device_gather, so an injected kernel covers offloaded stores
  too."""
  from jax.experimental import compute_on
  h = hot.shape[0]
  cold_idx = jnp.clip(rows - h, 0, cold.shape[0] - 1)
  idx_h = jax.device_put(cold_idx, jax.memory.Space.Host)
  with compute_on.compute_on('device_host'):
    c = cold[idx_h]
  c = jax.device_put(c, jax.memory.Space.Device)
  if h == 0:  # static shape: the whole table is cold
    return c
  safe = jnp.where(rows < h, rows, 0)
  x = (row_gather(hot, safe) if row_gather is not None
       else jnp.take(hot, safe, axis=0))
  return jnp.where((rows >= h)[:, None], c.astype(x.dtype), x)


@jax.jit
def _host_rows_gather(cold: jax.Array, idx: jax.Array) -> jax.Array:
  """Read rows of a pinned-host block (eager gathers cannot mix memory
  spaces, so even host-side convenience reads go through this jitted
  compute_on program)."""
  from jax.experimental import compute_on
  idx_h = jax.device_put(jnp.clip(idx, 0, cold.shape[0] - 1),
                         jax.memory.Space.Host)
  with compute_on.compute_on('device_host'):
    out = cold[idx_h]
  return jax.device_put(out, jax.memory.Space.Device)


class Feature:
  """2-D feature table split into [hot | cold] rows.

  Rows [0, hot_count) live on device, rows [hot_count, N) on host. Callers
  that reorder rows by hotness first (see :func:`glt_tpu.data.reorder.
  sort_by_in_degree`) get the reference's cache behavior: frequently
  sampled nodes resolve entirely in HBM.

  Args:
    feats: [N, D] array-like.
    split_ratio: fraction of rows resident on device (reference semantics,
      feature.py:101-140). 1.0 = fully device-resident (DMA mode), 0.0 =
      fully host (pure zero-copy mode).
    id2index: optional dense global-id -> row map applied before lookup
      (reference feature.py:142-155).
    dtype: optional cast (e.g. jnp.bfloat16 for fp16-style compression,
      examples/igbh compress path).
  """

  def __init__(self, feats, split_ratio: float = 1.0,
               id2index: Optional[np.ndarray] = None,
               device: Optional[jax.Device] = None,
               dtype=None, host_offload: Optional[bool] = None,
               row_gather=None):
    feats = as_numpy(feats)
    if feats.ndim == 1:
      feats = feats[:, None]
    self._host_full = feats
    # optional (table [N, D], rows [B]) -> [B, D] override for the
    # device-resident gather — the same injection seam the sharded
    # stores expose (parallel/dist_feature.py): tests pass the
    # interpret-mode Pallas kernel, deployments can pin a tuned one.
    # Resolved through ops.pallas_kernels.resolve_row_gather.
    self.row_gather = row_gather
    self.split_ratio = float(split_ratio)
    self.hot_count = int(round(feats.shape[0] * self.split_ratio))
    self.device = device
    self.dtype = dtype if dtype is not None else feats.dtype
    self._id2index = as_numpy(id2index)
    self._id2index_dev = None
    self._hot = None
    self._cold = None
    # host_offload: None = auto (on when spilled unless
    # GLT_HOST_OFFLOAD=0) — cold rows then ALSO live as a pinned-host
    # jax array served in-jit by gather_mixed (the UVA analog,
    # reference unified_tensor.cu:202-231); False keeps only the
    # numpy host phase (gather_cold_host)
    self._host_offload = host_offload
    self.cold_array = None
    self._initialized = False

  # -- lazy split/placement (reference lazy-init pattern, feature.py:29) --

  def lazy_init(self) -> None:
    if self._initialized:
      return
    n_hot = self.hot_count
    hot_np = self._host_full[:n_hot]
    self._hot = jax.device_put(
        jnp.asarray(hot_np, dtype=self.dtype), self.device)
    self._cold = self._host_full[n_hot:]
    if self._id2index is not None:
      self._id2index_dev = jax.device_put(
          jnp.asarray(self._id2index), self.device)
    from ..utils.offload import maybe_pin_host, offload_requested
    self._cold_count = int(self._cold.shape[0])
    if offload_requested(self._host_offload, self._cold_count > 0) \
        and self._cold_count:
      # cast in numpy and device_put the numpy array STRAIGHT into host
      # memory: jnp.asarray would first materialize the whole cold block
      # on the default device, which is exactly the HBM allocation a
      # beyond-HBM cold block cannot afford (the sharded builders in
      # parallel/dist_feature.py already follow this rule)
      cold_np = self._cold.astype(
          np.dtype(jnp.dtype(self.dtype)), copy=False)
      self.cold_array = maybe_pin_host(
          lambda: jax.device_put(cold_np, jax.memory.Space.Host),
          self._host_offload)
      if self.cold_array is not None:
        # the pinned block IS the cold copy; keeping the numpy view
        # would pin _host_full and double the cold footprint
        self._cold = None
    self._host_full = None  # single-copy invariant, as in the reference
    self._initialized = True

  # -- geometry ----------------------------------------------------------

  @property
  def shape(self):
    if self._initialized:
      return (self._hot.shape[0] + self._cold_count,
              self._hot.shape[1])
    return self._host_full.shape

  @property
  def num_rows(self) -> int:
    return self.shape[0]

  @property
  def feature_dim(self) -> int:
    return self.shape[1]

  @property
  def id_space(self) -> int:
    """Size of the id domain lookups accept: the id2index table length
    when an id map is configured (partitioned stores take GLOBAL ids),
    else the row count."""
    return (self._id2index.shape[0] if self._id2index is not None
            else self.num_rows)

  @property
  def fully_device_resident(self) -> bool:
    return self.hot_count >= self.num_rows

  @property
  def device_part(self) -> jax.Array:
    self.lazy_init()
    return self._hot

  @property
  def id2index(self):
    self.lazy_init()
    return self._id2index_dev

  # -- lookup ------------------------------------------------------------

  def map_ids(self, ids):
    if self._id2index is None:
      return ids
    if isinstance(ids, np.ndarray):
      return self._id2index[ids]
    self.lazy_init()
    return jnp.take(self._id2index_dev, ids, mode='clip')

  def device_gather(self, rows: jax.Array,
                    row_gather=None) -> jax.Array:
    """Jit-safe gather; only valid when fully device resident (hot==all).
    ``rows`` are post-id2index row indices. Gather selection follows
    ``resolve_row_gather``: an explicit ``row_gather`` (call-site or the
    store's own) wins, else the Pallas row-gather kernel when
    GLT_USE_PALLAS=1 on a TPU backend, else ``jnp.take``."""
    self.lazy_init()
    from ..ops.pallas_kernels import resolve_row_gather
    fn = resolve_row_gather(row_gather if row_gather is not None
                            else self.row_gather)
    if fn is not None:
      return fn(self._hot, rows.reshape(-1)).reshape(
          rows.shape + (self._hot.shape[1],))
    return jnp.take(self._hot, rows, axis=0, mode='clip')

  def gather_mixed(self, rows: jax.Array,
                   row_gather=None) -> jax.Array:
    """Jit-served gather over BOTH residency classes: hot rows from the
    device block, cold rows from the pinned-host block via a
    compute_on('device_host') gather — one compiled program, no host
    phase between batches. Requires the offloaded cold block
    (``cold_array``); loaders fall back to gather_cold_host otherwise.
    ``row_gather`` (call-site, else the store's own) overrides the
    hot-block gather kernel; unlike ``device_gather`` the env default
    (GLT_USE_PALLAS) does not apply here — only explicit injections."""
    self.lazy_init()
    assert self.cold_array is not None, 'host offload inactive'
    fn = row_gather if row_gather is not None else self.row_gather
    return _mixed_gather(self._hot, self.cold_array, rows,
                         row_gather=fn)

  def fused_gather_fn(self, row_gather=None):
    """Jit-safe ``ids [m] -> rows [m, D]`` closure for the in-walk
    (``pallas_fused``) feature gather: identical op chain to
    :func:`gather_features` on a fully-resident store — ``map_ids``
    (clip semantics included) then :meth:`device_gather` through the
    ``resolve_row_gather`` seam — so the assembled ``node_feats`` block
    is bit-identical to the post-hoc gather, padded lanes included.
    The returned closure captures this store's device buffers as
    compile-time constants (the same trade the samplers make with the
    graph arrays): swap the store, rebuild the sampler."""
    self.lazy_init()
    assert self.fully_device_resident, (
        'the fused in-walk gather serves device-resident stores only; '
        'spilled/offloaded rows keep the post-hoc gather_features path')
    fn = row_gather if row_gather is not None else self.row_gather

    def gather(ids):
      rows = self.map_ids(ids.astype(jnp.int32))
      return self.device_gather(rows, row_gather=fn)

    return gather

  def cold_block_numpy(self) -> np.ndarray:
    """The whole cold block as numpy, whichever residency holds it
    (store builders reassemble [hot | cold] through this)."""
    self.lazy_init()
    if self._cold is not None:
      return self._cold
    if self.cold_array is not None:
      return np.asarray(self.cold_array)
    return np.zeros((0, self.feature_dim), self.dtype)

  def gather_cold_host(self, rows: np.ndarray) -> np.ndarray:
    """Host gather of cold rows (rows are absolute; caller pre-filters
    rows >= hot_count). The UVA-read analogue; offloaded stores serve
    the same rows from the pinned block."""
    self.lazy_init()
    if self._cold is not None:
      return np.asarray(
          self._cold[rows - self.hot_count], dtype=self.dtype)
    return np.asarray(
        _host_rows_gather(self.cold_array,
                          jnp.asarray(rows - self.hot_count)),
        dtype=self.dtype)

  def stage_cold_rows(self, nodes: np.ndarray,
                      counts: np.ndarray) -> np.ndarray:
    """Host-gather the cold rows for pre-sampled node stacks — the
    single-store counterpart of ``ShardedFeature.stage_cold_rows``
    (which is what the SPMD streaming trainer in parallel/train.py
    uses). This one is the staging primitive for loader-driven
    single-store pipelines that pre-sample and then overlap the host
    cold gather with device compute.

    Args:
      nodes: [..., B] POST-id2index row indices (apply ``map_ids``
        first when an id map is configured).
      counts: [...] valid-slot counts per node stack.

    Returns [..., B, D] numpy: cold-row values on cold valid lanes,
    zeros elsewhere (hot lanes resolve on device; merging is one
    elementwise add/where).
    """
    self.lazy_init()
    nodes = as_numpy(nodes).astype(np.int64)
    counts = as_numpy(counts)
    valid = np.arange(nodes.shape[-1]) < counts[..., None]
    cold = valid & (nodes >= self.hot_count) & (nodes < self.num_rows)
    np_dtype = np.dtype(jnp.dtype(self.dtype))
    out = np.zeros(nodes.shape + (self.feature_dim,), np_dtype)
    lanes = np.nonzero(cold)
    if lanes[0].size:
      out[lanes] = self.gather_cold_host(nodes[lanes]).astype(np_dtype)
    return out

  def with_updated_rows(self, ids, values) -> 'Feature':
    """Functional row update: a NEW Feature sharing every buffer with
    this one except the updated rows — the snapshot-isolation primitive
    of the stream subsystem (readers of the old Feature keep seeing the
    old values; jitted gathers against either are shape-identical, so
    swapping costs no recompile).

    Hot rows ride jax's functional ``.at[].set`` (copy-on-write of the
    device block); cold rows copy the host block once per call, so
    confine streams with heavy cold-row churn to split_ratio=1.0
    stores. Offloaded (pinned-host) cold blocks reject cold-row updates
    — re-pinning per update would thrash the very placement the offload
    exists for.
    """
    self.lazy_init()
    ids = as_numpy(ids).astype(np.int64).reshape(-1)
    values = as_numpy(values)
    if values.ndim == 1:
      values = values[:, None]
    assert values.shape == (ids.shape[0], self.feature_dim), (
        f'expected {(ids.shape[0], self.feature_dim)} update block, '
        f'got {values.shape}')
    rows = self.map_ids(ids)
    if isinstance(rows, jax.Array):
      rows = as_numpy(rows)
    rows = rows.astype(np.int64)
    if rows.size and (rows.min() < 0 or rows.max() >= self.num_rows):
      raise ValueError(
          f'feature row out of range [0, {self.num_rows})')
    out = Feature.__new__(Feature)
    out.__dict__.update(self.__dict__)
    hot_sel = rows < self.hot_count
    if hot_sel.any():
      np_dtype = np.dtype(jnp.dtype(self.dtype))
      out._hot = self._hot.at[jnp.asarray(rows[hot_sel])].set(
          jnp.asarray(values[hot_sel].astype(np_dtype)))
    if (~hot_sel).any():
      assert self.cold_array is None, (
          'cold-row updates are unsupported on host-offloaded stores; '
          'use host_offload=False or keep updated rows in the hot '
          'split')
      cold = self._cold.copy()
      cold[rows[~hot_sel] - self.hot_count] = values[~hot_sel]
      out._cold = cold
    return out

  def __getitem__(self, ids) -> np.ndarray:
    """Host-side convenience lookup returning numpy (reference cpu_get,
    feature.py:157-164)."""
    self.lazy_init()
    ids = as_numpy(ids).astype(np.int64)
    rows = self.map_ids(ids)
    out = np.empty((rows.shape[0], self.feature_dim), dtype=self.dtype)
    hot_mask = rows < self.hot_count
    if hot_mask.any():
      out[hot_mask] = np.asarray(
          jnp.take(self._hot, jnp.asarray(rows[hot_mask]), axis=0))
    if (~hot_mask).any():
      out[~hot_mask] = self.gather_cold_host(rows[~hot_mask])
    return out


def gather_features(feat: Optional[Feature], node,
                    row_gather=None, fused=None) -> Optional[jax.Array]:
  """Batch gather over a Feature across BOTH residency classes — the
  single collate-time gather path shared by the training loaders
  (loader.node_loader) and the online serving engine (serving.engine).
  Hot rows stay on device; cold rows ride the pinned-host block
  (gather_mixed) when offloaded, else the host phase. ``row_gather``
  overrides the device-resident gather kernel at the call site (see
  :meth:`Feature.device_gather`) — it survives feature swaps (e.g.
  stream snapshot updates) because it rides the call, not the store.

  ``fused``: a feature block the sampler already assembled IN-WALK (the
  ``pallas_fused`` engine's ``node_feats`` metadata, bit-identical to
  what this function would gather) — passed through as the result, so
  every call site keeps one uniform entry point whichever engine ran.
  The ``gather.features`` span still opens (recording ~0 self time):
  per-stage breakdowns then show the gather cost moving INTO the fused
  sample stage rather than silently vanishing."""
  if feat is None:
    return None
  from ..obs import get_tracer
  tracer = get_tracer()
  if tracer.enabled:
    _out = {}
    with tracer.span('gather.features', sync=lambda: _out.get('x'),
                     fused=fused is not None):
      _out['x'] = x = (fused if fused is not None
                       else _gather_features(feat, node, row_gather))
    return x
  if fused is not None:
    return fused
  return _gather_features(feat, node, row_gather)


def _gather_features(feat: Feature, node, row_gather):
  rows = feat.map_ids(node)
  if feat.fully_device_resident:
    return feat.device_gather(rows, row_gather=row_gather)
  feat.lazy_init()  # offload is decided at placement time
  if feat.cold_array is not None:
    # host-offloaded cold block: one jitted program serves both
    # residency classes (compute_on host gather inside) — no host
    # phase between batches at all (jnp.asarray is a no-op for rows
    # already on device)
    return feat.gather_mixed(jnp.asarray(rows), row_gather=row_gather)
  # legacy mixed residency (host_offload=False): hot rows stay on
  # device end-to-end; only the cold slice crosses host->device (the
  # UVA-read analogue). The previous design pulled the hot gather D2H
  # and re-uploaded the whole batch — hot rows crossed PCIe twice,
  # defeating the split.
  rows_np = as_numpy(rows).astype(np.int64)
  if feat.hot_count == 0:
    # no device block at all (split_ratio=0.0): the whole batch is
    # cold; an empty jnp.take would raise, so serve host-side only
    return jnp.asarray(feat.gather_cold_host(rows_np)
                       .astype(feat.dtype))
  rows_dev = jnp.asarray(rows_np)
  hot = jnp.where(rows_dev < feat.hot_count, rows_dev, 0)
  x = feat.device_gather(hot, row_gather=row_gather)  # cold lanes junk
  cold_idx = np.nonzero(rows_np >= feat.hot_count)[0]
  if cold_idx.size:
    cold_vals = feat.gather_cold_host(rows_np[cold_idx]) \
        .astype(feat.dtype)
    # pad to the next power of two (duplicating the first cold lane)
    # so the eager scatter compiles O(log B) shapes, not one per batch
    cap = 1 << (int(cold_idx.size - 1)).bit_length()
    pad = cap - cold_idx.size
    if pad:
      cold_idx = np.concatenate(
          [cold_idx, np.full(pad, cold_idx[0], cold_idx.dtype)])
      cold_vals = np.concatenate(
          [cold_vals, np.broadcast_to(cold_vals[0], (pad,) +
                                      cold_vals.shape[1:])])
    x = x.at[jnp.asarray(cold_idx)].set(jax.device_put(cold_vals))
  return x

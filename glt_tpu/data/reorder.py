"""Degree-based feature reordering for hot-cache locality.

Reference: graphlearn_torch/python/data/reorder.py:19-36
(``sort_by_in_degree``): sort feature rows by descending in-degree so the hot
prefix lands in the device cache; returns (reordered features, old->new map).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils import as_numpy
from .topology import Topology


def in_degrees(topo: Topology) -> np.ndarray:
  if topo.layout == 'CSC':
    return np.asarray(topo.degrees)
  deg = np.bincount(as_numpy(topo.indices).astype(np.int64),
                    minlength=topo.num_cols)
  return deg


def sort_by_in_degree(
    feats: np.ndarray,
    split_ratio: float,
    topo: Topology,
    shuffle_ratio: float = 0.0,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
  """Returns (reordered_feats, old2new) with hottest rows first.

  ``split_ratio`` is part of the sort-func calling convention used by
  ``Dataset.init_node_features`` (the reference passes it so sort funcs can
  tailor ordering to the cache size, data/dataset.py:236-298); the pure
  degree sort does not need it. ``shuffle_ratio`` randomly swaps a fraction
  of assignments, matching the reference's optional perturbation.
  """
  feats = as_numpy(feats)
  deg = in_degrees(topo)
  n = feats.shape[0]
  if deg.shape[0] < n:
    deg = np.concatenate([deg, np.zeros(n - deg.shape[0], dtype=deg.dtype)])
  order = np.argsort(-deg[:n], kind='stable')  # new row k holds old node order[k]
  if shuffle_ratio > 0.0:
    rng = rng or np.random.default_rng(0)
    k = int(n * shuffle_ratio)
    if k > 1:
      pick = rng.choice(n, size=k, replace=False)
      shuffled = rng.permutation(pick)
      order[pick] = order[shuffled]
  old2new = np.empty(n, dtype=np.int64)
  old2new[order] = np.arange(n, dtype=np.int64)
  return feats[order], old2new

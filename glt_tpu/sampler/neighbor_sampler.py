"""NeighborSampler — the single-machine multi-hop sampling engine.

Reference: graphlearn_torch/python/sampler/neighbor_sampler.py:38-692.
The reference lazily builds per-edge-type native samplers + an inducer and
runs a Python hop loop issuing CUDA kernels. Here the *entire multi-hop
walk* — sampling, dedup/relabel, frontier advance — is one jitted XLA
program per (batch_size,) shape: static padded frontiers per hop (capacity
``B·Πfanouts``, the same bound the reference sizes its inducer with,
neighbor_sampler.py:660-677), with the dense-table inducer threading its
tables through the jit via donation so there is no per-batch allocation.

Orientation contract (verified against the reference, see
neighbor_sampler.py:186-320): for every output edge key, ``row`` holds
message-source (child) labels and ``col`` message-destination (parent)
labels. For hetero graphs with edge_dir='out' the output key is the
*reversed* traversal type ('rev_' convention); with 'in' it is the
traversal type itself.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..data import Graph
from ..ops.pipeline import count_engine_fallback, dedup_engine, \
    edge_hop_offsets, hetero_edge_hop_offsets, hop_engine, \
    make_dedup_tables, multihop_sample, multihop_sample_hetero, \
    sample_budget
from ..ops.sample import (
    neighbor_probs, sample_full_neighbors, sample_neighbors,
    sample_neighbors_weighted,
)
from ..obs import get_tracer
from ..ops.subgraph import induced_subgraph
from ..typing import EdgeType, NodeType, reverse_edge_type
from ..utils import as_numpy
from ..utils.env import knob
from ..utils.rng import RandomSeedManager
from .base import (
    BaseSampler, HeteroSamplerOutput, NodeSamplerInput, SamplerOutput,
)

logger = logging.getLogger(__name__)

#: above this column-space size the dense label table is considered too
#: expensive (2 × 4 bytes per node in HBM)
DENSE_TABLE_NODE_LIMIT = 256_000_000


def _window_width() -> int:
  """Window width W of the windowed hop engines (`GLT_WINDOW_W`,
  default 96, floored at 8) — ONE definition so the homo plan, the
  hetero plan, and the demoted per-hop window read can never disagree
  on the geometry they share."""
  return max(knob('GLT_WINDOW_W', 96), 8)


class NeighborSampler(BaseSampler):
  """Uniform/weighted multi-hop neighbor sampling over device CSR/CSC.

  Args:
    graph: a :class:`Graph` or Dict[EdgeType, Graph] (hetero).
    num_neighbors: [K_1..K_h] or Dict[EdgeType, [K...]]; ``-1`` means
      full neighborhood (reference semantics, e.g. SEAL's ``[-1, -1]``):
      every neighbor is expanded inside a static window of
      ``full_neighbor_cap`` (default: the graph's max degree, which makes
      the expansion exact). Frontier capacity multiplies by the window
      size per ``-1`` hop, so use it on bounded-degree graphs or set
      ``full_neighbor_cap`` explicitly.
    with_edge: emit edge ids (for edge features).
    with_weight: edge-weight-biased sampling (reference CPUWeightedSampler
      equivalent, device-side).
    edge_dir: 'out' (CSR expansion) or 'in' (CSC expansion).
    max_weighted_degree: static neighbor-window bound for the weighted
      path; defaults to the graph's max degree.
    full_neighbor_cap: static neighbor-window bound for ``-1`` hops.
    seed: RNG seed; defaults to the process RandomSeedManager.
    fused_feature: optional fully-device-resident
      :class:`~glt_tpu.data.feature.Feature` for the ``pallas_fused``
      engine's in-walk feature gather: each hop's FRESH unique rows are
      gathered while the walk runs (through the existing
      ``gather_rows``/``row_gather`` path) and the assembled
      ``[budget, D]`` block lands in ``SamplerOutput.metadata
      ['node_feats']`` — bit-identical to ``gather_features(feat,
      out.node)``, which downstream call sites short-circuit through
      (``gather_features(..., fused=)``). The feature block is a
      compile-time constant of the sampler's programs, so swapping the
      store (stream snapshot updates) requires a fresh sampler — the
      stream path therefore never enables this.
    row_gather: optional gather-kernel override for ``fused_feature``
      (the ``resolve_row_gather`` seam, same contract as
      ``Feature.device_gather``).
  """

  def __init__(
      self,
      graph: Union[Graph, Dict[EdgeType, Graph]],
      num_neighbors,
      device: Optional[jax.Device] = None,
      with_edge: bool = False,
      with_weight: bool = False,
      edge_dir: str = 'out',
      replace: bool = False,
      seed: Optional[int] = None,
      max_weighted_degree: Optional[int] = None,
      full_neighbor_cap: Optional[int] = None,
      fused_feature=None,
      row_gather=None,
  ):
    assert edge_dir in ('out', 'in')
    self.graph = graph
    self.is_hetero = isinstance(graph, dict)
    self.with_edge = with_edge
    self.with_weight = with_weight
    self.edge_dir = edge_dir
    self.replace = replace
    self.device = device
    self.max_weighted_degree = max_weighted_degree
    self.full_neighbor_cap = full_neighbor_cap
    self.fused_feature = fused_feature
    self.row_gather = row_gather
    self._fallbacks_counted = set()
    from ..utils.rng import make_key
    self._base_key = make_key(
        seed if seed is not None
        else RandomSeedManager.getInstance().getSeed())
    self._step = 0

    # device placement must happen eagerly — inside a jit trace the
    # lazily-created arrays would be tracers and leak out of the trace
    if isinstance(graph, dict):
      for g in graph.values():
        g.lazy_init()
    else:
      graph.lazy_init()

    if self.is_hetero:
      self.edge_types = list(graph.keys())
      if isinstance(num_neighbors, dict):
        self.num_neighbors = {k: list(v) for k, v in num_neighbors.items()}
      else:
        self.num_neighbors = {
            k: list(num_neighbors) for k in self.edge_types}
      self.num_neighbors = {
          k: [self._resolve_fanout(f, graph[k]) for f in v]
          for k, v in self.num_neighbors.items()}
      hops = {len(v) for v in self.num_neighbors.values()}
      assert len(hops) == 1, 'all edge types need the same hop count'
      self.num_hops = hops.pop()
      self._node_counts = self._infer_node_counts()
    else:
      self.edge_types = None
      self.num_neighbors = [self._resolve_fanout(f, graph)
                            for f in num_neighbors]
      self.num_hops = len(self.num_neighbors)
      self._node_counts = None

    self._fn_cache = {}
    self._tables = {}   # key: ntype or '' -> (table, scratch)

  # -- helpers -----------------------------------------------------------

  @property
  def num_compiled_fns(self) -> int:
    """Number of compiled multihop programs (one per seed-shape
    signature). The serving engine's zero-recompile steady-state
    guarantee is asserted against this: after bucket warmup it must
    never grow."""
    return sum(1 for k in self._fn_cache if k[0] in ('homo', 'hetero'))

  def _resolve_fanout(self, fanout: int, g: Graph) -> int:
    """Map the user-facing fanout to the internal encoding: positive =
    sample ``fanout``; ``-1`` resolves to ``-window`` where ``window`` is
    the static full-neighborhood cap (pipeline capacity math uses |k|)."""
    fanout = int(fanout)
    if fanout == -1:
      cap = self.full_neighbor_cap or g.topo.max_degree
      assert cap > 0, 'graph has no edges; fanout=-1 is meaningless'
      return -int(cap)
    assert fanout > 0, f'fanout must be positive or -1, got {fanout}'
    return fanout

  def _infer_node_counts(self) -> Dict[NodeType, int]:
    counts: Dict[NodeType, int] = {}
    for (src, _, dst), g in self.graph.items():
      row_t = src if g.layout == 'CSR' else dst
      col_t = dst if g.layout == 'CSR' else src
      counts[row_t] = max(counts.get(row_t, 0), g.topo.num_rows)
      counts[col_t] = max(counts.get(col_t, 0), g.topo.num_cols)
    return counts

  def _next_key(self) -> jax.Array:
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _get_tables(self, ntype: str, num_nodes: int):
    if ntype not in self._tables:
      assert (dedup_engine() == 'sort'
              or num_nodes <= DENSE_TABLE_NODE_LIMIT), (
          f'node space {num_nodes} exceeds dense-table limit; '
          'shard the graph (distributed sampler) or use the sort-merge '
          'inducer (GLT_DEDUP=sort) instead')
      self._tables[ntype] = make_dedup_tables(num_nodes)
    return self._tables[ntype]

  def _window_kwargs(self, g: Graph, width: int, fields):
    """Opt-in Pallas DMA window-gather plumbing for the [S, width]
    window reads of the full/weighted paths (GLT_USE_PALLAS=1 on TPU;
    tests inject an interpret-mode gather via ``_window_gather_fn``)."""
    fn = getattr(self, '_window_gather_fn', None)
    if fn is None:
      from ..ops.pallas_kernels import gather_windows, use_pallas_default
      if not use_pallas_default():
        return {}
      fn = gather_windows
    sources = g.window_arrays(width, fields)
    if any(sources.get(f) is None for f in fields):
      return {}  # HOST-mode (or missing) edge arrays: XLA fallback
    return dict(window_gather=lambda arr, st, w: fn(arr, st, width=w),
                window_sources=sources)

  def _count_fallback(self, reason: str, resolved: str = 'pallas'):
    """Once-per-(sampler, reason) engine-fallback accounting — the
    event is a property of the sampler's configuration, so repeating it
    per hop/call would just inflate the counter. The ``requested``
    label carries what the operator actually asked for (``auto`` when
    the backend-aware default resolved to the fused engine), so a
    dashboard can tell a deliberate engine request from a default."""
    if reason not in self._fallbacks_counted:
      self._fallbacks_counted.add(reason)
      requested = knob('GLT_HOP_ENGINE', 'auto')
      if getattr(self, '_hop_engine_override', None):
        requested = self._hop_engine_override
      count_engine_fallback(requested, resolved, reason)

  def _resolved_hop_engine(self) -> str:
    """The engine this sampler ACTUALLY runs: ``pallas_fused`` demotes
    to ``pallas`` (counted, ``hop_engine_fallbacks_total``) for the hop
    shapes the fusion does not serve — weighted and full-neighborhood
    hops (no uniform offset pick to fuse) and a forced dense dedup
    engine (the fused kernel IS the sort-contract inducer). Hetero
    traversals are SERVED by the fused family (one padded
    multi-edge-type invocation per hop over the edge-type plane,
    :class:`~glt_tpu.ops.sample.HeteroFusedPlan`); the ``hetero``
    fallback reason fires only for genuinely unservable hetero shapes
    — a type-tagged key space past int32 (``_hetero_fused_plan``) —
    never for hetero as such."""
    eng = getattr(self, '_hop_engine_override', None) or hop_engine()
    if eng != 'pallas_fused':
      return eng
    if self.with_weight:
      self._count_fallback('weighted')
      return 'pallas'
    fanouts = (sum(self.num_neighbors.values(), []) if self.is_hetero
               else self.num_neighbors)
    if any(f < 0 for f in fanouts):
      self._count_fallback('full_neighborhood')
      return 'pallas'
    if knob('GLT_DEDUP', '') == 'table':
      self._count_fallback('dense_dedup_forced')
      return 'pallas'
    return eng

  def _fused_plan(self, batch_size: int):
    """Build the :class:`~glt_tpu.ops.sample.FusedHopPlan` for one
    compiled multihop program, or None with a counted fallback when the
    fused engine cannot engage at this shape (HOST-mode edge arrays; a
    node budget whose dedup table would blow the VMEM sizing knob,
    ``GLT_FUSED_TABLE_SLOTS``)."""
    if self._resolved_hop_engine() != 'pallas_fused':
      return None
    from ..ops.pallas_kernels import (fused_table_max_slots,
                                      fused_table_slots,
                                      interpret_default)
    from ..ops.sample import FusedHopPlan
    g: Graph = self.graph
    width = _window_width()
    fields = ('indices', 'edge_ids') if (
        self.with_edge and g.topo.edge_ids is not None) else ('indices',)
    # window_arrays BEFORE touching g.indices/edge_ids — the padded
    # copy supersedes the originals (one-resident-copy rule)
    sources = g.window_arrays(width, fields)
    if any(sources.get(f) is None for f in fields):
      # HOST-mode graphs have no device window arrays at all, so the
      # demoted hop read lands on the ELEMENT path (the same guard in
      # _uniform_hop_kwargs returns {})
      self._count_fallback('host_mode_arrays', resolved='element')
      return None
    budget = sample_budget(batch_size, self.num_neighbors)
    slots = fused_table_slots(budget)
    # geometry gauges BEFORE the overflow gate: an over-knob walk is
    # exactly the one whose chosen-slots-vs-knob distance matters
    self._publish_table_geometry(slots)
    if slots > fused_table_max_slots():
      self._count_fallback('table_overflow')
      return None
    gather_fn = feat_dim = feat_dtype = None
    feat = self.fused_feature
    if feat is not None and feat.fully_device_resident:
      gather_fn = feat.fused_gather_fn(row_gather=self.row_gather)
      feat_dim = feat.feature_dim
      feat_dtype = feat.device_part.dtype
      # opt-in narrow gather plane: the in-walk feature block (and the
      # emitted node_feats) carry this dtype, halving the gather's HBM
      # write traffic for float32 stores. A widening request is
      # ignored — the plane never up-converts.
      narrow = knob('GLT_FUSED_FEAT_DTYPE', None)
      if narrow:
        narrow = jnp.dtype(narrow)
        if narrow.itemsize < jnp.dtype(feat_dtype).itemsize:
          feat_dtype = narrow
    self._table_slots = slots
    return FusedHopPlan(
        g.indptr, g.indices, sources['indices'], width,
        g.hub_count(width), slots,
        edge_ids=g.edge_ids if self.with_edge else None,
        edge_ids_win=sources.get('edge_ids'), replace=self.replace,
        interpret=interpret_default(), gather_fn=gather_fn,
        feat_dim=feat_dim, feat_dtype=feat_dtype,
        indptr_pad=g.indptr_pad())

  def _hetero_fused_plan(self, batch_sizes: Dict[NodeType, int]):
    """Build the :class:`~glt_tpu.ops.sample.HeteroFusedPlan` for one
    compiled hetero multihop program, or None with a counted fallback
    when the fused engine cannot engage at this shape. Fallback reasons
    stay SPECIFIC — ``host_mode_arrays`` (no device window arrays),
    ``table_overflow`` (total cross-type budget past the VMEM knob) —
    and the bare ``hetero`` reason is reserved for the genuinely
    unservable hetero shapes: a type-tagged global id space or flat
    edge plane past int32 (build_type_plane raises)."""
    if self._resolved_hop_engine() != 'pallas_fused':
      return None
    from ..ops.pallas_kernels import (fused_table_max_slots,
                                      fused_table_slots,
                                      interpret_default)
    from ..ops.sample import HeteroFusedPlan
    width = _window_width()
    parts = {}
    for e in self.edge_types:
      g: Graph = self.graph[e]
      fields = ('indices', 'edge_ids') if (
          self.with_edge and g.topo.edge_ids is not None) \
          else ('indices',)
      # window_arrays BEFORE touching g.indptr — the padded copy
      # supersedes the originals (one-resident-copy rule)
      sources = g.window_arrays(width, fields)
      if any(sources.get(f) is None for f in fields):
        self._count_fallback('host_mode_arrays', resolved='element')
        return None
      parts[e] = dict(indptr=g.indptr, indices_win=sources['indices'],
                      num_edges=g.num_edges,
                      hub_count=g.hub_count(width),
                      edge_ids_win=sources.get('edge_ids'))
    caps, budgets = self._hetero_caps(batch_sizes)
    budget_total = sum(budgets.values())
    slots = fused_table_slots(budget_total)
    # geometry gauges BEFORE the overflow gate (same rationale as homo)
    self._publish_table_geometry(slots)
    if slots > fused_table_max_slots():
      self._count_fallback('table_overflow')
      return None
    try:
      plan = HeteroFusedPlan(
          self.edge_types, self._traversal_types(), self._node_counts,
          parts, width, slots, budget_total, replace=self.replace,
          interpret=interpret_default())
    except ValueError as e:
      # int32 type-tagged key space exceeded: the one hetero shape the
      # fused family genuinely cannot serve
      logger.warning('hetero fused plan unavailable: %s', e)
      self._count_fallback('hetero')
      return None
    self._table_slots = slots
    return plan

  def _publish_table_geometry(self, slots: int) -> None:
    """Registry gauges for the fused dedup table's static geometry —
    chosen slot count and VMEM bytes (both planes) — so a
    ``table_overflow`` demotion is diagnosable from a registry snapshot
    (how close was the walk to the knob?) instead of only a fallback
    counter."""
    try:
      from ..obs import get_registry
      from ..ops.pallas_kernels import fused_table_max_slots
      reg = get_registry()
      reg.gauge('fused_table_slots').set(float(slots))
      reg.gauge('fused_table_vmem_bytes').set(float(2 * slots * 4))
      reg.gauge('fused_table_max_slots').set(
          float(fused_table_max_slots()))
    except Exception:  # metrics must never break sampling
      pass

  def _update_table_occupancy(self, out) -> None:
    """Occupancy high-water gauge for the fused table: the walk's
    distinct-node count over the table's slot capacity. Reading the
    count forces a device sync, so this only runs when the tracer is
    already sampling syncs (GLT_OBS_TRACE_SAMPLE) or under the explicit
    ``GLT_OBS_TABLE_OCCUPANCY=1`` opt-in — steady-state sampling stays
    fully async."""
    slots = getattr(self, '_table_slots', None)
    if not slots:
      return
    try:
      from ..obs import get_registry, get_tracer
      if not knob('GLT_OBS_TABLE_OCCUPANCY', False):
        t = get_tracer()
        # mirror the tracer's own probabilistic sync draw: reading the
        # count blocks on the walk, so it must happen on the SAMPLED
        # FRACTION of calls, not on every call while sampling is on
        import random
        if not (t.enabled and t._sample > 0
                and random.random() < t._sample):
          return
      occ = out['node_count']
      # hetero: the table is shared across types (type-tagged keys), so
      # occupancy is the cross-type distinct total
      occ = (sum(int(c) for c in occ.values())
             if isinstance(occ, dict) else int(occ))
      hwm = max(getattr(self, '_table_occ_hwm', 0), occ)
      self._table_occ_hwm = hwm
      reg = get_registry()
      reg.gauge('fused_table_occupancy_hwm').set(float(hwm))
      reg.gauge('fused_table_occupancy_ratio_hwm').set(
          float(hwm) / float(slots))
    except Exception:  # metrics must never break sampling
      pass

  def _uniform_hop_kwargs(self, g: Graph, frontier_size: int):
    """Windowed-engine plumbing for the UNIFORM hop read
    (ops/pipeline.py::hop_engine, read at trace time): resolves the
    window width (``GLT_WINDOW_W``, default 96, floored at 8), the
    exact hub capacity from the graph's true degree distribution
    (:meth:`Graph.hub_count` — host-side, once per width), and the
    W-padded edge arrays. Returns {} on the element engine or when the
    padded arrays are unavailable (HOST-mode graphs). Tests inject an
    engine/interpret override via ``_hop_engine_override``. A
    ``pallas_fused`` request reaching THIS path (a hop shape outside
    the fused plan — hetero, weighted/full companions, plan fallback)
    reads windows through the plain ``pallas`` megakernel."""
    eng = self._resolved_hop_engine()
    if eng == 'pallas_fused':
      eng = 'pallas'
    if eng == 'element':
      return {}
    width = _window_width()
    fields = ('indices', 'edge_ids') if (
        self.with_edge and g.topo.edge_ids is not None) else ('indices',)
    sources = g.window_arrays(width, fields)
    if any(sources.get(f) is None for f in fields):
      return {}  # HOST-mode (or missing) edge arrays: XLA fallback
    # a frontier can't hold more hub rows than it has rows: clamping H
    # keeps the fix-up buffers frontier-sized without ever undershooting
    n_hub = min(g.hub_count(width), int(frontier_size))
    kw = dict(window=(width, n_hub),
              indices_win=sources['indices'],
              edge_ids_win=sources.get('edge_ids'), engine=eng)
    if eng == 'pallas':
      from ..ops.pallas_kernels import interpret_default
      kw['interpret'] = interpret_default()
    return kw

  def _one_hop(self, g: Graph, frontier, fanout, key, mask):
    """Dispatch full/uniform/weighted one-hop sampling on graph ``g``."""
    if fanout < 0:  # full neighborhood inside a |fanout|-wide window
      # build window kwargs BEFORE touching g.indices/edge_ids: the
      # padded window copy supersedes the originals (Graph.window_arrays
      # rebinds the fields), so reading them afterwards keeps the
      # compiled program referencing ONE resident copy per edge array
      want_eids = self.with_edge and g.topo.edge_ids is not None
      wk = self._window_kwargs(
          g, -fanout, ('indices', 'edge_ids') if want_eids
          else ('indices',))
      eids = g.edge_ids if self.with_edge else None
      return sample_full_neighbors(
          g.indptr, g.indices, frontier, -fanout, seed_mask=mask,
          edge_ids=eids, **wk)
    if self.with_weight and g.edge_weights is not None:
      max_deg = self.max_weighted_degree or g.topo.max_degree
      max_deg = max(max_deg, fanout)
      wk = self._window_kwargs(g, max_deg, ('edge_weights',))
      eids = g.edge_ids if self.with_edge else None
      return sample_neighbors_weighted(
          g.indptr, g.indices, g.edge_weights, frontier, fanout, key,
          max_degree=max_deg, seed_mask=mask, edge_ids=eids, **wk)
    # build window kwargs BEFORE touching g.indices/edge_ids (same
    # one-resident-copy rule as the full-neighborhood branch above)
    wk = self._uniform_hop_kwargs(g, frontier.shape[0])
    eids = g.edge_ids if self.with_edge else None
    return sample_neighbors(
        g.indptr, g.indices, frontier, fanout, key, seed_mask=mask,
        edge_ids=eids, replace=self.replace, **wk)

  # -- homogeneous sampling ---------------------------------------------

  def _build_homo_fn(self, batch_size: int):
    g: Graph = self.graph
    one_hop = lambda ids, fanout, key, mask: self._one_hop(
        g, ids, fanout, key, mask)
    fused_plan = self._fused_plan(batch_size)

    def fn(seeds, n_valid, key, table, scratch):
      # trace-time side effect: one compiles_total{fn=...} tick per
      # compiled seed-shape program (the registry counterpart of
      # num_compiled_fns — executions never bump it)
      from ..obs.perf import count_compile
      count_compile('sampler.homo')
      return multihop_sample(one_hop, seeds, n_valid, self.num_neighbors,
                             key, table, scratch,
                             with_edge=self.with_edge,
                             fused_plan=fused_plan)

    return jax.jit(fn, donate_argnums=(3, 4))

  def _edge_hop_offsets(self, batch_size: int) -> List[int]:
    return edge_hop_offsets(batch_size, self.num_neighbors)

  def sample_from_nodes(self, inputs, **kwargs) -> SamplerOutput:
    """Multi-hop sampling from seed nodes (reference
    neighbor_sampler.py:169-230). ``inputs`` may be a NodeSamplerInput or a
    plain array of seed ids; padded seeds (beyond ``n_valid``) are ignored.
    """
    if self.is_hetero:
      with get_tracer().span('sample.multihop', kind='hetero'):
        return self._hetero_sample_from_nodes(inputs, **kwargs)
    if isinstance(inputs, NodeSamplerInput):
      seeds = as_numpy(inputs.node)
    else:
      seeds = as_numpy(inputs)
    n_valid = kwargs.get('n_valid', seeds.shape[0])
    batch_size = seeds.shape[0]
    cache_key = ('homo', batch_size)
    if cache_key not in self._fn_cache:
      self._fn_cache[cache_key] = self._build_homo_fn(batch_size)
    table, scratch = self._get_tables('', self.graph.num_nodes)
    # dispatch is async: the sync closure hands the output back to the
    # span so sampled device-syncs (GLT_OBS_TRACE_SAMPLE) measure real
    # compute, not just dispatch
    _synced = {}
    with get_tracer().span('sample.multihop', batch=batch_size,
                           hops=len(self.num_neighbors),
                           sync=lambda: _synced.get('out')):
      out, table, scratch = self._fn_cache[cache_key](
          jnp.asarray(seeds.astype(np.int32)), jnp.asarray(n_valid),
          kwargs.get('key', self._next_key()), table, scratch)
      _synced['out'] = out['num_sampled_edges']
    self._tables[''] = (table, scratch)
    self._update_table_occupancy(out)
    metadata = {'seed_labels': out['seed_labels'],
                'seed_count': out['seed_count']}
    if 'node_feats' in out:
      # the fused in-walk gather (pallas_fused + fused_feature):
      # bit-identical to gather_features(feat, node) — consumers
      # short-circuit through gather_features(..., fused=...)
      metadata['node_feats'] = out['node_feats']
    return SamplerOutput(
        node=out['node'], node_count=out['node_count'],
        row=out['row'], col=out['col'], edge_mask=out['edge_mask'],
        edge=out.get('edge'), batch=out['batch'],
        num_sampled_nodes=out['num_sampled_nodes'],
        num_sampled_edges=out['num_sampled_edges'],
        edge_hop_offsets=self._edge_hop_offsets(batch_size),
        metadata=metadata,
    )

  # -- heterogeneous sampling -------------------------------------------

  def _traversal_types(self):
    """Per traversal etype: (expand-from ntype, neighbor ntype)."""
    out = {}
    for etype in self.edge_types:
      src, _, dst = etype
      g = self.graph[etype]
      row_t = src if g.layout == 'CSR' else dst
      col_t = dst if g.layout == 'CSR' else src
      out[etype] = (row_t, col_t)
    return out

  def _hetero_caps(self, batch_sizes: Dict[NodeType, int]):
    """Static per-type frontier capacities and node budgets per hop."""
    trav = self._traversal_types()
    caps = [{t: batch_sizes.get(t, 0) for t in self._node_counts}]
    for h in range(self.num_hops):
      nxt = {t: 0 for t in self._node_counts}
      for etype, (row_t, col_t) in trav.items():
        k = self.num_neighbors[etype][h]
        nxt[col_t] += caps[h][row_t] * abs(k)
      caps.append(nxt)
    budgets = {t: max(1, sum(c[t] for c in caps))
               for t in self._node_counts}
    return caps, budgets

  def _build_hetero_fn(self, batch_sizes: Dict[NodeType, int]):
    """Multi-type seeding: ``batch_sizes`` gives each seed type's static
    batch size (single-type node sampling passes one entry; two-type
    link sampling passes both endpoint types). The hop loop itself is
    the shared ops.pipeline.multihop_sample_hetero core."""
    trav = self._traversal_types()
    caps, budgets = self._hetero_caps(batch_sizes)
    one_hops = {
        e: (lambda ids, fanout, key, mask, _e=e: self._one_hop(
            self.graph[_e], ids, fanout, key, mask))
        for e in self.edge_types}
    fused_plan = self._hetero_fused_plan(batch_sizes)

    def fn(seeds, n_valid, key, tables):
      from ..obs.perf import count_compile
      count_compile('sampler.hetero')  # trace-time only, like homo
      return multihop_sample_hetero(
          one_hops, trav, self.num_neighbors, self.num_hops, caps,
          budgets, seeds, n_valid, key, tables,
          with_edge=self.with_edge, fused_plan=fused_plan)

    return jax.jit(fn, donate_argnums=(3,))

  def _hetero_sample_from_nodes(self, inputs, **kwargs) \
      -> HeteroSamplerOutput:
    if isinstance(inputs, NodeSamplerInput):
      seed_dict = {inputs.input_type: as_numpy(inputs.node)}
      seed_type = inputs.input_type
    elif isinstance(inputs, dict):
      seed_dict = {t: as_numpy(s) for t, s in inputs.items()}
      seed_type = kwargs.pop('seed_type', next(iter(seed_dict)))
    else:
      seed_type, seeds = inputs
      seed_dict = {seed_type: as_numpy(seeds)}
    assert seed_type is not None, 'hetero sampling needs a seed node type'
    n_valid = kwargs.get('n_valid')
    if not isinstance(n_valid, dict):
      n_valid = {t: (n_valid if n_valid is not None else s.shape[0])
                 for t, s in seed_dict.items()}
    batch_sizes = {t: s.shape[0] for t, s in seed_dict.items()}
    cache_key = ('hetero', tuple(sorted(batch_sizes.items())))
    if cache_key not in self._fn_cache:
      self._fn_cache[cache_key] = self._build_hetero_fn(batch_sizes)
    tables = {t: self._get_tables(t, n)
              for t, n in self._node_counts.items()}
    key = kwargs.pop('key', None)
    out, new_tables = self._fn_cache[cache_key](
        {t: jnp.asarray(s.astype(np.int32))
         for t, s in seed_dict.items()},
        {t: jnp.asarray(v) for t, v in n_valid.items()},
        key if key is not None else self._next_key(), tables)
    self._tables.update(new_tables)
    self._update_table_occupancy(out)

    # final keys: 'out' reverses the traversal type, 'in' keeps it; row
    # must carry child labels (= our cols), col parent labels (= our rows)
    def final_key(etype):
      return reverse_edge_type(etype) if self.edge_dir == 'out' else etype

    row = {final_key(e): v for e, v in out['col'].items()}
    col = {final_key(e): v for e, v in out['row'].items()}
    edge_mask = {final_key(e): v for e, v in out['edge_mask'].items()}
    edge = ({final_key(e): v for e, v in out['edge'].items()}
            if self.with_edge else None)
    num_sampled_edges = {final_key(e): v
                         for e, v in out['num_sampled_edges'].items()}
    # static per-etype hop offsets (final-key space) for hierarchical
    # per-layer trimming (reference trim_to_layer) — cached per
    # batch-size signature alongside the compiled fn
    offs_key = ('hetero_offs', cache_key[1])
    if offs_key not in self._fn_cache:
      caps, _ = self._hetero_caps(batch_sizes)
      raw = hetero_edge_hop_offsets(
          caps, self._traversal_types(), self.num_neighbors,
          self.num_hops)
      self._fn_cache[offs_key] = {
          final_key(e): tuple(v) for e, v in raw.items()}
    hop_offs = {k: v for k, v in self._fn_cache[offs_key].items()
                if k in row}
    return HeteroSamplerOutput(
        node=out['node'], node_count=out['node_count'],
        row=row, col=col, edge_mask=edge_mask, edge=edge,
        batch=out['batch'],
        num_sampled_nodes=out['num_sampled_nodes'],
        num_sampled_edges=num_sampled_edges,
        input_type=seed_type,
        metadata={'seed_labels': out['seed_labels'],
                  'edge_hop_offsets': hop_offs},
    )

  # -- link sampling (reference neighbor_sampler.py:319-446) --------------

  def _get_neg_sampler(self, etype=None):
    if not hasattr(self, '_neg_samplers'):
      self._neg_samplers = {}
    if etype not in self._neg_samplers:
      from .negative_sampler import RandomNegativeSampler
      g = self.graph[etype] if self.is_hetero else self.graph
      self._neg_samplers[etype] = RandomNegativeSampler(
          g, mode='non-strict', edge_dir=self.edge_dir)
    return self._neg_samplers[etype]

  def sample_from_edges(self, inputs: 'EdgeSamplerInput', **kwargs):
    """Link-prediction sampling: seeds are the endpoints of positive
    (and sampled negative) edges; metadata carries edge_label_index /
    edge_label (binary) or src/dst_pos/dst_neg indices (triplet) exactly
    as the reference emits them. The inducer's first-occurrence seed
    labels are the reference's `unique(return_inverse=True)` inverse.

    Static-shape note: strict negative sampling uses padding=True so the
    negative block is always full (the reference's padding semantics);
    hetero inputs are supported for same-src/dst edge types (two-type
    merge is handled by the link loaders at collate time).
    """
    from .base import EdgeSamplerInput
    assert isinstance(inputs, EdgeSamplerInput)
    src = as_numpy(inputs.row).astype(np.int64)
    dst = as_numpy(inputs.col).astype(np.int64)
    edge_label = (as_numpy(inputs.label)
                  if inputs.label is not None else None)
    input_type = inputs.input_type
    neg = inputs.neg_sampling
    num_pos = src.shape[0]
    num_neg = 0
    key = kwargs.pop('key', None)
    if key is None:
      key = self._next_key()

    if neg is not None:
      num_neg = neg.sample_size(num_pos)
      sampler = self._get_neg_sampler(input_type)
      sampler.strict = neg.strict
      kneg, key = jax.random.split(key)
      pair = sampler.sample(num_neg, padding=True, key=kneg)
      if neg.is_binary():
        src = np.concatenate([src, as_numpy(pair.rows)])
        dst = np.concatenate([dst, as_numpy(pair.cols)])
        if edge_label is None:
          edge_label = np.ones(num_pos, np.float32)
        edge_label = np.concatenate(
            [edge_label,
             np.zeros((num_neg,) + edge_label.shape[1:],
                      edge_label.dtype)])
      else:  # triplet
        assert num_neg % max(num_pos, 1) == 0, \
            'triplet amount must be an integer multiple'
        dst = np.concatenate([dst, as_numpy(pair.cols)])
        assert edge_label is None

    if input_type is not None and input_type[0] != input_type[-1]:
      # two distinct endpoint types: seed both type spaces at once (the
      # reference merges two sampler outputs, neighbor_sampler.py:376-398;
      # our multi-type hetero engine seeds them natively)
      src_t, _, dst_t = input_type
      out = self._hetero_sample_from_nodes(
          {src_t: src, dst_t: dst}, seed_type=src_t, key=key, **kwargs)
      inverse_src = out.metadata['seed_labels'][src_t]
      inverse_dst = out.metadata['seed_labels'][dst_t]
      meta = dict(out.metadata or {})
      if neg is None or neg.is_binary():
        meta['edge_label_index'] = jnp.stack([inverse_src, inverse_dst])
        meta['edge_label'] = (jnp.asarray(edge_label)
                              if edge_label is not None else None)
      else:
        meta['src_index'] = inverse_src[:num_pos]
        meta['dst_pos_index'] = inverse_dst[:num_pos]
        dst_neg = inverse_dst[num_pos:]
        if num_pos > 0 and num_neg // num_pos > 1:
          dst_neg = dst_neg.reshape(num_pos, -1)
        meta['dst_neg_index'] = dst_neg
      meta['num_pos'] = num_pos
      meta['num_neg'] = num_neg
      out.metadata = meta
      out.input_type = input_type
      return out

    seeds = np.concatenate([src, dst])
    if input_type is not None:
      out = self._hetero_sample_from_nodes(
          NodeSamplerInput(seeds, input_type[0]), key=key, **kwargs)
      inverse = out.metadata['seed_labels'][input_type[0]]
    else:
      out = self.sample_from_nodes(seeds, key=key, **kwargs)
      inverse = out.metadata['seed_labels']
    meta = dict(out.metadata or {})
    if neg is None or neg.is_binary():
      meta['edge_label_index'] = inverse.reshape(2, -1)
      meta['edge_label'] = (jnp.asarray(edge_label)
                            if edge_label is not None else None)
    else:
      meta['src_index'] = inverse[:num_pos]
      meta['dst_pos_index'] = inverse[num_pos:2 * num_pos]
      dst_neg = inverse[2 * num_pos:]
      if num_pos > 0 and num_neg // num_pos > 1:
        dst_neg = dst_neg.reshape(num_pos, -1)
      meta['dst_neg_index'] = dst_neg
    meta['num_pos'] = num_pos
    meta['num_neg'] = num_neg
    out.metadata = meta
    if input_type is not None:
      out.input_type = input_type
    return out

  # -- subgraph & hotness ------------------------------------------------

  def subgraph(self, seeds, max_degree: Optional[int] = None,
               node_capacity: Optional[int] = None):
    """Induced subgraph over the merged multi-hop neighborhood (reference
    neighbor_sampler.py:474-498 NodeSubGraph path)."""
    assert not self.is_hetero, 'subgraph is homogeneous-only (as upstream)'
    seeds = as_numpy(seeds)
    out = self.sample_from_nodes(seeds)
    g: Graph = self.graph
    cap = node_capacity or out.node.shape[0]
    return induced_subgraph(
        g.indptr, g.indices, out.node,
        jnp.arange(out.node.shape[0]) < out.node_count,
        node_capacity=cap,
        max_degree=max_degree or g.topo.max_degree,
        edge_ids=g.edge_ids, with_edge=self.with_edge)

  def sample_prob(self, train_idx, node_count=None):
    """Pre-sampling hotness estimation (reference
    neighbor_sampler.py:500-627 + CalNbrProbKernel): propagate access
    probability from the training seeds through the fanouts.

    Homo: ``train_idx`` array + ``node_count`` int -> [N] probs.
    Hetero: ``train_idx`` = (seed_type, ids); ``node_count`` optional
    Dict[ntype, int] (defaults to the inferred counts); returns
    Dict[ntype, probs], pushing probability across edge types each hop
    (the per-etype loop of the reference's hetero estimator).
    """
    if self.is_hetero:
      seed_type, ids = train_idx
      counts = dict(node_count or self._node_counts)
      probs = {t: jnp.zeros((counts[t],), jnp.float32) for t in counts}
      probs[seed_type] = probs[seed_type].at[
          jnp.asarray(as_numpy(ids))].set(1.0)
      acc = {t: p for t, p in probs.items()}
      trav = self._traversal_types()
      for h in range(self.num_hops):
        nxt = {t: jnp.zeros((counts[t],), jnp.float32) for t in counts}
        for etype, (row_t, col_t) in trav.items():
          g = self.graph[etype]
          k = self.num_neighbors[etype][h]
          if k == 0:
            continue
          contrib = neighbor_probs(g.indptr, g.indices, acc[row_t], k,
                                   counts[col_t])
          nxt[col_t] = jnp.minimum(nxt[col_t] + contrib, 1.0)
        acc = nxt
        probs = {t: jnp.minimum(probs[t] + acc[t], 1.0) for t in counts}
      return probs

    g: Graph = self.graph
    assert node_count is not None
    probs = jnp.zeros((node_count,), jnp.float32)
    probs = probs.at[jnp.asarray(as_numpy(train_idx))].set(1.0)
    acc = probs
    for fanout in self.num_neighbors:
      acc = neighbor_probs(g.indptr, g.indices, acc, fanout, node_count)
      probs = jnp.minimum(probs + acc, 1.0)
    return probs

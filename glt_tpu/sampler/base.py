"""Sampler I/O dataclasses — the PyG-compatible sampling contract.

Reference: graphlearn_torch/python/sampler/base.py (NodeSamplerInput:44,
EdgeSamplerInput:149, SamplerOutput:207, HeteroSamplerOutput:245,
NegativeSampling:85-145, SamplingConfig:339-352, BaseSampler:355-407).
Semantics preserved; payloads are jax arrays in **padded static-shape
layout**: every variable-length field carries a companion mask or count,
which is what lets the whole sampling step live inside one jit.

Orientation convention (reference neighbor_sampler.py:186-230): ``row`` is
the message-source (child) label and ``col`` the message-destination
(parent) label, i.e. ``edge_index = stack([row, col])`` is already in PyG
message-passing order for both edge_dir settings.
"""
from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, List, Optional, Union

import jax
import numpy as np

from ..typing import EdgeType, NodeType


class SamplingType(enum.Enum):
  NODE = 'node'
  LINK = 'link'
  SUBGRAPH = 'subgraph'
  RANDOM_WALK = 'random_walk'


@dataclasses.dataclass
class NodeSamplerInput:
  """Seed nodes for node-based sampling (reference base.py:44-82)."""
  node: np.ndarray
  input_type: Optional[NodeType] = None

  def __len__(self):
    return int(np.asarray(self.node).shape[0])

  def __getitem__(self, index) -> 'NodeSamplerInput':
    return NodeSamplerInput(np.asarray(self.node)[index], self.input_type)

  def share_memory(self):  # API-compat no-op (numpy is process-local)
    return self


from ..utils.common import CastMixin


@dataclasses.dataclass
class NegativeSampling(CastMixin):
  """Binary or triplet negative sampling config (reference base.py:85-145).
  CastMixin lets callers pass a dict/tuple anywhere a NegativeSampling is
  accepted (reference utils/mixin.py pattern)."""
  mode: str = 'binary'          # 'binary' | 'triplet'
  amount: Union[int, float] = 1
  strict: bool = False

  def __post_init__(self):
    assert self.mode in ('binary', 'triplet')
    if isinstance(self.amount, (int, float)) and self.amount <= 0:
      raise ValueError(
          f'negative sampling amount must be positive, got {self.amount}')
    if self.is_triplet() and isinstance(self.amount, float):
      # triplet mode needs an integral per-positive count
      # (reference base.py NegativeSampling.__init__ coerces via ceil)
      self.amount = int(math.ceil(self.amount))

  def is_binary(self) -> bool:
    return self.mode == 'binary'

  def is_triplet(self) -> bool:
    return self.mode == 'triplet'

  def sample_size(self, num_pos: int) -> int:
    # ceil matches the reference sampler's num_neg computation
    # (neighbor_sampler.py:344)
    return int(math.ceil(num_pos * float(self.amount)))


@dataclasses.dataclass
class EdgeSamplerInput:
  """Seed edges for link-based sampling (reference base.py:149-204)."""
  row: np.ndarray
  col: np.ndarray
  label: Optional[np.ndarray] = None
  input_type: Optional[EdgeType] = None
  neg_sampling: Optional[NegativeSampling] = None

  def __len__(self):
    return int(np.asarray(self.row).shape[0])

  def __getitem__(self, index) -> 'EdgeSamplerInput':
    return EdgeSamplerInput(
        np.asarray(self.row)[index],
        np.asarray(self.col)[index],
        np.asarray(self.label)[index] if self.label is not None else None,
        self.input_type, self.neg_sampling)

  def share_memory(self):
    return self


@dataclasses.dataclass
class SamplerOutput:
  """Homogeneous sampling result (reference base.py:207-242), padded.

  node: [node_capacity] global ids (-1 padded); node_count valid.
  row/col: [edge_capacity] compact labels into ``node``; edge_mask valid.
  edge: [edge_capacity] edge ids (optional).
  batch: [batch_size] labels of the seeds (always the first entries).
  num_sampled_nodes/num_sampled_edges: per-hop counts for trim_to_layer
  (reference loader/transform.py:79-100).
  """
  node: jax.Array
  node_count: jax.Array
  row: jax.Array
  col: jax.Array
  edge_mask: jax.Array
  edge: Optional[jax.Array] = None
  batch: Optional[jax.Array] = None
  num_sampled_nodes: Optional[jax.Array] = None
  num_sampled_edges: Optional[jax.Array] = None
  #: per-hop static slot boundaries (python ints; hop h edges occupy
  #: slots [edge_hop_offsets[h], edge_hop_offsets[h+1]) of row/col)
  edge_hop_offsets: Optional[List[int]] = None
  node_hop_offsets: Optional[List[int]] = None
  metadata: Optional[Dict] = None

  @property
  def batch_size(self):
    return None if self.batch is None else int(self.batch.shape[0])


@dataclasses.dataclass
class HeteroSamplerOutput:
  """Heterogeneous sampling result (reference base.py:245-302), padded:
  every per-type field mirrors SamplerOutput."""
  node: Dict[NodeType, jax.Array]
  node_count: Dict[NodeType, jax.Array]
  row: Dict[EdgeType, jax.Array]
  col: Dict[EdgeType, jax.Array]
  edge_mask: Dict[EdgeType, jax.Array]
  edge: Optional[Dict[EdgeType, jax.Array]] = None
  batch: Optional[Dict[NodeType, jax.Array]] = None
  num_sampled_nodes: Optional[Dict[NodeType, jax.Array]] = None
  num_sampled_edges: Optional[Dict[EdgeType, jax.Array]] = None
  edge_hop_offsets: Optional[Dict[EdgeType, List[int]]] = None
  input_type: Optional[Union[NodeType, EdgeType]] = None
  metadata: Optional[Dict] = None

  def get_edge_index(self) -> Dict[EdgeType, jax.Array]:
    import jax.numpy as jnp
    return {k: jnp.stack([self.row[k], self.col[k]]) for k in self.row}


@dataclasses.dataclass
class SamplingConfig:
  """The single sampling descriptor shipped to workers
  (reference base.py:339-352)."""
  sampling_type: SamplingType = SamplingType.NODE
  num_neighbors: Optional[Union[List[int], Dict[EdgeType, List[int]]]] = None
  batch_size: int = 1
  shuffle: bool = False
  drop_last: bool = False
  with_edge: bool = False
  with_weight: bool = False
  collect_features: bool = False
  edge_dir: str = 'out'
  seed: Optional[int] = None
  neg_sampling: Optional[NegativeSampling] = None


class BaseSampler:
  """ABC (reference base.py:355-407)."""

  def sample_from_nodes(self, inputs: NodeSamplerInput, **kwargs):
    raise NotImplementedError

  def sample_from_edges(self, inputs: EdgeSamplerInput, **kwargs):
    raise NotImplementedError

  @property
  def edge_permutation(self):
    return None

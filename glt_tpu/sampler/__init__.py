from .base import (
    BaseSampler, EdgeSamplerInput, HeteroSamplerOutput, NegativeSampling,
    NodeSamplerInput, SamplerOutput, SamplingConfig, SamplingType,
)
from .neighbor_sampler import NeighborSampler
from .negative_sampler import RandomNegativeSampler

__all__ = [
    'BaseSampler', 'EdgeSamplerInput', 'HeteroSamplerOutput',
    'NegativeSampling', 'NodeSamplerInput', 'SamplerOutput',
    'SamplingConfig', 'SamplingType',
    'NeighborSampler', 'RandomNegativeSampler',
]

"""Negative sampler wrapper (reference sampler/negative_sampler.py:21-57):
chooses row/col id spaces by edge_dir and delegates to the strict/padded
negative sampling op."""
from __future__ import annotations

from typing import Optional

import jax

from ..data import Graph
from ..ops.negative import NegativeOutput, random_negative_sample
from ..utils.rng import RandomSeedManager


class RandomNegativeSampler:
  """Samples (src, dst) non-edges from a Graph.

  ``mode='strict'`` rejects existing edges (binary-search membership);
  ``padding=True`` always returns a full batch (reference semantics,
  negative_sampler.py:39-57).
  """

  def __init__(self, graph: Graph, mode: str = 'strict',
               edge_dir: str = 'out'):
    self.graph = graph
    self.strict = (mode == 'strict')
    self.edge_dir = edge_dir

  def sample(self, req_num: int, trials_num: int = 5,
             padding: bool = False,
             key: Optional[jax.Array] = None) -> NegativeOutput:
    g = self.graph
    if key is None:
      key = RandomSeedManager.getInstance().nextKey()
    out = random_negative_sample(
        g.indptr, g.indices, req_num=req_num, trials_num=trials_num,
        key=key, num_rows=g.topo.num_rows, num_cols=g.topo.num_cols,
        strict=self.strict, padding=padding)
    if (self.edge_dir == 'in'):
      # stored layout is CSC (rows = dst): swap so callers always get
      # (src, dst) pairs in original-graph orientation
      return NegativeOutput(rows=out.cols, cols=out.rows, mask=out.mask)
    return out

"""glt_tpu — a TPU-native graph-learning framework.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of
GraphLearn-for-PyTorch (graph sampling, unified feature store, distributed
sampling/training), built for TPU: static shapes, SPMD meshes, XLA
collectives, and Pallas kernels on the hot paths.
"""

__version__ = '0.1.0'

from .utils import compat as _compat  # noqa: E402

_compat.install()  # backfill jax.shard_map / jax.memory on older jax

from . import typing  # noqa: F401
from . import utils  # noqa: F401
from . import obs  # noqa: F401
from . import data  # noqa: F401
from . import ops  # noqa: F401
from . import sampler  # noqa: F401
from . import loader  # noqa: F401
from . import models  # noqa: F401
from . import channel  # noqa: F401
from . import partition  # noqa: F401
from . import parallel  # noqa: F401
from . import distributed  # noqa: F401
from . import resilience  # noqa: F401
from . import serving  # noqa: F401
from . import stream  # noqa: F401

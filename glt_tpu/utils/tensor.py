"""Array conversion helpers (reference: graphlearn_torch/python/utils/tensor.py).

The reference converts arbitrary nested inputs to torch tensors and builds
dense id->index maps (tensor.py:30-97). Here the host-side currency is numpy
and the device-side currency is jax arrays.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def as_numpy(x: Any, dtype=None) -> Optional[np.ndarray]:
  """Convert array-likes (lists, jax arrays, torch tensors) to numpy."""
  if x is None:
    return None
  if isinstance(x, dict):
    return {k: as_numpy(v, dtype) for k, v in x.items()}
  if isinstance(x, np.ndarray):
    arr = x
  elif isinstance(x, jax.Array):
    arr = np.asarray(x)
  elif hasattr(x, 'detach'):  # torch tensor without importing torch
    arr = x.detach().cpu().numpy()
  else:
    arr = np.asarray(x)
  if dtype is not None:
    arr = arr.astype(dtype, copy=False)
  return arr


def as_jax(x: Any, dtype=None) -> Optional[jax.Array]:
  if x is None:
    return None
  if isinstance(x, dict):
    return {k: as_jax(v, dtype) for k, v in x.items()}
  arr = jnp.asarray(as_numpy(x))
  if dtype is not None:
    arr = arr.astype(dtype)
  return arr


def ensure_device(x: Any, device=None) -> Any:
  """device_put pytree leaves (host->HBM transfer point)."""
  if device is None:
    return jax.device_put(x)
  return jax.device_put(x, device)


def id2idx(ids: np.ndarray) -> np.ndarray:
  """Dense global-id -> local-index map (reference utils/tensor.py:30-39).

  Returns an array of size max(ids)+1 where out[ids[i]] = i.
  """
  ids = as_numpy(ids).astype(np.int64)
  max_id = int(ids.max()) if ids.size else 0
  out = np.zeros(max_id + 1, dtype=np.int64)
  out[ids] = np.arange(ids.shape[0], dtype=np.int64)
  return out


def index_select(data: Any, index: np.ndarray) -> Any:
  """Row-select over arrays / dicts of arrays."""
  if data is None:
    return None
  if isinstance(data, dict):
    return {k: index_select(v, index) for k, v in data.items()}
  return data[index]

"""Seed management.

TPU-native analogue of the reference's native ``RandomSeedManager`` singleton
(reference include/common.h:36-61, used by neighbor_sampler.py:67-68): a
process-wide base seed from which functional jax PRNG keys are derived.
Every consumer folds in a fresh counter so independent samplers never share
a key stream, while the whole run stays reproducible from one seed.
"""
from __future__ import annotations

import threading

import jax

from .env import knob


def make_key(seed: int) -> jax.Array:
  """Typed PRNG key honoring ``GLT_PRNG`` (e.g. ``rbg``).

  threefry (jax default) is counter-based and bit-reproducible across
  backends — the right default for tests and parity. ``GLT_PRNG=rbg``
  selects the XLA RngBitGenerator implementation, which generates bits
  several times faster on TPU (benchmarks/microbench_prims.py
  uniform_15x153k A/B) at the cost of cross-backend reproducibility.
  The impl travels inside the typed key, so every ``jax.random.split``
  / ``fold_in`` downstream inherits it.
  """
  impl = knob('GLT_PRNG', None) or None
  return jax.random.key(int(seed), impl=impl)


class RandomSeedManager:
  _instance = None
  _lock = threading.Lock()

  def __init__(self):
    self._seed = 42
    self._counter = 0
    self._local = threading.Lock()

  @classmethod
  def getInstance(cls) -> 'RandomSeedManager':
    with cls._lock:
      if cls._instance is None:
        cls._instance = cls()
      return cls._instance

  def setSeed(self, seed: int) -> None:
    with self._local:
      self._seed = int(seed)
      self._counter = 0

  def getSeed(self) -> int:
    with self._local:
      return self._seed

  def nextKey(self) -> jax.Array:
    # seed and counter must come from ONE lock hold: a setSeed racing
    # between the counter draw and the seed read would pair the new
    # seed with the old stream position (gltlint GLT002)
    with self._local:
      c = self._counter
      self._counter += 1
      seed = self._seed
    return jax.random.fold_in(make_key(seed), c)


def new_key() -> jax.Array:
  return RandomSeedManager.getInstance().nextKey()

"""Training checkpoint/resume via orbax.

The reference leaves checkpointing to examples (torch.save of model
state, examples/igbh/dist_train_rgnn.py:190-213 with ckpt_steps); here
it is a first-class utility: save/restore (params, opt_state, step) with
retention, usable from any training loop.
"""
from __future__ import annotations

import os
from typing import Any, Optional



def _manager(ckpt_dir: str, max_to_keep: int = 3):
  import orbax.checkpoint as ocp
  options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                         create=True)
  return ocp.CheckpointManager(os.path.abspath(ckpt_dir),
                               options=options)


def save_checkpoint(ckpt_dir: str, step: int, params: Any,
                    opt_state: Any = None, extra: Any = None,
                    max_to_keep: int = 3) -> None:
  import orbax.checkpoint as ocp
  mgr = _manager(ckpt_dir, max_to_keep)
  payload = {'params': params}
  if opt_state is not None:
    payload['opt_state'] = opt_state
  if extra is not None:
    payload['extra'] = extra
  mgr.save(step, args=ocp.args.StandardSave(payload))
  mgr.wait_until_finished()
  mgr.close()


def restore_checkpoint(ckpt_dir: str, step: Optional[int] = None,
                       template: Any = None):
  """Returns (step, payload dict). ``template`` (a matching pytree of
  arrays) restores with correct shardings/dtypes when given."""
  import orbax.checkpoint as ocp
  mgr = _manager(ckpt_dir)
  step = mgr.latest_step() if step is None else step
  if step is None:
    return None, None
  if template is not None:
    out = mgr.restore(step, args=ocp.args.StandardRestore(template))
  else:
    try:
      out = mgr.restore(step)
    except KeyError:
      # newer orbax refuses a bare restore of a StandardSave item
      # without args; an explicit template-less StandardRestore
      # reconstructs the tree as saved
      out = mgr.restore(step, args=ocp.args.StandardRestore())
  mgr.close()
  return step, out

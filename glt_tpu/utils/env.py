"""Typed environment knobs that can never crash ``import glt_tpu``.

Every ``GLT_*`` tunable is read through :func:`knob`: a malformed value
(``GLT_OBS_BUFFER=zillion``) warns once and falls back to the default
instead of raising ``ValueError`` at import time — the bug class that
took down whole processes twice (GLT_OBS_BUFFER in PR 6,
GLT_OBS_POSTMORTEM_MIN_S in PR 11) before gltlint rule GLT001 made raw
``os.environ`` parses illegal in package code.

Parsing contract (chosen by the ``default``'s type, or an explicit
``parse`` callable):

  * bool  — '1'/'true'/'yes'/'on' → True; '0'/''/'false'/'no'/'off' →
    False (case-insensitive); anything else warns and defaults.
  * int / float — the obvious conversions; ValueError warns + defaults.
  * str / None default — the raw string, unset → default.

``knob`` reads the environment on every call (tests monkeypatch knobs
mid-process; caching would make the patch a no-op). :func:`raw` is the
sanctioned passthrough for non-GLT infra vars (``JAX_PLATFORMS``,
``XLA_FLAGS``) whose values are opaque strings, not parses.
"""
from __future__ import annotations

import os
import warnings
from typing import Callable, Optional, TypeVar

T = TypeVar('T')

_TRUE = frozenset(('1', 'true', 'yes', 'on'))
_FALSE = frozenset(('0', '', 'false', 'no', 'off'))

#: malformed values we already warned for: (name, raw value) — one
#: warning per distinct bad value, not one per read in a hot loop
_warned: set = set()


def parse_bool(raw: str) -> bool:
  low = raw.strip().lower()
  if low in _TRUE:
    return True
  if low in _FALSE:
    return False
  raise ValueError(f'not a boolean: {raw!r}')


def knob(name: str, default: T,
         parse: Optional[Callable[[str], T]] = None) -> T:
  """Read env var ``name``, parsed to the type of ``default``.

  Unset or empty → ``default``. Malformed → ``warnings.warn`` once per
  distinct bad value, then ``default`` — never an exception.

  Args:
    name: environment variable, by convention ``GLT_*``.
    default: returned when unset/empty/malformed; its type picks the
      parser when ``parse`` is None (bool → :func:`parse_bool`,
      int/float → the constructor, anything else → identity).
    parse: explicit ``str -> T`` override; a raised ``ValueError`` /
      ``TypeError`` triggers the warn-and-default path.
  """
  raw = os.environ.get(name)
  if raw is None or raw == '':
    return default
  if parse is None:
    if isinstance(default, bool):        # before int: bool is an int
      parse = parse_bool
    elif isinstance(default, int):
      parse = int
    elif isinstance(default, float):
      parse = float
    else:
      return raw  # type: ignore[return-value]
  try:
    return parse(raw)
  except (ValueError, TypeError):
    key = (name, raw)
    if key not in _warned:
      _warned.add(key)
      warnings.warn(
          f'{name}={raw!r} is malformed; using default {default!r}',
          RuntimeWarning, stacklevel=2)
    return default


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
  """Opaque string read (no parse, nothing to crash) — the sanctioned
  path for infra vars like ``JAX_PLATFORMS``/``XLA_FLAGS``."""
  return os.environ.get(name, default)

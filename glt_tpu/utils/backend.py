"""Central backend/platform selection — the axon-plugin footgun guard.

The TPU plugin in this environment IGNORES the ``JAX_PLATFORMS`` env
var: the only authoritative switch is
``jax.config.update('jax_platforms', ...)``, and it must run BEFORE the
first backend contact — a process that touches the default backend
while the TPU tunnel is wedged hangs silently in backend init. Every
entry point (``__graft_entry__``, benches, the test conftest, examples)
routes through :func:`force_backend` so that rule lives in code once
(VERDICT r4 next #8), not in per-file docstrings.
"""
from __future__ import annotations

import os
from typing import Optional

from .env import raw as raw_env

_ENV_VARS = ('GLT_BENCH_PLATFORM', 'GLT_PLATFORM')


def force_backend(platform: Optional[str] = None,
                  host_devices: Optional[int] = None) -> Optional[str]:
  """Select the jax platform safely; call before any other jax use.

  Args:
    platform: 'cpu' / 'tpu' / None. None consults GLT_BENCH_PLATFORM
      then GLT_PLATFORM (the bench/example conventions) and leaves the
      default backend alone when neither is set.
    host_devices: if given, ensure XLA_FLAGS carries
      ``--xla_force_host_platform_device_count=<n>`` (the virtual-mesh
      testing setup) — also only effective before backend init.

  Returns the platform applied (or None if untouched).

  Raises RuntimeError when a DIFFERENT backend was already initialized:
  a too-late call is the exact bug this helper exists to prevent, and
  silently proceeding would re-wedge entry points on the axon tunnel.
  """
  if platform is None:
    for var in _ENV_VARS:
      if raw_env(var):
        platform = raw_env(var)
        break
  if host_devices is not None:
    flags = raw_env('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
      os.environ['XLA_FLAGS'] = (
          flags + f' --xla_force_host_platform_device_count'
          f'={host_devices}').strip()
  if platform is None:
    return None

  import jax
  initialized = None
  try:  # private, version-sensitive: best-effort too-late detection
    from jax._src import xla_bridge
    if xla_bridge._backends:
      initialized = sorted(xla_bridge._backends)
  except Exception:
    pass
  if initialized is not None:
    if platform not in initialized:
      raise RuntimeError(
          f'force_backend({platform!r}) called after backend(s) '
          f'{initialized} initialized — platform selection must run '
          'before the first jax backend contact (the axon plugin '
          'ignores JAX_PLATFORMS, so this ordering is the only switch)')
    return platform  # already on the requested platform: idempotent
  jax.config.update('jax_platforms', platform)
  return platform

"""Misc helpers (reference: graphlearn_torch/python/utils/common.py, units.py)."""
from __future__ import annotations

import random
from typing import Dict

import numpy as np


def seed_everything(seed: int) -> None:
  """Seed python/numpy and the glt_tpu RandomSeedManager
  (reference utils/common.py:31-41)."""
  random.seed(seed)
  np.random.seed(seed)
  from .rng import RandomSeedManager
  RandomSeedManager.getInstance().setSeed(seed)


def merge_dict(in_dict: Dict, out_dict: Dict) -> Dict:
  """Append values of ``in_dict`` onto value-lists of ``out_dict``
  (reference utils/common.py:85-97)."""
  for k, v in in_dict.items():
    vals = out_dict.get(k, [])
    vals.append(v)
    out_dict[k] = vals
  return out_dict


_UNITS = {
    'k': 1024, 'm': 1024 ** 2, 'g': 1024 ** 3, 't': 1024 ** 4,
    'kb': 1024, 'mb': 1024 ** 2, 'gb': 1024 ** 3, 'tb': 1024 ** 4,
}


def parse_size(size: object) -> int:
  """'10GB' -> bytes (reference utils/units.py)."""
  if isinstance(size, (int, np.integer)):
    return int(size)
  s = str(size).strip().lower()
  num = s
  unit = ''
  for i, ch in enumerate(s):
    if not (ch.isdigit() or ch == '.'):
      num, unit = s[:i], s[i:].strip()
      break
  if unit and unit not in _UNITS:
    raise ValueError(f'unknown size unit {unit!r}')
  scale = _UNITS.get(unit, 1)
  return int(float(num) * scale)


class CastMixin:
  """Construct from dict/tuple transparently (reference utils/mixin.py)."""

  @classmethod
  def cast(cls, *args, **kwargs):
    if len(args) == 1 and len(kwargs) == 0:
      elem = args[0]
      if elem is None or isinstance(elem, cls):
        return elem
      if isinstance(elem, (tuple, list)):
        return cls(*elem)
      if isinstance(elem, dict):
        return cls(**elem)
    return cls(*args, **kwargs)

"""MLPerf-style structured logging hooks.

Reference: examples/igbh/mlperf_logging_utils.py (GLT was an MLPerf GNN
submission vehicle). A dependency-free shim emitting the ':::MLLOG'
line format so result parsers work; swaps transparently for the official
mlperf_logging package when installed.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

INTERVAL_START = 'INTERVAL_START'
INTERVAL_END = 'INTERVAL_END'
POINT_IN_TIME = 'POINT_IN_TIME'


class MLLogger:
  def __init__(self, benchmark: str = 'gnn', org: str = 'glt_tpu',
               emit=print):
    self.benchmark = benchmark
    self.org = org
    self._emit = emit

  def _log(self, event_type: str, key: str, value: Any = None,
           metadata: Optional[Dict] = None) -> None:
    record = {
        'namespace': self.benchmark,
        'time_ms': int(time.time() * 1000),
        'event_type': event_type,
        'key': key,
        'value': value,
        'metadata': metadata or {},
    }
    self._emit(f':::MLLOG {json.dumps(record)}')

  def start(self, key: str, value: Any = None, metadata=None):
    self._log(INTERVAL_START, key, value, metadata)

  def end(self, key: str, value: Any = None, metadata=None):
    self._log(INTERVAL_END, key, value, metadata)

  def event(self, key: str, value: Any = None, metadata=None):
    self._log(POINT_IN_TIME, key, value, metadata)

  # convenience markers used by the IGBH-style loop
  def run_start(self):
    self.start('run_start')

  def run_stop(self, status: str = 'success', epoch: int = None):
    md = {'status': status}
    if epoch is not None:
      md['epoch_num'] = epoch
    self.end('run_stop', metadata=md)

  def epoch_start(self, epoch: int):
    self.start('epoch_start', metadata={'epoch_num': epoch})

  def epoch_stop(self, epoch: int):
    self.end('epoch_stop', metadata={'epoch_num': epoch})

  def eval_start(self, epoch: int):
    self.start('eval_start', metadata={'epoch_num': epoch})

  def eval_stop(self, epoch: int):
    self.end('eval_stop', metadata={'epoch_num': epoch})

  def eval_accuracy(self, value: float, epoch: int):
    self.event('eval_accuracy', value, metadata={'epoch_num': epoch})

  # submission/init block — the reference emits these via the official
  # mlperf_logging constants (examples/igbh/mlperf_logging_utils.py:12-33,
  # dist_train_rgnn.py:345-346,435-440); same key strings here so result
  # parsers treat the two logs identically.
  def submission_info(self, benchmark: str = 'GNN',
                      submitter: str = 'glt_tpu',
                      platform: str = 'tpu'):
    self.event('submission_benchmark', benchmark)
    self.event('submission_org', submitter)
    self.event('submission_division', 'closed')
    self.event('submission_status', 'onprem')
    self.event('submission_platform', platform)

  def init_start(self):
    self.event('cache_clear', True)
    self.start('init_start')

  def init_stop(self):
    self.end('init_stop')

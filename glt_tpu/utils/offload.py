"""Shared host-offload placement policy.

One decision, three stores (data.Feature, parallel.ShardedFeature,
distributed.DistFeature): spilled cold rows default to a PINNED-HOST
jax array served in-program (the UVA analog, reference
unified_tensor.cu:202-231), opt out with GLT_HOST_OFFLOAD=0 or
host_offload=False, and an EXPLICIT host_offload=True must surface
placement failures instead of silently degrading to the host phase.
"""
from __future__ import annotations

from typing import Optional

from .env import knob


def offload_requested(host_offload: Optional[bool],
                      spilled: bool) -> bool:
  """Resolve the tri-state flag: None = auto (on when spilled unless
  GLT_HOST_OFFLOAD=0)."""
  if host_offload is None:
    return spilled and knob('GLT_HOST_OFFLOAD', True)
  return bool(host_offload)


def pinned_host_supported(device=None) -> bool:
  """Capability probe: can this backend place arrays in pinned host
  memory at all? Distinguishes 'the platform cannot offload' (fall back
  / skip) from 'offload regressed on a platform that can' (fail loudly)
  — graft dryruns and platform-conditional tests key off it."""
  import jax
  dev = device or jax.devices()[0]
  try:
    return any(getattr(m, 'kind', None) == 'pinned_host'
               for m in dev.addressable_memories())
  except Exception:
    pass
  try:  # older jax without addressable_memories: probe with a put
    import numpy as np
    from jax.sharding import SingleDeviceSharding
    jax.device_put(np.zeros((1,), np.float32),
                   SingleDeviceSharding(dev, memory_kind='pinned_host'))
    return True
  except Exception:
    return False


def maybe_pin_host(build_fn, host_offload: Optional[bool]):
  """Run ``build_fn()`` (which must place an array in pinned host
  memory) tolerating platforms without memory kinds: auto mode returns
  None on failure (caller keeps its host-phase path), an explicit
  ``host_offload=True`` re-raises."""
  try:
    return build_fn()
  except Exception:
    if host_offload:  # explicitly asked for: do not mask the failure
      raise
    return None

from .tensor import (
    as_numpy, as_jax, id2idx, ensure_device, index_select,
)
from .common import seed_everything, merge_dict, parse_size
from .rng import RandomSeedManager, new_key

__all__ = [
    'as_numpy', 'as_jax', 'id2idx', 'ensure_device', 'index_select',
    'seed_everything', 'merge_dict', 'parse_size',
    'RandomSeedManager', 'new_key',
]
from . import profile  # noqa: F401
from . import checkpoint  # noqa: F401

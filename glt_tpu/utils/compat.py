"""JAX version compatibility shims.

The codebase targets the current jax API (``jax.shard_map`` with
``check_vma``, ``jax.memory.Space``); some deployment images pin an
older jax (0.4.x) where shard_map still lives at
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` kwarg
and the memory-space enum does not exist yet. Rather than fork every
call site, :func:`install` backfills the modern names onto the ``jax``
module once, at ``glt_tpu`` import time. On a current jax it is a
no-op.
"""
from __future__ import annotations

import functools
import types

import jax


def _shard_map_backport():
  from jax.experimental.shard_map import shard_map as legacy

  @functools.wraps(legacy)
  def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                check_vma=True, **kwargs):
    # modern kwarg name -> legacy one; semantics are identical (whether
    # to verify per-output replication/varying-manual-axes claims)
    kwargs.setdefault('check_rep', check_vma)
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)

  return shard_map


#: True when install() had to backfill jax.shard_map — i.e. we are on a
#: legacy (0.4.x) jax. Some code paths work around old-jax miscompiles
#: keyed off this (e.g. collectives under a traced lax.while_loop inside
#: shard_map produce wrong values there; the capped-bucket drain then
#: unrolls statically instead).
LEGACY_JAX = False


def install() -> None:
  """Idempotently backfill modern jax API names used by glt_tpu."""
  global LEGACY_JAX
  if not hasattr(jax, 'shard_map'):
    LEGACY_JAX = True
    jax.shard_map = _shard_map_backport()
  if not hasattr(jax.lax, 'axis_size'):
    from jax._src import core as _core

    def axis_size(axis_name):
      # 0.4.x: the axis env frame for a name IS its (static int) size
      return _core.axis_frame(axis_name)

    jax.lax.axis_size = axis_size
  if not hasattr(jax, 'memory'):
    # jax.memory.Space.{Host,Device} appeared after 0.4.x; the transfer
    # targets map onto the older TransferToMemoryKind markers
    try:
      from jax._src.sharding_impls import TransferToMemoryKind
      space = types.SimpleNamespace(
          Host=TransferToMemoryKind('pinned_host'),
          Device=TransferToMemoryKind('device'))
      jax.memory = types.SimpleNamespace(Space=space)
    except ImportError:
      pass  # neither the modern nor the legacy spelling exists: leave
      # the offload paths to their own graceful fallbacks

"""Host-side prefetching iterator.

The reference hides sampling/feature latency behind training with
multi-process producers and shm channels (dist_sampling_producer.py). For
the in-process loaders the same overlap comes from a small prefetch
thread: while the device executes step N, the host prepares batch N+1
(seed shuffling, cold-row gathers, device_put). jit dispatch being async,
depth 2 is usually enough to keep the chip busy.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator


class PrefetchIterator:
  """Wraps any batch iterable; materializes up to ``depth`` batches ahead
  on a worker thread. Exceptions propagate to the consumer. Closing or
  abandoning the consumer generator stops AND JOINS the worker (bounded
  wait), so its batch references — device arrays, pinned buffers — are
  dropped promptly instead of leaking until process exit."""

  _END = object()

  #: how long the consumer's cleanup waits for the worker to notice the
  #: stop flag. The worker polls it every 0.1 s between queue puts; a
  #: longer wait only happens when it is blocked INSIDE the wrapped
  #: iterable (e.g. a device sync), in which case cleanup gives up and
  #: leaves the daemon thread to finish that one item on its own.
  JOIN_TIMEOUT = 5.0

  def __init__(self, iterable: Iterable, depth: int = 2):
    self.iterable = iterable
    self.depth = max(1, int(depth))
    #: the most recent __iter__'s worker (introspection/tests)
    self.worker_thread = None

  def __iter__(self) -> Iterator:
    q: 'queue.Queue' = queue.Queue(maxsize=self.depth)
    stop = threading.Event()

    def _put(item) -> bool:
      # bounded puts poll the stop flag so an abandoned consumer can't
      # leave the worker blocked forever holding batch references
      while not stop.is_set():
        try:
          q.put(item, timeout=0.1)
          return True
        except queue.Full:
          continue
      return False

    def worker():
      try:
        for item in self.iterable:
          if not _put(item):
            return
      except BaseException as e:  # surface to consumer
        _put(e)
        return
      _put(self._END)

    t = threading.Thread(target=worker, daemon=True)
    self.worker_thread = t
    t.start()
    try:
      while True:
        item = q.get()
        if item is self._END:
          return
        if isinstance(item, BaseException):
          raise item
        yield item
    finally:
      stop.set()
      t.join(timeout=self.JOIN_TIMEOUT)


def prefetch(iterable: Iterable, depth: int = 2) -> PrefetchIterator:
  return PrefetchIterator(iterable, depth)

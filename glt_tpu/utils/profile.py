"""Profiling / throughput instrumentation.

The reference measures throughput with manual time.time() +
cuda.synchronize in bench scripts (SURVEY.md §5.1) and has no built-in
tracer. Here timing hooks are first-class: a ThroughputMeter for the
sampled-edges/sec north-star metric, a device-synchronizing Timer, and a
context manager around the XLA profiler for real traces.
"""
from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


class Timer:
  """Wall-clock timer that synchronizes outstanding device work.

  ``elapsed`` accumulates across start/stop intervals; each ``stop()``
  consumes the matching ``start()``, so a stop without a running
  interval raises a clear RuntimeError instead of the historical
  ``TypeError: unsupported operand`` on the ``None`` start stamp."""

  def __init__(self):
    self.reset()

  def reset(self):
    self._t0 = None
    self.elapsed = 0.0

  @property
  def running(self) -> bool:
    return self._t0 is not None

  def start(self):
    # re-entrant start (incl. reusing one Timer across `with` blocks)
    # cleanly restarts the interval stamp; accumulated elapsed stays
    self._t0 = time.perf_counter()
    return self

  def stop(self, sync: Optional[jax.Array] = None) -> float:
    if self._t0 is None:
      raise RuntimeError(
          'Timer.stop() without a running interval: call start() (or '
          'enter the context manager) first; each stop() consumes its '
          'start()')
    if sync is not None:
      jax.block_until_ready(sync)
    self.elapsed += time.perf_counter() - self._t0
    self._t0 = None
    return self.elapsed

  def __enter__(self):
    return self.start()

  def __exit__(self, *exc):
    if self._t0 is not None:  # tolerate an explicit stop() in the body
      self.stop()


class ThroughputMeter:
  """Accumulates (count, seconds) and reports rate — the
  'Sampled Edges per secs' metric (benchmarks/api/bench_sampler.py)."""

  def __init__(self, unit: str = 'edges'):
    self.unit = unit
    self.count = 0
    self.seconds = 0.0

  def update(self, count: int, seconds: float):
    self.count += int(count)
    self.seconds += seconds

  @property
  def rate(self) -> float:
    return self.count / self.seconds if self.seconds > 0 else 0.0

  def report(self) -> str:
    # auto-scale the unit: a hard-coded /1e6 printed every sub-million
    # rate (e.g. serving QPS) as '0.00M'
    r = self.rate
    if r >= 1e6:
      return f'{r / 1e6:.2f}M {self.unit}/s'
    if r >= 1e3:
      return f'{r / 1e3:.2f}K {self.unit}/s'
    return f'{r:.2f} {self.unit}/s'


@contextlib.contextmanager
def trace(log_dir: str):
  """XLA profiler trace (view with tensorboard / xprof)."""
  jax.profiler.start_trace(log_dir)
  try:
    yield
  finally:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
  """Named region inside a trace."""
  with jax.profiler.TraceAnnotation(name):
    yield

"""StreamSampler — delta-aware multi-hop sampling over versioned
snapshots.

Same contract as :class:`~glt_tpu.sampler.neighbor_sampler.
NeighborSampler` (homogeneous node sampling), with two structural
differences that make live updates compile-stable:

  1. The graph arrays are **jit arguments**, not closure constants: the
     compiled multihop program is keyed only on the seed batch shape,
     so a snapshot swap (same padded capacities) or a delta-overlay
     refresh re-runs the SAME executable — zero steady-state
     recompiles, asserted by tests via :attr:`num_compiled_fns` /
     :attr:`trace_count`.
  2. Every hop is a :func:`~glt_tpu.ops.delta.delta_one_hop`: base
     sample + tombstone mask + a fixed-capacity per-node insert window,
     so the effective hop width is ``abs(fanout) + delta_window``
     (static). Capacity math (frontier budgets, edge hop offsets) uses
     the effective widths throughout.

Reads follow the manager's RCU protocol: each ``sample_from_nodes``
acquires the current snapshot, samples against its arrays, and releases
it — compaction never yanks device buffers from under an in-flight
sample.

Not supported (assert-guarded): hetero graphs, weighted sampling, and
``with_edge`` (delta edges have no stable compressed slot until
compaction folds them into the CSR).
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.delta import delta_one_hop
from ..ops.pipeline import edge_hop_offsets, hop_engine, \
    make_dedup_tables, multihop_sample
from ..sampler.base import BaseSampler, NodeSamplerInput, SamplerOutput
from ..utils import as_numpy
from ..utils.env import knob
from ..utils.rng import RandomSeedManager, make_key
from .snapshot import SnapshotManager

logger = logging.getLogger(__name__)


class StreamSampler(BaseSampler):
  """Multi-hop sampling over a :class:`SnapshotManager`.

  Args:
    manager: snapshot chain + overlay builder.
    num_neighbors: [K_1..K_h]; -1 = full neighborhood inside
      ``full_neighbor_cap`` (resolved ONCE at construction — the window
      is a compile-shape constant, so size it for the max degree the
      stream is expected to reach, not just the startup graph's).
    delta_window: per-node insert-overlay window per hop (static). A
      frontier node with more pending inserts than this truncates until
      compaction.
    tombstone_window: per-node delete-overlay window (defaults to
      ``delta_window``).
    edge_dir: must match the manager's base layout ('out' = CSR).
    window_hub_cap: static hub capacity ``H`` for the windowed base-hop
      engines (``GLT_HOP_ENGINE=window|pallas``); defaults to the
      startup snapshot's true hub count plus 25% headroom. A snapshot
      whose hub count outgrows the cap warns loudly (hub rows past the
      cap keep window-truncated picks until the cap is raised).
    seed: RNG seed (defaults to the process RandomSeedManager).
  """

  def __init__(self, manager: SnapshotManager,
               num_neighbors: Sequence[int],
               *, delta_window: int = 8,
               tombstone_window: Optional[int] = None,
               replace: bool = False,
               edge_dir: Optional[str] = None,
               full_neighbor_cap: Optional[int] = None,
               window_hub_cap: Optional[int] = None,
               seed: Optional[int] = None):
    self.manager = manager
    self.is_hetero = False
    self.with_edge = False
    self.replace = replace
    self.delta_window = int(delta_window)
    self.tombstone_window = int(
        delta_window if tombstone_window is None else tombstone_window)
    assert self.delta_window >= 0 and self.tombstone_window >= 0
    layout_dir = 'out' if manager.layout == 'CSR' else 'in'
    if edge_dir is None:
      edge_dir = layout_dir
    assert edge_dir == layout_dir, (
        f'edge_dir {edge_dir!r} needs a '
        f'{"CSR" if edge_dir == "out" else "CSC"} base, manager holds '
        f'{manager.layout}')
    self.edge_dir = edge_dir

    base = manager.current().topo
    self._base_fanouts = []
    for f in num_neighbors:
      f = int(f)
      if f == -1:
        # default headroom: one delta epoch's worth of per-node inserts
        # lands in the base at compaction, so the startup max degree
        # alone would truncate right after the first insert-heavy swap
        cap = int(full_neighbor_cap
                  or base.max_degree + self.delta_window)
        assert cap > 0, 'graph has no edges; fanout=-1 is meaningless'
        self._base_fanouts.append(-cap)
      else:
        assert f > 0, f'fanout must be positive or -1, got {f}'
        self._base_fanouts.append(f)
    self._full_cap = min((abs(f) for f in self._base_fanouts if f < 0),
                         default=None)
    self._trunc_warned_version = -1
    # effective pipeline widths: every hop appends the insert window.
    # negative encoding: the pipeline treats these as fixed windows
    # (capacity math via abs), never as uniform-sample fanouts.
    self.num_neighbors = [-(abs(f) + self.delta_window)
                          for f in self._base_fanouts]
    self.num_hops = len(self._base_fanouts)

    self.window_hub_cap = window_hub_cap
    self._hub_cap = {}            # width -> resolved static hub cap
    self._hub_checked_key = None  # last (version, width) hub-checked
    self._window_warned_version = -1
    self._base_key = make_key(
        seed if seed is not None
        else RandomSeedManager.getInstance().getSeed())
    self._step = 0
    self._fn_cache = {}
    self._tables = {}
    #: times any multihop program was traced (trace-time side effect;
    #: flat in steady state even across snapshot swaps)
    self.trace_count = 0
    self._overlay = manager.empty_overlay()

  # -- compile discipline ------------------------------------------------

  @property
  def num_compiled_fns(self) -> int:
    """Compiled multihop programs, one per seed-shape signature (the
    serving engine's zero-recompile assertions read this, exactly as
    with NeighborSampler)."""
    return sum(1 for k in self._fn_cache if k[0] == 'homo')

  # -- live-update hooks -------------------------------------------------

  def set_overlay(self, overlay: dict) -> None:
    """Install freshly built delta overlays (manager.build_overlay).
    Takes effect on the next sample call; in-flight calls finish on the
    arrays they captured."""
    self._overlay = overlay

  def refresh_overlay(self, buffer) -> None:
    self.set_overlay(self.manager.build_overlay(buffer))

  def clear_overlay(self) -> None:
    self.set_overlay(self.manager.empty_overlay())

  # -- sampling ----------------------------------------------------------

  def _next_key(self) -> jax.Array:
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _get_tables(self, num_nodes: int):
    if '' not in self._tables:
      self._tables[''] = make_dedup_tables(num_nodes)
    return self._tables['']

  def _window_plan(self, snap) -> tuple:
    """Resolve the base-hop read engine for this snapshot: ('element',
    0, 0) or (engine, W, H_cap). Static per compiled program (part of
    the fn cache key) so a stable engine choice keeps the zero-
    steady-state-recompile guarantee; the ONLY flips are env changes or
    a snapshot whose capacity slack no longer covers W (loud warning,
    one retrace — same class of event as a capacity growth).

    The snapshot's capacity-padded ``indices`` doubles as the window
    source: every valid window needs ``start + W <= capacity``, i.e.
    padding slack >= W (starts never exceed the live edge count)."""
    eng = getattr(self, '_hop_engine_override', None) or hop_engine()
    if eng == 'pallas_fused':
      # delta hops interleave base picks with tombstone masks and
      # insert-overlay expansion — the VMEM dedup table can't sit
      # across that merge, so the stream path rides the plain pallas
      # megakernel for its base reads (counted, once per sampler)
      if not getattr(self, '_fused_fallback_counted', False):
        self._fused_fallback_counted = True
        from ..ops.pipeline import count_engine_fallback
        requested = (getattr(self, '_hop_engine_override', None)
                     or knob('GLT_HOP_ENGINE', 'auto'))
        count_engine_fallback(requested, 'pallas', 'stream_overlay')
      eng = 'pallas'
    if eng == 'element' or not any(f > 0 for f in self._base_fanouts):
      return ('element', 0, 0)
    from ..sampler.neighbor_sampler import _window_width
    width = _window_width()
    slack = int(snap.arrays['indices'].shape[0]) - int(snap.num_edges)
    if slack < width:
      if snap.version != self._window_warned_version:
        self._window_warned_version = snap.version
        logger.warning(
            'snapshot v%d capacity slack %d < window width %d: the '
            'windowed base-hop engine (%s) falls back to element reads '
            'until a compaction grows capacity. Raise edge_capacity/'
            'delta_capacity to keep >= W slots free.',
            snap.version, slack, width, eng)
      return ('element', 0, 0)
    # ONE O(num_rows) degree scan per (snapshot version, width): it
    # both resolves the static cap (first time) and checks the current
    # snapshot against it. Only the latest version's marker is kept —
    # versions are monotone, so per-version memo entries would grow
    # without bound over a long-running stream.
    if width not in self._hub_cap or \
        self._hub_checked_key != (snap.version, width):
      hubs = int((np.diff(snap.topo.indptr) > width).sum())
      self._hub_checked_key = (snap.version, width)
      if width not in self._hub_cap:
        self._hub_cap[width] = int(
            self.window_hub_cap if self.window_hub_cap is not None
            else hubs + max(8, hubs // 4))
      elif hubs > self._hub_cap[width]:
        logger.warning(
            'snapshot v%d has %d hub rows (degree > %d) but the static '
            'hub cap is %d: rows past the cap sample from a truncated '
            'window. Rebuild the sampler with a larger window_hub_cap.',
            snap.version, hubs, width, self._hub_cap[width])
    return (eng, width, self._hub_cap[width])

  def _build_fn(self, batch_size: int, plan: tuple):
    eff = list(self.num_neighbors)
    base = list(self._base_fanouts)
    eng, width, hub_cap = plan
    interp = False
    if eng == 'pallas':
      from ..ops.pallas_kernels import interpret_default
      interp = interpret_default()

    def fn(arrays, seeds, n_valid, key, table, scratch):
      self.trace_count += 1  # trace-time only; executions never bump
      from ..obs.perf import count_compile
      count_compile('stream.sample')  # compiles_total{fn=...}
      hop = {'i': 0}

      def one_hop(ids, _eff_fanout, sub, mask):
        f = base[hop['i']]
        hop['i'] += 1
        wk = {}
        if eng != 'element' and f > 0:
          wk = dict(base_window=(width, min(hub_cap, ids.shape[0])),
                    indices_win=arrays['indices'], engine=eng,
                    interpret=interp)
        return delta_one_hop(
            arrays['indptr'], arrays['indices'],
            arrays['ins_indptr'], arrays['ins_indices'],
            arrays['del_indptr'], arrays['del_indices'],
            ids, f, sub, mask,
            ins_window=self.delta_window,
            del_window=self.tombstone_window,
            replace=self.replace, **wk)

      return multihop_sample(one_hop, seeds, n_valid, eff, key,
                             table, scratch, with_edge=False)

    return jax.jit(fn, donate_argnums=(4, 5))

  def sample_from_nodes(self, inputs, **kwargs) -> SamplerOutput:
    """Delta-merged multi-hop sampling from seed nodes; same output
    contract as NeighborSampler.sample_from_nodes (homogeneous)."""
    if isinstance(inputs, NodeSamplerInput):
      seeds = as_numpy(inputs.node)
    else:
      seeds = as_numpy(inputs)
    n_valid = kwargs.get('n_valid', seeds.shape[0])
    batch_size = seeds.shape[0]
    table, scratch = self._get_tables(self.manager.num_nodes)
    snap = self.manager.acquire()
    try:
      plan = self._window_plan(snap)
      cache_key = ('homo', batch_size, plan)
      if cache_key not in self._fn_cache:
        self._fn_cache[cache_key] = self._build_fn(batch_size, plan)
      if (self._full_cap is not None
          and snap.max_degree > self._full_cap
          and snap.version != self._trunc_warned_version):
        self._trunc_warned_version = snap.version
        logger.warning(
            'snapshot v%d max degree %d exceeds the static full-'
            'neighborhood window %d: hub rows truncate. Rebuild the '
            'sampler with a larger full_neighbor_cap.',
            snap.version, snap.max_degree, self._full_cap)
      arrays = dict(snap.arrays)
      arrays.update(self._overlay)
      out, table, scratch = self._fn_cache[cache_key](
          arrays, jnp.asarray(seeds.astype(np.int32)),
          jnp.asarray(n_valid),
          kwargs.get('key', self._next_key()), table, scratch)
    finally:
      self.manager.release(snap)
    self._tables[''] = (table, scratch)
    return SamplerOutput(
        node=out['node'], node_count=out['node_count'],
        row=out['row'], col=out['col'], edge_mask=out['edge_mask'],
        edge=None, batch=out['batch'],
        num_sampled_nodes=out['num_sampled_nodes'],
        num_sampled_edges=out['num_sampled_edges'],
        edge_hop_offsets=edge_hop_offsets(batch_size,
                                          self.num_neighbors),
        metadata={'seed_labels': out['seed_labels'],
                  'seed_count': out['seed_count'],
                  'snapshot_version': snap.version},
    )

  def sample_from_edges(self, inputs, **kwargs):
    raise NotImplementedError(
        'StreamSampler serves node-anchored inference; link sampling '
        'stays on NeighborSampler (train-time, frozen snapshots)')

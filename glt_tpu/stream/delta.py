"""Host-side staging buffers for live graph and feature updates.

Writers (RPC handlers, the ingestor API, Kafka-style consumers) append
into these thread-safe, capacity-bounded buffers; the sampling path
never reads them directly — the :class:`~glt_tpu.stream.snapshot.
SnapshotManager` turns the pending set into small static-shape device
overlays (bounded staleness), and periodic compaction folds it into a
fresh immutable CSR.

Effective adjacency is ``(base \\ tombstones) ∪ inserts`` — deletes
apply to the base *before* inserts are appended, in the overlay merge
(ops/delta.py) and at compaction alike. That rule plus one staging-time
cancellation resolves op ordering:

  * ``delete_edges`` cancels matching *pending inserts* in place (an
    edge inserted and deleted inside one delta epoch never existed) and
    records a tombstone for the base graph — required, because
    tombstones only ever filter the base;
  * ``insert_edges`` just appends. A pending tombstone plus a later
    insert of the same pair coexist deliberately: the tombstone clears
    every base instance, the insert contributes exactly one fresh one —
    correct whether or not the base ever held the edge.

Deletes are multigraph-wide: a tombstone (u, v) removes **every**
base instance of u->v.
"""
from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional

import numpy as np

from ..utils import as_numpy


class DeltaOverflow(RuntimeError):
  """The delta buffer is full: compact (or raise capacity) before
  staging more updates. Raised instead of silently dropping — a lost
  update would silently serve stale neighborhoods forever."""


class EdgeDeltaCut(NamedTuple):
  """An atomically drained batch of pending edge ops (compaction input)."""
  ins_src: np.ndarray
  ins_dst: np.ndarray
  del_src: np.ndarray
  del_dst: np.ndarray

  @property
  def num_ops(self) -> int:
    return int(self.ins_src.shape[0] + self.del_src.shape[0])


class FeatureDeltaCut(NamedTuple):
  """Drained feature-row updates: ``ids`` unique, last-write-wins."""
  ids: np.ndarray
  values: np.ndarray

  @property
  def num_ops(self) -> int:
    return int(self.ids.shape[0])


def _pair_key(src: np.ndarray, dst: np.ndarray,
              num_cols: int) -> np.ndarray:
  """Dense (src, dst) -> int64 key for set matching. Safe while
  num_rows * num_cols < 2**63 — beyond that shard the stream per
  partition (the distributed apply-delta path)."""
  return src.astype(np.int64) * np.int64(max(num_cols, 1)) \
      + dst.astype(np.int64)


class EdgeDeltaBuffer:
  """Thread-safe, capacity-bounded staging of edge inserts + deletes.

  Args:
    capacity: max pending ops (inserts + tombstones together). This is
      also the static width of the device overlays built from the
      buffer, so it is a **compile-shape** constant — pick it once.
    num_nodes: id-space bound for square graphs; out-of-range endpoints
      are rejected at staging time (past this boundary they would be
      silently dropped by the CSR scatters, a wrong-but-quiet outcome).
    num_src/num_dst: independent per-axis bounds for bipartite
      topologies (src checked against num_src, dst against num_dst);
      default to ``num_nodes``.
  """

  def __init__(self, capacity: int = 4096,
               num_nodes: Optional[int] = None,
               num_src: Optional[int] = None,
               num_dst: Optional[int] = None):
    assert capacity > 0
    self.capacity = int(capacity)
    self.num_nodes = None if num_nodes is None else int(num_nodes)
    self.num_src = int(num_src) if num_src is not None \
        else self.num_nodes
    self.num_dst = int(num_dst) if num_dst is not None \
        else self.num_nodes
    #: bumped on every successful stage/drain/restage — overlay builds
    #: key on it to skip rebuilding an unchanged pending set
    self.mutation_seq = 0
    self._lock = threading.Lock()
    self._ins_src: list = []
    self._ins_dst: list = []
    self._del_src: list = []
    self._del_dst: list = []
    self._oldest_ts: Optional[float] = None
    self.total_inserts = 0
    self.total_deletes = 0
    self.high_watermark = 0.0

  # -- staging -----------------------------------------------------------

  def _check_ids(self, src: np.ndarray, dst: np.ndarray) -> None:
    if src.size == 0:
      return
    for name, ids, bound in (('src', src, self.num_src),
                             ('dst', dst, self.num_dst)):
      if bound is None:
        continue
      lo, hi = int(ids.min()), int(ids.max())
      if lo < 0 or hi >= bound:
        raise ValueError(
            f'{name} endpoint out of range [0, {bound}): '
            f'saw [{lo}, {hi}]')

  def _note_occupancy_locked(self) -> None:
    occ = self._size_locked() / self.capacity
    if occ > self.high_watermark:
      self.high_watermark = occ
    if self._oldest_ts is None and self._size_locked():
      self._oldest_ts = time.monotonic()

  def _size_locked(self) -> int:
    return (len(self._ins_src) + len(self._del_src))

  def insert_edges(self, src, dst) -> int:
    """Stage new edge instances; returns the number staged. A pending
    tombstone for the same pair is deliberately left in place (see the
    module docstring): it clears the base instances, this insert
    contributes the fresh one — cancelling it instead would silently
    lose the insert whenever the base never held the edge."""
    src = as_numpy(src).astype(np.int64).reshape(-1)
    dst = as_numpy(dst).astype(np.int64).reshape(-1)
    assert src.shape == dst.shape
    self._check_ids(src, dst)
    with self._lock:
      if self._size_locked() + src.size > self.capacity:
        raise DeltaOverflow(
            f'edge delta full ({self._size_locked()}/{self.capacity} '
            f'pending, {src.size} incoming): compact first')
      self._ins_src.extend(src.tolist())
      self._ins_dst.extend(dst.tolist())
      self.total_inserts += int(src.size)
      self.mutation_seq += 1
      self._note_occupancy_locked()
      return int(src.size)

  def delete_edges(self, src, dst) -> int:
    """Stage tombstones; pending inserts matching (src, dst) are
    cancelled in place. Returns the number of tombstones recorded."""
    src = as_numpy(src).astype(np.int64).reshape(-1)
    dst = as_numpy(dst).astype(np.int64).reshape(-1)
    assert src.shape == dst.shape
    self._check_ids(src, dst)
    with self._lock:
      keep = None
      if self._ins_src:
        nc = 1 + int(max(src.max(initial=0), dst.max(initial=0),
                         max(self._ins_src), max(self._ins_dst)))
        ikeys = _pair_key(np.asarray(self._ins_src),
                          np.asarray(self._ins_dst), nc)
        dkeys = _pair_key(src, dst, nc)
        keep = ~np.isin(ikeys, dkeys)
      # admission check BEFORE any mutation (the cancellation itself
      # frees slots, so count it): a rejected call must leave the
      # pending set — and the overlay memoized on mutation_seq —
      # exactly as it found them
      cancelled = 0 if keep is None else int((~keep).sum())
      if self._size_locked() - cancelled + src.size > self.capacity:
        raise DeltaOverflow(
            f'edge delta full ({self._size_locked()}/{self.capacity} '
            f'pending, {src.size} incoming): compact first')
      if keep is not None and cancelled:
        self._ins_src = list(np.asarray(self._ins_src)[keep])
        self._ins_dst = list(np.asarray(self._ins_dst)[keep])
      self._del_src.extend(src.tolist())
      self._del_dst.extend(dst.tolist())
      self.total_deletes += int(src.size)
      self.mutation_seq += 1
      self._note_occupancy_locked()
      return int(src.size)

  # -- reading -----------------------------------------------------------

  @property
  def size(self) -> int:
    with self._lock:
      return self._size_locked()

  @property
  def occupancy(self) -> float:
    return self.size / self.capacity

  @property
  def staleness_s(self) -> float:
    """Age of the oldest pending op (0 when empty)."""
    with self._lock:
      return (time.monotonic() - self._oldest_ts
              if self._oldest_ts is not None else 0.0)

  def view(self) -> EdgeDeltaCut:
    """Copy of the pending set WITHOUT draining (overlay refresh)."""
    with self._lock:
      return EdgeDeltaCut(
          np.asarray(self._ins_src, np.int64),
          np.asarray(self._ins_dst, np.int64),
          np.asarray(self._del_src, np.int64),
          np.asarray(self._del_dst, np.int64))

  def drain(self) -> EdgeDeltaCut:
    """Atomically take the pending set and clear the buffer (the
    compaction cut). Writers keep appending for the NEXT epoch; the
    live overlay still carries the cut until it is rebuilt post-swap,
    so readers never lose visibility mid-compaction."""
    with self._lock:
      cut = EdgeDeltaCut(
          np.asarray(self._ins_src, np.int64),
          np.asarray(self._ins_dst, np.int64),
          np.asarray(self._del_src, np.int64),
          np.asarray(self._del_dst, np.int64))
      self._ins_src, self._ins_dst = [], []
      self._del_src, self._del_dst = [], []
      self._oldest_ts = None
      self.mutation_seq += 1
      return cut

  def restage(self, cut: EdgeDeltaCut) -> None:
    """Put a drained cut back (failed compaction). Prepends, so op
    ordering against post-cut appends is preserved — including the one
    ordering delete_edges normally resolves at staging time: a
    tombstone staged *while the cut was out* is ordered after the
    cut's inserts, so it cancels the matching restaged inserts here
    (otherwise the restage would resurrect a deleted edge)."""
    with self._lock:
      ins_src, ins_dst = cut.ins_src, cut.ins_dst
      if self._del_src and ins_src.size:
        nc = 1 + int(max(ins_src.max(initial=0),
                         ins_dst.max(initial=0),
                         max(self._del_src), max(self._del_dst)))
        ikeys = _pair_key(ins_src, ins_dst, nc)
        dkeys = _pair_key(np.asarray(self._del_src),
                          np.asarray(self._del_dst), nc)
        keep = ~np.isin(ikeys, dkeys)
        ins_src, ins_dst = ins_src[keep], ins_dst[keep]
      self._ins_src = ins_src.tolist() + self._ins_src
      self._ins_dst = ins_dst.tolist() + self._ins_dst
      self._del_src = cut.del_src.tolist() + self._del_src
      self._del_dst = cut.del_dst.tolist() + self._del_dst
      if cut.num_ops:
        self._oldest_ts = time.monotonic()
      self.mutation_seq += 1
      self._note_occupancy_locked()

  def stats(self) -> dict:
    with self._lock:
      return {
          'pending': self._size_locked(),
          'capacity': self.capacity,
          'occupancy': self._size_locked() / self.capacity,
          'high_watermark': self.high_watermark,
          'total_inserts': self.total_inserts,
          'total_deletes': self.total_deletes,
      }


class FeatureDeltaBuffer:
  """Thread-safe staging of feature-row updates (last-write-wins per
  node id). Row values are copied at staging time — callers may reuse
  their buffers immediately.

  ``feature_dim`` (when known) makes wrong-width rows fail HERE, at the
  writer's call site; deferred to compaction a bad row would fail the
  merge, get restaged, and fail every subsequent flush — a permanently
  wedged stream."""

  def __init__(self, capacity: int = 4096,
               num_nodes: Optional[int] = None,
               feature_dim: Optional[int] = None):
    assert capacity > 0
    self.capacity = int(capacity)
    self.num_nodes = None if num_nodes is None else int(num_nodes)
    self.feature_dim = None if feature_dim is None else int(feature_dim)
    self._lock = threading.Lock()
    self._rows: dict = {}        # id -> np row
    self._oldest_ts: Optional[float] = None
    self.total_updates = 0
    self.high_watermark = 0.0

  def update_rows(self, ids, values) -> int:
    ids = as_numpy(ids).astype(np.int64).reshape(-1)
    values = as_numpy(values)
    if values.ndim == 1:
      values = values[None, :] if ids.size == 1 \
          else values[:, None]
    if values.shape[0] != ids.shape[0]:
      raise ValueError(
          f'{ids.shape[0]} ids vs {values.shape[0]} rows')
    if self.feature_dim is not None \
        and values.shape[1] != self.feature_dim:
      raise ValueError(
          f'row width {values.shape[1]} != feature dim '
          f'{self.feature_dim}')
    if self.num_nodes is not None and ids.size:
      if int(ids.min()) < 0 or int(ids.max()) >= self.num_nodes:
        raise ValueError(
            f'feature id out of range [0, {self.num_nodes})')
    with self._lock:
      new = sum(1 for i in ids.tolist() if i not in self._rows)
      if len(self._rows) + new > self.capacity:
        raise DeltaOverflow(
            f'feature delta full ({len(self._rows)}/{self.capacity} '
            f'pending, {new} new ids): compact first')
      for i, row in zip(ids.tolist(), values):
        self._rows[i] = np.array(row, copy=True)
      self.total_updates += int(ids.size)
      occ = len(self._rows) / self.capacity
      if occ > self.high_watermark:
        self.high_watermark = occ
      if self._oldest_ts is None and self._rows:
        self._oldest_ts = time.monotonic()
      return int(ids.size)

  @property
  def size(self) -> int:
    with self._lock:
      return len(self._rows)

  @property
  def occupancy(self) -> float:
    return self.size / self.capacity

  @property
  def staleness_s(self) -> float:
    with self._lock:
      return (time.monotonic() - self._oldest_ts
              if self._oldest_ts is not None else 0.0)

  def drain(self) -> FeatureDeltaCut:
    with self._lock:
      if not self._rows:
        cut = FeatureDeltaCut(np.zeros((0,), np.int64),
                              np.zeros((0, 0), np.float32))
      else:
        ids = np.fromiter(self._rows, np.int64, len(self._rows))
        cut = FeatureDeltaCut(ids,
                              np.stack([self._rows[i]
                                        for i in ids.tolist()]))
      self._rows = {}
      self._oldest_ts = None
      return cut

  def restage(self, cut: FeatureDeltaCut) -> None:
    """Failed-compaction path: re-stage WITHOUT clobbering newer writes
    (last-write-wins means a post-cut update supersedes the cut's)."""
    with self._lock:
      for i, row in zip(cut.ids.tolist(), cut.values):
        self._rows.setdefault(i, row)
      if self._rows and self._oldest_ts is None:
        self._oldest_ts = time.monotonic()

  def stats(self) -> dict:
    with self._lock:
      return {
          'pending': len(self._rows),
          'capacity': self.capacity,
          'occupancy': len(self._rows) / self.capacity,
          'high_watermark': self.high_watermark,
          'total_updates': self.total_updates,
      }

"""Online graph & feature mutation engine: delta buffers, versioned
snapshots, cache-coherent serving.

The write path is::

  writers --> EdgeDeltaBuffer / FeatureDeltaBuffer   (stage, µs)
                  |-- SnapshotManager.build_overlay  (refresh: static-
                  |                                   shape device CSR
                  |                                   overlays)
                  `-- StreamIngestor ----------------(compact: merge to
                         |                            a fresh sorted CSR,
                         |                            RCU swap)
                         |-- StreamSampler.set_overlay / snapshot swap
                         `-- InferenceEngine.update_snapshot
                                `-- EmbeddingCache.invalidate(touched)

and the read path stays on the immutable, locality-sorted CSR the
samplers were built for — delta visibility costs one fixed-width window
per hop, never a recompile. See docs/streaming.md for the consistency
model (snapshot isolation, staleness bounds, window sizing).
"""
from .delta import (  # noqa: F401
    DeltaOverflow, EdgeDeltaBuffer, EdgeDeltaCut, FeatureDeltaBuffer,
    FeatureDeltaCut,
)
from .ingest import CompactionPolicy, StreamIngestor  # noqa: F401
from .sampler import StreamSampler  # noqa: F401
from .snapshot import Snapshot, SnapshotManager  # noqa: F401

__all__ = [
    'DeltaOverflow', 'EdgeDeltaBuffer', 'EdgeDeltaCut',
    'FeatureDeltaBuffer', 'FeatureDeltaCut',
    'CompactionPolicy', 'StreamIngestor',
    'StreamSampler', 'Snapshot', 'SnapshotManager',
]

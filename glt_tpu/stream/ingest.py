"""StreamIngestor — the background applier that turns buffered updates
into visible graph state.

The write path is three stages with different latencies:

  1. **stage** (microseconds): `insert_edges` / `delete_edges` /
     `update_features` append into the host delta buffers;
  2. **refresh** (sub-millisecond, default synchronous): the pending
     edge set is rebuilt into the static-shape device overlays, making
     topology changes visible to the very next sample with zero
     recompiles;
  3. **compact** (the heavy step): the drained delta merges into a
     fresh CSR snapshot, features apply, the serving cache invalidates
     touched nodes, and the overlay resets to the residual pending set.

Compaction fires from the auto-policy (delta occupancy or staleness
thresholds, checked by the background thread and opportunistically on
every staging call) or explicitly via :meth:`flush`. Observability
rides the shared :class:`~glt_tpu.serving.metrics.ServingMetrics`
gauges — no parallel metrics class.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

import numpy as np

from ..obs import get_tracer
from ..serving.metrics import ServingMetrics
from ..utils.profile import Timer
from .delta import EdgeDeltaBuffer, FeatureDeltaBuffer
from .sampler import StreamSampler
from .snapshot import SnapshotManager

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CompactionPolicy:
  """When the ingestor folds the delta into a fresh snapshot.

  occupancy_threshold: compact once pending ops reach this fraction of
    the delta capacity (bounds truncation risk of the per-node windows
    and keeps headroom for write bursts).
  max_staleness_s: compact once the oldest pending op is this old —
    feature updates are only visible post-compaction, so this IS the
    feature-freshness bound (see docs/streaming.md).
  min_interval_s: floor between auto-compactions (swap hygiene under
    sustained write load; explicit flush() ignores it).
  """
  occupancy_threshold: float = 0.5
  max_staleness_s: float = 30.0
  min_interval_s: float = 0.0


class StreamIngestor:
  """Owns the delta buffers and drives refresh + compaction.

  Args:
    manager: the snapshot chain.
    sampler: optional StreamSampler to keep overlay-fresh.
    engine: optional serving InferenceEngine; on compaction its
      ``update_snapshot`` swaps features and invalidates touched cache
      entries (with optional reverse-adjacency expansion).
    metrics: optional shared ServingMetrics; the ingestor publishes
      gauges (snapshot_version, delta_occupancy, compactions,
      last_compaction_ms, ...) into it.
    auto_refresh: rebuild the device overlay synchronously on every
      staging call (default) — freshest reads, but each rebuild is
      O(num_rows) host work plus an indptr upload, so on very large
      node spaces prefer False and let the background thread refresh
      on its poll cadence (higher ingest throughput, staleness bounded
      by ``poll_interval_s``). Unchanged pending sets never rebuild
      either way (memoized on the buffer's mutation_seq).
    expand_invalidation: pass touched ids through the snapshot's
      reverse-layout 1-hop expansion before cache invalidation.
    restart_policy: what a background-tick exception does —
      ``'restart'`` (default): log + keep the applier running, but
      after ``max_tick_failures`` CONSECUTIVE failing ticks declare the
      thread dead and surface the error; ``'raise'``: first tick
      failure is fatal; ``'log'``: the pre-resilience behavior (log
      forever, never surface — discouraged). A fatal background error
      is re-raised from the next ``insert_edges`` / ``delete_edges`` /
      ``update_features`` / ``flush`` / ``stop`` so writers can never
      keep staging into a stream whose applier is a corpse.
  """

  def __init__(self, manager: SnapshotManager,
               sampler: Optional[StreamSampler] = None,
               engine=None,
               policy: Optional[CompactionPolicy] = None,
               metrics: Optional[ServingMetrics] = None,
               feature_capacity: Optional[int] = None,
               auto_refresh: bool = True,
               expand_invalidation: bool = False,
               restart_policy: str = 'restart',
               max_tick_failures: int = 3):
    assert restart_policy in ('restart', 'raise', 'log'), restart_policy
    self.restart_policy = restart_policy
    self.max_tick_failures = int(max_tick_failures)
    self.manager = manager
    self.sampler = sampler
    self.engine = engine
    self.policy = policy or CompactionPolicy()
    self.metrics = metrics
    self.auto_refresh = auto_refresh
    self.expand_invalidation = expand_invalidation
    self.edges = EdgeDeltaBuffer(capacity=manager.delta_capacity,
                                 num_src=manager.num_src_nodes,
                                 num_dst=manager.num_dst_nodes)
    feat = manager.current().feature
    # feature staging is constructed against the actual store geometry
    # so bad updates (wrong row width, topology-only stream) fail at
    # the caller's staging call — deferred to compaction they would
    # wedge the stream (failed flush restages the same bad cut forever)
    # bound by the feature's ID SPACE, not its row count: a
    # partitioned store takes global ids through its id2index map
    # (ownership of each id is checked in update_features)
    self.features = FeatureDeltaBuffer(
        capacity=feature_capacity or manager.delta_capacity,
        num_nodes=feat.id_space,
        feature_dim=feat.feature_dim) if feat is not None else None
    self._compact_lock = threading.Lock()
    self._last_compaction_ts: Optional[float] = None
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    # background-failure surfacing: the last fatal tick error (None =
    # healthy); once set, every staging call re-raises it
    self._bg_error: Optional[BaseException] = None
    self._tick_failures = 0      # consecutive failing ticks
    self.tick_errors_total = 0   # lifetime count (observability)
    self._publish_gauges()

  # -- write API ---------------------------------------------------------

  def _check_bg_error(self) -> None:
    """Surface a fatal background-applier error on the caller's thread:
    silently staging into a stream whose compaction loop died would
    buffer updates that can never become visible."""
    if self._bg_error is not None:
      raise RuntimeError(
          'stream ingest background applier died '
          f'(restart_policy={self.restart_policy!r}, after '
          f'{self.tick_errors_total} tick error(s)); no further '
          'updates will compact — fix the cause and build a new '
          'ingestor') from self._bg_error

  def insert_edges(self, src, dst) -> int:
    self._check_bg_error()
    n = self.edges.insert_edges(src, dst)
    self._after_stage(refresh=True)
    return n

  def delete_edges(self, src, dst) -> int:
    self._check_bg_error()
    n = self.edges.delete_edges(src, dst)
    self._after_stage(refresh=True)
    return n

  def update_features(self, ids, values) -> int:
    self._check_bg_error()
    if self.features is None:
      raise ValueError(
          'this stream carries no Feature (SnapshotManager was built '
          'without one); feature updates have nowhere to land')
    # ownership check at STAGING time: on a partitioned store an
    # unowned global id maps to an out-of-range local row — deferred
    # to compaction it would fail the merge, restage, and wedge the
    # stream (the same failure class the width check guards)
    feat = self.manager.current().feature
    ids_np = np.asarray(ids, np.int64).reshape(-1)
    if ids_np.size and (int(ids_np.min()) < 0
                        or int(ids_np.max()) >= feat.id_space):
      raise ValueError(
          f'feature id out of range [0, {feat.id_space})')
    rows = np.asarray(feat.map_ids(ids_np))
    bad = ids_np[(rows < 0) | (rows >= feat.num_rows)]
    if bad.size:
      raise ValueError(
          f'feature ids not owned by this store (local rows '
          f'[0, {feat.num_rows})): {bad[:8].tolist()}')
    n = self.features.update_rows(ids, values)
    # feature rows only land at compaction (snapshot isolation): no
    # overlay refresh, but the staleness policy may fire right away
    self._after_stage(refresh=False)
    return n

  def _after_stage(self, refresh: bool) -> None:
    if refresh and self.auto_refresh and self.sampler is not None:
      self.sampler.refresh_overlay(self.edges)
    self._publish_gauges()
    self.maybe_compact()

  # -- compaction --------------------------------------------------------

  def _due(self) -> bool:
    p = self.policy
    if self._last_compaction_ts is not None and p.min_interval_s > 0:
      if time.monotonic() - self._last_compaction_ts < p.min_interval_s:
        return False
    feat_occ = self.features.occupancy if self.features else 0.0
    if (self.edges.occupancy >= p.occupancy_threshold
        or feat_occ >= p.occupancy_threshold):
      return True
    staleness = max(self.edges.staleness_s,
                    self.features.staleness_s if self.features else 0.0)
    return p.max_staleness_s > 0 and staleness >= p.max_staleness_s

  def maybe_compact(self):
    """Compact iff the policy says so; returns the info dict or None."""
    if not self._due():
      return None
    return self.flush()

  def flush(self):
    """Force a compaction of everything pending; returns the info dict
    or None when there was nothing to fold."""
    self._check_bg_error()
    with self._compact_lock:
      if self.edges.size == 0 \
          and (self.features is None or self.features.size == 0):
        return None
      t = Timer().start()
      edge_cut = feat_cut = None
      try:
        with get_tracer().span('stream.compact',
                               pending=self.edges.size):
          edge_cut = self.edges.drain()
          feat_cut = self.features.drain() if self.features else None
          snap, info = self.manager.compact(edge_cut, feat_cut)
      except Exception:
        # failed anywhere past the first drain: put whatever was
        # drained back so no update is lost
        if edge_cut is not None:
          self.edges.restage(edge_cut)
        if feat_cut is not None:
          self.features.restage(feat_cut)
        raise
      # order matters: (1) new base live for samplers, (2) overlay
      # drops the folded ops, (3) cache entries computed against the
      # old snapshot are invalidated LAST — any request racing between
      # (1) and (3) may cache a stale row, and (3) sweeps it
      if self.sampler is not None:
        self.sampler.refresh_overlay(self.edges)
      if self.engine is not None:
        # stamp the manager's version as the engine's snapshot_version:
        # the fleet consistency token compares engine versions across
        # shards, so they must share the snapshot chain's numbering
        info['invalidated'] = self.engine.update_snapshot(
            snap, touched_ids=info['touched'],
            expand_in_neighbors=self.expand_invalidation,
            version=info.get('version'))
      self._last_compaction_ts = time.monotonic()
      info['wall_s'] = t.stop()
      if info['capacity_grown']:
        logger.info(
            'stream: edge capacity grew to %d (snapshot v%d) — '
            'samplers retrace once', info['edge_capacity'],
            info['version'])
      self._publish_gauges()
      return info

  # -- metrics -----------------------------------------------------------

  def _publish_gauges(self) -> None:
    if self.metrics is None:
      return
    m = self.manager
    self.metrics.set_gauge('snapshot_version', m.current().version)
    self.metrics.set_gauge('delta_occupancy', self.edges.occupancy)
    self.metrics.set_gauge(
        'feature_delta_occupancy',
        self.features.occupancy if self.features else 0.0)
    self.metrics.set_gauge('compactions', m.compactions)
    self.metrics.set_gauge('last_compaction_ms',
                           m.last_compaction_s * 1e3)
    self.metrics.set_gauge('edge_capacity', m.edge_capacity)
    self.metrics.set_gauge('capacity_growths', m.capacity_growths)
    self.metrics.set_gauge(
        'ingest_ops_total',
        self.edges.total_inserts + self.edges.total_deletes
        + (self.features.total_updates if self.features else 0))

  def stats(self) -> dict:
    return {
        'snapshot_version': self.manager.current().version,
        'compactions': self.manager.compactions,
        'last_compaction_ms': self.manager.last_compaction_s * 1e3,
        'edge_capacity': self.manager.edge_capacity,
        'capacity_growths': self.manager.capacity_growths,
        'edge_delta': self.edges.stats(),
        'feature_delta': (self.features.stats()
                          if self.features else None),
    }

  # -- background applier ------------------------------------------------

  def start(self, poll_interval_s: float = 0.5) -> 'StreamIngestor':
    """Run the policy check (and, with auto_refresh=False, the overlay
    refresh) on a daemon thread."""
    assert self._thread is None, 'ingestor already started'
    self._stop.clear()

    def loop():
      while not self._stop.wait(poll_interval_s):
        try:
          if not self.auto_refresh and self.sampler is not None:
            self.sampler.refresh_overlay(self.edges)
          self._publish_gauges()
          self.maybe_compact()
        except Exception as e:
          self.tick_errors_total += 1
          self._tick_failures += 1
          logger.exception(
              'stream ingest tick failed (%d consecutive, policy=%s)',
              self._tick_failures, self.restart_policy)
          if self.metrics is not None:
            self.metrics.set_gauge('ingest_tick_errors',
                                   float(self.tick_errors_total))
          if self.restart_policy == 'log':
            continue  # legacy: swallow forever
          if (self.restart_policy == 'raise'
              or self._tick_failures >= self.max_tick_failures):
            # fatal: record for the next stage()/stop() to re-raise,
            # then exit — a crash-looping applier must not keep
            # draining/restaging the same poisoned cut forever
            self._bg_error = e
            try:  # postmortem: the applier dying IS the incident
              from ..obs.recorder import get_recorder
              get_recorder().trip(
                  'ingestor_crash', error=repr(e),
                  tick_failures=self._tick_failures,
                  tick_errors_total=self.tick_errors_total,
                  restart_policy=self.restart_policy)
            except Exception:  # gltlint: disable=GLT006
              pass  # the recorder itself failed; nothing left to record to
            return
        else:
          self._tick_failures = 0

    self._thread = threading.Thread(target=loop, daemon=True,
                                    name='glt-stream-ingest')
    self._thread.start()
    return self

  def stop(self, raise_background_error: bool = True) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=10)
      self._thread = None
    if raise_background_error:
      self._check_bg_error()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    # when the body is already raising, a background-crash re-raise
    # here would REPLACE that exception — report it only on the clean
    # path
    self.stop(raise_background_error=exc_type is None)

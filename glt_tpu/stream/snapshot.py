"""Versioned immutable snapshots with RCU-style swap and delta
compaction.

A :class:`Snapshot` is one immutable ``(Topology, Feature)`` version
plus its device-resident, **capacity-padded** CSR arrays. Padding is the
whole trick: every snapshot's arrays share one static shape
(``[num_rows + 1]`` indptr, ``[edge_capacity]`` indices), so the stream
sampler's jitted multi-hop program — which takes them as *arguments*,
never closure constants — keeps serving across compactions with zero
steady-state recompiles. Only outgrowing ``edge_capacity`` changes
shapes (one recompile, reported in the compaction info).

Swap protocol (read-copy-update): readers ``acquire()`` the current
snapshot, sample against its arrays, then ``release()``. ``compact()``
publishes the merged snapshot and *retires* the old one; its device
buffers are freed when the last in-flight reader releases — in-flight
sampling always finishes on the snapshot it started with.

Compaction itself is host-side and reuses the one battle-tested CSR
builder in the codebase: the merged COO goes through ``Topology``'s
constructor (``data/topology._compress`` + ``_sort_within_rows``), so
the compacted graph is locality-sorted exactly like a cold-start build.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..data.feature import Feature
from ..data.topology import Topology
from ..utils import as_numpy
from .delta import EdgeDeltaBuffer, EdgeDeltaCut, FeatureDeltaCut, \
    _pair_key


def _padded_csr_device(indptr: np.ndarray, indices: np.ndarray,
                       capacity: int, device=None
                       ) -> Tuple[jax.Array, jax.Array]:
  """int32 device (indptr, indices) with indices padded to ``capacity``
  slots (sentinel -1; valid lanes never read the pad)."""
  assert indices.shape[0] <= capacity, \
      f'{indices.shape[0]} edges exceed capacity {capacity}'
  assert indptr[-1] < np.iinfo(np.int32).max
  pad = np.full(capacity - indices.shape[0], -1, np.int32)
  return (jax.device_put(indptr.astype(np.int32), device),
          jax.device_put(
              np.concatenate([indices.astype(np.int32), pad]), device))


def _delta_csr(src: np.ndarray, dst: np.ndarray, num_rows: int,
               capacity: int, layout: str, device=None) -> dict:
  """Build one capacity-padded overlay CSR from (src, dst) pairs,
  oriented to the base layout's row axis."""
  row, col = (src, dst) if layout == 'CSR' else (dst, src)
  order = np.lexsort((col, row))
  row, col = row[order], col[order]
  indptr = np.zeros(num_rows + 1, np.int64)
  np.cumsum(np.bincount(row, minlength=num_rows), out=indptr[1:])
  return _padded_csr_device(indptr, col, capacity, device)


class Snapshot:
  """One immutable graph/feature version (see module docstring).

  Attributes:
    version: monotonically increasing snapshot id.
    topo: host Topology (immutable by convention).
    feature: node Feature for this version (None when the stream is
      topology-only); may be shared with the previous snapshot when a
      compaction carried no feature updates.
  """

  def __init__(self, version: int, topo: Topology,
               feature: Optional[Feature],
               edge_capacity: int, device=None):
    self.version = int(version)
    self.topo = topo
    self.feature = feature
    self.edge_capacity = int(edge_capacity)
    indptr, indices = _padded_csr_device(
        topo.indptr, topo.indices, edge_capacity, device)
    #: static-shape jit arguments: base CSR of this version
    self.arrays: Dict[str, jax.Array] = {
        'indptr': indptr, 'indices': indices}
    self._refs = 0
    self._retired = False
    self._freed = False
    self._flipped: Optional[Topology] = None

    #: computed once at build (O(N) host scan): samplers consult it per
    #: call to detect full-window truncation
    self.max_degree = topo.max_degree

  @property
  def num_rows(self) -> int:
    return self.topo.num_rows

  @property
  def num_edges(self) -> int:
    return self.topo.num_edges

  @property
  def freed(self) -> bool:
    return self._freed

  def _free(self) -> None:
    """Release device buffers (manager-internal; called once the
    snapshot is retired and the last reader released). The Feature is
    NOT freed — it may be shared with the successor snapshot."""
    if self._freed:
      return
    self._freed = True
    for arr in self.arrays.values():
      try:
        arr.delete()
      except Exception:
        pass  # backend without explicit delete: GC reclaims
    self.arrays = {}

  def flipped_topo(self) -> Topology:
    """The opposite-layout view (CSC for a CSR base), host-side, built
    once per snapshot — reverse-adjacency probes for cache
    invalidation fan-out."""
    if self._flipped is None:
      self._flipped = self.topo.flip_layout()
    return self._flipped

  def expand_affected(self, ids: np.ndarray) -> np.ndarray:
    """ids ∪ their reverse-layout 1-hop neighborhood: with a CSR base
    ('out' sampling) these are the in-neighbors — every node whose
    sampled neighborhood can contain an id, i.e. whose cached embedding
    aggregates over it."""
    ids = as_numpy(ids).astype(np.int64).reshape(-1)
    flip = self.flipped_topo()
    valid = ids[(ids >= 0) & (ids < flip.num_rows)]
    starts = flip.indptr[valid]
    ends = flip.indptr[valid + 1]
    chunks = [ids] + [flip.indices[s:e] for s, e in zip(starts, ends)]
    return np.unique(np.concatenate(chunks).astype(np.int64))


class SnapshotManager:
  """Owns the snapshot chain, the delta overlays, and compaction.

  Args:
    topo: the startup Topology (version 0 base).
    feature: the startup node Feature (optional).
    delta_capacity: static overlay width = max pending delta ops; the
      EdgeDeltaBuffer feeding this manager must use the same bound.
    edge_capacity: static padded edge-array size; defaults to
      ``num_edges + 4 * delta_capacity`` (headroom for several
      compactions of pure inserts before a capacity growth —and
      recompile— is needed).
    num_nodes: fixed row-space size; streams cannot add node ids past
      it (pre-size the id space, the standard practice for online
      recommendation graphs).
  """

  def __init__(self, topo: Topology, feature: Optional[Feature] = None,
               *, delta_capacity: int = 4096,
               edge_capacity: Optional[int] = None,
               device=None):
    self.delta_capacity = int(delta_capacity)
    self.device = device
    self.edge_capacity = int(
        edge_capacity if edge_capacity is not None
        else topo.num_edges + 4 * self.delta_capacity)
    self._lock = threading.Lock()
    self._compact_serial = threading.Lock()
    self._current = Snapshot(0, topo, feature, self.edge_capacity,
                             device)
    self._retired: List[Snapshot] = []
    eids = topo.edge_ids
    self._next_edge_id = int(eids.max()) + 1 if eids.size else 0
    self._empty_overlay: Optional[dict] = None
    self._overlay_cache = None  # ((buffer id, seq, version), overlay)
    self.compactions = 0
    self.capacity_growths = 0
    self.last_compaction_s = 0.0

  # -- geometry ----------------------------------------------------------

  @property
  def num_nodes(self) -> int:
    t = self.current().topo
    return max(t.num_rows, t.num_cols)

  @property
  def num_src_nodes(self) -> int:
    """src-axis id bound in (src, dst) orientation (row axis for a CSR
    base, col axis for CSC) — what edge-delta src endpoints must obey."""
    t = self.current().topo
    return t.num_rows if t.layout == 'CSR' else t.num_cols

  @property
  def num_dst_nodes(self) -> int:
    t = self.current().topo
    return t.num_cols if t.layout == 'CSR' else t.num_rows

  @property
  def layout(self) -> str:
    return self.current().topo.layout

  # -- RCU read path -----------------------------------------------------

  def current(self) -> Snapshot:
    return self._current

  def acquire(self) -> Snapshot:
    with self._lock:
      snap = self._current
      snap._refs += 1
      return snap

  def release(self, snap: Snapshot) -> None:
    with self._lock:
      snap._refs -= 1
      assert snap._refs >= 0, 'unbalanced snapshot release'
      self._reap_locked()

  def _reap_locked(self) -> None:
    alive = []
    for s in self._retired:
      if s._refs == 0:
        s._free()
      else:
        alive.append(s)
    self._retired = alive

  @property
  def num_retired(self) -> int:
    with self._lock:
      return len(self._retired)

  # -- delta overlays ----------------------------------------------------

  def empty_overlay(self) -> dict:
    """All-empty insert/tombstone overlays (cached; the common
    steady-state argument between delta refreshes)."""
    if self._empty_overlay is None:
      n = self._current.num_rows
      zeros = np.zeros(0, np.int64)
      ip, ix = _delta_csr(zeros, zeros, n, self.delta_capacity,
                          self.layout, self.device)
      dp, dx = _delta_csr(zeros, zeros, n, self.delta_capacity,
                          self.layout, self.device)
      self._empty_overlay = {
          'ins_indptr': ip, 'ins_indices': ix,
          'del_indptr': dp, 'del_indices': dx,
      }
    return self._empty_overlay

  def build_overlay(self, buffer: EdgeDeltaBuffer) -> dict:
    """Device overlays for the buffer's CURRENT pending set (a
    non-draining view). Shapes are always [N+1]/[delta_capacity] —
    refreshing the overlay never changes compiled signatures.

    Builds are memoized on the buffer's ``mutation_seq``, so redundant
    refreshes (feature-only staging, background-thread ticks with no
    new ops) cost a dict lookup. An actual change still rebuilds the
    full [N+1] indptr host-side — on very large node spaces prefer
    StreamIngestor(auto_refresh=False) + the background cadence over
    per-write refreshes.
    """
    assert buffer.capacity <= self.delta_capacity, (
        f'buffer capacity {buffer.capacity} exceeds the overlay '
        f'capacity {self.delta_capacity} the compiled shapes carry')
    # ONE reference load: key version and build geometry must come from
    # the same snapshot even if compact() swaps mid-call (GLT002)
    cur = self._current  # gltlint: disable=GLT002
    key = (id(buffer), buffer.mutation_seq, cur.version)
    if self._overlay_cache is not None \
        and self._overlay_cache[0] == key:
      return self._overlay_cache[1]
    cut = buffer.view()
    if cut.num_ops == 0:
      self._overlay_cache = (key, self.empty_overlay())
      return self._overlay_cache[1]
    n = cur.num_rows
    ip, ix = _delta_csr(cut.ins_src, cut.ins_dst, n,
                        self.delta_capacity, self.layout, self.device)
    dp, dx = _delta_csr(cut.del_src, cut.del_dst, n,
                        self.delta_capacity, self.layout, self.device)
    self._overlay_cache = (key, {'ins_indptr': ip, 'ins_indices': ix,
                                 'del_indptr': dp, 'del_indices': dx})
    return self._overlay_cache[1]

  # -- compaction --------------------------------------------------------

  def compact(self, edge_cut: Optional[EdgeDeltaCut] = None,
              feat_cut: Optional[FeatureDeltaCut] = None
              ) -> Tuple[Snapshot, dict]:
    """Merge a drained delta into a fresh snapshot and swap it in.

    Returns (new_snapshot, info). ``info['touched']`` is the node-id
    set whose cached embeddings the merge staled: row-axis endpoints of
    inserted/deleted edges (their sampled neighborhood changed) plus
    feature-updated ids. ``info['capacity_grown']`` flags an
    edge-capacity growth (the one event that recompiles samplers).

    Concurrent compactions are serialized (readers are never blocked);
    each call folds its own cut on top of whatever version is current
    when it enters.
    """
    with self._compact_serial:
      return self._compact_locked(edge_cut, feat_cut)

  def _compact_locked(self, edge_cut, feat_cut):
    t0 = time.perf_counter()
    old = self._current
    topo = old.topo
    layout = topo.layout

    # base edge list in (src, dst) orientation + aligned ids/weights
    ptr_axis, other, eids = topo.to_coo()
    weights = topo.edge_weights
    if layout == 'CSR':
      src, dst = ptr_axis, other
    else:
      src, dst = other, ptr_axis
    touched: List[np.ndarray] = []

    if edge_cut is not None and edge_cut.del_src.size:
      space = max(topo.num_rows, topo.num_cols,
                  int(edge_cut.del_src.max(initial=0)) + 1,
                  int(edge_cut.del_dst.max(initial=0)) + 1)
      base_keys = _pair_key(src, dst, space)
      del_keys = _pair_key(edge_cut.del_src, edge_cut.del_dst, space)
      keep = ~np.isin(base_keys, del_keys)
      src, dst, eids = src[keep], dst[keep], eids[keep]
      if weights is not None:
        weights = weights[keep]
      touched.append(edge_cut.del_src if layout == 'CSR'
                     else edge_cut.del_dst)
    if edge_cut is not None and edge_cut.ins_src.size:
      n_ins = edge_cut.ins_src.shape[0]
      new_ids = np.arange(self._next_edge_id,
                          self._next_edge_id + n_ins, dtype=np.int64)
      self._next_edge_id += n_ins
      src = np.concatenate([src, edge_cut.ins_src])
      dst = np.concatenate([dst, edge_cut.ins_dst])
      eids = np.concatenate([eids, new_ids])
      if weights is not None:
        # inserted edges default to unit weight (weighted streaming
        # inserts are a follow-up; the surviving base weights persist)
        weights = np.concatenate(
            [weights, np.ones(n_ins, weights.dtype)])
      touched.append(edge_cut.ins_src if layout == 'CSR'
                     else edge_cut.ins_dst)

    new_topo = Topology(
        edge_index=np.stack([src, dst]).astype(np.int64),
        edge_ids=eids, edge_weights=weights, layout=layout,
        num_rows=topo.num_rows, num_cols=topo.num_cols,
        index_dtype=topo._index_dtype)

    feature = old.feature
    if feat_cut is not None and feat_cut.ids.size:
      assert feature is not None, \
          'feature updates staged but the stream carries no Feature'
      feature = feature.with_updated_rows(feat_cut.ids,
                                          feat_cut.values)
      touched.append(feat_cut.ids)

    capacity = self.edge_capacity
    grown = False
    if new_topo.num_edges > capacity:
      # round up in delta-sized steps: repeated pure-insert epochs pay
      # one growth (and one recompile) per several compactions
      grow = new_topo.num_edges + 4 * self.delta_capacity - capacity
      steps = -(-grow // max(self.delta_capacity, 1))
      capacity += steps * max(self.delta_capacity, 1)
      grown = True
      self.capacity_growths += 1

    snap = Snapshot(old.version + 1, new_topo, feature, capacity,
                    self.device)
    with self._lock:
      self.edge_capacity = capacity
      self._current = snap
      old._retired = True
      self._retired.append(old)
      self._reap_locked()
    self.compactions += 1
    self.last_compaction_s = time.perf_counter() - t0
    info = {
        'version': snap.version,
        'num_edges': snap.num_edges,
        'touched': (np.unique(np.concatenate(touched))
                    if touched else np.zeros(0, np.int64)),
        'capacity_grown': grown,
        'edge_capacity': capacity,
        'compaction_s': self.last_compaction_s,
    }
    return snap, info

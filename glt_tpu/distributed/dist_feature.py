"""DistFeature — partitioned feature store with collective lookup.

Reference: graphlearn_torch/python/distributed/dist_feature.py:69-452.
The design kept (per SURVEY.md §7) is the all2all path
(dist_feature.py:270-366); the rpc path has no TPU analogue. Unlike
parallel.ShardedFeature (uniform range sharding), this store follows an
arbitrary *feature partition book* — including hot-cache rewrites where
a remote row is also cached locally (cat_feature_cache,
partition/base.py:866-907): the PB maps each id to a serving partition
and the per-partition dense id2index maps it to the local row.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.collectives import all_to_all, bucket_by_owner, unbucket
from ..utils import as_numpy
from .dist_graph import _pb_dense


class DistFeature:
  """Stacked per-partition feature blocks, sharded over the mesh.

  Args:
    mesh: device mesh; axis size == number of partitions.
    parts: per-partition (feats [R_p, D], id2index [N]) — id2index maps a
      global id to its row in this partition's block (-1 if absent).
    feat_pb: the feature partition book(s). Cache-rewritten PBs differ
      per partition (each marks its own cached remote rows as local,
      reference base.py:903-905), so this is a list of one PB per
      partition (a single PB is broadcast); routing uses the
      *requesting* device's book, exactly like the reference workers.
    num_ids: global id-space size.
  """

  def __init__(self, mesh: Mesh, parts: Sequence, feat_pb,
               num_ids: int, axis: str = 'data', dtype=None,
               row_gather=None):
    # row_gather: optional serving-gather override (see
    # parallel.ShardedFeature); must be set before the first lookup —
    # the jitted shard_map traces it in on first call
    self._row_gather = row_gather
    self.mesh = mesh
    self.axis = axis
    self.num_ids = int(num_ids)
    n_parts = len(parts)
    assert mesh.shape[axis] == n_parts
    rows_max = max(max(f.shape[0] for f, _ in parts), 1)
    self.feature_dim = parts[0][0].shape[1]
    feats_l, maps_l = [], []
    for feats, id2index in parts:
      feats = as_numpy(feats)
      if dtype is not None:
        feats = feats.astype(dtype)
      pad = rows_max - feats.shape[0]
      if pad:
        feats = np.concatenate(
            [feats, np.zeros((pad, feats.shape[1]), feats.dtype)])
      m = as_numpy(id2index).astype(np.int32)
      if m.shape[0] < self.num_ids:
        m = np.concatenate(
            [m, np.full(self.num_ids - m.shape[0], -1, np.int32)])
      feats_l.append(feats)
      maps_l.append(m[:self.num_ids])
    shard = NamedSharding(mesh, P(axis))
    self.array = jax.device_put(np.stack(feats_l), shard)   # [P, R, D]
    self.id2index = jax.device_put(np.stack(maps_l), shard)  # [P, N]
    if not isinstance(feat_pb, (list, tuple)):
      feat_pb = [feat_pb] * n_parts
    self.feat_pb = jax.device_put(
        np.stack([_pb_dense(pb, self.num_ids) for pb in feat_pb]),
        shard)                                               # [P, N]
    self.rows_max = rows_max
    self.num_partitions = n_parts
    # compiled once; rebuilding shard_map per call would re-trace
    self._lookup_fn = jax.jit(jax.shard_map(
        lambda f, m, pb, i, v: self.lookup_local(f[0], m[0], pb[0], i, v),
        mesh=self.mesh,
        in_specs=(P(self.axis), P(self.axis), P(self.axis), P(self.axis),
                  P(self.axis)),
        out_specs=P(self.axis), check_vma=False))

  # -- in-shard lookup (call inside shard_map) ---------------------------

  def lookup_local(self, feat_shard, map_shard, pb, ids, valid,
                   axis_name: Optional[str] = None) -> jax.Array:
    """feat_shard: [R, D] block; map_shard: [N]; pb: [N] — THIS device's
    routing book; ids/valid: [B]. Returns [B, D] (zeros where invalid)."""
    ax = axis_name or self.axis
    n = self.num_partitions
    owner = jnp.take(pb, jnp.clip(ids, 0, self.num_ids - 1), mode='clip')
    owner = jnp.where(valid, owner, n)
    req, meta = bucket_by_owner(ids, owner, n)
    req_in = all_to_all(req, ax)                      # [P, B]
    flat = req_in.reshape(-1)
    rows = jnp.take(map_shard, jnp.clip(flat, 0, self.num_ids - 1),
                    mode='clip')
    ok = (flat >= 0) & (rows >= 0)
    safe_rows = jnp.clip(rows, 0, self.rows_max - 1)
    from ..ops.pallas_kernels import resolve_row_gather
    gather = resolve_row_gather(self._row_gather)
    if gather is not None:   # per-row DMA serving gather (see
      #                        parallel.ShardedFeature.lookup_local)
      rows_out = gather(feat_shard, safe_rows)
    else:
      rows_out = jnp.take(feat_shard, safe_rows, axis=0)
    served = jnp.where(ok[:, None], rows_out, 0)
    resp = all_to_all(served.reshape(n, -1, self.feature_dim), ax)
    return unbucket(resp, meta, n)

  def lookup(self, ids, valid=None) -> jax.Array:
    """Whole-mesh lookup: ids [P * B] shard-major."""
    ids = jnp.asarray(as_numpy(ids), jnp.int32)
    if valid is None:
      valid = jnp.ones(ids.shape, bool)
    return self._lookup_fn(self.array, self.id2index, self.feat_pb, ids,
                           jnp.asarray(valid))

  # -- builders ----------------------------------------------------------

  def collate_edge_attr(self, out: dict) -> None:
    """Attach ``out['edge_attr']`` gathered for the sampler output's
    padded [P, E] eids grid (one static-shape whole-mesh lookup —
    the shared collate used by every dist loader)."""
    eids = out['edge']
    ea = self.lookup(jnp.maximum(jnp.asarray(eids).reshape(-1), 0),
                     jnp.asarray(out['edge_mask']).reshape(-1))
    out['edge_attr'] = ea.reshape(tuple(eids.shape) + (-1,))

  @classmethod
  def from_dist_datasets(cls, mesh: Mesh, datasets, ntype=None,
                         axis: str = 'data', dtype=None,
                         kind: str = 'node', row_gather=None):
    """Single-host simulation: build from every partition's DistDataset
    (features must be fully device-resident).

    ``kind='edge'`` builds the *edge*-feature store (id space = global
    edge ids, routed by the edge-feature partition book) — the TPU
    counterpart of the reference's edge DistFeature
    (dist_feature.py:69-452 with group='edge_feat'); ``ntype`` then
    selects the edge type for hetero datasets.
    """
    assert kind in ('node', 'edge')
    parts, pbs = [], []
    num_ids = 0
    for ds in datasets:
      if kind == 'edge':
        feat = (ds.edge_features[ntype] if ntype is not None
                else ds.edge_features)
        pb = ds.get_edge_feat_pb(ntype)
      else:
        feat = (ds.node_features[ntype] if ntype is not None
                else ds.node_features)
        pb = ds.get_node_feat_pb(ntype)
      feat.lazy_init()
      pbs.append(pb)
      num_ids = max(num_ids, pb.table.shape[0])
      parts.append((np.asarray(feat.device_part), feat._id2index))
    return cls(mesh, parts, pbs, num_ids, axis=axis, dtype=dtype,
               row_gather=row_gather)


def dist_feature_from_partitions_multihost(mesh, root_dir: str,
                                           ntype=None, axis: str = 'data',
                                           dtype=None,
                                           kind: str = 'node'
                                           ) -> DistFeature:
  """Multi-host DistFeature: each process loads ONLY its partitions'
  feature blocks (cache-concat + PB rewrite included) and contributes
  them via process-local assembly; padding agreed with an allgather.
  Counterpart of dist_graph_from_partitions_multihost.

  ``kind='edge'`` builds the edge-feature store from the partitions'
  efeat blocks + edge partition books (``ntype`` then selects the edge
  type for hetero trees)."""
  assert kind in ('node', 'edge')
  import jax
  import jax.numpy as jnp
  from ..parallel.multihost import global_from_local
  from ..partition import cat_feature_cache, load_meta, load_partition
  meta = load_meta(root_dir)
  devices = mesh.devices.reshape(-1)
  n_parts = devices.shape[0]
  if meta['num_parts'] != n_parts:
    raise ValueError(
        f"mesh has {n_parts} devices but the partition dir holds "
        f"{meta['num_parts']} partitions")
  mine = [i for i, d in enumerate(devices)
          if d.process_index == jax.process_index()]

  blocks = {}
  num_ids = 0
  feat_dim = None
  local_max_rows = 0
  for p in mine:
    _, _, nfeat, efeat, node_pb, edge_pb = load_partition(root_dir, p)
    src, books = ((efeat, edge_pb) if kind == 'edge'
                  else (nfeat, node_pb))
    f = src[ntype] if isinstance(src, dict) and ntype is not None else src
    pb = (books[ntype] if isinstance(books, dict) and ntype is not None
          else books)
    if f is None:
      raise ValueError(
          f'partition {p} of {root_dir} holds no {kind} features '
          f'(ntype={ntype!r}); partition with '
          f'{"edge_feat" if kind == "edge" else "node_feat"} to use '
          f'kind={kind!r}')
    feats, ids, id2index, pb2 = cat_feature_cache(p, f, pb)
    blocks[p] = (feats, id2index, pb2)
    num_ids = max(num_ids, pb2.table.shape[0])
    feat_dim = feats.shape[1]
    local_max_rows = max(local_max_rows, feats.shape[0])

  if jax.process_count() > 1:
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(
        jnp.asarray([local_max_rows, num_ids, feat_dim or 0]))
    arr = np.asarray(gathered)
    rows_max = int(arr[:, 0].max())
    num_ids = int(arr[:, 1].max())
    feat_dim = int(arr[:, 2].max())
  else:
    rows_max = max(local_max_rows, 1)

  feats_l, maps_l, pbs_l = [], [], []
  for p in mine:
    feats, id2index, pb2 = blocks[p]
    if dtype is not None:
      feats = feats.astype(dtype)
    pad = rows_max - feats.shape[0]
    if pad:
      feats = np.concatenate(
          [feats, np.zeros((pad, feats.shape[1]), feats.dtype)])
    m = np.asarray(id2index).astype(np.int32)
    if m.shape[0] < num_ids:
      m = np.concatenate([m, np.full(num_ids - m.shape[0], -1,
                                     np.int32)])
    feats_l.append(feats)
    maps_l.append(m[:num_ids])
    pbs_l.append(_pb_dense(pb2, num_ids))

  store = DistFeature.__new__(DistFeature)
  store.mesh = mesh
  store.axis = axis
  store.num_ids = num_ids
  store.feature_dim = feat_dim
  store.rows_max = rows_max
  store.num_partitions = n_parts

  def stack_or_empty(parts, shape_tail, dtype_):
    if parts:
      return np.stack(parts)
    return np.zeros((0,) + shape_tail, dtype_)

  store.array = global_from_local(
      mesh, stack_or_empty(feats_l, (rows_max, feat_dim), np.float32),
      axis)
  store.id2index = global_from_local(
      mesh, stack_or_empty(maps_l, (num_ids,), np.int32), axis)
  store.feat_pb = global_from_local(
      mesh, stack_or_empty(pbs_l, (num_ids,), np.int32), axis)
  import jax as _jax
  from jax.sharding import PartitionSpec as _P
  store._lookup_fn = _jax.jit(_jax.shard_map(
      lambda f, m, pb, i, v: store.lookup_local(f[0], m[0], pb[0], i, v),
      mesh=mesh,
      in_specs=(_P(axis), _P(axis), _P(axis), _P(axis), _P(axis)),
      out_specs=_P(axis), check_vma=False))
  return store

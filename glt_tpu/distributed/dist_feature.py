"""DistFeature — partitioned feature store with collective lookup.

Reference: graphlearn_torch/python/distributed/dist_feature.py:69-452.
The design kept (per SURVEY.md §7) is the all2all path
(dist_feature.py:270-366); the rpc path has no TPU analogue. Unlike
parallel.ShardedFeature (uniform range sharding), this store follows an
arbitrary *feature partition book* — including hot-cache rewrites where
a remote row is also cached locally (cat_feature_cache,
partition/base.py:866-907): the PB maps each id to a serving partition
and the per-partition dense id2index maps it to the local row.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.collectives import all_to_all, bucket_by_owner, unbucket
from ..utils import as_numpy
from .dist_graph import _pb_dense


def _flag_lanes(flag) -> np.ndarray:
  """Global lane indices where a sharded bool array is True, collected
  from this process's addressable shards."""
  lanes = []
  for s in flag.addressable_shards:
    nz = np.nonzero(np.asarray(s.data))[0]
    if nz.size:
      lanes.append((s.index[0].start or 0) + nz)
  return (np.concatenate(lanes) if lanes else np.zeros(0, np.int64))


#: (rows [M, D], index [M]) — a partition's contribution to a lookup,
#: positions indexing into the requesting batch (reference
#: dist_feature.py:37-41 PartialFeature). The collective path stitches
#: positionally inside the program; this alias types the HOST-side
#: surfaces (cold_get / cold_fetcher payloads).
PartialFeature = Tuple[np.ndarray, np.ndarray]


class DistFeature:
  """Stacked per-partition feature blocks, sharded over the mesh.

  Args:
    mesh: device mesh; axis size == number of partitions.
    parts: per-partition (feats [R_p, D], id2index [N]) — id2index maps a
      global id to its row in this partition's block (-1 if absent).
    feat_pb: the feature partition book(s). Cache-rewritten PBs differ
      per partition (each marks its own cached remote rows as local,
      reference base.py:903-905), so this is a list of one PB per
      partition (a single PB is broadcast); routing uses the
      *requesting* device's book, exactly like the reference workers.
    num_ids: global id-space size.
  """

  def __init__(self, mesh: Mesh, parts: Sequence, feat_pb,
               num_ids: int, axis: str = 'data', dtype=None,
               row_gather=None, split_ratio: float = 1.0,
               hot_counts: Optional[Sequence[int]] = None,
               cold_fetcher=None, bucket_cap: int = 0,
               host_offload: Optional[bool] = None):
    n_parts = len(parts)
    assert mesh.shape[axis] == n_parts
    rows_max = max(max(f.shape[0] for f, _ in parts), 1)
    if hot_counts is None:
      hot_counts = [int(round(f.shape[0] * float(split_ratio)))
                    for f, _ in parts]
    spill = any(h < f.shape[0] for h, (f, _) in zip(hot_counts, parts))
    self._finish_init(mesh, axis, num_ids, parts[0][0].shape[1],
                      rows_max, n_parts, row_gather=row_gather,
                      hot_counts=hot_counts, cold_fetcher=cold_fetcher,
                      spill=spill, bucket_cap=bucket_cap)
    if not isinstance(feat_pb, (list, tuple)):
      feat_pb = [feat_pb] * n_parts
    feats_l, maps_l, pbs_l = [], [], []
    for p, (feats, id2index) in enumerate(parts):
      feats = as_numpy(feats)
      if dtype is not None:
        feats = feats.astype(dtype)
      hot = self.hot_counts[p]
      pb_dense = _pb_dense(feat_pb[p], self.num_ids)
      pbs_l.append(pb_dense)
      if self._spill:
        # every local partition keeps its host routing book: a
        # fully-resident requester can still route a lane to a spilled
        # owner, and the host phase resolves by the requester's book
        self._host_pb[p] = pb_dense
      if hot < feats.shape[0]:   # spill: cold rows stay host-resident
        self._host_cold[p] = feats[hot:]
        self._host_id2index[p] = as_numpy(id2index).astype(np.int32)
      feats = feats[:hot]
      pad = self.hot_max - feats.shape[0]
      if pad:
        feats = np.concatenate(
            [feats, np.zeros((pad, feats.shape[1]), feats.dtype)])
      m = as_numpy(id2index).astype(np.int32)
      if m.shape[0] < self.num_ids:
        m = np.concatenate(
            [m, np.full(self.num_ids - m.shape[0], -1, np.int32)])
      feats_l.append(feats)
      maps_l.append(m[:self.num_ids])
    shard = NamedSharding(mesh, P(axis))
    self.array = jax.device_put(np.stack(feats_l), shard)  # [P, Rh, D]
    self.id2index = jax.device_put(np.stack(maps_l), shard)  # [P, N]
    self.feat_pb = jax.device_put(np.stack(pbs_l), shard)    # [P, N]
    # Host-offload (reference unified_tensor.cu:202-231 UVA analog, see
    # parallel.ShardedFeature): the cold blocks become one stacked
    # pinned-host array gathered INSIDE the compiled program, so fused
    # SPMD train steps can consume spilled stores and lookup() needs no
    # host phase. Default on when spilling (GLT_HOST_OFFLOAD=0 or
    # host_offload=False opt out).
    from ..utils.offload import maybe_pin_host, offload_requested
    if offload_requested(host_offload, self._spill) and self._host_cold:
      c_max = max(c.shape[0] for c in self._host_cold.values())
      np_dtype = np.dtype(self.array.dtype)
      stack = np.zeros((n_parts, c_max, self.feature_dim), np_dtype)
      for p, c in self._host_cold.items():
        stack[p, :c.shape[0]] = c
      self.cold_array = maybe_pin_host(
          lambda: jax.device_put(
              stack, NamedSharding(mesh, P(axis),
                                   memory_kind='pinned_host')),
          host_offload)
      if self.cold_array is not None:
        # host-phase state (and the cold_get rpc surface) is unused
        # when cold rows are served in-program; keeping the numpy
        # blocks would double the cold footprint in host RAM
        self._host_cold = {}
        self._host_id2index = {}
        self._host_pb = {}
      self._build_lookup_fn()

  def _finish_init(self, mesh: Mesh, axis: str, num_ids: int,
                   feat_dim: int, rows_max: int, n_parts: int,
                   row_gather=None, hot_counts=None, cold_fetcher=None,
                   spill=None, bucket_cap: int = 0):
    """Non-array state shared by __init__ and every alternate builder.
    ANY new scalar/config field must be set here, so a builder that
    assembles the arrays differently (e.g. the multihost
    process-local path) can never miss it."""
    # row_gather: optional serving-gather override (see
    # parallel.ShardedFeature); must be set before the first lookup —
    # the jitted shard_map traces it in on first call
    self._row_gather = row_gather
    self.mesh = mesh
    self.axis = axis
    self.num_ids = int(num_ids)
    self.feature_dim = int(feat_dim)
    self.rows_max = int(rows_max)
    self.num_partitions = int(n_parts)
    # host-spill state (UnifiedTensor pinned-CPU shard analogue,
    # reference unified_tensor.cu:202-231): rows [hot_p, R_p) of each
    # partition's block stay in that process's host RAM. hot_counts ==
    # rows_max everywhere (the default) means fully device-resident.
    if hot_counts is None:
      hot_counts = [rows_max] * n_parts
    self.hot_counts = np.asarray(hot_counts, np.int32)
    self.hot_max = max(1, int(self.hot_counts.max()))
    if spill is None:
      spill = bool((self.hot_counts < rows_max).any())
    self._spill = spill
    self._host_cold = {}      # part -> np [R_p - hot_p, D]
    self._host_id2index = {}  # part -> np [N] (local partitions only)
    self._host_pb = {}        # part -> np [N] requester routing book
    self._cold_fetcher = cold_fetcher
    # bucket_cap < B caps each per-peer request bucket (see
    # parallel.ShardedFeature.bucket_cap); lookup_local drains the
    # overflow in-program (round loop + pmax round count)
    self.bucket_cap = int(bucket_cap)
    # the cap is baked into the shard_map trace on first lookup; a later
    # mutation would silently keep routing with the old cap — record
    # the cap actually traced and refuse mismatched lookups (lookup())
    self._traced_cap = None
    self._hot_counts_dev = jnp.asarray(self.hot_counts)
    # stacked pinned-host cold blocks [P, C_max, D]; builders that
    # host-offload set this after assembling the arrays and rebuild
    self.cold_array = None
    self._build_lookup_fn()

  def _call_lookup_fn(self, ids, valid):
    """Dispatch to the compiled lookup with the operand list matching
    the _build_lookup_fn variant in effect."""
    if self.cold_array is not None:
      return self._lookup_fn(self.array, self.id2index, self.feat_pb,
                             self.cold_array, ids, valid)
    return self._lookup_fn(self.array, self.id2index, self.feat_pb,
                           ids, valid)

  def _build_lookup_fn(self):
    """(Re)build the compiled whole-mesh lookup. Compiled once per
    build; rebuilding shard_map per call would re-trace."""
    sp = P(self.axis)
    if self.cold_array is not None:
      # offloaded: cold lanes are served in-program — single output
      self._lookup_fn = jax.jit(jax.shard_map(
          lambda f, m, pb, c, i, v: self.lookup_local(
              f[0], m[0], pb[0], i, v, cold_shard=c[0]),
          mesh=self.mesh, in_specs=(sp,) * 6, out_specs=sp,
          check_vma=False))
      return
    self._lookup_fn = jax.jit(jax.shard_map(
        lambda f, m, pb, i, v: self.lookup_local(f[0], m[0], pb[0], i, v),
        mesh=self.mesh,
        in_specs=(sp, sp, sp, sp, sp),
        out_specs=(sp if not self._spill else (sp, sp)),
        check_vma=False))

  # -- in-shard lookup (call inside shard_map) ---------------------------

  def lookup_local(self, feat_shard, map_shard, pb, ids, valid,
                   axis_name: Optional[str] = None, cold_shard=None):
    """feat_shard: [Rh, D] hot block; map_shard: [N]; pb: [N] — THIS
    device's routing book; ids/valid: [B]. Returns [B, D] (zeros where
    invalid). With host spill active and no ``cold_shard``, returns
    ([B, D], cold_flag [B]): flagged lanes are valid ids whose row
    lives in the owner's host shard — served as zeros here and resolved
    by lookup()'s host phase. With ``cold_shard`` (this device's
    pinned-host [C_max, D] block), cold lanes are instead served
    in-program by a compute_on('device_host') gather and the return is
    the plain [B, D] — the form fused train steps consume.

    With ``bucket_cap`` set the overflow drain runs IN-PROGRAM (round k
    ships bucket ranks [k*cap, (k+1)*cap); the round count is the
    mesh-wide pmax of bucket occupancy over the cap) — no host replay
    of the routing, no retained books, and fused train steps can use
    capped stores (see parallel.collectives.drain_rounds)."""
    from ..parallel.collectives import bucket_payload, capped_drain
    ax = axis_name or self.axis
    n = self.num_partitions
    b = ids.shape[0]
    owner = jnp.take(pb, jnp.clip(ids, 0, self.num_ids - 1), mode='clip')
    owner = jnp.where(valid, owner, n)
    cap = (self.bucket_cap if 0 < self.bucket_cap < b else 0)
    _, meta = bucket_by_owner(ids, owner, n, capacity=cap)
    eff_cap = cap if cap else b
    two_outputs = self._spill and cold_shard is None

    def round_serve(base):
      req = bucket_payload(ids, meta, n, fill_value=-1,
                           capacity=eff_cap, round_offset=base)
      req_in = all_to_all(req, ax)                      # [P, C]
      flat = req_in.reshape(-1)
      rows = jnp.take(map_shard, jnp.clip(flat, 0, self.num_ids - 1),
                      mode='clip')
      ok = (flat >= 0) & (rows >= 0)
      if self._spill:
        my_hot = jnp.take(self._hot_counts_dev, jax.lax.axis_index(ax))
        cold = ok & (rows >= my_hot)
        ok = ok & (rows < my_hot)
      safe_rows = jnp.clip(rows, 0, self.hot_max - 1)
      from ..ops.pallas_kernels import resolve_row_gather
      gather = resolve_row_gather(self._row_gather)
      if gather is not None:   # per-row DMA serving gather (see
        #                        parallel.ShardedFeature.lookup_local)
        rows_out = gather(feat_shard, safe_rows)
      else:
        rows_out = jnp.take(feat_shard, safe_rows, axis=0)
      served = jnp.where(ok[:, None], rows_out, 0)
      if not self._spill:
        resp = all_to_all(served.reshape(n, -1, self.feature_dim), ax)
        return unbucket(resp, meta, n, round_offset=base)
      if cold_shard is not None:
        # serve the owner's spilled rows from pinned host memory
        # without leaving the program: index arithmetic stays on
        # device, the gather runs host-side (raw indexing — bounds ops
        # would materialize device-space constants inside the host
        # region)
        from jax.experimental import compute_on
        cold_idx = jnp.clip(rows - my_hot, 0, cold_shard.shape[0] - 1)
        idx_h = jax.device_put(cold_idx, jax.memory.Space.Host)
        with compute_on.compute_on('device_host'):
          cold_out = cold_shard[idx_h]
        cold_out = jax.device_put(cold_out, jax.memory.Space.Device)
        served = jnp.where(cold[:, None],
                           cold_out.astype(served.dtype), served)
        resp = all_to_all(served.reshape(n, -1, self.feature_dim), ax)
        return unbucket(resp, meta, n, round_offset=base)
      # ride the cold flag back as one extra response column so the
      # requester learns hot/cold without holding the owner's id2index
      payload = jnp.concatenate(
          [served, cold[:, None].astype(served.dtype)], axis=1)
      resp = all_to_all(payload.reshape(n, -1, self.feature_dim + 1),
                        ax)
      full = unbucket(resp, meta, n, round_offset=base)
      return full[:, :self.feature_dim], full[:, self.feature_dim] > 0

    if not cap:
      return round_serve(0)
    zeros_feat = jnp.zeros((b, self.feature_dim), feat_shard.dtype)
    zeros = ((zeros_feat, jnp.zeros((b,), bool)) if two_outputs
             else zeros_feat)
    return capped_drain(round_serve, meta, n, eff_cap, b, ax, zeros)

  def lookup(self, ids, valid=None) -> jax.Array:
    """Whole-mesh lookup: ids [P * B] shard-major.

    Capped stores drain their overflow inside the compiled program
    (lookup_local runs the round loop on device) — one call regardless
    of skew. With host spill, flagged cold lanes are resolved from the
    host shards at the end; both compose: a lane that overflowed in
    round k and turns out cold in round k+1 still resolves exactly
    once."""
    if self._traced_cap is None:
      self._traced_cap = self.bucket_cap
    elif self.bucket_cap != self._traced_cap:
      raise RuntimeError(
          f'bucket_cap changed from {self._traced_cap} to '
          f'{self.bucket_cap} after the first lookup compiled it in; '
          'the cached program would keep routing with the old cap. '
          'Set bucket_cap before the first lookup, or build a new '
          'store.')
    ids_np = as_numpy(ids).astype(np.int64)
    ids = jnp.asarray(ids_np, jnp.int32)
    if valid is None:
      valid_np = np.ones(ids_np.shape, bool)
    else:
      valid_np = as_numpy(valid).astype(bool)
    res = self._call_lookup_fn(ids, jnp.asarray(valid_np))
    if self._spill and self.cold_array is None:
      out, flag = res
      lanes = _flag_lanes(flag)
      if lanes.size:
        out = self._resolve_cold(out, lanes, ids_np)
      return out
    return res

  # -- host spill resolution ---------------------------------------------

  def _resolve_cold(self, out, lanes, ids_np) -> jax.Array:
    """Serve the flagged lanes from the host shards and merge on device.
    Cold lanes are zero in ``out`` (the device phase masks them), so the
    merge is one sharded add — no SPMD-hostile scatter. Remote-process
    partitions resolve through ``cold_fetcher(part, ids) -> [M, D]``
    (e.g. an rpc callee); local ones read the in-process block."""
    b = ids_np.shape[0] // self.num_partitions
    cold_ids = ids_np[lanes]
    dev_of = lanes // b
    owners = np.empty(lanes.shape[0], np.int64)
    for d in np.unique(dev_of):
      m = dev_of == d
      book = self._host_pb.get(int(d))
      if book is None:
        raise RuntimeError(
            f'cold lane routed by partition {d} but its host routing '
            'book is not in this process — build the store with '
            'host-spill in the owning process')
      owners[m] = book[np.clip(cold_ids[m], 0, self.num_ids - 1)]
    np_dtype = np.dtype(out.dtype)
    vals = np.zeros((lanes.shape[0], self.feature_dim), np_dtype)
    for p in np.unique(owners):
      m = owners == p
      p = int(p)
      if p in self._host_cold:
        rows = self._host_id2index[p][cold_ids[m]]
        vals[m] = self._host_cold[p][rows - int(self.hot_counts[p])]
      elif self._cold_fetcher is not None:
        vals[m] = self._cold_fetcher(p, cold_ids[m])
      else:
        raise RuntimeError(
            f'partition {p} holds cold rows in another process and no '
            'cold_fetcher is registered (see set_cold_fetcher)')
    delta = np.zeros((ids_np.shape[0], self.feature_dim), np_dtype)
    delta[lanes] = vals
    if jax.process_count() == 1:
      delta_arr = jax.device_put(delta, out.sharding)
    else:
      # flat [P*B, D] layout: supply this process's B-row blocks in
      # device order (global_from_local is for [P, ...] stacks)
      local = np.concatenate(
          [delta[d * b:(d + 1) * b]
           for d, dev in enumerate(self.mesh.devices.reshape(-1))
           if dev.process_index == jax.process_index()])
      delta_arr = jax.make_array_from_process_local_data(
          NamedSharding(self.mesh, P(self.axis)), local,
          global_shape=delta.shape)
    return out + delta_arr

  def set_cold_fetcher(self, fetcher) -> None:
    """Register the remote cold-row resolver:
    ``fetcher(partition: int, ids: np.int64 [M]) -> np [M, D]``.
    Wrap with :func:`resilient_cold_fetcher` for replica failover +
    bounded-staleness degradation on dead owners."""
    self._cold_fetcher = fetcher

  def cold_get(self, partition: int, ids: np.ndarray) -> np.ndarray:
    """Serve cold rows of a locally-held partition (the rpc-callee
    counterpart of ``cold_fetcher``; reference RpcFeatureLookupCallee,
    dist_feature.py:57-66). Only meaningful on the legacy host-phase
    path — host-offloaded stores serve cold rows in-program and free
    this surface's state."""
    if self.cold_array is not None:
      raise RuntimeError(
          'cold_get is the legacy host-phase rpc surface; this store '
          'host-offloads its cold rows (served in-program) and does '
          'not retain the numpy blocks — build with host_offload=False '
          'to use cold_get/cold_fetcher')
    rows = self._host_id2index[int(partition)][np.asarray(ids)]
    return self._host_cold[int(partition)][
        rows - int(self.hot_counts[int(partition)])]

  # -- builders ----------------------------------------------------------

  def collate_edge_attr(self, out: dict) -> None:
    """Attach ``out['edge_attr']`` gathered for the sampler output's
    padded [P, E] eids grid (one static-shape whole-mesh lookup —
    the shared collate used by every dist loader)."""
    eids = out['edge']
    ea = self.lookup(jnp.maximum(jnp.asarray(eids).reshape(-1), 0),
                     jnp.asarray(out['edge_mask']).reshape(-1))
    out['edge_attr'] = ea.reshape(tuple(eids.shape) + (-1,))

  @classmethod
  def from_dist_datasets(cls, mesh: Mesh, datasets, ntype=None,
                         axis: str = 'data', dtype=None,
                         kind: str = 'node', row_gather=None,
                         cold_fetcher=None, split_ratio=None,
                         bucket_cap: int = 0,
                         host_offload: Optional[bool] = None):
    """Single-host simulation: build from every partition's DistDataset.
    Each partition Feature's own hot/cold split carries over: its cold
    rows become this store's host shard for that partition (beyond-HBM
    distributed features, reference unified_tensor.cu:202-231).
    ``split_ratio`` overrides the per-Feature split when given.

    ``kind='edge'`` builds the *edge*-feature store (id space = global
    edge ids, routed by the edge-feature partition book) — the TPU
    counterpart of the reference's edge DistFeature
    (dist_feature.py:69-452 with group='edge_feat'); ``ntype`` then
    selects the edge type for hetero datasets.
    """
    assert kind in ('node', 'edge')
    parts, pbs, hots = [], [], []
    num_ids = 0
    for ds in datasets:
      if kind == 'edge':
        feat = (ds.edge_features[ntype] if ntype is not None
                else ds.edge_features)
        pb = ds.get_edge_feat_pb(ntype)
      else:
        feat = (ds.node_features[ntype] if ntype is not None
                else ds.node_features)
        pb = ds.get_node_feat_pb(ntype)
      feat.lazy_init()
      pbs.append(pb)
      num_ids = max(num_ids, pb.table.shape[0])
      if feat.fully_device_resident:
        block = np.asarray(feat.device_part)
      else:  # reassemble [hot | cold] on host; __init__ re-splits.
        # _cold keeps the SOURCE dtype — cast it so a compression cast
        # (Feature(dtype=bf16)) survives instead of promoting the stack
        block = np.concatenate(
            [np.asarray(feat.device_part, dtype=feat.dtype),
             np.asarray(feat.cold_block_numpy(), dtype=feat.dtype)])
      hots.append(feat.hot_count if split_ratio is None
                  else int(round(block.shape[0] * float(split_ratio))))
      parts.append((block, feat._id2index))
    return cls(mesh, parts, pbs, num_ids, axis=axis, dtype=dtype,
               row_gather=row_gather, hot_counts=hots,
               cold_fetcher=cold_fetcher, bucket_cap=bucket_cap,
               host_offload=host_offload)


def resilient_cold_fetcher(fetchers, feature_dim: Optional[int] = None,
                           metrics=None, cache_capacity: int = 200_000):
  """Compose per-partition cold fetchers into one fault-tolerant
  ``fetcher(partition, ids) -> [M, D]`` for
  :meth:`DistFeature.set_cold_fetcher`.

  Args:
    fetchers: ``{partition: [fn, ...]}`` — each ``fn(ids) -> [M, D]``,
      primaries first, replicas after (build the list from
      ``rpc_sync_data_partitions``: every rank serving a partition is a
      replica of its rows).
    feature_dim: row width for zero-fill before any fetch succeeded.
    metrics: optional ServingMetrics — failovers and stale serves are
      counted there (the same counters the serving stack uses).

  Ladder per lookup: primary -> replicas in order (each connection
  failure recorded, first success wins and refreshes the staleness
  cache) -> cached rows + zero-fill for true misses. Raises only when
  degradation is impossible (no cache rows AND unknown row width).
  """
  from ..resilience import DegradedFeatureCache
  stale = DegradedFeatureCache(capacity=cache_capacity)
  if feature_dim is not None:
    stale.feature_dim = int(feature_dim)
  fetchers = {int(p): list(fs) for p, fs in fetchers.items()}

  def fetch(partition: int, ids: np.ndarray) -> np.ndarray:
    chain = fetchers.get(int(partition), [])
    last: Optional[BaseException] = None
    for k, fn in enumerate(chain):
      try:
        rows = np.asarray(fn(np.asarray(ids, np.int64)))
      except (ConnectionError, OSError) as e:
        last = e
        continue
      if k > 0 and metrics is not None:
        metrics.record_failover()
      stale.update(ids, rows)
      return rows
    return stale.serve_counted(
        ids, metrics, what=f'cold fetch(partition {partition})',
        cause=last)

  return fetch


def dist_feature_from_partitions_multihost(mesh, root_dir: str,
                                           ntype=None, axis: str = 'data',
                                           dtype=None,
                                           kind: str = 'node',
                                           row_gather=None,
                                           split_ratio: float = 1.0,
                                           cold_fetcher=None,
                                           bucket_cap: int = 0,
                                           host_offload=None
                                           ) -> DistFeature:
  """Multi-host DistFeature: each process loads ONLY its partitions'
  feature blocks (cache-concat + PB rewrite included) and contributes
  them via process-local assembly; padding agreed with an allgather.
  Counterpart of dist_graph_from_partitions_multihost.

  ``split_ratio < 1`` spills each partition's cold tail to its OWN
  process's host RAM (beyond-HBM features). By default (host_offload
  auto) the cold tails become a pinned-host sharded array served
  in-program — each partition's cold rows live in its OWN process's
  host RAM and are gathered by its own device, so no cross-process
  fetch exists at all. With ``host_offload=False`` cross-process cold
  lookups instead need a ``cold_fetcher`` wired to the rpc fabric (see
  DistFeature.set_cold_fetcher / cold_get).

  ``kind='edge'`` builds the edge-feature store from the partitions'
  efeat blocks + edge partition books (``ntype`` then selects the edge
  type for hetero trees)."""
  assert kind in ('node', 'edge')
  import jax
  import jax.numpy as jnp
  from ..parallel.multihost import global_from_local
  from ..partition import cat_feature_cache, load_meta, load_partition
  meta = load_meta(root_dir)
  devices = mesh.devices.reshape(-1)
  n_parts = devices.shape[0]
  if meta['num_parts'] != n_parts:
    raise ValueError(
        f"mesh has {n_parts} devices but the partition dir holds "
        f"{meta['num_parts']} partitions")
  mine = [i for i, d in enumerate(devices)
          if d.process_index == jax.process_index()]

  blocks = {}
  num_ids = 0
  feat_dim = None
  local_max_rows = 0
  for p in mine:
    _, _, nfeat, efeat, node_pb, edge_pb = load_partition(root_dir, p)
    src, books = ((efeat, edge_pb) if kind == 'edge'
                  else (nfeat, node_pb))
    f = src[ntype] if isinstance(src, dict) and ntype is not None else src
    pb = (books[ntype] if isinstance(books, dict) and ntype is not None
          else books)
    if f is None:
      raise ValueError(
          f'partition {p} of {root_dir} holds no {kind} features '
          f'(ntype={ntype!r}); partition with '
          f'{"edge_feat" if kind == "edge" else "node_feat"} to use '
          f'kind={kind!r}')
    feats, ids, id2index, pb2 = cat_feature_cache(p, f, pb)
    blocks[p] = (feats, id2index, pb2)
    num_ids = max(num_ids, pb2.table.shape[0])
    feat_dim = feats.shape[1]
    local_max_rows = max(local_max_rows, feats.shape[0])

  spill = float(split_ratio) < 1.0
  # per-partition hot counts must be agreed globally (they are baked
  # into every process's trace); partitions are disjoint so a summed
  # allgather assembles the full [P] vector
  local_hot = np.zeros(n_parts, np.int64)
  for p in mine:
    r = blocks[p][0].shape[0]
    local_hot[p] = int(round(r * float(split_ratio))) if spill else r
  if jax.process_count() > 1:
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(
        jnp.asarray([local_max_rows, num_ids, feat_dim or 0]))
    arr = np.asarray(gathered)
    rows_max = int(arr[:, 0].max())
    num_ids = int(arr[:, 1].max())
    feat_dim = int(arr[:, 2].max())
    hot_counts = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(local_hot))
    ).sum(axis=0)
  else:
    rows_max = max(local_max_rows, 1)
    hot_counts = local_hot
  pad_rows = int(hot_counts.max()) if spill else rows_max
  pad_rows = max(pad_rows, 1)

  store = DistFeature.__new__(DistFeature)
  store._finish_init(mesh, axis, num_ids, feat_dim, rows_max, n_parts,
                     row_gather=row_gather, hot_counts=hot_counts,
                     cold_fetcher=cold_fetcher, spill=spill,
                     bucket_cap=bucket_cap)

  feats_l, maps_l, pbs_l = [], [], []
  for p in mine:
    feats, id2index, pb2 = blocks[p]
    if dtype is not None:
      feats = feats.astype(dtype)
    pb_dense = _pb_dense(pb2, num_ids)
    if spill:
      store._host_pb[p] = pb_dense
      hot = int(hot_counts[p])
      if hot < feats.shape[0]:
        store._host_cold[p] = feats[hot:]
        store._host_id2index[p] = np.asarray(id2index).astype(np.int32)
      feats = feats[:hot]
    pad = pad_rows - feats.shape[0]
    if pad:
      feats = np.concatenate(
          [feats, np.zeros((pad, feats.shape[1]), feats.dtype)])
    m = np.asarray(id2index).astype(np.int32)
    if m.shape[0] < num_ids:
      m = np.concatenate([m, np.full(num_ids - m.shape[0], -1,
                                     np.int32)])
    feats_l.append(feats)
    maps_l.append(m[:num_ids])
    pbs_l.append(pb_dense)

  def stack_or_empty(parts, shape_tail, dtype_):
    if parts:
      return np.stack(parts)
    return np.zeros((0,) + shape_tail, dtype_)

  store.array = global_from_local(
      mesh, stack_or_empty(feats_l, (pad_rows, feat_dim), np.float32),
      axis)
  store.id2index = global_from_local(
      mesh, stack_or_empty(maps_l, (num_ids,), np.int32), axis)
  store.feat_pb = global_from_local(
      mesh, stack_or_empty(pbs_l, (num_ids,), np.int32), axis)
  from ..utils.offload import maybe_pin_host, offload_requested
  if offload_requested(host_offload, spill) and spill:
    # global cold capacity must be agreed (it is baked into every
    # process's trace); partitions are disjoint, so max-allgather
    local_cmax = max((c.shape[0] for c in store._host_cold.values()),
                     default=0)
    if jax.process_count() > 1:
      from jax.experimental import multihost_utils
      c_max = int(np.asarray(multihost_utils.process_allgather(
          jnp.asarray([local_cmax]))).max())
    else:
      c_max = local_cmax
    if c_max:
      np_dtype = np.dtype(store.array.dtype)
      local_stack = np.zeros((len(mine), c_max, feat_dim), np_dtype)
      for i, p in enumerate(mine):
        c = store._host_cold.get(p)
        if c is not None:
          local_stack[i, :c.shape[0]] = c
      store.cold_array = maybe_pin_host(
          lambda: global_from_local(mesh, local_stack, axis,
                                    memory_kind='pinned_host'),
          host_offload)
      if store.cold_array is not None:
        store._host_cold = {}
        store._host_id2index = {}
        if not store.bucket_cap:
          store._host_pb = {}
      store._build_lookup_fn()
  return store

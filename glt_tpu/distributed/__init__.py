from .dist_context import (
    DistContext, DistRole, assign_server_by_order, get_context,
    init_client_context, init_server_context, init_worker_group, shutdown,
)
from .dist_dataset import DistDataset, DistTableDataset
from .dist_graph import DistGraph
from .dist_feature import DistFeature
from .dist_neighbor_sampler import DistNeighborSampler

__all__ = [
    'DistContext', 'DistRole', 'assign_server_by_order', 'get_context',
    'init_client_context', 'init_server_context', 'init_worker_group',
    'shutdown',
    'DistDataset', 'DistTableDataset', 'DistGraph', 'DistFeature',
    'DistNeighborSampler',
]
from .dist_train import DistTrainStep
from .dist_loader import DistNeighborLoader

__all__ += ['DistTrainStep', 'DistNeighborLoader']
from .dist_options import (
    CollocatedDistSamplingWorkerOptions, MpDistSamplingWorkerOptions,
    RemoteDistSamplingWorkerOptions,
)
from .dist_sampling_producer import (
    DistCollocatedSamplingProducer, DistMpSamplingProducer,
)
from .channel_loader import MpNeighborLoader, RemoteNeighborLoader
from .dist_server import (
    DistServer, get_server, init_server, shutdown_server,
    wait_and_shutdown_server,
)
from .dist_client import (
    async_request_server, collect_obs, export_fabric_trace,
    fabric_stats, init_client, request_server, request_with_failover,
    set_replicas, shutdown_client,
)

__all__ += [
    'CollocatedDistSamplingWorkerOptions', 'MpDistSamplingWorkerOptions',
    'RemoteDistSamplingWorkerOptions',
    'DistCollocatedSamplingProducer', 'DistMpSamplingProducer',
    'MpNeighborLoader', 'RemoteNeighborLoader',
    'DistServer', 'init_server', 'shutdown_server',
    'wait_and_shutdown_server',
    'async_request_server', 'init_client', 'request_server',
    'shutdown_client', 'request_with_failover', 'set_replicas',
    'fabric_stats', 'collect_obs', 'export_fabric_trace',
]
from .dist_hetero import DistHeteroGraph, DistHeteroNeighborSampler, \
    DistHeteroTrainStep

__all__ += ['DistHeteroGraph', 'DistHeteroNeighborSampler',
            'DistHeteroTrainStep']
from .dist_random_partitioner import DistRandomPartitioner

__all__ += ['DistRandomPartitioner']
from .dist_link_loader import DistLinkNeighborLoader

__all__ += ['DistLinkNeighborLoader']
from .dist_subgraph_loader import DistSubGraphLoader

__all__ += ['DistSubGraphLoader']
from .dist_negative import DistRandomNegativeSampler

__all__ += ['DistRandomNegativeSampler']
from .dist_graph import dist_graph_from_partitions_multihost

__all__ += ['dist_graph_from_partitions_multihost']
from .dist_feature import dist_feature_from_partitions_multihost
from .dist_hetero import dist_hetero_graph_from_partitions_multihost
__all__ += ['dist_hetero_graph_from_partitions_multihost']

__all__ += ['dist_feature_from_partitions_multihost']

from .dist_feature import PartialFeature, resilient_cold_fetcher
from .dist_random_partitioner import DistTableRandomPartitioner
from .rpc import (
    RpcCalleeBase, RpcClient, RpcDataPartitionRouter, RpcServer,
    all_gather, barrier, get_rpc_master_addr, get_rpc_master_port,
    global_all_gather, global_barrier, init_rpc, rpc_global_request,
    rpc_global_request_async, rpc_is_initialized, rpc_register,
    rpc_request, rpc_request_async, rpc_sync_data_partitions,
    shutdown_rpc,
)

__all__ += [
    'PartialFeature', 'resilient_cold_fetcher',
    'DistTableRandomPartitioner', 'get_server',
    'RpcCalleeBase', 'RpcClient', 'RpcDataPartitionRouter', 'RpcServer',
    'all_gather', 'barrier', 'get_rpc_master_addr',
    'get_rpc_master_port', 'global_all_gather', 'global_barrier',
    'init_rpc', 'rpc_global_request', 'rpc_global_request_async',
    'rpc_is_initialized', 'rpc_register', 'rpc_request',
    'rpc_request_async', 'rpc_sync_data_partitions', 'shutdown_rpc',
]

from .dist_loader import DistLoader
from .event_loop import ConcurrentEventLoop

__all__ += ['DistLoader', 'ConcurrentEventLoop']

"""Bounded host-side task concurrency.

Reference: graphlearn_torch/python/distributed/event_loop.py (asyncio
daemon-thread loop + BoundedSemaphore backpressure driving concurrent
sampling tasks). On TPU the DEVICE pipeline needs none of this — XLA
dispatch is already async and the fused SPMD steps are one program —
so this exists for the surfaces that stay host-side: partition-block
I/O, rpc fan-out (cold fetchers, producer control), channel prefetch.
A thread pool with a bounded in-flight window gives the same
``add_task``/``run_task``/``wait_all`` contract without an asyncio
dependency.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional


class ConcurrentEventLoop:
  """Reference event_loop.py:39-102 surface: submit up to
  ``concurrency`` tasks in flight; ``add_task`` blocks when the window
  is full (the reference's BoundedSemaphore backpressure), ``run_task``
  executes synchronously through the same window, ``wait_all`` joins
  every outstanding task (re-raising the first failure)."""

  def __init__(self, concurrency: int = 32):
    assert concurrency > 0
    self._sem = threading.BoundedSemaphore(concurrency)
    self._pool = ThreadPoolExecutor(max_workers=concurrency)
    self._pending: List[Future] = []
    self._lock = threading.Lock()

  def _wrap(self, fn: Callable, args, kwargs):
    try:
      return fn(*args, **kwargs)
    finally:
      self._sem.release()

  def add_task(self, fn: Callable, *args,
               callback: Optional[Callable] = None, **kwargs) -> Future:
    """Submit; blocks while ``concurrency`` tasks are in flight.
    ``callback`` (if given) receives the result on completion."""
    self._sem.acquire()
    fut = self._pool.submit(self._wrap, fn, args, kwargs)
    if callback is not None:
      fut.add_done_callback(lambda f: callback(f.result()))
    with self._lock:
      self._pending.append(fut)
    return fut

  def run_task(self, fn: Callable, *args, **kwargs):
    """Synchronous execution through the same backpressure window."""
    return self.add_task(fn, *args, **kwargs).result()

  def wait_all(self) -> None:
    """Join every outstanding task; re-raises the first failure."""
    while True:
      with self._lock:
        if not self._pending:
          return
        pending, self._pending = self._pending, []
      for f in pending:
        f.result()

  def shutdown(self) -> None:
    self.wait_all()
    self._pool.shutdown(wait=True)

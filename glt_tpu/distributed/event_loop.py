"""Bounded host-side task concurrency.

Reference: graphlearn_torch/python/distributed/event_loop.py (asyncio
daemon-thread loop + BoundedSemaphore backpressure driving concurrent
sampling tasks). On TPU the DEVICE pipeline needs none of this — XLA
dispatch is already async and the fused SPMD steps are one program —
so this exists for the surfaces that stay host-side: partition-block
I/O, rpc fan-out (cold fetchers, producer control), channel prefetch.
A thread pool with a bounded in-flight window gives the same
``add_task``/``run_task``/``wait_all`` contract without an asyncio
dependency.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional


class ConcurrentEventLoop:
  """Reference event_loop.py:39-102 surface: submit up to
  ``concurrency`` tasks in flight; ``add_task`` blocks when the window
  is full (the reference's BoundedSemaphore backpressure), ``run_task``
  executes synchronously through the same window, ``wait_all`` joins
  every outstanding task (re-raising the first failure)."""

  def __init__(self, concurrency: int = 32):
    assert concurrency > 0
    self._sem = threading.BoundedSemaphore(concurrency)
    # per-instance prefix: nested submission to THIS loop deadlocks and
    # is rejected below; submission to a sibling loop stays legal
    self._thread_prefix = f'glt-evloop-{id(self):x}'
    self._pool = ThreadPoolExecutor(max_workers=concurrency,
                                    thread_name_prefix=self._thread_prefix)
    self._pending: List[Future] = []
    self._lock = threading.Lock()

  def _wrap(self, fn: Callable, args, kwargs, callback):
    try:
      result = fn(*args, **kwargs)
      # the callback runs INSIDE the worker so its exceptions land in
      # the future (add_done_callback would swallow them into the
      # executor's logger) and only a successful task invokes it
      if callback is not None:
        callback(result)
      return result
    finally:
      self._sem.release()

  def add_task(self, fn: Callable, *args,
               callback: Optional[Callable] = None, **kwargs) -> Future:
    """Submit; blocks while ``concurrency`` tasks are in flight.
    ``callback`` (if given) receives the result on success, running on
    the worker thread (its exceptions surface through the future).

    Tasks must NOT submit nested tasks through the same loop: with the
    window full, the submitting worker would block on the semaphore it
    can only release by finishing (and a fixed-size pool can deadlock
    the same way on result()); this raises instead of deadlocking.
    Use a second ConcurrentEventLoop for a nested stage.
    """
    if threading.current_thread().name.startswith(self._thread_prefix):
      raise RuntimeError(
          'nested add_task from inside a ConcurrentEventLoop task '
          'would deadlock under backpressure; use a separate loop for '
          'the nested stage')
    self._sem.acquire()
    fut = self._pool.submit(self._wrap, fn, args, kwargs, callback)
    with self._lock:
      self._pending.append(fut)
    return fut

  def run_task(self, fn: Callable, *args, **kwargs):
    """Synchronous execution through the same backpressure window.
    A failure raises HERE and is consumed — ``wait_all`` will not
    re-raise it a second time."""
    fut = self.add_task(fn, *args, **kwargs)
    try:
      return fut.result()
    finally:
      with self._lock:
        if fut in self._pending:
          self._pending.remove(fut)

  def wait_all(self) -> None:
    """Join every outstanding task; re-raises the first failure."""
    while True:
      with self._lock:
        if not self._pending:
          return
        pending, self._pending = self._pending, []
      for f in pending:
        f.result()

  def shutdown(self) -> None:
    self.wait_all()
    self._pool.shutdown(wait=True)

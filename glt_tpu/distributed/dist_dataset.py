"""DistDataset — a Dataset plus partition metadata.

Reference: graphlearn_torch/python/distributed/dist_dataset.py:30-318.
Holds the local partition's graph/features, the node/edge partition
books, and the *feature* partition books (rewritten when hot-cache rows
are concatenated in front, reference dist_dataset.py:85-181 +
partition/base.py:866-907). ``load()`` reads the on-disk layout written
by glt_tpu.partition.
"""
from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

from ..data import Dataset, Feature
from ..partition import (
    PartitionBook, cat_feature_cache, load_partition,
)
from ..typing import EdgeType, GraphMode, NodeType
from ..utils import as_numpy


class DistDataset(Dataset):
  def __init__(self,
               num_partitions: int = 1,
               partition_idx: int = 0,
               graph=None, node_features=None, edge_features=None,
               node_labels=None, edge_dir: str = 'out',
               node_pb: Union[PartitionBook, Dict, None] = None,
               edge_pb: Union[PartitionBook, Dict, None] = None,
               node_feat_pb=None, edge_feat_pb=None):
    super().__init__(graph, node_features, edge_features, node_labels,
                     edge_dir)
    self.num_partitions = int(num_partitions)
    self.partition_idx = int(partition_idx)
    self.node_pb = node_pb
    self.edge_pb = edge_pb
    #: feature PBs differ from graph PBs once hot rows are cached locally
    self.node_feat_pb = node_feat_pb
    self.edge_feat_pb = edge_feat_pb

  def load(self, root_dir: str, partition_idx: int,
           graph_mode: Union[str, GraphMode] = GraphMode.HBM,
           feature_dtype=None,
           whole_node_label_file: Optional[Union[str, Dict]] = None,
           device=None) -> 'DistDataset':
    """Load one partition from the on-disk layout (reference
    dist_dataset.py:85-181): build the local Graph from this partition's
    edges, concat cached features, and rewrite the feature PBs."""
    meta, graph, nfeat, efeat, node_pb, edge_pb = load_partition(
        root_dir, partition_idx)
    self.num_partitions = meta['num_parts']
    self.partition_idx = partition_idx
    self.edge_dir = meta.get('edge_dir', self.edge_dir)
    self.node_pb = node_pb
    self.edge_pb = edge_pb

    hetero = meta['data_cls'] == 'hetero'
    if hetero:
      edge_index = {e: g.edge_index for e, g in graph.items()}
      edge_ids = {e: g.eids for e, g in graph.items()}
      weights = {e: g.weights for e, g in graph.items()
                 if g.weights is not None}
      num_nodes = {nt: pb.table.shape[0] for nt, pb in node_pb.items()}
      self.init_graph(edge_index=edge_index, edge_ids=edge_ids,
                      edge_weights=weights or None, num_nodes=num_nodes,
                      graph_mode=graph_mode, device=device)
      if nfeat:
        self.node_features = {}
        self.node_feat_pb = {}
        for nt, f in nfeat.items():
          feats, ids, id2index, pb2 = cat_feature_cache(
              partition_idx, f, node_pb[nt])
          self.node_features[nt] = Feature(
              feats, id2index=id2index, dtype=feature_dtype,
              device=device)
          self.node_feat_pb[nt] = pb2
      if efeat:
        self.edge_features = {}
        self.edge_feat_pb = {}
        for e, f in efeat.items():
          feats, ids, id2index, pb2 = cat_feature_cache(
              partition_idx, f, edge_pb[e])
          self.edge_features[e] = Feature(
              feats, id2index=id2index, dtype=feature_dtype,
              device=device)
          self.edge_feat_pb[e] = pb2
    else:
      self.init_graph(edge_index=graph.edge_index, edge_ids=graph.eids,
                      edge_weights=graph.weights,
                      num_nodes=node_pb.table.shape[0],
                      graph_mode=graph_mode, device=device)
      if nfeat is not None:
        feats, ids, id2index, pb2 = cat_feature_cache(
            partition_idx, nfeat, node_pb)
        self.node_features = Feature(feats, id2index=id2index,
                                     dtype=feature_dtype, device=device)
        self.node_feat_pb = pb2
      if efeat is not None:
        feats, ids, id2index, pb2 = cat_feature_cache(
            partition_idx, efeat, edge_pb)
        self.edge_features = Feature(feats, id2index=id2index,
                                     dtype=feature_dtype, device=device)
        self.edge_feat_pb = pb2

    if whole_node_label_file is not None:
      if isinstance(whole_node_label_file, dict):
        self.init_node_labels({nt: np.load(p) for nt, p
                               in whole_node_label_file.items()})
      else:
        self.init_node_labels(np.load(whole_node_label_file))
    return self

  def get_node_feat_pb(self, ntype: Optional[NodeType] = None):
    pb = self.node_feat_pb if self.node_feat_pb is not None \
        else self.node_pb
    if isinstance(pb, dict) and ntype is not None:
      return pb[ntype]
    return pb

  def get_node_pb(self, ntype: Optional[NodeType] = None):
    if isinstance(self.node_pb, dict) and ntype is not None:
      return self.node_pb[ntype]
    return self.node_pb

  def get_edge_feat_pb(self, etype=None):
    """Edge-feature routing book (reference dist_dataset.py exposes the
    same beside the node book; used by the edge DistFeature)."""
    pb = self.edge_feat_pb if self.edge_feat_pb is not None \
        else self.edge_pb
    if isinstance(pb, dict) and etype is not None:
      return pb[etype]
    return pb


class DistTableDataset(DistDataset):
  """Distributed table loading (reference
  distributed/dist_table_dataset.py:149): each rank streams its table
  slice through readers, then partitions online via
  DistRandomPartitioner. Thin composition over TableDataset readers."""

  def load_tables(self, edge_reader, node_reader, rank: int,
                  world_size: int, num_nodes: int, output_dir: str,
                  edge_id_offset: int = 0,
                  master_addr: str = '127.0.0.1',
                  master_port: int = 30800,
                  peer_addrs=None) -> 'DistTableDataset':
    """Stream this rank's table slices and partition online.

    Readers feed RAW slices (no densification): edge records become this
    rank's edge slice with GLOBAL edge ids ``edge_id_offset + local
    position`` (ranks must pass disjoint offsets, e.g. exclusive prefix
    sums of their row counts — the reference's table sharding gives each
    worker a disjoint row range the same way); node records contribute
    exactly the (ids, rows) the reader produced.
    """
    from .dist_random_partitioner import DistTableRandomPartitioner
    partitioner = DistTableRandomPartitioner(
        output_dir, rank=rank, world_size=world_size,
        num_nodes=num_nodes, edge_reader=edge_reader,
        node_reader=node_reader, edge_id_offset=edge_id_offset,
        master_addr=master_addr, master_port=master_port,
        peer_addrs=peer_addrs)
    try:
      partitioner.partition()
    finally:
      partitioner.shutdown()
    return self.load(output_dir, rank)

"""Distributed strict negative sampling.

Reference behavior: the native negative samplers reject proposals that
are existing edges via binary search over the local CSR
(random_negative_sampler.cu:37-54); in distributed deployments the
reference checks against each worker's local portion. The TPU version is
*globally* strict: each proposed (src, dst) pair is routed to src's
owning partition with the bucket/all_to_all pattern, membership-tested
against the owner's sorted local adjacency (edge_in_csr), and the
verdict routed back — so a negative is rejected if the edge exists
anywhere in the partitioned graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.negative import edge_in_csr
from ..parallel.collectives import (
    all_to_all, bucket_by_owner, bucket_payload, unbucket,
)
from .dist_graph import DistGraph


def make_dist_edge_membership(graph_shards, num_nodes: int, n_parts: int,
                              rows_max: int, axis: str):
  """In-shard closure: (rows, cols, valid) [B] global pairs ->
  bool [B] (does the edge exist in the partitioned graph)."""
  indptr = graph_shards['indptr']
  indices = graph_shards['indices']
  local_row = graph_shards['local_row']
  node_pb = graph_shards['node_pb']

  def member(rows, cols, valid):
    owner = jnp.take(node_pb, jnp.clip(rows, 0, num_nodes - 1),
                     mode='clip')
    owner = jnp.where(valid, owner, n_parts)
    req_rows, meta = bucket_by_owner(rows.astype(jnp.int32), owner,
                                     n_parts)
    req_cols = bucket_payload(cols.astype(jnp.int32), meta, n_parts,
                              fill_value=-1)
    rows_in = all_to_all(req_rows, axis).reshape(-1)
    cols_in = all_to_all(req_cols, axis).reshape(-1)
    lrow = jnp.take(local_row, jnp.clip(rows_in, 0, num_nodes - 1),
                    mode='clip')
    ok = (rows_in >= 0) & (lrow >= 0) & (cols_in >= 0)
    exists = edge_in_csr(indptr, indices,
                         jnp.clip(lrow, 0, rows_max - 1), cols_in)
    exists = exists & ok
    resp = all_to_all(exists.reshape(n_parts, -1), axis)
    return unbucket(resp, meta, n_parts, invalid_value=False)

  return member


class DistRandomNegativeSampler:
  """Globally-strict negative pairs over a DistGraph: per-device
  proposals, all-trials-at-once collective rejection, padding mode —
  the distributed analogue of ops.negative.random_negative_sample."""

  def __init__(self, dist_graph: DistGraph, trials_num: int = 5,
               padding: bool = True):
    self.g = dist_graph
    self.trials = max(int(trials_num), 1)
    self.padding = padding
    self.mesh = dist_graph.mesh
    self.axis = dist_graph.axis
    self._fn_cache = {}

  def _build(self, req_num: int):
    g = self.g
    t = self.trials
    n_parts = g.num_partitions
    axis = self.axis
    padding = self.padding

    def device_fn(indptr, indices, local_row, node_pb, key, src_pool):
      shards = dict(indptr=indptr[0], indices=indices[0],
                    local_row=local_row[0], node_pb=node_pb)
      member = make_dist_edge_membership(shards, g.num_nodes, n_parts,
                                         g.max_rows, axis)
      my_key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      kr, kc = jax.random.split(my_key)
      if src_pool is None:
        prop_r = jax.random.randint(kr, (t, req_num), 0, g.num_nodes,
                                    dtype=jnp.int32)
      else:
        # per-source mode: rows are the caller's fixed sources
        prop_r = jnp.broadcast_to(src_pool[0].astype(jnp.int32),
                                  (t, req_num))
      prop_c = jax.random.randint(kc, (t, req_num), 0, g.num_nodes,
                                  dtype=jnp.int32)
      # the store's row axis is src for edge_dir='out' and dst for 'in';
      # proposals are (src, dst) pairs, so membership queries swap on 'in'
      # (single-device parity: sampler/negative_sampler.py edge_dir swap)
      if g.edge_dir == 'in':
        q_rows, q_cols = prop_c, prop_r
      else:
        q_rows, q_cols = prop_r, prop_c
      exists = member(q_rows.reshape(-1), q_cols.reshape(-1),
                      jnp.ones(t * req_num, bool)).reshape(t, req_num)
      ok = ~exists
      first = jnp.argmax(ok, axis=0)
      any_ok = jnp.any(ok, axis=0)
      sel_r = jnp.take_along_axis(prop_r, first[None, :], axis=0)[0]
      sel_c = jnp.take_along_axis(prop_c, first[None, :], axis=0)[0]
      if padding:
        rows = jnp.where(any_ok, sel_r, prop_r[-1])
        cols = jnp.where(any_ok, sel_c, prop_c[-1])
        mask = jnp.ones((req_num,), bool)
      else:
        rows, cols, mask = sel_r, sel_c, any_ok
      return rows[None], cols[None], mask[None]

    sp = P(self.axis)

    def make(with_src):
      specs = (sp, sp, sp, P(), sp, sp if with_src else None)
      fn = jax.shard_map(
          device_fn, mesh=self.mesh,
          in_specs=specs, out_specs=(sp, sp, sp), check_vma=False)

      jit_fn = jax.jit(fn)

      def step(key, src_pool=None):
        n_dev = self.mesh.shape[self.axis]
        keys = jax.random.split(key, n_dev)
        # arrays passed as args: safe for multi-host global arrays
        return jit_fn(g.indptr, g.indices, g.local_row, g.node_pb, keys,
                      src_pool)
      return step
    return make(False), make(True)

  def _fns(self, req_num: int):
    if req_num not in self._fn_cache:
      self._fn_cache[req_num] = self._build(req_num)
    return self._fn_cache[req_num]

  def sample(self, req_num_per_device: int, key=None):
    """Returns (rows, cols, mask) each [P, req] — per-device negative
    (src, dst) pairs, globally strict."""
    if key is None:
      from ..utils.rng import RandomSeedManager
      key = RandomSeedManager.getInstance().nextKey()
    free_fn, _ = self._fns(req_num_per_device)
    return free_fn(key)

  def sample_dst(self, src_per_device, key=None):
    """Per-source strict destinations (triplet mode): for each given
    src, draw dsts until (src, dst) is not an edge anywhere. Returns
    (rows, cols, mask) with rows == the given sources."""
    src_per_device = jnp.asarray(np.asarray(src_per_device), jnp.int32)
    if key is None:
      from ..utils.rng import RandomSeedManager
      key = RandomSeedManager.getInstance().nextKey()
    _, src_fn = self._fns(src_per_device.shape[1])
    from jax.sharding import NamedSharding
    shard = NamedSharding(self.mesh, P(self.axis))
    return src_fn(key, jax.device_put(src_per_device, shard))

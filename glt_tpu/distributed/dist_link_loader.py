"""DistLinkNeighborLoader — edge-seeded loading over the SPMD sampler.

Reference: graphlearn_torch/python/distributed/dist_link_neighbor_loader.py
(160): per-rank edge seed batches, negative sampling, endpoint
neighborhood expansion through the distributed engine, edge_label_index
metadata. TPU formulation: each device seeds the concatenated endpoint
list of its edge batch (positives + uniformly drawn negatives) into the
collective sampler; the dense inducer's first-occurrence labels give
edge_label_index per device, exactly as the single-device link path.

Negative sampling note: non-strict negatives are uniform global pairs;
``NegativeSampling(strict=True)`` routes proposals through the globally
strict collective membership check (DistRandomNegativeSampler) — strict
across ALL partitions, which the reference's local-portion check is not.
"""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

import jax
import numpy as np

from ..sampler.base import NegativeSampling
from ..utils import as_numpy
from .dist_feature import DistFeature
from .dist_graph import DistGraph
from .dist_neighbor_sampler import DistNeighborSampler


class DistLinkNeighborLoader:
  """Args:
    dist_graph / dist_feature: the sharded stores.
    num_neighbors: fanouts.
    edge_label_index_per_device: list of P [2, E_p] arrays — each
      device's edge seed pool (original (src, dst) orientation).
    neg_sampling: binary or triplet (non-strict).
    batch_size: positive edges per device per step.
  """

  def __init__(self, dist_graph: DistGraph,
               num_neighbors: Sequence[int],
               edge_label_index_per_device,
               dist_feature: Optional[DistFeature] = None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 256,
               shuffle: bool = False,
               drop_last: bool = False,
               seed: Optional[int] = None,
               rng: Optional[np.random.Generator] = None,
               edge_feature: Optional[DistFeature] = None,
               with_edge: bool = False):
    self.g = dist_graph
    self.n_dev = dist_graph.mesh.shape[dist_graph.axis]
    self.edges = [as_numpy(e).astype(np.int64)
                  for e in edge_label_index_per_device]
    assert len(self.edges) == self.n_dev
    self.neg_sampling = NegativeSampling.cast(neg_sampling)
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.rng = rng or np.random.default_rng(seed or 0)
    num_neg = (self.neg_sampling.sample_size(self.batch_size)
               if self.neg_sampling else 0)
    if self.neg_sampling and self.neg_sampling.is_binary():
      self.seeds_per_device = 2 * (self.batch_size + num_neg)
    elif self.neg_sampling:  # triplet
      self.seeds_per_device = 2 * self.batch_size + num_neg
    else:
      self.seeds_per_device = 2 * self.batch_size
    self.num_neg = num_neg
    self.sampler = DistNeighborSampler(
        dist_graph, num_neighbors,
        with_edge=with_edge or edge_feature is not None, seed=seed)
    self.edge_feature = edge_feature
    self._strict_neg = None
    if self.neg_sampling and self.neg_sampling.strict and num_neg:
      from .dist_negative import DistRandomNegativeSampler
      self._strict_neg = DistRandomNegativeSampler(
          dist_graph, trials_num=5, padding=True)
    # reproducible negative stream derived from the loader's seed
    from ..utils.rng import make_key
    self._neg_key = make_key(seed if seed is not None else 0)
    self.feature = dist_feature

  def __len__(self):
    n = min(e.shape[1] for e in self.edges)
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def _strict_negatives(self, it: int, srcs=None):
    """Binary mode: free strict pairs. Triplet mode: strict dsts for
    the batch's OWN sources (membership tested on the emitted pairs).
    Keys derive from the loader seed + iteration (reproducible)."""
    if self._strict_neg is None:
      return None, None
    key = jax.random.fold_in(self._neg_key, it)
    if self.neg_sampling.is_binary():
      rows, cols, _ = self._strict_neg.sample(self.num_neg, key=key)
      return np.asarray(rows), np.asarray(cols)
    rows, cols, _ = self._strict_neg.sample_dst(srcs, key=key)
    return np.asarray(rows), np.asarray(cols)

  def _make_seeds(self, lo: int, orders, neg_rows=None,
                  neg_cols=None) -> tuple:
    bs, num_neg = self.batch_size, self.num_neg
    seeds = np.zeros((self.n_dev, self.seeds_per_device), np.int64)
    n_valid = np.zeros(self.n_dev, np.int32)
    n_pos = np.zeros(self.n_dev, np.int32)
    for p in range(self.n_dev):
      sel = orders[p][lo:lo + bs]
      k = sel.shape[0]
      if k == 0:
        continue
      src = self.edges[p][0][sel]
      dst = self.edges[p][1][sel]
      if k < bs:  # pad with the last edge, mask via n_pos
        pad = np.full(bs - k, sel[-1])
        src = np.concatenate([src, self.edges[p][0][pad]])
        dst = np.concatenate([dst, self.edges[p][1][pad]])
      if self.neg_sampling and self.neg_sampling.is_binary():
        if neg_rows is not None:
          ns, nd = neg_rows[p], neg_cols[p]
        else:
          ns = self.rng.integers(0, self.g.num_nodes, num_neg)
          nd = self.rng.integers(0, self.g.num_nodes, num_neg)
        parts = [np.concatenate([src, ns]), np.concatenate([dst, nd])]
      elif self.neg_sampling:
        nd = (neg_cols[p] if neg_cols is not None
              else self.rng.integers(0, self.g.num_nodes, num_neg))
        parts = [src, np.concatenate([dst, nd])]
      else:
        parts = [src, dst]
      seeds[p] = np.concatenate(parts)
      n_valid[p] = self.seeds_per_device
      n_pos[p] = k
    return seeds, n_valid, n_pos

  def __iter__(self) -> Iterator[dict]:
    orders = [(self.rng.permutation(e.shape[1]) if self.shuffle
               else np.arange(e.shape[1])) for e in self.edges]
    for it in range(len(self)):
      lo = it * self.batch_size
      neg_rows = neg_cols = None
      if self._strict_neg is not None:
        srcs = None
        if self.neg_sampling.is_triplet():
          # per-positive sources, tiled to the negative amount
          amount = self.num_neg // max(self.batch_size, 1)
          srcs = np.zeros((self.n_dev, self.num_neg), np.int64)
          for p in range(self.n_dev):
            sel = orders[p][lo:lo + self.batch_size]
            if sel.shape[0] == 0:
              continue
            s = self.edges[p][0][sel]
            if s.shape[0] < self.batch_size:
              s = np.concatenate(
                  [s, np.full(self.batch_size - s.shape[0], s[-1])])
            # lane layout must match dst_neg_index's [bs, amount]
            # reshape: amount consecutive lanes per source (repeat,
            # NOT tile — tiling paired src i's negatives with src
            # i*amount//bs and emitted real edges as "negatives")
            srcs[p] = np.repeat(s, max(amount, 1))[:self.num_neg]
        neg_rows, neg_cols = self._strict_negatives(it, srcs)
      seeds, n_valid, n_pos = self._make_seeds(lo, orders, neg_rows,
                                               neg_cols)
      out = self.sampler.sample_from_nodes(seeds, n_valid)
      bs, num_neg = self.batch_size, self.num_neg
      inv = np.asarray(out['seed_labels'])      # [P, seeds_per_device]
      if self.neg_sampling is None or self.neg_sampling.is_binary():
        half = bs + (num_neg if self.neg_sampling else 0)
        out['edge_label_index'] = np.stack(
            [inv[:, :half], inv[:, half:]], axis=1)   # [P, 2, half]
        label = np.zeros((self.n_dev, half), np.float32)
        label[:, :bs] = 1.0
        out['edge_label'] = label
      else:
        out['src_index'] = inv[:, :bs]
        out['dst_pos_index'] = inv[:, bs:2 * bs]
        out['dst_neg_index'] = inv[:, 2 * bs:].reshape(
            self.n_dev, bs, -1) if num_neg // max(bs, 1) > 1 \
            else inv[:, 2 * bs:]
      if self.feature is not None:
        import jax.numpy as jnp
        node = out['node'].reshape(-1)
        valid = (jnp.arange(out['node'].shape[1])[None, :]
                 < out['node_count'][:, None]).reshape(-1)
        x = self.feature.lookup(jnp.maximum(node, 0), valid)
        out['x'] = x.reshape(out['node'].shape + (-1,))
      if self.edge_feature is not None and 'edge' in out:
        self.edge_feature.collate_edge_attr(out)
      out['n_pos'] = n_pos
      yield out

"""Sampling worker options (reference distributed/dist_options.py:26-292).

Three deployment modes:
  * Collocated — sampling inline in the training process/program.
  * Mp — N CPU sampling worker subprocesses streaming through the native
    shm channel (the reference's spawn+shm design; on TPU this is the
    host-CPU-samples / chip-trains split that hides sampling latency).
  * Remote — sampling runs inside server processes (server-client mode).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Union


@dataclasses.dataclass
class _BasicDistSamplingWorkerOptions:
  num_workers: int = 1
  worker_concurrency: int = 4            # API parity; XLA pipelines instead
  master_addr: Optional[str] = None
  master_port: Optional[int] = None
  rpc_timeout: float = 180.0


@dataclasses.dataclass
class CollocatedDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Reference dist_options.py:119-147."""
  num_workers: int = 1


@dataclasses.dataclass
class MpDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Reference dist_options.py:149-208."""
  channel_capacity_bytes: int = 256 * 1024 * 1024
  pin_memory: bool = False               # parity; device_put at consumer
  use_shm: bool = True                   # False -> mp.Queue fallback


@dataclasses.dataclass
class RemoteDistSamplingWorkerOptions(_BasicDistSamplingWorkerOptions):
  """Reference dist_options.py:210-292.

  ``degrade_on_server_failure``: when a server's connection is lost
  past the rpc retry budget (or its circuit is open), the loader logs
  the dropout, records it in the fabric metrics/health, and finishes
  the epoch with the surviving servers instead of raising — the
  degradation tier docs/fault_tolerance.md documents. Set False for
  the legacy fail-stop behavior (the error propagates out of
  ``recv``)."""
  server_rank: Union[int, List[int], None] = None
  buffer_capacity_bytes: int = 256 * 1024 * 1024
  prefetch_size: int = 4
  worker_key: str = 'default'
  degrade_on_server_failure: bool = True

"""DistNeighborSampler — multi-hop sampling over sharded topology.

Reference: graphlearn_torch/python/distributed/dist_neighbor_sampler.py
(96-807): an asyncio engine that splits each hop's frontier by partition
book, samples locally, RPCs remote partitions, and stitches
(_sample_one_hop, :616-687). The TPU-native design collapses all of that
into collectives (SURVEY.md §7 "One SPMD program instead of rpc actors"):

    owner = node_pb[frontier]            # the PB routing
    all_to_all(requests)                 # the rpc fan-out
    local Pallas/XLA sample on each owner
    all_to_all(responses)                # the rpc returns
    positional unbucket                  # the stitch

and the hop loop + dedup run unchanged from ops.pipeline — the same
`multihop_sample` the single-device engine uses, with the one-hop
function swapped for the collective version. No event loop, no
concurrency semaphore: latency hiding is XLA's async collectives.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.pipeline import edge_hop_offsets, multihop_sample
from ..ops.sample import sample_neighbors
from ..ops.pipeline import make_dedup_tables
from ..parallel.collectives import all_to_all, bucket_by_owner, unbucket
from ..utils import as_numpy
from ..utils.rng import RandomSeedManager
from .dist_graph import DistGraph


def make_dist_one_hop(graph_shards: Dict[str, jax.Array], num_nodes: int,
                      n_parts: int, rows_max: int, axis: str,
                      with_weight: bool = False,
                      max_weighted_degree: int = 0):
  """Build the in-shard one-hop closure over sharded CSR blocks.

  graph_shards: dict with this device's 'indptr' [R+1], 'indices' [E],
  'edge_ids' [E], 'local_row' [N], replicated 'node_pb' [N] and (for the
  weighted path) 'edge_weights' [E].
  """
  indptr = graph_shards['indptr']
  indices = graph_shards['indices']
  eids = graph_shards['edge_ids']
  local_row = graph_shards['local_row']
  node_pb = graph_shards['node_pb']
  weights = graph_shards.get('edge_weights')

  def one_hop(ids, fanout, key, mask):
    f = ids.shape[0]
    width = abs(fanout)  # negative = full-neighborhood hop, window |k|
    owner = jnp.take(node_pb, jnp.clip(ids, 0, num_nodes - 1),
                     mode='clip')
    owner = jnp.where(mask, owner, n_parts)
    req, meta = bucket_by_owner(ids.astype(jnp.int32), owner, n_parts)
    req_in = all_to_all(req, axis)                       # [P, F]
    flat = req_in.reshape(-1)
    lrow = jnp.take(local_row, jnp.clip(flat, 0, num_nodes - 1),
                    mode='clip')
    ok = (flat >= 0) & (lrow >= 0)
    # every device serves with the same folded key stream: fold by the
    # serving device so remote requests get independent randomness
    serve_key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    if fanout < 0:
      from ..ops.sample import sample_full_neighbors
      out = sample_full_neighbors(
          indptr, indices, jnp.clip(lrow, 0, rows_max - 1), width,
          seed_mask=ok, edge_ids=eids)
    elif with_weight and weights is not None:
      from ..ops.sample import sample_neighbors_weighted
      out = sample_neighbors_weighted(
          indptr, indices, weights, jnp.clip(lrow, 0, rows_max - 1),
          fanout, serve_key,
          max_degree=max(max_weighted_degree, fanout),
          seed_mask=ok, edge_ids=eids)
    else:
      out = sample_neighbors(indptr, indices,
                             jnp.clip(lrow, 0, rows_max - 1), fanout,
                             serve_key, seed_mask=ok, edge_ids=eids)
    resp_nbrs = all_to_all(out.nbrs.reshape(n_parts, f, width), axis)
    resp_mask = all_to_all(out.mask.reshape(n_parts, f, width), axis)
    resp_eids = all_to_all(out.eids.reshape(n_parts, f, width), axis)
    nbrs = unbucket(resp_nbrs, meta, n_parts)
    nmask = unbucket(resp_mask, meta, n_parts, invalid_value=False)
    out_eids = unbucket(resp_eids, meta, n_parts, invalid_value=-1)
    from ..ops.sample import NeighborOutput
    return NeighborOutput(nbrs=nbrs, mask=nmask & mask[:, None],
                          eids=out_eids)

  return one_hop


class DistNeighborSampler:
  """Drives SPMD sampling over a DistGraph; one seed batch per device.

  The jitted program takes [P * B] shard-major seeds and returns stacked
  per-device SamplerOutput payloads [P, ...].
  """

  def __init__(self, dist_graph: DistGraph, num_neighbors: Sequence[int],
               with_edge: bool = False, with_weight: bool = False,
               max_weighted_degree: Optional[int] = None,
               seed: Optional[int] = None,
               full_neighbor_cap: Optional[int] = None):
    self.g = dist_graph
    self.num_neighbors = []
    for f in num_neighbors:
      f = int(f)
      if f == -1:  # full neighborhood: resolve to a static -window
        cap = full_neighbor_cap or getattr(dist_graph, 'max_degree', 0)
        assert cap > 0, ('fanout=-1 needs full_neighbor_cap or a '
                         'DistGraph with a known max_degree')
        f = -int(cap)
      else:
        assert f > 0, f'fanout must be positive or -1, got {f}'
      self.num_neighbors.append(f)
    self.with_edge = with_edge
    self.with_weight = with_weight and dist_graph.edge_weights is not None
    self.max_weighted_degree = (max_weighted_degree
                                or getattr(dist_graph, 'max_degree', 1))
    self.mesh = dist_graph.mesh
    self.axis = dist_graph.axis
    from ..utils.rng import make_key
    self._base_key = make_key(
        seed if seed is not None
        else RandomSeedManager.getInstance().getSeed())
    self._step = 0
    self._fn_cache = {}
    n_dev = self.mesh.shape[self.axis]
    table, scratch = make_dedup_tables(dist_graph.num_nodes)
    shard = NamedSharding(self.mesh, P(self.axis))
    self.tables = jax.device_put(
        jnp.broadcast_to(table, (n_dev,) + table.shape), shard)
    self.scratches = jax.device_put(
        jnp.broadcast_to(scratch, (n_dev,) + scratch.shape), shard)

  def _next_key(self):
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _build(self, batch_size: int):
    g = self.g
    n_parts = g.num_partitions
    axis = self.axis
    fanouts = self.num_neighbors
    with_edge = self.with_edge

    def device_fn(indptr, indices, eids, weights, local_row, node_pb,
                  seeds, n_valid, key, table, scratch):
      shards = dict(indptr=indptr[0], indices=indices[0],
                    edge_ids=eids[0], local_row=local_row[0],
                    node_pb=node_pb)
      if weights is not None:
        shards['edge_weights'] = weights[0]
      one_hop = make_dist_one_hop(
          shards, g.num_nodes, n_parts, g.max_rows, axis,
          with_weight=self.with_weight,
          max_weighted_degree=self.max_weighted_degree)
      my_key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      out, table_o, scratch_o = multihop_sample(
          one_hop, seeds, n_valid[0], fanouts, my_key, table[0],
          scratch[0], with_edge=with_edge)
      out = {k: v[None] for k, v in out.items()}
      return out, table_o[None], scratch_o[None]

    sp = P(self.axis)
    w_spec = sp if g.edge_weights is not None else None
    fn = jax.shard_map(
        device_fn, mesh=self.mesh,
        in_specs=(sp, sp, sp, w_spec, sp, P(), sp, sp, sp, sp, sp),
        out_specs=({k: sp for k in self._out_keys()}, sp, sp),
        check_vma=False)

    import functools
    # graph arrays enter as ARGUMENTS (closure capture would embed them
    # as jit constants, which cannot span processes in multi-host runs)
    @functools.partial(jax.jit, donate_argnums=(9, 10))
    def step(indptr, indices, edge_ids, edge_weights, local_row, node_pb,
             seeds, n_valid, keys, tables, scratches):
      return fn(indptr, indices, edge_ids, edge_weights, local_row,
                node_pb, seeds, n_valid, keys, tables, scratches)

    def run(seeds, n_valid, keys, tables, scratches):
      return step(g.indptr, g.indices, g.edge_ids, g.edge_weights,
                  g.local_row, g.node_pb, seeds, n_valid, keys, tables,
                  scratches)

    return run

  def _out_keys(self):
    keys = ['node', 'node_count', 'row', 'col', 'edge_mask', 'batch',
            'seed_labels', 'seed_count', 'num_sampled_nodes',
            'num_sampled_edges']
    if self.with_edge:
      keys.append('edge')
    return keys

  def sample_from_nodes(self, seeds_per_device: np.ndarray,
                        n_valid_per_device=None, key=None):
    """seeds_per_device: [P, B] or [P*B] shard-major. Returns a dict of
    stacked arrays [P, ...] (one SamplerOutput per device) plus updated
    internal tables."""
    seeds = as_numpy(seeds_per_device)
    n_dev = self.mesh.shape[self.axis]
    if seeds.ndim == 2:
      seeds = seeds.reshape(-1)
    batch_size = seeds.shape[0] // n_dev
    if n_valid_per_device is None:
      n_valid_per_device = np.full(n_dev, batch_size, np.int32)
    if batch_size not in self._fn_cache:
      self._fn_cache[batch_size] = self._build(batch_size)
    if key is None:
      key = self._next_key()
    keys = jax.random.split(key, n_dev)
    shard = NamedSharding(self.mesh, P(self.axis))
    out, self.tables, self.scratches = self._fn_cache[batch_size](
        jax.device_put(jnp.asarray(seeds, jnp.int32), shard),
        jax.device_put(jnp.asarray(n_valid_per_device, jnp.int32), shard),
        keys, self.tables, self.scratches)
    out['edge_hop_offsets'] = edge_hop_offsets(batch_size, fanouts=
                                               self.num_neighbors)
    return out

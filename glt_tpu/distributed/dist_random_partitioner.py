"""Online parallel partitioning — DistRandomPartitioner.

Reference: graphlearn_torch/python/distributed/dist_random_partitioner.py
(539): each rank partitions its *slice* of nodes/edges/features (mod-hash
over its id range), RPC-pushes per-partition payloads to their owners
(DistPartitionManager.process, :88-127), and each owner saves its own
partition locally (rank == partition index). Used when the graph is too
big for one partitioner.

Here the push fabric is the framework's socket RPC: every rank runs an
RpcServer exposing 'push_*' callees; chunked sends (``_partition_by_chunk``
equivalent) with a barrier per phase via the built-in barrier callee.
The same object also works world_size=1 (pure local) for testing.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

from ..channel import pack_message, unpack_message
from ..utils import as_numpy
from .rpc import RpcClient, RpcServer

CHUNK = 2 * 1024 * 1024


class _PartitionBuffer:
  """Accumulates pushed rows for the partition this rank owns
  (the DistPartitionManager analogue)."""

  def __init__(self):
    self.lock = threading.Lock()
    self.edge_chunks: List[np.ndarray] = []     # [3, m] rows/cols/eids
    self.node_feat_chunks: List[np.ndarray] = []
    self.node_id_chunks: List[np.ndarray] = []

  def push_edges(self, payload: bytes) -> bool:
    msg = unpack_message(payload)
    with self.lock:
      self.edge_chunks.append(
          np.stack([msg['rows'], msg['cols'], msg['eids']]))
    return True

  def push_node_feat(self, payload: bytes) -> bool:
    msg = unpack_message(payload)
    with self.lock:
      self.node_id_chunks.append(msg['ids'])
      self.node_feat_chunks.append(msg['feats'])
    return True


class DistRandomPartitioner:
  """Args:
    output_dir: shared filesystem root (every rank writes part{rank}).
    rank / world_size: this rank's identity; rank == partition index.
    num_nodes: global node count.
    edge_slice: this rank's [2, E_r] COO slice + eid_slice global edge
      ids ([E_r]); edges are re-owned by src node (by_src).
    node_ids / node_feat: this rank's feature slice (global ids + rows).
    master_addr / master_port: rpc rendezvous (port + rank per server).
  """

  def __init__(self, output_dir: str, rank: int, world_size: int,
               num_nodes: int, edge_slice, eid_slice,
               node_ids=None, node_feat=None,
               master_addr: str = '127.0.0.1', master_port: int = 30500,
               chunk_size: int = CHUNK, seed: int = 0,
               bind_addr: Optional[str] = None,
               peer_addrs: Optional[List[str]] = None):
    self.output_dir = output_dir
    self.rank = int(rank)
    self.world = int(world_size)
    self.num_nodes = int(num_nodes)
    self.edge_slice = as_numpy(edge_slice)
    self.eid_slice = as_numpy(eid_slice)
    self.node_ids = as_numpy(node_ids)
    self.node_feat = as_numpy(node_feat)
    self.chunk_size = int(chunk_size)
    self.seed = seed
    self.buffer = _PartitionBuffer()
    # default stays loopback-safe (master_addr, typically 127.0.0.1);
    # multi-host deployments pass bind_addr='0.0.0.0' (or the local
    # interface) plus peer_addrs for the other ranks' hosts
    self.server = RpcServer(bind_addr or master_addr,
                            master_port + rank, auto_start=False)
    self.server.register('push_edges', self.buffer.push_edges)
    self.server.register('push_node_feat', self.buffer.push_node_feat)
    self.server.start()  # accept only after all callees exist
    self.peer_addrs = peer_addrs or [master_addr] * world_size
    assert len(self.peer_addrs) == world_size
    self.base_port = master_port
    self._clients: Dict[int, RpcClient] = {}

  def _client(self, peer: int) -> RpcClient:
    if peer not in self._clients:
      self._clients[peer] = RpcClient(self.peer_addrs[peer],
                                      self.base_port + peer)
    return self._clients[peer]

  def _owner_of(self, ids: np.ndarray) -> np.ndarray:
    """Deterministic mod-hash ownership over the whole id space
    (reference _partition_node, :294-330)."""
    rng_mix = (ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
               + np.uint64(self.seed))
    return ((rng_mix >> np.uint64(32)) % np.uint64(self.world)) \
        .astype(np.int32)

  def _push(self, peer: int, method: str, payload: dict) -> None:
    if peer == self.rank:
      getattr(self.buffer, method)(pack_message(payload))
    else:
      self._client(peer).request(method, pack_message(payload))

  def _barrier(self, key: str) -> None:
    self._client(0).request('_barrier', key, self.world)

  def partition(self) -> np.ndarray:
    """Runs all phases; returns the full node partition table."""
    node_pb = self._owner_of(np.arange(self.num_nodes, dtype=np.int64))

    # phase 1: edges by src owner, chunked
    rows, cols = self.edge_slice
    for lo in range(0, rows.shape[0], self.chunk_size):
      hi = min(lo + self.chunk_size, rows.shape[0])
      owner = node_pb[rows[lo:hi]]
      for p in range(self.world):
        sel = np.nonzero(owner == p)[0] + lo
        if sel.size:
          self._push(p, 'push_edges',
                     {'rows': rows[sel], 'cols': cols[sel],
                      'eids': self.eid_slice[sel]})
    self._barrier('edges_done')

    # phase 2: node features by owner. EVERY rank joins the phase barrier
    # (a rank may legitimately hold no feature slice).
    if self.node_ids is not None:
      for lo in range(0, self.node_ids.shape[0], self.chunk_size):
        hi = min(lo + self.chunk_size, self.node_ids.shape[0])
        ids = self.node_ids[lo:hi]
        owner = node_pb[ids]
        for p in range(self.world):
          sel = np.nonzero(owner == p)[0]
          if sel.size:
            self._push(p, 'push_node_feat',
                       {'ids': ids[sel],
                        'feats': self.node_feat[lo:hi][sel]})
    self._barrier('feats_done')

    # phase 3: each rank saves its own partition (rank == partition)
    self._save(node_pb)
    self._barrier('save_done')
    if self.rank == 0:
      self._save_meta(node_pb)
    self._barrier('meta_done')
    return node_pb

  def _save(self, node_pb: np.ndarray) -> None:
    pdir = os.path.join(self.output_dir, f'part{self.rank}')
    os.makedirs(os.path.join(pdir, 'graph'), exist_ok=True)
    if self.buffer.edge_chunks:
      all_e = np.concatenate(self.buffer.edge_chunks, axis=1)
    else:
      all_e = np.zeros((3, 0), np.int64)
    np.savez(os.path.join(pdir, 'graph', 'data.npz'),
             rows=all_e[0], cols=all_e[1], eids=all_e[2])
    if self.buffer.node_feat_chunks:
      ids = np.concatenate(self.buffer.node_id_chunks)
      feats = np.concatenate(self.buffer.node_feat_chunks)
      order = np.argsort(ids)
      os.makedirs(os.path.join(pdir, 'node_feat'), exist_ok=True)
      np.savez(os.path.join(pdir, 'node_feat', 'data.npz'),
               ids=ids[order], feats=feats[order])

  def _save_meta(self, node_pb: np.ndarray) -> None:
    import json
    np.save(os.path.join(self.output_dir, 'node_pb.npy'),
            node_pb.astype(np.int32))
    # assemble the global edge PB from every rank's saved partition (all
    # parts are on the shared filesystem after the 'save_done' barrier) —
    # load_partition requires it
    chunks = []
    for r in range(self.world):
      z = np.load(os.path.join(self.output_dir, f'part{r}', 'graph',
                               'data.npz'))
      chunks.append((z['eids'], r))
    # size by the global id space (ids are disjoint but need not be a
    # compact 0..E-1 range if a rank contributed nothing)
    total = max((int(c[0].max()) + 1 for c in chunks if c[0].size),
                default=0)
    edge_pb = np.zeros(total, np.int32)
    for eids, r in chunks:
      edge_pb[eids] = r
    np.save(os.path.join(self.output_dir, 'edge_pb.npy'), edge_pb)
    with open(os.path.join(self.output_dir, 'META.json'), 'w') as f:
      json.dump({'num_parts': self.world, 'data_cls': 'homo',
                 'edge_dir': 'out', 'edge_assign': 'by_src'}, f)

  def shutdown(self) -> None:
    for c in self._clients.values():
      c.close()
    self.server.stop()


class DistTableRandomPartitioner(DistRandomPartitioner):
  """Online random partitioning fed by TABLE readers (reference
  distributed/dist_table_dataset.py:38 DistTableRandomPartitioner):
  each rank drains its edge/node table slice — records with EXPLICIT
  global node ids, as ODPS/CSV shards deliver them — into the slice
  form the base engine consumes, with global edge ids assigned as
  ``edge_id_offset + local position`` (ranks pass disjoint offsets,
  e.g. exclusive prefix sums of their row counts, mirroring the
  reference's disjoint table row ranges).

  Readers follow glt_tpu.data.table_dataset's protocol: edge readers
  yield (src_ids, dst_ids[, ...]) records, node readers yield
  (node_ids, feature_rows).
  """

  def __init__(self, output_dir: str, rank: int, world_size: int,
               num_nodes: int, edge_reader=None, node_reader=None,
               edge_id_offset: int = 0, **kwargs):
    srcs, dsts = [], []
    for rec in (edge_reader or ()):
      srcs.append(as_numpy(rec[0]).astype(np.int64))
      dsts.append(as_numpy(rec[1]).astype(np.int64))
    src = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
    dst = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
    eids = edge_id_offset + np.arange(src.shape[0], dtype=np.int64)
    ids_l, feats_l = [], []
    for rec in (node_reader or ()):
      ids_l.append(as_numpy(rec[0]).astype(np.int64))
      feats_l.append(as_numpy(rec[1]))
    super().__init__(
        output_dir, rank=rank, world_size=world_size,
        num_nodes=num_nodes, edge_slice=np.stack([src, dst]),
        eid_slice=eids,
        node_ids=np.concatenate(ids_l) if ids_l else None,
        node_feat=np.concatenate(feats_l) if feats_l else None,
        **kwargs)

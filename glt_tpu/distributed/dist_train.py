"""Collocated distributed training: sample + feature exchange + DDP step
as ONE SPMD program over sharded topology and features.

This is the TPU equivalent of the reference's worker-mode deployment
(DistNeighborLoader + MpDistSamplingWorkerOptions + DDP,
examples/distributed/dist_train_sage_supervised.py): what the reference
does with sampling subprocesses, shm channels, rpc feature lookups and a
NCCL allreduce is here a single jitted shard_map step — sampling
collectives, feature all_to_all, gradient pmean all riding ICI.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..loader.transform import Batch
from ..ops.pipeline import edge_hop_offsets, multihop_sample
from ..ops.pipeline import make_dedup_tables
from .dist_feature import DistFeature
from .dist_graph import DistGraph
from .dist_neighbor_sampler import make_dist_one_hop


class DistTrainStep:
  """One-program distributed train step over DistGraph + DistFeature.

  Args:
    dist_graph / dist_feature: the sharded stores (same mesh/axis).
    model: flax module over Batch.
    tx: optax optimizer.
    labels: [N] global labels (replicated; label lookups are cheap).
    fanouts, batch_size_per_device: sampling shape.
    edge_feature: optional edge-feature DistFeature (id space = global
      edge ids); when given, sampling emits eids and the batch carries
      ``edge_attr`` gathered through the same all_to_all path — the
      reference's efeat collate (dist_neighbor_sampler.py:689-807).
  """

  def __init__(self, dist_graph: DistGraph, dist_feature: DistFeature,
               model, tx, labels, fanouts: Sequence[int],
               batch_size_per_device: int,
               edge_feature: Optional[DistFeature] = None):
    from ..parallel.dist_feature import require_device_resident
    require_device_resident(dist_feature, 'DistTrainStep features')
    require_device_resident(edge_feature, 'DistTrainStep edge features')
    self.g = dist_graph
    self.f = dist_feature
    self.ef = edge_feature
    self.model = model
    self.tx = tx
    self.fanouts = list(fanouts)
    self.bs = int(batch_size_per_device)
    self.mesh = dist_graph.mesh
    self.axis = dist_graph.axis
    self.labels = jax.device_put(
        np.asarray(labels), NamedSharding(self.mesh, P()))
    n_dev = self.mesh.shape[self.axis]
    table, scratch = make_dedup_tables(dist_graph.num_nodes)
    shard = NamedSharding(self.mesh, P(self.axis))
    self.tables = jax.device_put(
        jnp.broadcast_to(table, (n_dev,) + table.shape), shard)
    self.scratches = jax.device_put(
        jnp.broadcast_to(scratch, (n_dev,) + scratch.shape), shard)
    self._step_fn = self._build()

  def _dummy_batch(self) -> Batch:
    from ..ops.pipeline import sample_budget
    budget = sample_budget(self.bs, self.fanouts)
    ecap = edge_hop_offsets(self.bs, self.fanouts)[-1]
    return Batch(
        x=jnp.zeros((budget, self.f.feature_dim)),
        row=jnp.zeros((ecap,), jnp.int32),
        col=jnp.zeros((ecap,), jnp.int32),
        edge_mask=jnp.zeros((ecap,), bool),
        node=jnp.zeros((budget,), jnp.int32),
        node_count=jnp.zeros((), jnp.int32),
        y=jnp.zeros((self.bs,), jnp.int32),
        edge=(jnp.zeros((ecap,), jnp.int32)
              if self.ef is not None else None),
        edge_attr=(jnp.zeros((ecap, self.ef.feature_dim))
                   if self.ef is not None else None),
        batch_size=self.bs,
        edge_hop_offsets=tuple(edge_hop_offsets(self.bs, self.fanouts)))

  def init_params(self, key):
    params = self.model.init(key, self._dummy_batch())
    return jax.device_put(params, NamedSharding(self.mesh, P()))

  def _build(self):
    g, f, ef = self.g, self.f, self.ef
    model, tx, axis, bs = self.model, self.tx, self.axis, self.bs
    fanouts = self.fanouts
    offs = tuple(edge_hop_offsets(bs, fanouts))
    n_parts = g.num_partitions
    with_edge = ef is not None

    f_off = f.cold_array is not None
    ef_off = ef is not None and ef.cold_array is not None

    def device_step(params, opt_state, indptr, indices, geids, local_row,
                    node_pb, feats, id2index, feat_pb, labels, seeds,
                    n_valid, key, table, scratch, *rest):
      rest = list(rest)
      fcold = rest.pop(0) if f_off else None
      efeats, eid2index, efeat_pb = \
          (rest[:3] if with_edge else (None,) * 3)
      efcold = rest[3] if ef_off else None
      shards = dict(indptr=indptr[0], indices=indices[0],
                    edge_ids=geids[0], local_row=local_row[0],
                    node_pb=node_pb)
      one_hop = make_dist_one_hop(shards, g.num_nodes, n_parts,
                                  g.max_rows, axis)
      my_key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      out, table_o, scratch_o = multihop_sample(
          one_hop, seeds, n_valid[0], fanouts, my_key, table[0],
          scratch[0], with_edge=with_edge)
      node_valid = jnp.arange(out['node'].shape[0]) < out['node_count']
      x = f.lookup_local(feats[0], id2index[0], feat_pb[0],
                         jnp.maximum(out['node'], 0), node_valid,
                         axis_name=axis,
                         cold_shard=fcold[0] if f_off else None)
      edge_attr = None
      if with_edge:
        # the efeat collate of the reference loop, as one more
        # all_to_all over the sampled global edge ids
        edge_attr = ef.lookup_local(
            efeats[0], eid2index[0], efeat_pb[0],
            jnp.maximum(out['edge'], 0), out['edge_mask'],
            axis_name=axis,
            cold_shard=efcold[0] if ef_off else None)
      y = jnp.take(labels, jnp.maximum(out['batch'], 0)[:bs])
      batch = Batch(x=x, row=out['row'], col=out['col'],
                    edge_mask=out['edge_mask'], node=out['node'],
                    node_count=out['node_count'], y=y, batch_size=bs,
                    edge=out.get('edge'), edge_attr=edge_attr,
                    edge_hop_offsets=offs)

      def loss_fn(p):
        logits = model.apply(p, batch)
        mask = jnp.arange(bs) < n_valid[0]
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, y)
        return (jnp.where(mask, losses, 0).sum()
                / jnp.maximum(mask.sum(), 1))

      loss, grads = jax.value_and_grad(loss_fn)(params)
      grads = jax.lax.pmean(grads, axis)
      loss = jax.lax.pmean(loss, axis)
      updates, opt_state = tx.update(grads, opt_state, params)
      params = optax.apply_updates(params, updates)
      return params, opt_state, table_o[None], scratch_o[None], loss[None]

    sp = P(self.axis)
    extra = ((sp,) if f_off else ()) \
        + ((sp, sp, sp) if with_edge else ()) \
        + ((sp,) if ef_off else ())
    fn = jax.shard_map(
        device_step, mesh=self.mesh,
        in_specs=(P(), P(), sp, sp, sp, sp, P(), sp, sp, sp, P(), sp, sp,
                  sp, sp, sp) + extra,
        out_specs=(P(), P(), sp, sp, sp),
        check_vma=False)

    # global arrays enter as jit ARGUMENTS (closure constants cannot
    # span processes in multi-host runs)
    @functools.partial(jax.jit, donate_argnums=(14, 15))
    def step(params, opt_state, indptr, indices, geids, local_row,
             node_pb, feats, id2index, feat_pb, labels, seeds, n_valid,
             keys, tables, scratches, *eargs):
      return fn(params, opt_state, indptr, indices, geids, local_row,
                node_pb, feats, id2index, feat_pb, labels, seeds,
                n_valid, keys, tables, scratches, *eargs)

    def run(params, opt_state, tables, scratches, seeds, n_valid, keys):
      eargs = ((f.cold_array,) if f_off else ()) \
          + ((ef.array, ef.id2index, ef.feat_pb) if with_edge else ()) \
          + ((ef.cold_array,) if ef_off else ())
      return step(params, opt_state, g.indptr, g.indices, g.edge_ids,
                  g.local_row, g.node_pb, f.array, f.id2index,
                  f.feat_pb, self.labels, seeds, n_valid, keys, tables,
                  scratches, *eargs)

    return run

  def __call__(self, params, opt_state, seeds, n_valid_per_device, key):
    n_dev = self.mesh.shape[self.axis]
    shard = NamedSharding(self.mesh, P(self.axis))
    seeds = jax.device_put(
        jnp.asarray(np.asarray(seeds).reshape(-1), jnp.int32), shard)
    nv = jax.device_put(
        jnp.asarray(n_valid_per_device, jnp.int32), shard)
    keys = jax.random.split(key, n_dev)
    params, opt_state, self.tables, self.scratches, loss = self._step_fn(
        params, opt_state, self.tables, self.scratches, seeds, nv, keys)
    return params, opt_state, loss

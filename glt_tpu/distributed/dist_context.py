"""Distributed role/context management.

Reference: graphlearn_torch/python/distributed/dist_context.py (DistRole
WORKER/SERVER/CLIENT groups with local+global ranks, init_worker_group,
assign_server_by_order). On TPU the process fabric is jax.distributed
(one process per host, all chips visible as jax.devices()), so the
context wraps process_index/process_count when jax.distributed is live
and falls back to explicit ranks for single-host simulation — the same
"multi-process on one host" strategy the reference's tests use.
"""
from __future__ import annotations

import enum
from typing import Optional

import jax


class DistRole(enum.Enum):
  WORKER = 1    # collocated sampling + training (worker mode)
  SERVER = 2    # sampling/feature service (server-client mode)
  CLIENT = 3    # training client


class DistContext:
  def __init__(self, role: DistRole, world_size: int, rank: int,
               group_name: str = 'default',
               global_world_size: Optional[int] = None,
               global_rank: Optional[int] = None):
    self.role = role
    self.world_size = int(world_size)
    self.rank = int(rank)
    self.group_name = group_name
    self.global_world_size = (int(global_world_size)
                              if global_world_size is not None
                              else self.world_size)
    self.global_rank = (int(global_rank) if global_rank is not None
                        else self.rank)

  @property
  def is_worker(self) -> bool:
    return self.role == DistRole.WORKER

  @property
  def is_server(self) -> bool:
    return self.role == DistRole.SERVER

  @property
  def is_client(self) -> bool:
    return self.role == DistRole.CLIENT

  def __repr__(self):
    return (f'DistContext(role={self.role.name}, rank={self.rank}/'
            f'{self.world_size}, group={self.group_name!r})')


_context: Optional[DistContext] = None


def get_context() -> Optional[DistContext]:
  return _context


def init_worker_group(world_size: Optional[int] = None,
                      rank: Optional[int] = None,
                      group_name: str = 'worker') -> DistContext:
  """Reference dist_context.py init_worker_group: establish this process's
  role group. With no explicit ranks, adopt jax's process topology
  (jax.distributed.initialize must have run for true multi-host)."""
  global _context
  if world_size is None or rank is None:
    world_size = jax.process_count()
    rank = jax.process_index()
  _context = DistContext(DistRole.WORKER, world_size, rank, group_name)
  return _context


def init_server_context(num_servers: int, num_clients: int, rank: int,
                        group_name: str = 'server') -> DistContext:
  global _context
  _context = DistContext(
      DistRole.SERVER, num_servers, rank, group_name,
      global_world_size=num_servers + num_clients, global_rank=rank)
  return _context


def init_client_context(num_servers: int, num_clients: int, rank: int,
                        group_name: str = 'client') -> DistContext:
  global _context
  _context = DistContext(
      DistRole.CLIENT, num_clients, rank, group_name,
      global_world_size=num_servers + num_clients,
      global_rank=num_servers + rank)
  return _context


def shutdown() -> None:
  global _context
  _context = None


def assign_server_by_order(client_rank: int, num_servers: int,
                           num_clients: int):
  """Round-robin client -> server mapping (reference
  dist_context.py:174-196)."""
  if num_clients >= num_servers:
    per = num_clients // num_servers
    return [min(client_rank // max(per, 1), num_servers - 1)]
  per = num_servers // num_clients
  lo = client_rank * per
  hi = num_servers if client_rank == num_clients - 1 else lo + per
  return list(range(lo, hi))

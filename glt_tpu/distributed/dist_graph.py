"""DistGraph — partitioned topology as one mesh-sharded SPMD store.

Reference: graphlearn_torch/python/distributed/dist_graph.py:28-124 (local
Graph + partition books, get_node_partitions). The TPU translation packs
every partition's CSR into stacked, padded device arrays sharded over the
mesh axis (device p holds partition p's rows), plus:

  * ``node_pb``    [N] replicated — owner partition per global node id
    (the partition book, dense form)
  * ``local_row``  [P, N] sharded — global id -> local CSR row on its
    owner (-1 elsewhere); this is the id2index the reference builds per
    partition (partition/base.py:903-905), kept dense so the sampling
    kernel can gather it

Padding to the max partition size keeps every shard the same shape —
the SPMD requirement — at the cost of max/mean imbalance, identical to
the reference's per-partition load imbalance.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data import Topology
from ..partition import PartitionBook, RangePartitionBook, \
    TablePartitionBook
from ..typing import GraphPartitionData
from ..utils import as_numpy


def _pb_dense(pb, num_ids: int) -> np.ndarray:
  if isinstance(pb, TablePartitionBook):
    t = pb.table
    if t.shape[0] < num_ids:
      t = np.concatenate(
          [t, np.zeros(num_ids - t.shape[0], t.dtype)])
    return t.astype(np.int32)
  if isinstance(pb, RangePartitionBook):
    return pb[np.arange(num_ids)]
  return as_numpy(pb).astype(np.int32)


class DistGraph:
  """Builds the sharded store from per-partition edge lists.

  Args:
    mesh: mesh whose ``axis`` size equals the partition count.
    num_nodes: global node count (the column/indices id space).
    parts: per-partition GraphPartitionData (edge_index in original
      (src, dst) orientation, matching the partitioner output).
    node_pb: the node partition book.
    edge_dir: 'out' -> CSR over src, 'in' -> CSC over dst.
  """

  def __init__(self, mesh: Mesh, num_nodes: int,
               parts: Sequence[GraphPartitionData],
               node_pb: PartitionBook, edge_dir: str = 'out',
               axis: str = 'data'):
    n_parts = len(parts)
    assert mesh.shape[axis] == n_parts, (
        f'mesh axis size {mesh.shape[axis]} != partitions {n_parts}')

    indptrs, indices_l, eids_l, locals_l, weights_l = [], [], [], [], []
    max_rows, max_edges = 1, 1
    built = []
    has_weights = all(p.weights is not None for p in parts)
    for g in parts:
      topo, local_of = _build_partition_block(
          g, int(num_nodes), edge_dir, with_weights=has_weights)
      built.append((topo, local_of))
      max_rows = max(max_rows, topo.num_rows)
      max_edges = max(max_edges, topo.num_edges)

    max_degree = 1
    for topo, local_of in built:
      ip, ind, eid, w, lo = _pad_block(topo, local_of, max_rows,
                                       max_edges)
      indptrs.append(ip)
      indices_l.append(ind)
      eids_l.append(eid)
      locals_l.append(lo)
      if has_weights:
        weights_l.append(w)
      max_degree = max(max_degree, topo.max_degree)

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    self.indptr = jax.device_put(np.stack(indptrs), shard)    # [P, R+1]
    self.indices = jax.device_put(np.stack(indices_l), shard)  # [P, E]
    self.edge_ids = jax.device_put(np.stack(eids_l), shard)
    self.edge_weights = (jax.device_put(np.stack(weights_l), shard)
                         if has_weights else None)
    self.local_row = jax.device_put(np.stack(locals_l), shard)  # [P, N]
    self.node_pb = jax.device_put(
        _pb_dense(node_pb, int(num_nodes)), repl)               # [N]
    self._finish_init(mesh, axis, num_nodes, edge_dir, n_parts,
                      max_rows, max_edges, max_degree)

  def _finish_init(self, mesh: Mesh, axis: str, num_nodes: int,
                   edge_dir: str, n_parts: int, max_rows: int,
                   max_edges: int, max_degree: int):
    """Non-array state shared by __init__ and the multihost builder.
    ANY new scalar/config field must be set here so alternate builders
    can never miss it."""
    self.mesh = mesh
    self.axis = axis
    self.num_nodes = int(num_nodes)
    self.edge_dir = edge_dir
    self.num_partitions = int(n_parts)
    self.max_rows = int(max_rows)
    self.max_edges = int(max_edges)
    self.max_degree = int(max_degree)

  @classmethod
  def from_dataset_partitions(cls, mesh: Mesh, root_dir: str,
                              edge_dir: str = 'out', axis: str = 'data'):
    """Single-host simulation helper: load every partition from disk
    (the reference test pattern of running all ranks in one host)."""
    from ..partition import load_partition, load_meta
    meta = load_meta(root_dir)
    need = 'by_src' if edge_dir == 'out' else 'by_dst'
    got = meta.get('edge_assign', 'by_src')
    if got != need:
      raise ValueError(
          f'partition was edge-assigned {got!r} but edge_dir='
          f'{edge_dir!r} sampling requires {need!r}')
    parts, node_pb = [], None
    for p in range(meta['num_parts']):
      _, g, _, _, npb, _ = load_partition(root_dir, p)
      parts.append(g)
      node_pb = npb
    num_nodes = node_pb.table.shape[0]
    return cls(mesh, num_nodes, parts, node_pb, edge_dir, axis)


def _build_partition_block(g, num_nodes: int, edge_dir: str,
                           with_weights: bool = False,
                           num_cols: int = None):
  """One partition's padded-ready CSR pieces (pre-padding).

  ``num_nodes`` is the ROW id space; ``num_cols`` defaults to it and
  differs for hetero etype stores (col type's id space)."""
  src, dst = as_numpy(g.edge_index)
  row, col = (src, dst) if edge_dir == 'out' else (dst, src)
  owned = np.unique(row)
  local_of = np.full(num_nodes, -1, np.int32)
  local_of[owned] = np.arange(owned.shape[0], dtype=np.int32)
  topo = Topology(edge_index=np.stack([local_of[row], col]),
                  edge_ids=as_numpy(g.eids),
                  edge_weights=(as_numpy(g.weights) if with_weights
                                else None),
                  layout='CSR',
                  num_rows=owned.shape[0],
                  num_cols=num_nodes if num_cols is None else num_cols)
  return topo, local_of


def _stack_or_empty(parts, width, dtype):
  """Stack this process's blocks; empty [0, width] when it owns none
  (make_array_from_process_local_data still needs the trailing dims)."""
  if parts:
    return np.stack(parts)
  return np.zeros((0, width), dtype)


def _pad_block(topo, local_of, max_rows: int, max_edges: int):
  ip = topo.indptr.astype(np.int32)
  ip = np.concatenate(
      [ip, np.full(max_rows + 1 - ip.shape[0], ip[-1], np.int32)])
  ind = np.concatenate(
      [topo.indices,
       np.zeros(max_edges - topo.num_edges, topo.indices.dtype)])
  eid = np.concatenate(
      [topo.edge_ids.astype(np.int64),
       np.full(max_edges - topo.num_edges, -1, np.int64)])
  w = None
  if topo.edge_weights is not None:
    w = np.concatenate(
        [topo.edge_weights.astype(np.float32),
         np.zeros(max_edges - topo.num_edges, np.float32)])
  return ip, ind, eid, w, local_of


def _assemble_multihost_store(mesh, axis: str, mine, blocks,
                              num_rows_global: int, max_rows: int,
                              max_edges: int, max_degree: int,
                              has_weights: bool, node_pb, n_parts: int,
                              edge_dir: str = 'out') -> 'DistGraph':
  """Shared multihost store assembly (homo builder + one hetero etype):
  pad this process's blocks to the GLOBALLY-AGREED maxima and
  contribute them to the collective sharded stacks. Every process must
  call this with identical maxima/has_weights (agree them with an
  allgather first) — mismatched participation in
  make_array_from_process_local_data hangs the job, which is why this
  code must not be duplicated per builder."""
  import jax
  from ..parallel.multihost import global_from_local
  ips, inds, eids_l, locals_l, weights_l = [], [], [], [], []
  for p in mine:
    topo, local_of = blocks[p]
    ip, ind, eid, w, lo = _pad_block(topo, local_of, max_rows, max_edges)
    ips.append(ip)
    inds.append(ind)
    eids_l.append(eid)
    locals_l.append(lo)
    if has_weights:
      weights_l.append(w)
  store = DistGraph.__new__(DistGraph)
  store._finish_init(mesh, axis, num_rows_global, edge_dir, n_parts,
                     max_rows, max_edges, max_degree)
  store.indptr = global_from_local(
      mesh, _stack_or_empty(ips, max_rows + 1, np.int32), axis)
  store.indices = global_from_local(
      mesh, _stack_or_empty(inds, max_edges, np.int32), axis)
  store.edge_ids = global_from_local(
      mesh, _stack_or_empty(eids_l, max_edges, np.int64), axis)
  store.edge_weights = (global_from_local(
      mesh, _stack_or_empty(weights_l, max_edges, np.float32), axis)
      if has_weights else None)
  store.local_row = global_from_local(
      mesh, _stack_or_empty(locals_l, num_rows_global, np.int32), axis)
  store.node_pb = jax.device_put(
      _pb_dense(node_pb, num_rows_global), NamedSharding(mesh, P()))
  return store


def dist_graph_from_partitions_multihost(mesh, root_dir: str,
                                         edge_dir: str = 'out',
                                         axis: str = 'data') -> DistGraph:
  """Multi-host DistGraph: each process loads ONLY the partitions owned
  by its local devices and contributes its blocks to the global sharded
  arrays (jax.make_array_from_process_local_data via
  parallel.multihost.global_from_local) — no host ever materializes the
  whole graph, the reference's per-rank partition loading discipline.

  Requires jax.distributed to be initialized when process_count > 1.
  """
  import jax
  from ..partition import load_meta, load_partition
  meta = load_meta(root_dir)
  need = 'by_src' if edge_dir == 'out' else 'by_dst'
  got_assign = meta.get('edge_assign', 'by_src')
  if got_assign != need:
    raise ValueError(f'edge_assign {got_assign!r} incompatible with '
                     f'edge_dir {edge_dir!r}')
  devices = mesh.devices.reshape(-1)
  n_parts = devices.shape[0]
  if meta['num_parts'] != n_parts:
    raise ValueError(
        f"mesh has {n_parts} devices but the partition dir holds "
        f"{meta['num_parts']} partitions — they must match")
  mine = [i for i, d in enumerate(devices)
          if d.process_index == jax.process_index()]

  node_pb = None
  blocks = {}
  parts_raw = {}
  # rows, edges, degree maxima + a weights-presence bit: ALL of these
  # steer collective array construction, so every process must agree —
  # a shard-less process in particular must not locally conclude
  # "no weights" while peers build the weights array (mismatched
  # participation in make_array_from_process_local_data hangs the job)
  local_max = np.zeros(3, np.int64)
  local_has_w = 1
  for p in mine:
    _, g, _, _, npb, _ = load_partition(root_dir, p)
    node_pb = npb
    parts_raw[p] = g
    if g.weights is None:
      local_has_w = 0
  for p, g in parts_raw.items():
    topo, local_of = _build_partition_block(
        g, node_pb.table.shape[0], edge_dir,
        with_weights=g.weights is not None)
    blocks[p] = (topo, local_of)
    local_max = np.maximum(
        local_max, [topo.num_rows, topo.num_edges, topo.max_degree])
  if node_pb is None:  # a process with no shards still needs the PB
    _, _, _, _, node_pb, _ = load_partition(root_dir, 0)
  num_nodes = node_pb.table.shape[0]

  if jax.process_count() > 1:
    from jax.experimental import multihost_utils
    gathered = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(np.concatenate([local_max, [local_has_w]]))))
    gmax = gathered[:, :3].max(axis=0)
    has_weights = bool(gathered[:, 3].min())
  else:
    gmax = local_max
    has_weights = bool(parts_raw) and bool(local_has_w)
  return _assemble_multihost_store(
      mesh, axis, mine, blocks, num_nodes,
      max_rows=max(int(gmax[0]), 1), max_edges=max(int(gmax[1]), 1),
      max_degree=max(int(gmax[2]), 1), has_weights=has_weights,
      node_pb=node_pb, n_parts=n_parts, edge_dir=edge_dir)

"""Hetero distributed stores + sampler — IGBH-class workloads.

Reference: the hetero paths of dist_neighbor_sampler.py (per-etype
concurrent rpc tasks, :315-347) and dist_dataset/dist_graph hetero
handling; the deployment target is examples/igbh/dist_train_rgnn.py
(billion-edge hetero training). TPU design: one DistGraph-style sharded
store per edge type (all on the same mesh), per-node-type dense inducer
tables, and a shard_map hop loop that issues the collective one-hop of
every edge type then merges each destination type once — the same
structure as the single-device hetero engine with the one-hop swapped
for the all_to_all version.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pipeline import multihop_sample_hetero
from ..ops.pipeline import make_dedup_tables
from ..typing import EdgeType, NodeType, reverse_edge_type
from ..utils import as_numpy
from ..utils.rng import RandomSeedManager
from .dist_graph import DistGraph
from .dist_neighbor_sampler import make_dist_one_hop


class DistHeteroGraph:
  """Dict of per-edge-type sharded stores over one mesh.

  Built from per-partition hetero GraphPartitionData dicts + per-ntype
  partition books.
  """

  def __init__(self, mesh: Mesh, node_counts: Dict[NodeType, int],
               parts_per_etype: Dict[EdgeType, Sequence],
               node_pbs: Dict[NodeType, object], edge_dir: str = 'out',
               axis: str = 'data'):
    self.mesh = mesh
    self.axis = axis
    self.edge_dir = edge_dir
    self.node_counts = dict(node_counts)
    self.graphs: Dict[EdgeType, DistGraph] = {}
    for etype, parts in parts_per_etype.items():
      src_t, _, dst_t = etype
      row_t = src_t if edge_dir == 'out' else dst_t
      col_t = dst_t if edge_dir == 'out' else src_t
      # the per-etype store routes by the *row* type's partition book and
      # emits col-type global ids
      store = DistGraph.__new__(DistGraph)
      self._build_etype_store(store, mesh, parts, node_pbs[row_t],
                              node_counts[row_t], node_counts[col_t],
                              axis)
      self.graphs[etype] = store
    self.num_partitions = mesh.shape[axis]

  @staticmethod
  def _build_etype_store(store, mesh, parts, node_pb, num_rows_global,
                         num_cols_global, axis):
    """Like DistGraph.__init__ but with independent row/col id spaces."""
    from ..data import Topology
    from .dist_graph import _pb_dense
    n_parts = len(parts)
    indptrs, indices_l, eids_l, locals_l, weights_l = [], [], [], [], []
    max_rows, max_edges, max_degree = 1, 1, 1
    has_weights = all(p.weights is not None for p in parts)
    built = []
    for g in parts:
      src, dst = as_numpy(g.edge_index)
      row, col = src, dst  # caller passes pre-oriented (row, col)
      owned = np.unique(row)
      local_of = np.full(num_rows_global, -1, np.int32)
      local_of[owned] = np.arange(owned.shape[0], dtype=np.int32)
      topo = Topology(edge_index=np.stack([local_of[row], col]),
                      edge_ids=as_numpy(g.eids),
                      edge_weights=(as_numpy(g.weights) if has_weights
                                    else None),
                      layout='CSR',
                      num_rows=owned.shape[0],
                      num_cols=num_cols_global)
      built.append((topo, local_of))
      max_rows = max(max_rows, owned.shape[0])
      max_edges = max(max_edges, topo.num_edges)
      max_degree = max(max_degree, topo.max_degree)
    for topo, local_of in built:
      ip = topo.indptr.astype(np.int32)
      ip = np.concatenate(
          [ip, np.full(max_rows + 1 - ip.shape[0], ip[-1], np.int32)])
      indptrs.append(ip)
      indices_l.append(np.concatenate(
          [topo.indices,
           np.zeros(max_edges - topo.num_edges, topo.indices.dtype)]))
      eids_l.append(np.concatenate(
          [topo.edge_ids.astype(np.int64),
           np.full(max_edges - topo.num_edges, -1, np.int64)]))
      locals_l.append(local_of)
      if has_weights:
        weights_l.append(np.concatenate(
            [topo.edge_weights.astype(np.float32),
             np.zeros(max_edges - topo.num_edges, np.float32)]))
    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    store._finish_init(mesh, axis, num_rows_global, 'out', n_parts,
                       max_rows, max_edges, max_degree)
    store.indptr = jax.device_put(np.stack(indptrs), shard)
    store.indices = jax.device_put(np.stack(indices_l), shard)
    store.edge_ids = jax.device_put(np.stack(eids_l), shard)
    store.edge_weights = (jax.device_put(np.stack(weights_l), shard)
                          if has_weights else None)
    store.local_row = jax.device_put(np.stack(locals_l), shard)
    store.node_pb = jax.device_put(_pb_dense(node_pb, num_rows_global),
                                   repl)

  @classmethod
  def from_dataset_partitions(cls, mesh: Mesh, root_dir: str,
                              edge_dir: str = 'out', axis: str = 'data'):
    from ..partition import load_meta, load_partition
    meta = load_meta(root_dir)
    assert meta['data_cls'] == 'hetero'
    # routing uses the expand-from node's PB: edges must have been
    # assigned by that same endpoint or cross-partition neighbors would
    # silently vanish (ok = local_row >= 0 masks them)
    need = 'by_src' if edge_dir == 'out' else 'by_dst'
    got = meta.get('edge_assign', 'by_src')
    if got != need:
      raise ValueError(
          f'partition was edge-assigned {got!r} but edge_dir='
          f'{edge_dir!r} sampling requires {need!r}; re-partition with '
          f'edge_assign_strategy={need!r}')
    etypes = [tuple(e) for e in meta['edge_types']]
    parts_per_etype = {e: [] for e in etypes}
    node_pbs = None
    for p in range(meta['num_parts']):
      _, graphs, _, _, npb, _ = load_partition(root_dir, p)
      node_pbs = npb
      for e in etypes:
        g = graphs[e]
        src, dst = g.edge_index
        if edge_dir == 'out':
          oriented = np.stack([src, dst])
        else:
          oriented = np.stack([dst, src])
        from ..typing import GraphPartitionData
        parts_per_etype[e].append(
            GraphPartitionData(edge_index=oriented, eids=g.eids,
                               weights=g.weights))
    node_counts = {nt: pb.table.shape[0] for nt, pb in node_pbs.items()}
    return cls(mesh, node_counts, parts_per_etype, node_pbs,
               edge_dir=edge_dir, axis=axis)


def dist_hetero_graph_from_partitions_multihost(
    mesh: Mesh, root_dir: str, edge_dir: str = 'out',
    axis: str = 'data') -> DistHeteroGraph:
  """Multi-host DistHeteroGraph: each process loads ONLY the partitions
  owned by its local devices and contributes per-etype blocks to the
  global sharded stacks (jax.make_array_from_process_local_data) — the
  hetero counterpart of dist_graph_from_partitions_multihost, and the
  reference's per-rank partition loading discipline for IGBH-class
  training (dist_train_rgnn.py loads rank-local partitions only).

  Padding widths (max rows/edges/degree per etype) are agreed with one
  allgather so every process lowers the identical SPMD program.
  """
  import jax
  from ..partition import load_meta, load_partition
  from .dist_graph import (
      _assemble_multihost_store, _build_partition_block,
  )
  meta = load_meta(root_dir)
  assert meta['data_cls'] == 'hetero'
  need = 'by_src' if edge_dir == 'out' else 'by_dst'
  got = meta.get('edge_assign', 'by_src')
  if got != need:
    raise ValueError(
        f'partition was edge-assigned {got!r} but edge_dir='
        f'{edge_dir!r} sampling requires {need!r}')
  etypes = [tuple(e) for e in meta['edge_types']]
  devices = mesh.devices.reshape(-1)
  n_parts = devices.shape[0]
  if meta['num_parts'] != n_parts:
    raise ValueError(
        f"mesh has {n_parts} devices but the partition dir holds "
        f"{meta['num_parts']} partitions — they must match")
  mine = [i for i, d in enumerate(devices)
          if d.process_index == jax.process_index()]

  node_pbs = None
  parts_raw = {}
  for p in mine:
    _, graphs, _, _, npb, _ = load_partition(root_dir, p)
    node_pbs = npb
    parts_raw[p] = graphs
  if node_pbs is None:  # a process with no shards still needs the PBs
    _, _, _, _, node_pbs, _ = load_partition(root_dir, 0)
  node_counts = {nt: pb.table.shape[0] for nt, pb in node_pbs.items()}

  # per-etype local blocks + maxima; weights-presence must also be
  # agreed globally (all-or-nothing per etype)
  blocks = {e: {} for e in etypes}
  local_stats = np.zeros((len(etypes), 4), np.int64)  # rows,edges,deg,w
  local_stats[:, 3] = 1
  for p, graphs in parts_raw.items():
    for i, e in enumerate(etypes):
      src_t, _, dst_t = e
      g = graphs[e]
      row_t = src_t if edge_dir == 'out' else dst_t
      col_t = dst_t if edge_dir == 'out' else src_t
      topo, local_of = _build_partition_block(
          g, node_counts[row_t], edge_dir,
          with_weights=g.weights is not None,
          num_cols=node_counts[col_t])
      blocks[e][p] = (topo, local_of)
      local_stats[i, :3] = np.maximum(
          local_stats[i, :3],
          [topo.num_rows, topo.num_edges, topo.max_degree])
      if g.weights is None:
        local_stats[i, 3] = 0
  if jax.process_count() > 1:
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(local_stats)))
    stats = np.concatenate([gathered[..., :3].max(axis=0),
                            gathered[..., 3:].min(axis=0)], axis=-1)
  else:
    stats = local_stats

  out = DistHeteroGraph.__new__(DistHeteroGraph)
  out.mesh = mesh
  out.axis = axis
  out.edge_dir = edge_dir
  out.node_counts = node_counts
  out.num_partitions = n_parts
  out.graphs = {}
  for i, e in enumerate(etypes):
    src_t, _, dst_t = e
    row_t = src_t if edge_dir == 'out' else dst_t
    # per-etype stores are always pre-oriented, hence edge_dir='out'
    # (same convention as _build_etype_store)
    out.graphs[e] = _assemble_multihost_store(
        mesh, axis, mine, blocks[e], node_counts[row_t],
        max_rows=max(int(stats[i, 0]), 1),
        max_edges=max(int(stats[i, 1]), 1),
        max_degree=max(int(stats[i, 2]), 1),
        has_weights=bool(stats[i, 3]), node_pb=node_pbs[row_t],
        n_parts=n_parts, edge_dir='out')
  return out


class DistHeteroNeighborSampler:
  """SPMD hetero sampling: per-device seed batches of one seed type."""

  def __init__(self, graph: DistHeteroGraph, num_neighbors,
               with_edge: bool = False, with_weight: bool = False,
               max_weighted_degree: Optional[int] = None,
               seed: Optional[int] = None,
               full_neighbor_cap: Optional[int] = None):
    self.g = graph
    self.mesh = graph.mesh
    self.axis = graph.axis
    self.with_edge = with_edge
    self.with_weight = with_weight and all(
        s.edge_weights is not None for s in graph.graphs.values())
    self.max_weighted_degree = max_weighted_degree
    self.edge_types = list(graph.graphs.keys())
    if isinstance(num_neighbors, dict):
      self.num_neighbors = {k: list(v) for k, v in num_neighbors.items()}
    else:
      self.num_neighbors = {k: list(num_neighbors)
                            for k in self.edge_types}
    for e, v in self.num_neighbors.items():
      for i, f in enumerate(v):
        f = int(f)
        if f == -1:  # full neighborhood: resolve to a static -window
          cap = full_neighbor_cap or getattr(graph.graphs[e],
                                             'max_degree', 0)
          assert cap > 0, (f'fanout=-1 for {e} needs full_neighbor_cap '
                           'or a store with a known max_degree')
          f = -int(cap)
        else:
          assert f >= 0, f'fanout must be >= 0 or -1, got {f} for {e}'
        v[i] = f
    hops = {len(v) for v in self.num_neighbors.values()}
    assert len(hops) == 1
    self.num_hops = hops.pop()
    from ..utils.rng import make_key
    self._base_key = make_key(
        seed if seed is not None
        else RandomSeedManager.getInstance().getSeed())
    self._step = 0
    self._fn_cache = {}
    n_dev = self.mesh.shape[self.axis]
    shard = NamedSharding(self.mesh, P(self.axis))
    self.tables = {}
    for t, n in graph.node_counts.items():
      table, scratch = make_dedup_tables(n)
      self.tables[t] = (
          jax.device_put(jnp.broadcast_to(table, (n_dev,) + table.shape),
                         shard),
          jax.device_put(
              jnp.broadcast_to(scratch, (n_dev,) + scratch.shape),
              shard))

  def _next_key(self):
    self._step += 1
    return jax.random.fold_in(self._base_key, self._step)

  def _trav(self):
    out = {}
    for etype in self.edge_types:
      src_t, _, dst_t = etype
      row_t = src_t if self.g.edge_dir == 'out' else dst_t
      col_t = dst_t if self.g.edge_dir == 'out' else src_t
      out[etype] = (row_t, col_t)
    return out

  def _caps(self, batch_size: int, seed_type: NodeType):
    trav = self._trav()
    types = list(self.g.node_counts)
    caps = [{t: (batch_size if t == seed_type else 0) for t in types}]
    for h in range(self.num_hops):
      nxt = {t: 0 for t in types}
      for etype, (row_t, col_t) in trav.items():
        nxt[col_t] += caps[h][row_t] * abs(self.num_neighbors[etype][h])
      caps.append(nxt)
    budgets = {t: max(1, sum(c[t] for c in caps)) for t in types}
    return caps, budgets

  def _make_device_core(self, batch_size: int, seed_type: NodeType):
    """Returns device_core(shards, seeds, n_valid_scalar, key, flat_tables)
    -> (result dict, out_tables) with NO leading shard dims — reusable by
    the train step."""
    g = self.g
    trav = self._trav()
    caps, budgets = self._caps(batch_size, seed_type)
    axis = self.axis
    n_parts = g.num_partitions
    types = list(g.node_counts)
    # an edge type participates only if its expand-from type ever has a
    # frontier; inactive types produce no edges and must be excluded from
    # outputs (and from shard_map out_specs)
    etypes = [e for e in self.edge_types
              if any(caps[h][trav[e][0]] * abs(self.num_neighbors[e][h])
                     > 0 for h in range(self.num_hops))]

    def device_core(shards, seeds, n_valid, key, tables):
      one_hops = {}
      for e in etypes:
        sh = shards[e]
        gs = dict(indptr=sh['indptr'], indices=sh['indices'],
                  edge_ids=sh['edge_ids'],
                  local_row=sh['local_row'],
                  node_pb=sh['node_pb'])
        if 'edge_weights' in sh:
          gs['edge_weights'] = sh['edge_weights']
        one_hops[e] = make_dist_one_hop(
            gs, g.graphs[e].num_nodes, n_parts, g.graphs[e].max_rows,
            axis, with_weight=self.with_weight,
            max_weighted_degree=(self.max_weighted_degree
                                 or getattr(g.graphs[e], 'max_degree',
                                            1)))

      trav_active = {e: trav[e] for e in etypes}
      result, out_tables = multihop_sample_hetero(
          one_hops, trav_active, self.num_neighbors, self.num_hops,
          caps, budgets, {seed_type: seeds},
          {seed_type: n_valid}, key, tables,
          with_edge=self.with_edge)
      # flatten the per-seed-type dicts to the flat fields dist callers
      # consume (single seed type in dist mode)
      result['batch'] = result['batch'][seed_type]
      result['seed_labels'] = result['seed_labels'][seed_type]
      return result, out_tables

    return device_core, caps, budgets, etypes

  def _build(self, batch_size: int, seed_type: NodeType):
    g = self.g
    types = list(g.node_counts)
    device_core, caps, budgets, etypes = self._make_device_core(
        batch_size, seed_type)

    def device_fn(shards, seeds, n_valid, key, tables):
      def unpack(sh):
        d = dict(indptr=sh['indptr'][0], indices=sh['indices'][0],
                 edge_ids=sh['edge_ids'][0],
                 local_row=sh['local_row'][0], node_pb=sh['node_pb'])
        if 'edge_weights' in sh:
          d['edge_weights'] = sh['edge_weights'][0]
        return d
      shards_in = {e: unpack(sh) for e, sh in shards.items()}
      key = jax.random.fold_in(key[0], jax.lax.axis_index(self.axis))
      flat_tables = {t: (tables[t][0][0], tables[t][1][0])
                     for t in tables}
      result, out_tables = device_core(shards_in, seeds, n_valid[0], key,
                                       flat_tables)
      result = jax.tree_util.tree_map(lambda a: a[None], result)
      out_tables = {t: (tb[None], sc[None])
                    for t, (tb, sc) in out_tables.items()}
      return result, out_tables

    sp = P(self.axis)
    def etype_spec(e):
      d = dict(indptr=sp, indices=sp, edge_ids=sp, local_row=sp,
               node_pb=P())
      if g.graphs[e].edge_weights is not None:
        d['edge_weights'] = sp
      return d
    shard_specs = {e: etype_spec(e) for e in etypes}
    out_elem = {
        'node': {t: sp for t in types},
        'node_count': {t: sp for t in types},
        'row': {e: sp for e in etypes}, 'col': {e: sp for e in etypes},
        'edge_mask': {e: sp for e in etypes},
        'batch': sp, 'seed_labels': sp,
        'num_sampled_nodes': {t: sp for t in types},
        'num_sampled_edges': {e: sp for e in etypes},
    }
    if self.with_edge:
      out_elem['edge'] = {e: sp for e in etypes}
    table_specs = {t: (sp, sp) for t in types}

    fn = jax.shard_map(
        device_fn, mesh=self.mesh,
        in_specs=(shard_specs, sp, sp, sp, table_specs),
        out_specs=(out_elem, table_specs), check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(3,))
    def step(seeds, n_valid, keys, tables):
      def etype_payload(e):
        d = dict(indptr=g.graphs[e].indptr, indices=g.graphs[e].indices,
                 edge_ids=g.graphs[e].edge_ids,
                 local_row=g.graphs[e].local_row,
                 node_pb=g.graphs[e].node_pb)
        if g.graphs[e].edge_weights is not None:
          d['edge_weights'] = g.graphs[e].edge_weights
        return d
      shards = {e: etype_payload(e) for e in etypes}
      return fn(shards, seeds, n_valid, keys, tables)

    return step

  def sample_from_nodes(self, seed_type: NodeType,
                        seeds_per_device, n_valid_per_device=None,
                        key=None) -> dict:
    seeds = as_numpy(seeds_per_device)
    n_dev = self.mesh.shape[self.axis]
    if seeds.ndim == 2:
      seeds = seeds.reshape(-1)
    batch_size = seeds.shape[0] // n_dev
    if n_valid_per_device is None:
      n_valid_per_device = np.full(n_dev, batch_size, np.int32)
    cache_key = (batch_size, seed_type)
    if cache_key not in self._fn_cache:
      self._fn_cache[cache_key] = self._build(batch_size, seed_type)
    if key is None:
      key = self._next_key()
    shard = NamedSharding(self.mesh, P(self.axis))
    out, self.tables = self._fn_cache[cache_key](
        jax.device_put(jnp.asarray(seeds, jnp.int32), shard),
        jax.device_put(jnp.asarray(n_valid_per_device, jnp.int32), shard),
        jax.random.split(key, n_dev), self.tables)

    def final_key(e):
      return reverse_edge_type(e) if self.g.edge_dir == 'out' else e

    # message-passing orientation + key reversal, as the single-device
    # hetero engine emits
    out['row'], out['col'] = (
        {final_key(e): v for e, v in out['col'].items()},
        {final_key(e): v for e, v in out['row'].items()})
    out['edge_mask'] = {final_key(e): v
                        for e, v in out['edge_mask'].items()}
    out['num_sampled_edges'] = {
        final_key(e): v for e, v in out['num_sampled_edges'].items()}
    if self.with_edge:
      out['edge'] = {final_key(e): v for e, v in out['edge'].items()}
    out['input_type'] = seed_type
    return out


class DistHeteroTrainStep:
  """One-program hetero distributed training (the IGBH deployment shape,
  examples/igbh/dist_train_rgnn.py): hetero collective sampling +
  per-type feature all_to_all + RGNN forward/backward + gradient pmean,
  all inside a single shard_map step.
  """

  def __init__(self, graph: DistHeteroGraph,
               features: Dict[NodeType, object],   # DistFeature per type
               model, tx, labels: Dict[NodeType, np.ndarray],
               num_neighbors, batch_size_per_device: int,
               seed_type: NodeType, seed: Optional[int] = None,
               edge_features: Optional[Dict[EdgeType, object]] = None,
               with_weight: bool = False,
               max_weighted_degree: Optional[int] = None):
    """``edge_features`` maps *traversal* edge types to edge-id-space
    DistFeatures; when given, sampling emits eids and the batch carries
    ``edge_attr_dict`` (reference efeat collate,
    dist_neighbor_sampler.py:689-807). ``with_weight`` enables the
    weighted per-etype collective one-hop (reference
    neighbor_sampler.py:96-144 hetero weighted loops)."""
    import optax
    from ..parallel.dist_feature import require_device_resident
    for t, st in features.items():
      require_device_resident(st, f'DistHeteroTrainStep features[{t!r}]')
    for e, st in (edge_features or {}).items():
      require_device_resident(
          st, f'DistHeteroTrainStep edge_features[{e!r}]')
    self.g = graph
    self.features = features
    self.edge_features = edge_features or {}
    self.model = model
    self.tx = tx
    self.seed_type = seed_type
    self.bs = int(batch_size_per_device)
    self.mesh = graph.mesh
    self.axis = graph.axis
    self.sampler = DistHeteroNeighborSampler(
        graph, num_neighbors, with_edge=bool(self.edge_features),
        with_weight=with_weight, max_weighted_degree=max_weighted_degree,
        seed=seed)
    self.labels = {t: jax.device_put(as_numpy(v),
                                     NamedSharding(self.mesh, P()))
                   for t, v in labels.items()}
    self._optax = optax
    #: times each program was TRACED (trace-time side effects;
    #: executions never bump these) — the zero-steady-state-recompile
    #: assertions on the hetero train path read them
    self.step_traces = 0
    self.superstep_traces = 0
    self._step_fn = self._build()
    self._superstep_fn = None  # built lazily on first superstep call
    self._eval_fn = None  # built lazily on first eval_step call

  def _final_key(self, e):
    return reverse_edge_type(e) if self.g.edge_dir == 'out' else e

  def dummy_batch(self):
    from ..loader.transform import HeteroBatch
    _, caps, budgets, active = self.sampler._make_device_core(
        self.bs, self.seed_type)
    trav = {e: tc for e, tc in self.sampler._trav().items()
            if e in active}
    x_dict = {t: jnp.zeros((budgets[t], self.features[t].feature_dim))
              for t in self.features}
    from ..ops.pipeline import hetero_edge_capacities
    ecaps = hetero_edge_capacities(caps, trav, self.sampler.num_neighbors,
                                   self.sampler.num_hops)
    row_d, col_d, mask_d, eattr_d, eid_d = {}, {}, {}, {}, {}
    for e in trav:
      ecap = max(ecaps[e], 1)
      k = self._final_key(e)
      row_d[k] = jnp.zeros((ecap,), jnp.int32)
      col_d[k] = jnp.zeros((ecap,), jnp.int32)
      mask_d[k] = jnp.zeros((ecap,), bool)
      if self.sampler.with_edge:
        eid_d[k] = jnp.zeros((ecap,), jnp.int32)
      if e in self.edge_features:
        eattr_d[k] = jnp.zeros((ecap,
                                self.edge_features[e].feature_dim))
    return HeteroBatch(
        x_dict=x_dict, row_dict=row_d, col_dict=col_d,
        edge_mask_dict=mask_d,
        edge_attr_dict=eattr_d or None,
        edge_dict=eid_d or None,
        node_dict={t: jnp.zeros((budgets[t],), jnp.int32)
                   for t in self.features},
        node_count_dict={t: jnp.zeros((), jnp.int32)
                         for t in self.features},
        y_dict={self.seed_type: jnp.zeros((self.bs,), jnp.int32)},
        input_type=self.seed_type, batch_size=self.bs)

  def init_params(self, key):
    params = self.model.init(key, self.dummy_batch())
    return jax.device_put(params, NamedSharding(self.mesh, P()))

  def _assembly(self):
    """Shared device-batch assembly for the train and eval programs:
    returns (device_batch, specs, payloads, table_specs) where
    ``device_batch(...)`` runs sampling + feature/efeat collate inside
    shard_map and yields (batch, y, out_tables)."""
    from ..loader.transform import HeteroBatch
    g, axis, bs = self.g, self.axis, self.bs
    seed_type = self.seed_type
    device_core, caps, budgets, etypes = self.sampler._make_device_core(
        bs, seed_type)
    types = list(g.node_counts)
    feats = self.features
    unknown = set(self.edge_features) - set(self.sampler.edge_types)
    assert not unknown, (
        f'edge_features keys {sorted(map(str, unknown))} are not '
        'traversal edge types; valid keys: '
        f'{sorted(map(str, self.sampler.edge_types))} '
        '(pass the traversal type, not the reversed output key)')
    # inactive etypes (no frontier ever reaches them) sample no edges
    efeats = {e: v for e, v in self.edge_features.items() if e in etypes}

    def device_batch(shards, feat_shards, efeat_shards, labels, seeds,
                     n_valid, key, tables):
      def unpack(sh):
        d = dict(indptr=sh['indptr'][0], indices=sh['indices'][0],
                 edge_ids=sh['edge_ids'][0],
                 local_row=sh['local_row'][0], node_pb=sh['node_pb'])
        if 'edge_weights' in sh:
          d['edge_weights'] = sh['edge_weights'][0]
        return d
      shards_in = {e: unpack(sh) for e, sh in shards.items()}
      my_key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      flat_tables = {t: (tables[t][0][0], tables[t][1][0])
                     for t in tables}
      out, out_tables = device_core(shards_in, seeds, n_valid[0], my_key,
                                    flat_tables)
      x_dict = {}
      for t in types:
        fs = feat_shards[t]
        valid = (jnp.arange(out['node'][t].shape[0])
                 < out['node_count'][t])
        x_dict[t] = feats[t].lookup_local(
            fs['array'][0], fs['id2index'][0], fs['feat_pb'][0],
            jnp.maximum(out['node'][t], 0), valid, axis_name=axis,
            cold_shard=fs['cold'][0] if 'cold' in fs else None)
      y = jnp.take(labels[seed_type],
                   jnp.maximum(out['batch'], 0)[:bs])
      fk = self._final_key
      edge_attr_dict = None
      if efeats:
        edge_attr_dict = {}
        for e in efeats:
          fs = efeat_shards[e]
          edge_attr_dict[fk(e)] = efeats[e].lookup_local(
              fs['array'][0], fs['id2index'][0], fs['feat_pb'][0],
              jnp.maximum(out['edge'][e], 0), out['edge_mask'][e],
              axis_name=axis,
              cold_shard=fs['cold'][0] if 'cold' in fs else None)
      batch = HeteroBatch(
          x_dict=x_dict,
          row_dict={fk(e): out['col'][e] for e in etypes},
          col_dict={fk(e): out['row'][e] for e in etypes},
          edge_mask_dict={fk(e): out['edge_mask'][e] for e in etypes},
          edge_attr_dict=edge_attr_dict,
          edge_dict=({fk(e): out['edge'][e] for e in etypes}
                     if 'edge' in out else None),
          node_dict=out['node'], node_count_dict=out['node_count'],
          y_dict={seed_type: y}, input_type=seed_type, batch_size=bs)
      out_tables = {t: (tb[None], sc[None])
                    for t, (tb, sc) in out_tables.items()}
      return batch, y, out_tables

    sp = P(self.axis)
    def etype_spec(e):
      d = dict(indptr=sp, indices=sp, edge_ids=sp, local_row=sp,
               node_pb=P())
      if g.graphs[e].edge_weights is not None:
        d['edge_weights'] = sp
      return d
    def store_spec(st):
      d = dict(array=sp, id2index=sp, feat_pb=sp)
      if st.cold_array is not None:  # pinned-host offloaded cold block
        d['cold'] = sp
      return d
    specs = dict(
        shards={e: etype_spec(e) for e in etypes},
        feats={t: store_spec(feats[t]) for t in types},
        efeats={e: store_spec(efeats[e]) for e in efeats},
        tables={t: (sp, sp) for t in types},
        labels={t: P() for t in self.labels},
        sp=sp)

    def payloads():
      def etype_payload(e):
        d = dict(indptr=g.graphs[e].indptr, indices=g.graphs[e].indices,
                 edge_ids=g.graphs[e].edge_ids,
                 local_row=g.graphs[e].local_row,
                 node_pb=g.graphs[e].node_pb)
        if g.graphs[e].edge_weights is not None:
          d['edge_weights'] = g.graphs[e].edge_weights
        return d
      def store_payload(st):
        d = dict(array=st.array, id2index=st.id2index,
                 feat_pb=st.feat_pb)
        if st.cold_array is not None:
          d['cold'] = st.cold_array
        return d
      return (
          {e: etype_payload(e) for e in etypes},
          {t: store_payload(feats[t]) for t in types},
          {e: store_payload(efeats[e]) for e in efeats})

    return device_batch, specs, payloads

  def _build(self):
    optax = self._optax
    model, tx, axis, bs = self.model, self.tx, self.axis, self.bs
    device_batch, specs, payloads = self._assembly()

    def device_step(params, opt_state, shards, feat_shards, efeat_shards,
                    labels, seeds, n_valid, key, tables):
      batch, y, out_tables = device_batch(
          shards, feat_shards, efeat_shards, labels, seeds, n_valid,
          key, tables)

      def loss_fn(p):
        logits = model.apply(p, batch)
        mask = jnp.arange(bs) < n_valid[0]
        l = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(), 1)

      loss, grads = jax.value_and_grad(loss_fn)(params)
      grads = jax.lax.pmean(grads, axis)
      loss = jax.lax.pmean(loss, axis)
      updates, opt_state = tx.update(grads, opt_state, params)
      params = optax.apply_updates(params, updates)
      return params, opt_state, out_tables, loss[None]

    sp = specs['sp']
    fn = jax.shard_map(
        device_step, mesh=self.mesh,
        in_specs=(P(), P(), specs['shards'], specs['feats'],
                  specs['efeats'], specs['labels'], sp, sp, sp,
                  specs['tables']),
        out_specs=(P(), P(), specs['tables'], sp), check_vma=False)

    import functools
    @functools.partial(jax.jit, donate_argnums=(9,))
    def step(params, opt_state, shards, feat_shards, efeat_shards,
             labels, seeds, n_valid, keys, tables):
      self.step_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.hetero_step')
      return fn(params, opt_state, shards, feat_shards, efeat_shards,
                labels, seeds, n_valid, keys, tables)

    def run(params, opt_state, tables, seeds, n_valid, keys):
      shards, feat_shards, efeat_shards = payloads()
      return step(params, opt_state, shards, feat_shards, efeat_shards,
                  self.labels, seeds, n_valid, keys, tables)

    return run

  # -- superstep: K hetero batches per donated dispatch ------------------

  def _build_superstep(self):
    """The fused hetero superstep program (ISSUE 14 tentpole, first
    move): lax.scan of the per-batch hetero body — per-edge-type
    collective sampling + per-type feature all_to_all + RGNN
    forward/backward + pmean'd update — with params/opt-state/per-type
    dedup tables threaded through the carry
    (ops/superstep.py::superstep_hetero). K batches then cost ONE
    donated dispatch: the per-batch train loop's host round-trip, seed
    transfer, and dispatch latency amortize 1/K — exactly the homo
    superstep's collapse (parallel/train.py), now on the per-edge-type
    dispatch train VERDICT round 5 measured at 174 seeds/s."""
    optax = self._optax
    model, tx, axis, bs = self.model, self.tx, self.axis, self.bs
    device_batch, specs, payloads = self._assembly()
    from ..ops.superstep import superstep_hetero

    def device_superstep(params, opt_state, shards, feat_shards,
                         efeat_shards, labels, seeds_stack,
                         n_valid_stack, keys, tables):
      def body(params, opt_state, tables, seeds, n_valid, key):
        batch, y, out_tables = device_batch(
            shards, feat_shards, efeat_shards, labels, seeds, n_valid,
            key, tables)

        def loss_fn(p):
          logits = model.apply(p, batch)
          mask = jnp.arange(bs) < n_valid[0]
          l = optax.softmax_cross_entropy_with_integer_labels(logits, y)
          return jnp.where(mask, l, 0).sum() / jnp.maximum(mask.sum(),
                                                           1)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.pmean(grads, axis)
        loss = jax.lax.pmean(loss, axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, out_tables, loss[None]

      run = superstep_hetero(body)
      return run(params, opt_state, tables, seeds_stack, n_valid_stack,
                 keys)

    stacked = P(None, self.axis)
    fn = jax.shard_map(
        device_superstep, mesh=self.mesh,
        in_specs=(P(), P(), specs['shards'], specs['feats'],
                  specs['efeats'], specs['labels'], stacked, stacked,
                  stacked, specs['tables']),
        out_specs=(P(), P(), specs['tables'], stacked),
        check_vma=False)

    import functools
    @functools.partial(jax.jit, donate_argnums=(0, 1, 9))
    def step(params, opt_state, shards, feat_shards, efeat_shards,
             labels, seeds_stack, n_valid_stack, keys, tables):
      self.superstep_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.hetero_superstep')
      return fn(params, opt_state, shards, feat_shards, efeat_shards,
                labels, seeds_stack, n_valid_stack, keys, tables)

    def run(params, opt_state, tables, seeds_stack, n_valid_stack,
            keys):
      shards, feat_shards, efeat_shards = payloads()
      return step(params, opt_state, shards, feat_shards, efeat_shards,
                  self.labels, seeds_stack, n_valid_stack, keys, tables)

    return run

  def superstep(self, params, opt_state, seeds_stack, n_valid_stack,
                keys):
    """Run T hetero training steps in ONE donated dispatch.

    seeds_stack: [T, n_dev * bs] shard-major per batch; n_valid_stack:
    [T, n_dev]; keys: [T, n_dev] PRNG keys (batch t on device d
    consumes keys[t, d], exactly as T sequential ``__call__``\\ s
    would). Params/opt-state are DONATED — reuse the returned ones.
    Returns (params, opt_state, loss [T, n_dev]). Steady state is one
    dispatch per T batches — ``dispatches_per_step`` drops from 1 to
    1/T — with zero recompiles across calls of the same T
    (``superstep_traces`` stays flat; a ragged epoch tail traces once
    more by design, like the homo superstep)."""
    if self._superstep_fn is None:
      self._superstep_fn = self._build_superstep()
    sh = NamedSharding(self.mesh, P(None, self.axis))
    seeds = jax.device_put(
        jnp.asarray(np.asarray(seeds_stack).reshape(
            len(seeds_stack), -1), jnp.int32), sh)
    nv = jax.device_put(jnp.asarray(n_valid_stack, jnp.int32), sh)
    keys = jax.device_put(keys, sh)
    from ..obs import get_registry, get_tracer
    tracer = get_tracer()
    _synced = {}
    with tracer.span('train.hetero_superstep', k=int(seeds.shape[0]),
                     sync=lambda: _synced.get('loss')):
      (params, opt_state, self.sampler.tables,
       loss) = self._superstep_fn(params, opt_state,
                                  self.sampler.tables, seeds, nv, keys)
      _synced['loss'] = loss
    if tracer.enabled:
      get_registry().set('train_hetero_superstep_traces',
                         float(self.superstep_traces))
    return params, opt_state, loss

  def __call__(self, params, opt_state, seeds, n_valid_per_device, key):
    n_dev = self.mesh.shape[self.axis]
    shard = NamedSharding(self.mesh, P(self.axis))
    seeds = jax.device_put(
        jnp.asarray(np.asarray(seeds).reshape(-1), jnp.int32), shard)
    nv = jax.device_put(jnp.asarray(n_valid_per_device, jnp.int32),
                        shard)
    keys = jax.random.split(key, n_dev)
    params, opt_state, self.sampler.tables, loss = self._step_fn(
        params, opt_state, self.sampler.tables, seeds, nv, keys)
    return params, opt_state, loss

  # -- evaluation (reference dist_train_rgnn.py evaluate loop) -----------

  def _build_eval(self):
    """Forward-only SPMD step returning (correct, total) mesh-summed."""
    model, axis, bs = self.model, self.axis, self.bs
    device_batch, specs, payloads = self._assembly()

    def device_eval(params, shards, feat_shards, efeat_shards, labels,
                    seeds, n_valid, key, tables):
      batch, y, out_tables = device_batch(
          shards, feat_shards, efeat_shards, labels, seeds, n_valid,
          key, tables)
      logits = model.apply(params, batch)
      mask = jnp.arange(bs) < n_valid[0]
      correct = jnp.where(mask, jnp.argmax(logits, -1) == y, False)
      correct = jax.lax.psum(correct.sum(), axis)
      total = jax.lax.psum(mask.sum(), axis)
      return out_tables, correct[None], total[None]

    sp = specs['sp']
    fn = jax.shard_map(
        device_eval, mesh=self.mesh,
        in_specs=(P(), specs['shards'], specs['feats'], specs['efeats'],
                  specs['labels'], sp, sp, sp, specs['tables']),
        out_specs=(specs['tables'], sp, sp), check_vma=False)

    import functools
    @functools.partial(jax.jit, donate_argnums=(8,))
    def jfn(params, shards, feat_shards, efeat_shards, labels, seeds,
            n_valid, keys, tables):
      return fn(params, shards, feat_shards, efeat_shards, labels,
                seeds, n_valid, keys, tables)

    def run(params, tables, seeds, n_valid, keys):
      shards, feat_shards, efeat_shards = payloads()
      return jfn(params, shards, feat_shards, efeat_shards, self.labels,
                 seeds, n_valid, keys, tables)

    return run

  def eval_step(self, params, seeds, n_valid_per_device, key):
    """Forward-only accuracy over one seed batch; returns
    (num_correct, num_total) summed over the mesh."""
    if self._eval_fn is None:
      self._eval_fn = self._build_eval()
    n_dev = self.mesh.shape[self.axis]
    shard = NamedSharding(self.mesh, P(self.axis))
    seeds = jax.device_put(
        jnp.asarray(np.asarray(seeds).reshape(-1), jnp.int32), shard)
    nv = jax.device_put(jnp.asarray(n_valid_per_device, jnp.int32),
                        shard)
    keys = jax.random.split(key, n_dev)
    self.sampler.tables, correct, total = self._eval_fn(
        params, self.sampler.tables, seeds, nv, keys)
    # every lane carries the same psum; read a process-LOCAL shard so
    # multihost runs (where the global array spans other processes)
    # can fetch it
    return (int(np.asarray(correct.addressable_shards[0].data)[0]),
            int(np.asarray(total.addressable_shards[0].data)[0]))

"""DistLoader / DistNeighborLoader — epoch iteration over the SPMD
distributed sampler.

Reference: graphlearn_torch/python/distributed/dist_loader.py (451) +
dist_neighbor_loader.py. The reference's three deployment modes map as:

  * collocated  -> this loader: sampling runs in the same program as
    training consumes (one SPMD dispatch per batch).
  * mp (producer subprocesses + shm channel) -> the host prefetch
    channel (glt_tpu.channel): epoch seed planning happens on host
    threads that keep the device queue fed; device work is identical.
  * remote (server-client) -> glt_tpu.distributed.server.

Each iteration yields a *stacked* per-device batch dict ([P, ...] arrays,
shard-major) plus per-device validity — the shape DistTrainStep and DDP
consumers expect.

Fault tolerance: this collective loader's data plane is XLA all2all
(no sockets to fail independently — a lost mesh process is a
whole-program fault handled by the launcher). The rpc-fed loaders are
where graceful degradation lives: RemoteNeighborLoader drops a dead
server from the epoch instead of stalling (channel_loader.py), and
DistFeature cold fetchers fail over / degrade via
``resilient_cold_fetcher`` — see docs/fault_tolerance.md.
"""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..sampler.base import SamplingConfig
from ..utils import as_numpy
from .dist_feature import DistFeature
from .dist_graph import DistGraph
from .dist_neighbor_sampler import DistNeighborSampler


class DistNeighborLoader:
  """Args:
    dist_graph / dist_feature: sharded stores.
    num_neighbors: fanouts.
    input_nodes: per-device seed lists — [P, n_p] array or list of P
      arrays (each device iterates its own partition's training ids,
      exactly like the reference's per-rank seed splits).
    batch_size: per-device batch size.

  bucket_cap sizing (pass to the DistFeature builder): measured on the
  8-device mesh (benchmarks/bench_bucket_drain.py, committed grid in
  benchmarks/results/bench_bucket_drain_cpu.json), capped request
  buckets beat the uncapped [P, B] exchange at EVERY tested skew —
  smaller messages outweigh extra drain rounds:

    * near-uniform ids: ``bucket_cap = 2 * ceil(B / P)`` — 1 round,
      ~6x faster than uncapped at 1/4 the bytes per round;
    * zipf-skewed / adversarial ids: ``4 * ceil(B / P)`` — 2 rounds,
      still ~1.5x faster than uncapped.

  Default stays uncapped (0) until the TPU wall-times confirm the
  virtual-mesh ordering; drain ROUND counts are exact either way (the
  host replay is deterministic).
  """

  def __init__(self, dist_graph: DistGraph,
               num_neighbors: Sequence[int],
               input_nodes,
               dist_feature: Optional[DistFeature] = None,
               labels: Optional[np.ndarray] = None,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               with_edge: bool = False,
               seed: Optional[int] = None,
               rng: Optional[np.random.Generator] = None,
               edge_feature: Optional[DistFeature] = None):
    self.sampler = DistNeighborSampler(
        dist_graph, num_neighbors,
        with_edge=with_edge or edge_feature is not None, seed=seed)
    self.feature = dist_feature
    self.edge_feature = edge_feature
    self.labels = as_numpy(labels)
    self.n_dev = dist_graph.mesh.shape[dist_graph.axis]
    if isinstance(input_nodes, (list, tuple)):
      self.seeds = [as_numpy(s).astype(np.int64) for s in input_nodes]
    else:
      arr = as_numpy(input_nodes)
      self.seeds = [arr[p] for p in range(arr.shape[0])]
    assert len(self.seeds) == self.n_dev
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.rng = rng or np.random.default_rng(0)

  def __len__(self):
    n = min(s.shape[0] for s in self.seeds)
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def __iter__(self) -> Iterator[dict]:
    orders = [(self.rng.permutation(s.shape[0]) if self.shuffle
               else np.arange(s.shape[0])) for s in self.seeds]
    steps = len(self)
    for it in range(steps):
      lo = it * self.batch_size
      seeds = np.zeros((self.n_dev, self.batch_size), np.int64)
      n_valid = np.zeros(self.n_dev, np.int32)
      for p in range(self.n_dev):
        sel = orders[p][lo:lo + self.batch_size]
        n_valid[p] = sel.shape[0]
        if sel.shape[0]:
          chunk = self.seeds[p][sel]
          seeds[p, :sel.shape[0]] = chunk
          seeds[p, sel.shape[0]:] = chunk[-1] if chunk.size else 0
      out = self.sampler.sample_from_nodes(seeds, n_valid)
      if self.feature is not None:
        import jax.numpy as jnp
        node = out['node'].reshape(-1)
        valid = (jnp.arange(out['node'].shape[1])[None, :]
                 < out['node_count'][:, None]).reshape(-1)
        x = self.feature.lookup(jnp.maximum(node, 0), valid)
        out['x'] = x.reshape(out['node'].shape + (-1,))
      if self.edge_feature is not None and 'edge' in out:
        self.edge_feature.collate_edge_attr(out)
      if self.labels is not None:
        out['y'] = self.labels[np.maximum(np.asarray(out['batch']), 0)]
      out['n_valid'] = n_valid
      yield out


#: Reference-name compatibility (distributed/dist_loader.py:46): the
#: reference's generic DistLoader base carries the collocated/mp/remote
#: mode dispatch that here lives directly in DistNeighborLoader (and
#: the channel loaders); node-seeded loading IS the generic entry.
DistLoader = DistNeighborLoader

"""Minimal socket RPC fabric for server-client mode.

Reference: graphlearn_torch/python/distributed/rpc.py (529 lines over
torch.distributed.rpc/TensorPipe: callee registry, role-scoped
all_gather/barrier, request wrappers). The TPU build needs RPC only for
the *server-client control/data plane* (worker-mode exchanges ride XLA
collectives instead, SURVEY.md §2.3), so this is a deliberately small
length-prefixed-pickle protocol over TCP: a threaded RpcServer with a
callee registry plus built-in barrier/gather used by the client shutdown
choreography. Payload tensors travel as the channel's packed TensorMap
bytes, not pickled arrays.
"""
from __future__ import annotations

import itertools
import os
import pickle
import socket
import struct
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, FrozenSet, List, Optional

from ..obs.trace import get_tracer
from ..resilience.retry import (
    CircuitBreaker, CircuitOpenError, RetryPolicy,
)

_HDR = struct.Struct('<Q')

#: Callees safe to retry after a lost reply (read-only, or — like
#: fetch_one_sampled_message — made retry-safe by the server's
#: request-id dedup cache, which replays the original reply instead of
#: re-executing a pop). Mutating callees (exit, barriers) are
#: deliberately absent: they get transparent reconnect but never an
#: automatic re-send after the request may have been delivered.
#: ``apply_delta`` is also absent HERE, but clients whose every callee
#: is a delta-staging server (dist_client.init_client, the fleet
#: router's remote replicas) opt it in via ``idempotent=`` — the same
#: req-id dedup replay makes the mutation exactly-once-observable, so
#: a lost-reply retry can never double-stage a delta cut.
IDEMPOTENT_CALLEES: FrozenSet[str] = frozenset({
    'get_node_feature', 'get_node_label', 'get_dataset_meta',
    'get_tensor_size', 'get_edge_index', 'get_edge_size',
    'get_node_partition_id', 'fetch_one_sampled_message',
    'infer', 'stats', 'ping', '_ping', '_obs',
})


def _send_msg(sock: socket.socket, obj: Any) -> None:
  data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
  sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
  buf = b''
  while len(buf) < n:
    chunk = sock.recv(n - len(buf))
    if not chunk:
      raise ConnectionError('peer closed')
    buf += chunk
  return buf


def _recv_msg(sock: socket.socket) -> Any:
  (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
  return pickle.loads(_recv_exact(sock, n))


class RpcServer:
  """Threaded RPC endpoint with a callee registry
  (the RpcCalleeBase/rpc_register pattern, reference rpc.py:419-473)."""

  def __init__(self, host: str = '127.0.0.1', port: int = 0,
               auto_start: bool = True,
               resolve_timeout: Optional[float] = None):
    """``resolve_timeout``: how long an incoming request waits for a
    not-yet-registered callee before KeyError. Defaults to 30 s under
    ``auto_start=True`` (where the discovery/registration race is real
    — peers can learn the address before user code finishes
    registering) and 1 s otherwise (callers of auto_start=False
    register everything before start(), so an unknown name is almost
    certainly a typo and should fail fast instead of stalling the
    connection's serve loop — and every request queued behind it — for
    30 s per call)."""
    self._resolve_timeout = (resolve_timeout if resolve_timeout
                             is not None else (30.0 if auto_start
                                               else 1.0))
    self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
      # a bounced server must rebind its well-known port immediately:
      # some kernels keep TIME_WAIT pairs blocking plain SO_REUSEADDR
      # binds for minutes after the old process's conns drained
      self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    except (AttributeError, OSError):
      pass
    self._sock.bind((host, port))
    self._sock.listen(64)
    self.host, self.port = self._sock.getsockname()
    self._callees: Dict[str, Callable] = {}
    self._threads: List[threading.Thread] = []
    self._conns: List[socket.socket] = []
    self._stop = threading.Event()
    self._barriers: Dict[str, threading.Barrier] = {}
    self._gathers: Dict[str, dict] = {}
    self._lock = threading.Lock()
    self._reg_cond = threading.Condition(self._lock)
    # request-id dedup (at-least-once -> exactly-once-observable): a
    # retried idempotent request whose ORIGINAL attempt executed but
    # whose reply was lost gets the cached reply replayed instead of a
    # second execution — this is what makes fetch_one_sampled_message
    # (a queue pop) safe to retry
    # bounded two ways: entries can hold whole sampled-batch payloads,
    # so (a) a NEW request arriving on a connection proves the client
    # consumed the previous reply on it (requests are strictly serial
    # per connection; retries always redial) — the previous entry is
    # evicted immediately, bounding steady state to ~1 entry per live
    # connection — and (b) the LRU cap is the backstop for entries
    # orphaned by dropped connections
    self._dedup: 'OrderedDict[str, tuple]' = OrderedDict()
    self._dedup_cap = 256
    # req_id -> Event for requests currently EXECUTING: a retry that
    # lands while the original attempt is still running (client recv
    # timeout below the callee's legitimate block time) must WAIT for
    # that execution and replay its reply — re-executing concurrently
    # would double-pop fetch_one_sampled_message and lose a batch
    self._dedup_inflight: Dict[str, threading.Event] = {}
    self.dedup_hits = 0
    self.register('_barrier', self._barrier)
    self.register('_gather', self._gather)
    self.register('_ping', self._ping)
    self.register('_obs', self._obs)
    self._accept_thread = None
    if auto_start:
      self.start()

  def start(self) -> None:
    """Begin accepting connections. Callers that register callees after
    construction should prefer auto_start=False + start() once
    registration is complete; requests that arrive before a callee
    exists wait up to 30 s for it (_resolve) before failing — the
    discovery/registration race (observed under load as
    KeyError('push_edges')) costs latency, not correctness."""
    if self._accept_thread is None:
      self._accept_thread = threading.Thread(target=self._accept_loop,
                                             daemon=True)
      self._accept_thread.start()

  def register(self, name: str, fn: Callable) -> None:
    with self._reg_cond:
      self._callees[name] = fn
      self._reg_cond.notify_all()

  def _resolve(self, name: str,
               timeout: Optional[float] = None) -> Callable:
    """Look up a callee, WAITING briefly for late registration — peers
    discover this server's address before user code finishes
    registering (the KeyError('push_edges') race the start() docstring
    documents); a bounded wait turns that race into latency. The wait
    is ``resolve_timeout`` (see __init__): long only under auto_start,
    so a typo'd name fails fast on pre-registered servers."""
    if timeout is None:
      timeout = self._resolve_timeout
    deadline = None
    with self._reg_cond:
      while name not in self._callees:
        import time as _time
        if deadline is None:
          deadline = _time.monotonic() + timeout
        remaining = deadline - _time.monotonic()
        if remaining <= 0 or not self._reg_cond.wait(timeout=remaining):
          if name not in self._callees:
            raise KeyError(name)
      return self._callees[name]

  def _ping(self) -> dict:
    """Built-in liveness probe every endpoint answers (HealthMonitor
    targets this; servers may also register a richer 'ping')."""
    with self._lock:
      return {'ok': True, 'callees': len(self._callees)}

  def _obs(self) -> dict:
    """Built-in observability harvest every endpoint answers: this
    process's finished trace spans (Chrome-event dicts) + the global
    registry snapshot. A client assembling a cross-machine trace pulls
    each peer's buffer through here (obs.collect_endpoint_obs) and
    merges — server-side handler spans carry the caller's trace id, so
    they slot under the originating client spans."""
    from ..obs import get_registry
    return {'events': get_tracer().events(),
            'metrics': get_registry().snapshot()}

  # built-in synchronization callees (reference rpc.py:105-235)
  def _barrier(self, key: str, world: int) -> bool:
    with self._lock:
      if key not in self._barriers:
        self._barriers[key] = threading.Barrier(world)
      b = self._barriers[key]
    idx = b.wait(timeout=180)
    if idx == 0:  # one releasee frees the slot (keys are single-use)
      with self._lock:
        self._barriers.pop(key, None)
    return True

  def _gather(self, key: str, rank: int, world: int, value) -> dict:
    with self._lock:
      slot = self._gathers.setdefault(
          key, {'vals': {}, 'served': 0,
                'cond': threading.Condition(self._lock)})
      slot['vals'][rank] = value
      slot['cond'].notify_all()
      while len(slot['vals']) < world:
        if not slot['cond'].wait(timeout=180):
          raise TimeoutError(f'gather {key} timed out')
      out = dict(slot['vals'])
      slot['served'] += 1
      if slot['served'] >= world:  # every rank got its copy: free it
        self._gathers.pop(key, None)
      return out

  def _accept_loop(self) -> None:
    while not self._stop.is_set():
      try:
        conn, _ = self._sock.accept()
      except OSError:
        break
      t = threading.Thread(target=self._serve_conn, args=(conn,),
                           daemon=True)
      with self._lock:
        self._conns.append(conn)
        self._threads.append(t)
      t.start()

  def _dedup_get(self, req_id: Optional[str]):
    """Cached reply for ``req_id``, WAITING out an in-flight original
    execution first (so a duplicate never executes concurrently).
    Returns None only when this thread should execute the request."""
    if req_id is None:
      return None
    while True:
      with self._lock:
        hit = self._dedup.get(req_id)
        if hit is not None:
          self.dedup_hits += 1
          self._dedup.move_to_end(req_id)
          return hit
        ev = self._dedup_inflight.get(req_id)
        if ev is None:
          self._dedup_inflight[req_id] = threading.Event()
          return None
      # another connection is executing this very request: wait for it,
      # then loop — the re-check either replays its reply or (executor
      # vanished without one) atomically claims execution
      if not ev.wait(timeout=300):
        with self._lock:
          if self._dedup_inflight.get(req_id) is ev:
            # executor presumed dead after the full wait: claim it
            self._dedup_inflight[req_id] = threading.Event()
            return None

  def _dedup_put(self, req_id: Optional[str], reply) -> None:
    if req_id is None:
      return
    with self._lock:
      if reply is not None:
        self._dedup[req_id] = reply
        self._dedup.move_to_end(req_id)
        while len(self._dedup) > self._dedup_cap:
          self._dedup.popitem(last=False)
      ev = self._dedup_inflight.pop(req_id, None)
    if ev is not None:
      ev.set()

  def _serve_conn(self, conn: socket.socket) -> None:
    try:
      with conn:
        self._serve_conn_loop(conn)
    finally:
      # prune: reconnect-heavy clients (the hardened RpcClient redials
      # on every recovery) would otherwise grow _conns — and the dead
      # per-connection Thread objects — without bound
      me = threading.current_thread()
      with self._lock:
        try:
          self._conns.remove(conn)
        except ValueError:
          pass
        try:
          self._threads.remove(me)
        except ValueError:
          pass

  def _serve_conn_loop(self, conn: socket.socket) -> None:
    prev_req_id: Optional[str] = None
    while not self._stop.is_set():
      try:
        msg = _recv_msg(conn)
      except (ConnectionError, EOFError, OSError):
        return
      # wire format: (name, args, kwargs[, req_id[, trace_ctx]]) — the
      # 4th element rides only on retryable requests (None placeholder
      # when only tracing), the 5th only on trace-sampled requests
      name, args, kwargs = msg[0], msg[1], msg[2]
      req_id = msg[3] if len(msg) > 3 else None
      trace_ctx = msg[4] if len(msg) > 4 else None
      # any subsequent request on this connection proves the client
      # consumed the previous reply (serial per connection; a retry
      # after a drop redials) — release the cached payload now instead
      # of pinning up to _dedup_cap full batch replies in steady state
      if prev_req_id is not None and prev_req_id != req_id:
        with self._lock:
          self._dedup.pop(prev_req_id, None)
      if req_id is not None:
        prev_req_id = req_id
      cached = self._dedup_get(req_id)
      if cached is not None:
        try:
          _send_msg(conn, cached)
        except (ConnectionError, OSError):
          return
        continue
      try:
        fn = self._resolve(name)
        # reopen the caller's span context (if any) around the handler:
        # the server-side span shares the client's trace id and parents
        # under the client's rpc span, so a harvested + merged trace
        # nests correctly across processes. With no incoming context
        # this is a local span (or a cached no-op when tracing is off).
        with get_tracer().remote_span(f'rpc.server:{name}', trace_ctx,
                                      callee=name):
          reply = ('ok', fn(*args, **kwargs))
      except BaseException as e:  # deliver errors to the caller
        try:
          pickle.dumps(e)
          reply = ('err', e)
        except Exception:
          reply = ('err', RuntimeError(str(e)))
      # callee errors are cached too: a retried request must observe
      # the SAME outcome as the lost original, success or not
      self._dedup_put(req_id, reply)
      try:
        _send_msg(conn, reply)
      except (ConnectionError, OSError):
        return

  def stop(self) -> None:
    self._stop.set()
    try:
      self._sock.close()
    except OSError:
      pass
    # close live per-connection sockets too: serve threads unblock and
    # exit, and the port is immediately rebindable (a bounced server
    # can come back on the same address — the reconnect story depends
    # on it)
    with self._lock:
      conns, self._conns = self._conns, []
    for c in conns:
      try:
        c.close()
      except OSError:
        pass


def ping_endpoint(host: str, port: int, timeout: float = 2.0) -> dict:
  """One-shot liveness probe on a FRESH connection: connect, call the
  built-in ``_ping``, close. Health probers use this instead of a
  shared RpcClient so a wedged in-flight request (which holds the
  client's lock for its whole recv) can never stall health detection
  for the other peers."""
  sock = socket.create_connection((host, int(port)), timeout=timeout)
  try:
    sock.settimeout(timeout)
    _send_msg(sock, ('_ping', (), {}))
    status, payload = _recv_msg(sock)
  finally:
    try:
      sock.close()
    except OSError:
      pass
  if status == 'err':
    raise payload
  return payload


#: process-unique prefix for request ids (pid guards against forked
#: twins colliding in one server's dedup cache)
_CLIENT_IDS = itertools.count()


class RpcClient:
  """One connection per (client, server); thread-safe; async via a pool
  (the reference's async_request_server, dist_client.py:82-101).

  Hardened (docs/fault_tolerance.md):

    * **transparent reconnect** — a peer close no longer kills the
      client; the dead socket is dropped and the next request redials;
    * **per-request deadlines** — ``_rpc_timeout`` bounds one request's
      recv instead of the connection-wide 180 s default;
    * **idempotent retry** — requests to :data:`IDEMPOTENT_CALLEES`
      (plus ``idempotent`` extras) carry a request id and are retried
      under ``retry`` (capped exponential backoff + jitter); the
      server's dedup cache replays a lost reply rather than
      re-executing. Send-phase failures (the request provably never
      left) are retried for EVERY callee;
    * **circuit breaker** — ``failure_threshold`` consecutive
      connection errors trip the per-peer breaker and subsequent calls
      fail fast with :class:`CircuitOpenError` until the reset timeout
      admits a probe, instead of each eating a full timeout.

  ``metrics`` (any object with record_retry / record_reconnect /
  record_breaker_open, e.g. ServingMetrics) observes recovery actions;
  the client also keeps local ``retries`` / ``reconnects`` counters.
  """

  _pool = ThreadPoolExecutor(max_workers=16)

  def __init__(self, host: str, port: int, timeout: float = 180.0,
               connect_retries: int = 60, retry_interval: float = 0.5,
               retry: Optional[RetryPolicy] = None,
               breaker: Optional[CircuitBreaker] = None,
               idempotent: Optional[FrozenSet[str]] = None,
               metrics=None):
    self._addr = (host, port)
    self._timeout = timeout
    self._lock = threading.Lock()
    self._sock = None
    self._retry = retry or RetryPolicy()
    self._idempotent = IDEMPOTENT_CALLEES | frozenset(idempotent or ())
    self.metrics = metrics
    self.breaker = breaker or CircuitBreaker(name=f'{host}:{port}')
    if self.breaker.on_open is None:
      self.breaker.on_open = self._on_breaker_open
    self.retries = 0
    self.reconnects = 0
    self._req_prefix = f'{os.getpid()}.{next(_CLIENT_IDS)}'
    self._req_seq = itertools.count()
    self._connect(connect_retries, retry_interval)

  def _on_breaker_open(self) -> None:
    if self.metrics is not None:
      self.metrics.record_breaker_open()

  def _connect(self, retries: int = 1, interval: float = 0.5,
               timeout: Optional[float] = None) -> None:
    # peers race at startup (the reference retries rendezvous the same
    # way, rpc.py:280-322 MAX_RETRY 60 @ 3s). ``timeout`` caps ONE
    # connect attempt; deadline-bounded requests pass their remaining
    # budget so a SYN-blackholed peer can't hold them for the full
    # connection-wide timeout.
    last = None
    tries = max(retries, 1)
    connect_timeout = self._timeout if timeout is None \
        else min(self._timeout, timeout)
    for k in range(tries):
      try:
        self._sock = socket.create_connection(self._addr,
                                              timeout=connect_timeout)
        return
      except OSError as e:
        last = e
        if k + 1 < tries:  # no pointless sleep after the final attempt
          time.sleep(interval)
    raise ConnectionError(
        f'could not connect to {self._addr}: {last}')

  def _drop_sock_locked(self) -> None:
    if self._sock is not None:
      try:
        self._sock.close()
      except OSError:
        pass
      self._sock = None

  def _request_once(self, name: str, args, kwargs,
                    req_id: Optional[str],
                    rpc_timeout: Optional[float],
                    trace_ctx=None):
    """One attempt over the (re)established socket. Raises
    ``_SendPhaseError`` when the failure provably predates delivery
    (safe to retry for any callee)."""
    with self._lock:
      if self._sock is None:
        try:
          self._connect(retries=1, timeout=rpc_timeout)
        except ConnectionError as e:
          raise _SendPhaseError(e) from e
        self.reconnects += 1
        if self.metrics is not None:
          self.metrics.record_reconnect()
      if trace_ctx is not None:
        # trace context rides a 5th element; req_id keeps slot 3 (None
        # placeholder is fine — the server treats it as untracked)
        msg = (name, args, kwargs, req_id, tuple(trace_ctx))
      elif req_id is not None:
        msg = (name, args, kwargs, req_id)
      else:
        msg = (name, args, kwargs)
      try:
        _send_msg(self._sock, msg)
      except (ConnectionError, OSError) as e:
        self._drop_sock_locked()
        raise _SendPhaseError(e) from e
      try:
        if rpc_timeout is not None:
          self._sock.settimeout(rpc_timeout)
        try:
          status, payload = _recv_msg(self._sock)
        finally:
          if rpc_timeout is not None and self._sock is not None:
            self._sock.settimeout(self._timeout)
      except (ConnectionError, EOFError, OSError,
              pickle.UnpicklingError):
        # the reply is unrecoverable on this connection either way —
        # a stray late reply on a reused socket would answer the WRONG
        # request
        self._drop_sock_locked()
        raise
    if status == 'err':
      # wrapped so a callee-raised ConnectionError is never mistaken
      # for a transport failure (which would wrongly trip the breaker
      # and burn retry attempts replaying the same cached error)
      raise _CalleeError(payload)
    return payload

  def request(self, name: str, *args, _rpc_timeout: Optional[float]
              = None, **kwargs):
    """Call ``name`` on the peer. ``_rpc_timeout`` (seconds) is this
    request's TOTAL reply budget across every retry (reserved kwarg —
    never forwarded to the callee): each attempt's recv gets the
    remaining slice, and the retry loop stops once the budget is spent
    — a wedged peer cannot hold the caller for attempts x timeout.
    Connection errors engage reconnect/retry/breaker as described on
    the class.

    With tracing enabled (glt_tpu.obs) the call runs inside an
    ``rpc.client:<name>`` span whose context ships with the request,
    so the peer's handler span nests under it in a merged trace."""
    tracer = get_tracer()
    if not tracer.enabled:
      return self._request_with_retries(name, args, kwargs,
                                        _rpc_timeout, None)
    with tracer.span(f'rpc.client:{name}', cat='rpc', callee=name,
                     peer=f'{self._addr[0]}:{self._addr[1]}') as ctx:
      return self._request_with_retries(name, args, kwargs,
                                        _rpc_timeout, ctx)

  def _request_with_retries(self, name: str, args, kwargs,
                            _rpc_timeout: Optional[float], trace_ctx):
    retryable = name in self._idempotent
    attempts = self._retry.max_attempts
    req_id = (f'{self._req_prefix}.{next(self._req_seq)}'
              if retryable else None)
    deadline = (time.monotonic() + _rpc_timeout
                if _rpc_timeout is not None else None)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
      if not self.breaker.allow():
        raise CircuitOpenError(
            f'circuit open for peer {self._addr} '
            f'(after {self.breaker.failure_threshold} consecutive '
            'failures); failing fast')
      budget = None
      if deadline is not None:
        # slice the remaining budget over the remaining attempts: a
        # dropped reply must leave room to retry, yet the attempts can
        # never sum past the caller's deadline
        remaining = max(deadline - time.monotonic(), 0.001)
        budget = remaining / (attempts - attempt) if retryable \
            else remaining
      try:
        out = self._request_once(name, args, kwargs, req_id, budget,
                                 trace_ctx=trace_ctx)
      except _CalleeError as e:
        # callee-raised error: delivered + executed — the peer is
        # healthy, so neither the breaker nor the retry loop applies
        self.breaker.record_success()
        raise e.error
      except _SendPhaseError as e:
        # request never delivered: retry is safe for ANY callee
        self.breaker.record_failure()
        last = e.cause
      except (ConnectionError, EOFError, OSError,
              pickle.UnpicklingError) as e:
        self.breaker.record_failure()
        if not retryable:
          raise
        last = e
      except BaseException:
        # anything else (an unpicklable argument, a caller bug) never
        # exercised the peer: hand back a HALF_OPEN probe token taken
        # by allow() — without this the breaker wedges OPEN forever
        self.breaker.release_probe()
        raise
      else:
        self.breaker.record_success()
        return out
      if deadline is not None and time.monotonic() >= deadline:
        break  # budget spent: no further attempts
      if attempt + 1 < attempts:
        self.retries += 1
        if self.metrics is not None:
          self.metrics.record_retry()
        self._retry.sleep(attempt)
    assert last is not None
    raise last

  def async_request(self, name: str, *args, **kwargs) -> Future:
    if get_tracer().enabled:
      # propagate the caller's span context into the pool thread —
      # without this every async rpc span would open as an orphan root
      # and fall out of the assembled cross-process trace
      import contextvars
      ctx = contextvars.copy_context()
      return self._pool.submit(ctx.run, self.request, name, *args,
                               **kwargs)
    return self._pool.submit(self.request, name, *args, **kwargs)

  def close(self) -> None:
    with self._lock:
      self._drop_sock_locked()


class _SendPhaseError(Exception):
  """Internal: a connection failure that provably happened before the
  request could reach the peer (connect refused / send reset), so a
  retry cannot double-execute even a mutating callee."""

  def __init__(self, cause: BaseException):
    super().__init__(str(cause))
    self.cause = cause


class _CalleeError(Exception):
  """Internal: the peer answered with an error the CALLEE raised — a
  healthy-peer outcome that must reach the caller verbatim."""

  def __init__(self, error: BaseException):
    super().__init__(str(error))
    self.error = error


# ---------------------------------------------------------------------------
# Reference-shaped any-to-any fabric (reference rpc.py:240-529): a
# process-global context where every process runs an RpcServer, ranks
# rendezvous through the master (rank 0 hosts it), and the convenience
# functions mirror the reference's module surface — init_rpc /
# rpc_register / rpc_request(_async) / barrier / all_gather (+ global
# variants) / rpc_sync_data_partitions / RpcDataPartitionRouter.
# The data plane still rides XLA collectives (SURVEY.md §2.3); this
# fabric is the control plane plus host-side exchanges (cold_fetcher,
# online partitioning, server-client choreography).

import abc


class RpcCalleeBase(abc.ABC):
  """Registered callee contract (reference rpc.py:419-433): implement
  ``call`` and pass the instance to ``rpc_register``."""

  @abc.abstractmethod
  def call(self, *args, **kwargs):
    ...


class RpcDataPartitionRouter:
  """Round-robin among the workers serving each data partition
  (reference rpc.py:364-382)."""

  def __init__(self, partition2workers: Dict[int, List[int]]):
    self._p2w = {int(p): list(ws)
                 for p, ws in partition2workers.items()}
    self._next = {p: 0 for p in self._p2w}

  def get_to_worker(self, partition_idx: int) -> int:
    ws = self._p2w[int(partition_idx)]
    i = self._next[int(partition_idx)]
    self._next[int(partition_idx)] = (i + 1) % len(ws)
    return ws[i]


class _Fabric:
  def __init__(self, master_addr: str, master_port: int, rank: int,
               world_size: int, advertise_addr: str = None):
    self.rank, self.world = int(rank), int(world_size)
    self.master_addr, self.master_port = master_addr, int(master_port)
    local_only = master_addr in ('127.0.0.1', 'localhost')
    self.server = RpcServer(
        host='127.0.0.1' if local_only else '0.0.0.0')
    self.master_server = None
    if self.rank == 0:
      self.master_server = RpcServer(
          host='127.0.0.1' if local_only else '0.0.0.0',
          port=int(master_port))
    self.master = RpcClient(master_addr, int(master_port),
                            connect_retries=240, retry_interval=0.25)
    # rendezvous: everyone contributes the (host, port) its PEERS can
    # reach — a 0.0.0.0 bind must advertise a routable address (the
    # UDP-connect trick discovers the interface facing the master; no
    # packet is sent)
    host = advertise_addr or self.server.host
    if host == '0.0.0.0':
      probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
      try:
        probe.connect((master_addr, int(master_port)))
        host = probe.getsockname()[0]
      finally:
        probe.close()
    book = self.master.request(
        '_gather', 'rpc:addrs', self.rank, self.world,
        (host, self.server.port))
    self.addrs = {int(r): tuple(a) for r, a in book.items()}
    self._clients: Dict[int, RpcClient] = {}
    self._lock = threading.Lock()
    self._seq: Dict[str, int] = {}

  def client(self, dst: int) -> RpcClient:
    # self-requests go through the socket too: one code path
    dst = int(dst)
    with self._lock:
      c = self._clients.get(dst)
    if c is None:
      # connect OUTSIDE the lock: a slow/dead peer's retry window must
      # not stall requests to healthy ranks or seq()
      c = RpcClient(*self.addrs[dst], connect_retries=40)
      with self._lock:
        have = self._clients.get(dst)
        if have is not None:
          c.close()
          return have
        self._clients[dst] = c
    return c

  def seq(self, base: str) -> str:
    # collective calls happen in the same order on every rank, so a
    # local sequence number makes each collective's master key unique
    with self._lock:
      n = self._seq.get(base, 0)
      self._seq[base] = n + 1
      return f'{base}:{n}'

  def close(self) -> None:
    for c in self._clients.values():
      c.close()
    self.master.close()
    self.server.stop()
    if self.master_server is not None:
      self.master_server.stop()


_fabric: 'Dict[str, _Fabric]' = {}


def _role_scope():
  """(key_prefix, world) of the caller's role group — falls back to the
  whole fabric when no DistContext is set."""
  from .dist_context import get_context
  ctx = get_context()
  fab = _fabric['ctx']
  if ctx is None:
    return 'all', fab.world
  return f'{ctx.role.name}:{ctx.group_name}', ctx.world_size


def init_rpc(master_addr: str = '127.0.0.1', master_port: int = 29388,
             rank: int = None, world_size: int = None,
             advertise_addr: str = None) -> None:
  """Bring up the any-to-any fabric (reference rpc.py:240-346). rank /
  world_size default to the DistContext's GLOBAL identity.
  ``master_port`` must be a concrete pre-agreed port — every rank
  connects to it before any channel exists to share an ephemeral one.
  ``advertise_addr`` overrides the address peers use to reach THIS
  rank's server (multihost deployments behind NAT/overlay networks)."""
  if 'ctx' in _fabric:
    raise RuntimeError('init_rpc called twice (see shutdown_rpc)')
  if not int(master_port):
    raise ValueError('master_port must be a concrete pre-agreed port '
                     '(port 0 cannot rendezvous: ranks would have no '
                     'way to learn the ephemeral choice)')
  if rank is None or world_size is None:
    from .dist_context import get_context
    ctx = get_context()
    if ctx is None:
      raise ValueError('init_rpc needs rank/world_size when no '
                       'DistContext is set')
    rank = ctx.global_rank if rank is None else rank
    world_size = (ctx.global_world_size if world_size is None
                  else world_size)
  _fabric['ctx'] = _Fabric(master_addr, master_port, rank, world_size,
                           advertise_addr=advertise_addr)


def rpc_is_initialized() -> bool:
  return 'ctx' in _fabric


def get_rpc_master_addr() -> str:
  return _fabric['ctx'].master_addr


def get_rpc_master_port() -> int:
  return _fabric['ctx'].master_port


def shutdown_rpc(graceful: bool = True) -> None:
  """Tear the fabric down; with ``graceful`` every rank waits at a
  global barrier first so in-flight requests drain (reference
  rpc.py:349-361). Teardown happens even if the drain barrier fails
  (a dead peer must not wedge shutdown or leak the fabric)."""
  fab = _fabric.get('ctx')
  if fab is None:
    return
  try:
    if graceful:
      global_barrier()
  finally:
    del _fabric['ctx']
    fab.close()


def rpc_register(name: str, callee) -> None:
  """Register a callee on THIS process's server. Register before any
  peer can legitimately request ``name`` (the contract the reference
  enforces with registry-id allocation, rpc.py:435-454)."""
  fn = callee.call if isinstance(callee, RpcCalleeBase) else callee
  _fabric['ctx'].server.register(name, fn)


def rpc_request(dst_rank: int, name: str, *args, **kwargs):
  return _fabric['ctx'].client(dst_rank).request(name, *args, **kwargs)


def rpc_request_async(dst_rank: int, name: str, *args,
                      **kwargs) -> Future:
  return _fabric['ctx'].client(dst_rank).async_request(name, *args,
                                                       **kwargs)


def barrier() -> None:
  """Role-scoped barrier (reference rpc.py:105-211)."""
  scope, world = _role_scope()
  fab = _fabric['ctx']
  fab.master.request('_barrier', fab.seq(f'bar:{scope}'), world)


def all_gather(value) -> dict:
  """Role-scoped gather: returns {role_rank: value}."""
  from .dist_context import get_context
  scope, world = _role_scope()
  ctx = get_context()
  rank = _fabric['ctx'].rank if ctx is None else ctx.rank
  fab = _fabric['ctx']
  return fab.master.request(
      '_gather', fab.seq(f'ag:{scope}'), rank, world, value)


def global_barrier() -> None:
  fab = _fabric['ctx']
  fab.master.request('_barrier', fab.seq('gbar'), fab.world)


def global_all_gather(value) -> dict:
  fab = _fabric['ctx']
  return fab.master.request('_gather', fab.seq('gag'), fab.rank,
                            fab.world, value)


def rpc_sync_data_partitions(data_partitions) -> Dict[int, List[int]]:
  """Gather each rank's served partition list and invert it into
  partition -> [ranks] (reference rpc.py:386-414); feed the result to
  RpcDataPartitionRouter."""
  got = all_gather(list(map(int, data_partitions)))
  out: Dict[int, List[int]] = {}
  for rank in sorted(got):
    for p in got[rank]:
      out.setdefault(int(p), []).append(int(rank))
  return out


# The fabric is GLOBAL-rank addressed (every process has one identity),
# so the reference's role-crossing request variants (rpc.py:477-529
# rpc_global_request*) are the same operation under its names.
rpc_global_request = rpc_request
rpc_global_request_async = rpc_request_async

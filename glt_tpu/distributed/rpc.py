"""Minimal socket RPC fabric for server-client mode.

Reference: graphlearn_torch/python/distributed/rpc.py (529 lines over
torch.distributed.rpc/TensorPipe: callee registry, role-scoped
all_gather/barrier, request wrappers). The TPU build needs RPC only for
the *server-client control/data plane* (worker-mode exchanges ride XLA
collectives instead, SURVEY.md §2.3), so this is a deliberately small
length-prefixed-pickle protocol over TCP: a threaded RpcServer with a
callee registry plus built-in barrier/gather used by the client shutdown
choreography. Payload tensors travel as the channel's packed TensorMap
bytes, not pickled arrays.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List

_HDR = struct.Struct('<Q')


def _send_msg(sock: socket.socket, obj: Any) -> None:
  data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
  sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
  buf = b''
  while len(buf) < n:
    chunk = sock.recv(n - len(buf))
    if not chunk:
      raise ConnectionError('peer closed')
    buf += chunk
  return buf


def _recv_msg(sock: socket.socket) -> Any:
  (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
  return pickle.loads(_recv_exact(sock, n))


class RpcServer:
  """Threaded RPC endpoint with a callee registry
  (the RpcCalleeBase/rpc_register pattern, reference rpc.py:419-473)."""

  def __init__(self, host: str = '127.0.0.1', port: int = 0,
               auto_start: bool = True):
    self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    self._sock.bind((host, port))
    self._sock.listen(64)
    self.host, self.port = self._sock.getsockname()
    self._callees: Dict[str, Callable] = {}
    self._threads: List[threading.Thread] = []
    self._stop = threading.Event()
    self._barriers: Dict[str, threading.Barrier] = {}
    self._gathers: Dict[str, dict] = {}
    self._lock = threading.Lock()
    self.register('_barrier', self._barrier)
    self.register('_gather', self._gather)
    self._accept_thread = None
    if auto_start:
      self.start()

  def start(self) -> None:
    """Begin accepting connections. Callers that register callees after
    construction MUST use auto_start=False and call start() once
    registration is complete — otherwise a fast peer can connect in the
    window before its callee exists (observed under load as
    KeyError('push_edges'))."""
    if self._accept_thread is None:
      self._accept_thread = threading.Thread(target=self._accept_loop,
                                             daemon=True)
      self._accept_thread.start()

  def register(self, name: str, fn: Callable) -> None:
    self._callees[name] = fn

  # built-in synchronization callees (reference rpc.py:105-235)
  def _barrier(self, key: str, world: int) -> bool:
    with self._lock:
      if key not in self._barriers:
        self._barriers[key] = threading.Barrier(world)
      b = self._barriers[key]
    b.wait(timeout=180)
    return True

  def _gather(self, key: str, rank: int, world: int, value) -> dict:
    with self._lock:
      slot = self._gathers.setdefault(
          key, {'vals': {}, 'cond': threading.Condition(self._lock)})
      slot['vals'][rank] = value
      slot['cond'].notify_all()
      while len(slot['vals']) < world:
        if not slot['cond'].wait(timeout=180):
          raise TimeoutError(f'gather {key} timed out')
      return dict(slot['vals'])

  def _accept_loop(self) -> None:
    while not self._stop.is_set():
      try:
        conn, _ = self._sock.accept()
      except OSError:
        break
      t = threading.Thread(target=self._serve_conn, args=(conn,),
                           daemon=True)
      t.start()
      self._threads.append(t)

  def _serve_conn(self, conn: socket.socket) -> None:
    with conn:
      while not self._stop.is_set():
        try:
          name, args, kwargs = _recv_msg(conn)
        except (ConnectionError, EOFError, OSError):
          return
        try:
          fn = self._callees[name]
          _send_msg(conn, ('ok', fn(*args, **kwargs)))
        except BaseException as e:  # deliver errors to the caller
          try:
            _send_msg(conn, ('err', e))
          except Exception:
            _send_msg(conn, ('err', RuntimeError(str(e))))

  def stop(self) -> None:
    self._stop.set()
    try:
      self._sock.close()
    except OSError:
      pass


class RpcClient:
  """One connection per (client, server); thread-safe; async via a pool
  (the reference's async_request_server, dist_client.py:82-101)."""

  _pool = ThreadPoolExecutor(max_workers=16)

  def __init__(self, host: str, port: int, timeout: float = 180.0,
               connect_retries: int = 60, retry_interval: float = 0.5):
    self._addr = (host, port)
    self._timeout = timeout
    self._lock = threading.Lock()
    self._sock = None
    self._connect(connect_retries, retry_interval)

  def _connect(self, retries: int = 1, interval: float = 0.5) -> None:
    # peers race at startup (the reference retries rendezvous the same
    # way, rpc.py:280-322 MAX_RETRY 60 @ 3s)
    import time as _time
    last = None
    for _ in range(max(retries, 1)):
      try:
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        return
      except OSError as e:
        last = e
        _time.sleep(interval)
    raise ConnectionError(
        f'could not connect to {self._addr}: {last}')

  def request(self, name: str, *args, **kwargs):
    with self._lock:
      _send_msg(self._sock, (name, args, kwargs))
      status, payload = _recv_msg(self._sock)
    if status == 'err':
      raise payload
    return payload

  def async_request(self, name: str, *args, **kwargs) -> Future:
    return self._pool.submit(self.request, name, *args, **kwargs)

  def close(self) -> None:
    with self._lock:
      if self._sock is not None:
        try:
          self._sock.close()
        finally:
          self._sock = None

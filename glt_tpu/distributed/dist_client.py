"""Client side of server-client mode.

Reference: graphlearn_torch/python/distributed/dist_client.py (101):
init_client, request_server/async_request_server, and the ordered
shutdown choreography (client barrier -> client 0 tells servers to exit
-> teardown, :57-79).

Resilience (docs/fault_tolerance.md): every server connection rides the
hardened :class:`~glt_tpu.distributed.rpc.RpcClient` (reconnect,
idempotent retry, per-peer circuit breaker), a background
:class:`~glt_tpu.resilience.HealthMonitor` publishes per-server
UP/DEGRADED/DOWN, and remote feature lookups fail over to replica
partitions (``set_replicas``) or degrade to the bounded-staleness
cache + zero-fill answer — counted, never silent.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from ..resilience import (
    CircuitBreaker, DegradedFeatureCache, HealthMonitor, RetryPolicy,
)
from .dist_context import init_client_context
from .dist_server import server_port
from .rpc import RpcClient, ping_endpoint

logger = logging.getLogger(__name__)

_clients: Dict[int, RpcClient] = {}
_num_servers = 0
_client_rank = 0
_num_clients = 0
_health: Optional[HealthMonitor] = None
_metrics = None                         # shared ServingMetrics
_replicas: Dict[int, List[int]] = {}    # server -> replica servers
_feat_cache = DegradedFeatureCache()
_dropouts: set = set()


def init_client(num_servers: int, num_clients: int, client_rank: int,
                master_addr: str = '127.0.0.1',
                master_port: int = 29500,
                rpc_timeout: float = 180.0,
                retry: Optional[RetryPolicy] = None,
                breaker_threshold: int = 5,
                breaker_reset_s: float = 5.0,
                health_interval_s: Optional[float] = 1.0,
                registry=None) -> None:
  """``health_interval_s=None`` disables the background prober (passive
  health from the request path still applies); the other knobs
  parameterize each per-server RpcClient's retry/breaker stack.
  ``registry``: publish the fabric failure counters into a shared
  MetricsRegistry (e.g. ``glt_tpu.obs.get_registry()``, labeled
  ``view="dist_client"``) instead of a private per-session one —
  private stays the default so each init_client session's counters
  start from zero."""
  global _num_servers, _client_rank, _num_clients, _health, _metrics, \
      _feat_cache
  from ..serving.metrics import ServingMetrics
  init_client_context(num_servers, num_clients, client_rank)
  _num_servers = num_servers
  _client_rank = client_rank
  _num_clients = num_clients
  _metrics = ServingMetrics(registry=registry,
                            name='dist_client' if registry is not None
                            else '')
  _dropouts.clear()
  _replicas.clear()
  # fresh per client session: rows cached against a PREVIOUS session's
  # dataset must never be served as this session's degraded answers
  _feat_cache = DegradedFeatureCache()
  for s in range(num_servers):
    _clients[s] = RpcClient(
        master_addr, server_port(master_port, s), timeout=rpc_timeout,
        retry=retry,
        breaker=CircuitBreaker(failure_threshold=breaker_threshold,
                               reset_timeout_s=breaker_reset_s,
                               name=f'server:{s}',
                               registry=registry),
        # apply_delta is MUTATING but safe to retry WITH a request id:
        # the server-side dedup LRU replays the recorded reply on a
        # lost-reply retry instead of staging the delta cut twice
        # (rpc.IDEMPOTENT_CALLEES deliberately excludes it, so opt in
        # per-client here where every callee is a DistServer)
        idempotent=frozenset({'apply_delta'}),
        metrics=_metrics)

  def probe(rank):
    # single-attempt probe on a FRESH socket (rpc.ping_endpoint): it
    # must neither hide failure behind the retry budget nor contend on
    # the shared client's request lock (held for a wedged request's
    # whole recv — probing THROUGH it would stall the sweep)
    addr = (master_addr, server_port(master_port, rank))
    return lambda: ping_endpoint(*addr, timeout=2.0)

  _health = HealthMonitor({s: probe(s) for s in range(num_servers)},
                          interval_s=health_interval_s or 1.0,
                          degraded_after=1, down_after=3)
  if health_interval_s is not None:
    _health.start()


def get_health() -> Optional[HealthMonitor]:
  return _health


def get_metrics():
  return _metrics


def set_replicas(mapping: Dict[int, List[int]]) -> None:
  """Declare replica servers per partition server: a failed lookup on
  ``rank`` fails over, in order, to ``mapping[rank]`` (servers loaded
  with a copy of that partition)."""
  _replicas.clear()
  for k, v in mapping.items():
    _replicas[int(k)] = [int(r) for r in v]


def request_server(server_rank: int, method: str, *args, **kwargs):
  try:
    out = _clients[server_rank].request(method, *args, **kwargs)
  except (ConnectionError, OSError):
    if _health is not None:
      _health.record_failure(server_rank)
    raise
  if _health is not None:
    _health.record_success(server_rank)
  return out


def async_request_server(server_rank: int, method: str, *args, **kwargs):
  return _clients[server_rank].async_request(method, *args, **kwargs)


def request_with_failover(server_rank: int, method: str, *args,
                          **kwargs):
  """``request_server`` that walks the replica chain on connection
  failure. Known-DOWN candidates are skipped (fail fast past them)
  unless they are the last resort — except for an occasional
  rate-limited probe-through (``HealthMonitor.allow_probe``), so a
  restarted primary rejoins even when no background prober is running
  (its passive ``record_success`` is the only recovery signal then)."""
  chain = [int(server_rank)] + _replicas.get(int(server_rank), [])
  last: Optional[BaseException] = None
  for k, rank in enumerate(chain):
    if (_health is not None and _health.is_down(rank)
        and k < len(chain) - 1
        and not _health.allow_probe(rank)):
      last = last or ConnectionError(f'server {rank} is DOWN')
      continue
    try:
      out = request_server(rank, method, *args, **kwargs)
    except (ConnectionError, OSError) as e:
      last = e
      continue
    if k > 0 and _metrics is not None:
      _metrics.record_failover()
    return out
  assert last is not None
  raise last


def get_node_feature(server_rank: int, ids, degrade: bool = True
                    ) -> np.ndarray:
  """Remote node-feature rows with the full degradation ladder:
  primary -> replicas (``set_replicas``) -> bounded-staleness cache
  (recently fetched rows; zero-fill for true misses, both counted in
  the fabric metrics). ``degrade=False`` stops after the replica tier
  and re-raises."""
  from ..channel import pack_message, unpack_message
  ids = np.asarray(ids, np.int64).reshape(-1)
  try:
    out = unpack_message(request_with_failover(
        server_rank, 'get_node_feature', pack_message({'ids': ids})))
  except (ConnectionError, OSError) as e:
    if not degrade:
      raise
    return _feat_cache.serve_counted(
        ids, _metrics, what=f'get_node_feature(server {server_rank})',
        cause=e)
  rows = np.asarray(out['feats'])
  _feat_cache.update(ids, rows)
  return rows


def record_server_dropout(server_rank: int) -> None:
  """A consumer (loader) gave up on this server for the epoch: fold it
  into health + metrics so the degradation is observable."""
  _dropouts.add(int(server_rank))
  if _health is not None:
    _health.record_failure(server_rank)
  if _metrics is not None:
    _metrics.set_gauge('server_dropouts', float(len(_dropouts)))


def fabric_stats() -> dict:
  """Client-side resilience observability: retry/reconnect/breaker/
  failover counters, per-server health, degraded-cache occupancy."""
  return {
      'metrics': _metrics.snapshot() if _metrics is not None else {},
      'health': _health.snapshot() if _health is not None else {},
      'dropouts': sorted(_dropouts),
      'degraded_cache_rows': len(_feat_cache),
  }


def collect_obs(server_rank: int) -> dict:
  """Harvest one server's obs buffers (finished trace spans as
  Chrome-event dicts + its registry snapshot) through the rpc fabric's
  built-in ``_obs`` callee."""
  return request_server(server_rank, '_obs')


def export_fabric_trace(path: str,
                        trace_id: Optional[str] = None) -> str:
  """Assemble ONE Chrome-trace/Perfetto JSON for the whole fabric: this
  client's spans merged with every reachable server's handler spans.
  Server-side spans carry the trace ids the client propagated over rpc,
  so they nest under the originating client spans in the merged view.
  ``trace_id`` filters to a single trace; unreachable servers are
  skipped (a dead peer must not block exporting everyone else)."""
  from ..obs import get_tracer, merge_chrome_traces

  def keep(events):
    if trace_id is None:
      return events
    return [e for e in events if e['args'].get('trace_id') == trace_id]

  lists = [keep(get_tracer().events())]
  for s in range(_num_servers):
    try:
      lists.append(keep(collect_obs(s)['events']))
    except Exception as e:  # noqa: BLE001 — harvest is best-effort
      # a dead endpoint is a counted miss, never an abort: the merged
      # trace still ships with every reachable peer's spans
      logger.warning('obs harvest from server %d failed: %s', s, e)
      from ..obs import get_registry
      get_registry().counter('obs_harvest_misses_total',
                             server=str(s)).inc()
  import json
  with open(path, 'w') as f:
    json.dump(merge_chrome_traces(*lists), f)
  return path


def apply_delta(server_rank: int, ins=None, dels=None, feat_ids=None,
                feat_rows=None, compact: bool = False) -> dict:
  """Post live graph/feature updates to one partition server (its
  ``DistServer.apply_delta``). ``ins``/``dels`` are [2, n] edge blocks
  in that partition's local ids; ``compact=True`` forces the server to
  fold the delta into a fresh snapshot immediately.

  Exactly-once-observable: ``init_client`` marks ``apply_delta``
  idempotent on every per-server RpcClient, so the request carries a
  request id and a retry after a lost reply gets the server's RECORDED
  reply from its dedup LRU — the delta cut is never staged twice (a
  double-stage would double-insert edges and double-bump the snapshot
  version)."""
  from ..channel import pack_message
  msg = {}
  if ins is not None:
    msg['ins'] = np.asarray(ins, np.int64)
  if dels is not None:
    msg['dels'] = np.asarray(dels, np.int64)
  if feat_ids is not None:
    msg['feat_ids'] = np.asarray(feat_ids, np.int64)
    msg['feat_rows'] = np.asarray(feat_rows)
  if compact:
    msg['compact'] = np.ones(1, np.int8)
  return request_server(server_rank, 'apply_delta', pack_message(msg))


def barrier() -> None:
  """Client-group barrier via server 0's built-in (reference rpc
  role-scoped barrier)."""
  request_server(0, '_barrier', f'clients', _num_clients)


def shutdown_client() -> None:
  """Ordered shutdown (reference dist_client.py:57-79). A dead server
  must not wedge teardown: the drain barrier is best-effort."""
  global _health, _metrics
  if not _clients:
    return
  if _health is not None:
    _health.stop()
  try:
    barrier()
  except (ConnectionError, OSError):
    logger.warning('shutdown barrier failed (dead server?); '
                   'tearing down anyway')
  if _client_rank == 0:
    for s in range(_num_servers):
      try:
        request_server(s, 'exit')
      except Exception:
        pass
  for c in _clients.values():
    c.close()
  _clients.clear()
  _health = None
  _dropouts.clear()

"""Client side of server-client mode.

Reference: graphlearn_torch/python/distributed/dist_client.py (101):
init_client, request_server/async_request_server, and the ordered
shutdown choreography (client barrier -> client 0 tells servers to exit
-> teardown, :57-79).
"""
from __future__ import annotations

from typing import Dict

from .dist_context import init_client_context
from .dist_server import server_port
from .rpc import RpcClient

_clients: Dict[int, RpcClient] = {}
_num_servers = 0
_client_rank = 0
_num_clients = 0


def init_client(num_servers: int, num_clients: int, client_rank: int,
                master_addr: str = '127.0.0.1',
                master_port: int = 29500) -> None:
  global _num_servers, _client_rank, _num_clients
  init_client_context(num_servers, num_clients, client_rank)
  _num_servers = num_servers
  _client_rank = client_rank
  _num_clients = num_clients
  for s in range(num_servers):
    _clients[s] = RpcClient(master_addr, server_port(master_port, s))


def request_server(server_rank: int, method: str, *args, **kwargs):
  return _clients[server_rank].request(method, *args, **kwargs)


def async_request_server(server_rank: int, method: str, *args, **kwargs):
  return _clients[server_rank].async_request(method, *args, **kwargs)


def apply_delta(server_rank: int, ins=None, dels=None, feat_ids=None,
                feat_rows=None, compact: bool = False) -> dict:
  """Post live graph/feature updates to one partition server (its
  ``DistServer.apply_delta``). ``ins``/``dels`` are [2, n] edge blocks
  in that partition's local ids; ``compact=True`` forces the server to
  fold the delta into a fresh snapshot immediately."""
  import numpy as np

  from ..channel import pack_message
  msg = {}
  if ins is not None:
    msg['ins'] = np.asarray(ins, np.int64)
  if dels is not None:
    msg['dels'] = np.asarray(dels, np.int64)
  if feat_ids is not None:
    msg['feat_ids'] = np.asarray(feat_ids, np.int64)
    msg['feat_rows'] = np.asarray(feat_rows)
  if compact:
    msg['compact'] = np.ones(1, np.int8)
  return request_server(server_rank, 'apply_delta', pack_message(msg))


def barrier() -> None:
  """Client-group barrier via server 0's built-in (reference rpc
  role-scoped barrier)."""
  request_server(0, '_barrier', f'clients', _num_clients)


def shutdown_client() -> None:
  """Ordered shutdown (reference dist_client.py:57-79)."""
  if not _clients:
    return
  barrier()
  if _client_rank == 0:
    for s in range(_num_servers):
      try:
        request_server(s, 'exit')
      except Exception:
        pass
  for c in _clients.values():
    c.close()
  _clients.clear()

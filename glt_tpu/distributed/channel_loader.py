"""Channel-fed loaders: mp mode (local producer subprocesses) and remote
mode (server-client).

Reference: graphlearn_torch/python/distributed/dist_loader.py mode
dispatch (:130-262): 'mp' spawns DistMpSamplingProducer + ShmChannel and
consumes locally; 'remote' asks servers to create producers and consumes
through RemoteReceivingChannel (:157-197). Both yield the same Batch
pytrees as the inline loaders, so a training loop is mode-agnostic.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..channel import (
    RemoteReceivingChannel, ShmChannel, pack_message,
    unpack_message,
)
from ..channel.mp_channel import MpChannel
from ..loader.transform import Batch
from ..ops.pipeline import edge_hop_offsets
from ..sampler.base import SamplingConfig
from ..utils import as_numpy
from .dist_options import (
    MpDistSamplingWorkerOptions, RemoteDistSamplingWorkerOptions,
)
from .dist_sampling_producer import (
    DistMpSamplingProducer, END_KEY, EPOCH_KEY,
)


def message_to_batch(msg, config: SamplingConfig,
                     device=None) -> Batch:
  """Flat SampleMessage -> Batch pytree (device_put here is the single
  H2D transfer point, the reference's channel.recv + .to(device))."""
  put = lambda a: (jax.device_put(jnp.asarray(a), device)
                   if a is not None else None)
  if '#hop_offsets' in msg:
    # producer-resolved offsets (fanout=-1 resolves worker-side to a
    # static window the client cannot derive from config alone)
    offs = [int(o) for o in msg['#hop_offsets']]
  else:
    offs = edge_hop_offsets(config.batch_size, config.num_neighbors)
  meta = {'n_valid': int(msg['n_valid'][0])} if 'n_valid' in msg else {}
  return Batch(
      x=put(msg.get('nfeats')),
      y=put(msg.get('nlabels')),
      row=put(msg['row']), col=put(msg['col']),
      edge_mask=put(msg['edge_mask']),
      node=put(msg['node']),
      node_count=put(msg['node_count'][0]),
      edge=put(msg.get('eids')),
      edge_attr=put(msg.get('efeats')),
      num_sampled_nodes=put(msg.get('num_sampled_nodes')),
      num_sampled_edges=put(msg.get('num_sampled_edges')),
      metadata=meta,
      batch_size=config.batch_size,
      edge_hop_offsets=tuple(offs))


class MpNeighborLoader:
  """Mp-mode loader: CPU sampling subprocesses feed the training process
  through the native shm ring (reference DistLoader mp branch)."""

  def __init__(self, dataset_builder: Callable, num_neighbors,
               input_nodes, batch_size: int = 512,
               shuffle: bool = False, drop_last: bool = False,
               with_edge: bool = False, collect_features: bool = True,
               seed: Optional[int] = None,
               worker_options: Optional[MpDistSamplingWorkerOptions]
               = None, device=None):
    self.options = worker_options or MpDistSamplingWorkerOptions()
    self.config = SamplingConfig(
        num_neighbors=list(num_neighbors), batch_size=batch_size,
        shuffle=shuffle, drop_last=drop_last, with_edge=with_edge,
        collect_features=collect_features, seed=seed)
    if self.options.use_shm:
      try:
        self.channel = ShmChannel(
            capacity_bytes=self.options.channel_capacity_bytes)
      except Exception:
        self.channel = MpChannel(capacity=256)
    else:
      self.channel = MpChannel(capacity=256)
    self.producer = DistMpSamplingProducer(
        dataset_builder, self.config, as_numpy(input_nodes),
        self.channel, num_workers=self.options.num_workers)
    self.producer.init()
    self.device = device
    self._epoch = 0

  def __iter__(self):
    epoch = self._epoch
    self.producer.produce_all(epoch)
    self._epoch += 1
    ends = 0
    while ends < self.producer.num_expected_ends:
      msg = self.channel.recv(
          timeout_ms=int(self.options.rpc_timeout * 1000))
      if EPOCH_KEY in msg and int(msg[EPOCH_KEY][0]) != epoch:
        continue  # leftover buffered by a partially-consumed prior epoch
      if END_KEY in msg:
        ends += 1
        continue
      yield message_to_batch(msg, self.config, self.device)

  def shutdown(self) -> None:
    self.producer.shutdown()
    if hasattr(self.channel, 'close'):
      self.channel.close()


class RemoteNeighborLoader:
  """Remote-mode loader: sampling runs inside server processes; batches
  are pulled over rpc with prefetch (reference DistLoader remote branch
  + RemoteReceivingChannel)."""

  def __init__(self, num_neighbors, input_nodes_per_server,
               batch_size: int = 512, shuffle: bool = False,
               drop_last: bool = False, with_edge: bool = False,
               collect_features: bool = True, seed: Optional[int] = None,
               worker_options: Optional[RemoteDistSamplingWorkerOptions]
               = None, num_workers_per_server: int = 1, device=None):
    from . import dist_client
    self.options = worker_options or RemoteDistSamplingWorkerOptions()
    ranks = self.options.server_rank
    if ranks is None:
      assert not isinstance(input_nodes_per_server, str), (
          'split-name seeding needs explicit server_rank in options')
      ranks = list(range(len(input_nodes_per_server)))
    if isinstance(ranks, int):
      ranks = [ranks]
    self.server_ranks = ranks
    self.config = SamplingConfig(
        num_neighbors=list(num_neighbors), batch_size=batch_size,
        shuffle=shuffle, drop_last=drop_last, with_edge=with_edge,
        collect_features=collect_features, seed=seed)
    cfg_kwargs = dict(
        num_neighbors=list(num_neighbors), batch_size=batch_size,
        shuffle=shuffle, drop_last=drop_last, with_edge=with_edge,
        collect_features=collect_features, seed=seed)
    self.worker_key = (f'{self.options.worker_key}'
                       f'@client{dist_client._client_rank}')
    if isinstance(input_nodes_per_server, str):
      # split name: every server materializes its own seeds
      # (RemoteNodeSplitSamplerInput parity)
      payloads = [pack_message({'split': np.frombuffer(
          input_nodes_per_server.encode(), np.uint8)})] * len(ranks)
    else:
      payloads = [pack_message({'seeds':
                                as_numpy(s).astype(np.int64)})
                  for s in input_nodes_per_server]
    for rank, payload in zip(ranks, payloads):
      dist_client.request_server(
          rank, 'create_sampling_producer', self.worker_key, payload,
          cfg_kwargs, num_workers_per_server,
          self.options.buffer_capacity_bytes)
    self.device = device
    self._epoch = 0
    self._epoch_active = 0

    self.degraded_servers: set = set()

    def make_fetcher(rank):
      def fetch():
        # passes the epoch this iteration belongs to; a stale puller
        # surviving an abandoned epoch gets #STALE back (server-side
        # guard) instead of consuming a live batch. The per-request
        # deadline keeps a wedged (not dead) server from holding the
        # puller past the rpc budget.
        try:
          out = dist_client.request_server(
              rank, 'fetch_one_sampled_message', self.worker_key,
              self._epoch_active,
              _rpc_timeout=self.options.rpc_timeout)
        except (ConnectionError, OSError) as e:
          # rpc retry + breaker already ran their course: the server is
          # gone. Degrade (finish the epoch minus this server) or
          # re-raise per policy — never hang.
          if not self.options.degrade_on_server_failure:
            raise
          if rank not in self.degraded_servers:
            self.degraded_servers.add(rank)
            dist_client.record_server_dropout(rank)
            import logging
            logging.getLogger(__name__).warning(
                'server %d lost mid-epoch (%s); continuing with %d '
                'surviving server(s)', rank, e,
                len(self.server_ranks) - len(self.degraded_servers))
          raise StopIteration
        if out in (b'#EPOCH_END', b'#STALE'):
          raise StopIteration
        return unpack_message(out)
      return fetch

    self.channel = RemoteReceivingChannel(
        [make_fetcher(r) for r in ranks],
        prefetch_size=self.options.prefetch_size)

  def __iter__(self):
    from . import dist_client
    # order matters: stop old pullers first, then advance the epoch and
    # re-arm the servers, then re-arm the channel — so an in-flight stale
    # fetch can only ever see old-epoch data or #STALE
    self.channel.stop()
    epoch = self._epoch
    self._epoch += 1
    self._epoch_active = epoch
    for rank in self.server_ranks:
      try:
        dist_client.request_server(rank, 'start_new_epoch_sampling',
                                   self.worker_key, epoch)
      except (ConnectionError, OSError):
        # a server that died BETWEEN epochs: its fetcher will observe
        # the same failure and degrade; a recovered server re-arms on
        # the next epoch
        if not self.options.degrade_on_server_failure:
          raise
        if rank not in self.degraded_servers:
          self.degraded_servers.add(rank)
          dist_client.record_server_dropout(rank)
    self.channel.reset()
    while True:
      try:
        msg = self.channel.recv(
            timeout_ms=int(self.options.rpc_timeout * 1000))
      except StopIteration:
        return
      yield message_to_batch(msg, self.config, self.device)

"""DistServer / server lifecycle — the sampling-service side of
server-client mode.

Reference: graphlearn_torch/python/distributed/dist_server.py (296):
producer pool keyed by worker_key with per-producer buffers + epoch
tracking (:50-211), PyG-remote-backend data-plane RPCs (:87-127), poll
fetch (:193-210), lifecycle init_server/wait_and_shutdown_server
(:224-281). Here servers are CPU sampling hosts (TPU clients train);
the transport is glt_tpu.distributed.rpc, batches travel as packed
TensorMap bytes.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ..channel import (
    ShmChannel, pack_message, unpack_message,
)
from ..channel.mp_channel import MpChannel
from ..sampler.base import SamplingConfig
from ..utils import as_numpy
from .dist_context import init_server_context
from .dist_sampling_producer import (
    DistMpSamplingProducer, END_KEY, EPOCH_KEY,
)
from .rpc import RpcServer

_END = b'#EPOCH_END'
_STALE = b'#STALE'


class DistServer:
  """Reference dist_server.py:50-211."""

  def __init__(self, dataset, dataset_builder=None):
    self.dataset = dataset
    self.dataset_builder = dataset_builder
    self._producers: Dict[str, DistMpSamplingProducer] = {}
    self._channels: Dict[str, object] = {}
    self._ends_seen: Dict[str, int] = {}
    self._epochs: Dict[str, int] = {}
    self._stream = None  # lazy StreamIngestor for apply_delta
    self._stream_lock = threading.Lock()
    self._stream_bound_version = 0
    self._exit = threading.Event()

  # -- control plane -----------------------------------------------------

  def ping(self) -> dict:
    """Liveness + readiness probe (HealthMonitor target; richer than
    the rpc fabric's built-in ``_ping``)."""
    from ..obs import get_tracer
    return {
        'ok': True,
        'exiting': self._exit.is_set(),
        'producers': len(self._producers),
        'partition_idx': getattr(self.dataset, 'partition_idx', 0),
        # surfaced so a fleet sweep can see which peers are tracing
        # (their span buffers are harvestable via the _obs builtin)
        'obs_tracing': get_tracer().enabled,
    }

  def get_dataset_meta(self):
    ds = self.dataset
    num_nodes = (None if ds.is_hetero else ds.get_graph().num_nodes)
    return {
        'num_partitions': getattr(ds, 'num_partitions', 1),
        'partition_idx': getattr(ds, 'partition_idx', 0),
        'is_hetero': ds.is_hetero,
        'num_nodes': num_nodes,
        'edge_dir': ds.edge_dir,
    }

  def create_sampling_producer(self, worker_key: str, seeds_bytes: bytes,
                               config_kwargs: dict,
                               num_workers: int = 1,
                               buffer_capacity: int = 256 << 20) -> bool:
    if worker_key in self._producers:
      return True
    assert self.dataset_builder is not None, (
        'server needs a picklable dataset_builder to spawn sampling '
        'workers')
    msg = unpack_message(seeds_bytes)
    if 'split' in msg:
      # server-side seed materialization (reference RemoteSamplerInput /
      # RemoteNodeSplitSamplerInput, sampler/base.py:409-462): the client
      # names a split; this server resolves it against ITS dataset
      from ..typing import Split
      split = Split(bytes(msg['split'].tobytes()).decode().rstrip('\0'))
      seeds = as_numpy(self.dataset.get_split(split))
    else:
      seeds = msg['seeds']
    config = SamplingConfig(**config_kwargs)
    try:
      channel = ShmChannel(capacity_bytes=buffer_capacity)
    except Exception:
      channel = MpChannel(capacity=256)
    producer = DistMpSamplingProducer(
        self.dataset_builder, config, seeds, channel,
        num_workers=num_workers)
    producer.init()
    self._producers[worker_key] = producer
    self._channels[worker_key] = channel
    self._ends_seen[worker_key] = 0
    return True

  def start_new_epoch_sampling(self, worker_key: str, epoch: int) -> bool:
    self._ends_seen[worker_key] = 0
    self._epochs[worker_key] = int(epoch)
    self._producers[worker_key].produce_all(epoch)
    return True

  def fetch_one_sampled_message(self, worker_key: str, epoch=None,
                                timeout_ms: int = 60_000) -> bytes:
    """Returns packed SampleMessage bytes or the epoch-end marker once
    every worker has finished (reference :193-210 poll loop).

    Epoch consistency: every producer message is epoch-tagged. Leftovers
    from an abandoned epoch are discarded here, and a fetch from a stale
    client puller (``epoch`` behind the server's current epoch) gets
    ``#STALE`` back — any current-epoch message it raced onto is returned
    to the buffer first, so no live batch is ever lost to a stale puller.
    """
    producer = self._producers[worker_key]
    channel = self._channels[worker_key]
    deadline = time.time() + timeout_ms / 1000
    while True:
      cur = self._epochs.get(worker_key, 0)
      if epoch is not None and int(epoch) != cur:
        return _STALE
      remaining = max(int((deadline - time.time()) * 1000), 1)
      msg = channel.recv(timeout_ms=remaining)
      cur = self._epochs.get(worker_key, 0)
      msg_epoch = int(msg[EPOCH_KEY][0]) if EPOCH_KEY in msg else cur
      if msg_epoch != cur:
        continue  # leftover from an abandoned epoch: drop
      if epoch is not None and int(epoch) != cur:
        channel.send(msg)  # not ours — hand back to the live epoch
        return _STALE
      if END_KEY in msg:
        self._ends_seen[worker_key] += 1
        if self._ends_seen[worker_key] >= producer.num_expected_ends:
          return _END
        continue
      return pack_message(msg)

  # -- data plane (PyG remote backend, reference :87-127) ----------------

  def get_node_feature(self, ids_bytes: bytes) -> bytes:
    ids = unpack_message(ids_bytes)['ids']
    feat = self.dataset.get_node_feature()
    return pack_message({'feats': feat[ids]})

  def get_node_label(self, ids_bytes: bytes) -> bytes:
    ids = unpack_message(ids_bytes)['ids']
    return pack_message(
        {'labels': as_numpy(self.dataset.get_node_label())[ids]})

  def get_tensor_size(self) -> tuple:
    feat = self.dataset.get_node_feature()
    return tuple(feat.shape)

  def get_edge_index(self) -> bytes:
    g = self.dataset.get_graph()
    ptr, other, _ = g.topo.to_coo()
    if g.layout == 'CSR':
      ei = np.stack([ptr, other])
    else:
      ei = np.stack([other, ptr])
    return pack_message({'edge_index': ei})

  def get_edge_size(self) -> int:
    return self.dataset.get_graph().num_edges

  def get_node_partition_id(self, ids_bytes: bytes) -> bytes:
    ids = unpack_message(ids_bytes)['ids']
    pb = self.dataset.get_node_pb() if hasattr(self.dataset,
                                               'get_node_pb') else None
    if pb is None:
      part = np.zeros(ids.shape[0], np.int32)
    else:
      part = pb[ids]
    return pack_message({'partition': part})

  # -- live updates (stream subsystem) -----------------------------------

  def _stream_ingestor(self, delta_capacity: int = 4096):
    # locked: RpcServer serves each connection on its own thread, and
    # two racing first-calls would each build a snapshot chain off the
    # startup topology — one client's updates silently discarded
    with self._stream_lock:
      if self._stream is None:
        assert not self.dataset.is_hetero, (
            'apply_delta is homogeneous-only for now (hetero needs '
            'per-edge-type delta buffers)')
        from ..stream import SnapshotManager, StreamIngestor
        g = self.dataset.get_graph()
        manager = SnapshotManager(
            g.topo, self.dataset.get_node_feature(),
            delta_capacity=delta_capacity)
        self._stream = StreamIngestor(manager)
      return self._stream

  def apply_delta(self, delta_bytes: bytes) -> dict:
    """Apply live updates to THIS partition's dataset (the fan-out arm
    of the stream subsystem: a coordinator shards updates by partition
    book and posts each server its slice).

    Payload (packed TensorMap): optional ``ins`` / ``dels`` ``[2, n]``
    edge blocks in partition-LOCAL ids, optional ``feat_ids`` +
    ``feat_rows`` feature updates, optional ``compact`` flag (any
    1-element array; forces compaction now instead of the policy).

    On compaction the server's ``dataset.graph`` / ``node_features``
    rebind to the new snapshot, so the data-plane RPCs
    (get_node_feature, get_edge_index, ...) and any producer created
    afterwards serve the fresh graph. Producers already running keep
    their epoch's snapshot until their next epoch restart — staleness
    at epoch granularity, the same bound trainers already accept.
    """
    msg = unpack_message(delta_bytes)
    stream = self._stream_ingestor()
    v0 = stream.manager.current().version
    applied = {'inserts': 0, 'deletes': 0, 'feature_rows': 0}
    if 'ins' in msg:
      ins = as_numpy(msg['ins'])
      applied['inserts'] = stream.insert_edges(ins[0], ins[1])
    if 'dels' in msg:
      dels = as_numpy(msg['dels'])
      applied['deletes'] = stream.delete_edges(dels[0], dels[1])
    if 'feat_ids' in msg:
      applied['feature_rows'] = stream.update_features(
          msg['feat_ids'], msg['feat_rows'])
    if 'compact' in msg:
      stream.flush()
    else:
      stream.maybe_compact()
    # rebind keyed on the VERSION, not on whether this call's explicit
    # flush compacted: the staging calls above auto-compact through the
    # ingestor policy, and another client's call may have swapped too
    version = stream.manager.current().version
    with self._stream_lock:
      if version != self._stream_bound_version:
        from ..data import Graph
        snap = stream.manager.current()
        old = self.dataset.get_graph()
        self.dataset.graph = Graph(snap.topo, mode=old.mode,
                                   device=old.device)
        if snap.feature is not None:
          self.dataset.node_features = snap.feature
        self._stream_bound_version = snap.version
        version = snap.version
    return {
        'applied': applied,
        'version': version,
        'pending': stream.edges.size + (stream.features.size
                                        if stream.features else 0),
        'compacted': version > v0,
    }

  # -- lifecycle ---------------------------------------------------------

  def exit(self) -> bool:
    for producer in self._producers.values():
      producer.shutdown()
    self._producers.clear()
    self._exit.set()
    return True

  @property
  def should_exit(self) -> bool:
    return self._exit.is_set()


_server: Optional[DistServer] = None
_rpc_server: Optional[RpcServer] = None


def server_port(master_port: int, server_rank: int) -> int:
  return master_port + server_rank


def init_server(num_servers: int, num_clients: int, server_rank: int,
                dataset, master_addr: str = '127.0.0.1',
                master_port: int = 29500, dataset_builder=None
                ) -> DistServer:
  """Reference dist_server.py:224-260: bind the rpc endpoint (port =
  master_port + rank by convention) and expose the DistServer surface."""
  global _server, _rpc_server
  init_server_context(num_servers, num_clients, server_rank)
  _server = DistServer(dataset, dataset_builder)
  _rpc_server = RpcServer(master_addr,
                          server_port(master_port, server_rank),
                          auto_start=False)
  for name in ('get_dataset_meta', 'create_sampling_producer',
               'start_new_epoch_sampling', 'fetch_one_sampled_message',
               'get_node_feature', 'get_node_label', 'get_tensor_size',
               'get_edge_index', 'get_edge_size',
               'get_node_partition_id', 'apply_delta', 'exit', 'ping'):
    _rpc_server.register(name, getattr(_server, name))
  _rpc_server.start()  # accept only after all callees exist
  return _server


def wait_and_shutdown_server(poll_s: float = 0.2) -> None:
  """Reference :263-281 poll loop."""
  assert _server is not None
  while not _server.should_exit:
    time.sleep(poll_s)
  shutdown_server()


def shutdown_server() -> None:
  global _server, _rpc_server
  if _rpc_server is not None:
    _rpc_server.stop()
  _server = None
  _rpc_server = None


def get_server() -> Optional[DistServer]:
  """The process's DistServer singleton (reference
  dist_server.py:216-221) — None before init_server."""
  return _server

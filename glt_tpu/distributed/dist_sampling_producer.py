"""Sampling producers: subprocess workers streaming sampled batches.

Reference: graphlearn_torch/python/distributed/dist_sampling_producer.py
(DistMpSamplingProducer:206-294 spawns N workers running
_sampling_worker_loop:54-163, commands over a task queue, batches over
the shm channel; DistCollocatedSamplingProducer:297-365 is the in-process
variant). TPU translation: workers force the CPU jax backend (the chip
belongs to the trainer) and stream flat SampleMessages through the native
C++ shm ring; the consumer device_puts them. Epoch protocol: one
``_END_MSG`` per worker closes the epoch, as the reference's epoch
tracking does.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Callable, List

import numpy as np

from ..channel import ChannelBase, SampleMessage
from ..sampler.base import SamplingConfig
from ..utils import as_numpy

_SAMPLE_ALL = 'SAMPLE_ALL'
_EXIT = 'EXIT'
END_KEY = '#END'
EPOCH_KEY = '#epoch'
MP_STATUS_CHECK_INTERVAL = 5.0  # reference dist_sampling_producer.py:41-44


def flatten_sampler_output(out, y=None, x=None,
                           edge_attr=None) -> SampleMessage:
  """SamplerOutput -> flat SampleMessage (the reference _colloate_fn keys,
  dist_neighbor_sampler.py:689-807, including the ``efeats`` collate)."""
  msg = {
      'node': as_numpy(out.node),
      'node_count': as_numpy(out.node_count).reshape(1),
      'row': as_numpy(out.row),
      'col': as_numpy(out.col),
      'edge_mask': as_numpy(out.edge_mask),
      'batch': as_numpy(out.batch),
      'num_sampled_nodes': as_numpy(out.num_sampled_nodes),
      'num_sampled_edges': as_numpy(out.num_sampled_edges),
  }
  if out.edge is not None:
    msg['eids'] = as_numpy(out.edge)
  if y is not None:
    msg['nlabels'] = as_numpy(y)
  if x is not None:
    msg['nfeats'] = as_numpy(x)
  if edge_attr is not None:
    msg['efeats'] = as_numpy(edge_attr)
  return msg


def _sampling_worker_loop(rank: int, num_workers: int,
                          dataset_builder: Callable,
                          config: SamplingConfig,
                          seeds: np.ndarray,
                          task_queue, channel: ChannelBase) -> None:
  """Reference _sampling_worker_loop (dist_sampling_producer.py:54-163)."""
  # the TPU chip belongs to the trainer; workers sample on host CPU
  os.environ.setdefault('XLA_FLAGS', '')
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from ..sampler import NeighborSampler

  ds = dataset_builder()
  sampler = NeighborSampler(
      ds.graph, config.num_neighbors, with_edge=config.with_edge,
      with_weight=config.with_weight, edge_dir=config.edge_dir,
      seed=(config.seed or 0) + rank)
  # the sampler resolves fanout=-1 to a static window; ship the resolved
  # hop offsets with every message so the consumer's Batch slices line up
  from ..ops.pipeline import edge_hop_offsets
  resolved = (sampler.num_neighbors if not sampler.is_hetero
              else config.num_neighbors)
  hop_offs = (np.array(edge_hop_offsets(config.batch_size, resolved),
                       np.int32) if not sampler.is_hetero else None)
  labels = ds.node_labels
  feats = ds.node_features if config.collect_features else None
  efeats = (ds.edge_features
            if config.with_edge and config.collect_features else None)

  while True:
    try:
      cmd = task_queue.get(timeout=MP_STATUS_CHECK_INTERVAL)
    except Exception:
      continue
    if cmd[0] == _EXIT:
      break
    epoch = cmd[1]
    order = np.arange(seeds.shape[0])
    if config.shuffle:
      order = np.random.default_rng(epoch * num_workers + rank) \
          .permutation(seeds.shape[0])
    bs = config.batch_size
    n = order.shape[0]
    for lo in range(0, n, bs):
      sel = order[lo:lo + bs]
      if sel.shape[0] < bs:
        if config.drop_last:
          break
        pad = np.full(bs - sel.shape[0], sel[-1] if sel.size else 0,
                      sel.dtype)
        sel = np.concatenate([sel, pad])
      batch_seeds = seeds[sel]
      n_valid = min(bs, n - lo)
      out = sampler.sample_from_nodes(batch_seeds, n_valid=n_valid)
      y = labels[batch_seeds] if labels is not None else None
      x = None
      if feats is not None:
        x = feats[as_numpy(out.node).clip(min=0)]
      ea = None
      if efeats is not None and out.edge is not None:
        ea = efeats[as_numpy(out.edge).clip(min=0)]
      msg = flatten_sampler_output(out, y=y, x=x, edge_attr=ea)
      msg['n_valid'] = np.array([n_valid], np.int32)
      if hop_offs is not None:
        msg['#hop_offsets'] = hop_offs
      # every message is epoch-tagged so consumers can discard leftovers
      # from a partially-consumed, abandoned epoch
      msg[EPOCH_KEY] = np.array([epoch], np.int32)
      channel.send(msg)
    channel.send({END_KEY: np.array([rank], np.int32),
                  EPOCH_KEY: np.array([epoch], np.int32)})


class DistMpSamplingProducer:
  """Spawn-based producer pool (reference :206-294)."""

  def __init__(self, dataset_builder: Callable, config: SamplingConfig,
               seeds, channel: ChannelBase, num_workers: int = 1):
    self.dataset_builder = dataset_builder
    self.config = config
    self.seeds = as_numpy(seeds).astype(np.int64)
    self.channel = channel
    self.num_workers = int(num_workers)
    self._ctx = mp.get_context('spawn')
    self._task_queues = []
    self._workers: List[mp.Process] = []
    self._respawns: dict = {}
    self.max_respawns_per_rank = 3

  def _spawn(self, rank: int):
    splits = np.array_split(self.seeds, self.num_workers)
    tq = self._ctx.Queue()
    w = self._ctx.Process(
        target=_sampling_worker_loop,
        args=(rank, self.num_workers, self.dataset_builder, self.config,
              splits[rank], tq, self.channel),
        daemon=True)
    w.start()
    return tq, w

  def init(self) -> None:
    for rank in range(self.num_workers):
      tq, w = self._spawn(rank)
      self._task_queues.append(tq)
      self._workers.append(w)

  def respawn_dead(self) -> int:
    """Self-healing (exceeds the reference, which only times out): any
    worker that died is relaunched with its own seed slice so the NEXT
    epoch is complete again. Returns the number respawned. A mid-epoch
    death still surfaces as a recv timeout for that epoch — the healing
    boundary is the epoch, where re-arming cannot duplicate batches.

    Each respawn is logged with the dead worker's exit code, and a
    persistent crash loop (a rank respawned more than
    ``max_respawns_per_rank`` times) raises instead of silently eating
    an rpc timeout per epoch."""
    import logging
    n = 0
    for rank, w in enumerate(self._workers):
      if not w.is_alive():
        self._respawns[rank] = self._respawns.get(rank, 0) + 1
        logging.getLogger(__name__).warning(
            'sampling worker %d died (exitcode=%s); respawning '
            '(%d/%d)', rank, w.exitcode, self._respawns[rank],
            self.max_respawns_per_rank)
        if self._respawns[rank] > self.max_respawns_per_rank:
          raise RuntimeError(
              f'sampling worker {rank} crash-looped '
              f'{self._respawns[rank]} times (last exitcode '
              f'{w.exitcode}); check the dataset_builder in the '
              'subprocess')
        tq, w2 = self._spawn(rank)
        self._task_queues[rank] = tq
        self._workers[rank] = w2
        n += 1
    return n

  def produce_all(self, epoch: int = 0) -> None:
    self.respawn_dead()
    for tq in self._task_queues:
      tq.put((_SAMPLE_ALL, epoch))

  def shutdown(self) -> None:
    for tq in self._task_queues:
      try:
        tq.put((_EXIT,))
      except Exception:
        pass
    for w in self._workers:
      w.join(timeout=10)
      if w.is_alive():
        w.terminate()
    self._workers = []

  @property
  def num_expected_ends(self) -> int:
    return self.num_workers


class DistCollocatedSamplingProducer:
  """Synchronous in-process producer (reference :297-365)."""

  def __init__(self, dataset, config: SamplingConfig, seeds):
    from ..sampler import NeighborSampler
    self.config = config
    self.seeds = as_numpy(seeds).astype(np.int64)
    self.sampler = NeighborSampler(
        dataset.graph, config.num_neighbors, with_edge=config.with_edge,
        with_weight=config.with_weight, edge_dir=config.edge_dir,
        seed=config.seed)
    self.dataset = dataset

  def sample_batch(self, batch_seeds: np.ndarray, n_valid: int):
    out = self.sampler.sample_from_nodes(batch_seeds, n_valid=n_valid)
    y = (self.dataset.node_labels[batch_seeds]
         if self.dataset.node_labels is not None else None)
    return out, y

"""DistSubGraphLoader — induced-subgraph batches over sharded topology.

Reference: graphlearn_torch/python/distributed/dist_subgraph_loader.py
(94): full-neighborhood expansion (NeighborSampler with fanout -1) then
induced-subgraph extraction, distributed. TPU formulation: expand with a
static ``max_degree`` window per hop through the collective sampler
(exact when max_degree bounds the true degrees, the same condition the
single-device subgraph op documents), then keep the sampled edges whose
endpoints both landed in the final node set — with full-degree windows
every induced edge is discovered, so the filter is exact.
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ..utils import as_numpy
from .dist_feature import DistFeature
from .dist_graph import DistGraph
from .dist_neighbor_sampler import DistNeighborSampler


class DistSubGraphLoader:
  def __init__(self, dist_graph: DistGraph,
               num_hops: int,
               input_nodes_per_device,
               max_degree: Optional[int] = None,
               dist_feature: Optional[DistFeature] = None,
               batch_size: int = 64,
               shuffle: bool = False,
               drop_last: bool = False,
               seed: Optional[int] = None,
               rng: Optional[np.random.Generator] = None,
               edge_feature: Optional[DistFeature] = None):
    self.g = dist_graph
    self.n_dev = dist_graph.mesh.shape[dist_graph.axis]
    self.seeds = [as_numpy(s).astype(np.int64)
                  for s in input_nodes_per_device]
    assert len(self.seeds) == self.n_dev
    self.max_degree = int(max_degree or dist_graph.max_degree)
    self.sampler = DistNeighborSampler(
        dist_graph, [self.max_degree] * num_hops, with_edge=True,
        seed=seed)
    #: second pass: one full-window hop over the ENTIRE node set — the
    #: sampled walk alone misses edges between two outermost-hop nodes
    #: (neither endpoint's out-edges are expanded); this is the
    #: SubGraphOp-style extraction pass
    self._extract = DistNeighborSampler(
        dist_graph, [self.max_degree], with_edge=True, seed=seed)
    self.feature = dist_feature
    self.edge_feature = edge_feature
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.rng = rng or np.random.default_rng(seed or 0)

  def __len__(self):
    n = min(s.shape[0] for s in self.seeds)
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def __iter__(self) -> Iterator[dict]:
    orders = [(self.rng.permutation(s.shape[0]) if self.shuffle
               else np.arange(s.shape[0])) for s in self.seeds]
    for it in range(len(self)):
      lo = it * self.batch_size
      seeds = np.zeros((self.n_dev, self.batch_size), np.int64)
      n_valid = np.zeros(self.n_dev, np.int32)
      for p in range(self.n_dev):
        sel = orders[p][lo:lo + self.batch_size]
        n_valid[p] = sel.shape[0]
        if sel.shape[0]:
          chunk = self.seeds[p][sel]
          seeds[p, :sel.shape[0]] = chunk
          seeds[p, sel.shape[0]:] = chunk[-1] if chunk.size else 0
      out = self.sampler.sample_from_nodes(seeds, n_valid)
      # extraction pass: expand EVERY set node one hop; because the set
      # is unique and fed in order, the extractor's seed labels coincide
      # with the set's own labels, so membership is 'label < count'
      set_nodes = np.maximum(np.asarray(out['node']), 0)
      counts = np.asarray(out['node_count'])
      ex = self._extract.sample_from_nodes(set_nodes, counts)
      rows = np.asarray(ex['row'])
      cols = np.asarray(ex['col'])
      masks = np.asarray(ex['edge_mask'])
      eids = np.asarray(ex['edge'])
      all_ea = None
      if self.edge_feature is not None:
        # ONE static-shape whole-mesh lookup over the padded [P, E]
        # slot grid (keeps DistFeature's compile-once contract); the
        # ragged induced lists below slice it host-side
        self.edge_feature.collate_edge_attr(ex)
        all_ea = np.asarray(ex['edge_attr'])
      induced = []
      for p in range(self.n_dev):
        ok = masks[p] & (rows[p] >= 0) & (cols[p] >= 0) \
            & (rows[p] < counts[p]) & (cols[p] < counts[p])
        e = eids[p][ok]
        r = rows[p][ok]
        c = cols[p][ok]
        _, first = np.unique(e, return_index=True)
        item = dict(rows=r[first], cols=c[first], eids=e[first])
        if all_ea is not None:  # uniform schema, even when empty
          item['edge_attr'] = all_ea[p][ok][first]
        induced.append(item)
      out['induced'] = induced
      if self.feature is not None:
        import jax.numpy as jnp
        node = out['node'].reshape(-1)
        valid = (jnp.arange(out['node'].shape[1])[None, :]
                 < out['node_count'][:, None]).reshape(-1)
        x = self.feature.lookup(jnp.maximum(node, 0), valid)
        out['x'] = x.reshape(out['node'].shape + (-1,))
      out['n_valid'] = n_valid
      yield out

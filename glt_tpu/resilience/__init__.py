"""Fault-tolerance fabric: retry/backoff, circuit breaking, health
monitoring, degradation tiers, and deterministic chaos injection.

The failure model, retry/idempotency contract, and degradation tiers
are documented in docs/fault_tolerance.md. Everything here is host-side
control-plane code — none of it touches traced/jitted programs, so the
zero-steady-state-recompile guarantees of the serving and stream layers
are preserved by construction.
"""
from .chaos import (  # noqa: F401
    ChaosChannel, ChaosTcpProxy, FaultPlan, chaos_seed, flaky,
)
from .health import (  # noqa: F401
    DEGRADED, DOWN, UP, DegradedFeatureCache, HealthMonitor,
)
from .retry import (  # noqa: F401
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker, CircuitOpenError,
    RetryPolicy,
)

__all__ = [
    'ChaosChannel', 'ChaosTcpProxy', 'FaultPlan', 'chaos_seed', 'flaky',
    'DegradedFeatureCache', 'HealthMonitor', 'UP', 'DEGRADED', 'DOWN',
    'CircuitBreaker', 'CircuitOpenError', 'RetryPolicy',
    'CLOSED', 'OPEN', 'HALF_OPEN',
]

"""Retry policy + per-peer circuit breaker — the two host-side
primitives the fault-tolerant fabric is built from.

Failure model (docs/fault_tolerance.md): peers are fail-stop processes
behind lossy links. A transient fault (dropped frame, flaky link, peer
restart) is survived by a bounded *retry with capped exponential
backoff + jitter*; a persistent fault (dead peer) must FAIL FAST — the
:class:`CircuitBreaker` turns the N-th consecutive connection error
into an immediate :class:`CircuitOpenError` instead of letting every
caller eat a full connect/recv timeout against a corpse.

Both primitives are deliberately transport-agnostic host-side objects:
they never touch traced code, so wiring them through the serving and
distributed layers preserves the zero-steady-state-recompile guarantee.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Optional

#: breaker states (the classic 3-state machine)
CLOSED = 'CLOSED'
OPEN = 'OPEN'
HALF_OPEN = 'HALF_OPEN'


class CircuitOpenError(ConnectionError):
  """Fail-fast rejection: the peer's breaker is OPEN. Subclasses
  ConnectionError so existing connection-failure handling (failover,
  epoch degradation) treats a breaker rejection exactly like the dead
  peer it stands in for."""


@dataclasses.dataclass
class RetryPolicy:
  """Capped exponential backoff with full jitter.

  delay(attempt) = uniform(min_fraction, 1) * min(base * 2^attempt, cap)

  Args:
    max_attempts: total tries (1 = no retry).
    base_delay_s: backoff base for attempt 0.
    max_delay_s: cap on the un-jittered delay.
    jitter: fraction of the delay that is randomized; 0 = deterministic
      (chaos tests pin schedules), 1 = classic full jitter.
  """
  max_attempts: int = 4
  base_delay_s: float = 0.05
  max_delay_s: float = 2.0
  jitter: float = 0.5

  def delay(self, attempt: int, rng: Optional[random.Random] = None
            ) -> float:
    d = min(self.base_delay_s * (2.0 ** max(attempt, 0)),
            self.max_delay_s)
    if self.jitter <= 0:
      return d
    r = (rng or random).uniform(1.0 - self.jitter, 1.0)
    return d * r

  def sleep(self, attempt: int,
            rng: Optional[random.Random] = None) -> float:
    d = self.delay(attempt, rng)
    if d > 0:
      time.sleep(d)
    return d


class CircuitBreaker:
  """Per-peer CLOSED -> OPEN -> HALF_OPEN breaker.

  CLOSED: requests flow; ``failure_threshold`` CONSECUTIVE failures
  trip it OPEN (a single success resets the streak — an occasionally
  flaky peer never trips).
  OPEN: ``allow()`` is False (callers raise CircuitOpenError without
  touching the socket) until ``reset_timeout_s`` elapses, then one
  probe is admitted (HALF_OPEN).
  HALF_OPEN: exactly one in-flight probe; its success closes the
  breaker, its failure re-opens (and re-arms the timeout).

  Thread-safe; all transitions happen under one lock. ``on_open`` is
  called (outside the lock) every CLOSED/HALF_OPEN -> OPEN transition —
  the metrics hook. Every open also lands on the process flight
  recorder (``glt_tpu.obs.get_recorder().trip('breaker_open')``): a
  breaker opening is exactly the moment a postmortem wants the recent
  span/counter context captured. ``name`` labels the peer in that
  event (optional, purely observational).

  ``labels`` (e.g. ``{'shard': 'shard0', 'replica': 'r1'}``) ride
  every trip payload and every registry series, so two shards sharing
  one registry never merge their breaker series — the fleet lesson:
  an unlabeled ``breaker_opens_total`` summed across shards cannot
  tell "shard 2 is dying" from "everything is mildly flaky". With
  ``registry=`` set, the breaker also publishes a labeled
  ``breaker_state`` gauge (0=CLOSED, 1=HALF_OPEN, 2=OPEN) and a
  ``breaker_opens_total`` counter on every transition.
  """

  def __init__(self, failure_threshold: int = 5,
               reset_timeout_s: float = 5.0,
               on_open: Optional[Callable[[], None]] = None,
               name: str = '',
               labels: Optional[Dict[str, str]] = None,
               registry=None):
    assert failure_threshold >= 1
    self.failure_threshold = int(failure_threshold)
    self.reset_timeout_s = float(reset_timeout_s)
    self.on_open = on_open
    self.name = str(name)
    self.labels = {str(k): str(v) for k, v in (labels or {}).items()}
    self.registry = registry
    self._lock = threading.Lock()
    self._state = CLOSED
    self._consecutive_failures = 0
    self._opened_at = 0.0
    self._probe_inflight = False
    self.opens = 0  # lifetime OPEN transitions (metrics)

  @property
  def state(self) -> str:
    with self._lock:
      return self._state_locked()

  def _state_locked(self) -> str:
    if (self._state == OPEN and not self._probe_inflight
        and time.monotonic() - self._opened_at >= self.reset_timeout_s):
      return HALF_OPEN
    return self._state

  def allow(self) -> bool:
    """True if a request may proceed. In HALF_OPEN this ADMITS the one
    probe (side effect: the token is taken until record_*)."""
    with self._lock:
      s = self._state_locked()
      if s == CLOSED:
        return True
      if s == HALF_OPEN and not self._probe_inflight:
        self._probe_inflight = True
        return True
      return False

  def _series_labels(self) -> Dict[str, str]:
    out = dict(self.labels)
    if self.name:
      out.setdefault('breaker', self.name)
    return out

  def _publish_state(self, state: str) -> None:
    """Labeled ``breaker_state`` gauge (0/1/2) — best-effort, outside
    the lock; metrics must never wedge the failure path."""
    if self.registry is None:
      return
    try:
      code = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}[state]
      self.registry.set('breaker_state', code, **self._series_labels())
    except Exception:
      pass

  def record_success(self) -> None:
    with self._lock:
      self._state = CLOSED
      self._consecutive_failures = 0
      self._probe_inflight = False
    self._publish_state(CLOSED)

  def record_failure(self) -> None:
    fire = False
    with self._lock:
      self._consecutive_failures += 1
      if self._probe_inflight:  # failed HALF_OPEN probe: re-open
        self._probe_inflight = False
        self._state = OPEN
        self._opened_at = time.monotonic()
        self.opens += 1
        fire = True
      elif (self._state == CLOSED
            and self._consecutive_failures >= self.failure_threshold):
        self._state = OPEN
        self._opened_at = time.monotonic()
        self.opens += 1
        fire = True
      # snapshot the trip payload under the lock: a concurrent
      # record_success resetting the streak before the trip below
      # would otherwise record consecutive_failures=0 for an OPEN
      failures, opens = self._consecutive_failures, self.opens
    if fire:
      self._publish_state(OPEN)
      if self.registry is not None:
        try:
          self.registry.inc('breaker_opens_total',
                            **self._series_labels())
        except Exception:
          pass
      if self.on_open is not None:
        try:
          self.on_open()
        except Exception:
          pass
      try:  # postmortem hook — must never break the failure path
        from ..obs.recorder import get_recorder
        payload = dict(self.labels)
        payload.update(breaker=self.name,
                       consecutive_failures=failures, opens=opens)
        get_recorder().trip('breaker_open', **payload)
      except Exception:
        pass

  def release_probe(self) -> None:
    """Return a HALF_OPEN probe token taken by ``allow()`` when the
    attempt aborted before the peer was ever exercised (an unpicklable
    argument, a caller bug) — neither a success nor a peer failure, so
    the token must come back or the breaker wedges OPEN forever with
    no probe ever admitted again."""
    with self._lock:
      self._probe_inflight = False

  def reset(self) -> None:
    """Force-close (admin/testing hook)."""
    self.record_success()

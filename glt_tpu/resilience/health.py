"""Health monitoring + degradation primitives for the distributed
fabric.

:class:`HealthMonitor` runs a background probe loop over named targets
(partition servers, serving endpoints) and publishes a 3-level status:

  * UP       — last probe succeeded;
  * DEGRADED — ``degraded_after`` consecutive probe failures (the peer
    is struggling: callers should prefer replicas but may still try);
  * DOWN     — ``down_after`` consecutive failures (callers must not
    wait on this peer; fail over or degrade).

Call sites can also feed *passive* observations (``record_failure`` /
``record_success`` from the request path) so a peer that dies between
probe ticks is demoted immediately rather than an interval later.

:class:`DegradedFeatureCache` is the bounded-staleness answer for
remote feature lookups when every replica of a partition is gone:
recently-fetched rows are served from a host-side LRU and true misses
zero-fill — an epoch completes minus one server instead of
deadlocking (the documented degradation tier, docs/fault_tolerance.md).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

UP = 'UP'
DEGRADED = 'DEGRADED'
DOWN = 'DOWN'


class HealthMonitor:
  """Background prober publishing UP/DEGRADED/DOWN per target.

  Args:
    probes: {name: callable} — a probe returns normally for healthy,
      raises for unhealthy (e.g. ``lambda: client.request('_ping')``).
    interval_s: probe cadence.
    degraded_after / down_after: consecutive-failure thresholds.
    on_change: ``fn(name, old_status, new_status)`` called outside the
      lock on every transition (metrics / logging hook).
    labels: extra series labels (e.g. ``{'shard': 'shard1'}``) riding
      every published ``health_status`` point, so two shards' monitors
      on one shared registry never merge series (target names alone
      collide: every shard calls its replicas ``r0``/``r1``).
    registry: optional MetricsRegistry; when set, every transition
      publishes a labeled ``health_status`` gauge
      (0=UP, 1=DEGRADED, 2=DOWN) per target.
  """

  def __init__(self, probes: Dict[object, Callable[[], object]],
               interval_s: float = 1.0, degraded_after: int = 1,
               down_after: int = 3,
               on_change: Optional[Callable] = None,
               labels: Optional[Dict[str, str]] = None,
               registry=None):
    assert 1 <= degraded_after <= down_after
    self.interval_s = float(interval_s)
    self.degraded_after = int(degraded_after)
    self.down_after = int(down_after)
    self.on_change = on_change
    self.labels = {str(k): str(v) for k, v in (labels or {}).items()}
    self.registry = registry
    self._probes = dict(probes)
    self._lock = threading.Lock()
    self._cond = threading.Condition(self._lock)
    self._failures = {k: 0 for k in self._probes}
    self._status = {k: UP for k in self._probes}
    self._last_probe: Dict[object, float] = {}
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  # -- status surface ----------------------------------------------------

  def status(self, name) -> str:
    with self._lock:
      return self._status.get(name, DOWN)

  def is_up(self, name) -> bool:
    return self.status(name) == UP

  def is_down(self, name) -> bool:
    return self.status(name) == DOWN

  def snapshot(self) -> dict:
    with self._lock:
      return dict(self._status)

  def healthy(self) -> list:
    """Targets currently not DOWN."""
    with self._lock:
      return [k for k, s in self._status.items() if s != DOWN]

  def allow_probe(self, name,
                  min_interval_s: Optional[float] = None) -> bool:
    """Admit an occasional live request through to a DOWN peer so
    passive-only deployments (no background prober running) can
    observe recovery — callers that skip DOWN peers would otherwise
    never exercise a restarted one and it would stay DOWN forever.
    Rate-limited to one admission per ``min_interval_s`` (defaults to
    the probe cadence); stamps the admission time."""
    if min_interval_s is None:
      min_interval_s = self.interval_s
    now = time.monotonic()
    with self._lock:
      if now - self._last_probe.get(name, 0.0) >= min_interval_s:
        self._last_probe[name] = now
        return True
      return False

  def wait_for(self, name, status: str, timeout_s: float = 10.0) -> bool:
    """Block until ``name`` reaches ``status`` (tests / choreography)."""
    deadline = time.monotonic() + timeout_s
    with self._cond:
      while self._status.get(name) != status:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          return False
        self._cond.wait(timeout=remaining)
      return True

  # -- observations ------------------------------------------------------

  def _transition(self, name, failures: int) -> None:
    """Map a consecutive-failure count to a status; must hold _lock."""
    if failures >= self.down_after:
      new = DOWN
    elif failures >= self.degraded_after:
      new = DEGRADED
    else:
      new = UP
    old = self._status.get(name, UP)
    self._status[name] = new
    self._cond.notify_all()
    if new != old:
      logger.warning('health: %s %s -> %s', name, old, new)
      if self.registry is not None:
        try:  # registry has its own lock and never re-enters ours
          self.registry.set('health_status',
                            {UP: 0.0, DEGRADED: 1.0, DOWN: 2.0}[new],
                            target=str(name), **self.labels)
        except Exception:
          pass
      if self.on_change is not None:
        cb = self.on_change
        # fire outside the lock: a callback that re-enters status()
        # must not deadlock
        threading.Thread(target=cb, args=(name, old, new),
                         daemon=True).start()

  def record_failure(self, name) -> None:
    """Passive demotion from the request path (a failed rpc is as good
    an observation as a failed probe — and arrives sooner)."""
    with self._lock:
      if name not in self._failures:
        return
      self._failures[name] += 1
      self._transition(name, self._failures[name])

  def record_success(self, name) -> None:
    with self._lock:
      if name not in self._failures:
        return
      self._failures[name] = 0
      self._transition(name, 0)

  # -- probing -----------------------------------------------------------

  def check_now(self, name=None) -> dict:
    """Run probes synchronously (all targets, or one) and return the
    updated status map — the deterministic path tests drive."""
    names = [name] if name is not None else list(self._probes)
    for n in names:
      try:
        self._probes[n]()
      except Exception:
        self.record_failure(n)
      else:
        self.record_success(n)
    return self.snapshot()

  def start(self, interval_s: Optional[float] = None) -> 'HealthMonitor':
    if interval_s is not None:
      self.interval_s = float(interval_s)
    assert self._thread is None, 'monitor already started'
    self._stop.clear()

    def loop():
      while not self._stop.wait(self.interval_s):
        try:
          self.check_now()
        except Exception:  # a probe dict mutation race etc: keep going
          logger.exception('health probe sweep failed')

    self._thread = threading.Thread(target=loop, daemon=True,
                                    name='glt-health')
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5)
      self._thread = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.stop()


class DegradedFeatureCache:
  """Bounded LRU of node id -> feature row, fed by successful remote
  fetches and consulted only when a partition has NO live replica.

  ``serve`` zero-fills true misses and reports how many rows were
  cached vs zero-filled, so metrics can account for every degraded
  lookup (the bounded-staleness contract: stale-but-real rows beat a
  deadlocked epoch; zeros are the documented last resort and are
  COUNTED, never silent).
  """

  def __init__(self, capacity: int = 200_000):
    self.capacity = int(capacity)
    self._rows: 'dict[int, np.ndarray]' = {}
    self._lock = threading.Lock()
    self.feature_dim: Optional[int] = None
    self.dtype = np.float32

  def __len__(self) -> int:
    with self._lock:
      return len(self._rows)

  def update(self, ids, rows) -> None:
    if self.capacity <= 0:
      return
    ids = np.asarray(ids).reshape(-1)
    rows = np.asarray(rows)
    with self._lock:
      self.feature_dim = int(rows.shape[1])
      self.dtype = rows.dtype
      for i, row in zip(ids.tolist(), rows):
        self._rows[int(i)] = np.array(row, copy=True)
      if len(self._rows) > self.capacity:
        # cheap wholesale trim (this cache is a disaster fallback, not
        # a hot path): drop the oldest-inserted overflow
        drop = len(self._rows) - self.capacity
        for k in list(self._rows)[:drop]:
          del self._rows[k]

  def serve_counted(self, ids, metrics=None, what: str = 'lookup',
                    cause: Optional[BaseException] = None) -> np.ndarray:
    """``serve`` plus the bookkeeping every degradation tier shares —
    stale-serve / zero-fill counters and the mandatory (never silent)
    warning — so the dist_client and cold-fetcher ladders can't drift
    apart on what a degraded answer records."""
    rows, cached = self.serve(ids)
    n = int(np.asarray(ids).size)
    if metrics is not None:
      metrics.record_stale_serve(int(cached.sum()))
      metrics.add_gauge('degraded_zero_fills', float((~cached).sum()))
    logger.warning(
        '%s degraded (%s): %d/%d rows from the staleness cache, '
        '%d zero-filled', what, cause, int(cached.sum()), n,
        int((~cached).sum()))
    return rows

  def serve(self, ids, feature_dim: Optional[int] = None):
    """Returns (rows [n, D], cached_mask [n]) — zeros where missed."""
    ids = np.asarray(ids).reshape(-1)
    with self._lock:
      dim = feature_dim or self.feature_dim
      if dim is None:
        raise RuntimeError(
            'degraded feature serve before any successful fetch: the '
            'row width is unknown (no cached rows to serve either)')
      out = np.zeros((ids.shape[0], int(dim)), self.dtype)
      mask = np.zeros(ids.shape[0], bool)
      for k, i in enumerate(ids.tolist()):
        row = self._rows.get(int(i))
        if row is not None:
          out[k] = row
          mask[k] = True
    return out, mask

"""Deterministic chaos injection for the rpc/channel fabric.

Every fault a test injects must be reproducible: the whole harness is
seeded (env knob ``GLT_CHAOS_SEED``, default 0) and every decision is
drawn from a :class:`FaultPlan` — a seeded schedule that answers "what
happens to event k" identically on every run. Concurrency cannot
perturb the schedule because each concurrent consumer (a proxy pump
direction, a wrapped channel) gets its own deterministic ``fork`` of
the plan; interleaving changes *when* a fault fires, never *whether*.

Injectable faults:

  * ``delay``      — hold an event for ``delay_s`` (latency spike);
  * ``drop``       — swallow a frame/message (lossy link; the caller's
    deadline machinery must notice);
  * ``disconnect`` — close the connection mid-stream (peer crash as
    observed from the other end);
  * ``truncate``   — forward a partial frame then close (torn write:
    exercises the ``_recv_exact`` 'peer closed' path with bytes already
    consumed).

:class:`ChaosTcpProxy` injects at the socket layer between a real
RpcClient and RpcServer — the retry/reconnect/breaker stack is
exercised against genuine TCP behavior, not mocks. :class:`ChaosChannel`
wraps any :class:`~glt_tpu.channel.ChannelBase` for the sampling
message plane.
"""
from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..utils.env import knob

logger = logging.getLogger(__name__)

_HDR = struct.Struct('<Q')  # the rpc fabric's length-prefix header

DELAY = 'delay'
DROP = 'drop'
DISCONNECT = 'disconnect'
TRUNCATE = 'truncate'
_FAULTS = (DELAY, DROP, DISCONNECT, TRUNCATE)


def chaos_seed(default: int = 0) -> int:
  """The run-wide chaos seed (env ``GLT_CHAOS_SEED``). CI pins it so
  every fault scenario replays identically on every PR."""
  return knob('GLT_CHAOS_SEED', int(default))


class FaultPlan:
  """Seeded per-event fault schedule.

  Args:
    seed: RNG seed (None -> ``chaos_seed()``).
    delay / drop / disconnect / truncate: per-event probabilities,
      evaluated in that fixed order (at most one fault per event).
    delay_s: injected latency for ``delay`` faults.
    start_after: first ``start_after`` events pass untouched (lets a
      scenario establish healthy state before the weather turns).
    max_faults: stop injecting after this many faults (None =
      unlimited) — guarantees an eventually-successful retry story.
  """

  def __init__(self, seed: Optional[int] = None, *, delay: float = 0.0,
               drop: float = 0.0, disconnect: float = 0.0,
               truncate: float = 0.0, delay_s: float = 0.05,
               start_after: int = 0, max_faults: Optional[int] = None):
    self.seed = chaos_seed() if seed is None else int(seed)
    self.rates = {DELAY: float(delay), DROP: float(drop),
                  DISCONNECT: float(disconnect),
                  TRUNCATE: float(truncate)}
    self.delay_s = float(delay_s)
    self.start_after = int(start_after)
    self.max_faults = max_faults
    self._rng = random.Random(self.seed)
    self._lock = threading.Lock()
    self._events = 0
    self.injected: Dict[str, int] = {f: 0 for f in _FAULTS}

  def fork(self, salt: int) -> 'FaultPlan':
    """A derived plan with an independent deterministic stream — one
    per concurrent consumer, so thread interleaving never reorders any
    single stream's draws."""
    child = FaultPlan(
        seed=(self.seed * 1_000_003 + int(salt) + 1) & 0x7FFFFFFF,
        delay_s=self.delay_s, start_after=self.start_after,
        max_faults=self.max_faults)
    child.rates = dict(self.rates)
    return child

  def next_fault(self) -> Optional[str]:
    """The fault for the next event (None = pass through). Consumes
    exactly one rng draw per event regardless of rates, so schedules
    are stable under rate tweaks of later fault kinds."""
    with self._lock:
      self._events += 1
      u = self._rng.random()
      if self._events <= self.start_after:
        return None
      if (self.max_faults is not None
          and sum(self.injected.values()) >= self.max_faults):
        return None
      edge = 0.0
      for kind in _FAULTS:
        edge += self.rates[kind]
        if u < edge:
          self.injected[kind] += 1
          return kind
      return None

  def schedule(self, n: int) -> list:
    """First ``n`` decisions of a FRESH copy of this plan (pure
    introspection for determinism asserts; does not consume this
    plan's stream)."""
    probe = self.fork(-1)
    probe.seed = self.seed
    probe._rng = random.Random(self.seed)
    return [probe.next_fault() for _ in range(n)]


class ChaosTcpProxy:
  """Frame-aware TCP proxy injecting faults between an RpcClient and an
  RpcServer.

  Listens on an ephemeral port (``.address``); each accepted connection
  dials ``upstream`` and two pump threads forward length-prefixed
  frames, consulting a forked FaultPlan per direction. Chaos applies to
  both requests and responses — a dropped *response* is the nastier
  case (the callee executed, the caller never heard), which is exactly
  what the request-id dedup cache on the server must absorb.
  """

  def __init__(self, upstream_host: str, upstream_port: int,
               plan: FaultPlan, host: str = '127.0.0.1'):
    self.upstream = (upstream_host, int(upstream_port))
    self.plan = plan
    self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
      self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    except (AttributeError, OSError):
      pass
    self._sock.bind((host, 0))
    self._sock.listen(16)
    self.host, self.port = self._sock.getsockname()
    self._stop = threading.Event()
    self._conn_idx = 0
    self._lock = threading.Lock()
    self.connections = 0
    self._live: list = []
    self._accept = threading.Thread(target=self._accept_loop,
                                    daemon=True, name='glt-chaos-proxy')
    self._accept.start()

  @property
  def address(self):
    return (self.host, self.port)

  def retarget(self, host: str, port: int) -> None:
    """Point NEW connections at a different upstream (a restarted
    server on a fresh port); existing pumps keep their old sockets
    until they die — exactly a DNS/VIP failover as the client sees it."""
    self.upstream = (host, int(port))

  @property
  def faults_injected(self) -> Dict[str, int]:
    """Aggregate fault counts over every per-direction fork."""
    out = {f: 0 for f in _FAULTS}
    with self._lock:
      plans = [p for _, _, p in self._live]
    for p in plans:
      for f, n in p.injected.items():
        out[f] += n
    return out

  def _accept_loop(self) -> None:
    while not self._stop.is_set():
      try:
        client, _ = self._sock.accept()
      except OSError:
        return
      try:
        server = socket.create_connection(self.upstream, timeout=10)
      except OSError:
        client.close()
        continue
      with self._lock:
        idx = self._conn_idx
        self._conn_idx += 1
        self.connections += 1
      closed = threading.Event()
      for d, (src, dst) in enumerate(((client, server),
                                      (server, client))):
        p = self.plan.fork(2 * idx + d)
        with self._lock:
          self._live.append((src, dst, p))
        threading.Thread(
            target=self._pump, args=(src, dst, p, closed),
            daemon=True, name=f'glt-chaos-pump-{idx}-{d}').start()

  @staticmethod
  def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b''
    while len(buf) < n:
      try:
        chunk = sock.recv(n - len(buf))
      except OSError:
        return None
      if not chunk:
        return None
      buf += chunk
    return buf

  def _pump(self, src: socket.socket, dst: socket.socket,
            plan: FaultPlan, closed: threading.Event) -> None:
    try:
      while not self._stop.is_set() and not closed.is_set():
        hdr = self._recv_exact(src, _HDR.size)
        if hdr is None:
          break
        (n,) = _HDR.unpack(hdr)
        payload = self._recv_exact(src, n)
        if payload is None:
          break
        fault = plan.next_fault()
        try:
          if fault == DROP:
            continue
          if fault == DELAY:
            time.sleep(plan.delay_s)
          elif fault == DISCONNECT:
            break
          elif fault == TRUNCATE:
            dst.sendall(hdr + payload[:max(n // 2, 1)])
            break
          dst.sendall(hdr + payload)
        except OSError:
          break
    finally:
      closed.set()
      for s in (src, dst):
        try:
          s.close()
        except OSError:
          pass

  def close(self) -> None:
    self._stop.set()
    try:
      self._sock.close()
    except OSError:
      pass
    with self._lock:
      live = list(self._live)
    for src, dst, _ in live:
      for s in (src, dst):
        try:
          s.close()
        except OSError:
          pass

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class ChaosChannel:
  """FaultPlan wrapper over any ChannelBase: recv-side injection for
  the sampling message plane (drop = message lost, delay = slow link,
  disconnect = producer death as the consumer sees it)."""

  def __init__(self, inner, plan: FaultPlan):
    self.inner = inner
    self.plan = plan

  def send(self, msg) -> None:
    self.inner.send(msg)

  def recv(self, timeout_ms: int = 60_000):
    deadline = time.monotonic() + timeout_ms / 1e3
    while True:
      remaining_ms = max(int((deadline - time.monotonic()) * 1e3), 1)
      msg = self.inner.recv(timeout_ms=remaining_ms)
      fault = self.plan.next_fault()
      if fault == DROP:
        continue  # the message is gone; keep waiting out the budget
      if fault == DELAY:
        time.sleep(self.plan.delay_s)
      elif fault == DISCONNECT:
        raise ConnectionError('chaos: injected disconnect')
      elif fault == TRUNCATE:
        raise ConnectionError('chaos: injected truncated frame')
      return msg

  def empty(self) -> bool:
    return self.inner.empty()

  def __getattr__(self, name):
    return getattr(self.inner, name)


def flaky(fn, plan: FaultPlan):
  """Wrap a callable with plan-driven faults (drop/disconnect ->
  ConnectionError, delay -> sleep) — stalls and crashes for components
  that are functions rather than sockets (engine forwards, fetchers)."""
  def wrapped(*args, **kwargs):
    fault = plan.next_fault()
    if fault in (DROP, DISCONNECT, TRUNCATE):
      raise ConnectionError(f'chaos: injected {fault}')
    if fault == DELAY:
      time.sleep(plan.delay_s)
    return fn(*args, **kwargs)
  return wrapped

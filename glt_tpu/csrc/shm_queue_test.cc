// Native unit tests for the SysV shm ring buffer — the reference keeps
// googletest binaries for its native layer (test/cpp/test_shm_queue.cu);
// this is the plain-assert equivalent (no gtest in this image).
//
// Covers: FIFO order, wraparound with variable block sizes, dequeue
// timeout, -EMSGSIZE refusal without consumption, cross-process
// transfer via fork, multi-threaded producers/consumers, and survival
// of a consumer killed while blocked (robust-mutex path must leave the
// queue usable for everyone else).
//
// Build & run: make -C glt_tpu/csrc test
#include <cassert>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <pthread.h>
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

extern "C" {
int shmq_create(uint64_t capacity);
void* shmq_attach(int shmid);
int shmq_detach(void* handle);
int shmq_destroy(int shmid);
int shmq_enqueue(void* handle, const void* data, uint64_t size,
                 int timeout_ms);
int64_t shmq_peek_size(void* handle, int timeout_ms);
int64_t shmq_dequeue(void* handle, void* out, uint64_t cap,
                     int timeout_ms);
uint64_t shmq_size(void* handle);
}

static void test_fifo_and_wraparound() {
  int id = shmq_create(1 << 12);
  assert(id >= 0);
  void* q = shmq_attach(id);
  assert(q);
  // deterministic xorshift PRNG (rand_r needs _POSIX_C_SOURCE)
  uint32_t seed = 7;
  auto next = [&seed]() {
    seed ^= seed << 13; seed ^= seed >> 17; seed ^= seed << 5;
    return seed;
  };
  std::vector<std::vector<char>> sent;
  for (int round = 0; round < 50; ++round) {
    sent.clear();
    for (int i = 0; i < 4; ++i) {
      int len = 1 + next() % 700;
      std::vector<char> buf(len);
      for (int j = 0; j < len; ++j) buf[j] = char(next());
      assert(shmq_enqueue(q, buf.data(), buf.size(), 1000) == 0);
      sent.push_back(buf);
    }
    for (auto& buf : sent) {
      char out[1024];
      int64_t got = shmq_dequeue(q, out, sizeof(out), 1000);
      assert(got == (int64_t)buf.size());
      assert(std::memcmp(out, buf.data(), got) == 0);
    }
  }
  assert(shmq_size(q) == 0);
  shmq_detach(q);
  shmq_destroy(id);
  std::puts("fifo_and_wraparound ok");
}

static void test_timeout_and_msgsize() {
  int id = shmq_create(1 << 10);
  void* q = shmq_attach(id);
  assert(shmq_dequeue(q, nullptr, 0, 50) == -ETIMEDOUT);
  char big[4096];
  assert(shmq_enqueue(q, big, sizeof(big), 50) == -EMSGSIZE);
  // undersized output buffer refuses WITHOUT consuming
  const char* msg = "hello";
  assert(shmq_enqueue(q, msg, 5, 100) == 0);
  char tiny[2];
  assert(shmq_dequeue(q, tiny, sizeof(tiny), 100) == -EMSGSIZE);
  assert(shmq_size(q) == 1);
  char out[16];
  assert(shmq_dequeue(q, out, sizeof(out), 100) == 5);
  shmq_detach(q);
  shmq_destroy(id);
  std::puts("timeout_and_msgsize ok");
}

static void test_cross_process() {
  int id = shmq_create(1 << 14);
  pid_t pid = fork();
  if (pid == 0) {  // child: producer
    void* q = shmq_attach(id);
    for (int i = 0; i < 200; ++i) {
      assert(shmq_enqueue(q, &i, sizeof(i), 5000) == 0);
    }
    shmq_detach(q);
    _exit(0);
  }
  void* q = shmq_attach(id);
  for (int i = 0; i < 200; ++i) {
    int v = -1;
    assert(shmq_dequeue(q, &v, sizeof(v), 5000) == sizeof(int));
    assert(v == i);
  }
  int status = 0;
  waitpid(pid, &status, 0);
  assert(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  shmq_detach(q);
  shmq_destroy(id);
  std::puts("cross_process ok");
}

struct ThreadArg {
  void* q;
  int n;
  long sum;
};

static void* producer_main(void* p) {
  auto* a = static_cast<ThreadArg*>(p);
  for (int i = 1; i <= a->n; ++i) {
    assert(shmq_enqueue(a->q, &i, sizeof(i), 10000) == 0);
  }
  return nullptr;
}

static void* consumer_main(void* p) {
  auto* a = static_cast<ThreadArg*>(p);
  for (int i = 0; i < a->n; ++i) {
    int v = 0;
    int64_t got = shmq_dequeue(a->q, &v, sizeof(v), 10000);
    assert(got == sizeof(int));
    a->sum += v;
  }
  return nullptr;
}

static void test_mpmc_threads() {
  int id = shmq_create(1 << 12);  // small: heavy contention + wrap
  void* q = shmq_attach(id);
  const int kPer = 500;
  ThreadArg prod[3] = {{q, kPer, 0}, {q, kPer, 0}, {q, kPer, 0}};
  ThreadArg cons[3] = {{q, kPer, 0}, {q, kPer, 0}, {q, kPer, 0}};
  pthread_t pt[3], ct[3];
  for (int i = 0; i < 3; ++i) pthread_create(&ct[i], nullptr,
                                             consumer_main, &cons[i]);
  for (int i = 0; i < 3; ++i) pthread_create(&pt[i], nullptr,
                                             producer_main, &prod[i]);
  for (int i = 0; i < 3; ++i) pthread_join(pt[i], nullptr);
  long total = 0;
  for (int i = 0; i < 3; ++i) {
    pthread_join(ct[i], nullptr);
    total += cons[i].sum;
  }
  long expect = 3L * kPer * (kPer + 1) / 2;
  assert(total == expect);
  assert(shmq_size(q) == 0);
  shmq_detach(q);
  shmq_destroy(id);
  std::puts("mpmc_threads ok");
}

static void test_killed_consumer_leaves_queue_usable() {
  int id = shmq_create(1 << 12);
  pid_t pid = fork();
  if (pid == 0) {  // child: blocks forever on an empty queue
    void* q = shmq_attach(id);
    int v;
    shmq_dequeue(q, &v, sizeof(v), 60000);
    _exit(1);  // unreachable
  }
  usleep(100 * 1000);  // let the child block inside the cond wait
  kill(pid, SIGKILL);
  waitpid(pid, nullptr, 0);
  // the queue must remain fully usable for everyone else
  void* q = shmq_attach(id);
  int v = 42;
  assert(shmq_enqueue(q, &v, sizeof(v), 1000) == 0);
  int out = 0;
  assert(shmq_dequeue(q, &out, sizeof(out), 1000) == sizeof(int));
  assert(out == 42);
  shmq_detach(q);
  shmq_destroy(id);
  std::puts("killed_consumer ok");
}

int main() {
  test_fifo_and_wraparound();
  test_timeout_and_msgsize();
  test_cross_process();
  test_mpmc_threads();
  test_killed_consumer_leaves_queue_usable();
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}

// Cross-process shared-memory ring buffer of variable-size blocks.
//
// TPU-native host runtime equivalent of the reference's ShmQueue
// (graphlearn_torch/csrc/shm_queue.cc, include/shm_queue.h:65-122): a SysV
// shared-memory segment (picklable across processes by shmid, the same
// property the reference exploits in py_export_glt.cc:138-146) holding a
// byte ring plus pshared mutex/condvars. Blocks are length-prefixed; a
// zero-length marker denotes a wrapped tail fragment (the reference's
// tail-fragment handling). Used by glt_tpu.channel.ShmChannel to stream
// serialized sample batches from producer processes to the training
// process.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <pthread.h>
#include <sys/ipc.h>
#include <sys/shm.h>

namespace {

struct QueueHeader {
  uint64_t capacity;      // ring bytes
  uint64_t head;          // read offset  (monotonic)
  uint64_t tail;          // write offset (monotonic)
  uint64_t num_blocks;    // readable blocks
  pthread_mutex_t mutex;
  pthread_cond_t can_read;
  pthread_cond_t can_write;
  uint8_t ring[];         // capacity bytes
};

constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

inline uint64_t ring_pos(const QueueHeader* q, uint64_t off) {
  return off % q->capacity;
}

inline uint64_t free_bytes(const QueueHeader* q) {
  return q->capacity - (q->tail - q->head);
}

void write_bytes(QueueHeader* q, uint64_t off, const void* src,
                 uint64_t n) {
  uint64_t pos = ring_pos(q, off);
  uint64_t first = (pos + n <= q->capacity) ? n : q->capacity - pos;
  std::memcpy(q->ring + pos, src, first);
  if (n > first) {
    std::memcpy(q->ring, static_cast<const uint8_t*>(src) + first,
                n - first);
  }
}

void read_bytes(const QueueHeader* q, uint64_t off, void* dst,
                uint64_t n) {
  uint64_t pos = ring_pos(q, off);
  uint64_t first = (pos + n <= q->capacity) ? n : q->capacity - pos;
  std::memcpy(dst, q->ring + pos, first);
  if (n > first) {
    std::memcpy(static_cast<uint8_t*>(dst) + first, q->ring, n - first);
  }
}

timespec deadline_after_ms(int timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += static_cast<long>(timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

}  // namespace

extern "C" {

// Create a new queue; returns shmid (>=0) or -errno.
int shmq_create(uint64_t capacity) {
  uint64_t total = sizeof(QueueHeader) + capacity;
  int shmid = shmget(IPC_PRIVATE, total, IPC_CREAT | 0600);
  if (shmid < 0) return -errno;
  void* mem = shmat(shmid, nullptr, 0);
  if (mem == reinterpret_cast<void*>(-1)) return -errno;
  auto* q = static_cast<QueueHeader*>(mem);
  q->capacity = capacity;
  q->head = q->tail = 0;
  q->num_blocks = 0;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&q->mutex, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&q->can_read, &ca);
  pthread_cond_init(&q->can_write, &ca);
  shmdt(mem);
  return shmid;
}

// Attach to an existing queue by shmid; returns pointer handle or null.
void* shmq_attach(int shmid) {
  void* mem = shmat(shmid, nullptr, 0);
  if (mem == reinterpret_cast<void*>(-1)) return nullptr;
  return mem;
}

int shmq_detach(void* handle) {
  return shmdt(handle) == 0 ? 0 : -errno;
}

// Mark for destruction (segment disappears once all detach).
int shmq_destroy(int shmid) {
  return shmctl(shmid, IPC_RMID, nullptr) == 0 ? 0 : -errno;
}

// A holder died mid-update: make the mutex usable again and, if the
// header was left half-written, reset the ring to a sane empty state
// (losing in-flight blocks beats leaving every future op corrupt).
static void recover_dead_owner(QueueHeader* q) {
  pthread_mutex_consistent(&q->mutex);
  // head==tail with nonzero num_blocks catches a consumer killed between
  // advancing head and decrementing num_blocks on the last block; the
  // symmetric head!=tail with zero num_blocks catches a producer killed
  // between advancing tail and incrementing num_blocks on an empty ring.
  if (q->tail - q->head > q->capacity || q->num_blocks > q->capacity ||
      (q->head == q->tail && q->num_blocks != 0) ||
      (q->head != q->tail && q->num_blocks == 0)) {
    q->head = 0;
    q->tail = 0;
    q->num_blocks = 0;
  }
  // The ring state just changed out from under any sleeping waiters
  // (possibly to fully-empty/fully-free); wake them all to re-check.
  pthread_cond_broadcast(&q->can_read);
  pthread_cond_broadcast(&q->can_write);
}

static int lock_robust(QueueHeader* q) {
  int rc = pthread_mutex_lock(&q->mutex);
  if (rc == EOWNERDEAD) {
    recover_dead_owner(q);
    rc = 0;
  }
  return rc;
}

// Timed wait that handles robust-mutex reacquire outcomes: returns 0 to
// re-check the predicate (normal wake, or EOWNERDEAD recovered),
// ETIMEDOUT, or a hard errno the caller must propagate.
static int wait_robust(pthread_cond_t* cv, QueueHeader* q,
                       const timespec* dl) {
  int rc = pthread_cond_timedwait(cv, &q->mutex, dl);
  if (rc == EOWNERDEAD) {
    recover_dead_owner(q);
    return 0;
  }
  return rc;
}

// Blocking enqueue with timeout; returns 0, -ETIMEDOUT, or -EMSGSIZE.
int shmq_enqueue(void* handle, const void* data, uint64_t size,
                 int timeout_ms) {
  auto* q = static_cast<QueueHeader*>(handle);
  uint64_t need = size + sizeof(uint32_t);
  if (need + sizeof(uint32_t) > q->capacity) return -EMSGSIZE;
  timespec dl = deadline_after_ms(timeout_ms);
  if (lock_robust(q) != 0) return -EINVAL;
  for (;;) {
    // wrap handling: if the length prefix itself would straddle the end,
    // emit a wrap marker and start at offset 0 (reference tail-fragment)
    uint64_t pos = ring_pos(q, q->tail);
    uint64_t until_end = q->capacity - pos;
    uint64_t pad = (until_end < sizeof(uint32_t)) ? until_end : 0;
    if (free_bytes(q) >= need + pad) {
      if (pad) {
        // burn the tail fragment
        q->tail += pad;
      }
      uint32_t sz = static_cast<uint32_t>(size);
      write_bytes(q, q->tail, &sz, sizeof(sz));
      write_bytes(q, q->tail + sizeof(sz), data, size);
      q->tail += sizeof(sz) + size;
      q->num_blocks += 1;
      pthread_cond_signal(&q->can_read);
      pthread_mutex_unlock(&q->mutex);
      return 0;
    }
    int rc = wait_robust(&q->can_write, q, &dl);
    if (rc != 0) {
      pthread_mutex_unlock(&q->mutex);
      return -rc;
    }
  }
}

// Size of the next block without consuming it; -ETIMEDOUT on timeout.
int64_t shmq_peek_size(void* handle, int timeout_ms) {
  auto* q = static_cast<QueueHeader*>(handle);
  timespec dl = deadline_after_ms(timeout_ms);
  if (lock_robust(q) != 0) return -EINVAL;
  while (q->num_blocks == 0) {
    int rc = wait_robust(&q->can_read, q, &dl);
    if (rc != 0) {
      pthread_mutex_unlock(&q->mutex);
      return -rc;
    }
  }
  uint64_t head = q->head;
  uint64_t pos = ring_pos(q, head);
  if (q->capacity - pos < sizeof(uint32_t)) {
    head += q->capacity - pos;  // skip tail fragment
  }
  uint32_t sz;
  read_bytes(q, head, &sz, sizeof(sz));
  pthread_mutex_unlock(&q->mutex);
  return static_cast<int64_t>(sz);
}

// Dequeue into out (cap bytes); returns block size, -ETIMEDOUT, or
// -EMSGSIZE if cap is too small (block is left in place).
int64_t shmq_dequeue(void* handle, void* out, uint64_t cap,
                     int timeout_ms) {
  auto* q = static_cast<QueueHeader*>(handle);
  timespec dl = deadline_after_ms(timeout_ms);
  if (lock_robust(q) != 0) return -EINVAL;
  while (q->num_blocks == 0) {
    int rc = wait_robust(&q->can_read, q, &dl);
    if (rc != 0) {
      pthread_mutex_unlock(&q->mutex);
      return -rc;
    }
  }
  uint64_t pos = ring_pos(q, q->head);
  if (q->capacity - pos < sizeof(uint32_t)) {
    q->head += q->capacity - pos;  // skip tail fragment
  }
  uint32_t sz;
  read_bytes(q, q->head, &sz, sizeof(sz));
  if (sz > cap) {
    pthread_mutex_unlock(&q->mutex);
    return -EMSGSIZE;
  }
  read_bytes(q, q->head + sizeof(sz), out, sz);
  q->head += sizeof(sz) + sz;
  q->num_blocks -= 1;
  pthread_cond_signal(&q->can_write);
  pthread_mutex_unlock(&q->mutex);
  return static_cast<int64_t>(sz);
}

uint64_t shmq_size(void* handle) {
  auto* q = static_cast<QueueHeader*>(handle);
  lock_robust(q);
  uint64_t n = q->num_blocks;
  pthread_mutex_unlock(&q->mutex);
  return n;
}

int shmq_empty(void* handle) {
  return shmq_size(handle) == 0 ? 1 : 0;
}

}  // extern "C"

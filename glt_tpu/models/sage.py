"""GraphSAGE / GAT / GCN stacks over Batch pytrees.

Reference workloads: examples/train_sage_ogbn_products.py (supervised
SAGE), examples/graph_sage_unsup_ppi.py (unsupervised link-pred SAGE).
Hop-trimming (`trim_to_layer`, examples/train_sage_prod_with_trim.py) is
built in: with ``trim=True`` layer l only processes the edge slots of the
hops it still needs — a *static* slice thanks to edge_hop_offsets, so
trimming costs zero recompilation and shrinks every matmul.
"""
from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp

from ..loader.transform import Batch
from .conv import GATConv, GCNConv, SAGEConv

_CONVS = {
    'sage': lambda d, i: SAGEConv(d, name=f'conv{i}'),
    'gcn': lambda d, i: GCNConv(d, name=f'conv{i}'),
    'gat': lambda d, i: GATConv(d, heads=1, name=f'conv{i}'),
}


class GraphSAGE(nn.Module):
  """num_layers of conv + relu + dropout, then a classifier head read off
  the seed rows. Matches the reference example topology (3 layers, hidden
  256 for ogbn-products, train_sage_ogbn_products.py:111-120)."""
  hidden_features: int
  out_features: int
  num_layers: int = 3
  conv: str = 'sage'
  dropout: float = 0.0
  trim: bool = True

  @nn.compact
  def __call__(self, batch: Batch, train: bool = False,
               return_all: bool = False) -> jax.Array:
    x = batch.x
    row, col, mask = batch.row, batch.col, batch.edge_mask
    offsets = batch.edge_hop_offsets
    num_hops = len(offsets) - 1 if offsets else self.num_layers
    for i in range(self.num_layers):
      dim = (self.hidden_features if i < self.num_layers - 1
             else self.out_features)
      if self.trim and offsets is not None:
        # layer i still feeds num_layers-1-i later propagations, so hop
        # h is useful iff h <= num_layers - i (clamped to sampled hops);
        # later-hop edges feed representations no later layer reads
        keep = max(min(num_hops, self.num_layers - i), 1)
        end = offsets[keep]
        r, c, m = row[:end], col[:end], mask[:end]
      else:
        r, c, m = row, col, mask
      x = _CONVS[self.conv](dim, i)(x, r, c, m)
      if i < self.num_layers - 1:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    if return_all:
      return x
    return x[:batch.batch_size]

  def embed(self, batch: Batch, train: bool = False) -> jax.Array:
    """Embeddings for ALL sampled nodes (link/unsupervised tasks index
    these by edge_label_index / src_index / dst_*_index, which range over
    every seed endpoint, not just the first batch_size labels)."""
    return self.__call__(batch, train=train, return_all=True)

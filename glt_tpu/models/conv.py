"""GNN convolution layers (flax linen) over padded edge lists.

The reference trains standard PyG convs (SAGEConv/GATConv/RGCN/HGT —
examples/, examples/igbh/rgnn.py). These are from-scratch flax
implementations of the same math, designed for the framework's padded
static-shape batches: invalid edge slots are routed to a sacrificial
segment so aggregation is one masked segment_sum — no dynamic shapes, and
the feature matmuls stay dense on the MXU.
"""
from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp


def segment_mean(msgs: jax.Array, targets: jax.Array, mask: jax.Array,
                 num_segments: int) -> jax.Array:
  """Masked mean aggregation: invalid slots go to segment num_segments."""
  seg = jnp.where(mask, targets, num_segments)
  total = jax.ops.segment_sum(
      jnp.where(mask[:, None], msgs, 0.0), seg, num_segments + 1)
  cnt = jax.ops.segment_sum(mask.astype(msgs.dtype), seg, num_segments + 1)
  return total[:num_segments] / jnp.maximum(cnt[:num_segments, None], 1.0)


def segment_sum_masked(msgs, targets, mask, num_segments):
  seg = jnp.where(mask, targets, num_segments)
  return jax.ops.segment_sum(
      jnp.where(mask[:, None], msgs, 0.0), seg, num_segments + 1
  )[:num_segments]


def segment_max_masked(msgs, targets, mask, num_segments):
  seg = jnp.where(mask, targets, num_segments)
  out = jax.ops.segment_max(
      jnp.where(mask[:, None], msgs, -jnp.inf), seg, num_segments + 1)
  out = out[:num_segments]
  return jnp.where(jnp.isfinite(out), out, 0.0)


_AGGRS = {
    'mean': segment_mean,
    'sum': segment_sum_masked,
    'max': segment_max_masked,
}


class SAGEConv(nn.Module):
  """GraphSAGE convolution: W_root·x + W_nbr·aggr(x[children])."""
  out_features: int
  aggr: str = 'mean'
  use_bias: bool = True
  param_dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x: jax.Array, row: jax.Array, col: jax.Array,
               edge_mask: jax.Array) -> jax.Array:
    n = x.shape[0]
    safe_row = jnp.clip(row, 0, n - 1)
    msgs = jnp.take(x, safe_row, axis=0)
    agg = _AGGRS[self.aggr](msgs, jnp.clip(col, 0, n - 1),
                            edge_mask & (row >= 0) & (col >= 0), n)
    lin_nbr = nn.Dense(self.out_features, use_bias=False,
                       param_dtype=self.param_dtype, name='lin_nbr')
    lin_root = nn.Dense(self.out_features, use_bias=self.use_bias,
                        param_dtype=self.param_dtype, name='lin_root')
    return lin_root(x) + lin_nbr(agg)


class GATConv(nn.Module):
  """Graph attention (GATv1): per-edge attention logits softmax-normalized
  over each parent's incoming sampled edges, multi-head."""
  out_features: int
  heads: int = 1
  concat: bool = True
  negative_slope: float = 0.2
  param_dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x, row, col, edge_mask):
    n = x.shape[0]
    h, f = self.heads, self.out_features
    ok = edge_mask & (row >= 0) & (col >= 0)
    proj = nn.Dense(h * f, use_bias=False, param_dtype=self.param_dtype,
                    name='proj')(x).reshape(n, h, f)
    att_src = self.param('att_src', nn.initializers.glorot_uniform(),
                         (h, f), self.param_dtype)
    att_dst = self.param('att_dst', nn.initializers.glorot_uniform(),
                         (h, f), self.param_dtype)
    src = jnp.take(proj, jnp.clip(row, 0, n - 1), axis=0)   # [E, h, f]
    dst = jnp.take(proj, jnp.clip(col, 0, n - 1), axis=0)
    logit = nn.leaky_relu(
        (src * att_src).sum(-1) + (dst * att_dst).sum(-1),
        negative_slope=self.negative_slope)                 # [E, h]
    seg = jnp.where(ok, col, n)
    # numerically-stable masked segment softmax over each parent
    seg_max = jax.ops.segment_max(
        jnp.where(ok[:, None], logit, -jnp.inf), seg, n + 1)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    z = jnp.exp(logit - seg_max[jnp.clip(seg, 0, n)])
    z = jnp.where(ok[:, None], z, 0.0)
    denom = jax.ops.segment_sum(z, seg, n + 1)
    alpha = z / jnp.maximum(denom[jnp.clip(seg, 0, n)], 1e-16)  # [E, h]
    out = jax.ops.segment_sum(
        src * alpha[:, :, None], seg, n + 1)[:n]            # [n, h, f]
    if self.concat:
      return out.reshape(n, h * f)
    return out.mean(axis=1)


class GCNConv(nn.Module):
  """GCN layer with symmetric degree normalization computed on the sampled
  subgraph (masked)."""
  out_features: int
  use_bias: bool = True
  param_dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x, row, col, edge_mask):
    n = x.shape[0]
    ok = edge_mask & (row >= 0) & (col >= 0)
    h = nn.Dense(self.out_features, use_bias=False,
                 param_dtype=self.param_dtype, name='lin')(x)
    ones = ok.astype(h.dtype)
    seg_in = jnp.where(ok, col, n)
    # PyG GCN semantics: both endpoints are normalized by the in-degree
    # of the self-loop-augmented graph (deg_in includes the +1 loop), and
    # the self-loop term below uses 1/deg_in — models ported from the
    # reference match numerically.
    deg_in = jax.ops.segment_sum(ones, seg_in, n + 1)[:n] + 1.0
    norm = (jnp.take(deg_in, jnp.clip(row, 0, n - 1)) ** -0.5
            * jnp.take(deg_in, jnp.clip(col, 0, n - 1)) ** -0.5)
    msgs = jnp.take(h, jnp.clip(row, 0, n - 1), axis=0) * norm[:, None]
    agg = jax.ops.segment_sum(
        jnp.where(ok[:, None], msgs, 0.0), seg_in, n + 1)[:n]
    # self-loop term with its own normalization
    agg = agg + h / deg_in[:, None]
    if self.use_bias:
      agg = agg + self.param('bias', nn.initializers.zeros,
                             (self.out_features,), self.param_dtype)
    return agg

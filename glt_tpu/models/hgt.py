"""Heterogeneous Graph Transformer (HGT).

Reference workload: examples/hetero/train_hgt_mag.py (+_mp variant) —
PyG's HGTConv on ogbn-mag. From-scratch flax implementation of the HGT
layer (typed Q/K/V projections per node type, per-relation attention and
message transforms, per-dst-type softmax over incoming sampled edges),
over the framework's padded hetero batches.
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..loader.transform import HeteroBatch
from ..typing import EdgeType, NodeType, as_str


class HGTConv(nn.Module):
  node_types: Sequence[NodeType]
  edge_types: Sequence[EdgeType]
  out_features: int
  heads: int = 2

  @nn.compact
  def __call__(self, x_dict, row_dict, col_dict, mask_dict):
    h, f = self.heads, self.out_features
    assert f % h == 0
    d = f // h
    k_lin = {t: nn.DenseGeneral((h, d), name=f'k_{t}')
             for t in self.node_types}
    q_lin = {t: nn.DenseGeneral((h, d), name=f'q_{t}')
             for t in self.node_types}
    v_lin = {t: nn.DenseGeneral((h, d), name=f'v_{t}')
             for t in self.node_types}
    a_lin = {t: nn.Dense(f, name=f'a_{t}') for t in self.node_types}
    skip = {t: self.param(f'skip_{t}', nn.initializers.ones, ())
            for t in self.node_types}

    k_dict = {t: k_lin[t](x) for t, x in x_dict.items()}
    q_dict = {t: q_lin[t](x) for t, x in x_dict.items()}
    v_dict = {t: v_lin[t](x) for t, x in x_dict.items()}

    # accumulate per dst type: numerically-stable segment softmax needs
    # all relations' logits for a dst together; we do it per-relation
    # with shared max-subtraction per dst via two passes
    agg = {t: jnp.zeros(x_dict[t].shape[:1] + (h, d))
           for t in x_dict}
    norm = {t: jnp.zeros(x_dict[t].shape[:1] + (h,)) for t in x_dict}
    for etype in self.edge_types:
      if etype not in row_dict:
        continue
      src_t, _, dst_t = etype
      if src_t not in x_dict or dst_t not in x_dict:
        continue
      name = as_str(etype)
      w_att = self.param(f'watt_{name}', nn.initializers.glorot_uniform(),
                         (h, d, d))
      w_msg = self.param(f'wmsg_{name}', nn.initializers.glorot_uniform(),
                         (h, d, d))
      prior = self.param(f'prior_{name}', nn.initializers.ones, (h,))
      row, col, ok = row_dict[etype], col_dict[etype], mask_dict[etype]
      n_src = x_dict[src_t].shape[0]
      n_dst = x_dict[dst_t].shape[0]
      k = jnp.take(k_dict[src_t], jnp.clip(row, 0, n_src - 1), axis=0)
      q = jnp.take(q_dict[dst_t], jnp.clip(col, 0, n_dst - 1), axis=0)
      v = jnp.take(v_dict[src_t], jnp.clip(row, 0, n_src - 1), axis=0)
      # att logit: q^T (W_att k) * prior / sqrt(d)
      kt = jnp.einsum('ehd,hdf->ehf', k, w_att)
      logit = (q * kt).sum(-1) * prior / jnp.sqrt(d)      # [E, h]
      msg = jnp.einsum('ehd,hdf->ehf', v, w_msg)          # [E, h, d]
      w = jnp.where(ok[:, None], jnp.exp(jnp.clip(logit, -30, 30)), 0.0)
      seg = jnp.where(ok, col, n_dst)
      agg[dst_t] = agg[dst_t] + jax.ops.segment_sum(
          msg * w[:, :, None], seg, n_dst + 1)[:n_dst]
      norm[dst_t] = norm[dst_t] + jax.ops.segment_sum(
          w, seg, n_dst + 1)[:n_dst]

    out = {}
    for t, x in x_dict.items():
      msg = agg[t] / jnp.maximum(norm[t][:, :, None], 1e-9)
      o = a_lin[t](msg.reshape(msg.shape[0], f))
      alpha = nn.sigmoid(skip[t])
      base = x if x.shape[-1] == f else nn.Dense(f, name=f'res_{t}')(x)
      out[t] = alpha * nn.gelu(o) + (1 - alpha) * base
    return out


class HGT(nn.Module):
  """HGT stack with input projections per node type and a task head on
  the seed type (the train_hgt_mag topology)."""
  node_types: Sequence[NodeType]
  edge_types: Sequence[EdgeType]
  hidden_features: int
  out_features: int
  num_layers: int = 2
  heads: int = 2

  @nn.compact
  def __call__(self, batch: HeteroBatch, train: bool = False):
    x_dict = {t: nn.Dense(self.hidden_features, name=f'in_{t}')(x)
              for t, x in batch.x_dict.items()}
    for i in range(self.num_layers):
      x_dict = HGTConv(node_types=list(self.node_types),
                       edge_types=list(self.edge_types),
                       out_features=self.hidden_features,
                       heads=self.heads, name=f'hgt{i}')(
                           x_dict, batch.row_dict, batch.col_dict,
                           batch.edge_mask_dict)
    out = nn.Dense(self.out_features, name='head')(
        x_dict[batch.input_type])
    return out[:batch.batch_size]

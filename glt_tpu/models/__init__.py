from .conv import SAGEConv, GATConv, GCNConv, segment_mean
from .sage import GraphSAGE

__all__ = ['SAGEConv', 'GATConv', 'GCNConv', 'segment_mean', 'GraphSAGE']

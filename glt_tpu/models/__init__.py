from .conv import SAGEConv, GATConv, GCNConv, segment_mean
from .sage import GraphSAGE

__all__ = ['SAGEConv', 'GATConv', 'GCNConv', 'segment_mean', 'GraphSAGE']
from .rgnn import RGNN, HeteroConvLayer
from .hgt import HGT, HGTConv

__all__ += ['RGNN', 'HeteroConvLayer', 'HGT', 'HGTConv']

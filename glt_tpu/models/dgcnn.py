"""DGCNN — the SEAL link-prediction model (sort-pool readout).

Reference: examples/seal_link_pred.py:151-193 (stacked GCNConvs ->
global_sort_pool(k) -> Conv1d/MaxPool1d stack -> MLP -> 1 logit). Flax
re-design for padded static subgraphs: each enclosing subgraph is a
fixed-capacity [N] node / [E] edge-slot graph, the forward is written for
ONE subgraph and ``jax.vmap`` batches it — XLA then fuses the batch into
dense MXU matmuls (no scatter-based global pooling needed: sort-pool is a
top_k over the last GCN channel).
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .conv import GCNConv


class DGCNN(nn.Module):
  """Forward for ONE padded subgraph: use ``jax.vmap`` over a batch.

  Args:
    hidden: GCN hidden width (reference: 32).
    num_layers: number of hidden GCN layers (reference: 3); one extra
      1-channel conv provides the sort key.
    k: sort-pool size (static; reference computes the 60th-percentile
      subgraph size — pass that in).
  """
  hidden: int = 32
  num_layers: int = 3
  k: int = 30
  conv1d_channels: Sequence[int] = (16, 32)
  mlp_hidden: int = 128

  @nn.compact
  def __call__(self, x, row, col, edge_mask, node_mask,
               deterministic: bool = True):
    # the conv1d/maxpool stack needs floor((k-2)/2+1) - 5 + 1 >= 1
    # (the reference enforces the same with k = max(10, percentile))
    assert self.k >= 10, 'DGCNN sort-pool k must be >= 10'
    # GCN stack; tanh and channel-concat as the reference does
    xs = []
    h = x
    for i in range(self.num_layers):
      h = jnp.tanh(GCNConv(self.hidden, name=f'gcn{i}')(
          h, row, col, edge_mask))
      xs.append(h)
    sort_key = jnp.tanh(GCNConv(1, name='gcn_key')(h, row, col, edge_mask))
    xs.append(sort_key)
    h = jnp.concatenate(xs, axis=-1)        # [N, hidden*L + 1]
    h = jnp.where(node_mask[:, None], h, 0.0)

    # global_sort_pool: take the k nodes with the largest sort key
    # (invalid nodes sink to the bottom), in descending key order
    keyv = jnp.where(node_mask, sort_key[:, 0], -jnp.inf)
    _, top = jax.lax.top_k(keyv, self.k)    # [k]
    pooled = jnp.take(h, top, axis=0)       # [k, F]
    pooled = pooled * jnp.take(node_mask, top)[:, None]

    # Conv1d over the flattened [k*F] sequence with kernel=stride=F reads
    # one node per step (the reference's Conv1d(1, C, F, F))
    feat = pooled.reshape(-1, 1)[None]      # [1, k*F, 1]
    f_total = h.shape[-1]
    z = nn.Conv(self.conv1d_channels[0], kernel_size=(f_total,),
                strides=(f_total,), padding='VALID', name='conv1')(feat)
    z = nn.relu(z)                          # [1, k, C1]
    z = nn.max_pool(z, window_shape=(2,), strides=(2,))
    z = nn.Conv(self.conv1d_channels[1], kernel_size=(5,), strides=(1,),
                padding='VALID', name='conv2')(z)
    z = nn.relu(z).reshape(-1)              # dense_dim

    z = nn.Dense(self.mlp_hidden, name='mlp0')(z)
    z = nn.relu(z)
    z = nn.Dropout(0.5, deterministic=deterministic)(z)
    z = nn.Dense(1, name='mlp1')(z)
    return z[0]                             # scalar logit

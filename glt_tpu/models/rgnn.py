"""Relational (hetero) GNNs: HeteroConv composition + RGNN stacks.

Reference workloads: examples/igbh/rgnn.py:22 (RGAT / RSAGE for the
MLPerf IGBH benchmark), examples/hetero/* (hetero SAGE variants). The
composition rule matches PyG's HeteroConv: one conv per edge type, then
per-destination-type aggregation of the relation outputs.

Batch contract: HeteroBatch edge keys (s, r, d) carry row = s-type child
labels, col = d-type parent labels (message-flow orientation).
"""
from __future__ import annotations

from typing import Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..loader.transform import HeteroBatch
from ..typing import EdgeType, NodeType, as_str
from .conv import GATConv, SAGEConv


class HeteroConvLayer(nn.Module):
  """Applies a per-edge-type conv and sums relation outputs per dst type."""
  edge_types: Sequence[EdgeType]
  out_features: int
  conv: str = 'sage'       # 'sage' | 'gat'
  heads: int = 1

  def _make(self, etype):
    name = as_str(etype)
    if self.conv == 'gat':
      return GATConv(self.out_features, heads=self.heads, concat=False,
                     name=f'conv_{name}')
    return SAGEConv(self.out_features, name=f'conv_{name}')

  @nn.compact
  def __call__(self, x_dict: Dict[NodeType, jax.Array],
               row_dict, col_dict, mask_dict):
    out: Dict[NodeType, jax.Array] = {}
    for etype in self.edge_types:
      key = etype
      if key not in row_dict:
        continue
      src_t, _, dst_t = etype
      if src_t not in x_dict or dst_t not in x_dict:
        continue
      n_dst = x_dict[dst_t].shape[0]
      n_src = x_dict[src_t].shape[0]
      conv = self._make(etype)
      # bipartite message passing: gather from src space, aggregate into
      # dst space. Reuse the homo convs by building a stacked view:
      # [src || dst] with offset labels.
      x_cat = jnp.concatenate([x_dict[src_t], x_dict[dst_t]], axis=0) \
          if src_t != dst_t else x_dict[src_t]
      row = row_dict[key]
      col = col_dict[key] + (n_src if src_t != dst_t else 0)
      h = conv(x_cat, row, col, mask_dict[key])
      h_dst = h[n_src:] if src_t != dst_t else h
      out[dst_t] = out.get(dst_t, 0) + h_dst
    # types with no incoming relation keep a transformed self-embedding
    for t, x in x_dict.items():
      if t not in out:
        out[t] = nn.Dense(self.out_features, name=f'self_{t}')(x)
    return out


class RGNN(nn.Module):
  """Relational GNN stack (reference examples/igbh/rgnn.py): 'rsage' or
  'rgat' layers over a HeteroBatch, classifier head on the seed type.

  When the batch carries ``edge_hop_offsets_dict`` (hetero NeighborLoader
  batches do), layers trim hierarchically: layer i only reads the edge
  slots of hops [0, num_hops - i) per edge type — the reference's
  trim_to_layer (examples/hetero/hierarchical_sage.py), as static slices.
  """
  edge_types: Sequence[EdgeType]
  hidden_features: int
  out_features: int
  num_layers: int = 2
  conv: str = 'rsage'      # 'rsage' | 'rgat'
  heads: int = 4
  dropout: float = 0.0
  trim: bool = True

  @nn.compact
  def __call__(self, batch: HeteroBatch, train: bool = False,
               return_all: bool = False):
    conv_kind = 'gat' if self.conv == 'rgat' else 'sage'
    x_dict = dict(batch.x_dict)
    offs = batch.edge_hop_offsets_dict if self.trim else None
    num_hops = (max(len(v) for v in offs.values()) - 1) if offs else 0
    for i in range(self.num_layers):
      dim = (self.hidden_features if i < self.num_layers - 1
             else self.out_features)
      if offs is not None:
        # layer i still feeds num_layers-1-i later propagations, so hop
        # h is useful iff h <= num_layers - i (clamped to sampled hops)
        keep = max(min(num_hops, self.num_layers - i), 1)
        row_d, col_d, mask_d = {}, {}, {}
        for e, v in batch.row_dict.items():
          end = offs[e][min(keep, len(offs[e]) - 1)] \
              if e in offs else v.shape[0]
          end = max(end, 1)  # keep shapes non-empty for XLA
          row_d[e] = v[:end]
          col_d[e] = batch.col_dict[e][:end]
          mask_d[e] = batch.edge_mask_dict[e][:end]
      else:
        row_d, col_d, mask_d = (batch.row_dict, batch.col_dict,
                                batch.edge_mask_dict)
      x_dict = HeteroConvLayer(
          edge_types=list(self.edge_types), out_features=dim,
          conv=conv_kind, heads=self.heads, name=f'layer{i}')(
              x_dict, row_d, col_d, mask_d)
      if i < self.num_layers - 1:
        x_dict = {t: nn.relu(v) for t, v in x_dict.items()}
        if self.dropout > 0:
          drop = nn.Dropout(self.dropout, deterministic=not train)
          x_dict = {t: drop(v) for t, v in x_dict.items()}
    if return_all:
      return x_dict
    return x_dict[batch.input_type][:batch.batch_size]

"""glt_tpu.obs — the unified observability layer.

One process-wide surface for the three observability primitives every
subsystem (sampling, loaders, serving, stream ingest, resilience,
distributed fabric, parallel train) publishes into:

  * :class:`MetricsRegistry` — thread-safe labeled counters / gauges /
    log-spaced histograms with JSON and Prometheus-text exposition.
    :class:`~glt_tpu.serving.ServingMetrics` is a back-compat view over
    one of these, so serving / stream / resilience counters and the
    pipeline stage timings land on the SAME surface.
  * :class:`Tracer` — host-side spans per pipeline stage (sample hop,
    dedup, feature gather, superstep dispatch, batcher flush,
    compaction) that bridge into device traces via
    ``jax.profiler.TraceAnnotation`` and export as Chrome-trace-event /
    Perfetto-loadable JSON. Trace context propagates over the RPC
    fabric (``distributed.rpc``) so a cross-machine sample + feature
    lookup assembles into one trace.
  * profiling hooks — opt-in device-sync sampling
    (``GLT_OBS_TRACE_SAMPLE``) so steady-state overhead stays
    negligible; everything is host-side, so every zero-recompile
    invariant holds with obs enabled.

Disabled (the default), every hook is a near-free no-op: ``span()``
returns a cached null context manager and per-stage ``stage_seconds``
observations stop (plain registry counters keep counting — exposition
is independent of the tracing knob); the tier-1 overhead test pins the
no-op path below 2% of a sampled epoch.

Knobs (see docs/observability.md for the full table):

  GLT_OBS_TRACE=1         enable tracing at import time
  GLT_OBS_TRACE_SAMPLE=p  fraction of spans that device-sync on exit
  GLT_OBS_ANNOTATE=0      disable the device TraceAnnotation bridge
  GLT_OBS_BUFFER=n        span ring-buffer capacity (default 65536)
"""
from .registry import (
    Counter, Gauge, HistogramMetric, LatencyHistogram, MetricsRegistry,
    get_registry, set_registry,
)
from .trace import (
    Span, SpanContext, Tracer, collect_endpoint_obs, get_tracer,
    merge_chrome_traces, save_chrome_trace,
)

__all__ = [
    'Counter', 'Gauge', 'HistogramMetric', 'LatencyHistogram',
    'MetricsRegistry', 'get_registry', 'set_registry',
    'Span', 'SpanContext', 'Tracer', 'get_tracer',
    'collect_endpoint_obs', 'merge_chrome_traces', 'save_chrome_trace',
]

"""glt_tpu.obs — the unified observability layer.

One process-wide surface for the three observability primitives every
subsystem (sampling, loaders, serving, stream ingest, resilience,
distributed fabric, parallel train) publishes into:

  * :class:`MetricsRegistry` — thread-safe labeled counters / gauges /
    log-spaced histograms with JSON and Prometheus-text exposition.
    :class:`~glt_tpu.serving.ServingMetrics` is a back-compat view over
    one of these, so serving / stream / resilience counters and the
    pipeline stage timings land on the SAME surface.
  * :class:`Tracer` — host-side spans per pipeline stage (sample hop,
    dedup, feature gather, superstep dispatch, batcher flush,
    compaction) that bridge into device traces via
    ``jax.profiler.TraceAnnotation`` and export as Chrome-trace-event /
    Perfetto-loadable JSON. Trace context propagates over the RPC
    fabric (``distributed.rpc``) so a cross-machine sample + feature
    lookup assembles into one trace.
  * profiling hooks — opt-in device-sync sampling
    (``GLT_OBS_TRACE_SAMPLE``) so steady-state overhead stays
    negligible; everything is host-side, so every zero-recompile
    invariant holds with obs enabled.

Disabled (the default), every hook is a near-free no-op: ``span()``
returns a cached null context manager and per-stage ``stage_seconds``
observations stop (plain registry counters keep counting — exposition
is independent of the tracing knob); the tier-1 overhead test pins the
no-op path below 2% of a sampled epoch.

Two further pieces ride the same registry/tracer surfaces:

  * :mod:`perf` — XLA cost accounting (``compiles_total{fn}``,
    ``xla_flops``/``xla_bytes_accessed``/``xla_peak_bytes`` via the
    :func:`instrument_compiled` seam) and measured device rooflines
    (:func:`device_ceilings`, :func:`roofline_report`) so every
    throughput headline restates as % of a *measured* ceiling.
  * :mod:`recorder` — the always-on :class:`FlightRecorder` (bounded
    operational-event ring; resilience trips dump a postmortem JSON
    into ``GLT_OBS_POSTMORTEM_DIR``) and :class:`SloBurnEvaluator`
    (``slo_burn{slo=...}`` gauges over the registry histograms).

Knobs (see docs/observability.md for the full table):

  GLT_OBS_TRACE=1         enable tracing at import time
  GLT_OBS_TRACE_SAMPLE=p  fraction of spans that device-sync on exit
  GLT_OBS_ANNOTATE=0      disable the device TraceAnnotation bridge
  GLT_OBS_BUFFER=n        span ring-buffer capacity (default 65536)
  GLT_OBS_XLA_COST=1      opt-in AOT cost publication at test-pinned
                          compile points (serving warmup)
  GLT_ROOFLINE_CACHE      measured-ceiling JSON cache path
  GLT_OBS_POSTMORTEM_DIR  flight-recorder postmortem dump directory
  GLT_OBS_POSTMORTEM_MIN_S  floor between trip-initiated dumps
  GLT_OBS_SLO             SLO policies: name:metric:threshold[:obj];...
"""
from .registry import (
    Counter, Gauge, HistogramMetric, LatencyHistogram, MetricsRegistry,
    get_registry, set_registry,
)
from .trace import (
    Span, SpanContext, Tracer, collect_endpoint_obs, get_tracer,
    merge_chrome_traces, save_chrome_trace,
)
from .perf import (
    compile_counts, count_compile, device_ceilings, instrument_compiled,
    measure_hbm_bandwidth, measure_matmul_flops, roofline_report,
)
from .recorder import (
    FlightRecorder, SloBurnEvaluator, SloPolicy, get_recorder,
    parse_slo_env, set_recorder,
)

__all__ = [
    'Counter', 'Gauge', 'HistogramMetric', 'LatencyHistogram',
    'MetricsRegistry', 'get_registry', 'set_registry',
    'Span', 'SpanContext', 'Tracer', 'get_tracer',
    'collect_endpoint_obs', 'merge_chrome_traces', 'save_chrome_trace',
    'compile_counts', 'count_compile', 'device_ceilings',
    'instrument_compiled', 'measure_hbm_bandwidth',
    'measure_matmul_flops', 'roofline_report',
    'FlightRecorder', 'SloBurnEvaluator', 'SloPolicy', 'get_recorder',
    'parse_slo_env', 'set_recorder',
]

"""Performance accounting: XLA cost/memory analysis and measured
device rooflines.

Two halves, both publishing into the shared :class:`MetricsRegistry`:

**XLA cost accounting** — every jitted compile point already carries a
trace-time side effect (the per-module ``*_traces`` counters the
zero-steady-state-recompile tests assert); :func:`count_compile`
generalizes those into ONE process-wide ``compiles_total{fn=...}``
counter, and :func:`instrument_compiled` is the seam over
``jax.stages.Lowered.cost_analysis()`` /
``jax.stages.Compiled.cost_analysis()`` / ``memory_analysis()`` that
publishes per-program FLOPs, HBM bytes accessed, and peak memory as
``xla_flops{fn}`` / ``xla_bytes_accessed{fn}`` /
``xla_peak_bytes{fn}`` gauges. Lowering is cheap (a re-trace, no
compile) but IS a re-trace: callers whose trace counters are pinned by
tests (serving warmup) gate it behind ``GLT_OBS_XLA_COST``.

**Measured rooflines** — perf claims quoted against an *assumed*
ceiling are not self-grounding (PAPERS.md "GNNSampler", "Hardware
Acceleration of Sampling Algorithms in Sample and Aggregate GNNs"):
:func:`device_ceilings` runs a tiny microbench pair — HBM stream
bandwidth (saxpy over an HBM-resident array) and peak matmul FLOP/s —
once per device kind, caches the result as JSON
(``GLT_ROOFLINE_CACHE``), and publishes
``roofline_hbm_bytes_per_sec`` / ``roofline_flops_per_sec`` gauges.
:func:`roofline_report` then restates any items/s headline as % of the
*measured* ceilings plus bytes-per-item and FLOPs-per-item —
``bench.py`` emits one such cell per raced engine contender.

Everything here is host-side and allocation-free in steady state;
nothing touches traced code paths except the deliberate trace-time
``count_compile`` bump (a registry increment, same class of side
effect as the existing ``*_traces`` attribute bumps).
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from ..utils.env import knob
from .registry import MetricsRegistry, get_registry

logger = logging.getLogger(__name__)


# -- compile accounting ---------------------------------------------------

def count_compile(fn: str,
                  registry: Optional[MetricsRegistry] = None) -> None:
  """Trace-time hook: bump ``compiles_total{fn=...}``. Call it INSIDE a
  jitted function body (next to the existing ``*_traces`` attribute
  bumps) so executions never touch it — the counter then reads as
  "programs compiled/re-traced for this fn", the process-wide
  generalization of the per-module trace-counter asserts."""
  try:
    (registry or get_registry()).counter('compiles_total',
                                         fn=str(fn)).inc()
  except Exception:  # accounting must never break a trace
    pass


def compile_counts(registry: Optional[MetricsRegistry] = None) -> dict:
  """{fn: count} view over ``compiles_total`` — the assertable surface
  (tests pin a label's count flat across steady-state traffic)."""
  snap = (registry or get_registry()).snapshot()['counters']
  out = {}
  for key, v in snap.items():
    if key.startswith('compiles_total{'):
      inner = key[key.index('{') + 1:-1]
      for part in inner.split(','):
        k, _, val = part.partition('=')
        if k == 'fn':
          out[val.strip('"')] = out.get(val.strip('"'), 0) + v
  return out


def xla_cost_enabled() -> bool:
  """Whether opt-in AOT cost publication runs at compile points whose
  trace counters are test-pinned (serving warmup). ``GLT_OBS_XLA_COST=1``
  opts in; default off because the AOT ``lower()`` is an extra trace."""
  return knob('GLT_OBS_XLA_COST', False)


def _flatten_cost(cost) -> dict:
  """Normalize the cost_analysis return shape across jax versions:
  ``Lowered.cost_analysis()`` returns a flat dict, ``Compiled.
  cost_analysis()`` a list of per-module dicts (summed here)."""
  if cost is None:
    return {}
  if isinstance(cost, dict):
    return dict(cost)
  out: dict = {}
  for entry in cost:
    for k, v in (entry or {}).items():
      try:
        out[k] = out.get(k, 0.0) + float(v)
      except (TypeError, ValueError):
        pass
  return out


def instrument_compiled(fn_name: str, stage=None, *args,
                        registry: Optional[MetricsRegistry] = None,
                        aot_compile: bool = False,
                        **kwargs) -> dict:
  """Publish one program's XLA cost/memory analysis as registry gauges.

  ``stage`` is either an already-built ``jax.stages.Lowered`` /
  ``jax.stages.Compiled``, or a jit-wrapped callable — then ``*args`` /
  ``**kwargs`` (arrays or ``jax.ShapeDtypeStruct``\\ s) are lowered
  through it here. Lowering re-traces but never compiles; pass
  ShapeDtypeStructs when the real arguments were donated.

  ``aot_compile=True`` additionally compiles a Lowered stage first:
  ``Lowered.cost_analysis()`` counts the PRE-optimization HLO (every
  unfused intermediate reads as memory traffic), while the Compiled
  analysis reflects the optimized executable and unlocks
  ``memory_analysis()`` — callers quoting roofline evidence (bench.py)
  pay the compile (cheap when the persistent compilation cache already
  holds the program); ambient instrumentation stays lower-only.

  Publishes (all labeled ``fn=fn_name``):

  * ``xla_flops`` — model FLOPs of the program,
  * ``xla_bytes_accessed`` — HBM bytes the program moves,
  * ``xla_peak_bytes`` — argument + output + temp allocation peak
    (only when a ``Compiled`` with ``memory_analysis()`` is in hand —
    lowering alone has no allocation assignment).

  Returns the published numbers (plus whatever raw keys the backend
  reported); ``{}`` on any analysis failure — cost accounting is
  best-effort by contract (some backends return None).
  """
  reg = registry or get_registry()
  try:
    if callable(getattr(stage, 'lower', None)) \
        and not hasattr(stage, 'cost_analysis'):
      # trace-time launch accounting rides the lower: the delta of the
      # pallas module's per-trace counter around this re-trace is the
      # number of kernel entries in the program — the ground truth the
      # lowered text confirms on TPU (custom-call count) and the only
      # signal in interpret mode, where kernels inline into plain HLO
      from ..ops.pallas_kernels import kernel_launch_count
      before = kernel_launch_count()
      stage = stage.lower(*args, **kwargs)
      traced_launches = kernel_launch_count() - before
    else:
      traced_launches = None
    hlo_launches = None
    try:
      txt = stage.as_text() if callable(getattr(stage, 'as_text',
                                                None)) else ''
      if txt:
        # count ONLY Mosaic kernel entries — a generic custom_call
        # count would pick up RNG/sort library calls on some backends
        hlo_launches = txt.count('tpu_custom_call')
    except Exception:
      pass
    if aot_compile and callable(getattr(stage, 'compile', None)):
      try:
        stage = stage.compile()
      except Exception as e:  # fall back to the lowered analysis
        logger.debug('aot compile for %s failed (%s); using lowered '
                     'cost analysis', fn_name, e)
    compiled = stage
    cost = _flatten_cost(compiled.cost_analysis())
    out = {}
    # kernel launches per dispatch: the HLO custom-call count when the
    # program actually embeds kernels as custom calls (TPU), else the
    # trace-time pallas_call count (interpret mode). A traced delta of
    # ZERO is not evidence of "no kernels" — the inner jit wrappers may
    # have hit the jaxpr cache from an earlier trace of the same
    # shapes (kernel_launch_count's documented caveat) — so only a
    # POSITIVE count is ever recorded; absence means "not measurable
    # here", never "zero kernels"
    if hlo_launches:
      out['kernel_launches'] = int(hlo_launches)
    elif traced_launches:
      out['kernel_launches'] = int(traced_launches)
    if 'kernel_launches' in out:
      reg.set('xla_kernel_launches', float(out['kernel_launches']),
              fn=str(fn_name))
    if 'flops' in cost:
      out['flops'] = float(cost['flops'])
      reg.set('xla_flops', out['flops'], fn=str(fn_name))
    if 'bytes accessed' in cost:
      out['bytes_accessed'] = float(cost['bytes accessed'])
      reg.set('xla_bytes_accessed', out['bytes_accessed'],
              fn=str(fn_name))
    mem = getattr(compiled, 'memory_analysis', None)
    if callable(mem):
      m = mem()
      if m is not None:
        peak = (getattr(m, 'argument_size_in_bytes', 0)
                + getattr(m, 'output_size_in_bytes', 0)
                + getattr(m, 'temp_size_in_bytes', 0)
                - getattr(m, 'alias_size_in_bytes', 0))
        out['peak_bytes'] = float(peak)
        out['temp_bytes'] = float(getattr(m, 'temp_size_in_bytes', 0))
        reg.set('xla_peak_bytes', out['peak_bytes'], fn=str(fn_name))
    return out
  except Exception as e:  # noqa: BLE001 — accounting is best-effort
    logger.debug('cost analysis for %s unavailable: %s', fn_name, e)
    return {}


# -- measured rooflines ---------------------------------------------------

def default_cache_path() -> str:
  return knob(
      'GLT_ROOFLINE_CACHE',
      os.path.join(os.path.expanduser('~'), '.cache', 'glt_tpu',
                   'roofline.json'))


def measure_hbm_bandwidth(device=None, mib: int = 256,
                          iters: int = 5) -> float:
  """Measured HBM stream bandwidth in bytes/s: time ``y = a * x + y``
  (saxpy: 2 reads + 1 write per element) over an HBM-resident array,
  best of ``iters`` — best because every perturbation is additive
  noise; the max is the ceiling, exactly what a roofline needs."""
  import jax
  import jax.numpy as jnp
  dev = device or jax.devices()[0]
  n = max(mib, 1) * (1 << 20) // 4
  x = jax.device_put(jnp.ones((n,), jnp.float32), dev)
  y = jax.device_put(jnp.zeros((n,), jnp.float32), dev)

  @jax.jit
  def saxpy(x, y):
    return 2.0 * x + y

  jax.block_until_ready(saxpy(x, y))  # compile outside the timing
  best = float('inf')
  for _ in range(max(iters, 1)):
    t0 = time.perf_counter()
    jax.block_until_ready(saxpy(x, y))
    best = min(best, time.perf_counter() - t0)
  return 3.0 * 4.0 * n / best  # 2 loads + 1 store, fp32


def measure_matmul_flops(device=None, dim: int = 2048,
                         iters: int = 5) -> float:
  """Measured peak matmul throughput in FLOP/s: time an
  fp32 [dim, dim] x [dim, dim] matmul (2*dim^3 FLOPs), best of
  ``iters``."""
  import jax
  import jax.numpy as jnp
  dev = device or jax.devices()[0]
  a = jax.device_put(jnp.ones((dim, dim), jnp.float32), dev)
  b = jax.device_put(jnp.ones((dim, dim), jnp.float32), dev)

  @jax.jit
  def mm(a, b):
    return a @ b

  jax.block_until_ready(mm(a, b))
  best = float('inf')
  for _ in range(max(iters, 1)):
    t0 = time.perf_counter()
    jax.block_until_ready(mm(a, b))
    best = min(best, time.perf_counter() - t0)
  return 2.0 * dim ** 3 / best


#: in-process ceilings cache: one measurement per (device kind) per
#: process even when the disk cache is unwritable
_CEILINGS: dict = {}


def device_ceilings(device=None, refresh: bool = False,
                    cache_path: Optional[str] = None,
                    mib: int = 256, dim: int = 2048,
                    registry: Optional[MetricsRegistry] = None) -> dict:
  """The measured roofline pair for one device, cached per device kind.

  Returns ``{'device_kind', 'platform', 'hbm_bytes_per_sec',
  'flops_per_sec', 'measured_at'}``. Resolution order: in-process cache
  -> JSON disk cache (``GLT_ROOFLINE_CACHE``, keyed by device kind so a
  v5p entry never answers for a v6e) -> fresh microbench pair (a few
  hundred ms). Every resolution republishes the
  ``roofline_hbm_bytes_per_sec`` / ``roofline_flops_per_sec`` gauges so
  the ceilings ride every registry snapshot next to the throughput
  counters they ground."""
  import jax
  dev = device or jax.devices()[0]
  kind = f'{dev.platform}:{dev.device_kind}'
  path = cache_path or default_cache_path()
  entry = None
  if not refresh:
    entry = _CEILINGS.get(kind)
    if entry is None and os.path.exists(path):
      try:
        with open(path) as f:
          entry = json.load(f).get(kind)
      except (OSError, ValueError):
        entry = None
  if entry is None:
    entry = {
        'device_kind': dev.device_kind,
        'platform': dev.platform,
        'hbm_bytes_per_sec': measure_hbm_bandwidth(dev, mib=mib),
        'flops_per_sec': measure_matmul_flops(dev, dim=dim),
        'measured_at': time.time(),
    }
    try:
      os.makedirs(os.path.dirname(path), exist_ok=True)
      doc = {}
      if os.path.exists(path):
        try:
          with open(path) as f:
            doc = json.load(f)
        except (OSError, ValueError):
          doc = {}
      doc[kind] = entry
      with open(path, 'w') as f:
        json.dump(doc, f, indent=2)
    except OSError as e:  # unwritable cache: measure-per-process only
      logger.debug('roofline cache %s unwritable: %s', path, e)
  _CEILINGS[kind] = entry
  reg = registry or get_registry()
  reg.set('roofline_hbm_bytes_per_sec', entry['hbm_bytes_per_sec'],
          device=kind)
  reg.set('roofline_flops_per_sec', entry['flops_per_sec'], device=kind)
  return entry


def roofline_report(items_per_sec: float,
                    bytes_per_item: Optional[float] = None,
                    flops_per_item: Optional[float] = None,
                    ceilings: Optional[dict] = None,
                    item: str = 'edge') -> dict:
  """Restate a throughput headline against the measured ceilings.

  Given a rate (e.g. sampled edges/s), the program's HBM bytes moved
  per item and FLOPs per item (from :func:`instrument_compiled`'s
  ``bytes_accessed`` / ``flops`` divided by items per dispatch),
  returns the roofline cell::

      {'hbm_bytes_per_<item>':        bytes the program moves per item,
       'flops_per_<item>':            model FLOPs per item,
       'pct_of_measured_hbm_ceiling': 100 * rate*bytes / measured BW,
       'pct_of_measured_flop_ceiling': 100 * rate*flops / measured peak,
       'bound':                       'hbm' | 'flops' (larger share),
       'device_kind':                 the ceiling's device}

  ``ceilings=None`` resolves :func:`device_ceilings` (cached). The two
  percentages are exactly "how much of what the chip measurably has is
  this pipeline using" — the self-grounding restatement ROADMAP item 1
  asks for."""
  if ceilings is None:
    ceilings = device_ceilings()
  out: dict = {'device_kind': ceilings.get('device_kind', '?')}
  pct_hbm = pct_flop = None
  if bytes_per_item is not None:
    out[f'hbm_bytes_per_{item}'] = round(float(bytes_per_item), 2)
    bw = ceilings.get('hbm_bytes_per_sec') or 0.0
    if bw > 0:
      pct_hbm = 100.0 * items_per_sec * bytes_per_item / bw
      out['pct_of_measured_hbm_ceiling'] = round(pct_hbm, 3)
  if flops_per_item is not None:
    out[f'flops_per_{item}'] = round(float(flops_per_item), 2)
    peak = ceilings.get('flops_per_sec') or 0.0
    if peak > 0:
      pct_flop = 100.0 * items_per_sec * flops_per_item / peak
      out['pct_of_measured_flop_ceiling'] = round(pct_flop, 3)
  if pct_hbm is not None or pct_flop is not None:
    out['bound'] = ('hbm' if (pct_hbm or 0.0) >= (pct_flop or 0.0)
                    else 'flops')
  return out

"""Postmortem flight recorder and SLO burn-rate evaluation.

**FlightRecorder** — an always-on bounded ring of operational events
(breaker opens, engine stalls, ingestor crashes, engine fallbacks —
anything a subsystem :meth:`~FlightRecorder.record`\\ s). When
resilience *trips* (:meth:`~FlightRecorder.trip`), it dumps a
postmortem JSON into ``GLT_OBS_POSTMORTEM_DIR`` carrying:

  * the trip reason + context,
  * the recent event ring (what led up to this),
  * the last spans from the process tracer (the pipeline's final
    moments, when tracing is on),
  * a full registry snapshot plus counter DELTAS since the previous
    dump (what moved, not just where it ended up).

Dumps are rate-limited (``GLT_OBS_POSTMORTEM_MIN_S``) so a flapping
breaker cannot fill a disk; every trip is still recorded and counted
(``flight_trips_total{reason=...}``). With a postmortem dir configured
the recorder also chains ``sys.excepthook`` and registers an atexit
hook, so an abnormal process exit (uncaught exception, or exit after
any trip) leaves a dump behind even when nobody called ``dump()``.

**SloBurnEvaluator** — burn rate over the registry's log-spaced
histograms: for each policy (latency histogram + threshold + objective)
it tracks the windowed fraction of observations above the threshold
between ``evaluate()`` calls and publishes
``slo_burn{slo=...}`` = bad_fraction / error_budget. Burn 1.0 means
"exactly consuming budget"; >1 is the per-shard paging/autoscaling
signal ROADMAP item 4 names. Policies come from the API or the
``GLT_OBS_SLO`` knob (``name:metric:threshold_s:objective[;...]``,
metric optionally ``hist{label=value,...}``).

Everything is host-side; recording an event is one deque append + one
counter increment.
"""
from __future__ import annotations

import atexit
import dataclasses
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..utils.env import knob
from .registry import MetricsRegistry, get_registry
from .trace import Tracer, get_tracer


def postmortem_dir() -> Optional[str]:
  return knob('GLT_OBS_POSTMORTEM_DIR', None) or None


class FlightRecorder:
  """Bounded operational-event ring with postmortem dumping.

  Args:
    capacity: event-ring size (oldest drop first).
    dump_dir: postmortem directory; None reads
      ``GLT_OBS_POSTMORTEM_DIR`` *at each dump* (so enabling the knob
      mid-process works). No dir -> trips record + count but never
      touch the filesystem.
    min_dump_interval_s: floor between trip-initiated dumps
      (``GLT_OBS_POSTMORTEM_MIN_S``, default 30); explicit ``dump()``
      calls ignore it.
    spans_tail: max tracer spans included per dump.
    registry / tracer: explicit surfaces (tests); None = process
      globals.
  """

  def __init__(self, capacity: int = 512,
               dump_dir: Optional[str] = None,
               min_dump_interval_s: Optional[float] = None,
               spans_tail: int = 256,
               registry: Optional[MetricsRegistry] = None,
               tracer: Optional[Tracer] = None):
    if min_dump_interval_s is None:
      # knob() warns-and-defaults on a malformed value, so this can
      # never crash `import glt_tpu.obs` (the module-level recorder
      # runs this at import — the GLT_OBS_BUFFER bug class)
      min_dump_interval_s = knob('GLT_OBS_POSTMORTEM_MIN_S', 30.0)
    self._events: 'deque[dict]' = deque(maxlen=max(int(capacity), 16))
    self._lock = threading.Lock()
    self._dump_dir = dump_dir
    self._min_dump_s = float(min_dump_interval_s)
    self._spans_tail = int(spans_tail)
    self._registry = registry
    self._tracer = tracer
    self._last_dump_ts = 0.0
    self._last_counters: Dict[str, float] = {}
    self._abnormal = False          # a trip or uncaught exception seen
    self._exit_hooked = False
    self._file_seq = itertools.count(1)  # filename counter (attempt-
                                         # unique even for failed dumps)
    self.dumps = 0                  # postmortems WRITTEN (lifetime)

  # -- surfaces ----------------------------------------------------------

  def _reg(self) -> MetricsRegistry:
    return self._registry if self._registry is not None \
        else get_registry()

  def _trc(self) -> Tracer:
    return self._tracer if self._tracer is not None else get_tracer()

  def _dir(self) -> Optional[str]:
    return self._dump_dir if self._dump_dir is not None \
        else postmortem_dir()

  def events(self) -> List[dict]:
    with self._lock:
      return list(self._events)

  # -- recording ---------------------------------------------------------

  def record(self, kind: str, **data) -> None:
    """Append one operational event to the ring (cheap, never dumps):
    breaker state changes, fallbacks, shed decisions — the breadcrumb
    trail a postmortem replays."""
    evt = {'ts': time.time(), 'kind': str(kind), **data}
    with self._lock:
      self._events.append(evt)
    try:
      self._reg().counter('flight_events_total', kind=str(kind)).inc()
    except Exception:
      pass

  def trip(self, reason: str, **data) -> Optional[str]:
    """A resilience mechanism fired (breaker opened, engine stalled,
    ingestor died): record the event, count
    ``flight_trips_total{reason=...}``, arm the abnormal-exit hook, and
    — rate-limited, postmortem dir permitting — dump. Returns the dump
    path when one was written."""
    self.record(reason, **data)
    try:
      self._reg().counter('flight_trips_total',
                          reason=str(reason)).inc()
    except Exception:
      pass
    self._abnormal = True
    self._ensure_exit_hooks()
    now = time.monotonic()
    with self._lock:
      if self._last_dump_ts and now - self._last_dump_ts \
          < self._min_dump_s:
        return None
    return self.dump(reason)

  # -- dumping -----------------------------------------------------------

  def _counters_delta(self, counters: dict) -> dict:
    """Counter movement since the previous SUCCESSFUL dump — a flat
    registry snapshot says where counters ENDED; the delta says what
    moved during the failure window. Pure read: the baseline commits
    only after the dump actually lands on disk."""
    return {k: v - self._last_counters.get(k, 0.0)
            for k, v in counters.items()
            if v != self._last_counters.get(k, 0.0)}

  def dump(self, reason: str = 'manual') -> Optional[str]:
    """Write one postmortem JSON; returns its path (None when no
    postmortem dir is configured or the write failed). All dump state
    (rate-limit clock, dump counter, delta baseline) commits only on a
    SUCCESSFUL write — a transiently unwritable dir must not rate-limit
    away the retry that would have captured the incident."""
    d = self._dir()
    if not d:
      return None
    try:
      os.makedirs(d, exist_ok=True)
      snap = self._reg().snapshot()
      counters = snap.get('counters', {})
      with self._lock:
        doc = {
            'reason': str(reason),
            'ts': time.time(),
            'pid': os.getpid(),
            'events': list(self._events),
            'spans': self._trc().events()[-self._spans_tail:],
            'registry': snap,
            'counters_delta': self._counters_delta(counters),
        }
      n = next(self._file_seq)
      safe = ''.join(c if c.isalnum() or c in '-_' else '_'
                     for c in str(reason))[:48]
      path = os.path.join(
          d, f'postmortem_{os.getpid()}_{n:03d}_{safe}.json')
      with open(path, 'w') as f:
        json.dump(doc, f, indent=2, default=str)
      with self._lock:
        self._last_dump_ts = time.monotonic()
        self._last_counters = dict(counters)
        self.dumps += 1
      try:
        self._reg().counter('flight_dumps_total').inc()
      except Exception:
        pass
      return path
    except OSError:
      return None

  # -- abnormal-exit hooks -----------------------------------------------

  def _ensure_exit_hooks(self) -> None:
    """Chain sys.excepthook + register atexit once: an uncaught
    exception dumps immediately; a process that saw any trip leaves a
    final dump at interpreter exit (rate limit ignored — it is the
    last chance)."""
    if self._exit_hooked or not self._dir():
      return
    self._exit_hooked = True
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
      self._abnormal = True
      try:
        self.record('uncaught_exception', error=repr(exc))
        self.dump('uncaught_exception')
      except Exception:
        pass
      prev(exc_type, exc, tb)

    sys.excepthook = hook
    atexit.register(self._atexit_dump)

  def _atexit_dump(self) -> None:
    if self._abnormal:
      try:
        self.dump('atexit')
      except Exception:
        pass


@dataclasses.dataclass
class SloPolicy:
  """One latency SLO: "``objective`` of requests observed by
  ``metric``/``labels`` complete within ``threshold_s``"."""
  name: str
  metric: str
  threshold_s: float
  objective: float = 0.99
  labels: dict = dataclasses.field(default_factory=dict)

  @property
  def error_budget(self) -> float:
    return max(1.0 - float(self.objective), 1e-9)


def parse_slo_env(spec: Optional[str] = None) -> List[SloPolicy]:
  """``GLT_OBS_SLO='serve_p99:serving_latency_seconds:0.25:0.99;...'``
  -> policies. Metric may carry labels:
  ``stage_seconds{stage=serve.infer}``."""
  if spec is None:
    spec = knob('GLT_OBS_SLO', '')
  out = []
  for chunk in (spec or '').split(';'):
    chunk = chunk.strip()
    if not chunk:
      continue
    parts = chunk.split(':')
    if len(parts) < 3:
      raise ValueError(
          f'GLT_OBS_SLO entry {chunk!r}: expected '
          'name:metric:threshold_s[:objective]')
    name, metric, threshold = parts[0], parts[1], float(parts[2])
    objective = float(parts[3]) if len(parts) > 3 else 0.99
    labels = {}
    if '{' in metric:
      metric, _, inner = metric.partition('{')
      for pair in inner.rstrip('}').split(','):
        if pair:
          k, _, v = pair.partition('=')
          labels[k.strip()] = v.strip().strip('"')
    out.append(SloPolicy(name, metric, threshold, objective, labels))
  return out


class SloBurnEvaluator:
  """Windowed burn rate over registry histograms.

  Each ``evaluate()`` reads every policy's histogram, diffs (count,
  count_above_threshold) against the previous call, and publishes
  ``slo_burn{slo=name}`` = windowed bad fraction / error budget (0.0
  for an empty window — no traffic burns no budget). Call it from any
  periodic loop (serving stats thread, bench tail, ops cron); state is
  per-evaluator, so two evaluators window independently."""

  def __init__(self, policies: Optional[List[SloPolicy]] = None,
               registry: Optional[MetricsRegistry] = None,
               recorder: Optional[FlightRecorder] = None,
               trip_above: Optional[float] = None):
    self.policies = list(policies) if policies is not None \
        else parse_slo_env()
    self._registry = registry
    self._recorder = recorder
    #: burn level that counts as an SLO trip on the flight recorder
    #: (None disables; e.g. 10.0 = "burning 10x budget" fast-burn page)
    self.trip_above = trip_above
    self._last: Dict[str, tuple] = {}
    # window state is read-modify-write: concurrent evaluate() calls
    # (two monitoring clients pulling stats() at once) would double-
    # count the gap between overlapping windows without this
    self._lock = threading.Lock()

  def add(self, name: str, metric: str, threshold_s: float,
          objective: float = 0.99, **labels) -> 'SloBurnEvaluator':
    self.policies.append(
        SloPolicy(name, metric, threshold_s, objective, labels))
    return self

  def evaluate(self) -> Dict[str, float]:
    return {name: rec['burn']
            for name, rec in self.evaluate_detailed().items()}

  def evaluate_detailed(self) -> Dict[str, dict]:
    """Like :meth:`evaluate` but returns
    ``{name: {'burn': float, 'window': int}}`` — the window request
    count lets callers (the fleet scale-signal loop) suppress
    decisions over windows too thin to mean anything."""
    reg = self._registry if self._registry is not None \
        else get_registry()
    out = {}
    for p in self.policies:
      h = reg.histogram(p.metric, **p.labels)
      # one lock hold for the pair: separate reads tear under
      # concurrent observers and overstate the bad fraction
      count, above = h.count_and_above(p.threshold_s)
      with self._lock:
        l_count, l_above = self._last.get(p.name, (0, 0))
        if count < l_count:  # histogram replaced/reset: restart window
          l_count = l_above = 0
        d_count, d_above = count - l_count, above - l_above
        self._last[p.name] = (count, above)
      burn = (d_above / d_count) / p.error_budget if d_count > 0 \
          else 0.0
      out[p.name] = {'burn': burn, 'window': int(d_count)}
      # the policy's labels ride the gauge too: two shards sharing one
      # registry (distinct view= labels) publish distinct burn series
      # instead of clobbering each other
      reg.set('slo_burn', burn, slo=p.name, **p.labels)
      if (self.trip_above is not None and burn >= self.trip_above
          and self._recorder is not None):
        self._recorder.trip('slo_burn', slo=p.name, burn=round(burn, 3),
                            threshold_s=p.threshold_s,
                            objective=p.objective,
                            window_requests=d_count)
    return out


#: process-global recorder — the surface resilience hooks (breaker
#: on_open, the batcher stall watchdog, the stream ingestor's applier
#: death) report into without plumbing
_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
  return _RECORDER


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
  """Swap the process-global recorder (tests); returns the previous
  one."""
  global _RECORDER
  prev, _RECORDER = _RECORDER, recorder
  return prev

"""MetricsRegistry — thread-safe labeled counters / gauges / histograms
with JSON and Prometheus-text exposition.

Design constraints, in order:

  1. **One lock, one snapshot.** Every instrument mutation and every
     read goes through the registry's single lock, so ``snapshot()`` is
     one consistent cut — the torn-read bug class fixed twice already
     (EmbeddingCache.hit_rate in PR 3, the failure counters in PR 5)
     cannot recur for anything registered here.
  2. **Fixed memory.** Histograms are the log-spaced
     :class:`LatencyHistogram` (moved here from serving.metrics, which
     re-exports it): ~5% relative bucket error across 10 µs .. ~100 s,
     no reservoir, p99 independent of which samples survived.
  3. **Cheap steady state.** ``counter()``/``gauge()``/``histogram()``
     are get-or-create and return the instrument object — hot paths
     resolve once and call ``inc``/``observe`` directly (one lock hold,
     one float add).

Exposition: ``snapshot()`` (plain dict, json-dumpable), ``to_json()``,
and ``to_prometheus()`` (text format 0.0.4; histograms as summaries).
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, Optional, Tuple


class LatencyHistogram:
  """Log-spaced latency histogram: fixed memory, ~5% relative bucket
  error across 10 µs .. ~100 s."""

  #: geometric bucket layout
  _MIN = 1e-5
  _GROWTH = 1.1

  def __init__(self, num_bins: int = 170):
    self._counts = [0] * (num_bins + 2)  # [under | bins | over]
    self._num_bins = num_bins
    self.count = 0
    self.sum = 0.0
    self.max = 0.0

  def _bin(self, seconds: float) -> int:
    if seconds < self._MIN:
      return 0
    b = int(math.log(seconds / self._MIN) / math.log(self._GROWTH)) + 1
    return min(b, self._num_bins + 1)

  def observe(self, seconds: float) -> None:
    self._counts[self._bin(seconds)] += 1
    self.count += 1
    self.sum += seconds
    self.max = max(self.max, seconds)

  def count_above(self, seconds: float) -> int:
    """Observations in buckets strictly above the one holding
    ``seconds`` (bucket-resolution approximation, ~5% edge error like
    every other read here; the overflow bucket always counts). The SLO
    burn evaluator's windowed bad-event count derives from deltas of
    this."""
    return sum(self._counts[self._bin(seconds) + 1:])

  def fraction_above(self, seconds: float) -> float:
    """Fraction of all observations above ``seconds`` (0.0 when
    empty)."""
    if self.count == 0:
      return 0.0
    return self.count_above(seconds) / self.count

  def percentile(self, q: float) -> float:
    """q in [0, 100]; returns the upper edge of the bucket holding the
    q-th request (0.0 when empty). ``q=0`` returns the underflow edge
    (``_MIN``) — a lower bound on the smallest observation, consistent
    with every other bucket answer being an upper edge."""
    if self.count == 0:
      return 0.0
    target = math.ceil(self.count * q / 100.0)
    seen = 0
    for b, c in enumerate(self._counts):
      seen += c
      if seen >= target:
        if b == 0:
          return self._MIN
        if b > self._num_bins:
          # overflow bucket: it has no finite upper edge (the geometric
          # formula would even UNDERSHOOT real observations there), so
          # the tracked true max is the only honest answer
          return self.max
        return min(self._MIN * self._GROWTH ** b, self.max)
    return self.max

  @property
  def mean(self) -> float:
    return self.sum / self.count if self.count else 0.0


#: (metric name, sorted label items) — the registry's instrument key
_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Optional[dict]) -> _Key:
  if not labels:
    return (str(name), ())
  return (str(name),
          tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def _escape_label_value(v) -> str:
  """Prometheus text-exposition label-value escaping (format 0.0.4):
  backslash, double-quote and newline must be escaped or a value like
  ``say "hi"`` emits malformed exposition text that scrapers reject."""
  return (str(v).replace('\\', r'\\').replace('"', r'\"')
          .replace('\n', r'\n'))


def _render_key(key: _Key) -> str:
  name, items = key
  if not items:
    return name
  inner = ','.join(f'{k}="{v}"' for k, v in items)
  return f'{name}{{{inner}}}'


class _Instrument:
  __slots__ = ('name', 'labels', '_lock')

  def __init__(self, name: str, labels: Tuple, lock: threading.Lock):
    self.name = name
    self.labels = labels
    self._lock = lock


class Counter(_Instrument):
  """Monotonic counter."""

  __slots__ = ('_value',)

  def __init__(self, name, labels, lock):
    super().__init__(name, labels, lock)
    self._value = 0.0

  def inc(self, n: float = 1.0) -> float:
    with self._lock:
      self._value += float(n)
      return self._value

  @property
  def value(self) -> float:
    with self._lock:
      return self._value


class Gauge(_Instrument):
  """Last-value-wins instrument with atomic accumulate."""

  __slots__ = ('_value',)

  def __init__(self, name, labels, lock):
    super().__init__(name, labels, lock)
    self._value = 0.0

  def set(self, value: float) -> None:
    with self._lock:
      self._value = float(value)

  def add(self, delta: float) -> float:
    """Atomic accumulate (one lock hold — a get/set pair would tear
    under concurrent writers, the add_gauge contract)."""
    with self._lock:
      self._value += float(delta)
      return self._value

  @property
  def value(self) -> float:
    with self._lock:
      return self._value


class HistogramMetric(_Instrument):
  """Registry-locked wrapper over :class:`LatencyHistogram` exposing
  its full read API (count/sum/max/mean/percentile)."""

  __slots__ = ('_hist',)

  def __init__(self, name, labels, lock, num_bins: int = 170):
    super().__init__(name, labels, lock)
    self._hist = LatencyHistogram(num_bins)

  def observe(self, seconds: float) -> None:
    with self._lock:
      self._hist.observe(seconds)

  def percentile(self, q: float) -> float:
    with self._lock:
      return self._hist.percentile(q)

  def count_above(self, seconds: float) -> int:
    with self._lock:
      return self._hist.count_above(seconds)

  def fraction_above(self, seconds: float) -> float:
    with self._lock:
      return self._hist.fraction_above(seconds)

  def count_and_above(self, seconds: float) -> Tuple[int, int]:
    """(total count, count above threshold) under ONE lock hold — the
    paired read the SLO burn evaluator windows on (reading them
    separately tears under concurrent observers and can overstate the
    bad fraction)."""
    with self._lock:
      return self._hist.count, self._hist.count_above(seconds)

  @property
  def count(self) -> int:
    with self._lock:
      return self._hist.count

  @property
  def sum(self) -> float:
    with self._lock:
      return self._hist.sum

  @property
  def max(self) -> float:
    with self._lock:
      return self._hist.max

  @property
  def mean(self) -> float:
    with self._lock:
      return self._hist.mean


class MetricsRegistry:
  """Process-local registry of named (optionally labeled) instruments.

  All instruments created by one registry share ITS lock, which is what
  makes :meth:`snapshot` a single consistent cut across every counter,
  gauge and histogram — no reader can observe counter A incremented but
  its always-paired counter B not yet.
  """

  def __init__(self, namespace: str = 'glt'):
    self.namespace = str(namespace)
    self._lock = threading.RLock()
    self._counters: Dict[_Key, Counter] = {}
    self._gauges: Dict[_Key, Gauge] = {}
    self._hists: Dict[_Key, HistogramMetric] = {}

  # -- get-or-create -----------------------------------------------------

  def counter(self, name: str, **labels) -> Counter:
    k = _key(name, labels)
    with self._lock:
      c = self._counters.get(k)
      if c is None:
        c = self._counters[k] = Counter(name, k[1], self._lock)
      return c

  def gauge(self, name: str, **labels) -> Gauge:
    k = _key(name, labels)
    with self._lock:
      g = self._gauges.get(k)
      if g is None:
        g = self._gauges[k] = Gauge(name, k[1], self._lock)
      return g

  def histogram(self, name: str, num_bins: int = 170,
                **labels) -> HistogramMetric:
    k = _key(name, labels)
    with self._lock:
      h = self._hists.get(k)
      if h is None:
        h = self._hists[k] = HistogramMetric(name, k[1], self._lock,
                                             num_bins)
      return h

  # -- one-shot conveniences ---------------------------------------------

  def inc(self, name: str, n: float = 1.0, **labels) -> float:
    return self.counter(name, **labels).inc(n)

  def set(self, name: str, value: float, **labels) -> None:
    self.gauge(name, **labels).set(value)

  def add(self, name: str, delta: float, **labels) -> float:
    return self.gauge(name, **labels).add(delta)

  def observe(self, name: str, seconds: float, **labels) -> None:
    self.histogram(name, **labels).observe(seconds)

  def get(self, name: str, default: float = 0.0, **labels) -> float:
    """Current value of a counter or gauge (counters win on a name
    collision); ``default`` when neither exists."""
    k = _key(name, labels)
    with self._lock:
      c = self._counters.get(k)
      if c is not None:
        return c._value
      g = self._gauges.get(k)
      if g is not None:
        return g._value
      return default

  # -- exposition --------------------------------------------------------

  def snapshot(self) -> dict:
    """One consistent cut of every instrument (single lock hold)."""
    with self._lock:
      counters = {_render_key(k): c._value
                  for k, c in self._counters.items()}
      gauges = {_render_key(k): g._value
                for k, g in self._gauges.items()}
      hists = {}
      for k, h in self._hists.items():
        hh = h._hist
        hists[_render_key(k)] = {
            'count': hh.count,
            'sum': hh.sum,
            'max': hh.max,
            'mean': hh.mean,
            'p50': hh.percentile(50),
            'p99': hh.percentile(99),
        }
    return {'counters': counters, 'gauges': gauges,
            'histograms': hists}

  def to_json(self, **dump_kwargs) -> str:
    return json.dumps(self.snapshot(), **dump_kwargs)

  def to_prometheus(self) -> str:
    """Prometheus text exposition (format 0.0.4). Histograms export as
    summaries (quantile series + _count/_sum) — the log-spaced buckets
    answer percentiles directly, so shipping ~170 bucket series per
    histogram buys nothing."""
    ns = self.namespace

    def fq(name: str) -> str:
      return f'{ns}_{name}' if ns else name

    def labelstr(items, extra=()) -> str:
      pairs = list(items) + list(extra)
      if not pairs:
        return ''
      return ('{' + ','.join(
          f'{k}="{_escape_label_value(v)}"' for k, v in pairs) + '}')

    with self._lock:
      lines = []
      seen_types = set()

      def header(name, typ):
        if name not in seen_types:
          seen_types.add(name)
          lines.append(f'# TYPE {name} {typ}')

      for k, c in sorted(self._counters.items()):
        name = fq(k[0])
        header(name, 'counter')
        lines.append(f'{name}{labelstr(k[1])} {c._value:.17g}')
      for k, g in sorted(self._gauges.items()):
        name = fq(k[0])
        header(name, 'gauge')
        lines.append(f'{name}{labelstr(k[1])} {g._value:.17g}')
      for k, h in sorted(self._hists.items()):
        name = fq(k[0])
        hh = h._hist
        header(name, 'summary')
        for q in (0.5, 0.9, 0.99):
          lines.append(
              f'{name}{labelstr(k[1], [("quantile", q)])} '
              f'{hh.percentile(q * 100):.17g}')
        lines.append(f'{name}_sum{labelstr(k[1])} {hh.sum:.17g}')
        lines.append(f'{name}_count{labelstr(k[1])} {hh.count}')
    return '\n'.join(lines) + '\n'


#: process-global default registry — the ONE surface subsystems publish
#: into unless handed an explicit registry
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
  return _REGISTRY


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
  """Swap the process-global registry (tests / embedding apps); returns
  the previous one so callers can restore it."""
  global _REGISTRY
  prev, _REGISTRY = _REGISTRY, registry
  return prev

"""Host-side pipeline tracer with cross-process context propagation.

A :class:`Tracer` records **spans** — named, timed regions of the host
pipeline (``sample.multihop``, ``gather.features``, ``serve.flush``,
``train.superstep``, ``stream.compact``, ``rpc.client:<callee>`` /
``rpc.server:<callee>``...) — into a bounded ring buffer. Spans nest via
a contextvar, carry a shared ``trace_id``, and export as
Chrome-trace-event JSON (``chrome://tracing`` / Perfetto "open trace
file").

Three bridges make the host spans useful on an accelerator machine:

  * **device annotation** — every span also enters
    ``jax.profiler.TraceAnnotation`` (the :func:`glt_tpu.utils.profile.
    annotate` region), so when an XLA profiler trace is active the host
    stages line up against the device timeline;
  * **device-sync sampling** — JAX dispatch is async, so a host span
    around a jitted call measures dispatch, not compute. A span given
    ``sync=<arrays>`` calls ``jax.block_until_ready`` on exit for a
    sampled fraction of spans (``GLT_OBS_TRACE_SAMPLE``, default 0) —
    truthful stage times at a bounded, configurable cost;
  * **RPC propagation** — ``distributed.rpc`` ships the current
    (trace_id, span_id) with each traced request and the server reopens
    it (:meth:`Tracer.remote_span`), so a cross-machine sample +
    feature lookup assembles into ONE trace; per-endpoint buffers are
    harvested with :func:`collect_endpoint_obs` and merged with
    :func:`merge_chrome_traces`.

Disabled (default), ``span()`` returns a cached null context manager:
one attribute read + one ``if`` per call site. All state is host-side —
tracing cannot introduce recompiles.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Iterable, List, NamedTuple, Optional

from ..utils.env import knob
from .registry import MetricsRegistry, get_registry


class SpanContext(NamedTuple):
  """The propagatable identity of a live span (what crosses the RPC
  wire): everything a child — local or remote — needs to attach."""
  trace_id: str
  span_id: str


class Span(NamedTuple):
  """One finished span (immutable record in the ring buffer)."""
  name: str
  cat: str
  trace_id: str
  span_id: str
  parent_id: Optional[str]
  ts_us: int          # wall-clock start, µs since epoch (cross-process)
  dur_us: int
  pid: int
  tid: int
  args: dict

  def to_chrome(self) -> dict:
    args = {'trace_id': self.trace_id, 'span_id': self.span_id}
    if self.parent_id is not None:
      args['parent_id'] = self.parent_id
    args.update(self.args)
    return {'name': self.name, 'cat': self.cat, 'ph': 'X',
            'ts': self.ts_us, 'dur': self.dur_us,
            'pid': self.pid, 'tid': self.tid, 'args': args}


_current: 'contextvars.ContextVar[Optional[SpanContext]]' = \
    contextvars.ContextVar('glt_obs_span', default=None)


class _NullSpan:
  """Reusable no-op context manager — the disabled-tracer fast path."""

  __slots__ = ()

  def __enter__(self):
    return None

  def __exit__(self, *exc):
    return False


_NULL = _NullSpan()


class _LiveSpan:
  """Context manager for one recording span."""

  __slots__ = ('_tracer', '_name', '_cat', '_args', '_sync', '_ctx',
               '_token', '_parent', '_t0', '_ts', '_ann')

  def __init__(self, tracer: 'Tracer', name: str, cat: str, sync,
               args: dict):
    self._tracer = tracer
    self._name = name
    self._cat = cat
    self._args = args
    self._sync = sync
    self._ann = None

  def __enter__(self) -> SpanContext:
    parent = _current.get()
    if parent is None:
      return self._begin(self._tracer._new_trace_id(), None)
    return self._begin(parent.trace_id, parent.span_id)

  def _begin(self, trace_id: str,
             parent_id: Optional[str]) -> SpanContext:
    """Shared open path (local and remote-parent spans): contextvar
    push, device-annotation bridge, clock stamps."""
    t = self._tracer
    self._parent = parent_id
    self._ctx = SpanContext(trace_id, t._new_span_id())
    self._token = _current.set(self._ctx)
    if t._annotate:
      import jax
      self._ann = jax.profiler.TraceAnnotation(self._name)
      self._ann.__enter__()
    self._ts = time.time_ns() // 1000
    self._t0 = time.perf_counter()
    return self._ctx

  def __exit__(self, *exc):
    t = self._tracer
    if self._sync is not None and t._sample > 0.0 \
        and (t._sample >= 1.0 or random.random() < t._sample):
      import jax
      try:
        # sync may be a zero-arg callable: call sites that only know
        # their output arrays after dispatch hand back a closure
        target = self._sync() if callable(self._sync) else self._sync
        if target is not None:
          jax.block_until_ready(target)
          self._args = dict(self._args, synced=True)
      except Exception:
        pass  # a failed sync must not mask the body's exception
    dur = time.perf_counter() - self._t0
    if self._ann is not None:
      self._ann.__exit__(*exc)
    _current.reset(self._token)
    t._record(Span(self._name, self._cat, self._ctx.trace_id,
                   self._ctx.span_id, self._parent, self._ts,
                   int(dur * 1e6), t._pid,
                   threading.get_ident() & 0x7fffffff, self._args))
    return False


class _RemoteSpan(_LiveSpan):
  """A span re-opened under a REMOTE parent (the rpc server side): the
  incoming SpanContext becomes the parent, and nested local spans
  attach below this one via the contextvar as usual."""

  __slots__ = ('_remote',)

  def __init__(self, tracer, name, cat, remote: SpanContext, args):
    super().__init__(tracer, name, cat, None, args)
    self._remote = remote

  def __enter__(self) -> SpanContext:
    return self._begin(self._remote.trace_id, self._remote.span_id)


class Tracer:
  """Bounded-buffer span recorder; one per process (:func:`get_tracer`).

  Args:
    enabled: initial state (default: the ``GLT_OBS_TRACE`` env knob).
    sample: device-sync sampling rate in [0, 1] for spans that carry a
      ``sync=`` argument (default: ``GLT_OBS_TRACE_SAMPLE`` or 0).
    buffer: ring-buffer capacity in spans (``GLT_OBS_BUFFER``, default
      65536); oldest spans drop first.
    registry: a :class:`MetricsRegistry` that also receives every
      finished span's duration as a ``stage_seconds{stage=<name>}``
      histogram observation (None = the process-global registry) — the
      per-stage breakdown bench.py reports rides these.
  """

  def __init__(self, enabled: Optional[bool] = None,
               sample: Optional[float] = None,
               buffer: Optional[int] = None,
               registry: Optional[MetricsRegistry] = None):
    if enabled is None:
      enabled = knob('GLT_OBS_TRACE', False)
    if sample is None:
      sample = knob('GLT_OBS_TRACE_SAMPLE', 0.0)
    if buffer is None:
      buffer = knob('GLT_OBS_BUFFER', 65536)
    self.enabled = bool(enabled)
    self._sample = min(max(float(sample), 0.0), 1.0)
    self._annotate = knob('GLT_OBS_ANNOTATE', True)
    self._spans: 'deque[Span]' = deque(maxlen=max(int(buffer), 16))
    self._lock = threading.Lock()
    self._pid = os.getpid()
    self._seq = itertools.count()
    self._registry = registry
    self.dropped = 0

  # -- lifecycle ---------------------------------------------------------

  def enable(self, sample: Optional[float] = None) -> 'Tracer':
    self.enabled = True
    if sample is not None:
      self._sample = min(max(float(sample), 0.0), 1.0)
    return self

  def disable(self) -> 'Tracer':
    self.enabled = False
    return self

  def clear(self) -> None:
    with self._lock:
      self._spans.clear()
      self.dropped = 0

  # -- recording ---------------------------------------------------------

  def span(self, name: str, cat: str = 'pipeline', sync=None, **args):
    """Context manager for one pipeline-stage span. No-op (a cached
    null manager) while disabled — safe to leave on every hot path.

    ``sync``: arrays to ``jax.block_until_ready`` on exit for a sampled
    fraction of spans (see ``GLT_OBS_TRACE_SAMPLE``) so the span
    captures device time, not just dispatch."""
    if not self.enabled:
      return _NULL
    return _LiveSpan(self, name, cat, sync, args)

  def remote_span(self, name: str, ctx, cat: str = 'rpc', **args):
    """Reopen an incoming :class:`SpanContext` (e.g. from an RPC
    request header) as this span's parent. Records whenever ``ctx`` is
    present, even if this process's tracer is disabled — the caller
    opted the request into tracing, and its spans are harvested by the
    caller via :func:`collect_endpoint_obs`."""
    if ctx is None:
      return self.span(name, cat=cat, **args)
    if isinstance(ctx, (tuple, list)):
      ctx = SpanContext(str(ctx[0]), str(ctx[1]))
    return _RemoteSpan(self, name, cat, ctx, args)

  def current_context(self) -> Optional[SpanContext]:
    return _current.get()

  def _new_trace_id(self) -> str:
    return os.urandom(8).hex()

  def _new_span_id(self) -> str:
    return f'{self._pid:x}.{next(self._seq)}'

  def _record(self, span: Span) -> None:
    with self._lock:
      dropping = len(self._spans) == self._spans.maxlen
      if dropping:
        self.dropped += 1
      self._spans.append(span)
    reg = self._registry if self._registry is not None \
        else get_registry()
    if dropping:
      # ``dropped`` alone is a silent attribute nothing scrapes; the
      # counter makes span loss visible in every registry snapshot
      reg.inc('obs_spans_dropped_total')
    reg.observe('stage_seconds', span.dur_us / 1e6, stage=span.name)

  # -- export ------------------------------------------------------------

  def spans(self, trace_id: Optional[str] = None) -> List[Span]:
    with self._lock:
      out = list(self._spans)
    if trace_id is not None:
      out = [s for s in out if s.trace_id == trace_id]
    return out

  def events(self, trace_id: Optional[str] = None) -> List[dict]:
    """Finished spans as Chrome trace events (plain dicts — picklable,
    the payload ``collect_endpoint_obs`` harvests over RPC)."""
    return [s.to_chrome() for s in self.spans(trace_id)]

  def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
    return merge_chrome_traces(self.events(trace_id))

  def save(self, path: str, trace_id: Optional[str] = None) -> str:
    return save_chrome_trace(path, self.events(trace_id))


def merge_chrome_traces(*event_lists: Iterable[dict]) -> dict:
  """Merge per-process event lists into one Chrome-trace-event /
  Perfetto-loadable document, adding process_name metadata per pid."""
  events: List[dict] = []
  for lst in event_lists:
    events.extend(lst)
  pids = sorted({e['pid'] for e in events})
  meta = [{'name': 'process_name', 'ph': 'M', 'pid': pid, 'tid': 0,
           'args': {'name': f'glt pid {pid}'}} for pid in pids]
  return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}


def save_chrome_trace(path: str, *event_lists: Iterable[dict]) -> str:
  doc = merge_chrome_traces(*event_lists)
  with open(path, 'w') as f:
    json.dump(doc, f)
  return path


def collect_endpoint_obs(host: str, port: int,
                         timeout: float = 10.0) -> dict:
  """Harvest a remote RpcServer endpoint's obs state on a FRESH
  connection (the ping_endpoint pattern — never contends with a wedged
  shared client): returns ``{'events': [...], 'metrics': {...}}`` from
  the peer's built-in ``_obs`` callee."""
  # local import: distributed.rpc imports this module for propagation
  from ..distributed import rpc as _rpc
  import socket
  sock = socket.create_connection((host, int(port)), timeout=timeout)
  try:
    sock.settimeout(timeout)
    _rpc._send_msg(sock, ('_obs', (), {}))
    status, payload = _rpc._recv_msg(sock)
  finally:
    try:
      sock.close()
    except OSError:
      pass
  if status == 'err':
    raise payload
  return payload


#: process-global tracer
_TRACER = Tracer()


def get_tracer() -> Tracer:
  return _TRACER

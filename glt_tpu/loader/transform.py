"""Batch structures and SamplerOutput -> Batch conversion.

Reference: graphlearn_torch/python/loader/transform.py:26-136 (to_data /
to_hetero_data building PyG Data/HeteroData). Torch-geometric is not a
TPU-side dependency, so the yielded object is a jax pytree (flax struct)
carrying the same fields PyG models read — x, edge_index(row/col), y,
batch, batch_size, num_sampled_nodes/edges — plus the padding masks that
make every shape static. ``to_torch_data`` converts to a real PyG Data
when torch_geometric is importable (CPU interop only).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp

from ..sampler.base import HeteroSamplerOutput, SamplerOutput
from ..typing import EdgeType, NodeType


@flax.struct.dataclass
class Batch:
  """Homogeneous mini-batch, padded static shapes throughout."""
  x: Optional[jax.Array]            # [node_cap, D]
  row: jax.Array                    # [edge_cap] child labels
  col: jax.Array                    # [edge_cap] parent labels
  edge_mask: jax.Array              # [edge_cap]
  node: jax.Array                   # [node_cap] global node ids
  node_count: jax.Array
  y: Optional[jax.Array] = None     # [batch_size] seed labels
  edge_attr: Optional[jax.Array] = None
  edge: Optional[jax.Array] = None  # [edge_cap] edge ids
  num_sampled_nodes: Optional[jax.Array] = None
  num_sampled_edges: Optional[jax.Array] = None
  metadata: Optional[Dict[str, Any]] = None
  batch_size: int = flax.struct.field(pytree_node=False, default=0)
  edge_hop_offsets: Optional[tuple] = flax.struct.field(
      pytree_node=False, default=None)

  @property
  def edge_index(self) -> jax.Array:
    return jnp.stack([self.row, self.col])

  @property
  def num_nodes(self) -> int:
    return self.node.shape[0]

  @property
  def batch(self) -> jax.Array:
    """Global ids of the seed nodes (first batch_size labels)."""
    return self.node[:self.batch_size]


@flax.struct.dataclass
class HeteroBatch:
  x_dict: Dict[NodeType, jax.Array]
  row_dict: Dict[EdgeType, jax.Array]
  col_dict: Dict[EdgeType, jax.Array]
  edge_mask_dict: Dict[EdgeType, jax.Array]
  node_dict: Dict[NodeType, jax.Array]
  node_count_dict: Dict[NodeType, jax.Array]
  y_dict: Optional[Dict[NodeType, jax.Array]] = None
  edge_attr_dict: Optional[Dict[EdgeType, jax.Array]] = None
  edge_dict: Optional[Dict[EdgeType, jax.Array]] = None
  num_sampled_nodes: Optional[Dict[NodeType, jax.Array]] = None
  num_sampled_edges: Optional[Dict[EdgeType, jax.Array]] = None
  metadata: Optional[Dict[str, Any]] = None
  input_type: Optional[NodeType] = flax.struct.field(
      pytree_node=False, default=None)
  batch_size: int = flax.struct.field(pytree_node=False, default=0)
  #: static per-etype hop offsets into the edge buffers (hierarchical
  #: per-layer trimming, reference trim_to_layer); Dict[etype, tuple]
  edge_hop_offsets_dict: Optional[Dict] = flax.struct.field(
      pytree_node=False, default=None)

  def edge_index_dict(self) -> Dict[EdgeType, jax.Array]:
    return {k: jnp.stack([self.row_dict[k], self.col_dict[k]])
            for k in self.row_dict}

  @property
  def batch(self) -> jax.Array:
    return self.node_dict[self.input_type][:self.batch_size]


def to_batch(out: SamplerOutput,
             x: Optional[jax.Array] = None,
             y: Optional[jax.Array] = None,
             edge_attr: Optional[jax.Array] = None,
             batch_size: Optional[int] = None) -> Batch:
  """Assemble a Batch from a SamplerOutput (+ gathered payloads)."""
  return Batch(
      x=x, y=y, edge_attr=edge_attr,
      row=out.row, col=out.col, edge_mask=out.edge_mask,
      node=out.node, node_count=out.node_count, edge=out.edge,
      num_sampled_nodes=out.num_sampled_nodes,
      num_sampled_edges=out.num_sampled_edges,
      metadata=out.metadata,
      batch_size=batch_size if batch_size is not None
      else (out.batch.shape[0] if out.batch is not None else 0),
      edge_hop_offsets=tuple(out.edge_hop_offsets)
      if out.edge_hop_offsets else None,
  )


def to_hetero_batch(out: HeteroSamplerOutput,
                    x_dict=None, y_dict=None, edge_attr_dict=None,
                    batch_size: Optional[int] = None) -> HeteroBatch:
  # hop offsets are STATIC config, not batch data: they live in the
  # non-pytree field below and must not leak into the traced metadata
  meta = {k: v for k, v in (out.metadata or {}).items()
          if k != 'edge_hop_offsets'}
  return HeteroBatch(
      x_dict=x_dict or {},
      row_dict=out.row, col_dict=out.col, edge_mask_dict=out.edge_mask,
      node_dict=out.node, node_count_dict=out.node_count,
      y_dict=y_dict, edge_attr_dict=edge_attr_dict, edge_dict=out.edge,
      num_sampled_nodes=out.num_sampled_nodes,
      num_sampled_edges=out.num_sampled_edges,
      metadata=meta, input_type=out.input_type,
      batch_size=batch_size if batch_size is not None
      else (out.batch[out.input_type].shape[0] if out.batch else 0),
      edge_hop_offsets_dict=_freeze_offsets(
          (out.metadata or {}).get('edge_hop_offsets')),
  )


def _freeze_offsets(offs):
  if not offs:
    return None
  return {k: tuple(v) for k, v in offs.items()}


class EdgeIndex(NamedTuple):
  """Vendored PyG-v1 ``EdgeIndex`` adj (the reference re-exports
  torch_geometric's, sampler/neighbor_sampler.py:32; vendoring the
  3-field NamedTuple keeps the v1 training-loop idiom
  ``for batch_size, n_id, adjs in loader: ... adj.edge_index ...``
  working without a torch_geometric install)."""
  edge_index: object   # [2, m] numpy, message-flow orientation
  e_id: object         # [m] numpy global edge ids, or None
  size: tuple          # (src_count, dst_count)

  def to(self, device):  # PyG-v1 loops call adj.to(device); no-op here
    return self


def to_pyg_v1(batch: Batch):
  """PyG-v1-style (batch_size, n_id, adjs) view (the reference's
  ``as_pyg_v1`` NeighborLoader mode, loader/neighbor_loader.py:110,
  sampler/neighbor_sampler.py:448-472).

  adjs are returned outermost-hop-first (the order layer loops consume):
  each is an :class:`EdgeIndex` (edge_index [2, m] numpy in message-flow
  orientation, e_id or None, size (src_count, dst_count)). Requires
  edge_hop_offsets.
  """
  import numpy as np
  assert batch.edge_hop_offsets is not None
  offs = batch.edge_hop_offsets
  em = np.asarray(batch.edge_mask)
  row = np.asarray(batch.row)
  col = np.asarray(batch.col)
  eid = np.asarray(batch.edge) if batch.edge is not None else None
  counts = np.asarray(batch.num_sampled_nodes)
  n_id = np.asarray(batch.node)[:int(batch.node_count)]
  adjs = []
  for h in range(len(offs) - 1):
    sl = slice(offs[h], offs[h + 1])
    keep = em[sl]
    edge_index = np.stack([row[sl][keep], col[sl][keep]])
    e_id = eid[sl][keep] if eid is not None else None
    src_count = int(counts[:h + 2].sum())
    dst_count = int(counts[:h + 1].sum())
    adjs.append(EdgeIndex(edge_index, e_id, (src_count, dst_count)))
  return batch.batch_size, n_id, list(reversed(adjs))


def to_torch_data(batch: Batch):
  """Optional PyG interop (CPU): mirrors reference to_data field-for-field.
  Requires torch_geometric; raises ImportError otherwise."""
  import numpy as np
  import torch
  from torch_geometric.data import Data
  em = np.asarray(batch.edge_mask)
  edge_index = torch.as_tensor(
      np.stack([np.asarray(batch.row)[em], np.asarray(batch.col)[em]]))
  nc = int(batch.node_count)
  data = Data(
      x=torch.as_tensor(np.asarray(batch.x)[:nc])
      if batch.x is not None else None,
      edge_index=edge_index.long(),
      y=torch.as_tensor(np.asarray(batch.y))
      if batch.y is not None else None)
  data.node = torch.as_tensor(np.asarray(batch.node)[:nc])
  data.batch_size = batch.batch_size
  if batch.num_sampled_nodes is not None:
    data.num_sampled_nodes = np.asarray(batch.num_sampled_nodes).tolist()
    data.num_sampled_edges = np.asarray(batch.num_sampled_edges).tolist()
  return data

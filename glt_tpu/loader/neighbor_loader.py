"""NeighborLoader — the user-facing mini-batch loader.

Reference: graphlearn_torch/python/loader/neighbor_loader.py:27-112.
Builds a NeighborSampler over the dataset's graph and yields Batch /
HeteroBatch pytrees ready for a jitted train step.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..data import Dataset
from ..sampler import NeighborSampler
from .node_loader import NodeLoader


class NeighborLoader(NodeLoader):
  def __init__(self,
               data: Dataset,
               num_neighbors,
               input_nodes,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               with_edge: bool = False,
               with_weight: bool = False,
               collect_features: bool = True,
               replace: bool = False,
               seed: Optional[int] = None,
               device=None,
               prefetch_depth: Optional[int] = None,
               as_pyg_v1: bool = False,
               rng: Optional[np.random.Generator] = None):
    sampler = NeighborSampler(
        data.graph, num_neighbors,
        device=device, with_edge=with_edge, with_weight=with_weight,
        edge_dir=data.edge_dir, replace=replace, seed=seed)
    super().__init__(data, sampler, input_nodes,
                     batch_size=batch_size, shuffle=shuffle,
                     drop_last=drop_last, collect_features=collect_features,
                     prefetch_depth=prefetch_depth, rng=rng)
    #: yield PyG-v1 (batch_size, n_id, adjs) triples instead of Batch
    #: (reference neighbor_loader.py:110 as_pyg_v1 mode)
    self.as_pyg_v1 = bool(as_pyg_v1)

  def __iter__(self):
    it = super().__iter__()
    if not self.as_pyg_v1:
      return it
    from .transform import to_pyg_v1
    return (to_pyg_v1(b) for b in it)

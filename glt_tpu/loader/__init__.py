from .transform import (
    Batch, HeteroBatch, to_batch, to_hetero_batch, to_torch_data,
    to_pyg_v1,
)
from .node_loader import NodeLoader
from .neighbor_loader import NeighborLoader
from .device_epoch import (
    DeviceEpochLoader, SeedSuperstep, pad_seed_batch, shard_n_valid,
    stack_epoch_batches,
)
from .link_loader import LinkLoader, LinkNeighborLoader, \
    get_edge_label_index
from .subgraph_loader import SubGraphLoader

__all__ = [
    'Batch', 'HeteroBatch', 'to_batch', 'to_hetero_batch', 'to_torch_data',
    'to_pyg_v1',
    'NodeLoader', 'NeighborLoader',
    'DeviceEpochLoader', 'SeedSuperstep', 'pad_seed_batch',
    'shard_n_valid', 'stack_epoch_batches',
    'LinkLoader', 'LinkNeighborLoader', 'get_edge_label_index',
    'SubGraphLoader',
]

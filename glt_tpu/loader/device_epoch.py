"""DeviceEpochLoader — on-device seed staging for superstep training.

The per-batch loaders hand the trainer ONE padded seed batch per Python
iteration, so every training step pays a host->device seed transfer and
a jit dispatch. The superstep pipeline (ops/superstep.py) instead wants
an epoch's worth of shuffled, padded seed batches staged on device ONCE
as a ``[T, B]`` stack with per-batch ``n_valid``; the trainer then scans
``K`` batches per dispatch. This module owns that staging, plus the
single ragged-tail padding implementation the per-batch NodeLoader
shares (``pad_seed_batch``).
"""
from __future__ import annotations

from typing import Iterator, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..utils import as_numpy


def pad_seed_batch(seeds: np.ndarray,
                   batch_size: int) -> Tuple[np.ndarray, int]:
  """Pad a (possibly ragged) seed batch to the fixed batch size.

  Fill slots repeat the last valid seed — a real node id, so downstream
  sampling/gather shapes stay static and in-range; ``n_valid`` is what
  masks them out of the loss. THE padding implementation: NodeLoader's
  epoch iterator and the staged epoch stack below both call it.

  Returns ``(padded [batch_size], n_valid)``.
  """
  n_valid = int(seeds.shape[0])
  if n_valid == 0:
    raise ValueError('cannot pad an empty seed batch')
  if n_valid < batch_size:
    seeds = np.concatenate(
        [seeds, np.full(batch_size - n_valid, seeds[-1], seeds.dtype)])
  return seeds, n_valid


def stack_epoch_batches(seeds: np.ndarray, order: np.ndarray,
                        batch_size: int,
                        drop_last: bool) -> Tuple[np.ndarray, np.ndarray]:
  """Slice one epoch's permuted seeds into padded fixed-size batches.

  Returns ``(stack [T, batch_size], n_valid [T])`` — numpy, ready for a
  single ``device_put``.
  """
  n = order.shape[0]
  stack, n_valid = [], []
  for lo in range(0, n, batch_size):
    hi = min(lo + batch_size, n)
    if hi - lo < batch_size and drop_last:
      break
    batch, nv = pad_seed_batch(seeds[order[lo:hi]], batch_size)
    stack.append(batch)
    n_valid.append(nv)
  if not stack:  # fewer seeds than one batch under drop_last: empty
    # epoch (the per-batch NodeLoader's semantics), not a stack error
    return (np.empty((0, batch_size), seeds.dtype),
            np.empty((0,), np.int32))
  return (np.stack(stack),
          np.asarray(n_valid, np.int32))


def shard_n_valid(n_valid: np.ndarray, num_shards: int,
                  shard_batch: int) -> np.ndarray:
  """Split per-batch global valid counts into per-shard counts under the
  shard-major seed layout (shard d owns slots [d*B, (d+1)*B)): shard d
  of a batch with ``v`` valid seeds holds ``clip(v - d*B, 0, B)``.

  n_valid: [T] -> returns [T, num_shards] int32.
  """
  d = np.arange(num_shards, dtype=np.int64) * shard_batch
  return np.clip(n_valid.astype(np.int64)[:, None] - d[None, :],
                 0, shard_batch).astype(np.int32)


class SeedSuperstep(NamedTuple):
  """One K-batch window of the staged epoch.

  seeds: [K, B] device array (B = global batch = num_shards * per-shard
    batch), a slice of the once-per-epoch staged stack — no fresh
    host->device transfer.
  n_valid: [K, num_shards] device array of per-shard valid counts.
  length: K as a Python int (static; the tail window of an epoch whose
    batch count is not divisible by the superstep length is shorter and
    compiles its own program exactly once).
  """
  seeds: jax.Array
  n_valid: jax.Array
  length: int


class DeviceEpochLoader:
  """Stages an epoch of shuffled, padded seed batches on device once and
  yields K-batch windows for superstep training.

  Per epoch the host does ONE permutation + padding pass and ONE
  ``device_put`` of the [T, B] stack (plus [T, S] valid counts); each
  yielded window is a device-side slice. Compare NodeLoader, which
  re-pads and re-uploads per batch.

  Args:
    seeds: seed node ids (any array-like).
    batch_size: GLOBAL batch size (for SPMD: num_shards * per-device
      batch, shard-major layout as SPMDSageTrainStep expects).
    superstep_len: K, batches per dispatch.
    num_shards: mesh width; n_valid comes back per-shard [K, num_shards].
    shuffle/drop_last: epoch iteration controls (reference DataLoader
      semantics, same as NodeLoader).
    drop_last_superstep: also drop a trailing window shorter than K
      (keeps every dispatch the compiled steady-state shape).
    rng: numpy Generator for shuffling (seeded for reproducibility).
    sharding: optional ``jax.sharding.Sharding`` for the staged stacks
      (e.g. ``NamedSharding(mesh, P(None, 'data'))`` so each device
      holds only its seed columns). Default: single-device placement.
  """

  def __init__(self, seeds, batch_size: int, superstep_len: int = 8,
               num_shards: int = 1, shuffle: bool = False,
               drop_last: bool = False,
               drop_last_superstep: bool = False,
               rng: Optional[np.random.Generator] = None,
               sharding=None, n_valid_sharding=None):
    self.seeds = as_numpy(seeds).astype(np.int64)
    if self.seeds.shape[0] == 0:
      raise ValueError('DeviceEpochLoader needs at least one seed')
    self.batch_size = int(batch_size)
    if self.batch_size % int(num_shards):
      raise ValueError(
          f'batch_size {batch_size} not divisible by num_shards '
          f'{num_shards}')
    self.superstep_len = max(1, int(superstep_len))
    self.num_shards = int(num_shards)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.drop_last_superstep = drop_last_superstep
    self.rng = rng or np.random.default_rng(0)
    self.sharding = sharding
    self.n_valid_sharding = n_valid_sharding

  @property
  def batches_per_epoch(self) -> int:
    n = self.seeds.shape[0]
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def __len__(self) -> int:
    """Supersteps per epoch."""
    t = self.batches_per_epoch
    if self.drop_last_superstep:
      return t // self.superstep_len
    return (t + self.superstep_len - 1) // self.superstep_len

  def stage_epoch(self) -> Tuple[jax.Array, jax.Array]:
    """Shuffle, pad, and push one epoch to device: ``(seeds [T, B],
    n_valid [T, S])``, both committed to the loader's shardings."""
    order = (self.rng.permutation(self.seeds.shape[0])
             if self.shuffle else np.arange(self.seeds.shape[0]))
    stack, n_valid = stack_epoch_batches(
        self.seeds, order, self.batch_size, self.drop_last)
    per_shard = shard_n_valid(n_valid, self.num_shards,
                              self.batch_size // self.num_shards)
    seeds_dev = jax.device_put(stack.astype(np.int32), self.sharding)
    nv_dev = jax.device_put(per_shard, self.n_valid_sharding)
    return seeds_dev, nv_dev

  def __iter__(self) -> Iterator[SeedSuperstep]:
    seeds_dev, nv_dev = self.stage_epoch()
    t = seeds_dev.shape[0]
    k = self.superstep_len
    for lo in range(0, t, k):
      hi = min(lo + k, t)
      if hi - lo < k and self.drop_last_superstep:
        break
      # device-side window slice of the staged stack; at most two
      # distinct lengths per epoch (K and the tail), so the consumer
      # compiles at most two programs
      yield SeedSuperstep(
          seeds=jax.lax.slice_in_dim(seeds_dev, lo, hi, axis=0),
          n_valid=jax.lax.slice_in_dim(nv_dev, lo, hi, axis=0),
          length=hi - lo)

"""SubGraphLoader — induced-subgraph batches (SEAL-style workloads).

Reference: graphlearn_torch/python/loader/subgraph_loader.py:27-100:
sample the k-hop neighborhood of the seeds, extract the induced subgraph
over it, return batches with a ``mapping`` from seed order to subgraph
labels.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..data import Dataset
from ..data.feature import gather_features
from ..sampler import NeighborSampler
from .node_loader import NodeLoader
from .transform import Batch


class SubGraphLoader(NodeLoader):
  def __init__(self,
               data: Dataset,
               num_neighbors,
               input_nodes,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               with_edge: bool = False,
               collect_features: bool = True,
               seed: Optional[int] = None,
               device=None,
               rng: Optional[np.random.Generator] = None):
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=device, with_edge=with_edge,
        edge_dir=data.edge_dir, seed=seed)
    super().__init__(data, sampler, input_nodes, batch_size=batch_size,
                     shuffle=shuffle, drop_last=drop_last,
                     collect_features=collect_features, rng=rng)

  def _make_batch(self, seeds: np.ndarray, n_valid: int) -> Batch:
    sub = self.sampler.subgraph(seeds)
    node_valid = jnp.arange(sub.nodes.shape[0]) < sub.node_count
    x = None
    if self.collect_features and self.data.node_features is not None:
      x = gather_features(self.data.get_node_feature(),
                          jnp.maximum(sub.nodes, 0))
    y = None
    if self.data.node_labels is not None:
      y = jnp.asarray(self.data.get_node_label()[seeds])
    # seeds are first-occurrence heads of the node list -> their labels
    # are 0..batch_size-1 when seeds are unique (mapping metadata,
    # reference subgraph_loader.py:90-100)
    # framework orientation contract: row = child (message source),
    # col = parent; induced_subgraph emits rows=expanding(parent)
    return Batch(
        x=x, row=sub.cols, col=sub.rows,
        edge_mask=sub.edge_mask, node=sub.nodes,
        node_count=sub.node_count, y=y, edge=sub.eids,
        metadata={'mapping': jnp.arange(self.batch_size),
                  'n_valid': n_valid},
        batch_size=self.batch_size)

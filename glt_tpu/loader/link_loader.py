"""LinkLoader / LinkNeighborLoader — edge-seeded mini-batch loading.

Reference: graphlearn_torch/python/loader/link_loader.py:35-230 and
link_neighbor_loader.py:27-155. Iterates (row, col, label) edge seeds,
samples the combined endpoint neighborhood (with binary/triplet negative
sampling), and yields batches whose metadata carries edge_label_index /
edge_label or triplet indices. ``get_edge_label_index`` defaults to the
full COO of the graph (reference link_loader.py:203-230).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..data import Dataset
from ..data.feature import gather_features
from ..sampler import (
    EdgeSamplerInput, NegativeSampling, NeighborSampler,
)
from ..utils import as_numpy
from .node_loader import NodeLoader
from .transform import Batch, to_batch


def get_edge_label_index(data: Dataset, edge_label_index=None,
                         input_type=None):
  """Resolve edge seeds: explicit [2, E] array, or (etype, array), or all
  edges of the graph when None."""
  if isinstance(edge_label_index, tuple) \
      and not isinstance(edge_label_index[0], (np.ndarray, list)):
    input_type, edge_label_index = edge_label_index
  if edge_label_index is None:
    g = data.get_graph(input_type)
    ptr, other, _ = g.topo.to_coo()
    if g.layout == 'CSR':
      edge_label_index = np.stack([ptr, other])
    else:
      edge_label_index = np.stack([other, ptr])
  edge_label_index = as_numpy(edge_label_index)
  return input_type, edge_label_index


class LinkLoader(NodeLoader):
  """Edge-seeded loader over an arbitrary sampler."""

  def __init__(self,
               data: Dataset,
               sampler,
               edge_label_index=None,
               edge_label=None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               collect_features: bool = True,
               rng: Optional[np.random.Generator] = None):
    self.input_type, eli = get_edge_label_index(data, edge_label_index)
    self.edge_rows = eli[0].astype(np.int64)
    self.edge_cols = eli[1].astype(np.int64)
    self.edge_label = as_numpy(edge_label)
    self.neg_sampling = NegativeSampling.cast(neg_sampling)
    input_type = self.input_type
    super().__init__(data, sampler, input_nodes=np.arange(
        self.edge_rows.shape[0]), batch_size=batch_size, shuffle=shuffle,
        drop_last=drop_last, collect_features=collect_features, rng=rng)
    # NodeLoader.__init__ resets input_type (its seeds are node ids, ours
    # are edge positions) — restore the edge type
    self.input_type = input_type

  def _make_batch(self, seed_idx: np.ndarray, n_valid: int):
    rows = self.edge_rows[seed_idx]
    cols = self.edge_cols[seed_idx]
    label = (self.edge_label[seed_idx]
             if self.edge_label is not None else None)
    inputs = EdgeSamplerInput(rows, cols, label,
                              input_type=self.input_type,
                              neg_sampling=self.neg_sampling)
    out = self.sampler.sample_from_edges(inputs)
    if self.input_type is not None:
      return self._collate_hetero_link(out, n_valid)
    return self._collate_homo_link(out, n_valid)

  def _collate_homo_link(self, out, n_valid) -> Batch:
    x = None
    if self.collect_features and self.data.node_features is not None:
      x = gather_features(self.data.get_node_feature(), out.node,
                          fused=(out.metadata or {}).get('node_feats'))
    batch = to_batch(out, x=x, batch_size=self.batch_size)
    meta = dict(batch.metadata or {})
    meta['n_valid'] = n_valid
    return batch.replace(metadata=meta)

  def _collate_hetero_link(self, out, n_valid):
    from .transform import to_hetero_batch
    x_dict = {}
    if self.collect_features and self.data.node_features is not None:
      for ntype, node in out.node.items():
        feat = (self.data.node_features.get(ntype)
                if isinstance(self.data.node_features, dict) else None)
        if feat is not None:
          x_dict[ntype] = gather_features(feat, node)
    batch = to_hetero_batch(out, x_dict=x_dict, batch_size=self.batch_size)
    meta = dict(batch.metadata or {})
    meta['n_valid'] = n_valid
    return batch.replace(metadata=meta)


class LinkNeighborLoader(LinkLoader):
  """LinkLoader with a NeighborSampler (reference
  link_neighbor_loader.py:27-155)."""

  def __init__(self,
               data: Dataset,
               num_neighbors,
               edge_label_index=None,
               edge_label=None,
               neg_sampling: Optional[NegativeSampling] = None,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               with_edge: bool = False,
               with_weight: bool = False,
               collect_features: bool = True,
               replace: bool = False,
               seed: Optional[int] = None,
               device=None,
               rng: Optional[np.random.Generator] = None):
    sampler = NeighborSampler(
        data.graph, num_neighbors, device=device, with_edge=with_edge,
        with_weight=with_weight, edge_dir=data.edge_dir, replace=replace,
        seed=seed)
    super().__init__(data, sampler, edge_label_index=edge_label_index,
                     edge_label=edge_label, neg_sampling=neg_sampling,
                     batch_size=batch_size, shuffle=shuffle,
                     drop_last=drop_last,
                     collect_features=collect_features, rng=rng)

"""NodeLoader — seed iteration + sampling + feature collation.

Reference: graphlearn_torch/python/loader/node_loader.py:27-115. The
reference wraps a torch DataLoader for seed batching and gathers features
through UnifiedTensor on the fly. Here the host side only shuffles/pads
seed ids (numpy); everything per-batch — sampling, dedup, feature gather —
is jitted device work. The last ragged batch is padded to the fixed batch
size (with n_valid tracking) so the whole epoch reuses one compiled
program: no recompilation, which is the TPU replacement for the
reference's multi-worker DataLoader overlap.
"""
from __future__ import annotations

from typing import Iterator, Optional, Union

import jax.numpy as jnp
import numpy as np

from ..data import Dataset, Feature
from ..data.feature import gather_features
from ..obs import get_registry, get_tracer
from ..sampler import BaseSampler, NodeSamplerInput, SamplerOutput
from ..utils import as_numpy
from .device_epoch import pad_seed_batch
from .transform import Batch, HeteroBatch, to_batch, to_hetero_batch


class NodeLoader:
  """Iterates seed-node batches through a sampler.

  Args:
    data: the Dataset (graph + features + labels).
    sampler: any BaseSampler (NeighborLoader builds a NeighborSampler).
    input_nodes: seed ids, or (node_type, ids) for hetero.
    batch_size/shuffle/drop_last: epoch iteration controls.
    collect_features: gather node features into the batch.
    rng: numpy Generator for shuffling (seeded for reproducibility).
  """

  def __init__(self,
               data: Dataset,
               sampler: BaseSampler,
               input_nodes,
               batch_size: int = 512,
               shuffle: bool = False,
               drop_last: bool = False,
               collect_features: bool = True,
               prefetch_depth: Optional[int] = None,
               rng: Optional[np.random.Generator] = None):
    self.data = data
    self.sampler = sampler
    if isinstance(input_nodes, tuple) and isinstance(input_nodes[0], str):
      self.input_type, seeds = input_nodes
    else:
      self.input_type, seeds = None, input_nodes
    self.seeds = as_numpy(seeds).astype(np.int64)
    self.batch_size = int(batch_size)
    self.shuffle = shuffle
    self.drop_last = drop_last
    self.collect_features = collect_features
    #: >0 overlaps host batch prep (incl. cold-row gathers) with device
    #: compute via a prefetch thread — the in-process analogue of the
    #: reference's producer/channel overlap. Default (None) = auto:
    #: depth 2 when any feature store has a host phase (spill / HOST
    #: residency — there is host work to hide), else 0 (fully
    #: device-resident collate has nothing to overlap). Measured ratio:
    #: benchmarks/bench_spill_train.py.
    if prefetch_depth is None:
      prefetch_depth = 2 if (collect_features
                             and self._has_host_phase(data)) else 0
    self.prefetch_depth = int(prefetch_depth)
    self.rng = rng or np.random.default_rng(0)
    self._gather_cache = {}
    # resolved once: per-batch inc() is then a single lock hold instead
    # of a registry lookup per iteration (the registry's hot-path rule)
    self._batches_counter = get_registry().counter(
        'loader_batches_total')

  @staticmethod
  def _has_host_phase(data) -> bool:
    """True when collation must touch host RAM per batch (spilled
    feature rows WITHOUT a host-offloaded cold block), so a prefetch
    thread has latency to hide. Offloaded stores serve cold rows
    inside the jitted collate — nothing to overlap."""
    stores = []
    for feats in (data.node_features, data.edge_features):
      if isinstance(feats, dict):
        stores.extend(feats.values())
      elif feats is not None:
        stores.append(feats)
    def host_phase(f):
      if getattr(f, 'fully_device_resident', True):
        return False
      if getattr(f, '_initialized', False):
        return f.cold_array is None  # placement happened: exact answer
      # NOT yet placed: decide from the offload INTENT instead of
      # forcing device placement at loader construction (which would
      # change placement ordering for callers that build loaders before
      # arranging devices/memory — ADVICE r4). If an auto-mode offload
      # later fails at placement (platform without memory kinds) the
      # store falls back to a host phase we did not predict; that costs
      # only the missing prefetch overlap, never correctness.
      from ..utils.offload import offload_requested
      return not offload_requested(getattr(f, '_host_offload', None),
                                   True)
    return any(host_phase(f) for f in stores)

  def __len__(self):
    n = self.seeds.shape[0]
    if self.drop_last:
      return n // self.batch_size
    return (n + self.batch_size - 1) // self.batch_size

  def __iter__(self) -> Iterator[Union[Batch, HeteroBatch]]:
    if self.prefetch_depth > 0:
      from ..utils.prefetch import prefetch
      return iter(prefetch(self._epoch_iter(), self.prefetch_depth))
    return self._epoch_iter()

  def _epoch_iter(self) -> Iterator[Union[Batch, HeteroBatch]]:
    order = (self.rng.permutation(self.seeds.shape[0])
             if self.shuffle else np.arange(self.seeds.shape[0]))
    n = order.shape[0]
    for lo in range(0, n, self.batch_size):
      hi = min(lo + self.batch_size, n)
      if hi - lo < self.batch_size and self.drop_last:
        break
      # ragged tail padded by the shared staged-pad helper (same fill
      # rule as the superstep epoch stack, device_epoch.pad_seed_batch)
      seeds, n_valid = pad_seed_batch(self.seeds[order[lo:hi]],
                                      self.batch_size)
      # counter advances regardless of tracing: metrics exposition and
      # the tracing knob are independent surfaces
      self._batches_counter.inc()
      tracer = get_tracer()
      if tracer.enabled:
        with tracer.span('loader.batch', batch=self.batch_size,
                         n_valid=int(n_valid)):
          batch = self._make_batch(seeds, n_valid)
        yield batch
      else:
        yield self._make_batch(seeds, n_valid)

  # -- collate (reference node_loader.py:87-115 _collate_fn) -------------

  def _make_batch(self, seeds: np.ndarray, n_valid: int):
    if self.input_type is not None:
      out = self.sampler.sample_from_nodes(
          NodeSamplerInput(seeds, self.input_type), n_valid=n_valid)
      return self._collate_hetero(out, seeds, n_valid)
    out = self.sampler.sample_from_nodes(seeds, n_valid=n_valid)
    return self._collate_homo(out, seeds, n_valid)

  def _collate_homo(self, out: SamplerOutput, seeds, n_valid) -> Batch:
    x = None
    if self.collect_features and self.data.node_features is not None:
      # pallas_fused samplers with an in-walk gather hand the block
      # back through metadata; gather_features passes it through
      x = gather_features(self.data.get_node_feature(), out.node,
                          fused=(out.metadata or {}).get('node_feats'))
    y = None
    if self.data.node_labels is not None:
      y = jnp.asarray(self.data.get_node_label()[seeds])
    edge_attr = None
    if out.edge is not None and self.data.edge_features is not None:
      ef = self.data.get_edge_feature()
      edge_attr = gather_features(ef, jnp.maximum(out.edge, 0))
    batch = to_batch(out, x=x, y=y, edge_attr=edge_attr,
                     batch_size=self.batch_size)
    meta = dict(batch.metadata or {})
    meta['n_valid'] = n_valid
    return batch.replace(metadata=meta)

  def _collate_hetero(self, out, seeds, n_valid) -> HeteroBatch:
    x_dict = {}
    if self.collect_features and self.data.node_features is not None:
      for ntype, node in out.node.items():
        feat = (self.data.node_features.get(ntype)
                if isinstance(self.data.node_features, dict) else None)
        if feat is not None:
          x_dict[ntype] = gather_features(feat, node)
    y_dict = None
    if isinstance(self.data.node_labels, dict) \
        and self.input_type in self.data.node_labels:
      y_dict = {self.input_type:
                jnp.asarray(self.data.node_labels[self.input_type][seeds])}
    batch = to_hetero_batch(out, x_dict=x_dict, y_dict=y_dict,
                            batch_size=self.batch_size)
    meta = dict(batch.metadata or {})
    meta['n_valid'] = n_valid
    return batch.replace(metadata=meta)

"""Partition books: id -> partition maps.

Reference: graphlearn_torch/python/partition/partition_book.py (
RangePartitionBook:6-47 with OffsetId2Index:50-64, GLTPartitionBook:67-72)
and the abstract base (partition/base.py:30-37). Payloads are numpy on the
host and convert to jnp for in-jit routing (the SPMD sampler uses these to
bucket ids by owner before all_to_all).
"""
from __future__ import annotations


import numpy as np

from ..utils import as_numpy


class PartitionBook:
  """Abstract id -> partition-index mapping."""

  def __getitem__(self, ids) -> np.ndarray:
    raise NotImplementedError

  @property
  def device_array(self):
    """A representation usable inside jit (see subclasses)."""
    raise NotImplementedError


class RangePartitionBook(PartitionBook):
  """Partitions are consecutive id ranges; bounds[i] is the exclusive end
  of partition i (reference partition_book.py:6-47)."""

  def __init__(self, bounds):
    self.bounds = as_numpy(bounds).astype(np.int64)
    assert np.all(np.diff(self.bounds) >= 0)

  def __getitem__(self, ids) -> np.ndarray:
    ids = as_numpy(ids)
    return np.searchsorted(self.bounds, ids, side='right').astype(np.int32)

  @property
  def num_partitions(self) -> int:
    return int(self.bounds.shape[0])

  @property
  def device_array(self):
    import jax.numpy as jnp
    return jnp.asarray(self.bounds)

  def id2index(self, ids) -> np.ndarray:
    """Global id -> index within its owner partition
    (reference OffsetId2Index:50-64)."""
    ids = as_numpy(ids).astype(np.int64)
    part = self[ids]
    starts = np.concatenate([[0], self.bounds[:-1]])
    return ids - starts[part]


class TablePartitionBook(PartitionBook):
  """Dense per-id table (the reference's GLTPartitionBook:67-72)."""

  def __init__(self, table):
    self.table = as_numpy(table).astype(np.int32)

  def __getitem__(self, ids) -> np.ndarray:
    return self.table[as_numpy(ids)]

  @property
  def num_partitions(self) -> int:
    return int(self.table.max()) + 1 if self.table.size else 0

  @property
  def device_array(self):
    import jax.numpy as jnp
    return jnp.asarray(self.table)


def infer_partition_book(obj) -> PartitionBook:
  if isinstance(obj, PartitionBook):
    return obj
  arr = as_numpy(obj)
  return TablePartitionBook(arr)

"""Offline graph/feature partitioning and the on-disk partition layout.

Reference: graphlearn_torch/python/partition/base.py:192-582 (chunked
PartitionerBase), 755-863 (load_partition), 866-907 (cat_feature_cache).
The on-disk layout mirrors the reference's documented tree
(partition/base.py:459-533), with numpy .npz payloads instead of torch
saves:

    root/
      META.json                  {num_parts, data_cls, edge_dir,
                                  node_types?, edge_types?, graph_caching}
      node_pb.npy | node_pb/<ntype>.npy
      edge_pb.npy | edge_pb/<etype-str>.npy
      part{i}/
        graph.npz | graph/<etype-str>.npz          rows, cols, eids[, weights]
        node_feat.npz | node_feat/<ntype>.npz      feats, ids[, cache_feats,
                                                    cache_ids]
        edge_feat.npz | edge_feat/<etype-str>.npz  feats, ids

Hetero payloads live in per-type subdirectories keyed by ``as_str``.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from ..typing import (
    EdgeType, GraphPartitionData, FeaturePartitionData, NodeType, as_str,
)
from ..utils import as_numpy
from .partition_book import PartitionBook, \
    TablePartitionBook

CHUNK = 4 * 1024 * 1024


def _etype_dir(etype: EdgeType) -> str:
  return as_str(etype)



def _write_node_feat(root_dir: str, part: int, ntype, feats, ids,
                     cache_feats=None, cache_ids=None) -> None:
  """Single place that owns the node_feat on-disk payload/path contract
  (used by the offline partitioner and stage-2 feature builds)."""
  payload = dict(feats=feats, ids=ids)
  if cache_feats is not None and cache_ids is not None \
      and len(cache_ids):
    payload['cache_feats'] = cache_feats
    payload['cache_ids'] = cache_ids
  d = os.path.join(root_dir, f'part{part}', 'node_feat')
  os.makedirs(d, exist_ok=True)
  np.savez(os.path.join(d, f'{ntype}.npz') if ntype
           else os.path.join(d, 'data.npz'), **payload)


class PartitionerBase:
  """Chunked offline partitioner (abstract `_partition_node`).

  Args:
    output_dir: layout root.
    num_parts: partition count.
    num_nodes / num_edges: int (homo) or Dict keyed by type.
    edge_index: [2, E] or dict — COO in original orientation (src, dst).
    node_feat / edge_feat / edge_weights: optional arrays or dicts.
    edge_assign_strategy: 'by_src' | 'by_dst' (reference base.py:292-372).
    chunk_size: ids per processing chunk.
  """

  def __init__(self, output_dir: str, num_parts: int, num_nodes,
               edge_index, node_feat=None, edge_feat=None,
               edge_weights=None, edge_assign_strategy: str = 'by_src',
               chunk_size: int = CHUNK, edge_dir: str = 'out'):
    self.output_dir = output_dir
    self.num_parts = int(num_parts)
    self.is_hetero = isinstance(edge_index, dict)
    self.num_nodes = num_nodes
    self.edge_index = edge_index
    self.node_feat = node_feat
    self.edge_feat = edge_feat
    self.edge_weights = edge_weights
    assert edge_assign_strategy in ('by_src', 'by_dst')
    self.edge_assign_strategy = edge_assign_strategy
    self.chunk_size = int(chunk_size)
    self.edge_dir = edge_dir

  # -- abstract ----------------------------------------------------------

  def _partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    """Returns the node partition table [num_nodes] int32."""
    raise NotImplementedError

  def _cache_node(self, ntype: Optional[NodeType] = None) \
      -> Optional[np.ndarray]:
    """Optional per-partition hot-cache rows: [num_parts, k] id arrays
    (ragged: list of arrays). None = no caching."""
    return None

  # -- driver --------------------------------------------------------------

  def partition(self) -> None:
    os.makedirs(self.output_dir, exist_ok=True)
    if self.is_hetero:
      ntypes = set()
      for (s, _, d) in self.edge_index:
        ntypes.update((s, d))
      node_pbs = {}
      for nt in sorted(ntypes):
        node_pbs[nt] = self._partition_node(nt)
        self._save_pb(os.path.join('node_pb', nt), node_pbs[nt])
      for etype, ei in self.edge_index.items():
        self._partition_etype(etype, as_numpy(ei), node_pbs)
      for nt in sorted(ntypes):
        self._save_node_feat(nt, node_pbs[nt])
      meta = dict(num_parts=self.num_parts, data_cls='hetero',
                  edge_dir=self.edge_dir,
                  edge_assign=self.edge_assign_strategy,
                  node_types=sorted(ntypes),
                  edge_types=[list(e) for e in self.edge_index])
    else:
      node_pb = self._partition_node()
      self._save_pb('node_pb', node_pb)
      self._partition_etype(None, as_numpy(self.edge_index),
                            {None: node_pb})
      self._save_node_feat(None, node_pb)
      meta = dict(num_parts=self.num_parts, data_cls='homo',
                  edge_dir=self.edge_dir,
                  edge_assign=self.edge_assign_strategy)
    with open(os.path.join(self.output_dir, 'META.json'), 'w') as f:
      json.dump(meta, f)

  # -- pieces --------------------------------------------------------------

  def _save_pb(self, rel: str, pb: np.ndarray) -> None:
    path = os.path.join(self.output_dir, rel + '.npy')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    np.save(path, pb.astype(np.int32))

  def _partition_etype(self, etype: Optional[EdgeType], ei: np.ndarray,
                       node_pbs: Dict) -> None:
    """Assign edges through the node PB in chunks and write per-part
    graph payloads + the edge PB (reference base.py:292-372)."""
    num_edges = ei.shape[1]
    if etype is None:
      src_pb = dst_pb = node_pbs[None]
    else:
      src_pb = node_pbs[etype[0]]
      dst_pb = node_pbs[etype[2]]
    anchor_pb = src_pb if self.edge_assign_strategy == 'by_src' else dst_pb
    anchor_row = 0 if self.edge_assign_strategy == 'by_src' else 1

    edge_pb = np.zeros(num_edges, dtype=np.int32)
    per_part: List[List[np.ndarray]] = [[] for _ in range(self.num_parts)]
    for lo in range(0, num_edges, self.chunk_size):
      hi = min(lo + self.chunk_size, num_edges)
      owner = anchor_pb[ei[anchor_row, lo:hi]]
      edge_pb[lo:hi] = owner
      for p in range(self.num_parts):
        sel = np.nonzero(owner == p)[0] + lo
        if sel.size:
          per_part[p].append(sel)
    ename = _etype_dir(etype) if etype else None
    self._save_pb(os.path.join('edge_pb', ename) if ename else 'edge_pb',
                  edge_pb)
    w = (self.edge_weights.get(etype)
         if isinstance(self.edge_weights, dict) else self.edge_weights)
    w = as_numpy(w)
    ef = (self.edge_feat.get(etype)
          if isinstance(self.edge_feat, dict) else self.edge_feat)
    ef = as_numpy(ef)
    for p in range(self.num_parts):
      eids = (np.concatenate(per_part[p]) if per_part[p]
              else np.zeros(0, np.int64))
      payload = dict(rows=ei[0, eids], cols=ei[1, eids], eids=eids)
      if w is not None:
        payload['weights'] = w[eids]
      d = os.path.join(self.output_dir, f'part{p}', 'graph')
      os.makedirs(d, exist_ok=True)
      fname = (os.path.join(d, f'{ename}.npz') if ename
               else os.path.join(d, 'data.npz'))
      np.savez(fname, **payload)
      if ef is not None:
        fd = os.path.join(self.output_dir, f'part{p}', 'edge_feat')
        os.makedirs(fd, exist_ok=True)
        np.savez(os.path.join(fd, f'{ename}.npz') if ename
                 else os.path.join(fd, 'data.npz'),
                 feats=ef[eids], ids=eids)

  def _save_node_feat(self, ntype: Optional[NodeType],
                      node_pb: np.ndarray) -> None:
    feat = (self.node_feat.get(ntype)
            if isinstance(self.node_feat, dict) else self.node_feat)
    feat = as_numpy(feat)
    if feat is None:
      return
    cache = self._cache_node(ntype)
    for p in range(self.num_parts):
      ids = np.nonzero(node_pb == p)[0]
      _write_node_feat(
          self.output_dir, p, ntype, feat[ids], ids,
          cache_feats=(feat[cache[p]] if cache is not None
                       and cache[p].size else None),
          cache_ids=(cache[p] if cache is not None and cache[p].size
                     else None))


# -- loading -----------------------------------------------------------------

def _load_npz(path: str):
  with np.load(path) as z:
    return {k: z[k] for k in z.files}


def load_meta(root: str) -> dict:
  with open(os.path.join(root, 'META.json')) as f:
    return json.load(f)


def load_partition(root: str, part: int):
  """Load one partition (reference base.py:755-863).

  Returns (meta, graph_data, node_feat_data, edge_feat_data, node_pb,
  edge_pb) where payloads are GraphPartitionData / FeaturePartitionData
  (dicts keyed by type for hetero).
  """
  meta = load_meta(root)
  hetero = meta['data_cls'] == 'hetero'
  pdir = os.path.join(root, f'part{part}')

  def load_graph(fname):
    z = _load_npz(fname)
    return GraphPartitionData(
        edge_index=np.stack([z['rows'], z['cols']]),
        eids=z['eids'], weights=z.get('weights'))

  def load_feat(fname):
    z = _load_npz(fname)
    return FeaturePartitionData(
        feats=z['feats'], ids=z['ids'],
        cache_feats=z.get('cache_feats'), cache_ids=z.get('cache_ids'))

  if hetero:
    graph, nfeat, efeat = {}, {}, {}
    etypes = [tuple(e) for e in meta['edge_types']]
    for e in etypes:
      graph[e] = load_graph(
          os.path.join(pdir, 'graph', f'{_etype_dir(e)}.npz'))
      ef = os.path.join(pdir, 'edge_feat', f'{_etype_dir(e)}.npz')
      if os.path.exists(ef):
        efeat[e] = load_feat(ef)
    for nt in meta['node_types']:
      nf = os.path.join(pdir, 'node_feat', f'{nt}.npz')
      if os.path.exists(nf):
        nfeat[nt] = load_feat(nf)
    node_pb = {nt: TablePartitionBook(
        np.load(os.path.join(root, 'node_pb', f'{nt}.npy')))
        for nt in meta['node_types']}
    edge_pb = {e: TablePartitionBook(
        np.load(os.path.join(root, 'edge_pb', f'{_etype_dir(e)}.npy')))
        for e in etypes}
    return meta, graph, nfeat or None, efeat or None, node_pb, edge_pb

  graph = load_graph(os.path.join(pdir, 'graph', 'data.npz'))
  nf = os.path.join(pdir, 'node_feat', 'data.npz')
  nfeat = load_feat(nf) if os.path.exists(nf) else None
  ef = os.path.join(pdir, 'edge_feat', 'data.npz')
  efeat = load_feat(ef) if os.path.exists(ef) else None
  node_pb = TablePartitionBook(np.load(os.path.join(root, 'node_pb.npy')))
  edge_pb = TablePartitionBook(np.load(os.path.join(root, 'edge_pb.npy')))
  return meta, graph, nfeat, efeat, node_pb, edge_pb


def cat_feature_cache(part: int, feat: FeaturePartitionData,
                      pb: PartitionBook):
  """Concat cached hot rows in front of owned rows, build the id->index
  map, and rewrite the feature PB so cached remote ids resolve locally
  (reference base.py:866-907)."""
  table = (pb.table.copy() if isinstance(pb, TablePartitionBook)
           else pb[np.arange(pb.bounds[-1])].copy())
  if feat.cache_feats is None or feat.cache_ids is None:
    feats = feat.feats
    ids = feat.ids
  else:
    feats = np.concatenate([feat.cache_feats, feat.feats])
    ids = np.concatenate([feat.cache_ids, feat.ids])
    table[feat.cache_ids] = part
  max_id = int(ids.max()) + 1 if ids.size else 0
  id2index = np.full(max(max_id, table.shape[0]), -1, np.int64)
  id2index[ids] = np.arange(ids.shape[0])
  return feats, ids, id2index, TablePartitionBook(table)


def build_partition_feature(root_dir: str, node_feat, ntype=None,
                            cache_probs=None, cache_ratio: float = 0.0
                            ) -> None:
  """Two-stage partitioning, stage 2 (reference partition/base.py:585-703
  + examples/igbh/build_partition_feature.py): given an already-saved
  topology partitioning (node PBs on disk), extract and save each
  partition's feature rows — used when features are too large to
  partition together with the topology.
  """
  meta = load_meta(root_dir)
  from ..utils import as_numpy
  node_feat = as_numpy(node_feat)
  if meta['data_cls'] == 'hetero':
    assert ntype is not None
    pb = np.load(os.path.join(root_dir, 'node_pb', f'{ntype}.npy'))
  else:
    pb = np.load(os.path.join(root_dir, 'node_pb.npy'))
  probs = as_numpy(cache_probs)
  cache_num = int(pb.shape[0] * cache_ratio) if cache_ratio else 0
  for p in range(meta['num_parts']):
    ids = np.nonzero(pb == p)[0]
    cache_feats = cache_ids = None
    if cache_num and probs is not None:
      score = probs.copy()
      score[ids] = -1.0
      hot = np.argsort(-score)[:cache_num]
      hot = hot[score[hot] > 0]
      if hot.size:
        cache_feats, cache_ids = node_feat[hot], hot
    _write_node_feat(root_dir, p, ntype, node_feat[ids], ids,
                     cache_feats=cache_feats, cache_ids=cache_ids)

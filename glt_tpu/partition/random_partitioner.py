"""Random node partitioner (reference partition/random_partitioner.py:28-86):
ids assigned round-robin under a random permutation; no feature caching."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..typing import NodeType
from .base import PartitionerBase


class RandomPartitioner(PartitionerBase):
  def __init__(self, *args, seed: int = 0, **kwargs):
    super().__init__(*args, **kwargs)
    self.seed = seed

  def _partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    n = (self.num_nodes[ntype] if isinstance(self.num_nodes, dict)
         else self.num_nodes)
    import zlib
    # crc32, not hash(): python string hashing is per-process randomized
    rng = np.random.default_rng(
        self.seed if ntype is None
        else self.seed + zlib.crc32(ntype.encode()) % 9973)
    perm = rng.permutation(n)
    pb = np.empty(n, dtype=np.int32)
    pb[perm] = np.arange(n, dtype=np.int64) % self.num_parts
    return pb

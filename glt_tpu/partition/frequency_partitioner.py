"""Frequency (hotness) partitioner.

Reference: graphlearn_torch/python/partition/frequency_partitioner.py
(26-205): per-partition access-probability vectors (from pre-sampling the
training seeds of each partition, `NeighborSampler.sample_prob` /
CalNbrProbKernel) drive a greedy chunk assignment maximizing local
hotness; `_cache_node` then picks each partition's hottest remote rows
under a cache budget.
"""
from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

from ..typing import NodeType
from ..utils import as_numpy, parse_size
from .base import PartitionerBase


class FrequencyPartitioner(PartitionerBase):
  """Args beyond PartitionerBase:

    probs: [num_parts, num_nodes] access probabilities per target
      partition (dict keyed by ntype for hetero). Row p comes from
      sample_prob over partition p's training seeds.
    cache_ratio / cache_memory_budget: per-partition hot-cache size as a
      fraction of nodes or a byte budget ('1GB' etc.; converted using the
      feature row nbytes).
    balance: chunked greedy keeps partitions within chunk_size of each
      other (the reference's per-chunk assignment).
  """

  def __init__(self, *args, probs=None, cache_ratio: float = 0.0,
               cache_memory_budget: Union[int, str, None] = None,
               **kwargs):
    super().__init__(*args, **kwargs)
    assert probs is not None, 'FrequencyPartitioner needs probs'
    self.probs = probs
    self.cache_ratio = float(cache_ratio)
    self.cache_memory_budget = cache_memory_budget
    self._pb_cache: Dict = {}

  def _get_probs(self, ntype) -> np.ndarray:
    p = self.probs[ntype] if isinstance(self.probs, dict) else self.probs
    return np.stack([as_numpy(row) for row in p])

  def _partition_node(self, ntype: Optional[NodeType] = None) -> np.ndarray:
    if ntype in self._pb_cache:
      return self._pb_cache[ntype]
    probs = self._get_probs(ntype)          # [P, N]
    num_parts, n = probs.shape
    assert num_parts == self.num_parts
    pb = np.full(n, -1, dtype=np.int32)
    capacity = int(np.ceil(n / num_parts))
    sizes = np.zeros(num_parts, dtype=np.int64)
    # greedy chunked assignment by hotness (reference
    # frequency_partitioner.py:123-171): nodes go to the partition that
    # wants them most, subject to balance capacity. Fully vectorized:
    # per preference rank, each partition takes its hottest still-free
    # candidates up to remaining capacity.
    for lo in range(0, n, self.chunk_size):
      hi = min(lo + self.chunk_size, n)
      c = hi - lo
      chunk = probs[:, lo:hi]               # [P, C]
      order = np.argsort(-chunk, axis=0)    # partitions by desire
      assigned = np.zeros(c, dtype=bool)
      for rank in range(num_parts):
        pref = order[rank]                  # [C] preferred partition
        for p in range(num_parts):
          room = capacity - sizes[p]
          if room <= 0:
            continue
          cand = np.nonzero((pref == p) & ~assigned)[0]
          if cand.size == 0:
            continue
          take = cand[np.argsort(-chunk[p, cand], kind='stable')[:room]]
          pb[lo + take] = p
          assigned[take] = True
          sizes[p] += take.shape[0]
      left = np.nonzero(~assigned)[0]
      if left.size:
        # spread leftovers into spare capacity, least-loaded first
        spare = np.maximum(capacity - sizes, 0)
        while spare.sum() < left.size:       # all full: grow evenly
          spare += 1
        targets = np.repeat(np.argsort(sizes, kind='stable'),
                            spare[np.argsort(sizes, kind='stable')])
        targets = targets[:left.size].astype(np.int32)
        pb[lo + left] = targets
        np.add.at(sizes, targets, 1)
    self._pb_cache[ntype] = pb
    return pb

  def _cache_node(self, ntype: Optional[NodeType] = None):
    probs = self._get_probs(ntype)
    n = probs.shape[1]
    cache_num = int(n * self.cache_ratio)
    if self.cache_memory_budget:
      feat = (self.node_feat.get(ntype)
              if isinstance(self.node_feat, dict) else self.node_feat)
      feat = as_numpy(feat)
      if feat is not None and feat.shape[0]:
        row_bytes = feat[0].nbytes
        budget_num = int(parse_size(self.cache_memory_budget)
                         // max(row_bytes, 1))
        # the byte budget is an upper bound: the smaller of the two wins
        # (reference frequency_partitioner.py:188-198)
        cache_num = min(cache_num, budget_num) if cache_num else budget_num
    cache_num = min(cache_num, n)
    if cache_num <= 0:
      return None
    pb = self._partition_node(ntype)
    out = []
    for p in range(self.num_parts):
      score = probs[p].copy()
      score[pb == p] = -1.0                 # owned rows need no cache
      hot = np.argsort(-score)[:cache_num]
      out.append(hot[score[hot] > 0])
    return out

from .partition_book import (
    PartitionBook, RangePartitionBook, TablePartitionBook,
    infer_partition_book,
)
from .base import (
    PartitionerBase, load_partition, load_meta, cat_feature_cache,
    build_partition_feature,
)
from .random_partitioner import RandomPartitioner
from .frequency_partitioner import FrequencyPartitioner

__all__ = [
    'PartitionBook', 'RangePartitionBook', 'TablePartitionBook',
    'infer_partition_book',
    'PartitionerBase', 'load_partition', 'load_meta', 'cat_feature_cache',
    'build_partition_feature',
    'RandomPartitioner', 'FrequencyPartitioner',
]

"""Core type aliases and shared enums/structs for glt_tpu.

TPU-native re-design of the reference type layer
(reference: graphlearn_torch/python/typing.py:25-93). We keep the same
node/edge-type conventions (so hetero graphs, edge-type reversal and
partition data structures behave identically) but all tensor payloads are
numpy / jax arrays instead of torch tensors.
"""
from __future__ import annotations

import enum
from typing import Dict, List, NamedTuple, Optional, Tuple, Union

import numpy as np

# -- Hetero typing (reference typing.py:25-46) --------------------------------

NodeType = str
#: (src_node_type, relation, dst_node_type)
EdgeType = Tuple[str, str, str]

_REV_PREFIX = 'rev_'


def as_str(type_: Union[NodeType, EdgeType]) -> str:
  if isinstance(type_, NodeType):
    return type_
  if isinstance(type_, (list, tuple)) and len(type_) == 3:
    return '__'.join(type_)
  return ''


def reverse_edge_type(etype: EdgeType) -> EdgeType:
  """'rev_' naming convention for reversed relations."""
  src, rel, dst = etype
  if src != dst:
    if rel.startswith(_REV_PREFIX):
      rel = rel[len(_REV_PREFIX):]
    else:
      rel = _REV_PREFIX + rel
  return (dst, rel, src)


# -- Splits (reference typing.py:55-58) ---------------------------------------

class Split(enum.Enum):
  train = 'train'
  valid = 'valid'
  test = 'test'


# -- Graph residency mode ------------------------------------------------------
# The reference has CPU / DMA(copy-to-GPU) / ZERO_COPY(pinned-UVA)
# (include/graph.h:25-28).  On TPU the analogous residencies are:
#   HBM  -- topology lives as jax device arrays in TPU HBM (DMA analogue)
#   HOST -- topology stays in host memory as numpy; device code receives
#           gathered slices on demand (ZERO_COPY / UVA analogue).

class GraphMode(enum.Enum):
  HBM = 'HBM'
  HOST = 'HOST'


# -- Partition payloads (reference typing.py:62-82) ---------------------------

class GraphPartitionData(NamedTuple):
  """Edges assigned to one partition. ``edge_index``: [2, E] (row, col)."""
  edge_index: np.ndarray
  eids: np.ndarray
  weights: Optional[np.ndarray] = None


class FeaturePartitionData(NamedTuple):
  """Features of one partition: owned rows plus the hot-cache rows."""
  feats: Optional[np.ndarray]
  ids: Optional[np.ndarray]
  cache_feats: Optional[np.ndarray]
  cache_ids: Optional[np.ndarray]


HeteroNodeSeedDict = Dict[NodeType, np.ndarray]
HeteroEdgeSeedDict = Dict[EdgeType, np.ndarray]

NumNeighbors = Union[List[int], Dict[EdgeType, List[int]]]

InputNodes = Union[np.ndarray, Tuple[NodeType, np.ndarray]]
InputEdges = Union[np.ndarray, Tuple[EdgeType, np.ndarray]]

from .sample import (
    FusedHopPlan, NeighborOutput, sample_neighbors,
    sample_neighbors_fused, sample_neighbors_weighted, neighbor_probs,
)
from .unique import ordered_unique, InducerState, init_node, induce_next
from .negative import edge_in_csr, random_negative_sample, NegativeOutput
from .subgraph import induced_subgraph, SubGraph
from .stitch import stitch_rows
from .superstep import superstep, scan_consume
from .delta import delta_one_hop, tombstone_mask

__all__ = [
    'FusedHopPlan', 'NeighborOutput', 'sample_neighbors',
    'sample_neighbors_fused', 'sample_neighbors_weighted',
    'neighbor_probs',
    'ordered_unique', 'InducerState', 'init_node', 'induce_next',
    'edge_in_csr', 'random_negative_sample', 'NegativeOutput',
    'induced_subgraph', 'SubGraph',
    'stitch_rows',
    'superstep', 'scan_consume',
    'delta_one_hop', 'tombstone_mask',
]

"""Functional multi-hop sampling pipeline.

The hop loop shared by the single-device NeighborSampler and the SPMD
(shard_map) training step: sample -> dense-induce -> advance frontier,
all static shapes. Mirrors the reference homo loop
(neighbor_sampler.py:186-230) with the padded-frontier design described
in the NeighborSampler docstring.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp

from .sample import NeighborOutput
from .unique import dense_assign, dense_init, dense_reset

OneHopFn = Callable[[jax.Array, int, jax.Array, jax.Array], NeighborOutput]


def sample_budget(batch_size: int, fanouts: Sequence[int]) -> int:
  budget, width = batch_size, batch_size
  for k in fanouts:
    width *= k
    budget += width
  return budget


def edge_hop_offsets(batch_size: int, fanouts: Sequence[int]) -> List[int]:
  offs, cap = [0], batch_size
  for k in fanouts:
    cap *= k
    offs.append(offs[-1] + cap)
  return offs


def multihop_sample(one_hop: OneHopFn,
                    seeds: jax.Array,
                    n_valid: jax.Array,
                    fanouts: Sequence[int],
                    key: jax.Array,
                    table: jax.Array,
                    scratch: jax.Array,
                    with_edge: bool = False) -> Dict[str, jax.Array]:
  """Runs the full hop loop; returns (out_dict, table, scratch).

  ``one_hop(frontier_ids, fanout, key, mask)`` performs one sampling hop.
  Tables are returned reset, ready for the next batch.
  """
  batch_size = seeds.shape[0]
  budget = sample_budget(batch_size, fanouts)
  state = dense_init(table, scratch, budget)
  seed_mask = jnp.arange(batch_size) < n_valid
  state, seed_labels = dense_assign(state, seeds, seed_mask)
  frontier_ids = jax.lax.slice(state.nodes, (0,), (batch_size,))
  frontier_labels = jnp.arange(batch_size, dtype=jnp.int32)
  frontier_mask = frontier_labels < state.count
  seed_count = state.count

  rows_parent, cols_child, emasks, eid_list = [], [], [], []
  hop_node_counts = [seed_count]
  hop_edge_counts = []
  cap = batch_size
  for fanout in fanouts:
    key, sub = jax.random.split(key)
    out = one_hop(frontier_ids, fanout, sub, frontier_mask)
    prev_count = state.count
    state, labels_flat = dense_assign(
        state, out.nbrs.reshape(-1), out.mask.reshape(-1))
    rows_parent.append(jnp.repeat(frontier_labels, fanout))
    cols_child.append(labels_flat)
    emasks.append(out.mask.reshape(-1))
    if with_edge:
      eid_list.append(out.eids.reshape(-1))
    hop_node_counts.append(state.count - prev_count)
    hop_edge_counts.append(out.mask.sum().astype(jnp.int32))
    cap = cap * fanout
    frontier_labels = prev_count + jnp.arange(cap, dtype=jnp.int32)
    frontier_mask = frontier_labels < state.count
    frontier_ids = jnp.take(state.nodes,
                            jnp.minimum(frontier_labels, budget))

  table, scratch = dense_reset(state)
  out_dict = dict(
      node=jax.lax.slice(state.nodes, (0,), (budget,)),
      node_count=state.count,
      row=jnp.concatenate(cols_child),
      col=jnp.concatenate(rows_parent),
      edge_mask=jnp.concatenate(emasks),
      batch=jax.lax.slice(state.nodes, (0,), (batch_size,)),
      seed_labels=seed_labels,
      seed_count=seed_count,
      num_sampled_nodes=jnp.stack(hop_node_counts),
      num_sampled_edges=jnp.stack(hop_edge_counts),
  )
  if with_edge:
    out_dict['edge'] = jnp.concatenate(eid_list)
  return out_dict, table, scratch

"""Functional multi-hop sampling pipeline.

The hop loop shared by the single-device NeighborSampler and the SPMD
(shard_map) training step: sample -> dense-induce -> advance frontier,
all static shapes. Mirrors the reference homo loop
(neighbor_sampler.py:186-230) with the padded-frontier design described
in the NeighborSampler docstring.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp

from ..utils.env import knob
from .sample import NeighborOutput
from .unique import (dense_assign, dense_init, dense_reset,
                     sorted_hop_dedup, sorted_hop_dedup_fused,
                     sorted_nodes_by_label)

OneHopFn = Callable[[jax.Array, int, jax.Array, jax.Array], NeighborOutput]


def dedup_engine() -> str:
  """Which inducer backs the hop loops (:func:`multihop_sample` and
  :func:`multihop_sample_hetero`): 'table' (dense scatter tables, fast
  where random access is cheap — CPU) or 'sort' (sort-merge, fast where
  sorts are the vectorized primitive — TPU; see ops/unique.py).
  GLT_DEDUP=table|sort|auto overrides; auto picks by backend. The
  hetero sorted path restores slot order with one extra per-type sort
  so per-etype slicing stays exact."""
  mode = knob('GLT_DEDUP', 'auto')
  if mode not in ('auto', 'sort', 'table'):
    raise ValueError(f'GLT_DEDUP={mode!r}: expected auto|sort|table')
  if mode == 'auto':
    if knob('GLT_HOP_ENGINE', '') == 'pallas_fused':
      # the fused engine implements the sort/fused inducer CONTRACT in
      # its kernel (and its fallbacks land on the sort path), so the
      # auto dedup choice follows it on every backend — flipping to
      # dense tables mid-stack would allocate O(N) HBM nothing reads
      return 'sort'
    return 'sort' if jax.default_backend() == 'tpu' else 'table'
  return mode


def fused_hops() -> bool:
  """GLT_FUSED_HOP switches the sort engine's per-hop assign stage to
  :func:`glt_tpu.ops.unique.sorted_hop_dedup_fused` (one narrow sort +
  one packed scatter per hop instead of two wide sorts; within-hop new
  labels come out in value order rather than slot order — see its
  docstring for why that is the only observable change). The seed hop
  always stays on the exact path so ``batch``/``seed_labels`` remain
  bit-identical to the table engine. Read at trace time, like
  :func:`dedup_engine`.

  Default is ``auto``: ON when the sort engine is active on TPU —
  decided by the round-5 hardware A/B (benchmarks/tpu_runs/
  bench_sort_scan4.json: fused 29.87M vs plain 28.51M edges/s/chip,
  and fused >= plain in every scan/PRNG variant measured that round);
  OFF elsewhere (CPU measured it neutral-to-slower under contention).
  GLT_FUSED_HOP=1|0 forces."""
  mode = knob('GLT_FUSED_HOP', 'auto').lower()
  if mode == 'auto':
    return dedup_engine() == 'sort' and jax.default_backend() == 'tpu'
  return mode in ('1', 'true')


#: registered one-hop neighbor-read engines (sampler-side dispatch —
#: distinct from the dedup engines above, which pick the inducer)
HOP_ENGINES = ('element', 'window', 'pallas', 'pallas_fused')


def fused_walk_mode() -> str:
  """How the ``pallas_fused`` engine runs a multi-hop walk:

  * ``cross`` (the ``auto`` default) — the cross-hop fused walk: the
    WHOLE walk is one ``sample_walk_dedup`` kernel invocation whose
    grid spans every hop's frontier blocks, with the VMEM dedup table
    carried across hop boundaries (it never exists in HBM) and one
    window-DMA pipeline serving every hop.
  * ``per_hop`` — the unrolled per-hop kernel family
    (``sample_hop_dedup`` once per hop, table planes round-tripping
    HBM at each boundary) — the ISSUE-10 form, kept for A/B racing and
    as the fallback for shapes the walk does not serve (full-
    neighborhood/weighted hops never reach either form; an empty graph
    routes per-hop, whose empty-input early-outs are exact).

  ``GLT_FUSED_WALK=auto|cross|per_hop``; read at trace time like
  :func:`dedup_engine`. ``auto`` resolves to ``cross`` on a compiled
  TPU backend and ``per_hop`` under interpret mode: the walk's win is
  on-chip table residency and launch collapse, which the interpreter
  cannot deliver — it would only pay the (much larger) whole-walk
  interpret compile on every CPU parity/CI run. Forced values apply
  everywhere (the parity tests and the bench cost duel force
  ``cross`` in interpret mode deliberately)."""
  mode = knob('GLT_FUSED_WALK', 'auto')
  if mode not in ('auto', 'cross', 'per_hop'):
    raise ValueError(
        f'GLT_FUSED_WALK={mode!r}: expected auto|cross|per_hop')
  if mode == 'auto':
    from .pallas_kernels import interpret_default
    return 'per_hop' if interpret_default() else 'cross'
  return mode


#: env-level fallback events already counted this process — hop_engine()
#: is read per hop per trace, and a per-read count would report one
#: configuration event hops x traces times (sampler-level reasons
#: dedupe per sampler instance via their own sets)
_COUNTED_ENV_FALLBACKS = set()


def count_engine_fallback(requested: str, resolved: str,
                          reason: str) -> None:
  """Record an engine-fallback event on the metrics registry
  (``hop_engine_fallbacks_total{requested,resolved,reason}``): a
  requested ``pallas``/``pallas_fused`` engine silently resolving to a
  weaker one is an operational fact worth a counter, not just a log
  line — dashboards can alert on a fleet that quietly lost its fused
  kernels. Counted once per resolution event — a sampler gating a
  shape it can't fuse (callers dedupe per instance) or a process whose
  env requests an unimportable engine — never per sample call or per
  trace-time env read."""
  import logging
  logging.getLogger(__name__).warning(
      'GLT_HOP_ENGINE=%s resolved to %r (%s)', requested, resolved,
      reason)
  try:
    from ..obs import get_recorder, get_registry
    get_registry().counter('hop_engine_fallbacks_total',
                           requested=requested, resolved=resolved,
                           reason=reason).inc()
    # breadcrumb for postmortems: a fleet that quietly lost its fused
    # kernels shows up in the flight-recorder ring next to whatever
    # tripped later
    get_recorder().record('hop_engine_fallback', requested=requested,
                          resolved=resolved, reason=reason)
  except Exception:  # metrics must never break sampling
    pass


def hop_engine() -> str:
  """How the samplers read neighbor values inside a uniform hop:

  * ``element`` — [S, fanout] per-element random gather (the XLA
    baseline; every backend).
  * ``window``  — [S, W] contiguous per-row window read via
    ``lax.gather`` + exact hub fix-up (ops/sample.py window path).
  * ``pallas``  — the one-hop megakernel: window DMA + offset pick +
    hub tail pass fused in one Pallas kernel
    (ops/pallas_kernels.py::sample_hop). Off-TPU backends run it in
    interpret mode (parity/CI); only a TPU backend runs it compiled.
  * ``pallas_fused`` — the full per-hop pipeline fused: sample + dedup
    against a VMEM-resident table in one kernel, plus the optional
    in-walk feature row gather (ops/pallas_kernels.py::
    sample_hop_dedup, routed via ops/sample.py::FusedHopPlan). Label
    semantics are exactly the ``sort+fused`` inducer's; hops the
    fusion cannot serve (hetero, weighted, full-neighborhood, stream
    overlays, table-overflow budgets) fall back to ``pallas`` with a
    counted ``hop_engine_fallbacks_total`` event.

  ``GLT_HOP_ENGINE`` selects; ``auto`` (the default) resolves PER
  BACKEND: on CPU it stays ``element`` (the r5 microbench measured
  XLA's element gather fastest there, and interpret-mode kernels are a
  correctness harness, not a perf path); on TPU it resolves to the
  best servable fused engine — ``pallas_fused`` — gated on a one-time
  probe compile of the kernel family on the real backend
  (``pallas_kernels.auto_probe_ok``), demoting to ``element`` with a
  counted fallback if the probe fails. It deliberately never resolves
  to ``window``: the XLA window gather measured 437 ms for 153k x 96
  rows on a v5e (benchmarks/tpu_runs/microbench_prims_tpu2.json) — the
  window read is only viable as a Pallas DMA. The resolution is
  recorded once per process via
  ``hop_engine_fallbacks_total{requested="auto",...}`` so the flip is
  observable from a registry snapshot; ``GLT_HOP_ENGINE_AUTO=0`` is
  the escape hatch pinning the legacy (element-everywhere) auto.

  All engines draw offsets from the same ``jax.random`` stream, so
  results are bit-identical (ops/sample.py; ``pallas_fused`` is
  bit-identical to the ``sort+fused`` dedup engine, which it
  subsumes). Read at trace time, like :func:`dedup_engine`."""
  mode = knob('GLT_HOP_ENGINE', 'auto')
  if mode not in ('auto',) + HOP_ENGINES:
    raise ValueError(
        f'GLT_HOP_ENGINE={mode!r}: expected '
        'auto|element|window|pallas|pallas_fused')
  if mode == 'auto':
    if not knob('GLT_HOP_ENGINE_AUTO', True):
      return 'element'
    if jax.default_backend() != 'tpu':
      return 'element'
    from .pallas_kernels import auto_probe_ok, pallas_available
    if not pallas_available():
      key = ('auto', 'element', 'pallas_unimportable')
    elif not auto_probe_ok():
      key = ('auto', 'element', 'auto_probe_failed')
    else:
      key = ('auto', 'pallas_fused', 'auto_backend_tpu')
    if key not in _COUNTED_ENV_FALLBACKS:  # one config event per
      _COUNTED_ENV_FALLBACKS.add(key)      # process, not per read
      count_engine_fallback(*key)
    return key[1]
  if mode in ('pallas', 'pallas_fused'):
    from .pallas_kernels import pallas_available
    if not pallas_available():
      key = (mode, 'window', 'pallas_unimportable')
      if key not in _COUNTED_ENV_FALLBACKS:  # one config event, not
        _COUNTED_ENV_FALLBACKS.add(key)      # one per env read
        count_engine_fallback(*key)
      return 'window'
  return mode


def checksum_outputs(out: Dict[str, jax.Array]) -> jax.Array:
  """Fold every multihop output into one scalar so no pipeline stage is
  dead code under jit. Benchmarks that return only an edge-count
  reduction get their neighbor gathers and dedup deleted by XLA (their
  values feed nothing) and then measure a program no real consumer
  runs; summing each output is the static-shape equivalent of the
  reference bench materializing full sample results."""
  acc = jnp.zeros((), jnp.int32)
  for k in ('node', 'row', 'col', 'batch', 'seed_labels'):
    acc += out[k].sum(dtype=jnp.int32)
  acc += out['edge_mask'].sum(dtype=jnp.int32)
  acc += out['node_count'].sum(dtype=jnp.int32)
  return acc


def make_dedup_tables(num_nodes: int):
  """Allocate inducer state for the active dedup engine: the dense
  [N+1] tables for 'table', or 1-element placeholders for 'sort' —
  whose seen-set lives in batch-sized arrays, so allocating real tables
  would pin O(N) dead HBM per node type (~900 MB on papers100M). The
  engine choice is read once here and again at trace time in
  :func:`multihop_sample`; GLT_DEDUP must not change between allocating
  a sampler's tables and tracing its step."""
  from .unique import dense_make_tables
  if dedup_engine() == 'sort':
    # two distinct buffers: callers donate both, and donating one buffer
    # twice is an XLA execute error. Shape (1,) doubles as the engine
    # tag _check_engine_tables verifies at trace time (dense tables are
    # always [num_nodes + 1] >= 2).
    return jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32)
  return dense_make_tables(num_nodes)


def _check_engine_tables(table) -> None:
  """Trace-time guard for the alloc-time/trace-time engine contract:
  running the dense path against the sort engine's 1-element placeholder
  tables would produce silently wrong samples (every dense_assign
  collides on slot 0). Raising here turns an env flip between
  make_dedup_tables and the jitted trace into a loud error."""
  if dedup_engine() == 'table' and table.shape[0] < 2:
    raise ValueError(
        "dedup tables were allocated for the 'sort' engine (placeholder "
        "shape (1,)) but GLT_DEDUP/backend now selects 'table'; "
        "re-allocate with make_dedup_tables under the active engine")


def sample_budget(batch_size: int, fanouts: Sequence[int]) -> int:
  # a negative fanout encodes a full-neighborhood hop with static window
  # |k| (NeighborSampler resolves -1 to -max_degree); capacity math uses
  # the window size either way
  budget, width = batch_size, batch_size
  for k in fanouts:
    width *= abs(k)
    budget += width
  return budget


def edge_hop_offsets(batch_size: int, fanouts: Sequence[int]) -> List[int]:
  offs, cap = [0], batch_size
  for k in fanouts:
    cap *= abs(k)
    offs.append(offs[-1] + cap)
  return offs


def multihop_sample(one_hop: OneHopFn,
                    seeds: jax.Array,
                    n_valid: jax.Array,
                    fanouts: Sequence[int],
                    key: jax.Array,
                    table: jax.Array,
                    scratch: jax.Array,
                    with_edge: bool = False,
                    fused_plan=None) -> Dict[str, jax.Array]:
  """Runs the full hop loop; returns (out_dict, table, scratch).

  ``one_hop(frontier_ids, fanout, key, mask)`` performs one sampling hop.
  Tables are returned reset, ready for the next batch.

  ``fused_plan`` (an :class:`glt_tpu.ops.sample.FusedHopPlan`) routes
  every hop through the ``pallas_fused`` kernel family instead of
  ``one_hop`` + the sort dedup — label semantics identical to the
  ``sort+fused`` engine (the seed hop stays on the exact path), with
  the dedup table resident in VMEM and, when the plan carries a
  ``gather_fn``, each hop's fresh feature rows gathered in-walk
  (``node_feats`` lands in the output dict). The dedup-engine knob is
  ignored on this path; ``table``/``scratch`` pass through untouched
  (allocate them with :func:`make_dedup_tables`, which hands out
  placeholders under this engine).

  Result contract (both engines, homo and hetero): lanes where
  ``edge_mask`` is False carry -1 in the child-label buffer (``row``
  here; ``col`` holds parent labels which are always valid), and invalid
  seed slots carry -1 in ``seed_labels`` — consumers that ignore
  edge_mask still see one well-defined value per engine
  (tests/test_sorted_inducer.py pins this).
  """
  # trace-time tick on the shared hop loop: every enclosing program
  # that (re)traces it shows up under one process-wide label — the
  # pipeline-level row of compiles_total{fn=...} (jit-boundary callers
  # carry their own finer labels)
  from ..obs.perf import count_compile
  count_compile('ops.multihop_sample')
  if fused_plan is not None:
    out = _multihop_sample_fused(fused_plan, seeds, n_valid, fanouts,
                                 key, with_edge=with_edge)
    return out, table, scratch
  if dedup_engine() == 'sort':
    out = _multihop_sample_sorted(one_hop, seeds, n_valid, fanouts, key,
                                  with_edge=with_edge)
    return out, table, scratch
  _check_engine_tables(table)
  batch_size = seeds.shape[0]
  budget = sample_budget(batch_size, fanouts)
  state = dense_init(table, scratch, budget)
  seed_mask = jnp.arange(batch_size) < n_valid
  state, seed_labels = dense_assign(state, seeds, seed_mask)
  frontier_ids = jax.lax.slice(state.nodes, (0,), (batch_size,))
  frontier_labels = jnp.arange(batch_size, dtype=jnp.int32)
  frontier_mask = frontier_labels < state.count
  seed_count = state.count

  rows_parent, cols_child, emasks, eid_list = [], [], [], []
  hop_node_counts = [seed_count]
  hop_edge_counts = []
  cap = batch_size
  for hop_idx, fanout in enumerate(fanouts):
    width = abs(fanout)  # negative = full-neighborhood hop, window |k|
    key, sub = jax.random.split(key)
    # named_scope: trace-time-only labels so device profiler traces
    # (jax.profiler / xprof) break the fused program down by pipeline
    # stage — the in-jit counterpart of the host-side obs spans
    with jax.named_scope(f'sample_hop{hop_idx}'):
      out = one_hop(frontier_ids, fanout, sub, frontier_mask)
    prev_count = state.count
    with jax.named_scope(f'dedup{hop_idx}'):
      state, labels_flat = dense_assign(
          state, out.nbrs.reshape(-1), out.mask.reshape(-1))
    rows_parent.append(jnp.repeat(frontier_labels, width))
    cols_child.append(labels_flat)
    emasks.append(out.mask.reshape(-1))
    if with_edge:
      eid_list.append(out.eids.reshape(-1))
    hop_node_counts.append(state.count - prev_count)
    hop_edge_counts.append(out.mask.sum().astype(jnp.int32))
    cap = cap * width
    frontier_labels = prev_count + jnp.arange(cap, dtype=jnp.int32)
    frontier_mask = frontier_labels < state.count
    frontier_ids = jnp.take(state.nodes,
                            jnp.minimum(frontier_labels, budget))

  table, scratch = dense_reset(state)
  out_dict = dict(
      node=jax.lax.slice(state.nodes, (0,), (budget,)),
      node_count=state.count,
      row=jnp.concatenate(cols_child),
      col=jnp.concatenate(rows_parent),
      edge_mask=jnp.concatenate(emasks),
      batch=jax.lax.slice(state.nodes, (0,), (batch_size,)),
      seed_labels=seed_labels,
      seed_count=seed_count,
      num_sampled_nodes=jnp.stack(hop_node_counts),
      num_sampled_edges=jnp.stack(hop_edge_counts),
  )
  if with_edge:
    out_dict['edge'] = jnp.concatenate(eid_list)
  return out_dict, table, scratch


def _multihop_sample_sorted(one_hop: OneHopFn,
                            seeds: jax.Array,
                            n_valid: jax.Array,
                            fanouts: Sequence[int],
                            key: jax.Array,
                            with_edge: bool = False) -> Dict[str, jax.Array]:
  """The hop loop on the sort-merge inducer (ops/unique.py
  sorted_hop_dedup): no [N]-sized tables, no scatters, no gathers — two
  multi-operand sorts + prefix scans per hop. Labels, node list, batch,
  seed_labels and per-hop counts match the table path EXACTLY; edge
  tuples (row/col/mask/eid) are the same multiset per hop block but in a
  permuted order within the block (consumers are order-insensitive; the
  parity test canonicalizes)."""
  batch_size = seeds.shape[0]
  budget = sample_budget(batch_size, fanouts)
  seed_mask = jnp.arange(batch_size) < n_valid

  u_ids = jnp.zeros((0,), jnp.int32)
  u_labs = jnp.zeros((0,), jnp.int32)
  count = jnp.zeros((), jnp.int32)
  d = sorted_hop_dedup(u_ids, u_labs, count, seeds, seed_mask)
  # contract: seed_labels in seed-slot order (tiny unsort over [batch])
  seed_labels = jax.lax.sort([d['pos3'], d['labels3']], num_keys=1)[1]
  seed_labels = jnp.where(seed_mask, seed_labels, -1)
  seed_count = d['count2']
  u_ids, u_labs, count = d['u_ids2'], d['u_labs2'], d['count2']
  frontier_ids = d['ids3']
  frontier_labels = d['labels3']
  frontier_mask = d['new_head3']

  fused = fused_hops()
  rows_parent, cols_child, emasks, eid_list = [], [], [], []
  hop_node_counts = [seed_count]
  hop_edge_counts = []
  for hop_idx, fanout in enumerate(fanouts):
    width = abs(fanout)
    key, sub = jax.random.split(key)
    # trace-time stage labels for device profiler traces (the in-jit
    # counterpart of the host obs spans; see multihop_sample above)
    with jax.named_scope(f'sample_hop{hop_idx}'):
      out = one_hop(frontier_ids, fanout, sub, frontier_mask)
    rows_flat = jnp.repeat(frontier_labels, width)
    ids_flat = out.nbrs.reshape(-1)
    mask_flat = out.mask.reshape(-1)
    if fused:
      # single-sort assign; per-element outputs come back in SLOT
      # order, so edge payloads (rows/mask/eids) never ride a sort
      with jax.named_scope(f'dedup{hop_idx}'):
        d = sorted_hop_dedup_fused(u_ids, u_labs, count, ids_flat,
                                   mask_flat)
      rows_parent.append(rows_flat)
      cols_child.append(d['labels3'])
      emasks.append(mask_flat)
      if with_edge:
        eid_list.append(out.eids.reshape(-1))
      frontier_ids = jnp.where(d['new_head3'],
                               ids_flat.astype(jnp.int32),
                               jnp.iinfo(jnp.int32).max)
    else:
      eflat = out.eids.reshape(-1) if with_edge else None
      with jax.named_scope(f'dedup{hop_idx}'):
        d = sorted_hop_dedup(u_ids, u_labs, count, ids_flat, mask_flat,
                             rows_flat, eflat, with_mask=True)
      rows_parent.append(d['rows3'])
      cols_child.append(d['labels3'])
      emasks.append(d['mask3'])
      if with_edge:
        eid_list.append(d['eids3'])
      frontier_ids = d['ids3']
    u_ids, u_labs, count = d['u_ids2'], d['u_labs2'], d['count2']
    hop_node_counts.append(d['new_count'])
    hop_edge_counts.append(out.mask.sum().astype(jnp.int32))
    frontier_labels = d['labels3']
    frontier_mask = d['new_head3']

  nodes = sorted_nodes_by_label(u_ids, u_labs, count, budget)
  out_dict = dict(
      node=nodes,
      node_count=count,
      row=jnp.concatenate(cols_child),
      col=jnp.concatenate(rows_parent),
      edge_mask=jnp.concatenate(emasks),
      batch=jax.lax.slice(nodes, (0,), (batch_size,)),
      seed_labels=seed_labels,
      seed_count=seed_count,
      num_sampled_nodes=jnp.stack(hop_node_counts),
      num_sampled_edges=jnp.stack(hop_edge_counts),
  )
  if with_edge:
    out_dict['edge'] = jnp.concatenate(eid_list)
  return out_dict


def _fused_seed_hop(plan, seeds, n_valid, budget):
  """The exact seed hop shared by both fused walk forms: sorted-path
  seed dedup (``batch``/``seed_labels`` bit-identical to every engine)
  plus, when the plan gathers, the seed rows' feature block. Returns
  ``(d, seed_labels, feats|None)`` with ``d`` the raw
  ``sorted_hop_dedup`` dict."""
  big = jnp.iinfo(jnp.int32).max
  batch_size = seeds.shape[0]
  seed_mask = jnp.arange(batch_size) < n_valid
  zero = jnp.zeros((0,), jnp.int32)
  d = sorted_hop_dedup(zero, zero, jnp.zeros((), jnp.int32), seeds,
                       seed_mask)
  seed_labels = jax.lax.sort([d['pos3'], d['labels3']], num_keys=1)[1]
  seed_labels = jnp.where(seed_mask, seed_labels, -1)
  feats = None
  if plan.gather_fn is not None:
    feats = jnp.zeros((budget + 1, plan.feat_dim), plan.feat_dtype)
    # seed rows in label order: one tiny [B] sort
    lab_key = jnp.where(d['new_head3'], d['labels3'], big)
    seed_sorted = jax.lax.sort(
        [lab_key, jnp.where(d['new_head3'], d['ids3'], big)],
        num_keys=1)[1]
    feats = _gather_fresh_rows(feats, plan.gather_fn, seed_sorted,
                               jnp.zeros((), jnp.int32), d['count2'],
                               budget)
  return d, seed_labels, feats


def _fused_output_dict(plan, nodes, count, cols_child, rows_parent,
                       emasks, eid_list, batch_size, seed_labels,
                       seed_count, hop_node_counts, hop_edge_counts,
                       feats, with_edge, budget):
  """Assemble the multihop output surface shared by the per-hop fused
  loop and the cross-hop walk (identical contract, one constructor)."""
  out_dict = dict(
      node=nodes,
      node_count=count,
      row=jnp.concatenate(cols_child),
      col=jnp.concatenate(rows_parent),
      edge_mask=jnp.concatenate(emasks),
      batch=jax.lax.slice(nodes, (0,), (batch_size,)),
      seed_labels=seed_labels,
      seed_count=seed_count,
      num_sampled_nodes=jnp.stack(hop_node_counts),
      num_sampled_edges=jnp.stack(hop_edge_counts),
  )
  if with_edge:
    out_dict['edge'] = jnp.concatenate(eid_list)
  if feats is not None:
    # padded lanes (label >= count) must match the post-hoc gather at
    # node == -1 bit-for-bit, so parity with gather_features holds on
    # EVERY lane, not just the live prefix
    pad_row = plan.gather_fn(jnp.full((1,), -1, jnp.int32))
    lanes = jnp.arange(budget) < count
    out_dict['node_feats'] = jnp.where(
        lanes[:, None], feats[:budget], pad_row.astype(feats.dtype))
  return out_dict


def _multihop_sample_walk(plan, seeds, n_valid, fanouts, key,
                          with_edge: bool = False):
  """The CROSS-HOP fused walk (GLT_FUSED_WALK=cross, the default): one
  ``sample_walk_dedup`` kernel invocation runs every uniform hop —
  window DMA, offset pick, hub fix-up and dedup-table assign — with
  the table resident in VMEM across hop boundaries. The XLA epilogue
  restores the exact ``sorted_hop_dedup_fused`` label contract with an
  incremental remap table: per hop, one narrow [M_h] sort ranks the
  fresh ids by value, the (provisional -> final) mapping accumulates
  into ``R``, and every hop's emitted labels are one gather through
  ``R`` — no per-hop table rewrite exists because the table's
  provisional labels never leave the kernel. Outputs bit-identical to
  ``sort+fused`` and to the per-hop form on every surface (asserted in
  interpret mode by tests/test_pallas_fused.py)."""
  from .pallas_kernels import sample_walk_dedup, walk_geometry
  from .sample import hop_valid_mask, walk_hop_uniforms, \
      _value_order_ranks
  big = jnp.iinfo(jnp.int32).max
  batch_size = seeds.shape[0]
  budget = sample_budget(batch_size, fanouts)
  d, seed_labels, feats = _fused_seed_hop(plan, seeds, n_valid, budget)
  seed_count = d['count2']
  u_ids, u_labs = d['u_ids2'], d['u_labs2']
  count = seed_count
  num_edges = int(plan.indices.shape[0])

  hops, _ = walk_geometry(batch_size, fanouts)
  u_hops = walk_hop_uniforms(key, batch_size, fanouts, plan.replace)
  s1_pad = hops[0]['s_pad']
  pad1 = s1_pad - batch_size
  seed_ids = jnp.pad(d['ids3'].astype(jnp.int32), (0, pad1),
                     constant_values=big)
  seed_ok = jnp.pad(d['new_head3'].astype(jnp.int32), (0, pad1))
  stab_ids = jnp.pad(
      jnp.where(d['new_head3'], d['ids3'].astype(jnp.int32), -1),
      (0, pad1), constant_values=-1)
  stab_labs = jnp.pad(d['labels3'].astype(jnp.int32), (0, pad1))

  picks_t, eidp_t, prov_t, newh_t = sample_walk_dedup(
      plan.indices_win,
      plan.edge_ids_win if plan.edge_ids is not None else None,
      plan.indptr_pad, seed_ids, seed_ok, stab_ids, stab_labs,
      seed_count, u_hops,
      fanouts=tuple(int(f) for f in fanouts), width=plan.width,
      num_nodes=int(plan.indptr.shape[0]) - 1, num_edges=num_edges,
      table_slots=plan.table_slots, batch_size=batch_size,
      replace=plan.replace, interpret=plan.interpret)

  # XLA epilogue: per hop, recompute the draw mask from the shared
  # degree formula, rank the fresh ids by value, extend the
  # provisional->final remap, and emit the final-label surfaces
  remap = jnp.arange(budget + 1, dtype=jnp.int32)  # seeds: identity
  frontier_ids = d['ids3']
  frontier_mask = d['new_head3']
  frontier_labels = d['labels3']
  rows_parent, cols_child, emasks, eid_list = [], [], [], []
  hop_node_counts = [seed_count]
  hop_edge_counts = []
  for h_idx, fanout in enumerate(fanouts):
    h = hops[h_idx]
    s_h, k_h = h['s'], h['k']
    m_h = s_h * k_h
    picks = picks_t[h_idx][:s_h]
    prov_flat = prov_t[h_idx][:s_h].reshape(-1)
    nh = newh_t[h_idx][:s_h].reshape(-1) != 0
    ids_flat = picks.reshape(-1).astype(jnp.int32)
    mask = hop_valid_mask(plan.indptr, frontier_ids, k_h,
                          frontier_mask, plan.replace)
    mask_flat = mask.reshape(-1)
    sorted_new_ids, val_rank = _value_order_ranks(
        ids_flat, nh, prov_flat - count, m_h)
    final = count + jnp.take(
        val_rank, jnp.clip(prov_flat - count, 0, m_h - 1))
    remap = remap.at[jnp.where(nh, prov_flat, budget)].set(
        jnp.where(nh, final, remap[budget]))
    labels3 = jnp.where(
        mask_flat, jnp.take(remap, jnp.clip(prov_flat, 0, budget)), -1)
    new_count = nh.sum(dtype=jnp.int32)

    rows_parent.append(jnp.repeat(frontier_labels, k_h))
    cols_child.append(labels3)
    emasks.append(mask_flat)
    if with_edge:
      eid_list.append(eidp_t[h_idx][:s_h].reshape(-1))
    u_ids = jnp.concatenate([u_ids, jnp.where(nh, ids_flat, big)])
    u_labs = jnp.concatenate([u_labs, jnp.where(nh, labels3, big)])
    if feats is not None:
      with jax.named_scope(f'gather_walk{h_idx}'):
        feats = _gather_fresh_rows(feats, plan.gather_fn,
                                   sorted_new_ids, count, new_count,
                                   budget)
    hop_node_counts.append(new_count)
    hop_edge_counts.append(mask_flat.sum().astype(jnp.int32))
    frontier_ids = jnp.where(nh, ids_flat, big)
    frontier_mask = nh
    frontier_labels = labels3
    count = count + new_count

  nodes = sorted_nodes_by_label(u_ids, u_labs, count, budget)
  return _fused_output_dict(
      plan, nodes, count, cols_child, rows_parent, emasks, eid_list,
      batch_size, seed_labels, seed_count, hop_node_counts,
      hop_edge_counts, feats, with_edge, budget)


def _multihop_sample_fused(plan, seeds, n_valid, fanouts, key,
                           with_edge: bool = False):
  """The hop loop on the ``pallas_fused`` kernel family: the seed hop
  dedups on the EXACT sorted path (same as the fused sort engine, so
  ``batch``/``seed_labels`` stay bit-identical to every engine), its
  uniques seed the VMEM dedup table, and each subsequent hop is ONE
  fused kernel call (sample + table assign) plus the narrow value-order
  relabel — outputs bit-identical to ``sort+fused``
  (GLT_DEDUP=sort GLT_FUSED_HOP=1), asserted in interpret mode by
  tests/test_pallas_fused.py. With ``plan.gather_fn``, each hop's fresh
  unique rows are feature-gathered while the walk runs and assembled
  into ``node_feats`` (label order = row order, exactly
  ``gather_features(feat, node)`` including the padded-lane values).

  Under ``GLT_FUSED_WALK=cross`` (the default) a walk whose shapes the
  cross-hop kernel serves — uniform positive fanouts over a non-empty
  graph — routes to :func:`_multihop_sample_walk` instead: ONE kernel
  invocation for the whole walk, the dedup table never leaving VMEM."""
  if (fused_walk_mode() == 'cross' and plan.indices.shape[0] > 0
      and len(fanouts) > 0 and all(int(f) > 0 for f in fanouts)
      and (not with_edge or plan.edge_ids is not None)):
    # with_edge over a graph WITHOUT an edge-id plane stays per-hop:
    # its eids contract is the raw CSR slots, which only exist where
    # the offsets do — in the per-hop wrapper's XLA prologue (the walk
    # draws offsets on-chip and never materializes slots)
    return _multihop_sample_walk(plan, seeds, n_valid, fanouts, key,
                                 with_edge=with_edge)
  big = jnp.iinfo(jnp.int32).max
  batch_size = seeds.shape[0]
  budget = sample_budget(batch_size, fanouts)

  d, seed_labels, feats = _fused_seed_hop(plan, seeds, n_valid, budget)
  seed_count = d['count2']
  u_ids, u_labs, count = d['u_ids2'], d['u_labs2'], d['count2']
  frontier_ids = d['ids3']
  frontier_labels = d['labels3']
  frontier_mask = d['new_head3']
  table = plan.init_table(jnp.where(d['new_head3'], d['ids3'], -1),
                          d['labels3'],
                          d['new_head3'].astype(jnp.int32))

  rows_parent, cols_child, emasks, eid_list = [], [], [], []
  hop_node_counts = [seed_count]
  hop_edge_counts = []
  for hop_idx, fanout in enumerate(fanouts):
    width = abs(fanout)
    key, sub = jax.random.split(key)
    # one fused kernel = the whole sample+dedup stage; a single device
    # profiler scope covers what sample_hop<i>+dedup<i> label elsewhere
    with jax.named_scope(f'sample_dedup_fused{hop_idx}'):
      out, dd, table = plan(frontier_ids, fanout, sub, frontier_mask,
                            table, count)
    ids_flat = out.nbrs.reshape(-1).astype(jnp.int32)
    mask_flat = out.mask.reshape(-1)
    rows_parent.append(jnp.repeat(frontier_labels, width))
    cols_child.append(dd['labels3'])
    emasks.append(mask_flat)
    if with_edge:
      eid_list.append(out.eids.reshape(-1))
    u_ids = jnp.concatenate(
        [u_ids, jnp.where(dd['new_head3'], ids_flat, big)])
    u_labs = jnp.concatenate(
        [u_labs, jnp.where(dd['new_head3'], dd['labels3'], big)])
    if feats is not None:
      with jax.named_scope(f'gather_fused{hop_idx}'):
        feats = _gather_fresh_rows(feats, plan.gather_fn,
                                   dd['sorted_new_ids'], count,
                                   dd['new_count'], budget)
    frontier_ids = jnp.where(dd['new_head3'], ids_flat, big)
    frontier_labels = dd['labels3']
    frontier_mask = dd['new_head3']
    hop_node_counts.append(dd['new_count'])
    hop_edge_counts.append(out.mask.sum().astype(jnp.int32))
    count = dd['count2']

  nodes = sorted_nodes_by_label(u_ids, u_labs, count, budget)
  return _fused_output_dict(
      plan, nodes, count, cols_child, rows_parent, emasks, eid_list,
      batch_size, seed_labels, seed_count, hop_node_counts,
      hop_edge_counts, feats, with_edge, budget)


def _gather_fresh_rows(feats, gather_fn, ids_sorted, base, n_new,
                       budget):
  """Gather one stage's fresh unique rows (ascending id = label order)
  and scatter them at labels ``base..base+n_new-1``; lanes past
  ``n_new`` land on the sink row. The gather itself rides the plan's
  ``gather_fn`` — the resolve_row_gather seam, so injected/Pallas row
  kernels serve the fused path exactly like the post-hoc one."""
  cap = ids_sorted.shape[0]
  vals = gather_fn(ids_sorted)
  iota = jnp.arange(cap, dtype=jnp.int32)
  idx = jnp.where(iota < n_new, base + iota, budget)
  idx = jnp.clip(idx, 0, budget)
  return feats.at[idx].set(vals.astype(feats.dtype))


def hetero_edge_capacities(caps, trav, num_neighbors, num_hops):
  """Per-etype total edge-slot capacity across hops."""
  out = {}
  for e, (row_t, _) in trav.items():
    out[e] = sum(caps[h][row_t] * abs(num_neighbors[e][h])
                 for h in range(num_hops))
  return out


def hetero_edge_hop_offsets(caps, trav, num_neighbors, num_hops):
  """Per-etype cumulative hop offsets into the concatenated edge
  buffers — the hetero counterpart of :func:`edge_hop_offsets`, used for
  hierarchical per-layer trimming (reference trim_to_layer over
  num_sampled_edges_dict, examples/hetero/hierarchical_sage.py)."""
  offs = {e: [0] for e in trav}
  for h in range(num_hops):
    for e, (row_t, _) in trav.items():
      k = num_neighbors[e][h]
      w = caps[h][row_t] * abs(k) if (caps[h][row_t] and k) else 0
      offs[e].append(offs[e][-1] + w)
  return offs


def multihop_sample_hetero(one_hops, trav, num_neighbors, num_hops,
                           caps, budgets, seeds, n_valid, key, tables,
                           with_edge: bool = False, fused_plan=None):
  """Hetero hop loop shared by the single-device engine and the SPMD
  distributed engine (only the per-edge-type ``one_hops`` differ:
  in-HBM sampling vs the all_to_all collective version).

  Args:
    one_hops: Dict[EdgeType, OneHopFn].
    trav: Dict[EdgeType, (expand_from_type, neighbor_type)].
    num_neighbors: Dict[EdgeType, List[int]].
    caps/budgets: static per-hop frontier capacities / node budgets per
      node type (callers compute them identically from trav).
    seeds/n_valid: Dict[NodeType, array] — multi-type seeding.
    tables: Dict[NodeType, (table, scratch)].
    fused_plan: a :class:`glt_tpu.ops.sample.HeteroFusedPlan` — routes
      every hop through ONE padded multi-edge-type ``sample_hop_dedup``
      invocation (per-edge-type sampling batched over the flat
      edge-type plane, per-type dedup namespaces via type-tagged keys)
      instead of the per-etype ``one_hops`` + per-type sort dedup.
      Label semantics identical to the per-edge-type sorted reference
      with GLT_FUSED_HOP=1; ``tables`` pass through untouched.

  Returns (result dict, out_tables) with per-type node lists, per-etype
  row(parent)/col(child) label buffers in traversal orientation, batch
  and seed_labels dicts, per-hop counts. Tables come back reset.
  """
  from ..obs.perf import count_compile
  count_compile('ops.multihop_sample_hetero')  # trace-time only
  from .unique import dense_assign, dense_init, dense_reset
  if fused_plan is not None:
    result = _multihop_sample_hetero_fused(
        fused_plan, trav, num_neighbors, num_hops, caps, budgets,
        seeds, n_valid, key, with_edge=with_edge)
    return result, tables
  if dedup_engine() == 'sort':
    result = _multihop_sample_hetero_sorted(
        one_hops, trav, num_neighbors, num_hops, caps, budgets, seeds,
        n_valid, key, with_edge=with_edge)
    return result, tables
  for t in tables:
    _check_engine_tables(tables[t][0])
  types = list(budgets)
  states = {t: dense_init(tables[t][0], tables[t][1], budgets[t])
            for t in types}
  seed_labels = {}
  for t, s in seeds.items():
    mask = jnp.arange(s.shape[0]) < n_valid[t]
    states[t], seed_labels[t] = dense_assign(states[t], s, mask)

  frontier = {}
  for t in types:
    c0 = max(1, caps[0][t])
    labels = jnp.arange(c0, dtype=jnp.int32)
    frontier[t] = (jax.lax.slice(states[t].nodes, (0,), (c0,)),
                   labels, labels < states[t].count)

  rows_d, cols_d, mask_d, eid_d = {}, {}, {}, {}
  hop_nodes = {t: [states[t].count] for t in types}
  hop_edges = {}
  for h in range(num_hops):
    per_type_nbrs = {t: [] for t in types}
    per_meta = []
    for e, (row_t, col_t) in trav.items():
      k = num_neighbors[e][h]
      if caps[h][row_t] == 0 or k == 0:
        continue
      width = abs(k)  # negative = full-neighborhood hop, window |k|
      f_ids, f_labels, f_mask = frontier[row_t]
      key, sub = jax.random.split(key)
      out = one_hops[e](f_ids, k, sub, f_mask)
      per_type_nbrs[col_t].append(
          (out.nbrs.reshape(-1), out.mask.reshape(-1)))
      per_meta.append((e, col_t, jnp.repeat(f_labels, width),
                       out.mask.reshape(-1),
                       out.eids.reshape(-1) if with_edge else None,
                       caps[h][row_t] * width))
    prev = {t: states[t].count for t in types}
    labels_by_type = {}
    for t, chunks in per_type_nbrs.items():
      if not chunks:
        continue
      ids = jnp.concatenate([c[0] for c in chunks])
      ok = jnp.concatenate([c[1] for c in chunks])
      states[t], labels = dense_assign(states[t], ids, ok)
      labels_by_type[t] = labels
    cursor = {t: 0 for t in types}
    for e, col_t, rows_parent, mask, eids, width in per_meta:
      s = cursor[col_t]
      cursor[col_t] += width
      lab = jax.lax.slice(labels_by_type[col_t], (s,), (s + width,))
      rows_d.setdefault(e, []).append(rows_parent)
      cols_d.setdefault(e, []).append(lab)
      mask_d.setdefault(e, []).append(mask)
      if with_edge:
        eid_d.setdefault(e, []).append(eids)
      hop_edges.setdefault(e, []).append(mask.sum().astype(jnp.int32))
    for t in types:
      cap_next = max(1, caps[h + 1][t])
      labels = prev[t] + jnp.arange(cap_next, dtype=jnp.int32)
      frontier[t] = (
          jnp.take(states[t].nodes, jnp.minimum(labels, budgets[t])),
          labels, labels < states[t].count)
      hop_nodes[t].append(states[t].count - prev[t])

  out_tables = {}
  for t in types:
    out_tables[t] = dense_reset(states[t])
  result = dict(
      node={t: jax.lax.slice(states[t].nodes, (0,), (budgets[t],))
            for t in types},
      node_count={t: states[t].count for t in types},
      row={e: jnp.concatenate(v) for e, v in rows_d.items()},
      col={e: jnp.concatenate(v) for e, v in cols_d.items()},
      edge_mask={e: jnp.concatenate(v) for e, v in mask_d.items()},
      batch={t: jax.lax.slice(states[t].nodes, (0,),
                              (seeds[t].shape[0],)) for t in seeds},
      seed_labels=seed_labels,
      num_sampled_nodes={t: jnp.stack(v) for t, v in hop_nodes.items()},
      num_sampled_edges={e: jnp.stack(v) for e, v in hop_edges.items()},
  )
  if with_edge:
    result['edge'] = {e: jnp.concatenate(v) for e, v in eid_d.items()}
  return result, out_tables


def _multihop_sample_hetero_sorted(one_hops, trav, num_neighbors,
                                   num_hops, caps, budgets, seeds,
                                   n_valid, key, with_edge: bool = False):
  """The hetero hop loop on the sort-merge inducer: per node type an
  append-form seen-set threaded through :func:`sorted_hop_dedup`, with
  one extra sort per (type, hop) un-permuting labels back to slot order
  so the per-etype cursor slicing below is identical to the table path.
  Label/node/batch/count semantics match the table engine exactly (same
  first-occurrence order over valid slots); per-etype edge tuples are
  the same sets in the same slot order."""
  types = list(budgets)
  seen = {t: (jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.int32),
              jnp.zeros((), jnp.int32)) for t in types}
  seed_labels = {}
  frontier = {}
  for t in types:
    c0 = max(1, caps[0][t])
    if t in seeds:
      s = seeds[t]
      mask = jnp.arange(s.shape[0]) < n_valid[t]
      d = sorted_hop_dedup(*seen[t], s, mask)
      sl = jax.lax.sort([d['pos3'], d['labels3']], num_keys=1)[1]
      seed_labels[t] = jnp.where(mask, sl, -1)
      seen[t] = (d['u_ids2'], d['u_labs2'], d['count2'])
      frontier[t] = (d['ids3'], d['labels3'], d['new_head3'])
    else:
      frontier[t] = (jnp.zeros((c0,), jnp.int32),
                     jnp.full((c0,), -1, jnp.int32),
                     jnp.zeros((c0,), bool))

  rows_d, cols_d, mask_d, eid_d = {}, {}, {}, {}
  hop_nodes = {t: [seen[t][2]] for t in types}
  hop_edges = {}
  for h in range(num_hops):
    per_type = {t: [] for t in types}
    per_meta = []
    for e, (row_t, col_t) in trav.items():
      k = num_neighbors[e][h]
      if caps[h][row_t] == 0 or k == 0:
        continue
      width = abs(k)
      f_ids, f_labels, f_mask = frontier[row_t]
      key, sub = jax.random.split(key)
      out = one_hops[e](f_ids, k, sub, f_mask)
      mflat = out.mask.reshape(-1)
      per_type[col_t].append((out.nbrs.reshape(-1), mflat))
      per_meta.append((e, col_t, jnp.repeat(f_labels, width), mflat,
                       out.eids.reshape(-1) if with_edge else None,
                       caps[h][row_t] * width))
    labels_by_type = {}
    for t, chunks in per_type.items():
      if not chunks:
        cap_next = max(1, caps[h + 1][t])
        frontier[t] = (jnp.zeros((cap_next,), jnp.int32),
                       jnp.full((cap_next,), -1, jnp.int32),
                       jnp.zeros((cap_next,), bool))
        hop_nodes[t].append(jnp.zeros((), jnp.int32))
        continue
      ids = jnp.concatenate([c[0] for c in chunks])
      ok = jnp.concatenate([c[1] for c in chunks])
      if fused_hops():
        # single-sort assign already returns slot order — the
        # per-(type, hop) un-permuting sort below disappears too
        d = sorted_hop_dedup_fused(*seen[t], ids, ok)
        labels_by_type[t] = d['labels3']
        frontier[t] = (jnp.where(d['new_head3'], ids.astype(jnp.int32),
                                 jnp.iinfo(jnp.int32).max),
                       d['labels3'], d['new_head3'])
      else:
        # rows/mask/eids are NOT threaded through the sorts here: the
        # hop's edge buffers are rebuilt in slot order below (per_meta),
        # so the dedup sorts stay as narrow as possible
        d = sorted_hop_dedup(*seen[t], ids, ok)
        # slot-order labels: cols for this hop's edge buffers
        labels_by_type[t] = jax.lax.sort([d['pos3'], d['labels3']],
                                         num_keys=1)[1]
        frontier[t] = (d['ids3'], d['labels3'], d['new_head3'])
      seen[t] = (d['u_ids2'], d['u_labs2'], d['count2'])
      hop_nodes[t].append(d['new_count'])
    cursor = {t: 0 for t in types}
    for e, col_t, rows_parent, mask, eids, width in per_meta:
      s = cursor[col_t]
      cursor[col_t] += width
      lab = jax.lax.slice(labels_by_type[col_t], (s,), (s + width,))
      rows_d.setdefault(e, []).append(rows_parent)
      cols_d.setdefault(e, []).append(jnp.where(mask, lab, -1))
      mask_d.setdefault(e, []).append(mask)
      if with_edge:
        eid_d.setdefault(e, []).append(eids)
      hop_edges.setdefault(e, []).append(mask.sum().astype(jnp.int32))

  nodes = {t: sorted_nodes_by_label(*seen[t], budgets[t]) for t in types}
  result = dict(
      node=nodes,
      node_count={t: seen[t][2] for t in types},
      row={e: jnp.concatenate(v) for e, v in rows_d.items()},
      col={e: jnp.concatenate(v) for e, v in cols_d.items()},
      edge_mask={e: jnp.concatenate(v) for e, v in mask_d.items()},
      batch={t: jax.lax.slice(nodes[t], (0,), (seeds[t].shape[0],))
             for t in seeds},
      seed_labels=seed_labels,
      num_sampled_nodes={t: jnp.stack(v) for t, v in hop_nodes.items()},
      num_sampled_edges={e: jnp.stack(v) for e, v in hop_edges.items()},
  )
  if with_edge:
    result['edge'] = {e: jnp.concatenate(v) for e, v in eid_d.items()}
  return result


def _pad_cols(a, k_max):
  """Pad a [S, k] plane to [S, k_max] lanes (zeros — padded lanes ride
  an all-False validity plane, so the kernel never probes them)."""
  k = a.shape[1]
  return a if k == k_max else jnp.pad(a, ((0, 0), (0, k_max - k)))


def _empty_frontier(c0):
  """Placeholder frontier for a type with no live rows — identical to
  the sorted reference's (zero ids, -1 labels, all-False mask)."""
  return (jnp.zeros((c0,), jnp.int32), jnp.full((c0,), -1, jnp.int32),
          jnp.zeros((c0,), bool))


def _multihop_sample_hetero_fused(plan, trav, num_neighbors, num_hops,
                                  caps, budgets, seeds, n_valid, key,
                                  with_edge: bool = False):
  """The hetero hop loop on the ``pallas_fused`` kernel family: each
  hop's per-edge-type sampling runs as ONE padded multi-edge-type
  ``sample_hop_dedup`` invocation over the flat edge-type plane.

  Per hop: the XLA prologue draws offsets per edge type from the SAME
  key sequence as the reference loop (bit-identical offsets by
  construction), rebases each segment's window starts into the flat
  plane, pads fanouts to the hop's K_max behind the validity lanes,
  and concatenates the per-etype hub fix-ups. The kernel samples every
  segment's windows through one double-buffered DMA pipeline and
  probes/inserts the type-tagged picks into ONE VMEM-resident table —
  global ids never collide across types, so the per-type dedup
  namespaces come free. The XLA epilogue restores the exact per-type
  ``sorted_hop_dedup_fused`` label contract (new ids labeled
  ``count_t..count_t+n_t-1`` in within-hop VALUE order per type) with
  one narrow [m_t] sort per (type, hop) and an incremental provisional
  -> final remap ``R`` (the cross-hop walk's epilogue pattern), so the
  kernel's global first-occurrence labels never leave this function.

  Bit-identical to the per-edge-type sorted reference
  (GLT_DEDUP=sort GLT_FUSED_HOP=1) on every output surface; masked-out
  edge lanes are undefined per engine, as for every fused form
  (asserted in interpret mode by tests/test_pallas_fused.py)."""
  from .pallas_kernels import sample_hop_dedup
  from .sample import _draw_hop, _hub_fixup_inputs, _slots_i32
  big = jnp.iinfo(jnp.int32).max
  types = list(budgets)
  budget_total = int(plan.budget_total)

  # -- exact multi-type seed hop (identical to the sorted reference) --
  seen, seed_labels, frontier = {}, {}, {}
  zero = jnp.zeros((0,), jnp.int32)
  for t in types:
    c0 = max(1, caps[0][t])
    if t in seeds:
      s = seeds[t]
      mask = jnp.arange(s.shape[0]) < n_valid[t]
      d = sorted_hop_dedup(zero, zero, jnp.zeros((), jnp.int32), s,
                           mask)
      sl = jax.lax.sort([d['pos3'], d['labels3']], num_keys=1)[1]
      seed_labels[t] = jnp.where(mask, sl, -1)
      seen[t] = (d['u_ids2'], d['u_labs2'], d['count2'])
      frontier[t] = (d['ids3'], d['labels3'], d['new_head3'])
    else:
      seen[t] = (zero, zero, jnp.zeros((), jnp.int32))
      frontier[t] = _empty_frontier(c0)

  # provisional-global label space: type t's seed uniques take the
  # range [gbase_t, gbase_t + count_t) (gbase = running total in type
  # order); R maps provisional-global -> final per-type labels.
  count = {t: seen[t][2] for t in types}
  gcount = jnp.zeros((), jnp.int32)
  remap = jnp.zeros((budget_total + 1,), jnp.int32)
  ins_ids, ins_labs, ins_ok = [], [], []
  for t in types:
    if t not in seeds:
      continue
    ids3, labels3, nh3 = frontier[t]
    gid = jnp.where(nh3, ids3.astype(jnp.int32) + plan.type_base[t],
                    -1)
    gprov = jnp.where(nh3, gcount + labels3, 0)
    ins_ids.append(gid)
    ins_labs.append(gprov)
    ins_ok.append(nh3.astype(jnp.int32))
    remap = remap.at[jnp.where(nh3, gcount + labels3,
                               budget_total)].set(
        jnp.where(nh3, labels3, remap[budget_total]))
    gcount = gcount + count[t]
  table = plan.init_table(
      jnp.concatenate(ins_ids) if ins_ids else zero,
      jnp.concatenate(ins_labs) if ins_labs else zero,
      jnp.concatenate(ins_ok) if ins_ok else zero)

  rows_d, cols_d, mask_d, eid_d = {}, {}, {}, {}
  hop_nodes = {t: [count[t]] for t in types}
  hop_edges = {}
  for h in range(num_hops):
    # -- XLA prologue: per-etype draws (reference key sequence) -------
    segs = []
    for e, (row_t, col_t) in trav.items():
      k = num_neighbors[e][h]
      if caps[h][row_t] == 0 or k == 0:
        continue
      f_ids, f_labels, f_mask = frontier[row_t]
      key, sub = jax.random.split(key)
      sg = dict(e=e, row_t=row_t, col_t=col_t, k=k, s=f_ids.shape[0],
                f_labels=f_labels, empty=plan.num_edges[e] == 0)
      if not sg['empty']:
        indptr = plan.indptr[e]
        start, deg, offsets, mask = _draw_hop(
            indptr, f_ids.astype(indptr.dtype), k, sub, f_mask,
            plan.replace)
        sg.update(start=start, deg=deg, offsets=offsets, mask=mask,
                  slots=_slots_i32(start, offsets, plan.num_edges[e]))
      segs.append(sg)

    if segs:
      k_max = max(sg['k'] for sg in segs)
      starts_c, offs_c, valid_c, hub_idx_c, hub_slots_c = \
          [], [], [], [], []
      row_off = 0
      for sg in segs:
        sg['row_off'] = row_off
        s_e, k = sg['s'], sg['k']
        if sg['empty']:
          starts_c.append(jnp.zeros((s_e,), jnp.int32))
          offs_c.append(jnp.zeros((s_e, k_max), jnp.int32))
          valid_c.append(jnp.zeros((s_e, k_max), jnp.int32))
        else:
          eb = plan.edge_base[sg['e']]
          starts_c.append((sg['start'].astype(jnp.int32) + eb))
          offs_c.append(_pad_cols(sg['offsets'], k_max))
          valid_c.append(_pad_cols(sg['mask'].astype(jnp.int32),
                                   k_max))
          h_e = min(plan.hub_count[sg['e']], s_e)
          hub_idx, hub_slots = _hub_fixup_inputs(
              sg['deg'], sg['slots'] + eb, plan.width, h_e, k, s_e)
          hub_idx_c.append(jnp.where(hub_idx >= 0,
                                     hub_idx + row_off, -1))
          hub_slots_c.append(_pad_cols(hub_slots, k_max))
        row_off += s_e
      if not hub_idx_c:  # static dummy row: -1 never matches a block
        hub_idx_c = [jnp.full((1,), -1, jnp.int32)]
        hub_slots_c = [jnp.zeros((1, k_max), jnp.int32)]
      tab_ids, tab_labs = table
      with jax.named_scope(f'sample_dedup_hetero_fused{h}'):
        picks, eidp, prov, newh, tab_ids, tab_labs = sample_hop_dedup(
            plan.indices_flat,
            plan.eids_flat if (with_edge and plan.eids_flat is not None)
            else None,
            jnp.concatenate(starts_c), jnp.concatenate(offs_c),
            jnp.concatenate(valid_c), jnp.concatenate(hub_idx_c),
            jnp.concatenate(hub_slots_c), tab_ids, tab_labs, gcount,
            width=plan.width, interpret=plan.interpret)
      table = (tab_ids, tab_labs)
      for sg in segs:
        r0, s_e, k = sg['row_off'], sg['s'], sg['k']
        sg['picks'] = jax.lax.slice(
            picks, (r0, 0), (r0 + s_e, k)).reshape(-1)
        sg['prov'] = jax.lax.slice(
            prov, (r0, 0), (r0 + s_e, k)).reshape(-1)
        sg['nh'] = jax.lax.slice(
            newh, (r0, 0), (r0 + s_e, k)).reshape(-1) != 0
        if with_edge and eidp is not None:
          sg['eidp'] = jax.lax.slice(
              eidp, (r0, 0), (r0 + s_e, k)).reshape(-1)
        sg['mask_flat'] = (jnp.zeros((s_e * k,), bool) if sg['empty']
                          else sg['mask'].reshape(-1))

    # -- XLA epilogue: per-type value-order relabel through R ---------
    labels_by_type = {}
    new_this_hop = jnp.zeros((), jnp.int32)
    for t in types:
      tsegs = [sg for sg in segs if sg['col_t'] == t]
      if not tsegs:
        frontier[t] = _empty_frontier(max(1, caps[h + 1][t]))
        hop_nodes[t].append(jnp.zeros((), jnp.int32))
        continue
      ids_t = jnp.concatenate([sg['picks'].astype(jnp.int32)
                               for sg in tsegs])
      prov_t = jnp.concatenate([sg['prov'] for sg in tsegs])
      nh_t = jnp.concatenate([sg['nh'] for sg in tsegs])
      mask_t = jnp.concatenate([sg['mask_flat'] for sg in tsegs])
      m_t = ids_t.shape[0]
      # one narrow 2-operand sort ranks this hop's fresh type-t ids by
      # VALUE (global order == local order: the type base is a shared
      # additive constant) — the sorted_hop_dedup_fused contract
      keyv = jnp.where(nh_t, ids_t, big)
      iota = jnp.arange(m_t, dtype=jnp.int32)
      sorted_ids, sorted_pos = jax.lax.sort([keyv, iota], num_keys=1)
      rank_slot = jnp.zeros((m_t + 1,), jnp.int32).at[
          jnp.where(sorted_ids < big, sorted_pos, m_t)].set(iota)[:m_t]
      final_t = count[t] + rank_slot
      remap = remap.at[jnp.where(nh_t, prov_t, budget_total)].set(
          jnp.where(nh_t, final_t, remap[budget_total]))
      labels3_t = jnp.where(
          mask_t, jnp.take(remap, jnp.clip(prov_t, 0, budget_total)),
          -1)
      labels_by_type[t] = labels3_t
      new_t = nh_t.sum(dtype=jnp.int32)
      local_ids = ids_t - plan.type_base[t]
      u_ids_t, u_labs_t, _ = seen[t]
      seen[t] = (
          jnp.concatenate([u_ids_t, jnp.where(nh_t, local_ids, big)]),
          jnp.concatenate([u_labs_t, jnp.where(nh_t, labels3_t, big)]),
          count[t] + new_t)
      frontier[t] = (jnp.where(nh_t, local_ids, big), labels3_t, nh_t)
      hop_nodes[t].append(new_t)
      count[t] = count[t] + new_t
      new_this_hop = new_this_hop + new_t
    gcount = gcount + new_this_hop

    # -- per-etype edge buffers, cursor-sliced in traversal order -----
    cursor = {t: 0 for t in types}
    for sg in segs:
      e, col_t, k = sg['e'], sg['col_t'], sg['k']
      w_e = sg['s'] * k
      c0 = cursor[col_t]
      cursor[col_t] += w_e
      lab = jax.lax.slice(labels_by_type[col_t], (c0,), (c0 + w_e,))
      rows_d.setdefault(e, []).append(jnp.repeat(sg['f_labels'], k))
      cols_d.setdefault(e, []).append(
          jnp.where(sg['mask_flat'], lab, -1))
      mask_d.setdefault(e, []).append(sg['mask_flat'])
      if with_edge:
        if sg['empty']:
          eid = jnp.full((w_e,), -1, jnp.int32)
        elif plan.has_eids[e]:
          eid = sg['eidp']
        else:  # no edge-id plane for this type: slot contract (local)
          eid = sg['slots'].reshape(-1)
        eid_d.setdefault(e, []).append(eid)
      hop_edges.setdefault(e, []).append(
          sg['mask_flat'].sum().astype(jnp.int32))

  nodes = {t: sorted_nodes_by_label(*seen[t], budgets[t])
           for t in types}
  result = dict(
      node=nodes,
      node_count={t: seen[t][2] for t in types},
      row={e: jnp.concatenate(v) for e, v in rows_d.items()},
      col={e: jnp.concatenate(v) for e, v in cols_d.items()},
      edge_mask={e: jnp.concatenate(v) for e, v in mask_d.items()},
      batch={t: jax.lax.slice(nodes[t], (0,), (seeds[t].shape[0],))
             for t in seeds},
      seed_labels=seed_labels,
      num_sampled_nodes={t: jnp.stack(v) for t, v in hop_nodes.items()},
      num_sampled_edges={e: jnp.stack(v) for e, v in hop_edges.items()},
  )
  if with_edge:
    result['edge'] = {e: jnp.concatenate(v) for e, v in eid_d.items()}
  return result


def multihop_sample_hetero_many(one_hops, trav, num_neighbors,
                                num_hops, caps, budgets, seeds_stack,
                                n_valid_stack, key, tables,
                                with_edge: bool = False,
                                fused_plan=None):
  """T hetero sampling batches in ONE dispatch via lax.scan — the
  hetero counterpart of :func:`multihop_sample_many` (the sampling
  half of the hetero superstep; ops/superstep.py scans the full train
  body the same way). ``seeds_stack``: Dict[NodeType, [T, B_t]];
  ``n_valid_stack``: Dict[NodeType, [T]]. Iterations are independent
  (the fused path builds a fresh VMEM table per step; the table path's
  per-batch reset contract carries over), so results are identical to
  T separate :func:`multihop_sample_hetero` calls on the same key
  stream."""
  def step(carry, inp):
    tabs, k = carry
    seeds, n_valid = inp
    k, sub = jax.random.split(k)
    out, tabs = multihop_sample_hetero(
        one_hops, trav, num_neighbors, num_hops, caps, budgets, seeds,
        n_valid, sub, tabs, with_edge=with_edge, fused_plan=fused_plan)
    return (tabs, k), out

  (tables, _), outs = jax.lax.scan(step, (tables, key),
                                   (seeds_stack, n_valid_stack))
  return outs, tables


def multihop_sample_many(one_hop: OneHopFn,
                         seeds_stack: jax.Array,
                         n_valid_stack: jax.Array,
                         fanouts: Sequence[int],
                         key: jax.Array,
                         table: jax.Array,
                         scratch: jax.Array,
                         with_edge: bool = False,
                         fused_plan=None):
  """T sampling batches in ONE dispatch via lax.scan.

  seeds_stack: [T, B]; n_valid_stack: [T]. Returns (stacked out dicts
  [T, ...], table, scratch). Amortizes per-dispatch latency when host
  round-trips dominate (e.g. small batches over an interconnect-attached
  accelerator); the per-batch table reset keeps iterations independent,
  so results are identical to T separate multihop_sample calls.
  ``fused_plan`` routes each batch through the ``pallas_fused`` engine
  (fresh VMEM table per scan step — iterations stay independent).
  """
  def step(carry, inp):
    tab, scr, k = carry
    seeds, n_valid = inp
    k, sub = jax.random.split(k)
    out, tab, scr = multihop_sample(one_hop, seeds, n_valid, fanouts,
                                    sub, tab, scr, with_edge=with_edge,
                                    fused_plan=fused_plan)
    return (tab, scr, k), out

  (table, scratch, _), outs = jax.lax.scan(
      step, (table, scratch, key), (seeds_stack, n_valid_stack))
  return outs, table, scratch

"""Ordered dedup/relabel — the inducer's hash table, the TPU way.

The reference dedups frontier nodes with an open-addressing GPU hash table
(include/hash_table.cuh:27-84, atomicCAS insert + atomicMin first-occurrence
ordering) inside CUDAInducer (csrc/cuda/inducer.cu:33-133). TPUs have no
device atomics in that style, so we get identical semantics from sorts
(SURVEY.md §7 "Hard parts"): stable-sort by value, mark run heads, then
order runs by their first-occurrence position. All shapes static.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def ordered_unique(
    ids: jax.Array,
    valid: jax.Array,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
  """First-occurrence-ordered unique with inverse labels, static shapes.

  Args:
    ids: [M] integer ids.
    valid: [M] bool; invalid slots are ignored.
    capacity: static output size; must be >= the number of distinct valid
      ids (callers size it with the same Σ batch·Πfanouts bound the
      reference uses, neighbor_sampler.py:660-677).

  Returns:
    uniq: [capacity] distinct ids in order of first appearance, -1 padded.
    count: scalar int32 number of distinct ids.
    inverse: [M] int32, inverse[i] = position of ids[i] in uniq
      (first-occurrence order); -1 where ~valid.
  """
  m = ids.shape[0]
  big = jnp.iinfo(ids.dtype).max
  x = jnp.where(valid, ids, big)
  order = jnp.argsort(x, stable=True)                 # [M] value-sorted
  xs = jnp.take(x, order)
  head = jnp.concatenate(
      [jnp.ones((1,), bool), xs[1:] != xs[:-1]]) & (xs != big)
  # run index (value order) per sorted element; invalid tail inherits the
  # last run id but is masked out of `inverse` below.
  seg = jnp.cumsum(head) - 1                          # [M]
  # run heads carry the min original position (stable sort guarantees it)
  run_starts = jnp.nonzero(head, size=capacity, fill_value=m)[0]
  run_ok = run_starts < m
  safe = jnp.minimum(run_starts, m - 1)
  run_first_pos = jnp.where(run_ok, jnp.take(order, safe), m)
  run_vals = jnp.where(run_ok, jnp.take(xs, safe), big)
  # appearance order = ascending first position
  aorder = jnp.argsort(run_first_pos)
  uniq = jnp.take(run_vals, aorder)
  count = head.sum().astype(jnp.int32)
  # rank of each value-ordered run in appearance order
  rank = jnp.zeros((capacity,), jnp.int32).at[aorder].set(
      jnp.arange(capacity, dtype=jnp.int32))
  seg_at_orig = jnp.zeros((m,), jnp.int32).at[order].set(
      seg.astype(jnp.int32))
  inverse = jnp.take(rank, jnp.clip(seg_at_orig, 0, capacity - 1))
  inverse = jnp.where(valid, inverse, -1)
  uniq = jnp.where(jnp.arange(capacity) < count, uniq, -1)
  return uniq, count, inverse


class InducerState(NamedTuple):
  """Functional equivalent of the stateful CUDA/CPU Inducer
  (include/inducer_base.h:28-48): the growing list of unique nodes whose
  positions are the compact relabeled indices."""
  nodes: jax.Array   # [capacity] global ids, -1 padded
  count: jax.Array   # scalar int32


def init_node(seeds: jax.Array, seed_mask: jax.Array,
              capacity: int) -> Tuple[InducerState, jax.Array]:
  """Dedup seeds and open the node list (InducerBase::InitNode).

  Returns (state, seed_labels [S]) where seed_labels are each seed's
  compact index (-1 for masked seeds).
  """
  uniq, count, inv = ordered_unique(seeds, seed_mask, capacity)
  return InducerState(nodes=uniq, count=count), inv


def induce_next(
    state: InducerState,
    src_labels: jax.Array,   # [F] compact labels of the frontier
    nbrs: jax.Array,         # [F, K] sampled neighbor global ids
    nbr_mask: jax.Array,     # [F, K]
) -> Tuple[InducerState, jax.Array, jax.Array, jax.Array]:
  """Merge sampled neighbors into the node list (InducerBase::InduceNext).

  Returns (new_state, rows, cols, edge_mask):
    rows: [F*K] parent compact labels (src repeated per slot)
    cols: [F*K] child compact labels
    edge_mask: [F*K]
  Existing nodes keep their labels: the previous unique list is prepended
  before dedup, so its entries are the first occurrences by construction.
  """
  capacity = state.nodes.shape[0]
  f, k = nbrs.shape
  prev_valid = jnp.arange(capacity) < state.count
  cat_ids = jnp.concatenate([state.nodes, nbrs.reshape(-1)])
  cat_valid = jnp.concatenate([prev_valid, nbr_mask.reshape(-1)])
  uniq, count, inv = ordered_unique(cat_ids, cat_valid, capacity)
  cols = inv[capacity:]
  rows = jnp.repeat(src_labels, k)
  edge_mask = nbr_mask.reshape(-1) & (rows >= 0)
  return (InducerState(nodes=uniq, count=count), rows, cols, edge_mask)


# ---------------------------------------------------------------------------
# Dense-table inducer: the fast path.
#
# The sort-based path above is O((cap+M) log) per hop because it re-sorts the
# whole node list. When the graph's node count N is modest enough to afford
# two int32 tables in HBM (4+4 bytes/node — 19 MB for ogbn-products), the
# hash table the reference builds per batch (hash_table.cuh:27-84) is better
# expressed on TPU as a *dense* label table over node ids: dedup/relabel is
# then a handful of gathers/scatters + one cumsum per hop, no sorts at all.
# First-occurrence ordering (atomicMin in the reference) is recovered with a
# scatter-min of slot indices.
# ---------------------------------------------------------------------------

_BIG = jnp.iinfo(jnp.int32).max


class DenseInducerState(NamedTuple):
  """Functional state threaded through a batch; reset must run before the
  table is reused (``dense_reset``)."""
  table: jax.Array    # [N+1] int32, -1 = unseen; slot N is a write sink
  scratch: jax.Array  # [N+1] int32, _BIG when idle
  nodes: jax.Array    # [capacity+1] global ids; slot capacity is a sink
  count: jax.Array    # scalar int32


def dense_make_tables(num_nodes: int):
  """Allocate the persistent tables once per (device, graph)."""
  table = jnp.full((num_nodes + 1,), -1, jnp.int32)
  scratch = jnp.full((num_nodes + 1,), _BIG, jnp.int32)
  return table, scratch


def dense_init(table: jax.Array, scratch: jax.Array,
               capacity: int) -> DenseInducerState:
  nodes = jnp.full((capacity + 1,), -1, jnp.int32)
  return DenseInducerState(table=table, scratch=scratch, nodes=nodes,
                           count=jnp.zeros((), jnp.int32))


def dense_assign(state: DenseInducerState, ids: jax.Array,
                 valid: jax.Array):
  """Insert a flat batch of ids; returns (state', labels [M]).

  Labels are compact indices in global first-occurrence order (existing
  nodes keep theirs, new nodes get count..count+new-1 in slot order),
  exactly the reference inducer's insert semantics.
  """
  capacity = state.nodes.shape[0] - 1
  sink = state.table.shape[0] - 1
  m = ids.shape[0]
  ids = ids.astype(jnp.int32)
  safe = jnp.where(valid, ids, sink)
  existing = jnp.take(state.table, safe)                  # [M]
  is_new = valid & (existing < 0)
  slot = jnp.arange(m, dtype=jnp.int32)
  scratch = state.scratch.at[jnp.where(is_new, safe, sink)].min(
      jnp.where(is_new, slot, _BIG))
  winner = is_new & (jnp.take(scratch, safe) == slot)
  rank = jnp.cumsum(winner.astype(jnp.int32)) - winner    # exclusive
  new_label = state.count + rank
  table = state.table.at[jnp.where(winner, safe, sink)].set(
      jnp.where(winner, new_label, -1))
  labels = jnp.where(existing >= 0, existing, jnp.take(table, safe))
  labels = jnp.where(valid, labels, -1)
  nodes = state.nodes.at[jnp.where(winner, new_label, capacity)].set(ids)
  count = state.count + winner.sum(dtype=jnp.int32)
  # scratch returns to idle immediately
  scratch = scratch.at[safe].set(_BIG)
  return (DenseInducerState(table=table, scratch=scratch, nodes=nodes,
                            count=count), labels)


def dense_reset(state: DenseInducerState):
  """Un-mark every node touched this batch; returns (table, scratch) ready
  for the next batch (cost O(batch nodes), not O(N))."""
  capacity = state.nodes.shape[0] - 1
  sink = state.table.shape[0] - 1
  pos = jnp.arange(capacity + 1)
  tgt = jnp.where(pos < state.count, state.nodes, sink)
  table = state.table.at[tgt].set(-1)
  return table, state.scratch

"""Ordered dedup/relabel — the inducer's hash table, the TPU way.

The reference dedups frontier nodes with an open-addressing GPU hash table
(include/hash_table.cuh:27-84, atomicCAS insert + atomicMin first-occurrence
ordering) inside CUDAInducer (csrc/cuda/inducer.cu:33-133). TPUs have no
device atomics in that style, so we get identical semantics from sorts
(SURVEY.md §7 "Hard parts"): stable-sort by value, mark run heads, then
order runs by their first-occurrence position. All shapes static.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def ordered_unique(
    ids: jax.Array,
    valid: jax.Array,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
  """First-occurrence-ordered unique with inverse labels, static shapes.

  Args:
    ids: [M] integer ids.
    valid: [M] bool; invalid slots are ignored.
    capacity: static output size; must be >= the number of distinct valid
      ids (callers size it with the same Σ batch·Πfanouts bound the
      reference uses, neighbor_sampler.py:660-677).

  Returns:
    uniq: [capacity] distinct ids in order of first appearance, -1 padded.
    count: scalar int32 number of distinct ids.
    inverse: [M] int32, inverse[i] = position of ids[i] in uniq
      (first-occurrence order); -1 where ~valid.
  """
  m = ids.shape[0]
  big = jnp.iinfo(ids.dtype).max
  x = jnp.where(valid, ids, big)
  order = jnp.argsort(x, stable=True)                 # [M] value-sorted
  xs = jnp.take(x, order)
  head = jnp.concatenate(
      [jnp.ones((1,), bool), xs[1:] != xs[:-1]]) & (xs != big)
  # run index (value order) per sorted element; invalid tail inherits the
  # last run id but is masked out of `inverse` below.
  seg = jnp.cumsum(head) - 1                          # [M]
  # run heads carry the min original position (stable sort guarantees it)
  run_starts = jnp.nonzero(head, size=capacity, fill_value=m)[0]
  run_ok = run_starts < m
  safe = jnp.minimum(run_starts, m - 1)
  run_first_pos = jnp.where(run_ok, jnp.take(order, safe), m)
  run_vals = jnp.where(run_ok, jnp.take(xs, safe), big)
  # appearance order = ascending first position
  aorder = jnp.argsort(run_first_pos)
  uniq = jnp.take(run_vals, aorder)
  count = head.sum().astype(jnp.int32)
  # rank of each value-ordered run in appearance order
  rank = jnp.zeros((capacity,), jnp.int32).at[aorder].set(
      jnp.arange(capacity, dtype=jnp.int32))
  seg_at_orig = jnp.zeros((m,), jnp.int32).at[order].set(
      seg.astype(jnp.int32))
  inverse = jnp.take(rank, jnp.clip(seg_at_orig, 0, capacity - 1))
  inverse = jnp.where(valid, inverse, -1)
  uniq = jnp.where(jnp.arange(capacity) < count, uniq, -1)
  return uniq, count, inverse


class InducerState(NamedTuple):
  """Functional equivalent of the stateful CUDA/CPU Inducer
  (include/inducer_base.h:28-48): the growing list of unique nodes whose
  positions are the compact relabeled indices."""
  nodes: jax.Array   # [capacity] global ids, -1 padded
  count: jax.Array   # scalar int32


def init_node(seeds: jax.Array, seed_mask: jax.Array,
              capacity: int) -> Tuple[InducerState, jax.Array]:
  """Dedup seeds and open the node list (InducerBase::InitNode).

  Returns (state, seed_labels [S]) where seed_labels are each seed's
  compact index (-1 for masked seeds).
  """
  uniq, count, inv = ordered_unique(seeds, seed_mask, capacity)
  return InducerState(nodes=uniq, count=count), inv


def induce_next(
    state: InducerState,
    src_labels: jax.Array,   # [F] compact labels of the frontier
    nbrs: jax.Array,         # [F, K] sampled neighbor global ids
    nbr_mask: jax.Array,     # [F, K]
) -> Tuple[InducerState, jax.Array, jax.Array, jax.Array]:
  """Merge sampled neighbors into the node list (InducerBase::InduceNext).

  Returns (new_state, rows, cols, edge_mask):
    rows: [F*K] parent compact labels (src repeated per slot)
    cols: [F*K] child compact labels
    edge_mask: [F*K]
  Existing nodes keep their labels: the previous unique list is prepended
  before dedup, so its entries are the first occurrences by construction.
  """
  capacity = state.nodes.shape[0]
  f, k = nbrs.shape
  prev_valid = jnp.arange(capacity) < state.count
  cat_ids = jnp.concatenate([state.nodes, nbrs.reshape(-1)])
  cat_valid = jnp.concatenate([prev_valid, nbr_mask.reshape(-1)])
  uniq, count, inv = ordered_unique(cat_ids, cat_valid, capacity)
  cols = inv[capacity:]
  rows = jnp.repeat(src_labels, k)
  edge_mask = nbr_mask.reshape(-1) & (rows >= 0)
  return (InducerState(nodes=uniq, count=count), rows, cols, edge_mask)


# ---------------------------------------------------------------------------
# Dense-table inducer: the fast path.
#
# The sort-based path above is O((cap+M) log) per hop because it re-sorts the
# whole node list. When the graph's node count N is modest enough to afford
# two int32 tables in HBM (4+4 bytes/node — 19 MB for ogbn-products), the
# hash table the reference builds per batch (hash_table.cuh:27-84) is better
# expressed on TPU as a *dense* label table over node ids: dedup/relabel is
# then a handful of gathers/scatters + one cumsum per hop, no sorts at all.
# First-occurrence ordering (atomicMin in the reference) is recovered with a
# scatter-min of slot indices.
# ---------------------------------------------------------------------------

_BIG = jnp.iinfo(jnp.int32).max


class DenseInducerState(NamedTuple):
  """Functional state threaded through a batch; reset must run before the
  table is reused (``dense_reset``)."""
  table: jax.Array    # [N+1] int32, -1 = unseen; slot N is a write sink
  scratch: jax.Array  # [N+1] int32, _BIG when idle
  nodes: jax.Array    # [capacity+1] global ids; slot capacity is a sink
  count: jax.Array    # scalar int32


def dense_make_tables(num_nodes: int):
  """Allocate the persistent tables once per (device, graph)."""
  table = jnp.full((num_nodes + 1,), -1, jnp.int32)
  scratch = jnp.full((num_nodes + 1,), _BIG, jnp.int32)
  return table, scratch


def dense_init(table: jax.Array, scratch: jax.Array,
               capacity: int) -> DenseInducerState:
  nodes = jnp.full((capacity + 1,), -1, jnp.int32)
  return DenseInducerState(table=table, scratch=scratch, nodes=nodes,
                           count=jnp.zeros((), jnp.int32))


def dense_assign(state: DenseInducerState, ids: jax.Array,
                 valid: jax.Array):
  """Insert a flat batch of ids; returns (state', labels [M]).

  Labels are compact indices in global first-occurrence order (existing
  nodes keep theirs, new nodes get count..count+new-1 in slot order),
  exactly the reference inducer's insert semantics.
  """
  capacity = state.nodes.shape[0] - 1
  sink = state.table.shape[0] - 1
  m = ids.shape[0]
  ids = ids.astype(jnp.int32)
  safe = jnp.where(valid, ids, sink)
  existing = jnp.take(state.table, safe)                  # [M]
  is_new = valid & (existing < 0)
  slot = jnp.arange(m, dtype=jnp.int32)
  scratch = state.scratch.at[jnp.where(is_new, safe, sink)].min(
      jnp.where(is_new, slot, _BIG))
  winner = is_new & (jnp.take(scratch, safe) == slot)
  rank = jnp.cumsum(winner.astype(jnp.int32)) - winner    # exclusive
  new_label = state.count + rank
  table = state.table.at[jnp.where(winner, safe, sink)].set(
      jnp.where(winner, new_label, -1))
  labels = jnp.where(existing >= 0, existing, jnp.take(table, safe))
  labels = jnp.where(valid, labels, -1)
  nodes = state.nodes.at[jnp.where(winner, new_label, capacity)].set(ids)
  count = state.count + winner.sum(dtype=jnp.int32)
  # scratch returns to idle immediately
  scratch = scratch.at[safe].set(_BIG)
  return (DenseInducerState(table=table, scratch=scratch, nodes=nodes,
                            count=count), labels)


# ---------------------------------------------------------------------------
# Sort-merge inducer: the TPU fast path.
#
# Hardware measurement (benchmarks/microbench_prims.py, v5e): every random
# access XLA:TPU emits — gather or scatter, any operand size — costs
# ~7-16ns per OUTPUT ELEMENT, serialized; `lax.sort` by contrast runs
# vectorized at ~3-4ns/element and multi-operand sorts carry payloads for
# free. The dense-table inducer above spends ~7 random accesses per slot;
# this engine spends ZERO — dedup/relabel/frontier-compaction are all
# expressed as multi-operand sorts over the batch plus prefix scans, the
# same trick as the reference's sort-free GPU hash table but inverted for
# a machine whose fast primitive is the sort, not the atomic.
# ---------------------------------------------------------------------------


def _fill_forward(hd: jax.Array, *vals: jax.Array):
  """Segmented fill-forward: out_k[i] = vals_k at the most recent j<=i
  with hd[j]. Log-depth associative scan — no gathers, no scatters."""
  def comb(a, b):
    ah = a[0]
    bh = b[0]
    return (ah | bh,) + tuple(
        jnp.where(bh, bv, av) for av, bv in zip(a[1:], b[1:]))
  return jax.lax.associative_scan(comb, (hd,) + vals)[1:]


def sorted_hop_dedup(
    u_ids: jax.Array,    # [C] seen-set ids (any order, _BIG padding ok)
    u_labs: jax.Array,   # [C] their labels
    count: jax.Array,    # scalar int32: labels assigned so far
    ids: jax.Array,      # [M] sampled ids for this hop (dups allowed)
    valid: jax.Array,    # [M]
    rows: Optional[jax.Array] = None,  # [M] parent labels, carried along
    eids: Optional[jax.Array] = None,  # [M] edge ids, carried if given
    with_mask: bool = False,           # carry the validity per element
):
  """One hop of dedup/relabel with ZERO random-memory ops — two
  multi-operand sorts plus prefix scans.

  Labels are exact reference-inducer semantics: previously seen ids keep
  their labels; new ids get ``count..count+n-1`` in first-occurrence
  (slot) order. The returned per-element arrays are in a PERMUTED order
  (appearance-grouped), not slot order — every array below is aligned to
  the same permutation, so edge tuples stay consistent; within-hop edge
  order is unspecified (hop blocks themselves stay separate).

  ``rows``/``eids``/``with_mask`` add payload operands to both sorts —
  callers that rebuild edge buffers in slot order (the hetero loop)
  omit them to keep the sorts narrow.

  Returns a dict with:
    ids3 / labels3 : [M] aligned per-element
    rows3 / mask3 / eids3 : [M] iff the matching payload was requested
    new_head3 : [M] True at the first occurrence of each new id
    pos3      : [M] original slot index of each element
    u_ids2 / u_labs2 : [C+M] updated seen-set (append-form, not sorted)
    count2 : scalar, new_count : scalar
  """
  c = u_ids.shape[0]
  m = ids.shape[0]
  big = _BIG
  x = jnp.where(valid, ids.astype(jnp.int32), big)
  cat_id = jnp.concatenate([u_ids, x])
  cat_pos = jnp.concatenate([jnp.full((c,), -1, jnp.int32),
                             jnp.arange(m, dtype=jnp.int32)])
  cat_lab = jnp.concatenate([u_labs, jnp.full((m,), -1, jnp.int32)])
  ops = [cat_id, cat_pos, cat_lab]
  pay = []  # (name, array) payloads threaded through both sorts
  if rows is not None:
    pay.append(('rows3', jnp.concatenate(
        [jnp.full((c,), -1, jnp.int32), rows.astype(jnp.int32)])))
  if with_mask:
    pay.append(('mask3', jnp.concatenate(
        [jnp.zeros((c,), jnp.int32), valid.astype(jnp.int32)])))
  if eids is not None:
    pay.append(('eids3', jnp.concatenate(
        [jnp.full((c,), -1, eids.dtype), eids])))
  # sort 1: (id, pos) — a seen-set entry (pos -1) heads its id-run
  s = jax.lax.sort(ops + [p for _, p in pay], num_keys=2)
  sid, spos, slab = s[:3]
  spay = s[3:]

  hd = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
  hd = hd & (sid != big)
  head_slab, head_spos = _fill_forward(hd, slab, spos)
  is_new_run = (head_slab < 0) & (sid != big)    # run headed by a slot
  u_lab = jnp.where(is_new_run | (sid == big), -1, head_slab)

  # sort 2: (group key, pos). New runs group under their head's slot
  # position (= appearance order); seen/invalid slots key by their own
  # position; original seen-set entries are pushed to the back. All M
  # slot elements therefore land in [:M].
  is_slot = spos >= 0
  gkey = jnp.where(is_slot, jnp.where(is_new_run, head_spos, spos), big)
  ops2 = [gkey, spos, sid, u_lab, is_new_run.astype(jnp.int32)]
  s2 = jax.lax.sort(ops2 + list(spay), num_keys=2)
  gkey2, pos3, ids3, ulab3, new3 = (a[:m] for a in s2[:5])
  out_pay = {name: s2[5 + i][:m] for i, (name, _) in enumerate(pay)}
  if 'mask3' in out_pay:
    out_pay['mask3'] = out_pay['mask3'].astype(bool)
  new3 = new3.astype(bool)

  # the first element of each new group is its head (pos == group key);
  # inclusive prefix count over appearance-ordered groups = label rank
  new_head3 = new3 & (pos3 == gkey2)
  from .scan import cumsum_i32
  rank = cumsum_i32(new_head3.astype(jnp.int32))
  labels3 = jnp.where(new3, count + rank - 1, ulab3)

  new_count = rank[-1] if m > 0 else jnp.zeros((), jnp.int32)
  # seen-set append: each new id exactly once (at its head element)
  u_ids2 = jnp.concatenate([u_ids, jnp.where(new_head3, ids3, big)])
  u_labs2 = jnp.concatenate([u_labs, jnp.where(new_head3, labels3,
                                               big)])
  return dict(ids3=ids3, labels3=labels3, new_head3=new_head3,
              pos3=pos3, u_ids2=u_ids2, u_labs2=u_labs2,
              count2=count + new_count, new_count=new_count, **out_pay)


def sorted_hop_dedup_fused(
    u_ids: jax.Array,    # [C] seen-set ids (append-form, _BIG padding)
    u_labs: jax.Array,   # [C] their labels (_BIG at padding)
    count: jax.Array,    # scalar int32: labels assigned so far
    ids: jax.Array,      # [M] sampled ids for this hop (dups allowed)
    valid: jax.Array,    # [M]
):
  """One hop of dedup/relabel with ONE 3-operand sort — the fused
  sample+assign stage (GLT_FUSED_HOP).

  The committed TPU trace (benchmarks/tpu_runs/profile_sampler_tpu.json)
  puts the hop-2 assign at 41.1 ms against 15.3 ms of sampling: the
  dedup stage is the profiled bottleneck the reference solves with one
  fused CUDA kernel (csrc/cuda/random_sampler.cu:59-109 samples and
  emits in a single launch). :func:`sorted_hop_dedup` pays TWO wide
  multi-operand sorts per hop (5-8 operands over [C+M]); this variant
  pays one narrow one, by relaxing one property nothing downstream
  relies on: NEW ids get labels ``count..count+n-1`` in within-hop
  VALUE order instead of first-occurrence slot order. Seen ids keep
  their labels exactly; counts, masks, seed handling (callers keep the
  exact path for the seed hop) and the label<->node bijection are
  unchanged, so edges map to the same global-id multiset.

  How: sort (id, labkey, pos) with 2 keys — a seen entry's label is
  < _BIG so it heads its run and wins via a segmented fill-forward;
  new runs are ranked by one prefix scan; results return to SLOT order
  with a single packed scatter (labels + new-head bit in one int32),
  so every per-element output below is aligned to the caller's flat
  sample buffers and edge payloads never ride a sort at all.

  Returns dict with (all [M], slot order):
    labels3   : compact labels, -1 at ~valid
    new_head3 : True at exactly one slot per newly-seen id
    u_ids2 / u_labs2 : [C+M] updated append-form seen-set
    count2 / new_count : scalars
  """
  c = u_ids.shape[0]
  m = ids.shape[0]
  big = _BIG
  x = jnp.where(valid, ids.astype(jnp.int32), big)
  cat_id = jnp.concatenate([u_ids, x])
  cat_labkey = jnp.concatenate([u_labs, jnp.full((m,), big, jnp.int32)])
  cat_pos = jnp.concatenate([jnp.full((c,), -1, jnp.int32),
                             jnp.arange(m, dtype=jnp.int32)])
  sid, slabkey, spos = jax.lax.sort([cat_id, cat_labkey, cat_pos],
                                    num_keys=2)
  hd = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
  (run_lab,) = _fill_forward(hd, slabkey)
  ok = sid != big
  is_new = (run_lab == big) & ok
  new_head = hd & is_new
  from .scan import cumsum_i32
  rank = cumsum_i32(new_head.astype(jnp.int32))
  labels_all = jnp.where(is_new, count + rank - 1,
                         jnp.where(ok, run_lab, -1))
  # pack (label, new_head) into one int32: labels fit in 31 bits and
  # label == -1 implies new_head is False, so -1 packs to -2 (>> 1
  # recovers it; & 1 reads 0). One scatter instead of two.
  packed = labels_all * 2 + new_head.astype(jnp.int32)
  # slot elements carry pos >= 0 (a new run is headed by a slot
  # element); seen-set entries route to the sink row m
  buf = jnp.full((m + 1,), -2, jnp.int32).at[
      jnp.where(spos >= 0, spos, m)].set(
      jnp.where(spos >= 0, packed, -2))
  packed_slot = buf[:m]
  labels3 = packed_slot >> 1
  new_head3 = (packed_slot & 1) == 1
  new_count = rank[-1] if m + c > 0 else jnp.zeros((), jnp.int32)
  u_ids2 = jnp.concatenate([u_ids, jnp.where(new_head3, x, big)])
  u_labs2 = jnp.concatenate([u_labs, jnp.where(new_head3, labels3,
                                               big)])
  return dict(labels3=labels3, new_head3=new_head3,
              u_ids2=u_ids2, u_labs2=u_labs2,
              count2=count + new_count, new_count=new_count)


def sorted_nodes_by_label(u_ids: jax.Array, u_labs: jax.Array,
                          count: jax.Array, budget: int) -> jax.Array:
  """Materialize the dense node list (position = label) from the
  append-form seen-set with ONE sort by label; -1 padding past count."""
  lab_key = jnp.where(u_labs < 0, _BIG, u_labs)
  nodes = jax.lax.sort([lab_key, u_ids], num_keys=1)[1]
  nodes = nodes[:budget] if nodes.shape[0] >= budget else jnp.pad(
      nodes, (0, budget - nodes.shape[0]), constant_values=-1)
  return jnp.where(jnp.arange(budget) < count, nodes, -1)


def dense_reset(state: DenseInducerState):
  """Un-mark every node touched this batch; returns (table, scratch) ready
  for the next batch (cost O(batch nodes), not O(N))."""
  capacity = state.nodes.shape[0] - 1
  sink = state.table.shape[0] - 1
  pos = jnp.arange(capacity + 1)
  tgt = jnp.where(pos < state.count, state.nodes, sink)
  table = state.table.at[tgt].set(-1)
  return table, state.scratch

"""Ordered dedup/relabel — the inducer's hash table, the TPU way.

The reference dedups frontier nodes with an open-addressing GPU hash table
(include/hash_table.cuh:27-84, atomicCAS insert + atomicMin first-occurrence
ordering) inside CUDAInducer (csrc/cuda/inducer.cu:33-133). TPUs have no
device atomics in that style, so we get identical semantics from sorts
(SURVEY.md §7 "Hard parts"): stable-sort by value, mark run heads, then
order runs by their first-occurrence position. All shapes static.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def ordered_unique(
    ids: jax.Array,
    valid: jax.Array,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
  """First-occurrence-ordered unique with inverse labels, static shapes.

  Args:
    ids: [M] integer ids.
    valid: [M] bool; invalid slots are ignored.
    capacity: static output size; must be >= the number of distinct valid
      ids (callers size it with the same Σ batch·Πfanouts bound the
      reference uses, neighbor_sampler.py:660-677).

  Returns:
    uniq: [capacity] distinct ids in order of first appearance, -1 padded.
    count: scalar int32 number of distinct ids.
    inverse: [M] int32, inverse[i] = position of ids[i] in uniq
      (first-occurrence order); -1 where ~valid.
  """
  m = ids.shape[0]
  big = jnp.iinfo(ids.dtype).max
  x = jnp.where(valid, ids, big)
  order = jnp.argsort(x, stable=True)                 # [M] value-sorted
  xs = jnp.take(x, order)
  head = jnp.concatenate(
      [jnp.ones((1,), bool), xs[1:] != xs[:-1]]) & (xs != big)
  # run index (value order) per sorted element; invalid tail inherits the
  # last run id but is masked out of `inverse` below.
  seg = jnp.cumsum(head) - 1                          # [M]
  # run heads carry the min original position (stable sort guarantees it)
  run_starts = jnp.nonzero(head, size=capacity, fill_value=m)[0]
  run_ok = run_starts < m
  safe = jnp.minimum(run_starts, m - 1)
  run_first_pos = jnp.where(run_ok, jnp.take(order, safe), m)
  run_vals = jnp.where(run_ok, jnp.take(xs, safe), big)
  # appearance order = ascending first position
  aorder = jnp.argsort(run_first_pos)
  uniq = jnp.take(run_vals, aorder)
  count = head.sum().astype(jnp.int32)
  # rank of each value-ordered run in appearance order
  rank = jnp.zeros((capacity,), jnp.int32).at[aorder].set(
      jnp.arange(capacity, dtype=jnp.int32))
  seg_at_orig = jnp.zeros((m,), jnp.int32).at[order].set(
      seg.astype(jnp.int32))
  inverse = jnp.take(rank, jnp.clip(seg_at_orig, 0, capacity - 1))
  inverse = jnp.where(valid, inverse, -1)
  uniq = jnp.where(jnp.arange(capacity) < count, uniq, -1)
  return uniq, count, inverse


class InducerState(NamedTuple):
  """Functional equivalent of the stateful CUDA/CPU Inducer
  (include/inducer_base.h:28-48): the growing list of unique nodes whose
  positions are the compact relabeled indices."""
  nodes: jax.Array   # [capacity] global ids, -1 padded
  count: jax.Array   # scalar int32


def init_node(seeds: jax.Array, seed_mask: jax.Array,
              capacity: int) -> Tuple[InducerState, jax.Array]:
  """Dedup seeds and open the node list (InducerBase::InitNode).

  Returns (state, seed_labels [S]) where seed_labels are each seed's
  compact index (-1 for masked seeds).
  """
  uniq, count, inv = ordered_unique(seeds, seed_mask, capacity)
  return InducerState(nodes=uniq, count=count), inv


def induce_next(
    state: InducerState,
    src_labels: jax.Array,   # [F] compact labels of the frontier
    nbrs: jax.Array,         # [F, K] sampled neighbor global ids
    nbr_mask: jax.Array,     # [F, K]
) -> Tuple[InducerState, jax.Array, jax.Array, jax.Array]:
  """Merge sampled neighbors into the node list (InducerBase::InduceNext).

  Returns (new_state, rows, cols, edge_mask):
    rows: [F*K] parent compact labels (src repeated per slot)
    cols: [F*K] child compact labels
    edge_mask: [F*K]
  Existing nodes keep their labels: the previous unique list is prepended
  before dedup, so its entries are the first occurrences by construction.
  """
  capacity = state.nodes.shape[0]
  f, k = nbrs.shape
  prev_valid = jnp.arange(capacity) < state.count
  cat_ids = jnp.concatenate([state.nodes, nbrs.reshape(-1)])
  cat_valid = jnp.concatenate([prev_valid, nbr_mask.reshape(-1)])
  uniq, count, inv = ordered_unique(cat_ids, cat_valid, capacity)
  cols = inv[capacity:]
  rows = jnp.repeat(src_labels, k)
  edge_mask = nbr_mask.reshape(-1) & (rows >= 0)
  return (InducerState(nodes=uniq, count=count), rows, cols, edge_mask)

"""Pallas TPU kernels for the hot paths.

The XLA-native formulations in ops/ are the correctness baseline; these
kernels are drop-in accelerations, opt-in via ``GLT_USE_PALLAS=1`` until
profiled on hardware (the development environment's TPU tunnel was down
when they were written — interpret-mode parity tests gate correctness,
the flag gates deployment).

``gather_rows``: the feature-store row gather (UnifiedTensor's
GatherTensorKernel analogue, unified_tensor.cu:35-81). Uses the canonical
TPU embedding-gather pattern: row indices are scalar-prefetched so the
BlockSpec index_map can steer one row-block DMA per grid step, and the
Pallas pipeline double-buffers those HBM->VMEM copies behind the writes.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def pallas_available() -> bool:
  try:
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    return True
  except ImportError:
    return False


def use_pallas_default() -> bool:
  if os.environ.get('GLT_USE_PALLAS', '') not in ('1', 'true', 'True'):
    return False
  return (pallas_available()
          and jax.default_backend() == 'tpu')


def resolve_row_gather(override=None):
  """Gather-selection policy shared by every feature-serving path:
  an explicit override (tests inject the interpret-mode kernel) wins;
  otherwise the Pallas row-DMA gather when GLT_USE_PALLAS is on and the
  backend supports it; otherwise None (callers fall back to jnp.take)."""
  if override is not None:
    return override
  if use_pallas_default():
    return gather_rows
  return None


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def gather_windows(arr: jax.Array, starts: jax.Array, width: int,
                   block: int = 8, interpret: bool = False) -> jax.Array:
  """Contiguous-window gather: out[i] = arr[starts[i] : starts[i]+width].

  The windowed gathers of the sampling pipeline (weighted sampling and
  full-neighborhood expansion read a [S, max_degree] neighbor window per
  seed; the feature store reads [S, D] rows) lower on XLA:TPU to a
  serialized per-OUTPUT-element loop (~8-16 ns/element,
  benchmarks/microbench_prims.py) — ~0.8 us/row at width 96. Here each
  row is ONE async HBM->VMEM DMA descriptor instead; ``block`` rows'
  descriptors are in flight at once, so per-row cost is DMA-issue
  overhead + bytes/bandwidth, independent of width.

  CONTRACT (stricter than the XLA slice-gather): a window must lie
  fully inside the array — ``starts`` are clamped to
  [0, len(arr) - width], so a tail window with ``start > len - width``
  is SHIFTED left and returns wrong values in otherwise-valid lanes
  (XLA's per-element mode='clip' only corrupts lanes past the row's
  degree, which callers mask). Wire this into samplers only over a
  source array padded by ``width`` trailing elements; the microbench
  satisfies the precondition by drawing starts from [0, E - W].
  Callers mask invalid lanes themselves.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  e = arr.shape[0]
  s = starts.shape[0]
  assert e >= width, f'array ({e}) shorter than the window ({width})'
  starts = jnp.clip(starts.astype(jnp.int32), 0, e - width)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
  n_blocks = (s + pad) // block

  def kernel(starts_ref, arr_ref, out_ref, sems):
    i = pl.program_id(0)

    def start_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).start()
      return 0

    def wait_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).wait()
      return 0

    jax.lax.fori_loop(0, block, start_dma, 0)   # block DMAs in flight
    jax.lax.fori_loop(0, block, wait_dma, 0)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(n_blocks,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY)],   # stays in HBM
      out_specs=pl.BlockSpec((block, width), lambda i, idx: (i, 0)),
      scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((s + pad, width), arr.dtype),
      interpret=interpret,
  )(starts, arr)
  return out[:s]


@functools.partial(jax.jit, static_argnames=('interpret',))
def gather_rows(table: jax.Array, rows: jax.Array,
                interpret: bool = False) -> jax.Array:
  """table: [N, D]; rows: [B] int32 -> [B, D].

  Out-of-range rows are clamped (mode='clip' semantics of the XLA path).

  Lowering note (r5 hardware session): the original (1, D) block spec
  violated Mosaic's tiling rule (second-to-last block dim must be
  divisible by 8 or equal the array dim) and never compiled; the singleton middle
  dimension below satisfies it ("or equal": block (1, 1, D) vs array
  (N, 1, D)), and probe_pallas_compile.py rung 5 confirms this form
  compiles and runs on hardware. Measured there at 267 ns/row for
  (1, 128) blocks — grid-step overhead bound, SLOWER than XLA's row
  gather — so GLT_USE_PALLAS stays default-off; the kernel remains the
  scaffold for a multi-input steered variant if per-step overhead ever
  drops.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n, d = table.shape
  b = rows.shape[0]
  rows = jnp.clip(rows.astype(jnp.int32), 0, n - 1)
  table3 = table.reshape(n, 1, d)

  def kernel(idx_ref, row_ref, out_ref):
    out_ref[:] = row_ref[:]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b,),
      in_specs=[
          pl.BlockSpec((1, 1, d), lambda i, idx: (idx[i], 0, 0)),
      ],
      out_specs=pl.BlockSpec((1, 1, d), lambda i, idx: (i, 0, 0)),
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, 1, d), table.dtype),
      interpret=interpret,
  )(rows, table3)
  return out.reshape(b, d)

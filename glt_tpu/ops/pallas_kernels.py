"""Pallas TPU kernels for the hot paths.

The XLA-native formulations in ops/ are the correctness baseline; these
kernels are drop-in accelerations, opt-in via ``GLT_USE_PALLAS=1`` until
profiled on hardware (the development environment's TPU tunnel was down
when they were written — interpret-mode parity tests gate correctness,
the flag gates deployment).

``gather_rows``: the feature-store row gather (UnifiedTensor's
GatherTensorKernel analogue, unified_tensor.cu:35-81). Uses the canonical
TPU embedding-gather pattern: row indices are scalar-prefetched so the
BlockSpec index_map can steer one row-block DMA per grid step, and the
Pallas pipeline double-buffers those HBM->VMEM copies behind the writes.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def pallas_available() -> bool:
  try:
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    return True
  except ImportError:
    return False


def use_pallas_default() -> bool:
  if os.environ.get('GLT_USE_PALLAS', '') not in ('1', 'true', 'True'):
    return False
  return (pallas_available()
          and jax.default_backend() == 'tpu')


@functools.partial(jax.jit, static_argnames=('interpret',))
def gather_rows(table: jax.Array, rows: jax.Array,
                interpret: bool = False) -> jax.Array:
  """table: [N, D]; rows: [B] int32 -> [B, D].

  Out-of-range rows are clamped (mode='clip' semantics of the XLA path).
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n, d = table.shape
  b = rows.shape[0]
  rows = jnp.clip(rows.astype(jnp.int32), 0, n - 1)

  def kernel(idx_ref, row_ref, out_ref):
    out_ref[:] = row_ref[:]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b,),
      in_specs=[
          pl.BlockSpec((1, d), lambda i, idx: (idx[i], 0)),
      ],
      out_specs=pl.BlockSpec((1, d), lambda i, idx: (i, 0)),
  )
  return pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, d), table.dtype),
      interpret=interpret,
  )(rows, table)

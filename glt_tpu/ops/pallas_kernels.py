"""Pallas TPU kernels for the hot paths.

The XLA-native formulations in ops/ are the correctness baseline; these
kernels are drop-in accelerations, opt-in via ``GLT_USE_PALLAS=1`` until
profiled on hardware (the development environment's TPU tunnel was down
when they were written — interpret-mode parity tests gate correctness,
the flag gates deployment).

``gather_rows``: the feature-store row gather (UnifiedTensor's
GatherTensorKernel analogue, unified_tensor.cu:35-81). Uses the canonical
TPU embedding-gather pattern: row indices are scalar-prefetched so the
BlockSpec index_map can steer one row-block DMA per grid step, and the
Pallas pipeline double-buffers those HBM->VMEM copies behind the writes.

``sample_hop``: the one-hop sampling megakernel (the ``pallas`` hop
engine, ops/pipeline.py::hop_engine). Fuses the per-row CSR window read
and the fanout pick — the two stages GLT's CUDA samplers keep in one
kernel (random_sampler.cu:36-165) — so the [S, W] neighbor window never
round-trips through HBM: each frontier row's window is DMA'd HBM->VMEM
double-buffered across grid steps, the precomputed Floyd/replace
offsets pick inside VMEM, and hub rows (degree > W) are fixed up by a
per-element DMA tail pass folded into the same kernel.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def pallas_available() -> bool:
  try:
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    return True
  except ImportError:
    return False


def use_pallas_default() -> bool:
  if os.environ.get('GLT_USE_PALLAS', '') not in ('1', 'true', 'True'):
    return False
  return (pallas_available()
          and jax.default_backend() == 'tpu')


def interpret_default() -> bool:
  """Whether Pallas kernels must run in interpret mode on this backend:
  the kernels are Mosaic/TPU programs, so every non-TPU backend (the
  tier-1 CPU suite, the CI interpret job) executes them through the
  interpreter. On TPU, GLT_PALLAS_INTERPRET=1 forces interpretation for
  debugging."""
  if os.environ.get('GLT_PALLAS_INTERPRET', '') in ('1', 'true', 'True'):
    return True
  return jax.default_backend() != 'tpu'


def resolve_row_gather(override=None):
  """Gather-selection policy shared by every feature-serving path:
  an explicit override (tests inject the interpret-mode kernel) wins;
  otherwise the Pallas row-DMA gather when GLT_USE_PALLAS is on and the
  backend supports it; otherwise None (callers fall back to jnp.take)."""
  if override is not None:
    return override
  if use_pallas_default():
    return gather_rows
  return None


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def gather_windows(arr: jax.Array, starts: jax.Array, width: int,
                   block: int = 8, interpret: bool = False) -> jax.Array:
  """Contiguous-window gather: out[i] = arr[starts[i] : starts[i]+width].

  The windowed gathers of the sampling pipeline (weighted sampling and
  full-neighborhood expansion read a [S, max_degree] neighbor window per
  seed; the feature store reads [S, D] rows) lower on XLA:TPU to a
  serialized per-OUTPUT-element loop (~8-16 ns/element,
  benchmarks/microbench_prims.py) — ~0.8 us/row at width 96. Here each
  row is ONE async HBM->VMEM DMA descriptor instead; ``block`` rows'
  descriptors are in flight at once, so per-row cost is DMA-issue
  overhead + bytes/bandwidth, independent of width.

  CONTRACT (stricter than the XLA slice-gather): a window must lie
  fully inside the array — ``starts`` are clamped to
  [0, len(arr) - width], so a tail window with ``start > len - width``
  is SHIFTED left and returns wrong values in otherwise-valid lanes
  (XLA's per-element mode='clip' only corrupts lanes past the row's
  degree, which callers mask). Wire this into samplers only over a
  source array padded by ``width`` trailing elements; the microbench
  satisfies the precondition by drawing starts from [0, E - W].
  Callers mask invalid lanes themselves.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  e = arr.shape[0]
  s = starts.shape[0]
  assert e >= width, f'array ({e}) shorter than the window ({width})'
  starts = jnp.clip(starts.astype(jnp.int32), 0, e - width)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
  n_blocks = (s + pad) // block

  def kernel(starts_ref, arr_ref, out_ref, sems):
    i = pl.program_id(0)

    def start_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).start()
      return 0

    def wait_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).wait()
      return 0

    jax.lax.fori_loop(0, block, start_dma, 0)   # block DMAs in flight
    jax.lax.fori_loop(0, block, wait_dma, 0)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(n_blocks,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY)],   # stays in HBM
      out_specs=pl.BlockSpec((block, width), lambda i, idx: (i, 0)),
      scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((s + pad, width), arr.dtype),
      interpret=interpret,
  )(starts, arr)
  return out[:s]


@functools.partial(jax.jit, static_argnames=('interpret',))
def gather_rows(table: jax.Array, rows: jax.Array,
                interpret: bool = False) -> jax.Array:
  """table: [N, D]; rows: [B] int32 -> [B, D].

  Out-of-range rows are clamped (mode='clip' semantics of the XLA path).

  Lowering note (r5 hardware session): the original (1, D) block spec
  violated Mosaic's tiling rule (second-to-last block dim must be
  divisible by 8 or equal the array dim) and never compiled; the singleton middle
  dimension below satisfies it ("or equal": block (1, 1, D) vs array
  (N, 1, D)), and probe_pallas_compile.py rung 5 confirms this form
  compiles and runs on hardware. Measured there at 267 ns/row for
  (1, 128) blocks — grid-step overhead bound, SLOWER than XLA's row
  gather — so GLT_USE_PALLAS stays default-off; the kernel remains the
  scaffold for a multi-input steered variant if per-step overhead ever
  drops.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n, d = table.shape
  b = rows.shape[0]
  rows = jnp.clip(rows.astype(jnp.int32), 0, n - 1)
  table3 = table.reshape(n, 1, d)

  def kernel(idx_ref, row_ref, out_ref):
    out_ref[:] = row_ref[:]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b,),
      in_specs=[
          pl.BlockSpec((1, 1, d), lambda i, idx: (idx[i], 0, 0)),
      ],
      out_specs=pl.BlockSpec((1, 1, d), lambda i, idx: (i, 0, 0)),
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, 1, d), table.dtype),
      interpret=interpret,
  )(rows, table3)
  return out.reshape(b, d)


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def sample_hop(arr_win: jax.Array,
               eids_win: 'Optional[jax.Array]',
               starts: jax.Array,
               offsets: jax.Array,
               hub_rows: jax.Array,
               hub_slots: jax.Array,
               width: int,
               block: int = 8,
               interpret: bool = False):
  """One-hop sampling megakernel: window DMA + offset pick + hub tail.

  For each frontier row ``i``, DMAs the ``width``-wide CSR window
  ``arr_win[starts[i] : starts[i]+width]`` HBM->VMEM (double-buffered
  across grid steps, ``block`` rows' descriptors in flight per slot),
  applies the precomputed sampling ``offsets`` inside VMEM, and emits
  the packed ``[S, K]`` neighbor picks — the ``[S, width]`` window never
  materializes in HBM. Rows listed in ``hub_rows`` (degree > width, so
  their offsets can exceed the window) are fixed up by a per-element DMA
  tail pass in the SAME kernel: ``hub_slots`` holds their exact edge
  slots, and the combine overwrites only those rows.

  Args:
    arr_win: [E + width] edge array padded per the ``gather_windows``
      contract — every real row window lies fully inside it, so
      ``starts`` need no clamping.
    eids_win: optional second edge array (edge ids) read through the
      same windows/offsets; pass None to skip the second output.
    starts: [S] int32 per-row window starts (CSR row offsets).
    offsets: [S, K] int32 within-row sampling offsets, as drawn by the
      element path (unclamped; the kernel clips to the window for the
      main pass — hub rows get exact values from the tail pass).
    hub_rows: [H] int32 frontier row indices needing exact fix-up; -1
      marks unused capacity. H is a static cap. Every grid step scans
      the whole list for rows in its block (O(grid * H) scalar
      compares), so H must stay small relative to S — pick W so hubs
      are rare (callers clamp H to the frontier size, and the degree
      distribution bounds it); a sorted-hub-list + per-block-offset
      variant is the follow-up if a hardware A/B ever shows the scan.
    hub_slots: [H, K] int32 exact edge slots for the hub rows (already
      clipped to the real edge range by the caller).

  Returns ``picks`` [S, K] (and ``eid_picks`` [S, K] when ``eids_win``
  is given, else None) with the same dtype(s) as the source arrays.
  Rows beyond the hub cap fall back to window-clipped picks — identical
  confinement to the XLA window path (ops/sample.py docstring).
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  s = starts.shape[0]
  fanout = offsets.shape[1]
  n_hub = hub_rows.shape[0]
  with_eids = eids_win is not None
  if s == 0:
    empty = jnp.zeros((0, fanout), arr_win.dtype)
    return empty, (jnp.zeros((0, fanout), eids_win.dtype)
                   if with_eids else None)
  starts = starts.astype(jnp.int32)
  offsets = offsets.astype(jnp.int32)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
    offsets = jnp.pad(offsets, ((0, pad), (0, 0)))
  n_blocks = (s + pad) // block
  # per-row fix-up flag, derived from the SAME hub list the tail pass
  # walks — a row is only flagged if a tail DMA will actually fill it
  # (hub rows past the H cap keep their window picks, the documented
  # confinement of an undersized cap)
  valid_hub = (hub_rows >= 0).astype(jnp.int32)
  hub_flag = jnp.zeros((s + pad, 1), jnp.int32).at[
      jnp.clip(hub_rows, 0, s + pad - 1), 0].max(valid_hub)
  hub_rows = jnp.where(valid_hub > 0, hub_rows, -1).astype(jnp.int32)
  hub_slots = hub_slots.astype(jnp.int32)

  arrs = (arr_win, eids_win) if with_eids else (arr_win,)

  def kernel(starts_ref, hub_rows_ref, hub_slots_ref, offsets_ref,
             flag_ref, *rest):
    src_refs = rest[:len(arrs)]
    out_refs = rest[len(arrs):2 * len(arrs)]
    win_bufs = rest[2 * len(arrs):3 * len(arrs)]
    hub_bufs = rest[3 * len(arrs):4 * len(arrs)]
    sems = rest[4 * len(arrs):5 * len(arrs)]
    hub_sems = rest[5 * len(arrs):6 * len(arrs)]
    i = pl.program_id(0)

    def window_dma(a, slot, row, j):
      st = starts_ref[row]
      return pltpu.make_async_copy(src_refs[a].at[pl.ds(st, width)],
                                   win_bufs[a].at[slot, j],
                                   sems[a].at[slot, j])

    def issue(slot, blk):
      for j in range(block):
        for a in range(len(arrs)):
          window_dma(a, slot, blk * block + j, j).start()

    cur = jax.lax.rem(i, 2)
    nxt = jax.lax.rem(i + 1, 2)

    @pl.when(i == 0)
    def _():
      issue(cur, 0)                 # cold start: first block's windows

    @pl.when(i + 1 < n_blocks)
    def _():
      issue(nxt, i + 1)             # double-buffer: next block in flight

    for j in range(block):
      for a in range(len(arrs)):
        window_dma(a, cur, i * block + j, j).wait()

    # hub tail pass: exact per-element reads for rows whose degree
    # exceeds the window, folded into the owning block's grid step
    def hub_issue(h, _):
      row = hub_rows_ref[h]
      in_block = (row >= i * block) & (row < (i + 1) * block)

      @pl.when(in_block)
      def _():
        j = row - i * block
        for k in range(fanout):
          sl = hub_slots_ref[h, k]
          for a in range(len(arrs)):
            pltpu.make_async_copy(src_refs[a].at[pl.ds(sl, 1)],
                                  hub_bufs[a].at[j, pl.ds(k, 1)],
                                  hub_sems[a].at[j, k]).start()
        for k in range(fanout):
          sl = hub_slots_ref[h, k]
          for a in range(len(arrs)):
            pltpu.make_async_copy(src_refs[a].at[pl.ds(sl, 1)],
                                  hub_bufs[a].at[j, pl.ds(k, 1)],
                                  hub_sems[a].at[j, k]).wait()
      return 0

    jax.lax.fori_loop(0, n_hub, hub_issue, 0)

    woff = jnp.minimum(offsets_ref[...], width - 1)      # [block, K]
    iota = jax.lax.broadcasted_iota(jnp.int32, (block, fanout, width), 2)
    onehot = iota == woff[:, :, None]
    is_hub = flag_ref[...] != 0                          # [block, 1]
    for a in range(len(arrs)):
      win = win_bufs[a][cur]                             # [block, W]
      zero = jnp.zeros((), win.dtype)
      picks = jnp.sum(jnp.where(onehot, win[:, None, :], zero), axis=-1)
      out_refs[a][...] = jnp.where(is_hub, hub_bufs[a][...], picks)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=3,
      grid=(n_blocks,),
      in_specs=(
          [pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
           pl.BlockSpec((block, 1), lambda i, *_: (i, 0))]
          + [pl.BlockSpec(memory_space=pl.ANY)] * len(arrs)),
      out_specs=[pl.BlockSpec((block, fanout), lambda i, *_: (i, 0))
                 for _ in arrs],
      scratch_shapes=(
          [pltpu.VMEM((2, block, width), a.dtype) for a in arrs]
          + [pltpu.VMEM((block, fanout), a.dtype) for a in arrs]
          + [pltpu.SemaphoreType.DMA((2, block)) for _ in arrs]
          + [pltpu.SemaphoreType.DMA((block, fanout)) for _ in arrs]),
  )
  outs = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=[jax.ShapeDtypeStruct((s + pad, fanout), a.dtype)
                 for a in arrs],
      interpret=interpret,
  )(starts, hub_rows, hub_slots, offsets, hub_flag, *arrs)
  picks = outs[0][:s]
  return picks, (outs[1][:s] if with_eids else None)

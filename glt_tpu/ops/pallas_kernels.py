"""Pallas TPU kernels for the hot paths.

The XLA-native formulations in ops/ are the correctness baseline; these
kernels are drop-in accelerations, opt-in via ``GLT_USE_PALLAS=1`` until
profiled on hardware (the development environment's TPU tunnel was down
when they were written — interpret-mode parity tests gate correctness,
the flag gates deployment).

``gather_rows``: the feature-store row gather (UnifiedTensor's
GatherTensorKernel analogue, unified_tensor.cu:35-81). Uses the canonical
TPU embedding-gather pattern: row indices are scalar-prefetched so the
BlockSpec index_map can steer one row-block DMA per grid step, and the
Pallas pipeline double-buffers those HBM->VMEM copies behind the writes.

``sample_hop``: the one-hop sampling megakernel (the ``pallas`` hop
engine, ops/pipeline.py::hop_engine). Fuses the per-row CSR window read
and the fanout pick — the two stages GLT's CUDA samplers keep in one
kernel (random_sampler.cu:36-165) — so the [S, W] neighbor window never
round-trips through HBM: each frontier row's window is DMA'd HBM->VMEM
double-buffered across grid steps, the precomputed Floyd/replace
offsets pick inside VMEM, and hub rows (degree > W) are fixed up by a
per-element DMA tail pass folded into the same kernel.

``sample_hop_dedup`` + ``dedup_table_insert``: the ``pallas_fused``
kernel family. Extends ``sample_hop`` with the per-hop dedup stage run
against a VMEM-resident open-addressing table (bucketized, 128 ids per
bucket row so probes are vector compares), so the picked indices never
leave VMEM between the sample and the assign: each grid step DMAs its
CSR windows, picks in VMEM, and immediately probes/inserts the picks
into the table, emitting provisional first-occurrence labels. The
host-side wrapper (ops/sample.py::sample_neighbors_fused) converts
those to the exact ``sorted_hop_dedup_fused`` label contract (new ids
labeled in within-hop VALUE order) with ONE narrow single-operand sort
over the fresh unique ids — strictly narrower than the 3-operand
[C+M]-wide sort the ``sort+fused`` engine pays per hop.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.env import knob

#: trace-time kernel-launch accounting: every pallas_call built by this
#: module bumps the counter ONCE PER TRACE (executions never touch it).
#: ``kernel_launch_count()`` deltas around an AOT lower therefore equal
#: the number of kernel entries in the lowered program — the
#: interpret-mode fallback for bench.py's ``kernel_launches_per_dispatch``
#: (on TPU the lowered HLO's custom-call count is the ground truth; in
#: interpret mode kernels inline into plain HLO and leave no custom
#: call to count).
_LAUNCHES = {'n': 0}


def kernel_launch_count() -> int:
  """Cumulative pallas_call constructions traced by this process.
  CAVEAT: the bump lives in the jitted wrappers' Python bodies, so an
  inner jit-cache hit (same kernel, same avals, traced earlier) does
  NOT re-count — take deltas against a cold cache (jax.clear_caches())
  or around the FIRST lower of a given shape signature (what bench.py
  and instrument_compiled do)."""
  return _LAUNCHES['n']


def _count_launch() -> None:
  _LAUNCHES['n'] += 1


def pallas_available() -> bool:
  try:
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    return True
  except ImportError:
    return False


#: memoized auto-probe verdict (None = not yet probed)
_AUTO_PROBE = {'ok': None}


def auto_probe_ok() -> bool:
  """One-time compile probe gating the backend-aware ``auto`` hop
  engine (ops/pipeline.py::hop_engine): the fused kernels have never
  run on real TPU hardware (the dev tunnel has been down since r2), so
  ``auto`` must not put an unproven Mosaic program on every sampler in
  the fleet on the strength of interpret-mode tests alone. This
  compiles the per-hop AND cross-hop kernels at toy shapes on the
  actual backend once per process; any failure demotes ``auto`` to the
  XLA ``element`` engine with a counted fallback instead of breaking
  sampling. Explicit ``GLT_HOP_ENGINE=pallas_fused`` trusts the
  operator and skips the probe."""
  if _AUTO_PROBE['ok'] is not None:
    return _AUTO_PROBE['ok']
  try:
    interp = interpret_default()
    iw = jnp.concatenate([jnp.arange(64, dtype=jnp.int32),
                          jnp.full((8,), -1, jnp.int32)])
    ipad = jnp.concatenate(
        [jnp.arange(0, 66, 8, dtype=jnp.int32)[:9],
         jnp.full((1,), 64, jnp.int32)])
    starts = jnp.zeros((8,), jnp.int32)
    offsets = jnp.zeros((8, 2), jnp.int32)
    valid = jnp.ones((8, 2), jnp.int32)
    hub_rows = jnp.full((1,), -1, jnp.int32)
    hub_slots = jnp.zeros((1, 2), jnp.int32)
    tab_ids, tab_labs = make_dedup_table(8 * TABLE_LANES)
    count = jnp.zeros((), jnp.int32)
    sample_hop_dedup.lower(
        iw, None, starts, offsets, valid, hub_rows, hub_slots,
        tab_ids, tab_labs, count, width=8,
        interpret=interp).compile()
    u = (jnp.zeros((8, 2), jnp.float32),)
    sample_walk_dedup.lower(
        iw, None, ipad, jnp.zeros((8,), jnp.int32),
        jnp.ones((8,), jnp.int32), jnp.zeros((8,), jnp.int32),
        jnp.zeros((8,), jnp.int32), jnp.zeros((), jnp.int32), u,
        fanouts=(2,), width=8, num_nodes=8, num_edges=64,
        table_slots=8 * TABLE_LANES, batch_size=8,
        interpret=interp).compile()
    _AUTO_PROBE['ok'] = True
  except Exception as e:  # Mosaic/lowering failure: demote, don't break
    import logging
    logging.getLogger(__name__).warning(
        'pallas auto-probe failed (%s); GLT_HOP_ENGINE=auto stays on '
        'the XLA element engine for this process', e)
    _AUTO_PROBE['ok'] = False
  return _AUTO_PROBE['ok']


def use_pallas_default() -> bool:
  if not knob('GLT_USE_PALLAS', False):
    return False
  return (pallas_available()
          and jax.default_backend() == 'tpu')


def interpret_default() -> bool:
  """Whether Pallas kernels must run in interpret mode on this backend:
  the kernels are Mosaic/TPU programs, so every non-TPU backend (the
  tier-1 CPU suite, the CI interpret job) executes them through the
  interpreter. On TPU, GLT_PALLAS_INTERPRET=1 forces interpretation for
  debugging."""
  if knob('GLT_PALLAS_INTERPRET', False):
    return True
  return jax.default_backend() != 'tpu'


def resolve_row_gather(override=None):
  """Gather-selection policy shared by every feature-serving path:
  an explicit override (tests inject the interpret-mode kernel) wins;
  otherwise the Pallas row-DMA gather when GLT_USE_PALLAS is on and the
  backend supports it; otherwise None (callers fall back to jnp.take)."""
  if override is not None:
    return override
  if use_pallas_default():
    return gather_rows
  return None


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def gather_windows(arr: jax.Array, starts: jax.Array, width: int,
                   block: int = 8, interpret: bool = False) -> jax.Array:
  """Contiguous-window gather: out[i] = arr[starts[i] : starts[i]+width].

  The windowed gathers of the sampling pipeline (weighted sampling and
  full-neighborhood expansion read a [S, max_degree] neighbor window per
  seed; the feature store reads [S, D] rows) lower on XLA:TPU to a
  serialized per-OUTPUT-element loop (~8-16 ns/element,
  benchmarks/microbench_prims.py) — ~0.8 us/row at width 96. Here each
  row is ONE async HBM->VMEM DMA descriptor instead; ``block`` rows'
  descriptors are in flight at once, so per-row cost is DMA-issue
  overhead + bytes/bandwidth, independent of width.

  CONTRACT (stricter than the XLA slice-gather): a window must lie
  fully inside the array — ``starts`` are clamped to
  [0, len(arr) - width], so a tail window with ``start > len - width``
  is SHIFTED left and returns wrong values in otherwise-valid lanes
  (XLA's per-element mode='clip' only corrupts lanes past the row's
  degree, which callers mask). Wire this into samplers only over a
  source array padded by ``width`` trailing elements; the microbench
  satisfies the precondition by drawing starts from [0, E - W].
  Callers mask invalid lanes themselves.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  e = arr.shape[0]
  s = starts.shape[0]
  assert e >= width, f'array ({e}) shorter than the window ({width})'
  starts = jnp.clip(starts.astype(jnp.int32), 0, e - width)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
  n_blocks = (s + pad) // block

  def kernel(starts_ref, arr_ref, out_ref, sems):
    i = pl.program_id(0)

    def start_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).start()
      return 0

    def wait_dma(j, _):
      st = starts_ref[i * block + j]
      pltpu.make_async_copy(arr_ref.at[pl.ds(st, width)],
                            out_ref.at[j], sems.at[j]).wait()
      return 0

    jax.lax.fori_loop(0, block, start_dma, 0)   # block DMAs in flight
    jax.lax.fori_loop(0, block, wait_dma, 0)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(n_blocks,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY)],   # stays in HBM
      out_specs=pl.BlockSpec((block, width), lambda i, idx: (i, 0)),
      scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
  )
  _count_launch()
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((s + pad, width), arr.dtype),
      interpret=interpret,
  )(starts, arr)
  return out[:s]


@functools.partial(jax.jit, static_argnames=('interpret',))
def gather_rows(table: jax.Array, rows: jax.Array,
                interpret: bool = False) -> jax.Array:
  """table: [N, D]; rows: [B] int32 -> [B, D].

  Out-of-range rows are clamped (mode='clip' semantics of the XLA path).

  Lowering note (r5 hardware session): the original (1, D) block spec
  violated Mosaic's tiling rule (second-to-last block dim must be
  divisible by 8 or equal the array dim) and never compiled; the singleton middle
  dimension below satisfies it ("or equal": block (1, 1, D) vs array
  (N, 1, D)), and probe_pallas_compile.py rung 5 confirms this form
  compiles and runs on hardware. Measured there at 267 ns/row for
  (1, 128) blocks — grid-step overhead bound, SLOWER than XLA's row
  gather — so GLT_USE_PALLAS stays default-off; the kernel remains the
  scaffold for a multi-input steered variant if per-step overhead ever
  drops.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n, d = table.shape
  b = rows.shape[0]
  rows = jnp.clip(rows.astype(jnp.int32), 0, n - 1)
  table3 = table.reshape(n, 1, d)

  def kernel(idx_ref, row_ref, out_ref):
    out_ref[:] = row_ref[:]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(b,),
      in_specs=[
          pl.BlockSpec((1, 1, d), lambda i, idx: (idx[i], 0, 0)),
      ],
      out_specs=pl.BlockSpec((1, 1, d), lambda i, idx: (i, 0, 0)),
  )
  _count_launch()
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((b, 1, d), table.dtype),
      interpret=interpret,
  )(rows, table3)
  return out.reshape(b, d)


def _sampled_window_picks(n_blocks, block, width, fanout, starts_ref,
                          hub_rows_ref, hub_slots_ref, offsets_ref,
                          flag_ref, src_refs, win_bufs, hub_bufs, sems,
                          hub_sems):
  """The sampling stages shared — by construction, not by copy — by
  ``sample_hop`` and ``sample_hop_dedup``: per-row window DMA
  double-buffered across grid steps (slot (i+1)%2 issued while slot
  i%2 computes), the in-VMEM one-hot offset pick, and the per-element
  hub tail pass folded into the owning block's grid step. Returns the
  merged picks ``[block, fanout]`` per source array; a divergence here
  would break BOTH engines' bit-identity contracts at once instead of
  silently forking them."""
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n_a = len(src_refs)
  n_hub = hub_rows_ref.shape[0]
  i = pl.program_id(0)

  def window_dma(a, slot, row, j):
    st = starts_ref[row]
    return pltpu.make_async_copy(src_refs[a].at[pl.ds(st, width)],
                                 win_bufs[a].at[slot, j],
                                 sems[a].at[slot, j])

  def issue(slot, blk):
    for j in range(block):
      for a in range(n_a):
        window_dma(a, slot, blk * block + j, j).start()

  cur = jax.lax.rem(i, 2)
  nxt = jax.lax.rem(i + 1, 2)

  @pl.when(i == 0)
  def _():
    issue(cur, 0)                 # cold start: first block's windows

  @pl.when(i + 1 < n_blocks)
  def _():
    issue(nxt, i + 1)             # double-buffer: next block in flight

  for j in range(block):
    for a in range(n_a):
      window_dma(a, cur, i * block + j, j).wait()

  # hub tail pass: exact per-element reads for rows whose degree
  # exceeds the window, folded into the owning block's grid step
  def hub_issue(h, _):
    row = hub_rows_ref[h]
    in_block = (row >= i * block) & (row < (i + 1) * block)

    @pl.when(in_block)
    def _():
      j = row - i * block
      for k in range(fanout):
        sl = hub_slots_ref[h, k]
        for a in range(n_a):
          pltpu.make_async_copy(src_refs[a].at[pl.ds(sl, 1)],
                                hub_bufs[a].at[j, pl.ds(k, 1)],
                                hub_sems[a].at[j, k]).start()
      for k in range(fanout):
        sl = hub_slots_ref[h, k]
        for a in range(n_a):
          pltpu.make_async_copy(src_refs[a].at[pl.ds(sl, 1)],
                                hub_bufs[a].at[j, pl.ds(k, 1)],
                                hub_sems[a].at[j, k]).wait()
    return 0

  jax.lax.fori_loop(0, n_hub, hub_issue, 0)

  woff = jnp.minimum(offsets_ref[...], width - 1)      # [block, K]
  iota = jax.lax.broadcasted_iota(jnp.int32, (block, fanout, width), 2)
  onehot = iota == woff[:, :, None]
  is_hub = flag_ref[...] != 0                          # [block, 1]
  merged = []
  for a in range(n_a):
    win = win_bufs[a][cur]                             # [block, W]
    zero = jnp.zeros((), win.dtype)
    picks = jnp.sum(jnp.where(onehot, win[:, None, :], zero), axis=-1)
    merged.append(jnp.where(is_hub, hub_bufs[a][...], picks))
  return merged


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def sample_hop(arr_win: jax.Array,
               eids_win: 'Optional[jax.Array]',
               starts: jax.Array,
               offsets: jax.Array,
               hub_rows: jax.Array,
               hub_slots: jax.Array,
               width: int,
               block: int = 8,
               interpret: bool = False):
  """One-hop sampling megakernel: window DMA + offset pick + hub tail.

  For each frontier row ``i``, DMAs the ``width``-wide CSR window
  ``arr_win[starts[i] : starts[i]+width]`` HBM->VMEM (double-buffered
  across grid steps, ``block`` rows' descriptors in flight per slot),
  applies the precomputed sampling ``offsets`` inside VMEM, and emits
  the packed ``[S, K]`` neighbor picks — the ``[S, width]`` window never
  materializes in HBM. Rows listed in ``hub_rows`` (degree > width, so
  their offsets can exceed the window) are fixed up by a per-element DMA
  tail pass in the SAME kernel: ``hub_slots`` holds their exact edge
  slots, and the combine overwrites only those rows.

  Args:
    arr_win: [E + width] edge array padded per the ``gather_windows``
      contract — every real row window lies fully inside it, so
      ``starts`` need no clamping.
    eids_win: optional second edge array (edge ids) read through the
      same windows/offsets; pass None to skip the second output.
    starts: [S] int32 per-row window starts (CSR row offsets).
    offsets: [S, K] int32 within-row sampling offsets, as drawn by the
      element path (unclamped; the kernel clips to the window for the
      main pass — hub rows get exact values from the tail pass).
    hub_rows: [H] int32 frontier row indices needing exact fix-up; -1
      marks unused capacity. H is a static cap. Every grid step scans
      the whole list for rows in its block (O(grid * H) scalar
      compares), so H must stay small relative to S — pick W so hubs
      are rare (callers clamp H to the frontier size, and the degree
      distribution bounds it); a sorted-hub-list + per-block-offset
      variant is the follow-up if a hardware A/B ever shows the scan.
    hub_slots: [H, K] int32 exact edge slots for the hub rows (already
      clipped to the real edge range by the caller).

  Returns ``picks`` [S, K] (and ``eid_picks`` [S, K] when ``eids_win``
  is given, else None) with the same dtype(s) as the source arrays.
  Rows beyond the hub cap fall back to window-clipped picks — identical
  confinement to the XLA window path (ops/sample.py docstring).
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  s = starts.shape[0]
  fanout = offsets.shape[1]
  n_hub = hub_rows.shape[0]
  with_eids = eids_win is not None
  if s == 0:
    empty = jnp.zeros((0, fanout), arr_win.dtype)
    return empty, (jnp.zeros((0, fanout), eids_win.dtype)
                   if with_eids else None)
  starts = starts.astype(jnp.int32)
  offsets = offsets.astype(jnp.int32)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
    offsets = jnp.pad(offsets, ((0, pad), (0, 0)))
  n_blocks = (s + pad) // block
  # per-row fix-up flag, derived from the SAME hub list the tail pass
  # walks — a row is only flagged if a tail DMA will actually fill it
  # (hub rows past the H cap keep their window picks, the documented
  # confinement of an undersized cap)
  valid_hub = (hub_rows >= 0).astype(jnp.int32)
  hub_flag = jnp.zeros((s + pad, 1), jnp.int32).at[
      jnp.clip(hub_rows, 0, s + pad - 1), 0].max(valid_hub)
  hub_rows = jnp.where(valid_hub > 0, hub_rows, -1).astype(jnp.int32)
  hub_slots = hub_slots.astype(jnp.int32)

  arrs = (arr_win, eids_win) if with_eids else (arr_win,)

  def kernel(starts_ref, hub_rows_ref, hub_slots_ref, offsets_ref,
             flag_ref, *rest):
    src_refs = rest[:len(arrs)]
    out_refs = rest[len(arrs):2 * len(arrs)]
    win_bufs = rest[2 * len(arrs):3 * len(arrs)]
    hub_bufs = rest[3 * len(arrs):4 * len(arrs)]
    sems = rest[4 * len(arrs):5 * len(arrs)]
    hub_sems = rest[5 * len(arrs):6 * len(arrs)]
    merged = _sampled_window_picks(
        n_blocks, block, width, fanout, starts_ref, hub_rows_ref,
        hub_slots_ref, offsets_ref, flag_ref, src_refs, win_bufs,
        hub_bufs, sems, hub_sems)
    for a in range(len(arrs)):
      out_refs[a][...] = merged[a]

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=3,
      grid=(n_blocks,),
      in_specs=(
          [pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
           pl.BlockSpec((block, 1), lambda i, *_: (i, 0))]
          + [pl.BlockSpec(memory_space=pl.ANY)] * len(arrs)),
      out_specs=[pl.BlockSpec((block, fanout), lambda i, *_: (i, 0))
                 for _ in arrs],
      scratch_shapes=(
          [pltpu.VMEM((2, block, width), a.dtype) for a in arrs]
          + [pltpu.VMEM((block, fanout), a.dtype) for a in arrs]
          + [pltpu.SemaphoreType.DMA((2, block)) for _ in arrs]
          + [pltpu.SemaphoreType.DMA((block, fanout)) for _ in arrs]),
  )
  _count_launch()
  outs = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=[jax.ShapeDtypeStruct((s + pad, fanout), a.dtype)
                 for a in arrs],
      interpret=interpret,
  )(starts, hub_rows, hub_slots, offsets, hub_flag, *arrs)
  picks = outs[0][:s]
  return picks, (outs[1][:s] if with_eids else None)


# ---------------------------------------------------------------------------
# pallas_fused: sample -> dedup fused in one kernel (ISSUE 10 tentpole).
#
# The dedup table is a bucketized open-addressing hash table living in
# VMEM for the whole kernel: [n_buckets, 128] int32 ids + labels, so a
# probe is ONE vector load + compare over a bucket's 128 lanes instead
# of 128 scalar reads. Grid steps run sequentially on TPU, which makes
# the insert order deterministic (slot order) — the same first-
# occurrence semantics the sort engines recover with stable sorts.
# ---------------------------------------------------------------------------

#: lanes per hash bucket — one VMEM vector row per probe
TABLE_LANES = 128


def fused_table_max_slots() -> int:
  """VMEM dedup-table sizing knob: the largest table (in id slots) the
  ``pallas_fused`` engine may allocate. Both planes (ids + labels) of a
  full-size table cost ``2 * slots * 4`` bytes of VMEM for the whole
  kernel — the default (2^20 slots = 8 MB) leaves room for the window
  double-buffers inside a 16 MB VMEM budget. A multihop whose node
  budget needs more slots falls back to the ``pallas`` engine (counted
  in ``hop_engine_fallbacks_total``)."""
  return knob('GLT_FUSED_TABLE_SLOTS', 1 << 20)


def fused_table_slots(budget: int) -> int:
  """Slots for a walk with ``budget`` worst-case distinct nodes: the
  next power-of-two bucket count whose slot count covers the budget
  (capacity > occupancy guarantees probe termination; typical fill is
  the ACTUAL distinct count, far below the static budget, so the load
  factor in practice stays low)."""
  n_buckets = 8  # (8, 128) min int32 tile
  while n_buckets * TABLE_LANES <= budget:
    n_buckets *= 2
  return n_buckets * TABLE_LANES


def make_dedup_table(slots: int):
  """Fresh (ids, labels) table planes; -1 marks an empty lane."""
  assert slots % TABLE_LANES == 0
  shape = (slots // TABLE_LANES, TABLE_LANES)
  return (jnp.full(shape, -1, jnp.int32), jnp.full(shape, -1, jnp.int32))


def _hash_bucket(x, n_buckets):
  """Multiplicative (Fibonacci) hash of an int32 id -> bucket index."""
  h = x * jnp.int32(-1640531527)
  h = jnp.bitwise_xor(h, jax.lax.shift_right_logical(h, 16))
  return jnp.bitwise_and(h, n_buckets - 1)


def _probe(tab_ids_ref, x, n_buckets):
  """Walk buckets from hash(x) until one holds ``x`` or has an empty
  lane. Terminates because callers size the table past the worst-case
  occupancy (fused_table_slots) and lanes are never deleted; the cond
  is pure (loads live in the body) so the loop discharges in interpret
  mode."""
  from jax.experimental import pallas as pl

  def cond(c):
    return jnp.logical_not(c[1])

  def step(c):
    b, _ = c
    row = tab_ids_ref[pl.ds(b, 1), :]
    stop = jnp.any(row == x) | jnp.any(row == -1)
    return (jnp.where(stop, b, jnp.bitwise_and(b + 1, n_buckets - 1)),
            stop)

  b, _ = jax.lax.while_loop(cond, step, (_hash_bucket(x, n_buckets),
                                         False))
  return b


def _probe_insert(tab_ids_ref, tab_labs_ref, x, valid, new_label,
                  n_buckets, lane_iota):
  """One dedup element: find ``x``'s bucket, return (label, inserted).
  Invalid elements probe with -1 (stops at the first empty lane, never
  matches a real id as "found new") and are neutralized by masked
  writes, so the whole element is straight-line code — no pl.when."""
  from jax.experimental import pallas as pl
  xs = jnp.where(valid, x, jnp.int32(-1))
  b = _probe(tab_ids_ref, xs, n_buckets)
  row = tab_ids_ref[pl.ds(b, 1), :]
  eq = row == xs
  # xs == -1 "finds" the empty lanes; valid gating below discards it
  found = jnp.any(eq)
  do_insert = jnp.logical_and(valid, jnp.logical_not(found))
  labrow = tab_labs_ref[pl.ds(b, 1), :]
  found_lab = jnp.max(jnp.where(eq, labrow, -1))
  empty = row == -1
  first_empty = jnp.min(jnp.where(empty, lane_iota, TABLE_LANES))
  put = jnp.logical_and(do_insert, lane_iota == first_empty)
  tab_ids_ref[pl.ds(b, 1), :] = jnp.where(put, xs, row)
  tab_labs_ref[pl.ds(b, 1), :] = jnp.where(put, new_label, labrow)
  lab = jnp.where(valid,
                  jnp.where(found, found_lab, new_label),
                  jnp.int32(-1))
  return lab, do_insert.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('interpret',))
def dedup_table_insert(tab_ids: jax.Array, tab_labs: jax.Array,
                       ids: jax.Array, labs: jax.Array,
                       valid: jax.Array,
                       interpret: bool = False):
  """Insert pre-labeled ids into the dedup table (the seed hop: labels
  come from the EXACT seed dedup, the table just has to agree with them
  before the first fused hop probes it). Already-present ids keep their
  stored label; invalid slots are no-ops. Returns the updated planes.
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  n_buckets = tab_ids.shape[0]
  m = ids.shape[0]
  if m == 0:
    return tab_ids, tab_labs
  ids = ids.astype(jnp.int32)
  labs = labs.astype(jnp.int32)
  valid = valid.astype(jnp.int32)

  def kernel(ids_ref, labs_ref, valid_ref, ids_in, labs_in,
             ids_out, labs_out, tids, tlabs, sems):
    # table planes live in HBM (ANY) in/out; ONE VMEM copy is staged
    # by explicit DMA — blocked in+out specs would keep TWO resident
    # copies per plane and double the VMEM footprint
    pltpu.make_async_copy(ids_in, tids, sems.at[0]).start()
    pltpu.make_async_copy(labs_in, tlabs, sems.at[1]).start()
    pltpu.make_async_copy(ids_in, tids, sems.at[0]).wait()
    pltpu.make_async_copy(labs_in, tlabs, sems.at[1]).wait()
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, TABLE_LANES), 1)

    def body(t, _):
      _probe_insert(tids, tlabs, ids_ref[t], valid_ref[t] != 0,
                    labs_ref[t], n_buckets, lane)
      return 0

    jax.lax.fori_loop(0, m, body, 0)
    pltpu.make_async_copy(tids, ids_out, sems.at[0]).start()
    pltpu.make_async_copy(tlabs, labs_out, sems.at[1]).start()
    pltpu.make_async_copy(tids, ids_out, sems.at[0]).wait()
    pltpu.make_async_copy(tlabs, labs_out, sems.at[1]).wait()

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=3,
      grid=(1,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY)],
      out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)],
      scratch_shapes=[pltpu.VMEM(tab_ids.shape, jnp.int32),
                      pltpu.VMEM(tab_ids.shape, jnp.int32),
                      pltpu.SemaphoreType.DMA((2,))],
  )
  _count_launch()
  return pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=[jax.ShapeDtypeStruct(tab_ids.shape, jnp.int32),
                 jax.ShapeDtypeStruct(tab_ids.shape, jnp.int32)],
      interpret=interpret,
  )(ids, labs, valid, tab_ids, tab_labs)


@functools.partial(jax.jit, static_argnames=('width', 'block',
                                             'interpret'))
def sample_hop_dedup(arr_win: jax.Array,
                     eids_win: 'Optional[jax.Array]',
                     starts: jax.Array,
                     offsets: jax.Array,
                     valid: jax.Array,
                     hub_rows: jax.Array,
                     hub_slots: jax.Array,
                     tab_ids: jax.Array,
                     tab_labs: jax.Array,
                     count: jax.Array,
                     width: int,
                     block: int = 8,
                     interpret: bool = False):
  """The fused hop megakernel: window DMA + offset pick + hub tail +
  dedup-table assign, all in one kernel.

  The sampling stages are ``sample_hop``'s, unchanged (same
  double-buffered window DMA slots, same one-hot pick, same per-element
  hub fix-up). The new stage runs right after the pick, on the merged
  picks still in VMEM: each element probes the resident dedup table
  (``_probe_insert``) in slot order — grid steps are sequential, so
  insertion order is deterministic — and emits a PROVISIONAL label:
  previously seen ids return their stored label, fresh ids get
  ``count + r`` in first-occurrence order (r = running insert counter,
  carried across grid steps in SMEM). The ``sorted_hop_dedup_fused``
  value-order label contract is restored by the caller with one narrow
  sort over the fresh ids (ops/sample.py::sample_neighbors_fused),
  which also rewrites the table's labels for the next hop.

  Args (beyond sample_hop's):
    valid: [S, K] int32/bool element validity (the sample mask) — the
      dedup stage skips invalid lanes.
    tab_ids / tab_labs: [n_buckets, 128] table planes (make_dedup_table
      or a previous hop's outputs); n_buckets must be a power of two.
    count: scalar int32, labels assigned before this hop.

  Returns (picks, eid_picks|None, prov_labels [S, K], new_head [S, K]
  int32, tab_ids', tab_labs').
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  s = starts.shape[0]
  fanout = offsets.shape[1]
  n_hub = hub_rows.shape[0]
  n_buckets = tab_ids.shape[0]
  assert n_buckets & (n_buckets - 1) == 0, 'bucket count must be pow2'
  with_eids = eids_win is not None
  if s == 0:
    empty = jnp.zeros((0, fanout), arr_win.dtype)
    return (empty,
            jnp.zeros((0, fanout), eids_win.dtype) if with_eids else None,
            jnp.zeros((0, fanout), jnp.int32),
            jnp.zeros((0, fanout), jnp.int32), tab_ids, tab_labs)
  starts = starts.astype(jnp.int32)
  offsets = offsets.astype(jnp.int32)
  valid = valid.astype(jnp.int32)
  pad = (-s) % block
  if pad:
    starts = jnp.pad(starts, (0, pad))
    offsets = jnp.pad(offsets, ((0, pad), (0, 0)))
    valid = jnp.pad(valid, ((0, pad), (0, 0)))  # padded rows never insert
  n_blocks = (s + pad) // block
  valid_hub = (hub_rows >= 0).astype(jnp.int32)
  hub_flag = jnp.zeros((s + pad, 1), jnp.int32).at[
      jnp.clip(hub_rows, 0, s + pad - 1), 0].max(valid_hub)
  hub_rows = jnp.where(valid_hub > 0, hub_rows, -1).astype(jnp.int32)
  hub_slots = hub_slots.astype(jnp.int32)
  count = count.astype(jnp.int32).reshape((1,))

  arrs = (arr_win, eids_win) if with_eids else (arr_win,)
  n_a = len(arrs)

  def kernel(starts_ref, hub_rows_ref, hub_slots_ref, count_ref,
             offsets_ref, flag_ref, valid_ref, tids_in, tlabs_in,
             *rest):
    src_refs = rest[:n_a]
    out_refs = rest[n_a:2 * n_a]
    lab_ref, newh_ref, tids_out, tlabs_out = rest[2 * n_a:2 * n_a + 4]
    scr = rest[2 * n_a + 4:]
    win_bufs = scr[:n_a]
    hub_bufs = scr[n_a:2 * n_a]
    sems = scr[2 * n_a:3 * n_a]
    hub_sems = scr[3 * n_a:4 * n_a]
    r_ref, tids, tlabs, tsems = scr[4 * n_a:4 * n_a + 4]
    i = pl.program_id(0)

    # table planes ride HBM (ANY) in/out; the working copy is ONE VMEM
    # scratch per plane, DMA'd in at the first step and written back at
    # the last — blocked in+out table specs would pin two resident
    # copies per plane (2x the table's VMEM share for nothing)
    @pl.when(i == 0)
    def _():
      pltpu.make_async_copy(tids_in, tids, tsems.at[0]).start()
      pltpu.make_async_copy(tlabs_in, tlabs, tsems.at[1]).start()
      pltpu.make_async_copy(tids_in, tids, tsems.at[0]).wait()
      pltpu.make_async_copy(tlabs_in, tlabs, tsems.at[1]).wait()
      r_ref[0] = 0

    # sampling stages: the SAME helper sample_hop runs — the fused
    # kernel only appends the dedup stage below
    merged = _sampled_window_picks(
        n_blocks, block, width, fanout, starts_ref, hub_rows_ref,
        hub_slots_ref, offsets_ref, flag_ref, src_refs, win_bufs,
        hub_bufs, sems, hub_sems)
    for a in range(n_a):
      out_refs[a][...] = merged[a]
    picks0 = merged[0]

    # dedup stage: probe/insert the merged picks, slot order (row-major
    # over [block, fanout], sequential grid => global slot order)
    base = count_ref[0]
    r = r_ref[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, TABLE_LANES), 1)
    lab_rows, newh_rows = [], []
    for j in range(block):
      labs_k, newh_k = [], []
      for k in range(fanout):
        x = picks0[j, k].astype(jnp.int32)
        v = valid_ref[j, k] != 0
        lab, is_new = _probe_insert(tids, tlabs, x, v,
                                    base + r, n_buckets, lane)
        labs_k.append(lab)
        newh_k.append(is_new)
        r = r + is_new
      lab_rows.append(jnp.stack(labs_k))
      newh_rows.append(jnp.stack(newh_k))
    lab_ref[...] = jnp.stack(lab_rows)
    newh_ref[...] = jnp.stack(newh_rows)
    r_ref[0] = r

    @pl.when(i == n_blocks - 1)
    def _():
      pltpu.make_async_copy(tids, tids_out, tsems.at[0]).start()
      pltpu.make_async_copy(tlabs, tlabs_out, tsems.at[1]).start()
      pltpu.make_async_copy(tids, tids_out, tsems.at[0]).wait()
      pltpu.make_async_copy(tlabs, tlabs_out, tsems.at[1]).wait()

  tshape = tab_ids.shape
  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=4,
      grid=(n_blocks,),
      in_specs=(
          [pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
           pl.BlockSpec((block, 1), lambda i, *_: (i, 0)),
           pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
           pl.BlockSpec(memory_space=pl.ANY),
           pl.BlockSpec(memory_space=pl.ANY)]
          + [pl.BlockSpec(memory_space=pl.ANY)] * n_a),
      out_specs=([pl.BlockSpec((block, fanout), lambda i, *_: (i, 0))
                  for _ in arrs]
                 + [pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
                    pl.BlockSpec((block, fanout), lambda i, *_: (i, 0)),
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY)]),
      scratch_shapes=(
          [pltpu.VMEM((2, block, width), a.dtype) for a in arrs]
          + [pltpu.VMEM((block, fanout), a.dtype) for a in arrs]
          + [pltpu.SemaphoreType.DMA((2, block)) for _ in arrs]
          + [pltpu.SemaphoreType.DMA((block, fanout)) for _ in arrs]
          + [pltpu.SMEM((1,), jnp.int32),
             pltpu.VMEM(tshape, jnp.int32),
             pltpu.VMEM(tshape, jnp.int32),
             pltpu.SemaphoreType.DMA((2,))]),
  )
  _count_launch()
  outs = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=([jax.ShapeDtypeStruct((s + pad, fanout), a.dtype)
                  for a in arrs]
                 + [jax.ShapeDtypeStruct((s + pad, fanout), jnp.int32),
                    jax.ShapeDtypeStruct((s + pad, fanout), jnp.int32),
                    jax.ShapeDtypeStruct(tshape, jnp.int32),
                    jax.ShapeDtypeStruct(tshape, jnp.int32)]),
      interpret=interpret,
  )(starts, hub_rows, hub_slots, count, offsets, hub_flag, valid,
    tab_ids, tab_labs, *arrs)
  picks = outs[0][:s]
  eid_picks = outs[1][:s] if with_eids else None
  prov_labels = outs[n_a][:s]
  new_head = outs[n_a + 1][:s]
  return (picks, eid_picks, prov_labels, new_head,
          outs[n_a + 2], outs[n_a + 3])


# ---------------------------------------------------------------------------
# Hetero edge-type plane (ISSUE 14): the geometry that lets ONE
# sample_hop_dedup invocation serve EVERY edge type of a hetero hop.
#
# The kernel itself is type-agnostic — it reads windows at `starts`,
# picks at `offsets`, and dedups whatever int32 ids the windows hold.
# The edge-type plane exploits that: each edge type's W-padded indices
# block is concatenated into ONE flat array with its neighbor values
# rebased into a GLOBAL node-id space (local id + type_base[ntype]), so
#   * per-type window geometry is a per-row affine shift baked into
#     `starts` (indptr_e[row] + edge_base[e]) — the same double-
#     buffered HBM->VMEM window DMA serves every type;
#   * per-type fanouts ride the [S, K_max] offset/validity planes
#     (lanes past an edge type's fanout are invalid, never probed);
#   * per-type dedup namespaces come FREE from the type-tagged keys:
#     global ids never collide across types, so one VMEM table holds
#     every type's seen-set and a probe is type-correct by construction.
# The XLA epilogue (ops/pipeline.py::_multihop_sample_hetero_fused)
# converts the kernel's global provisional labels back to the per-type
# value-order label contract of the per-edge-type sorted reference.
# ---------------------------------------------------------------------------


def build_type_plane(etypes, trav, node_counts, parts, width):
  """Build the flat multi-edge-type window geometry (eager, once per
  compiled hetero program — plans are constructed outside jit).

  Args:
    etypes: traversal-order edge-type list (the reference hop loop's
      iteration order; first-occurrence semantics depend on it).
    trav: Dict[EdgeType, (expand_from_type, neighbor_type)].
    node_counts: Dict[NodeType, int] — the per-type id spaces being
      tagged into one global space.
    parts: Dict[EdgeType, dict] with per-etype ``indices_win`` (the
      W-padded indices, Graph.window_arrays contract), ``num_edges``,
      and optional ``edge_ids_win``.
    width: window width W (every block carries its own W-slot pad, so
      any row's window read stays inside its block).

  Returns dict(type_base, edge_base, indices_flat, eids_flat,
  has_eids, total_nodes). Raises ValueError when the type-tagged key
  space or the flat edge plane exceeds int32 — the genuinely
  unservable hetero shapes (callers demote with reason ``hetero``).
  """
  types = list(node_counts)
  type_base, base = {}, 0
  for t in types:
    type_base[t] = base
    base += int(node_counts[t])
  if base >= 2 ** 31:
    raise ValueError(
        f'{base} nodes across types exceed the int32 type-tagged key '
        'space of the fused dedup table')
  has_eids = {e: parts[e].get('edge_ids_win') is not None
              for e in etypes}
  any_eids = any(has_eids.values())
  edge_base, off = {}, 0
  blocks, eid_blocks = [], []
  for e in etypes:
    p = parts[e]
    iw = jnp.asarray(p['indices_win'])
    assert int(iw.shape[0]) == int(p['num_edges']) + int(width), (
        'indices_win must carry exactly width trailing pad slots '
        '(Graph.window_arrays contract)', e)
    b = type_base[trav[e][1]]
    # sentinel pad lanes stay -1 in the global space; valid lanes never
    # read them (offsets < deg <= W stay inside the row's real window,
    # hub rows are fixed by exact in-range slots)
    blocks.append(jnp.where(iw >= 0, iw.astype(jnp.int32) + b,
                            jnp.int32(-1)))
    edge_base[e] = off
    off += int(iw.shape[0])
    if not any_eids:  # no zero-plane churn when no type carries eids
      continue
    ew = p.get('edge_ids_win')
    if ew is None:
      eid_blocks.append(jnp.zeros((int(iw.shape[0]),), jnp.int32))
      continue
    ew = jnp.asarray(ew)
    if jnp.dtype(ew.dtype).itemsize > 4 and int(ew.shape[0]) \
        and int(ew.max()) >= 2 ** 31:
      # the flat eid plane is int32 (one common dtype across types);
      # silently truncating 64-bit edge-id VALUES would diverge from
      # the per-etype reference — fail the plan loudly instead (the
      # sampler demotes with the counted `hetero` reason)
      raise ValueError(
          f'edge ids of {e} exceed the int32 range of the flat hetero '
          'eid plane; remap edge ids below 2^31 per type or sample '
          'this graph without the fused hetero engine')
    eid_blocks.append(ew.astype(jnp.int32))
  if off >= 2 ** 31:
    raise ValueError(
        f'{off} flat edge slots exceed the int32 window-start space')
  return dict(
      type_base=type_base,
      edge_base=edge_base,
      indices_flat=jnp.concatenate(blocks) if blocks
      else jnp.zeros((0,), jnp.int32),
      eids_flat=jnp.concatenate(eid_blocks) if any_eids else None,
      has_eids=has_eids,
      total_nodes=base,
  )


# ---------------------------------------------------------------------------
# Cross-hop fused walk (ISSUE 13 tentpole): the WHOLE multi-hop walk as
# one kernel invocation.
#
# The per-hop family above still pays, at every hop boundary: a kernel
# teardown/launch, a full HBM write-back + reload of both [n_buckets,
# 128] table planes, and a fresh read of the padded edge array operand.
# Here the grid covers every hop's frontier blocks back to back (hop
# boundaries are grid phases, statically unrolled), and the dedup table
# lives in VMEM *scratch* for the whole walk — it never exists in HBM
# at all: step 0 memsets it and inserts the exact-dedup'd seed hop, and
# each phase probes/inserts its picks against the same resident planes.
#
# What had to move in-kernel for the walk to stay on-chip: hop h+1's
# frontier is hop h's picks, so the kernel (a) writes each hop's masked
# picks to a small HBM staging buffer (the only cross-hop HBM traffic
# left — [S_h, K_h] int32 per hop vs two table planes + the edge-array
# operand per hop before), (b) DMAs the next block's frontier ids +
# their indptr pairs while the current block computes, and (c) derives
# the Floyd/replace offsets from precomputed per-hop uniform draws (the
# draws are data-independent, so XLA generates them up front from the
# same jax.random stream — bit-identical offsets by construction). Hub
# rows are fixed up per-row (degree > W => exact per-element reads), so
# the walk needs no hub list and no hub cap at all.
#
# One DMA pipeline serves every hop: the double-buffered window slots
# prefetch block i+1's CSR windows (frontier -> indptr -> window chain
# resolved ahead of the probe section) across hop-interior steps; the
# pipeline only hiccups for one block at each hop boundary, where the
# next frontier literally does not exist until the current step's picks
# are written.
# ---------------------------------------------------------------------------


def walk_geometry(batch_size: int, fanouts, block: int = 8):
  """Static hop-phase geometry of the cross-hop walk: per hop a dict of
  frontier rows (``s``), block-padded rows (``s_pad``), first grid step
  (``step0``), step count (``nb``) and fanout (``k``). Returns
  ``(hops, total_steps)``."""
  hops = []
  s = max(int(batch_size), 1)
  step = 0
  for k in fanouts:
    k = int(k)
    assert k > 0, 'the cross-hop walk serves uniform positive fanouts'
    nb = -(-s // block)
    hops.append(dict(s=s, s_pad=nb * block, step0=step, nb=nb, k=k))
    step += nb
    s = s * k
  return hops, step


@functools.partial(jax.jit, static_argnames=(
    'fanouts', 'width', 'num_nodes', 'num_edges', 'table_slots',
    'batch_size', 'replace', 'block', 'interpret'))
def sample_walk_dedup(arr_win: jax.Array,
                      eids_win: 'Optional[jax.Array]',
                      indptr_pad: jax.Array,
                      seed_ids: jax.Array,
                      seed_ok: jax.Array,
                      seed_tab_ids: jax.Array,
                      seed_tab_labs: jax.Array,
                      base_count: jax.Array,
                      u_hops,
                      *,
                      fanouts,
                      width: int,
                      num_nodes: int,
                      num_edges: int,
                      table_slots: int,
                      batch_size: int,
                      replace: bool = False,
                      block: int = 8,
                      interpret: bool = False):
  """The cross-hop walk megakernel: every uniform hop's window DMA +
  offset pick + hub fix-up + dedup-table assign in ONE kernel, the
  table resident in VMEM scratch for the whole walk.

  Args:
    arr_win / eids_win: W-padded edge array(s), as in ``sample_hop``.
    indptr_pad: [N + 2] int32 — the CSR indptr with ONE trailing
      ``num_edges`` sentinel, so the kernel's 2-wide row reads at a
      clamped address reproduce the element path's per-element
      ``take(..., mode='clip')`` start/degree semantics exactly
      (an invalid frontier id — INT32_MAX — clamps to row N and reads
      ``[E, E]``: degree 0, window over the sentinel padding, the same
      values the XLA engines read for masked rows).
    seed_ids: [S1_pad] int32 — hop 1's frontier in the sorted-seed
      order (``sorted_hop_dedup``'s ``ids3``), RAW ids: duplicate seeds
      keep their real id (they read real windows, exactly like the
      ``sort+fused`` reference) and validity rides ``seed_ok``.
    seed_ok: [S1_pad] int32 — hop 1 frontier validity (``new_head3``).
    seed_tab_ids / seed_tab_labs: [B_pad] int32 — the exact-dedup'd
      seed uniques (+ labels) inserted into the fresh table at step 0;
      -1 ids are skipped. Scalar-prefetched (the insert loop indexes
      them dynamically).
    base_count: [1] int32 — labels assigned before hop 1 (seed count);
      fresh ids get provisional labels ``base + r`` in global
      first-occurrence order, ``r`` carried in SMEM across all hops.
    u_hops: tuple of per-hop uniform draws, hop h shaped
      [S_h_pad, K_h] float32 with ``u[row, j] = uniform_h[j, row]``
      (the element path's ``_floyd_offsets`` orientation transposed;
      for ``replace`` the natural [S, K] draw). Data-independent, so
      the caller draws them up front from the unchanged key sequence.
    fanouts: static positive per-hop fanouts.
    table_slots: dedup-table capacity (``fused_table_slots``); the two
      VMEM-resident planes cost ``2 * table_slots * 4`` bytes of
      scratch for the whole kernel.

  Returns ``(picks, eid_picks|None, prov, new_head)`` — tuples with one
  [S_h_pad, K_h] entry per hop; ``prov`` labels are provisional (global
  first-occurrence order), converted to the ``sorted_hop_dedup_fused``
  value-order contract by the caller
  (ops/pipeline.py::_multihop_sample_walk) with one narrow sort per
  hop. The masked-lane values of ``picks``/``eid_picks`` match the
  window-read reference bit-for-bit (same physical slots).
  """
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  big = jnp.iinfo(jnp.int32).max
  n_hops = len(fanouts)
  hops, total_steps = walk_geometry(batch_size, fanouts, block)
  with_eids = eids_win is not None
  arrs = (arr_win, eids_win) if with_eids else (arr_win,)
  n_a = len(arrs)
  assert table_slots % TABLE_LANES == 0
  n_buckets = table_slots // TABLE_LANES
  assert n_buckets & (n_buckets - 1) == 0, 'bucket count must be pow2'
  tshape = (n_buckets, TABLE_LANES)
  assert seed_ids.shape[0] == hops[0]['s_pad']
  assert len(u_hops) == n_hops
  for h, u in zip(hops, u_hops):
    assert u.shape == (h['s_pad'], h['k']), (u.shape, h)
  b_pad = seed_tab_ids.shape[0]
  k_max = max(f for f in fanouts)

  seed_tab_ids = seed_tab_ids.astype(jnp.int32)
  seed_tab_labs = seed_tab_labs.astype(jnp.int32)
  base_count = base_count.astype(jnp.int32).reshape((1,))
  seed_ids = seed_ids.astype(jnp.int32)
  seed_ok = seed_ok.astype(jnp.int32)
  indptr_pad = indptr_pad.astype(jnp.int32)

  def kernel(stab_ids_ref, stab_labs_ref, base_ref, *rest):
    u_refs = rest[:n_hops]
    ip_ref, sid_ref, sok_ref = rest[n_hops:n_hops + 3]
    src_refs = rest[n_hops + 3:n_hops + 3 + n_a]
    pos = n_hops + 3 + n_a
    picks_refs = rest[pos:pos + n_hops]; pos += n_hops
    if with_eids:
      eidp_refs = rest[pos:pos + n_hops]; pos += n_hops
    prov_refs = rest[pos:pos + n_hops]; pos += n_hops
    newh_refs = rest[pos:pos + n_hops]; pos += n_hops
    fp_refs = rest[pos:pos + max(n_hops - 1, 1)]
    pos += max(n_hops - 1, 1)
    scr = rest[pos:]
    vf, vok, vip = scr[0], scr[1], scr[2]
    win_bufs = scr[3:3 + n_a]
    hub_bufs = scr[3 + n_a:3 + 2 * n_a]
    fscrs = scr[3 + 2 * n_a:3 + 2 * n_a + max(n_hops - 1, 1)]
    spos = 3 + 2 * n_a + max(n_hops - 1, 1)
    tids, tlabs, r_ref = scr[spos], scr[spos + 1], scr[spos + 2]
    fsem, oksem, ipsem = scr[spos + 3], scr[spos + 4], scr[spos + 5]
    wsems = scr[spos + 6:spos + 6 + n_a]
    hubsems = scr[spos + 6 + n_a:spos + 6 + 2 * n_a]
    fpsem = scr[spos + 6 + 2 * n_a]

    i = pl.program_id(0)
    cur = jax.lax.rem(i, 2)
    nxt = jax.lax.rem(i + 1, 2)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, TABLE_LANES), 1)

    # step 0: fresh table planes (memset, never read from HBM) + the
    # exact-dedup'd seed insert — the walk's phase 0, folded into the
    # first sampling step so no separate launch exists even for seeding
    @pl.when(i == 0)
    def _():
      tids[...] = jnp.full(tshape, -1, jnp.int32)
      tlabs[...] = jnp.full(tshape, -1, jnp.int32)

      def body(t, _):
        x = stab_ids_ref[t]
        _probe_insert(tids, tlabs, x, x >= 0, stab_labs_ref[t],
                      n_buckets, lane)
        return 0

      jax.lax.fori_loop(0, b_pad, body, 0)
      r_ref[0] = 0

    # -- DMA chain helpers (slot-parity double buffered) ----------------
    def start_frontier(hop, b, slot):
      for j in range(block):
        if hop == 0:
          pltpu.make_async_copy(sid_ref.at[pl.ds(b * block + j, 1)],
                                vf.at[slot, j], fsem.at[slot, j]).start()
          pltpu.make_async_copy(sok_ref.at[pl.ds(b * block + j, 1)],
                                vok.at[slot, j],
                                oksem.at[slot, j]).start()
        else:
          prev = hops[hop - 1]
          r = b * block + j
          q = jnp.minimum(r // prev['k'], prev['s_pad'] - 1)
          l = jax.lax.rem(r, prev['k'])
          pltpu.make_async_copy(fp_refs[hop - 1].at[q, pl.ds(l, 1)],
                                vf.at[slot, j], fsem.at[slot, j]).start()

    def wait_frontier(hop, slot):
      for j in range(block):
        pltpu.make_async_copy(vf.at[slot, j], vf.at[slot, j],
                              fsem.at[slot, j]).wait()
        if hop == 0:
          pltpu.make_async_copy(vok.at[slot, j], vok.at[slot, j],
                                oksem.at[slot, j]).wait()

    def start_ip(slot):
      for j in range(block):
        fid = vf[slot, j, 0]
        addr = jnp.clip(fid, 0, num_nodes)
        pltpu.make_async_copy(ip_ref.at[pl.ds(addr, 2)],
                              vip.at[slot, j], ipsem.at[slot, j]).start()

    def wait_ip(slot):
      for j in range(block):
        pltpu.make_async_copy(vip.at[slot, j], vip.at[slot, j],
                              ipsem.at[slot, j]).wait()

    def start_windows(slot):
      for j in range(block):
        st = jnp.clip(vip[slot, j, 0], 0, num_edges)
        for a in range(n_a):
          pltpu.make_async_copy(src_refs[a].at[pl.ds(st, width)],
                                win_bufs[a].at[slot, j],
                                wsems[a].at[slot, j]).start()

    def wait_windows(slot):
      for j in range(block):
        for a in range(n_a):
          pltpu.make_async_copy(win_bufs[a].at[slot, j],
                                win_bufs[a].at[slot, j],
                                wsems[a].at[slot, j]).wait()

    def fetch_block(hop, b, slot):
      """Cold-start chain for a block with nothing prefetched (first
      block of each hop — at a hop boundary the frontier is written by
      the immediately preceding step, so there is nothing to overlap
      with: the documented per-boundary pipeline bubble)."""
      start_frontier(hop, b, slot)
      wait_frontier(hop, slot)
      start_ip(slot)
      wait_ip(slot)
      start_windows(slot)

    # -- hop phases, statically unrolled --------------------------------
    for hop in range(n_hops):
      h = hops[hop]
      k_h = h['k']

      @pl.when((i >= h['step0']) & (i < h['step0'] + h['nb']))
      def _(hop=hop, h=h, k_h=k_h):
        b = i - h['step0']

        @pl.when(b == 0)
        def _():
          fetch_block(hop, b, cur)

        has_next = b + 1 < h['nb']

        # next block's frontier starts resolving while this block's
        # windows land and compute runs
        @pl.when(has_next)
        def _():
          start_frontier(hop, b + 1, nxt)

        wait_windows(cur)
        ids_v = vf[cur][:, 0]                            # [block]
        if hop == 0:
          ok_v = vok[cur][:, 0] != 0
        else:
          ok_v = ids_v != big
        rowpos = b * block + jax.lax.broadcasted_iota(
            jnp.int32, (block,), 0)
        ok_v = jnp.logical_and(ok_v, rowpos < h['s'])
        ipv = vip[cur]                                   # [block, 2]
        deg = jnp.where(ok_v, ipv[:, 1] - ipv[:, 0], 0)
        u = u_refs[hop][...]                             # [block, K_h]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (block, k_h), 1)
        if replace:
          off = jnp.minimum(
              (u * deg[:, None].astype(u.dtype)).astype(jnp.int32),
              jnp.maximum(deg[:, None] - 1, 0))
          mask = jnp.broadcast_to(deg[:, None] > 0, (block, k_h))
        else:
          # Floyd's algorithm, vectorized over the block — literally
          # ops/sample.py::_floyd_offsets on the [block] slice, so the
          # offsets are bit-identical to every other engine's draw
          cols = []
          for j in range(k_h):
            bound = jnp.maximum(deg - k_h + j, 0)
            t = jnp.minimum(
                (u[:, j] * (bound + 1).astype(u.dtype)).astype(
                    jnp.int32), bound)
            if cols:
              prev_cols = jnp.stack(cols, axis=1)
              dup = jnp.any(prev_cols == t[:, None], axis=1)
            else:
              dup = jnp.zeros((block,), bool)
            cols.append(jnp.where(dup, bound, t))
          sampled = jnp.stack(cols, axis=1)
          off = jnp.where((deg <= k_h)[:, None], iota_k, sampled)
          mask = iota_k < jnp.minimum(deg, k_h)[:, None]

        # hub fix-up, per row: degree > W rows read their exact edge
        # slots element-wise (no hub list, no cap — every hub row in
        # the frontier is fixed, the per-hop engines' clamped-cap
        # guarantee strengthened to unconditional)
        for j in range(block):
          deg_j = deg[j]
          st_j = ipv[j, 0]

          @pl.when(deg_j > width)
          def _(j=j, st_j=st_j):
            for kk in range(k_h):
              sl = jnp.clip(st_j + off[j, kk], 0,
                            max(num_edges - 1, 0))
              for a in range(n_a):
                pltpu.make_async_copy(src_refs[a].at[pl.ds(sl, 1)],
                                      hub_bufs[a].at[j, pl.ds(kk, 1)],
                                      hubsems[a].at[j, kk]).start()
            for kk in range(k_h):
              for a in range(n_a):
                pltpu.make_async_copy(
                    src_refs[a].at[pl.ds(0, 1)],
                    hub_bufs[a].at[j, pl.ds(kk, 1)],
                    hubsems[a].at[j, kk]).wait()

        woff = jnp.minimum(off, width - 1)
        iota3 = jax.lax.broadcasted_iota(jnp.int32, (block, k_h, width),
                                         2)
        onehot = iota3 == woff[:, :, None]
        is_hub = deg > width
        merged = []
        for a in range(n_a):
          win = win_bufs[a][cur]                         # [block, W]
          zero = jnp.zeros((), win.dtype)
          p = jnp.sum(jnp.where(onehot, win[:, None, :], zero),
                      axis=-1)
          hubfix = hub_bufs[a][...][:, :k_h].astype(win.dtype)
          merged.append(jnp.where(is_hub[:, None], hubfix, p))

        # next block's dependent chain resolves NOW, so its window DMAs
        # overlap the probe section below — the one DMA pipeline that
        # serves every hop
        @pl.when(has_next)
        def _(hop=hop):
          wait_frontier(hop, nxt)
          start_ip(nxt)
          wait_ip(nxt)
          start_windows(nxt)

        # dedup stage against the walk-resident table, slot order
        base = base_ref[0]
        r = r_ref[0]
        picks0 = merged[0]
        lab_rows, new_rows = [], []
        for j in range(block):
          labs_k, newh_k = [], []
          for kk in range(k_h):
            x = picks0[j, kk].astype(jnp.int32)
            v = mask[j, kk]
            lab, is_new = _probe_insert(tids, tlabs, x, v, base + r,
                                        n_buckets, lane)
            labs_k.append(lab)
            newh_k.append(is_new)
            r = r + is_new
          lab_rows.append(jnp.stack(labs_k))
          new_rows.append(jnp.stack(newh_k))
        r_ref[0] = r
        lab_mat = jnp.stack(lab_rows)
        new_mat = jnp.stack(new_rows)

        picks_refs[hop][...] = picks0
        if with_eids:
          eidp_refs[hop][...] = merged[1]
        prov_refs[hop][...] = lab_mat
        newh_refs[hop][...] = new_mat

        if hop < n_hops - 1:
          # stage the next hop's frontier: first occurrences keep their
          # id, everything else reads the sentinel row — exactly the
          # where(new_head, ids, INT32_MAX) frontier of the sort engine
          fscrs[hop][...] = jnp.where(new_mat != 0,
                                      picks0.astype(jnp.int32), big)
          dst = fp_refs[hop].at[pl.ds(b * block, block), :]
          pltpu.make_async_copy(fscrs[hop], dst, fpsem.at[0]).start()
          pltpu.make_async_copy(fscrs[hop], dst, fpsem.at[0]).wait()

    # the walk's full output surface is the per-hop blocked outputs;
    # nothing else leaves the kernel — in particular the table planes
    # never touch HBM

  def out_map(h):
    step0, nb = h['step0'], h['nb']
    return lambda i, *_: (jnp.clip(i - step0, 0, nb - 1), 0)

  in_specs = (
      [pl.BlockSpec((block, h['k']), out_map(h)) for h in hops]   # u
      + [pl.BlockSpec(memory_space=pl.ANY)] * (3 + n_a))
  out_specs = []
  out_shapes = []
  for fam_dtype in ([a.dtype for a in arrs]
                    + [jnp.int32, jnp.int32]):
    for h in hops:
      out_specs.append(pl.BlockSpec((block, h['k']), out_map(h)))
      out_shapes.append(
          jax.ShapeDtypeStruct((h['s_pad'], h['k']), fam_dtype))
  # frontier staging buffers (ANY, explicit DMA): one per hop boundary
  n_fp = max(n_hops - 1, 1)
  for t in range(n_fp):
    h = hops[min(t, n_hops - 1)]
    out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    out_shapes.append(
        jax.ShapeDtypeStruct((h['s_pad'], h['k']), jnp.int32))

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=3,
      grid=(total_steps,),
      in_specs=in_specs,
      out_specs=out_specs,
      scratch_shapes=(
          [pltpu.VMEM((2, block, 1), jnp.int32),       # vf
           pltpu.VMEM((2, block, 1), jnp.int32),       # vok
           pltpu.VMEM((2, block, 2), jnp.int32)]       # vip
          + [pltpu.VMEM((2, block, width), a.dtype) for a in arrs]
          + [pltpu.VMEM((block, k_max), a.dtype) for a in arrs]
          + [pltpu.VMEM((block, hops[t]['k']), jnp.int32)
             for t in range(n_fp)]                     # fscr per hop
          + [pltpu.VMEM(tshape, jnp.int32),            # tids
             pltpu.VMEM(tshape, jnp.int32),            # tlabs
             pltpu.SMEM((1,), jnp.int32),              # r
             pltpu.SemaphoreType.DMA((2, block)),      # fsem
             pltpu.SemaphoreType.DMA((2, block)),      # oksem
             pltpu.SemaphoreType.DMA((2, block))]      # ipsem
          + [pltpu.SemaphoreType.DMA((2, block)) for _ in arrs]
          + [pltpu.SemaphoreType.DMA((block, k_max)) for _ in arrs]
          + [pltpu.SemaphoreType.DMA((1,))]),          # fpsem
  )
  _count_launch()
  outs = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=out_shapes,
      interpret=interpret,
  )(seed_tab_ids, seed_tab_labs, base_count, *u_hops,
    indptr_pad, seed_ids, seed_ok, *arrs)
  picks = tuple(outs[:n_hops])
  pos = n_hops
  if with_eids:
    eidp = tuple(outs[pos:pos + n_hops])
    pos += n_hops
  else:
    eidp = None
  prov = tuple(outs[pos:pos + n_hops]); pos += n_hops
  newh = tuple(outs[pos:pos + n_hops])
  return picks, eidp, prov, newh

"""Induced-subgraph extraction over a node set.

Reference: csrc/cuda/subgraph_op.cu (hash-insert nodes, count edges whose
dst is in the set with a warp reduce, prefix-scan, emit relabeled COO).
TPU formulation: the node set is deduped with :func:`ordered_unique`; each
node's neighbor window (capped at ``max_degree``) is gathered, membership
of the endpoint in the set is a fixed-depth binary search over the *sorted*
unique node list, and the relabeled COO comes out padded [U, max_degree]
with a mask — compaction happens only if the caller asks for it.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .unique import ordered_unique


class SubGraph(NamedTuple):
  """Reference py_export_glt.cc:77-82 SubGraph{nodes,rows,cols,eids}, in
  padded layout."""
  nodes: jax.Array       # [U_cap] unique input nodes, -1 padded
  node_count: jax.Array  # scalar
  rows: jax.Array        # [U_cap * D] relabeled src
  cols: jax.Array        # [U_cap * D] relabeled dst
  eids: jax.Array        # [U_cap * D]
  edge_mask: jax.Array   # [U_cap * D]


def _searchsorted_in_set(sorted_set: jax.Array, set_count: jax.Array,
                         queries: jax.Array):
  """Position of each query in the ascending ``sorted_set`` (padded with
  int-max); returns (pos, found)."""
  pos = jnp.searchsorted(sorted_set, queries)
  cap = sorted_set.shape[0]
  at = jnp.take(sorted_set, jnp.clip(pos, 0, cap - 1), mode='clip')
  found = (pos < set_count) & (at == queries)
  return pos, found


def induced_subgraph(
    indptr: jax.Array,
    indices: jax.Array,
    srcs: jax.Array,
    src_mask: jax.Array,
    node_capacity: int,
    max_degree: int,
    edge_ids: Optional[jax.Array] = None,
    with_edge: bool = True,
) -> SubGraph:
  """NodeSubGraph(srcs, with_edge) equivalent (subgraph_op.cu:34-117).

  Labels follow first-occurrence order of ``srcs`` (matching the
  reference's inducer-based relabeling). ``max_degree`` must bound the
  degree of every node in the set for exact extraction.
  """
  uniq, count, _ = ordered_unique(srcs, src_mask, node_capacity)
  node_valid = jnp.arange(node_capacity) < count

  # membership structure: sort unique ids ascending (-1 pads -> int max)
  big = jnp.iinfo(uniq.dtype).max
  masked = jnp.where(node_valid, uniq, big)
  sort_order = jnp.argsort(masked)
  sorted_ids = jnp.take(masked, sort_order)
  # label of sorted_ids[k] is sort_order[k] (position in appearance order)

  num_edges = indices.shape[0]
  start = jnp.take(indptr, jnp.clip(uniq, 0, None), mode='clip')
  win = jnp.arange(max_degree, dtype=jnp.int32)[None, :]
  deg = (jnp.take(indptr, jnp.clip(uniq, 0, None) + 1, mode='clip')
         - start).astype(jnp.int32)
  deg = jnp.where(node_valid, deg, 0)
  slot_valid = win < deg[:, None]                       # [U, D]
  slots = jnp.clip(start[:, None] + win.astype(start.dtype),
                   0, max(num_edges - 1, 0))
  nbr = jnp.take(indices, slots, mode='clip')           # [U, D] global ids
  pos, found = _searchsorted_in_set(sorted_ids, count, nbr.reshape(-1))
  nbr_label = jnp.take(sort_order, jnp.clip(pos, 0, node_capacity - 1),
                       mode='clip').astype(jnp.int32)
  edge_mask = slot_valid.reshape(-1) & found
  rows = jnp.repeat(jnp.arange(node_capacity, dtype=jnp.int32), max_degree)
  cols = jnp.where(edge_mask, nbr_label.reshape(-1), -1)
  rows = jnp.where(edge_mask, rows, -1)
  if with_edge:
    eids = (jnp.take(edge_ids, slots, mode='clip') if edge_ids is not None
            else slots).reshape(-1)
    eids = jnp.where(edge_mask, eids, -1)
  else:
    eids = jnp.full((node_capacity * max_degree,), -1, jnp.int32)
  return SubGraph(nodes=uniq, node_count=count, rows=rows, cols=cols,
                  eids=eids, edge_mask=edge_mask)

"""Negative edge sampling with vectorized strict-mode rejection.

Reference: csrc/cuda/random_negative_sampler.cu (uniform (row,col)
proposals; strict mode rejects existing edges via per-thread binary search
EdgeInCSR, retries ``trials_num`` times, compacts hits with thrust
copy_if, pads with non-strict samples). TPU translation (SURVEY.md §7):
all ``trials_num`` rounds are drawn at once, membership is a fixed-depth
vectorized binary search over the sorted-adjacency CSR, and compaction is
a stable argsort on validity — no dynamic shapes anywhere.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def edge_in_csr(indptr: jax.Array, indices: jax.Array,
                rows: jax.Array, cols: jax.Array) -> jax.Array:
  """Vectorized membership test: does edge (rows[i] -> cols[i]) exist?

  Requires columns sorted within each row (Topology guarantees this).
  Fixed-depth lower-bound binary search (34 steps covers 2^34 edges),
  the TPU analogue of EdgeInCSR (random_negative_sampler.cu:37-54).
  """
  num_edges = indices.shape[0]
  lo = jnp.take(indptr, rows, mode='clip')
  hi = jnp.take(indptr, rows + 1, mode='clip')
  cols = cols.astype(indices.dtype)
  for _ in range(34):
    probing = lo < hi
    # overflow-safe midpoint: indptr may be int32 with values near 2^31
    mid = lo + ((hi - lo) >> 1)
    val = jnp.take(indices, jnp.clip(mid, 0, max(num_edges - 1, 0)),
                   mode='clip')
    go_right = probing & (val < cols)
    lo = jnp.where(go_right, mid + 1, lo)
    hi = jnp.where(probing & ~go_right, mid, hi)
  in_range = lo < jnp.take(indptr, rows + 1, mode='clip')
  at = jnp.take(indices, jnp.clip(lo, 0, max(num_edges - 1, 0)), mode='clip')
  return in_range & (at == cols)


class NegativeOutput(NamedTuple):
  rows: jax.Array   # [req]
  cols: jax.Array   # [req]
  mask: jax.Array   # [req] valid negatives (False only if padding=False
                    # and trials exhausted)


def random_negative_sample(
    indptr: jax.Array,
    indices: jax.Array,
    req_num: int,
    trials_num: int,
    key: jax.Array,
    num_rows: int,
    num_cols: int,
    strict: bool = True,
    padding: bool = False,
) -> NegativeOutput:
  """Sample ``req_num`` node pairs that are (in strict mode) not edges.

  Mirrors CUDARandomNegativeSampler::Sample(req_num, trials_num, padding)
  (py_export_glt.cc:198-201): propose uniform pairs, keep non-edges; with
  ``padding=True`` remaining slots are filled with (possibly-positive)
  uniform pairs so the output is always full.
  """
  t = max(trials_num, 1)
  kr, kc = jax.random.split(key)
  prop_rows = jax.random.randint(kr, (t, req_num), 0, num_rows,
                                 dtype=jnp.int32)
  prop_cols = jax.random.randint(kc, (t, req_num), 0, num_cols,
                                 dtype=jnp.int32)
  if strict:
    ok = ~edge_in_csr(indptr, indices, prop_rows.reshape(-1),
                      prop_cols.reshape(-1)).reshape(t, req_num)
  else:
    ok = jnp.ones((t, req_num), bool)
  # column i: first trial row where ok — argmax over bool picks first True
  first = jnp.argmax(ok, axis=0)                       # [req]
  any_ok = jnp.any(ok, axis=0)
  sel_rows = jnp.take_along_axis(prop_rows, first[None, :], axis=0)[0]
  sel_cols = jnp.take_along_axis(prop_cols, first[None, :], axis=0)[0]
  if padding:
    # non-strict fill from the last trial round (reference
    # sampler/negative_sampler.py:39-57 semantics)
    rows = jnp.where(any_ok, sel_rows, prop_rows[-1])
    cols = jnp.where(any_ok, sel_cols, prop_cols[-1])
    mask = jnp.ones((req_num,), bool)
  else:
    rows, cols, mask = sel_rows, sel_cols, any_ok
  return NegativeOutput(rows=rows, cols=cols, mask=mask)

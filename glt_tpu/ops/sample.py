"""Neighbor sampling primitives — static-shape, XLA-friendly.

TPU-native equivalent of the reference's fused CUDA sampling kernels
(csrc/cuda/random_sampler.cu:36-165, csrc/cpu/random_sampler.cc,
csrc/cpu/weighted_sampler.cc). Design differences, per SURVEY.md §7:

* The reference allocates exact-size outputs after a device prefix-scan
  (random_sampler.cu:284-301). XLA wants static shapes, so every seed gets
  exactly ``fanout`` output slots plus a validity mask; ``nbrs_num``
  becomes ``mask.sum(-1)``.
* The reference's warp-per-row reservoir sampling with atomicMax ordering
  (random_sampler.cu:59-109) is replaced by **Floyd's algorithm**: K
  rounds of (draw, collision->swap-in-boundary) per seed. Same
  uniform-without-replacement distribution, no atomics, fully vectorized
  over the seed batch on the VPU; K is static and small so the loop
  unrolls into straight-line vector code.
* Weighted sampling (CPU-only upstream, weighted_sampler.cc:26-79) is done
  device-side via Gumbel-top-k over a degree-capped neighbor window —
  weight-proportional sampling *without replacement* in one vectorized
  top_k.

All functions are jit-safe and shard_map-safe (pure gathers + elementwise).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class NeighborOutput(NamedTuple):
  """One-hop sampling result (reference sampler/base.py NeighborOutput),
  in padded layout: every field is [S, K]."""
  nbrs: jax.Array        # neighbor node ids, undefined where ~mask
  mask: jax.Array        # bool validity
  eids: jax.Array        # edge ids (compressed-slot or original), if requested

  @property
  def nbrs_num(self) -> jax.Array:
    return self.mask.sum(axis=-1)


def _empty_output(s: int, width: int, indices, edge_ids,
                  indptr) -> 'NeighborOutput':
  """All-masked output for a zero-edge graph; dtypes follow the same
  contract as the non-empty paths (nbrs: indices.dtype, eids:
  edge_ids.dtype, or int32 — slot planes are int32 throughout the hot
  path)."""
  eid_dtype = edge_ids.dtype if edge_ids is not None else jnp.int32
  return NeighborOutput(nbrs=jnp.zeros((s, width), indices.dtype),
                        mask=jnp.zeros((s, width), bool),
                        eids=jnp.full((s, width), -1, eid_dtype))


def _floyd_offsets(deg: jax.Array, u: jax.Array, fanout: int) -> jax.Array:
  """Floyd's uniform sampling of `fanout` distinct offsets from [0, deg).

  Valid only where deg >= fanout (caller selects). u: [fanout, S] uniforms.
  """
  s = deg.shape[0]
  chosen = jnp.zeros((s, fanout), jnp.int32)
  for j in range(fanout):
    bound = deg - fanout + j           # draw from [0, bound] inclusive
    bound = jnp.maximum(bound, 0)
    t = jnp.minimum((u[j] * (bound + 1).astype(u.dtype)).astype(jnp.int32),
                    bound)
    if j > 0:
      dup = jnp.any(chosen[:, :j] == t[:, None], axis=1)
    else:
      dup = jnp.zeros((s,), bool)
    pick = jnp.where(dup, bound, t)
    chosen = chosen.at[:, j].set(pick)
  return chosen


def _hop_degrees(indptr, seeds, seed_mask):
  """Window start + masked degree per frontier row — the shared prefix
  of every engine's draw. Factored out so the cross-hop walk's XLA-side
  mask recomputation (ops/pipeline.py::_multihop_sample_walk) uses the
  LITERAL same clip/mask semantics as the draw it mirrors."""
  start = jnp.take(indptr, seeds, mode='clip')
  end = jnp.take(indptr, seeds + 1, mode='clip')
  deg = (end - start).astype(jnp.int32)
  if seed_mask is not None:
    deg = jnp.where(seed_mask, deg, 0)
  return start, deg


def hop_valid_mask(indptr, seeds, fanout, seed_mask, replace):
  """The draw's validity mask WITHOUT the offset draw: [S, K] lanes
  valid exactly where :func:`_draw_hop` would mark them. The cross-hop
  walk kernel computes its masks on-chip from the same degree formula;
  this recomputation (two [S] gathers) is what the XLA side uses for
  ``edge_mask`` so both derive from one definition."""
  seeds = seeds.astype(indptr.dtype)
  _, deg = _hop_degrees(indptr, seeds, seed_mask)
  if replace:
    return jnp.broadcast_to(deg[:, None] > 0, (seeds.shape[0], fanout))
  iota = jnp.arange(fanout, dtype=jnp.int32)[None, :]
  return iota < jnp.minimum(deg, fanout)[:, None]


def _draw_hop(indptr, seeds, fanout, key, seed_mask, replace):
  """The one uniform-hop offset draw shared by EVERY hop engine: degree
  window, Floyd/replace offsets, validity mask, absolute edge slots.
  Keeping this in one place is what makes the engines bit-identical —
  they differ only in WHERE neighbor values are read from."""
  start, deg = _hop_degrees(indptr, seeds, seed_mask)
  iota = jnp.arange(fanout, dtype=jnp.int32)[None, :]    # [1, K]
  if replace:
    u = jax.random.uniform(key, (seeds.shape[0], fanout))
    offsets = jnp.minimum((u * deg[:, None]).astype(jnp.int32),
                          jnp.maximum(deg[:, None] - 1, 0))
    mask = jnp.broadcast_to(deg[:, None] > 0, offsets.shape)
  else:
    u = jax.random.uniform(key, (fanout, seeds.shape[0]))
    sampled = _floyd_offsets(deg, u, fanout)
    exhaustive = jnp.broadcast_to(iota, sampled.shape)
    offsets = jnp.where((deg <= fanout)[:, None], exhaustive, sampled)
    mask = iota < jnp.minimum(deg, fanout)[:, None]
  return start, deg, offsets, mask


def _hub_fixup_inputs(deg, slots, w_width, n_hub, fanout, s):
  """Hub row indices + exact edge slots for the Pallas kernels' tail
  pass (shared by the ``pallas`` and ``pallas_fused`` engines)."""
  if n_hub > 0 and s > 0:
    hub_idx = jnp.nonzero(deg > w_width, size=n_hub,
                          fill_value=-1)[0].astype(jnp.int32)
    hub_slots = jnp.take(slots, jnp.maximum(hub_idx, 0),
                         axis=0).astype(jnp.int32)           # [H, K]
  else:  # static dummy row: -1 never matches a block
    hub_idx = jnp.full((1,), -1, jnp.int32)
    hub_slots = jnp.zeros((1, fanout), jnp.int32)
  return hub_idx, hub_slots


def _slots_i32(start, offsets, num_edges):
  """Absolute edge slots, narrowed to int32 — half the index bytes on
  the hot path. The narrowing is only sound while the edge count fits
  int32; ``num_edges`` is static, so the guard is a free trace-time
  assert that fails LOUDLY instead of letting slots wrap to negative
  (which take-clip would silently clamp to edge 0 — corrupt samples)."""
  assert num_edges < 2 ** 31, (
      f'{num_edges} edges exceed the int32 slot range: the hot-path '
      'slot planes are int32 by design — shard the graph (the '
      'distributed partitioner splits well before 2^31 edges/shard)')
  return jnp.clip(start[:, None] + offsets.astype(start.dtype),
                  0, max(num_edges - 1, 0)).astype(jnp.int32)


def _gather_row_windows(src: jax.Array, start: jax.Array,
                        width: int) -> jax.Array:
  """[S, width] contiguous slice per row: win[s, j] = src[start[s] + j].

  One gather descriptor per ROW instead of per element — on TPU this
  lowers to per-row DMA of a contiguous run, the memory-access shape the
  hardware is good at (vs the per-element random access of
  ``jnp.take(src, slots)``). ``src`` must carry >= width slots of
  padding past the last real element: CLIP mode clamps the *start* of an
  out-of-range slice, which would silently shift tail windows on an
  unpadded array (same contract as ops/pallas_kernels.py).
  """
  import jax.lax as lax
  return lax.gather(
      src, start[:, None].astype(jnp.int32),
      lax.GatherDimensionNumbers(
          offset_dims=(1,), collapsed_slice_dims=(),
          start_index_map=(0,)),
      slice_sizes=(width,), mode=lax.GatherScatterMode.CLIP)


def sample_neighbors(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    fanout: int,
    key: jax.Array,
    seed_mask: Optional[jax.Array] = None,
    edge_ids: Optional[jax.Array] = None,
    replace: bool = False,
    window: Optional[tuple] = None,
    indices_win: Optional[jax.Array] = None,
    edge_ids_win: Optional[jax.Array] = None,
    engine: Optional[str] = None,
    interpret: bool = False,
) -> NeighborOutput:
  """Uniformly sample up to ``fanout`` neighbors per seed from a CSR/CSC.

  fanout == -1 is not supported here (full neighborhood is the subgraph
  op's job); fanout must be a static positive int.

  Returns padded [S, fanout] neighbors + mask; when a seed's degree is
  <= fanout the sample is exhaustive and in adjacency order (which makes
  tiny-graph tests exact, the reference test strategy SURVEY.md §4).

  ``window=(W, H)`` enables the TPU window read path: neighbor values
  are read from a [S, W] contiguous per-row window (one DMA per row —
  see :func:`_gather_row_windows`) instead of a [S, fanout] per-element
  random gather, with the up-to-``H`` hub rows (degree > W) fixed up by
  an exact [H, fanout] element gather. Offsets are drawn identically in
  both paths, so results are BIT-IDENTICAL to the element path provided
  ``H >= number of hub ROWS in the frontier`` (a hub node occurring
  twice needs two fix-up slots) — the samplers derive H from the
  graph's true hub count (host-side, once), which bounds the row count
  because their internal frontiers are deduplicated/masked, so the
  guarantee is unconditional there; direct callers passing frontiers
  with duplicate hub ids must size H for the duplicates. An EAGER call
  (concrete arrays, outside jit) with an
  undersized H raises ValueError, while traced calls keep the
  documented confinement (only unfixed hub rows deviate). Requires
  ``indices_win``: the same indices array with >= W trailing padding
  slots (Graph.window_arrays / a one-time host pad); ``edge_ids_win``
  likewise when ``edge_ids`` is passed.

  ``engine`` picks the window-read implementation (see
  ops/pipeline.py::hop_engine): ``'window'`` (default when ``window``
  is given) keeps the XLA slice-gather path; ``'pallas'`` routes the
  window read + offset pick + hub fix-up through the fused one-hop
  megakernel (ops/pallas_kernels.py::sample_hop, ``interpret`` for
  off-TPU parity runs); ``'element'`` ignores ``window``. Offsets come
  from the same draw in every engine, so outputs stay bit-identical.
  """
  assert fanout > 0, 'fanout must be a static positive int'
  if engine is None:
    engine = 'window' if window is not None else 'element'
  if engine == 'pallas_fused':
    # the dedup fusion only engages through the pipeline entry point
    # (FusedHopPlan / multihop_sample); a plain NeighborOutput call
    # reads windows through the same megakernel machinery as 'pallas'
    engine = 'pallas'
  assert engine in ('element', 'window', 'pallas'), engine
  if engine == 'element':
    window = None
  else:
    assert window is not None, f"engine={engine!r} needs window=(W, H)"
  seeds = seeds.astype(indptr.dtype)
  num_edges = indices.shape[0]
  if num_edges == 0:  # legitimately empty (e.g. a rare-etype partition)
    return _empty_output(seeds.shape[0], fanout, indices, edge_ids,
                         indptr)
  start, deg, offsets, mask = _draw_hop(indptr, seeds, fanout, key,
                                        seed_mask, replace)
  # int32 everywhere edge slots flow: a shard's edge count fits int32
  # by construction in this stack (the partitioner splits well before
  # 2^31 edges/shard), so an int64 indptr must not widen the [S, K]
  # slot/eid planes it feeds — half the index bytes on the hot path
  slots = _slots_i32(start, offsets, num_edges)
  if window is not None:
    w_width, n_hub = window
    assert indices_win is not None, (
        'window read path needs indices_win (W-padded indices); pass '
        'Graph.window_arrays()["indices"] or pad host-side once')
    if not isinstance(deg, jax.core.Tracer):
      # eager call: the docstring guarantee is checkable — fail loudly
      # instead of silently truncating hub rows past the H capacity
      true_hubs = int((deg > w_width).sum())
      if true_hubs > n_hub:
        raise ValueError(
            f'window=(W={w_width}, H={n_hub}) underestimates the '
            f'frontier hub count: {true_hubs} ROWS have degree > W '
            '(a repeated hub seed counts once per occurrence). '
            'Graph.hub_count(W) bounds this for deduplicated/masked '
            'frontiers — the samplers\' internal hops; raise H to the '
            'frontier size for duplicate-bearing eager calls.')
    if engine == 'pallas':
      from .pallas_kernels import sample_hop
      assert edge_ids is None or edge_ids_win is not None, (
          'pallas engine with edge_ids needs edge_ids_win (the W-padded '
          'edge-id array, Graph.window_arrays()["edge_ids"])')
      hub_idx, hub_slots = _hub_fixup_inputs(deg, slots, w_width, n_hub,
                                             fanout, seeds.shape[0])
      nbrs, eid_picks = sample_hop(
          indices_win, edge_ids_win if edge_ids is not None else None,
          start.astype(jnp.int32), offsets, hub_idx, hub_slots,
          width=w_width, interpret=interpret)
      eids = eid_picks if edge_ids is not None else slots
      return NeighborOutput(nbrs=nbrs, mask=mask, eids=eids)
    win = _gather_row_windows(indices_win, start, w_width)   # [S, W]
    woff = jnp.minimum(offsets, w_width - 1)
    nbrs = jnp.take_along_axis(win, woff, axis=1)
    if edge_ids is not None:
      ewin = _gather_row_windows(edge_ids_win, start, w_width)
      eids = jnp.take_along_axis(ewin, woff, axis=1)
    else:
      eids = slots
    if n_hub > 0 and seeds.shape[0] > 0:
      # exact fix-up: element-gather only the hub rows
      hub_idx = jnp.nonzero(deg > w_width, size=n_hub,
                            fill_value=0)[0]                 # [H]
      hub_ok = jnp.take(deg, hub_idx) > w_width              # fill rows F
      hub_slots = jnp.take(slots, hub_idx, axis=0)           # [H, K]
      hub_vals = jnp.take(indices, hub_slots, mode='clip')
      nbrs = nbrs.at[hub_idx].set(
          jnp.where(hub_ok[:, None], hub_vals,
                    jnp.take(nbrs, hub_idx, axis=0)))
      if edge_ids is not None:
        hub_eids = jnp.take(edge_ids, hub_slots, mode='clip')
        eids = eids.at[hub_idx].set(
            jnp.where(hub_ok[:, None], hub_eids,
                      jnp.take(eids, hub_idx, axis=0)))
    return NeighborOutput(nbrs=nbrs, mask=mask, eids=eids)
  nbrs = jnp.take(indices, slots, mode='clip')
  eids = jnp.take(edge_ids, slots, mode='clip') if edge_ids is not None \
      else slots
  return NeighborOutput(nbrs=nbrs, mask=mask, eids=eids)


_BIG_I32 = jnp.iinfo(jnp.int32).max


def walk_hop_uniforms(key, batch_size, fanouts, replace, block=8):
  """Per-hop uniform draws for the cross-hop walk kernel, from the SAME
  key sequence as the per-hop loop (``key, sub = split(key)`` per hop,
  ``uniform(sub, (K, S))`` for Floyd / ``(S, K)`` for replace — see
  :func:`_draw_hop`). The draws are data-independent, which is what
  lets the whole walk's randomness be staged up front while the
  frontier itself is produced on-chip. Returned in the kernel's
  [S_pad, K] row-major orientation (Floyd draws transposed), rows
  block-padded with zeros."""
  from .pallas_kernels import walk_geometry
  hops, _ = walk_geometry(batch_size, fanouts, block)
  us = []
  for h in hops:
    key, sub = jax.random.split(key)
    if replace:
      u = jax.random.uniform(sub, (h['s'], h['k']))
    else:
      u = jax.random.uniform(sub, (h['k'], h['s'])).T
    us.append(jnp.pad(u, ((0, h['s_pad'] - h['s']), (0, 0))))
  return tuple(us)


def _value_order_ranks(ids_flat, new_head, prov_rank, m):
  """The value-order relabel core shared by the per-hop fused wrapper
  and the cross-hop walk: given a hop's fresh-id heads (``new_head``),
  their within-hop first-occurrence ranks (``prov_rank``) and ids,
  return ``(sorted_ids, val_rank)`` where ``sorted_ids`` is the fresh
  unique ids ascending (_BIG padded — the fused feature gather consumes
  these directly) and ``val_rank[first_occurrence_rank] = value rank``.
  One 2-operand sort over [M] — the only sort in a fused hop."""
  first_rank = jnp.where(new_head, prov_rank, m)        # pads -> sink
  new_by_rank = jnp.full((m + 1,), _BIG_I32, jnp.int32).at[
      first_rank].set(jnp.where(new_head, ids_flat, _BIG_I32))[:m]
  iota = jnp.arange(m, dtype=jnp.int32)
  sorted_ids, sorted_rank = jax.lax.sort([new_by_rank, iota],
                                         num_keys=1)
  val_rank = jnp.zeros((m + 1,), jnp.int32).at[
      jnp.where(sorted_ids < _BIG_I32, sorted_rank, m)].set(iota)[:m]
  return sorted_ids, val_rank


def sample_neighbors_fused(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    fanout: int,
    key: jax.Array,
    tab_ids: jax.Array,
    tab_labs: jax.Array,
    count: jax.Array,
    seed_mask: Optional[jax.Array] = None,
    edge_ids: Optional[jax.Array] = None,
    replace: bool = False,
    window: Optional[tuple] = None,
    indices_win: Optional[jax.Array] = None,
    edge_ids_win: Optional[jax.Array] = None,
    interpret: bool = False,
):
  """One FUSED hop: sample + dedup/relabel in a single kernel pass (the
  ``pallas_fused`` engine, ops/pipeline.py::hop_engine).

  Sampling offsets come from :func:`_draw_hop` — the same draw as every
  other engine — and the picks, the ``[S, W]`` windows, and the dedup
  probes all stay inside ``sample_hop_dedup``'s VMEM. The kernel emits
  PROVISIONAL labels (first-occurrence order); this wrapper restores
  the exact :func:`glt_tpu.ops.unique.sorted_hop_dedup_fused` contract
  — new ids labeled ``count..count+n-1`` in within-hop VALUE order,
  seen ids keeping their labels — with one single-payload sort over the
  fresh unique ids, and rewrites the table's labels to match so the
  NEXT hop's probes return final labels.

  Returns ``(out, d, (tab_ids', tab_labs'))`` where ``out`` is the
  usual :class:`NeighborOutput` and ``d`` carries (all slot-order,
  shapes ``[S*K]`` unless noted):

    labels3 / new_head3 / count2 / new_count : exactly
      ``sorted_hop_dedup_fused``'s fields;
    sorted_new_ids : [S*K] the fresh unique ids ASCENDING (= label
      order ``count..count+new_count-1``), _BIG padded — the fused
      feature gather consumes these directly.
  """
  assert fanout > 0, 'fanout must be a static positive int'
  assert window is not None and indices_win is not None, (
      'the fused engine always reads through windows; pass window=(W, '
      'H) and the W-padded indices (Graph.window_arrays)')
  from .pallas_kernels import sample_hop_dedup
  w_width, n_hub = window
  seeds = seeds.astype(indptr.dtype)
  s = seeds.shape[0]
  m = s * fanout
  num_edges = indices.shape[0]
  if num_edges == 0:  # legitimately empty graph: nothing dedups
    out = _empty_output(s, fanout, indices, edge_ids, indptr)
    d = dict(labels3=jnp.full((m,), -1, jnp.int32),
             new_head3=jnp.zeros((m,), bool),
             count2=count, new_count=jnp.zeros((), jnp.int32),
             sorted_new_ids=jnp.full((m,), _BIG_I32, jnp.int32))
    return out, d, (tab_ids, tab_labs)
  start, deg, offsets, mask = _draw_hop(indptr, seeds, fanout, key,
                                        seed_mask, replace)
  slots = _slots_i32(start, offsets, num_edges)
  assert edge_ids is None or edge_ids_win is not None, (
      'fused engine with edge_ids needs edge_ids_win (the W-padded '
      'edge-id array, Graph.window_arrays()["edge_ids"])')
  hub_idx, hub_slots = _hub_fixup_inputs(deg, slots, w_width, n_hub,
                                         fanout, s)
  picks, eid_picks, prov, new_head, tab_ids, tab_labs = \
      sample_hop_dedup(
          indices_win, edge_ids_win if edge_ids is not None else None,
          start.astype(jnp.int32), offsets, mask, hub_idx, hub_slots,
          tab_ids, tab_labs, count, width=w_width, interpret=interpret)
  eids = eid_picks if edge_ids is not None else slots
  out = NeighborOutput(nbrs=picks, mask=mask, eids=eids)

  # value-order relabel: kernel labels are first-occurrence ranks; the
  # sorted_hop_dedup_fused contract ranks fresh ids by VALUE. One
  # 2-operand sort over [M] — narrower than the engine it replaces
  # (3 operands over [C+M]) and the only sort left in the fused hop.
  ids_flat = picks.reshape(-1).astype(jnp.int32)
  m_flat = mask.reshape(-1)
  prov_flat = prov.reshape(-1)
  nh = new_head.reshape(-1) != 0
  sorted_ids, val_rank = _value_order_ranks(ids_flat, nh,
                                            prov_flat - count, m)
  is_new_el = m_flat & (prov_flat >= count)
  labels3 = jnp.where(
      is_new_el,
      count + jnp.take(val_rank, jnp.clip(prov_flat - count, 0, m - 1)),
      prov_flat)
  new_count = nh.sum(dtype=jnp.int32)
  # table fix-up: this hop's inserts carry provisional labels >= count;
  # map them through the same rank table so the next hop probes final
  tab_labs = jnp.where(
      (tab_ids >= 0) & (tab_labs >= count),
      count + jnp.take(val_rank, jnp.clip(tab_labs - count, 0, m - 1)),
      tab_labs)
  d = dict(labels3=labels3, new_head3=nh, count2=count + new_count,
           new_count=new_count, sorted_new_ids=sorted_ids)
  return out, d, (tab_ids, tab_labs)


class FusedHopPlan:
  """Trace-time bundle for the ``pallas_fused`` engine: the graph's
  window-padded edge arrays, the static window/hub/table geometry, and
  (optionally) the fused feature-gather closure. Built once per
  compiled multihop program (sampler/neighbor_sampler.py, bench.py) and
  consumed by :func:`glt_tpu.ops.pipeline.multihop_sample` — the plan
  is what routes the hop loop through :func:`sample_neighbors_fused`
  instead of the ``one_hop`` + sort-dedup pair.

  Args:
    indptr / indices: the CSR (device-resident).
    indices_win: W-padded indices (Graph.window_arrays contract).
    width: window width W.
    hub_count: the graph's true hub-row count for W (Graph.hub_count) —
      clamped per hop to the frontier size, like the other engines.
    table_slots: dedup-table capacity in id slots
      (pallas_kernels.fused_table_slots(budget); must exceed the walk's
      node budget so probes terminate).
    edge_ids / edge_ids_win: optional edge-id plane.
    gather_fn: optional ``ids [m] -> rows [m, D]`` feature row gather
      (Feature.fused_gather_fn) — set, the pipeline gathers each hop's
      fresh rows while the walk is still running and emits
      ``node_feats`` alongside the sample.
    feat_dim / feat_dtype: static output geometry for ``gather_fn``.
      ``feat_dtype`` may NARROW the store dtype (the opt-in bf16 gather
      plane, ``GLT_FUSED_FEAT_DTYPE=bfloat16``): the in-walk plane and
      the emitted ``node_feats`` then carry the narrow dtype, halving
      the gather's HBM write traffic — parity with the post-hoc
      ``gather_features`` holds after casting the reference (documented
      precision trade, default off).
    indptr_pad: optional [N + 2] int32 CSR offsets with a trailing
      ``num_edges`` sentinel — the cross-hop walk kernel's row-window
      source (see ``sample_walk_dedup``). Built eagerly here when not
      passed (plans are constructed outside jit, so the pad is a
      one-time host/device op, never a leaked tracer).
  """

  def __init__(self, indptr, indices, indices_win, width, hub_count,
               table_slots, edge_ids=None, edge_ids_win=None,
               replace=False, interpret=False, gather_fn=None,
               feat_dim=None, feat_dtype=None, indptr_pad=None):
    self.indptr = indptr
    self.indices = indices
    self.indices_win = indices_win
    self.width = int(width)
    self.hub_count = int(hub_count)
    self.table_slots = int(table_slots)
    self.edge_ids = edge_ids
    self.edge_ids_win = edge_ids_win
    self.replace = bool(replace)
    self.interpret = bool(interpret)
    self.gather_fn = gather_fn
    self.feat_dim = feat_dim
    self.feat_dtype = feat_dtype
    if indptr_pad is None:
      num_edges = int(indices.shape[0])
      indptr_pad = jnp.concatenate(
          [jnp.asarray(indptr, jnp.int32),
           jnp.full((1,), num_edges, jnp.int32)])
    self.indptr_pad = indptr_pad

  def init_table(self, ids, labs, valid):
    """Fresh table planes seeded with the exact-dedup'd seed hop."""
    from .pallas_kernels import dedup_table_insert, make_dedup_table
    tab_ids, tab_labs = make_dedup_table(self.table_slots)
    return dedup_table_insert(tab_ids, tab_labs, ids, labs, valid,
                              interpret=self.interpret)

  def __call__(self, frontier_ids, fanout, key, mask, table, count):
    tab_ids, tab_labs = table
    out, d, table = sample_neighbors_fused(
        self.indptr, self.indices, frontier_ids, fanout, key,
        tab_ids, tab_labs, count, seed_mask=mask,
        edge_ids=self.edge_ids, replace=self.replace,
        window=(self.width, min(self.hub_count, frontier_ids.shape[0])),
        indices_win=self.indices_win, edge_ids_win=self.edge_ids_win,
        interpret=self.interpret)
    return out, d, table


class HeteroFusedPlan:
  """Trace-time bundle for the ``pallas_fused`` engine over a HETERO
  graph: the flat multi-edge-type window geometry (the kernel family's
  edge-type plane, :func:`glt_tpu.ops.pallas_kernels.build_type_plane`)
  plus per-etype CSR handles and static hub/table sizing. Built once
  per compiled hetero multihop program (sampler/neighbor_sampler.py,
  bench.py) and consumed by
  :func:`glt_tpu.ops.pipeline.multihop_sample_hetero` — the plan routes
  each hop's per-edge-type sampling into ONE padded multi-edge-type
  ``sample_hop_dedup`` invocation: one concatenated frontier whose
  per-segment ``starts`` address the flat plane, per-type fanouts as
  [S, K_max] offset/validity lanes, and per-type dedup namespaces via
  the type-tagged global id space.

  Args:
    etypes: traversal-order edge types (= the reference hop loop's
      iteration order).
    trav: Dict[EdgeType, (expand_from_type, neighbor_type)].
    node_counts: Dict[NodeType, int].
    parts: Dict[EdgeType, dict(indptr, indices_win, num_edges,
      hub_count, edge_ids_win=None)] — ``indices_win`` per the
      Graph.window_arrays contract (W trailing pad slots).
    width: window width W (shared across edge types).
    table_slots: VMEM dedup-table capacity in id slots; must exceed the
      walk's TOTAL node budget across types (probe termination).
    budget_total: sum of per-type node budgets — sizes the provisional
      label remap of the XLA epilogue.
  """

  def __init__(self, etypes, trav, node_counts, parts, width,
               table_slots, budget_total, replace=False,
               interpret=False):
    from .pallas_kernels import build_type_plane
    self.etypes = list(etypes)
    self.trav = dict(trav)
    self.width = int(width)
    self.table_slots = int(table_slots)
    self.budget_total = int(budget_total)
    self.replace = bool(replace)
    self.interpret = bool(interpret)
    self.indptr = {e: parts[e]['indptr'] for e in self.etypes}
    self.num_edges = {e: int(parts[e]['num_edges'])
                      for e in self.etypes}
    self.hub_count = {e: int(parts[e].get('hub_count', 0))
                      for e in self.etypes}
    plane = build_type_plane(self.etypes, self.trav, node_counts,
                             parts, self.width)
    self.type_base = plane['type_base']
    self.edge_base = plane['edge_base']
    self.indices_flat = plane['indices_flat']
    self.eids_flat = plane['eids_flat']
    self.has_eids = plane['has_eids']

  def init_table(self, ids, labs, valid):
    """Fresh table planes seeded with the exact-dedup'd multi-type seed
    hop (ids already type-tagged, labels provisional-global)."""
    from .pallas_kernels import dedup_table_insert, make_dedup_table
    tab_ids, tab_labs = make_dedup_table(self.table_slots)
    return dedup_table_insert(tab_ids, tab_labs, ids, labs, valid,
                              interpret=self.interpret)


def sample_full_neighbors(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    max_degree: int,
    seed_mask: Optional[jax.Array] = None,
    edge_ids: Optional[jax.Array] = None,
    window_gather=None,
    window_sources: Optional[dict] = None,
) -> NeighborOutput:
  """Full-neighborhood expansion — the reference's ``fanout = -1``
  (csrc/cpu/random_sampler.cc FullSample path; examples/seal_link_pred.py
  uses ``[-1, -1]``). Every neighbor is returned in adjacency order
  inside a static ``[S, max_degree]`` window; callers pass
  ``max_degree >= graph max degree`` for exact semantics (NeighborSampler
  resolves this automatically). Degrees above the window are truncated.

  ``window_gather``/``window_sources``: optional fast path for the
  [S, max_degree] window reads (one DMA descriptor per row instead of a
  per-element slice-gather — ops/pallas_kernels.py::gather_windows).
  ``window_sources`` must hold the SAME edge arrays padded by
  ``max_degree`` trailing sentinels (Graph.window_arrays provides them);
  masked lanes read sentinel values exactly like the XLA path reads
  clipped garbage.
  """
  assert max_degree > 0
  seeds = seeds.astype(indptr.dtype)
  num_edges = indices.shape[0]
  if num_edges == 0:
    return _empty_output(seeds.shape[0], max_degree, indices, edge_ids,
                         indptr)
  start = jnp.take(indptr, seeds, mode='clip')
  end = jnp.take(indptr, seeds + 1, mode='clip')
  deg = (end - start).astype(jnp.int32)
  if seed_mask is not None:
    deg = jnp.where(seed_mask, deg, 0)
  deg = jnp.minimum(deg, max_degree)
  win = jnp.arange(max_degree, dtype=jnp.int32)[None, :]   # [1, D]
  mask = win < deg[:, None]
  if window_gather is not None:
    nbrs = window_gather(window_sources['indices'], start, max_degree)
    if edge_ids is not None:
      eids = window_gather(window_sources['edge_ids'], start, max_degree)
    else:
      eids = _slots_i32(start, win, num_edges)
    return NeighborOutput(nbrs=nbrs, mask=mask, eids=eids)
  slots = _slots_i32(start, win, num_edges)
  nbrs = jnp.take(indices, slots, mode='clip')
  eids = jnp.take(edge_ids, slots, mode='clip') if edge_ids is not None \
      else slots
  return NeighborOutput(nbrs=nbrs, mask=mask, eids=eids)


def sample_neighbors_weighted(
    indptr: jax.Array,
    indices: jax.Array,
    weights: jax.Array,
    seeds: jax.Array,
    fanout: int,
    key: jax.Array,
    max_degree: int,
    seed_mask: Optional[jax.Array] = None,
    edge_ids: Optional[jax.Array] = None,
    window_gather=None,
    window_sources: Optional[dict] = None,
) -> NeighborOutput:
  """Weight-proportional sampling without replacement via Gumbel-top-k.

  The neighbor window per seed is capped at ``max_degree`` (static): for
  hub nodes with more neighbors only the first ``max_degree`` (in
  adjacency order) participate. Pass ``max_degree >= topo.max_degree``
  for exact semantics.

  ``window_gather``/``window_sources``: optional DMA fast path for the
  [S, max_degree] weight-window read (see sample_full_neighbors).
  """
  assert fanout > 0
  assert fanout <= max_degree, (
      f'fanout ({fanout}) must be <= max_degree ({max_degree}); raise '
      'max_degree to at least the fanout')
  seeds = seeds.astype(indptr.dtype)
  num_edges = indices.shape[0]
  if num_edges == 0:
    return _empty_output(seeds.shape[0], fanout, indices, edge_ids,
                         indptr)
  start = jnp.take(indptr, seeds, mode='clip')
  end = jnp.take(indptr, seeds + 1, mode='clip')
  deg = (end - start).astype(jnp.int32)
  if seed_mask is not None:
    deg = jnp.where(seed_mask, deg, 0)
  deg = jnp.minimum(deg, max_degree)

  win = jnp.arange(max_degree, dtype=jnp.int32)[None, :]  # [1, D]
  valid = win < deg[:, None]                               # [S, D]
  if window_gather is not None:
    w = window_gather(window_sources['edge_weights'], start,
                      max_degree).astype(jnp.float32)
  else:
    slots = jnp.clip(start[:, None] + win.astype(start.dtype),
                     0, max(num_edges - 1, 0))
    w = jnp.take(weights, slots, mode='clip').astype(jnp.float32)
  w = jnp.where(valid & (w > 0), w, 0.0)
  g = -jnp.log(-jnp.log(
      jax.random.uniform(key, w.shape, minval=1e-20, maxval=1.0)))
  keys = jnp.where(w > 0, jnp.log(w) + g, -jnp.inf)
  _, top = jax.lax.top_k(keys, fanout)                    # [S, K] window idx
  top_valid = jnp.take_along_axis(keys, top, axis=1) > -jnp.inf
  off = top.astype(start.dtype)
  # int32 edge slots (see _slots_i32): the weighted path's picks were
  # the residual wide operands in the slots/labels flow
  pick = _slots_i32(start, off, num_edges)
  nbrs = jnp.take(indices, pick, mode='clip')
  eids = jnp.take(edge_ids, pick, mode='clip') if edge_ids is not None \
      else pick
  return NeighborOutput(nbrs=nbrs, mask=top_valid, eids=eids)


def neighbor_probs(
    indptr: jax.Array,
    indices: jax.Array,
    seed_probs: jax.Array,
    fanout: int,
    num_nodes: int,
) -> jax.Array:
  """Hotness propagation for FrequencyPartitioner — the CalNbrProbKernel
  equivalent (random_sampler.cu:167-209): given per-node access
  probabilities, push one hop of expected sampling probability to
  neighbors: p_nbr += p(src) * min(fanout, deg)/deg spread per neighbor.

  Edge-parallel formulation: for each edge (u -> v),
  contribution(v) = p(u) * min(fanout/deg(u), 1). A negative fanout
  (full-neighborhood hop) touches every neighbor: rate = 1.
  """
  deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
  if fanout < 0:
    rate = jnp.where(deg > 0, 1.0, 0.0)
  else:
    rate = jnp.where(deg > 0,
                     jnp.minimum(fanout / jnp.maximum(deg, 1.0), 1.0),
                     0.0)
  contrib_per_src = seed_probs * rate                     # [N]
  # expand to edges: edge e has src = row(e). ``indices`` may carry a
  # sentinel-padded tail (Graph.window_arrays supersedes the original
  # with the window-padded copy); positions at/after indptr[-1] are not
  # edges — zero their contribution and clamp the sentinel (-1) ids.
  pos = jnp.arange(indices.shape[0], dtype=indptr.dtype)
  rows = jnp.searchsorted(indptr, pos, side='right') - 1
  contrib = jnp.take(contrib_per_src, rows, mode='clip')
  contrib = jnp.where(pos < indptr[-1], contrib, 0.0)
  out = jnp.zeros((num_nodes,), jnp.float32)
  out = out.at[jnp.maximum(indices, 0)].add(contrib)
  return jnp.minimum(out, 1.0)

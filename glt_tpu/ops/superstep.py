"""Superstep: K full training steps inside one scanned dispatch.

The per-batch training loop pays one Python iteration, one host->device
seed transfer, and one jit dispatch per batch (loader/node_loader.py,
parallel/train.py). :func:`glt_tpu.ops.pipeline.multihop_sample_many`
already shows that scanning K *sampling* batches in one dispatch
amortizes that overhead; this module generalizes the same lax.scan
pattern to the WHOLE training step — sample -> feature gather ->
forward/backward -> optimizer update — with the dedup tables, params and
optimizer state threaded through the carry. Seed batches are staged on
device up front as a [T, B] stack (loader.DeviceEpochLoader), so steady
state is one dispatch per T batches and zero host round-trips on the hot
path. PyTorch-Direct (arxiv 2101.07956) and GPU-initiated direct-storage
sampling (arxiv 2306.16384) teach the same lesson on GPUs.

The per-batch body must return its dedup tables RESET (the
:func:`~glt_tpu.ops.pipeline.multihop_sample` contract), which makes
scan iterations independent: a T-step superstep is bit-identical to T
sequential calls of the same body with the same key stream.
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax

# body of one training step:
#   (params, opt_state, table, scratch, seeds, n_valid, key)
#     -> (params, opt_state, table, scratch, aux)
BatchStepFn = Callable[..., Tuple]


def superstep(batch_step: BatchStepFn, unroll: int = 1):
  """Lift a per-batch training body into a multi-batch lax.scan.

  Args:
    batch_step: one full training step (sample -> gather -> grad ->
      update). Tables must come back reset so iterations stay
      independent. ``aux`` is any pytree (typically the loss).
    unroll: forwarded to ``lax.scan`` (TPU sampling A/Bs found modest
      unrolling neutral; the knob exists for re-measurement).

  Returns ``run(params, opt_state, table, scratch, seeds_stack [T, B],
  n_valid_stack [T, ...], keys [T, ...]) -> (params, opt_state, table,
  scratch, aux_stack)`` where ``aux_stack`` carries the per-batch aux
  values stacked on a leading [T] axis. The leading axis of the three
  stacked inputs must agree; each scan iteration consumes one slice.
  """

  def body(params, opt_state, state, seeds, n_valid, key):
    table, scratch = state
    params, opt_state, table, scratch, aux = batch_step(
        params, opt_state, table, scratch, seeds, n_valid, key)
    return params, opt_state, (table, scratch), aux

  # the homo (table, scratch) pair is a special case of the pytree-
  # state lift below — ONE scan implementation serves both engines
  run_tree = superstep_hetero(body, unroll)

  def run(params, opt_state, table, scratch, seeds_stack, n_valid_stack,
          keys):
    params, opt_state, (table, scratch), aux = run_tree(
        params, opt_state, (table, scratch), seeds_stack,
        n_valid_stack, keys)
    return params, opt_state, table, scratch, aux

  return run


def superstep_hetero(batch_step: Callable, unroll: int = 1):
  """Hetero variant of :func:`superstep`: the dedup state is one opaque
  pytree (the hetero engine's per-type table dict — or, on the fused
  hetero engine, pass-through placeholders) instead of the homo
  ``(table, scratch)`` pair. Everything else is the same lax.scan
  lift: K hetero training batches (per-edge-type collective sampling +
  per-type feature exchange + RGNN update) run as ONE donated dispatch,
  bit-identical to K sequential per-batch calls on the same key stream.

  ``batch_step(params, opt_state, tables, seeds, n_valid, key) ->
  (params, opt_state, tables, aux)``; seeds/n_valid/keys carry a
  leading [T] axis (per-type seed dicts stack per leaf)."""

  def run(params, opt_state, tables, seeds_stack, n_valid_stack, keys):
    def step(carry, x):
      params, opt_state, tables = carry
      seeds, n_valid, key = x
      params, opt_state, tables, aux = batch_step(
          params, opt_state, tables, seeds, n_valid, key)
      return (params, opt_state, tables), aux

    (params, opt_state, tables), aux = jax.lax.scan(
        step, (params, opt_state, tables),
        (seeds_stack, n_valid_stack, keys), unroll=unroll)
    return params, opt_state, tables, aux

  return run


def scan_consume(consume_step: Callable, unroll: int = 1):
  """Scan a pre-staged consume body: ``consume_step(carry, x) ->
  (carry, aux)`` over stacked inputs whose sampling already ran (the
  cold-row streaming pipeline stages sampler outputs and cold feature
  rows for superstep N+1 while the chip executes superstep N; the
  consume scan then holds no dedup state — only params/opt ride the
  carry)."""

  def run(carry, xs):
    return jax.lax.scan(consume_step, carry, xs, unroll=unroll)

  return run

"""Stitching per-partition partial sample results back into seed order.

Reference: csrc/cuda/stitch_sample_results.cu:27-108 (scatter nbrs_num by
partial index lists, cumsum, copy each partial run to its global offset).
In the padded TPU layout stitching is a pure positional scatter: each
partition returns results for the seed *positions* it served, so merging
is ``out[idx_p] = part_p`` with no prefix scan at all — the reason the
reference needs one (variable-length runs) disappears with static [S, K]
blocks. Used by the SPMD distributed sampler after all_to_all returns.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def stitch_rows(idx_list: Sequence[jax.Array],
                parts: Sequence[jax.Array],
                total: int) -> jax.Array:
  """Scatter row-blocks to their global positions.

  Args:
    idx_list: per-partition [m_p] original positions (may be padded with
      -1, those rows are dropped).
    parts: per-partition [m_p, ...] row blocks.
    total: number of output rows.
  """
  first = parts[0]
  # one sacrificial row at index `total` absorbs padded (-1) positions so a
  # pad can never collide with a real row-0 write
  out = jnp.zeros((total + 1,) + first.shape[1:], first.dtype)
  for idx, part in zip(idx_list, parts):
    safe = jnp.where(idx >= 0, idx, total)
    out = out.at[safe].set(part)
  return out[:total]

"""Delta-aware one-hop sampling: base CSR + insert window - tombstones.

The live-update subsystem (:mod:`glt_tpu.stream`) keeps the hot sampling
path on an immutable, locality-sorted CSR and layers mutations on top as
two small static-shape CSR overlays:

  * an **insert overlay** of edges appended since the last compaction;
  * a **tombstone overlay** of edges deleted since the last compaction.

:func:`delta_one_hop` merges both into one hop inside the jitted
multi-hop walk: the base hop samples as usual, base lanes whose neighbor
appears in the frontier row's tombstone window are masked out, and up to
``ins_window`` delta neighbors per frontier node are appended. The
output width is ``abs(fanout) + ins_window`` — a **static** shape, so a
compiled program keeps serving unchanged across delta refreshes and
snapshot swaps (the overlay arrays are jit *arguments*, never closure
constants).

Exactness contract (what the stream tests pin):

  * full-neighborhood hops (``fanout < 0``) are exact over the effective
    adjacency ``(base \\ tombstones) ∪ inserts`` as long as each row's
    delta fits its window — identical node/edge sets to sampling the
    compacted CSR;
  * uniform hops (``fanout > 0``) draw from the base adjacency and then
    drop tombstoned picks, so rows with pending deletes see a reduced
    effective fanout until compaction (bounded-staleness approximation,
    documented in docs/streaming.md); inserted edges join the candidate
    pool via the full insert window.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .sample import (
    NeighborOutput, sample_full_neighbors, sample_neighbors,
)


def tombstone_mask(nbrs: jax.Array, mask: jax.Array,
                   del_nbrs: jax.Array,
                   del_mask: jax.Array) -> jax.Array:
  """Mask out sampled lanes whose neighbor id is tombstoned.

  nbrs/mask: [S, K] one-hop sample; del_nbrs/del_mask: [S, W] the
  per-row tombstone windows (same frontier rows). Returns the [S, K]
  validity with tombstone hits cleared. A delete of (u, v) kills every
  sampled copy of v under u — multigraph deletes are all-instances.
  """
  hit = (nbrs[:, :, None] == del_nbrs[:, None, :]) \
      & del_mask[:, None, :]                       # [S, K, W]
  return mask & ~hit.any(axis=-1)


def delta_one_hop(
    indptr: jax.Array,
    indices: jax.Array,
    ins_indptr: jax.Array,
    ins_indices: jax.Array,
    del_indptr: jax.Array,
    del_indices: jax.Array,
    frontier: jax.Array,
    fanout: int,
    key: jax.Array,
    seed_mask: Optional[jax.Array],
    ins_window: int,
    del_window: int,
    replace: bool = False,
    base_window: Optional[tuple] = None,
    indices_win: Optional[jax.Array] = None,
    engine: Optional[str] = None,
    interpret: bool = False,
) -> NeighborOutput:
  """One delta-merged hop; output width ``abs(fanout) + ins_window``.

  Args:
    indptr/indices: base CSR/CSC (indices may be capacity-padded past
      the live edge count — valid lanes never read the pad).
    ins_indptr/ins_indices: insert-overlay CSR over the same row space
      (indices padded to the static delta capacity).
    del_indptr/del_indices: tombstone-overlay CSR, same contract.
    frontier: [S] row ids to expand.
    fanout: static hop fanout; positive = uniform sample, negative =
      full neighborhood inside a ``-fanout`` window (NeighborSampler's
      internal encoding).
    seed_mask: [S] validity of frontier lanes.
    ins_window/del_window: static per-node delta window capacities. A
      row with more pending inserts (deletes) than the window truncates
      (under-masks) until compaction folds the delta into the base —
      the stream ingestor's occupancy policy bounds how long that lasts.

  Edge ids are slot-encoded (with_edge consumers are unsupported on the
  stream path — delta edges have no stable compressed slot until
  compaction).

  ``base_window``/``indices_win``/``engine``/``interpret`` route the
  BASE uniform hop through a windowed read engine (``window`` or
  ``pallas`` — see ops/pipeline.py::hop_engine); the delta overlays
  keep their fixed ``ins_window``/``del_window`` full-neighborhood
  reads regardless. The snapshot's capacity-padded indices array
  doubles as ``indices_win`` whenever its padding slack covers the
  window width (StreamSampler checks per snapshot).
  """
  if fanout < 0:
    base = sample_full_neighbors(indptr, indices, frontier, -fanout,
                                 seed_mask=seed_mask)
  else:
    base = sample_neighbors(indptr, indices, frontier, fanout, key,
                            seed_mask=seed_mask, replace=replace,
                            window=base_window, indices_win=indices_win,
                            engine=engine, interpret=interpret)
  keep = base.mask
  if del_window > 0:
    dels = sample_full_neighbors(del_indptr, del_indices, frontier,
                                 del_window, seed_mask=seed_mask)
    keep = tombstone_mask(base.nbrs, base.mask, dels.nbrs, dels.mask)
  if ins_window <= 0:
    return NeighborOutput(nbrs=base.nbrs, mask=keep, eids=base.eids)
  ins = sample_full_neighbors(ins_indptr, ins_indices, frontier,
                              ins_window, seed_mask=seed_mask)
  return NeighborOutput(
      nbrs=jnp.concatenate([base.nbrs, ins.nbrs], axis=1),
      mask=jnp.concatenate([keep, ins.mask], axis=1),
      eids=jnp.concatenate([base.eids.astype(jnp.int32),
                            ins.eids.astype(jnp.int32)], axis=1),
  )

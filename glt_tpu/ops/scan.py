"""Prefix-scan primitives tuned for TPU.

XLA lowers ``jnp.cumsum`` on TPU to a reduce-window pass that runs at
~2.4ns/element (benchmarks/microbench_prims.py). At the 1M-element scale
of the sampling pipeline a blocked formulation — per-block cumsum via a
triangular matmul on the MXU plus a tiny carry level — is ~6x faster
(benchmarks/proto_window_hop.py H3). int32 inputs stay exact: float32
accumulates exactly up to 2^24, and per-block sums of sampling
indicators are far below that; the carry level accumulates in int32.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_BLOCK = 512


def cumsum_i32(x: jax.Array) -> jax.Array:
  """Inclusive int32 cumsum of a 1-D array. Exact iff every value and
  every within-block (512) partial sum is exactly representable in
  float32, i.e. magnitudes < 2^24 — true for the 0/1 indicators the
  sampling pipeline feeds it. The matmul is pinned to HIGHEST precision
  so f32 inputs are not rounded to bf16 on the MXU. Falls back to native
  cumsum below one block."""
  m = x.shape[0]
  if m <= _BLOCK:
    return jnp.cumsum(x.astype(jnp.int32))
  b = _BLOCK
  pad = (-m) % b
  x2 = jnp.pad(x.astype(jnp.int32), (0, pad)).reshape(-1, b)
  tri = jnp.tril(jnp.ones((b, b), jnp.float32))
  within = jnp.matmul(x2.astype(jnp.float32), tri.T,
                      precision=jax.lax.Precision.HIGHEST
                      ).astype(jnp.int32)                      # [nb, b]
  block_tot = within[:, -1]                                    # [nb]
  carry = jnp.cumsum(block_tot) - block_tot                    # exclusive
  out = within + carry[:, None]
  return out.reshape(-1)[:m]

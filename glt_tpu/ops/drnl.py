"""Double-Radius Node Labeling (DRNL) for SEAL link prediction.

Reference: examples/seal_link_pred.py:107-136 computes DRNL per enclosing
subgraph with scipy shortest_path on the host. TPU formulation: the
subgraphs are padded static [N]-node / [E]-edge-slot graphs, so DRNL is a
pair of *edge-parallel BFS relaxations* (segment_min over edge slots
inside ``lax.while_loop``) — fully jittable and vmappable over a batch of
enclosing subgraphs, no host round-trip.

z(v) = 1 + min(d_src, d_dst) + (d//2) * (d//2 + d%2 - 1), d = d_src+d_dst,
with d_src computed on the graph minus dst (and vice versa), z(src) =
z(dst) = 1, unreachable nodes -> 0. Identical to the reference formula.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_INF = jnp.int32(1 << 29)


def bfs_distances(row: jax.Array, col: jax.Array, edge_mask: jax.Array,
                  num_nodes: int, source: jax.Array) -> jax.Array:
  """Unweighted shortest-path distances from ``source`` over masked,
  relabeled edge slots (directed relaxation; pass both directions for an
  undirected graph). Runs relaxation rounds until a fixpoint, so the
  result is exact for any diameter. Unreachable nodes hold a large
  sentinel (>= 1<<29).
  """
  seg = jnp.where(edge_mask, col, num_nodes)  # invalid slots -> overflow
  safe_row = jnp.clip(row, 0, num_nodes - 1)
  dist0 = jnp.where(jnp.arange(num_nodes) == source, 0, _INF)

  def body(carry):
    dist, _ = carry
    cand = jnp.where(edge_mask, jnp.take(dist, safe_row) + 1, _INF)
    relaxed = jax.ops.segment_min(cand, seg, num_nodes + 1)[:num_nodes]
    new = jnp.minimum(dist, relaxed)
    return new, jnp.any(new < dist)

  dist, _ = jax.lax.while_loop(lambda c: c[1], body, (dist0, True))
  return dist


def drnl_node_labeling(row: jax.Array, col: jax.Array,
                       edge_mask: jax.Array, num_nodes: int,
                       src: jax.Array, dst: jax.Array,
                       max_z: int) -> jax.Array:
  """DRNL labels for one padded enclosing subgraph; vmap for a batch.

  Args:
    row/col/edge_mask: relabeled padded edge slots (target link already
      removed by the caller, as the reference does).
    src/dst: the candidate link's labels (scalars).
    max_z: static clip bound for the label vocabulary (one-hot width is
      ``max_z + 1``).
  """
  keep_wo_dst = edge_mask & (row != dst) & (col != dst)
  keep_wo_src = edge_mask & (row != src) & (col != src)
  d_src = bfs_distances(row, col, keep_wo_dst, num_nodes, src)
  d_dst = bfs_distances(row, col, keep_wo_src, num_nodes, dst)
  reachable = (d_src < _INF) & (d_dst < _INF)
  d = d_src + d_dst
  half, rem = d // 2, d % 2
  z = 1 + jnp.minimum(d_src, d_dst) + half * (half + rem - 1)
  z = jnp.where(reachable, z, 0)
  idx = jnp.arange(num_nodes)
  z = jnp.where((idx == src) | (idx == dst), 1, z)
  return jnp.clip(z, 0, max_z).astype(jnp.int32)
